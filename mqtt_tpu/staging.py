"""The broker's publish staging loop: micro-batch concurrent PUBLISHes into
device match batches (SURVEY.md §7 stage 4).

The reference matches synchronously inside ``processPublish``
(server.go:984-1021) — free when the walk is an in-process trie, ruinous
when it is a device round trip. The stage turns the device matcher into a
pipelined batch engine:

- ``submit(topic)`` parks the publish on a future and returns immediately;
  the caller (one asyncio task per client, mirroring the reference's
  goroutine-per-connection) awaits it, so *that* client blocks while every
  other client keeps being served.
- A collector task gathers everything submitted within the accumulation
  window (or up to the batch cap) and issues ONE ``match_topics_async``
  dispatch. The issue leg (host tokenize + H2D + async device dispatch)
  runs on its OWN dispatch thread (``mqtt-tpu-h2d``), the blocking D2H
  sync + host materialization on another (``mqtt-tpu-resolve``), and the
  kernel itself is asynchronous on the device — a ``pipeline_depth``-deep
  (default 3) overlapped pipeline in which batch N+2 tokenizes while
  N+1 matches and N drains, so the event loop never carries staging
  work and the device never waits for the host between batches. Per-leg
  handoff waits are measured into the telemetry plane
  (``mqtt_tpu_staging_leg_wait_seconds{leg=h2d|d2h}``) — the numbers
  that must sit near zero when the pipeline is actually full.
- The window and the batch cap ADAPT to the measured per-batch service
  time against ``latency_budget_s`` (SURVEY §7 hard part 4: "adaptive
  batch window + host fast-path"): under light load the window shrinks
  toward immediate dispatch (p99 ~= one service time); under heavy load
  batches grow until the service-time EWMA approaches the budget, then
  the cap backs off so publish latency stays bounded instead of batches
  compounding (16K-topic batches cost >1.5s on a tunneled link —
  BENCH_r04 p99).
- A drainer task resolves batches IN ORDER off the event loop (the D2H
  sync blocks, so it runs in the executor) and completes the futures in
  submission order — per-publish fan-out order is exactly submission
  order, as in the reference.
- A matcher failure degrades, never drops: the affected futures fall back
  to the bit-identical host trie walk.
- Admission is BOUNDED (``max_pending``): under a publish storm the
  parked list never grows past its cap — overflow (and submissions whose
  projected pipeline wait already exceeds the deadline) resolves via the
  host walk immediately, and the overload governor (mqtt_tpu.overload)
  watches the same depth as its staging pressure signal.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

from .topics import Subscribers
from .utils.loopwitness import DEFAULT_LOOP_PLANE as _LOOP_PLANE

_log = logging.getLogger("mqtt_tpu.staging")


class MatchStage:
    """Micro-batching pipeline between ``process_publish`` and a device
    matcher (``DeltaMatcher`` or any object with ``match_topics_async``)."""

    def __init__(
        self,
        matcher,
        host_fallback: Callable[[str], Subscribers],
        window_s: float = 0.002,
        max_batch: int = 4096,
        max_inflight: int = 4,
        latency_budget_s: Optional[float] = 0.25,
        min_batch: int = 64,
        max_pending: int = 8192,
        telemetry=None,
        profiler=None,
        predicates=None,
        pipeline_depth: int = 3,
        recrypt=None,
    ) -> None:
        self.matcher = matcher
        self.host_fallback = host_fallback
        # overlapped-staging depth: how many batches may be in flight
        # across the h2d-tokenize / device-dispatch / d2h-drain legs
        # (0 falls back to max_inflight for embedders pinning the old
        # knob). Depth 3 keeps one batch per leg.
        self.pipeline_depth = pipeline_depth if pipeline_depth > 0 else max_inflight
        # MQTT+ predicate engine (mqtt_tpu.predicates.PredicateEngine) or
        # None. When attached, each batch's payload-feature rows ride to
        # the device BESIDE the tokenized topics — one extra dispatch,
        # zero extra round trips: both results sync in the drain loop's
        # single executor leg, and the resolved pass bits are stamped
        # back onto the per-publish feature carriers before the futures
        # complete, so fan-out receives the already-filtered set.
        self.predicates = predicates
        # tenant re-encryption engine (mqtt_tpu.tenancy.RecryptEngine)
        # or None. When attached, each batch's publisher-decrypt
        # keystream jobs (RecryptJob carriers) dispatch beside the
        # tokenized topics and resolve in the same drain-loop executor
        # leg — the MQT-TZ decrypt rides the staged batch with zero
        # extra device round trips, exactly like predicate rows.
        self.recrypt = recrypt
        # telemetry plane (mqtt_tpu.telemetry.Telemetry) or None: batch
        # service-time + fill-ratio histograms, fallback-class counters,
        # and the per-publish stage clock's staging_wait / device_batch
        # stamps all flow through it
        self.telemetry = telemetry
        # device pipeline profiler (mqtt_tpu.tracing.DeviceProfiler) or
        # None. When attached (and the matcher feeds it), sampled stage
        # clocks resolve device_batch into h2d / device_dispatch / d2h
        # using the boundaries the matcher recorded for this batch.
        self.profiler = profiler
        self.window_s = window_s  # the MAXIMUM accumulation window
        self.max_batch = max_batch
        self.max_inflight = max_inflight
        # p99 target for one staged publish: wait + service must fit it.
        # None disables adaptation (fixed window + cap — benchmarking the
        # throughput-optimal point needs this)
        self.latency_budget_s = latency_budget_s
        self.min_batch = max(1, min_batch)
        # bounded admission: _pending may never grow past this; overflow
        # (and submissions whose projected pipeline wait already blows
        # the deadline) resolves via the host walk instead of queueing —
        # a publish storm costs bounded memory, not an OOM
        self.max_pending = max(1, max_pending)
        self.admission_fallbacks = 0
        self.peak_pending = 0
        # parked publishes: (topic, future, stage clock or None).
        # Guarded by _plock: under the event-loop shard fabric
        # (mqtt_tpu.shards) submit() runs on every shard's loop while
        # the collector drains on the stage's own loop — the park list
        # is the one cross-thread hand-off point. Futures are created
        # on the SUBMITTING loop and resolved back onto it
        # (call_soon_threadsafe when it is not the stage loop), so each
        # publisher awaits a loop-local future exactly as before.
        self._pending: list[tuple] = []
        self._plock = threading.Lock()
        # the loop the collector/drainer run on (start()'s loop)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._wake: Optional[asyncio.Event] = None
        self._queue: Optional[asyncio.Queue] = None
        self._tasks: list[asyncio.Task] = []
        # the resolve leg's dedicated executor: NAMED threads
        # ("mqtt-tpu-resolve-N") so the host sampling profiler
        # (mqtt_tpu.profiling) attributes the blocking D2H sync to the
        # staging pipeline instead of an anonymous default-executor slot
        self._executor: Optional[ThreadPoolExecutor] = None
        # the issue leg's dedicated SINGLE dispatch thread
        # ("mqtt-tpu-h2d-0"): tokenize + H2D + async device dispatch run
        # here, in batch order, off the event loop — batch N+2 tokenizes
        # while N+1's kernel runs and N drains on the resolve leg
        self._h2d_executor: Optional[ThreadPoolExecutor] = None
        # batches currently inside the pipeline (enqueued or draining);
        # exported as mqtt_tpu_staging_pipeline_depth
        self.inflight_batches = 0
        self._stopping = False
        self._ewma_s = 0.0  # per-batch service-time EWMA (drainer-updated)
        self._batch_cap = max_batch if latency_budget_s is None else max(
            self.min_batch, min(max_batch, 1024)
        )

    @property
    def batch_cap(self) -> int:
        """The current adaptive batch-size cap (<= max_batch)."""
        return self._batch_cap

    def _window(self) -> float:
        """The adaptive accumulation sleep: a fraction of the measured
        service time (batching beyond that trades latency for nothing —
        the pipeline is already busy for that long), never exceeding the
        configured maximum window or the latency budget's headroom.

        Headroom is depth-scaled to match what _observe_service budgets:
        a submitted publish waits for every batch already queued, so the
        effective latency is depth x service — once that alone exceeds
        the budget, any window sleep is pure added wait on an already
        over-budget pipeline, and the window collapses to 0."""
        budget = self.latency_budget_s
        if budget is None or self._ewma_s <= 0.0:
            return self.window_s
        depth = 1 if self._queue is None else self._queue.qsize() + 1
        headroom = budget - depth * self._ewma_s
        if headroom <= 0.0:
            return 0.0  # over budget already: dispatch immediately
        return min(self.window_s, 0.5 * self._ewma_s, headroom)

    def _observe_service(self, dt: float, n: int, depth: int) -> None:
        """Feed one batch's resolve wall time into the controller: grow
        the cap while service time is comfortably under budget, shrink it
        proportionally when a batch overruns (service scales ~linearly in
        batch size past the fixed dispatch cost).

        ``depth`` is the number of batches that were queued behind this
        one: a submitted publish waits for every batch ahead of it, so the
        budget must bound depth x service, not one batch's service — the
        controller compares the EFFECTIVE latency (dt * depth) against the
        budget."""
        self._ewma_s = dt if self._ewma_s == 0.0 else (
            0.7 * self._ewma_s + 0.3 * dt
        )
        budget = self.latency_budget_s
        if budget is None or n <= 0:
            return
        effective = dt * max(1, depth)
        if effective > 0.8 * budget:
            target = max(int(n * 0.6 * budget / effective), self.min_batch)
            if target < self._batch_cap:
                self._batch_cap = target
        elif effective < 0.4 * budget and n >= self._batch_cap:
            # only grow when the cap actually bound the batch
            self._batch_cap = min(self.max_batch, self._batch_cap * 2)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Create the collector/drainer tasks on the running loop."""
        loop = asyncio.get_running_loop()
        self._loop = loop
        self._wake = asyncio.Event()
        self._executor = ThreadPoolExecutor(
            max_workers=max(2, self.max_inflight),
            thread_name_prefix="mqtt-tpu-resolve",
        )
        # ONE issue thread: the h2d leg must stay in batch order (the
        # drain loop completes futures in submission order)
        self._h2d_executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="mqtt-tpu-h2d"
        )
        # bounded: if resolution falls behind, collection backpressures
        # instead of queueing unbounded device batches
        self._queue = asyncio.Queue(maxsize=self.pipeline_depth)
        self.inflight_batches = 0  # a restarted stage begins empty
        self._tasks = [
            loop.create_task(self._collect_loop(), name="mqtt-tpu-stage-collect"),
            loop.create_task(self._drain_loop(), name="mqtt-tpu-stage-drain"),
        ]

    async def stop(self) -> None:
        """Stop the pipeline; anything still parked resolves via the host
        walk so no publish is ever lost."""
        self._stopping = True
        if self._wake is not None:
            self._wake.set()
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        with self._plock:
            parked, self._pending = self._pending, []
        self._fallback_all(parked, klass="stop")
        queue = self._queue
        if queue is not None:
            while not queue.empty():
                _resolver, futs, topics, *_rest = queue.get_nowait()
                self.inflight_batches -= 1
                self._fallback_all(list(zip(topics, futs)), klass="stop")
        if self._executor is not None:
            # in-flight resolves may finish on their own time; queued
            # ones are dead (their futures just resolved via fallback)
            self._executor.shutdown(wait=False, cancel_futures=True)
            self._executor = None
        if self._h2d_executor is not None:
            self._h2d_executor.shutdown(wait=False, cancel_futures=True)
            self._h2d_executor = None

    # -- submission --------------------------------------------------------

    def submit(
        self, topic: str, clock=None, feats=None, rjob=None
    ) -> "asyncio.Future[Subscribers]":
        """Park one publish; the future resolves with its Subscribers.
        ``clock`` is an optional sampled stage clock (mqtt_tpu.telemetry)
        stamped at batch issue (staging_wait) and resolve (device_batch).
        ``feats`` is the publish's optional payload-feature carrier
        (mqtt_tpu.predicates.PublishFeatures): the batch ships it to the
        device rule table and the resolved pass bits come back ON the
        carrier — host-fallback resolutions simply leave it unstamped
        and the fan-out path's host interpreter decides. ``rjob`` is
        the publish's optional decrypt carrier
        (mqtt_tpu.tenancy.RecryptJob) for encrypted-namespace publishes:
        its keystream dispatch rides the same batch and the resolved
        rows come back on the carrier the same way.

        Admission is bounded: once ``max_pending`` publishes are parked,
        or the pipeline's projected wait already exceeds the deadline
        (2x the latency budget), the publish resolves immediately via
        the host walk — the degraded-but-bounded mode — instead of
        growing the backlog."""
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        if _LOOP_PLANE.active:
            w = _LOOP_PLANE.witness
            if w is not None:
                w.note_crossing(
                    "match_stage", "submit_local", "submit_cross", self._loop
                )
        wake = self._wake
        if self._stopping or wake is None:
            fut.set_result(self.host_fallback(topic))
            return fut
        with self._plock:
            if len(self._pending) >= self.max_pending or self._past_deadline():
                admitted = False
            else:
                admitted = True
                self._pending.append((topic, fut, clock, feats, rjob))
                if len(self._pending) > self.peak_pending:
                    self.peak_pending = len(self._pending)
        if not admitted:
            self.admission_fallbacks += 1
            if self.telemetry is not None:
                self.telemetry.note_fallback("admission")
            fut.set_result(self.host_fallback(topic))
            return fut
        # the wake Event is loop-affine: shard-loop submitters marshal
        # the set() onto the stage's loop (mqtt_tpu.shards). A never-
        # started stage (_loop None: unit harnesses that drive the
        # collector by hand) keeps the direct set.
        if self._loop is None or loop is self._loop:
            wake.set()
        else:
            try:
                self._loop.call_soon_threadsafe(wake.set)
            except RuntimeError:
                # stage loop gone mid-shutdown: serve the host walk now
                if not fut.done():
                    fut.set_result(self.host_fallback(topic))
        return fut

    def _past_deadline(self) -> bool:
        """Deadline-aware admission: a new submission waits behind every
        queued batch plus every parked batch-worth of _pending; when that
        projected wait exceeds twice the latency budget, queueing only
        deepens an already-lost backlog — the host walk serves it now.

        An IDLE pipeline always admits, whatever the EWMA says: the
        service-time estimate only heals through real dispatches, so a
        one-off spike (the first batch's cold compile) must not starve
        the stage into a permanent host-walk detour."""
        budget = self.latency_budget_s
        if budget is None or self._ewma_s <= 0.0:
            return False
        qdepth = self._queue.qsize() if self._queue is not None else 0
        if qdepth == 0 and not self._pending:
            return False  # idle: admit, and let the EWMA re-learn
        depth = 1 + qdepth + len(self._pending) // max(1, self._batch_cap)
        return depth * self._ewma_s > 2.0 * budget

    @property
    def pending_depth(self) -> int:
        return len(self._pending)

    def alive(self) -> bool:
        """Pipeline liveness for ``GET /healthz`` (ISSUE 14 satellite):
        started, not stopping, and BOTH loop tasks still running — a
        crashed collector/drainer would otherwise strand every parked
        publish until its caller's timeout, which is exactly the state
        a readiness probe must surface."""
        if self._stopping or self._wake is None:
            return False
        return bool(self._tasks) and all(not t.done() for t in self._tasks)

    def pressure(self) -> float:
        """Normalized staging pressure for the overload governor: parked
        admission depth against its cap, plus the batch queue's fill at
        half weight (a full queue is normal pipelining; sustained
        _pending growth is the real overload signal)."""
        p = len(self._pending) / self.max_pending
        q = 0.0
        if self._queue is not None and self.pipeline_depth > 0:
            q = self._queue.qsize() / self.pipeline_depth
        return max(p, 0.5 * q)

    # -- pipeline ----------------------------------------------------------

    async def _collect_loop(self) -> None:
        wake, queue = self._wake, self._queue
        assert wake is not None and queue is not None  # start() created us
        if _LOOP_PLANE.active:
            w = _LOOP_PLANE.witness
            if w is not None:
                # the collector IS the stage loop's drainer of _pending
                w.check_owner("match_stage", "drain_owner", self._loop)
        while True:
            await wake.wait()
            wake.clear()
            if not self._pending:
                continue
            # the accumulation window: give concurrent publishers a beat to
            # land in this batch (latency cost) so the device sees real
            # batches (throughput win); adaptively sized (see _window) and
            # capped by the adaptive batch cap
            cap = self._batch_cap
            if len(self._pending) < cap:
                w = self._window()
                if w > 0:
                    await asyncio.sleep(w)
                cap = self._batch_cap  # the drainer may have adapted it
            with self._plock:
                batch, self._pending = (
                    self._pending[:cap],
                    self._pending[cap:],
                )
                leftovers = bool(self._pending)
            if leftovers:
                wake.set()  # leftovers start the next window now
            # a caller future cancelled mid-window (client disconnected
            # during accumulation) is dead weight: drop it here so the
            # device never matches for it and no resolver path trips on
            # an already-cancelled future
            batch = [item for item in batch if not item[1].cancelled()]
            if not batch:
                continue
            topics = [t for t, _, _, _, _ in batch]
            futs = [f for _, f, _, _, _ in batch]
            clocks = [c for _, _, c, _, _ in batch]
            feats = [p for _, _, _, p, _ in batch]
            rjobs = [r for _, _, _, _, r in batch]
            for c in clocks:
                if c is not None:  # end of the accumulation/park wait
                    c.stamp("staging_wait")
            # the ISSUE leg runs on the dedicated h2d dispatch thread,
            # in batch order (single worker): host tokenize + H2D + the
            # async device dispatch leave the event loop free, and batch
            # N+2 tokenizes while N+1's kernel runs and N drains — the
            # 3-deep overlap the device profiler's duty cycle gates on
            t_formed = time.perf_counter()
            profiler = self.profiler
            predicates = self.predicates
            recrypt = self.recrypt
            matcher = self.matcher
            telemetry = self.telemetry

            def issue():
                if telemetry is not None:
                    # h2d-leg handoff wait: batch formed -> issue start
                    telemetry.observe_leg_wait(
                        "h2d", time.perf_counter() - t_formed
                    )
                if profiler is not None:
                    # per-batch device-timing record (mqtt_tpu.tracing):
                    # the matcher fills its dispatch/D2H windows, the
                    # drain loop sub-stamps sampled clocks from it — the
                    # batch's OWN record, so concurrent or out-of-order
                    # resolution (the resilience guard pool) can never
                    # cross-attribute boundaries
                    rec = profiler.open_batch()
                    resolver = matcher.match_topics_async(
                        topics, profile=rec
                    )
                else:
                    rec = None
                    resolver = matcher.match_topics_async(topics)
                # MQTT+ predicate evaluation rides the SAME staged
                # batch: one extra async dispatch against the device
                # rule table, resolved in the same drain-loop executor
                # leg as the match result — no additional device round
                # trip. A None resolver (no rules, breaker open, eval
                # error) leaves the carriers unstamped and the fan-out
                # host interpreter decides.
                pred_resolver = None
                if predicates is not None:
                    try:
                        pred_resolver = predicates.eval_batch_async(feats)
                    except Exception:
                        _log.exception(
                            "predicate eval issue failed; host interpreter"
                        )
                # the tenant decrypt leg rides the same batch: one
                # fused keystream dispatch for every encrypted-namespace
                # publish here; a None resolver (no jobs, breaker open,
                # no backend) leaves the carriers unstamped and the
                # fan-out's host keystream serves (mqtt_tpu.tenancy)
                rec_resolver = None
                if recrypt is not None:
                    try:
                        rec_resolver = recrypt.issue_batch(rjobs)
                    except Exception:
                        _log.exception(
                            "recrypt issue failed; host keystream"
                        )
                return resolver, pred_resolver, rec_resolver, rec

            loop = asyncio.get_running_loop()
            try:
                (
                    resolver, pred_resolver, rec_resolver, rec,
                ) = await loop.run_in_executor(self._h2d_executor, issue)
            except asyncio.CancelledError:
                # stop() cancelled us with this batch in hand (in neither
                # _pending nor the queue): resolve it before going down.
                # An issue that already reached the device is harmless —
                # its result is simply never synced.
                self._fallback_all(batch, klass="stop")
                raise
            except Exception:
                _log.exception("stage issue failed; host fallback for batch")
                self._fallback_all(batch, klass="issue_error")
                continue
            t_ready = time.perf_counter()
            self.inflight_batches += 1
            try:
                await queue.put(
                    (
                        resolver, futs, topics, clocks, rec, pred_resolver,
                        feats, rec_resolver, t_ready,
                    )
                )
            except asyncio.CancelledError:
                self.inflight_batches -= 1
                self._fallback_all(batch, klass="stop")
                raise

    async def _drain_loop(self) -> None:
        loop = asyncio.get_running_loop()
        queue = self._queue
        assert queue is not None  # start() created us
        telemetry = self.telemetry
        while True:
            (
                resolver, futs, topics, clocks, rec, pred_resolver, feats,
                rec_resolver, t_ready,
            ) = await queue.get()
            try:
                # the D2H sync blocks — run it off the loop. Queue depth is
                # sampled at resolve time: batches still queued waited for
                # this one, so the controller budgets depth x service.
                # The predicate rows sync in the SAME executor leg (the
                # pred resolver never raises — failures degrade to None).
                depth = queue.qsize() + 1
                t0 = loop.time()
                pr, mr, rr = pred_resolver, resolver, rec_resolver

                def sync():
                    if telemetry is not None:
                        # d2h-leg handoff wait: dispatch returned (batch
                        # queued behind the pipeline) -> sync start
                        telemetry.observe_leg_wait(
                            "d2h", time.perf_counter() - t_ready
                        )
                    return (
                        mr(),
                        pr() if pr is not None else None,
                        rr() if rr is not None else None,
                    )

                results, pred_rows, rec_rows = await loop.run_in_executor(
                    self._executor, sync
                )
                if pred_rows is not None and self.predicates is not None:
                    self.predicates.attach_rows(feats, pred_rows)
                if rec_rows is not None and self.recrypt is not None:
                    self.recrypt.attach(rec_rows)
                dt = loop.time() - t0
                self._observe_service(dt, len(topics), depth)
                if telemetry is not None:
                    telemetry.observe_batch(dt, len(topics), self._batch_cap)
            except asyncio.CancelledError:
                # stop() cancelled us with this batch already popped: it is
                # invisible to stop()'s queue drain, so resolve it here
                self.inflight_batches -= 1
                self._fallback_all(list(zip(topics, futs)), klass="stop")
                raise
            except Exception:
                self.inflight_batches -= 1
                _log.exception("stage resolve failed; host fallback for batch")
                self._fallback_all(list(zip(topics, futs)), klass="resolve_error")
                continue
            self.inflight_batches -= 1
            # this batch's own device-timing record: both windows are
            # set only when the batch actually dispatched AND synced —
            # the exact-map fast path and host fallbacks leave them
            # None, and then the coarse device_batch stamp applies (no
            # phantom h2d for batches that never touched the device)
            dispatch = rec.dispatch if rec is not None else None
            d2h = rec.d2h if rec is not None else None
            for fut, subs, ck in zip(futs, results, clocks):
                if ck is not None:  # issue -> resolved (device round trip)
                    if dispatch is not None and d2h is not None:
                        # tokenize + device dispatch; then kernel queue +
                        # execution; then the blocking result transfer
                        ck.stamp_until("h2d", dispatch[1])
                        ck.stamp_until("device_dispatch", d2h[0])
                        ck.stamp_until("d2h", d2h[1])
                    else:
                        ck.stamp("device_batch")
                self._resolve(fut, subs)

    def _resolve(self, fut: "asyncio.Future", value) -> None:
        """Complete one caller future ON ITS OWN LOOP: a future parked
        by a shard-loop submitter (mqtt_tpu.shards) must not have
        set_result called from the stage's loop — done-callbacks would
        be scheduled cross-thread. Stage-loop futures resolve inline
        (the single-loop path, unchanged)."""
        loop = fut.get_loop()
        local = self._loop is None or loop is self._loop
        if _LOOP_PLANE.active:
            w = _LOOP_PLANE.witness
            if w is not None:
                w.note(
                    "match_stage",
                    "resolve_local" if local else "resolve_marshal",
                )
        if local:
            if not fut.done():
                fut.set_result(value)
            return

        def _set() -> None:
            if not fut.done():
                fut.set_result(value)

        try:
            loop.call_soon_threadsafe(_set)
        except RuntimeError:
            pass  # submitter's loop closed; nobody is awaiting

    def _reject(self, fut: "asyncio.Future", exc: BaseException) -> None:
        """The exception leg of :meth:`_resolve`: fail a caller future
        ON ITS OWN LOOP. Found by brokerlint R12 — the old inline
        ``fut.set_exception`` from ``_fallback_all`` ran the waiter's
        done-callbacks on the stage's thread when the future was parked
        by a shard-loop submitter."""
        loop = fut.get_loop()
        local = self._loop is None or loop is self._loop
        if _LOOP_PLANE.active:
            w = _LOOP_PLANE.witness
            if w is not None:
                w.note(
                    "match_stage",
                    "resolve_local" if local else "resolve_marshal",
                )
        if local:
            if not fut.done():
                fut.set_exception(exc)
            return

        def _set() -> None:
            if not fut.done():
                fut.set_exception(exc)

        try:
            loop.call_soon_threadsafe(_set)
        except RuntimeError:
            pass  # submitter's loop closed; nobody is awaiting

    def _fallback_all(self, items, klass: str = "stop") -> None:
        """Resolve parked items via the host walk. ``items`` yield
        ``(topic, future, ...)`` — both the 3-tuple _pending form and the
        2-tuple ``zip(topics, futs)`` form are accepted."""
        n = 0
        for item in items:
            topic, fut = item[0], item[1]
            if fut.done():
                continue
            n += 1
            try:
                self._resolve(fut, self.host_fallback(topic))
            except Exception as e:  # pragma: no cover - host walk is total
                self._reject(fut, e)
        if n and self.telemetry is not None:
            self.telemetry.note_fallback(klass, n)


# -- restart re-registration (the durable session plane's bulk path) ---------


def bulk_register(topics, entries, batch: int = 4096) -> tuple[int, int]:
    """Re-register persisted subscriptions through the trie's bulk-insert
    path in fixed-size batches — the restart leg of the durable session
    plane (ISSUE 16). ``entries`` yield ``(client_id, Subscription)``;
    each chunk of ``batch`` pays ONE trie lock acquisition via
    ``TopicsIndex.subscribe_bulk`` instead of a per-subscription
    ``subscribe`` round-trip, which is the difference between a bounded
    and an unbounded restart at a million sessions. Returns
    ``(new_subscriptions, batches)`` so recovery metrics can prove the
    path was actually batched."""
    added = 0
    batches = 0
    chunk: list = []
    for entry in entries:
        chunk.append(entry)
        if len(chunk) >= batch:
            added += topics.subscribe_bulk(chunk)
            batches += 1
            chunk = []
    if chunk:
        added += topics.subscribe_bulk(chunk)
        batches += 1
    return added, batches


def bulk_inflight(clients, messages, batch: int = 4096) -> tuple[int, int]:
    """Restore persisted inflight (QoS1/QoS2 window) messages in
    fixed-size per-client batches via ``Inflight.set_bulk`` — one lock
    acquisition per chunk, mirroring :func:`bulk_register` (ISSUE 17
    satellite: the unacked window survives kill -9 through the same
    batched restart leg as subscriptions and retained). ``messages``
    yield storage ``Message`` records (``.client`` + ``.to_packet()``);
    records for clients with no live session are skipped (their session
    re-inflates them on reconnect via the subscription restore path).
    Returns ``(restored, batches)``."""
    restored = 0
    batches = 0
    per_client: dict = {}
    for msg in messages:
        cl = clients.get(msg.client)
        if cl is None:
            continue
        chunk = per_client.setdefault(msg.client, (cl, []))[1]
        chunk.append(msg.to_packet())
        if len(chunk) >= batch:
            restored += cl.state.inflight.set_bulk(chunk)
            batches += 1
            chunk.clear()
    for cl, chunk in per_client.values():
        if chunk:
            restored += cl.state.inflight.set_bulk(chunk)
            batches += 1
    return restored, batches


def bulk_retain(topics, packets, batch: int = 4096) -> tuple[int, int]:
    """Re-seat persisted retained messages in fixed-size batches via
    ``TopicsIndex.retain_bulk`` (one lock acquisition per chunk).
    Returns ``(retained, batches)``."""
    retained = 0
    batches = 0
    chunk: list = []
    for pk in packets:
        chunk.append(pk)
        if len(chunk) >= batch:
            retained += topics.retain_bulk(chunk)
            batches += 1
            chunk = []
    if chunk:
        retained += topics.retain_bulk(chunk)
        batches += 1
    return retained, batches
