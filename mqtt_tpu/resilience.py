"""Degradation manager for the device matcher: circuit breaker + hang
watchdog + background half-open probes.

The staging loop (mqtt_tpu.staging) already degrades on matcher
*exceptions* — but a flaky real device mostly does not raise. It hangs:
a dead tunnel wedges the D2H sync inside ``run_in_executor`` forever,
the drainer never completes another future, and every publisher parks
behind it (BENCH_r05's zero headline was exactly this). This module is
the layer between the stage and the device matcher that makes hardware
flap survivable:

- Every dispatch (issue + resolve) runs on a :class:`GuardPool` worker
  thread; the caller waits at most ``watchdog_s``. A hang therefore
  costs one bounded wait and one abandoned thread (replaced, counted),
  never a wedged publish future.
- Timeouts, dispatch errors, and corrupt results feed a
  :class:`CircuitBreaker`. ``failure_threshold`` consecutive failures
  trip it OPEN: all matching is instantly routed to the bit-identical
  host trie walk with **no device round trip and no watchdog wait** —
  the broker keeps its latency budget while the device is dark.
- While OPEN, a background probe thread re-tries the device on an
  exponential-backoff-plus-jitter schedule (HALF_OPEN). Probe batches
  are *differentially verified* against the live host trie; only
  ``probe_successes`` consecutive verified-healthy probes close the
  breaker and re-admit live traffic.
- Corrupt results (a device returning plausible-but-wrong ids — bitrot,
  a torn upload, an interposed fault injector) are caught by the same
  differential re-walk: every batch re-walks ``verify_sample`` of its
  topics on the host trie and compares; a mismatch counts as a failure
  and the whole batch is served from the host.

Breaker state, trip counts, fallback rates, and probe counters surface
as ``$SYS/broker/matcher/breaker/...`` gauges via the server's $SYS
loop (server.py). The same :class:`Backoff` machinery drives the worker
mesh's peer-link reconnects (mqtt_tpu.cluster).

The chaos suite (tests/test_resilience.py) drives all of this with the
deterministic fault injector in :mod:`mqtt_tpu.faults`.
"""

from __future__ import annotations

import logging
import queue
import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

from .topics import Subscribers, TopicsIndex

_log = logging.getLogger("mqtt_tpu.resilience")

# breaker states (exported as $SYS gauges; the ints are stable codes)
CLOSED = "closed"
HALF_OPEN = "half_open"
OPEN = "open"
_STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class GuardTimeout(TimeoutError):
    """A guarded dispatch exceeded the watchdog budget."""


class Backoff:
    """Exponential backoff with bounded jitter, deterministic under a
    seed. Shared by the breaker's half-open probe schedule and the
    cluster's peer-link re-dial loop."""

    def __init__(
        self,
        initial: float = 0.5,
        maximum: float = 30.0,
        factor: float = 2.0,
        jitter: float = 0.1,
        seed: Optional[int] = None,
    ) -> None:
        self.initial = initial
        self.maximum = maximum
        self.factor = factor
        self.jitter = jitter
        self._rng = random.Random(seed)
        self.attempts = 0

    def next(self) -> float:
        """The delay before the next attempt; successive calls grow it
        geometrically up to ``maximum``, +/- ``jitter`` fraction so a
        fleet of workers does not re-dial in lockstep."""
        # clamp the exponent: factor**1024 overflows a float BEFORE min()
        # can cap it, and a peer/device down for hours must not kill the
        # re-dial loop with an OverflowError (any real maximum is reached
        # long before 2**63)
        exp = self.factor ** min(self.attempts, 63)
        delay = min(self.maximum, self.initial * exp)
        self.attempts += 1
        if self.jitter:
            delay *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(0.0, delay)

    def reset(self) -> None:
        self.attempts = 0


class CircuitBreaker:
    """A three-state (CLOSED / OPEN / HALF_OPEN) circuit breaker.

    Thread-safe: the stage drainer records outcomes from executor
    threads while the probe thread acquires probe slots. Live traffic
    consults :meth:`allow`; only the probe path runs against the guarded
    resource while not CLOSED.
    """

    def __init__(
        self,
        failure_threshold: int = 3,
        backoff: Optional[Backoff] = None,
        probe_successes: int = 2,
        clock: Callable[[], float] = time.monotonic,
        on_trip: Optional[Callable[[], None]] = None,
    ) -> None:
        self.failure_threshold = max(1, failure_threshold)
        self.backoff = backoff or Backoff()
        self.probe_successes = max(1, probe_successes)
        self.clock = clock
        self.on_trip = on_trip
        # lock-plane adoption (mqtt_tpu.utils.locked): executor resolve
        # threads record outcomes here while the probe thread acquires
        # probe slots — a measured contention point under storms
        from .utils.locked import InstrumentedLock

        self._lock = InstrumentedLock("matcher_breaker")
        self._state = CLOSED
        self._retry_at = 0.0
        self._probe_inflight = False
        self._probe_ok = 0
        # counters (exported via as_dict)
        self.trips = 0
        self.failures = 0
        self.consecutive_failures = 0
        self.successes = 0
        self.probes = 0
        self.probe_failures = 0
        self.failure_kinds: dict[str, int] = {}
        self.last_failure = ""

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May LIVE traffic use the guarded resource right now?"""
        with self._lock:
            return self._state == CLOSED

    def _trip_locked(self) -> None:
        self._state = OPEN
        self.trips += 1
        self._probe_ok = 0
        self._probe_inflight = False
        self._retry_at = self.clock() + self.backoff.next()

    def _fire_on_trip(self) -> None:
        """Invoke the trip observer — AFTER the breaker lock is released
        (brokerlint R5): a slow or re-registering observer under the lock
        would stall every record_* caller on the data plane."""
        cb = self.on_trip
        if cb is not None:
            try:
                cb()
            except Exception:  # pragma: no cover - observer must not break us
                _log.exception("breaker on_trip observer failed")

    def record_failure(self, kind: str = "error") -> None:
        """A LIVE dispatch failed. Only CLOSED-state failures drive
        transitions: a stale in-flight batch failing after the trip (or
        during a probe) is counted but must not be mistaken for the
        probe's outcome — probes report via record_probe_failure."""
        tripped = False
        with self._lock:
            self.failures += 1
            self.consecutive_failures += 1
            self.failure_kinds[kind] = self.failure_kinds.get(kind, 0) + 1
            self.last_failure = kind
            if (
                self._state == CLOSED
                and self.consecutive_failures >= self.failure_threshold
            ):
                _log.warning(
                    "circuit breaker OPEN after %d consecutive failures "
                    "(last: %s); matching degrades to the host trie",
                    self.consecutive_failures,
                    kind,
                )
                self._trip_locked()
                tripped = True
        if tripped:
            self._fire_on_trip()

    def record_success(self) -> None:
        """A LIVE dispatch verified healthy. A stale batch resolving
        during HALF_OPEN must not claim the probe slot's outcome, so
        this never advances probe accounting (record_probe_success
        does)."""
        with self._lock:
            self.successes += 1
            self.consecutive_failures = 0

    def record_probe_failure(self, kind: str = "error") -> None:
        """The HALF_OPEN probe (the acquire_probe holder) failed:
        re-open with grown backoff."""
        with self._lock:
            self.failures += 1
            self.failure_kinds[kind] = self.failure_kinds.get(kind, 0) + 1
            self.last_failure = kind
            self.probe_failures += 1
            self._trip_locked()
        self._fire_on_trip()

    def record_probe_success(self) -> None:
        """The HALF_OPEN probe verified healthy; enough of these in a
        row close the breaker."""
        with self._lock:
            self.successes += 1
            self.consecutive_failures = 0
            if self._state != HALF_OPEN:
                return  # a concurrent probe failure already re-tripped
            self._probe_inflight = False
            self._probe_ok += 1
            if self._probe_ok >= self.probe_successes:
                _log.info(
                    "circuit breaker CLOSED after %d verified probes",
                    self._probe_ok,
                )
                self._state = CLOSED
                self._probe_ok = 0
                self.backoff.reset()
            else:
                # healthy but not yet convincing: fast-follow probe at
                # the base cadence (no extra backoff growth)
                self._state = OPEN
                self._retry_at = self.clock() + self.backoff.initial

    def seconds_until_probe(self) -> Optional[float]:
        """Time until the next probe may run; None when CLOSED."""
        with self._lock:
            if self._state == CLOSED:
                return None
            return max(0.0, self._retry_at - self.clock())

    def acquire_probe(self, force: bool = False) -> bool:
        """Claim the single half-open probe slot. True moves the breaker
        to HALF_OPEN and the caller MUST follow with record_success or
        record_failure."""
        with self._lock:
            if self._state == CLOSED:
                return False
            if self._probe_inflight and not force:
                return False
            if not force and self.clock() < self._retry_at:
                return False
            self._state = HALF_OPEN
            self._probe_inflight = True
            self.probes += 1
            return True

    def as_dict(self) -> dict:
        with self._lock:
            d = {
                "state": self._state,
                "state_code": _STATE_CODES[self._state],
                "trips": self.trips,
                "failures": self.failures,
                "consecutive_failures": self.consecutive_failures,
                "successes": self.successes,
                "probes": self.probes,
                "probe_failures": self.probe_failures,
                "last_failure": self.last_failure or "none",
            }
            for kind, n in self.failure_kinds.items():
                d[f"failures_{kind}"] = n
            return d


class _GuardTask:
    """One guarded call: the waiter may abandon it at the watchdog
    budget; the worker thread discovers the abandonment when the call
    eventually returns. ``counted`` is pool-lock-guarded wedge
    accounting — set by ``report_wedged`` only if the call was still
    unfinished, so a call completing in the raise-to-report window never
    skews the wedge count."""

    __slots__ = ("_done", "_lock", "_result", "_exc", "abandoned", "counted")

    def __init__(self) -> None:
        self._done = threading.Event()
        self._lock = threading.Lock()
        self._result: Any = None
        self._exc: Optional[BaseException] = None
        self.abandoned = False
        self.counted = False

    def wait(self, timeout: Optional[float]) -> Any:
        if not self._done.wait(timeout):
            with self._lock:
                if not self._done.is_set():
                    self.abandoned = True
                    raise GuardTimeout(f"guarded call exceeded {timeout}s")
        if self._exc is not None:
            raise self._exc
        return self._result


class GuardPool:
    """A tiny daemon-thread pool whose workers are REPLACEABLE: when a
    caller abandons a task at the watchdog budget, the worker running it
    is presumed wedged (a hung device call cannot be interrupted), a
    substitute thread is spawned so capacity recovers, and the wedged
    worker retires itself if/when the hung call finally returns.

    Unlike ``concurrent.futures.ThreadPoolExecutor``, threads are daemon
    (a permanently hung dispatch must not block interpreter exit) and
    wedge accounting is first-class (``saturated`` lets the caller skip
    the queue entirely once everything is stuck)."""

    # hard cap on replacement spawns: a device whose every call hangs
    # forever costs at most target+MAX_WEDGED threads, never one per
    # probe attempt. Past it, probes short-circuit (live_unwedged == 0)
    # until some hung call returns and frees a worker.
    MAX_WEDGED = 16

    def __init__(self, workers: int = 4, name: str = "mqtt-tpu-guard") -> None:
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._name = name
        self._target = max(1, workers)
        self._lock = threading.Lock()
        self._wedged = 0
        self._spawned = 0
        self._live = 0  # threads currently inside _run (incl. wedged)
        self._owed_retires = 0  # replacements spawned for wedged workers
        self._closed = False
        with self._lock:
            for _ in range(self._target):
                self._spawn()

    def _spawn(self) -> None:
        # caller holds self._lock
        self._spawned += 1
        self._live += 1
        t = threading.Thread(
            target=self._run, daemon=True, name=f"{self._name}-{self._spawned}"
        )
        t.start()

    def _run(self) -> None:
        while True:
            item: Optional[tuple[_GuardTask, Callable[[], object]]] = self._q.get()
            if item is None:
                with self._lock:
                    self._live -= 1
                return
            task, fn = item
            exc: Optional[BaseException] = None
            result = None
            try:
                result = fn()
            except BaseException as e:  # noqa: BLE001 - ferried to the waiter
                exc = e
            with task._lock:
                task._result = result
                task._exc = exc
                abandoned = task.abandoned
                task._done.set()
            if abandoned:
                # the waiter gave up on this call long ago: the wedge is
                # over (if it was ever counted — a completion racing the
                # report window was not). Retire ONLY if a replacement
                # was actually spawned — otherwise keep serving, or the
                # pool bleeds capacity past MAX_WEDGED toward zero
                with self._lock:
                    if task.counted:
                        self._wedged -= 1
                        if self._owed_retires > 0:
                            self._owed_retires -= 1
                            self._live -= 1
                            return

    @property
    def saturated(self) -> bool:
        """All original capacity is wedged on hung calls."""
        with self._lock:
            return self._wedged >= self._target

    @property
    def wedged(self) -> int:
        with self._lock:
            return self._wedged

    @property
    def live_unwedged(self) -> int:
        """Workers able to take new tasks right now. 0 means every
        thread is stuck in a hung call — submissions would only queue,
        so the probe path must skip dispatching rather than burn more
        threads (ResilientMatcher._probe_once)."""
        with self._lock:
            return self._live - self._wedged

    def report_wedged(self, task: _GuardTask) -> None:
        """The caller abandoned ``task``: account the wedged worker and
        spawn a substitute, bounded by MAX_WEDGED in total — a device
        whose every call hangs FOREVER must cost a bounded number of
        threads, not one per probe attempt; recovery then rides on the
        hung calls eventually returning (a healed tunnel unblocks them),
        which un-wedges workers without new spawns. A task that
        completed in the raise-to-report race window is not a wedge at
        all and leaves the accounting untouched."""
        with self._lock:
            if task._done.is_set() or task.counted:
                return  # completed just after the deadline: no wedge
            task.counted = True
            self._wedged += 1
            if not self._closed and self._wedged <= self.MAX_WEDGED:
                self._owed_retires += 1
                self._spawn()

    def submit(self, fn: Callable[[], object]) -> _GuardTask:
        with self._lock:
            if self._closed:
                raise RuntimeError("guard pool closed")
        task = _GuardTask()
        self._q.put((task, fn))
        return task

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            live = self._live
        for _ in range(max(0, live)):
            self._q.put(None)


@dataclass
class BreakerConfig:
    """Knobs for the degradation manager (Options / config file map the
    ``breaker_*`` keys here; see README.md)."""

    failure_threshold: int = 3
    # per-batch hang budget: a dispatch not resolved within this is
    # abandoned and served from the host trie. This is a LAST-RESORT hang
    # bound, not a latency control (staging's latency_budget_s is that) —
    # it must sit above worst-case cold-compile time.
    watchdog_s: float = 5.0
    probe_backoff_s: float = 0.5
    probe_backoff_max_s: float = 30.0
    probe_jitter: float = 0.1
    probe_successes: int = 2
    # topics differentially re-walked on the host per healthy batch (0
    # disables the corrupt-result check outside probes)
    verify_sample: int = 1
    # deterministic jitter/probe schedule for tests; None = entropy
    seed: Optional[int] = None
    guard_workers: int = 4


class ResilientMatcher:
    """Wraps a device matcher (``DeltaMatcher`` or anything exposing
    ``match_topics_async``) with the circuit breaker + watchdog + probe
    machinery. Drop-in: the staging loop and ``subscribers`` callers see
    the same interface, every result stays bit-identical to the host
    trie (the host walk IS the fallback), and no caller ever waits past
    ``watchdog_s`` for a wedged device.

    Unknown attributes delegate to the wrapped matcher (``flush``,
    ``stats``, ``pending_deltas``, ...)."""

    def __init__(
        self,
        matcher: Any,
        topics: TopicsIndex,
        config: Optional[BreakerConfig] = None,
        host_walk: Optional[Callable[[str], Subscribers]] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        cfg = config or BreakerConfig()
        self.inner = matcher
        self.topics_index = topics
        self.host_walk = host_walk or topics.subscribers
        self.config = cfg
        self._trip_wake = threading.Event()
        self.breaker = CircuitBreaker(
            failure_threshold=cfg.failure_threshold,
            backoff=Backoff(
                initial=cfg.probe_backoff_s,
                maximum=cfg.probe_backoff_max_s,
                jitter=cfg.probe_jitter,
                seed=cfg.seed,
            ),
            probe_successes=cfg.probe_successes,
            clock=clock,
            on_trip=self._trip_wake.set,
        )
        self.pool = GuardPool(workers=cfg.guard_workers)
        self._stop = threading.Event()
        self._verify_rot = 0
        # replayable probe material: the last few live topics (a probe
        # against real traffic shapes exercises the real index paths)
        self._recent: list[str] = []
        self._recent_lock = threading.Lock()
        # fallback accounting (breaker_gauges)
        self.fallback_batches = 0
        self.fallback_topics = 0
        self.verified_batches = 0
        self._probe_thread = threading.Thread(
            target=self._probe_loop, daemon=True, name="mqtt-tpu-breaker-probe"
        )
        self._probe_thread.start()

    # -- delegation --------------------------------------------------------

    def __getattr__(self, name: str) -> Any:
        # only consulted for attributes not found on self: delegate the
        # wrapped matcher's surface (stats, flush, pending_deltas, ...)
        if name == "inner":  # not yet bound (partially-initialized self)
            raise AttributeError(name)
        return getattr(self.inner, name)

    # -- matching ----------------------------------------------------------

    def _host_batch(self, topics: list[str]) -> list[Subscribers]:
        self.fallback_batches += 1
        self.fallback_topics += len(topics)
        walk = self.host_walk
        return [walk(t) if t else Subscribers() for t in topics]

    def match_topics_async(
        self, topics: list[str], profile: Any = None
    ) -> Callable[[], list[Subscribers]]:
        """Issue one guarded batch; returns a zero-arg resolver whose
        wait is bounded by the watchdog budget. ``profile`` is the
        caller's optional per-batch BatchProfile (mqtt_tpu.tracing),
        forwarded to the wrapped matcher — the record rides WITH the
        batch, so eager guard-thread resolution can never attribute its
        device windows to another batch."""
        if topics:
            with self._recent_lock:
                self._recent.append(topics[0])
                del self._recent[:-8]
        if not self.breaker.allow() or self.pool.saturated:
            return lambda: self._host_batch(topics)
        inner = self.inner
        # verification baseline: a mutation any time after issue makes a
        # device-vs-host mismatch indeterminate (the device result is
        # bit-identical at RESOLVE time; the host walk at verify time may
        # legitimately have moved on), so _verify compares against this
        v_issue = self.topics_index.version
        try:
            # issue + resolve BOTH run on the guard thread: a dead link
            # can hang the upload/compile at issue time just as easily as
            # the D2H sync at resolve time, and neither may wedge the
            # caller (the event loop issues, the drainer resolves). The
            # submit happens NOW, so batch N+1's dispatch overlaps batch
            # N's resolve exactly as the unguarded pipeline did.
            if profile is None:
                # no kwarg when no record: wrapped matchers that predate
                # the profile contract (fault doubles, embedder shims)
                # keep working untouched
                issue = lambda: inner.match_topics_async(topics)()  # noqa: E731
            else:
                issue = lambda: inner.match_topics_async(  # noqa: E731
                    topics, profile=profile
                )()
            task = self.pool.submit(issue)
        except RuntimeError:  # pool closed (shutdown race)
            return lambda: self._host_batch(topics)

        def resolve() -> list[Subscribers]:
            try:
                results = task.wait(self.config.watchdog_s)
            except GuardTimeout:
                self.pool.report_wedged(task)
                self.breaker.record_failure("hang")
                _log.warning(
                    "device batch exceeded the %.3fs watchdog; host fallback",
                    self.config.watchdog_s,
                )
                return self._host_batch(topics)
            except Exception:
                self.breaker.record_failure("error")
                _log.exception("device batch failed; host fallback")
                return self._host_batch(topics)
            if not self._verify(topics, results, v_issue):
                self.breaker.record_failure("corrupt")
                _log.error(
                    "device result diverged from the host trie; host fallback"
                )
                return self._host_batch(topics)
            self.breaker.record_success()
            return results

        return resolve

    def match_topics(self, topics: list[str]) -> list[Subscribers]:
        return self.match_topics_async(topics)()

    def subscribers(self, topic: str) -> Subscribers:
        """Drop-in for ``TopicsIndex.subscribers`` (batch of one)."""
        return self.match_topics([topic])[0]

    # -- differential verification -----------------------------------------

    def _verify(
        self, topics: list[str], results: list[Subscribers], v_issue: int
    ) -> bool:
        """Re-walk ``verify_sample`` of the batch on the live host trie
        and compare. A mismatch while the trie has mutated since ISSUE is
        indeterminate — the device result was bit-identical at resolve
        time, but the live walk may legitimately have moved on (e.g. a
        SUBSCRIBE between resolve and verify) — and is skipped rather
        than counted as corruption."""
        from .ops.matcher import subscribers_equal

        k = self.config.verify_sample
        if k <= 0 or not topics:
            return True
        candidates = [i for i, t in enumerate(topics) if t]
        if not candidates:
            return True
        self._verify_rot += 1
        start = self._verify_rot % len(candidates)
        for j in range(min(k, len(candidates))):
            i = candidates[(start + j) % len(candidates)]
            host = self.host_walk(topics[i])
            if not subscribers_equal(results[i], host):
                if self.topics_index.version != v_issue:
                    continue  # churn window: indeterminate, skip
                return False
        self.verified_batches += 1
        return True

    # -- half-open probing --------------------------------------------------

    def _probe_topics(self) -> list[str]:
        with self._recent_lock:
            recent = list(dict.fromkeys(self._recent))
        return recent[-4:] or ["mqtt-tpu/breaker/probe"]

    def probe_now(self) -> bool:
        """Force one synchronous probe (tests / operator tooling); True
        when the probe verified healthy."""
        if not self.breaker.acquire_probe(force=True):
            return False
        return self._probe_once()

    def _probe_once(self) -> bool:
        """One HALF_OPEN probe: a small guarded batch, 100% verified
        against the live host walk. The caller must hold the probe slot;
        outcomes report through the probe-specific breaker paths so a
        stale live batch resolving mid-probe cannot claim the slot."""
        topics = self._probe_topics()
        from .ops.matcher import subscribers_equal

        if self.pool.live_unwedged <= 0:
            # every guard thread is stuck in a hung call: dispatching
            # another probe would only queue behind them and burn the
            # thread budget — recovery requires a hung call to return
            # first (a healed link unblocks them)
            self.breaker.record_probe_failure("saturated")
            return False
        v_issue = self.topics_index.version
        try:
            task = self.pool.submit(
                lambda: self.inner.match_topics_async(topics)()
            )
            results = task.wait(self.config.watchdog_s)
        except GuardTimeout:
            self.pool.report_wedged(task)
            self.breaker.record_probe_failure("hang")
            return False
        except Exception:
            self.breaker.record_probe_failure("error")
            return False
        for t, r in zip(topics, results):
            if not subscribers_equal(r, self.host_walk(t)):
                if self.topics_index.version != v_issue:
                    continue  # churn window: indeterminate
                self.breaker.record_probe_failure("corrupt")
                return False
        self.breaker.record_probe_success()
        return True

    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            self._trip_wake.wait()
            if self._stop.is_set():
                return
            self._trip_wake.clear()
            while not self._stop.is_set():
                delay = self.breaker.seconds_until_probe()
                if delay is None:  # CLOSED again: back to sleep
                    break
                if self._stop.wait(min(delay, 1.0)):
                    return
                if self.breaker.seconds_until_probe() not in (None, 0.0):
                    continue  # backoff not elapsed yet (bounded waits so
                    # close() never blocks behind a long schedule)
                if self.breaker.acquire_probe():
                    try:
                        self._probe_once()
                    except Exception:  # pragma: no cover - probe must not die
                        _log.exception("half-open probe crashed")
                        self.breaker.record_probe_failure("error")

    # -- observability / lifecycle -----------------------------------------

    def breaker_gauges(self) -> dict:
        """The $SYS gauge map (server.publish_sys_topics exports it under
        ``$SYS/broker/matcher/breaker/``)."""
        d = self.breaker.as_dict()
        d["fallback_batches"] = self.fallback_batches
        d["fallback_topics"] = self.fallback_topics
        d["verified_batches"] = self.verified_batches
        d["wedged_workers"] = self.pool.wedged
        return d

    def close(self) -> None:
        self._stop.set()
        self._trip_wake.set()
        self._probe_thread.join(timeout=2)
        self.pool.close()
        inner_close = getattr(self.inner, "close", None)
        if callable(inner_close):
            inner_close()
