"""Unified telemetry plane: low-overhead metrics registry, per-publish
stage clock, Prometheus text exposition, and a trigger-dumped flight
recorder.

The $SYS gauges from the overload governor (mqtt_tpu.overload) and the
matcher breaker (mqtt_tpu.resilience) are point-in-time counters; broker
benchmarking shows the differentiator under load is TAIL latency, not
throughput (PAPERS: "Benchmarking Message Brokers for IoT Edge
Computing"), and the broker itself is the right place for in-band
introspection (MQTT+). This module therefore instruments the publish
pipeline itself:

- ``MetricsRegistry``: monotonic counters, gauges (stored or
  callback-sampled at scrape time), and fixed-bucket log-scale
  ``Histogram``s with p50/p95/p99 extraction. Families carry Prometheus
  ``# HELP``/``# TYPE`` metadata and labeled children;
  ``exposition()`` renders the text format served at ``GET /metrics``
  (listeners/http.py) and ``sys_tree()`` renders the retained
  ``$SYS/broker/telemetry/#`` map (server.publish_sys_topics).
- ``StageClock``: one sampled publish's trip through the pipeline —
  decode -> admission -> staging wait -> device batch -> fanout write —
  stamped at each boundary and aggregated per-stage into histograms.
  Sampling is 1-in-N (``Options.telemetry_sample``, default 64): the
  unsampled hot path pays one integer increment and one modulo.
- ``FlightRecorder``: a bounded ring of recent stage-clock records that
  auto-dumps a JSON snapshot to disk when the overload governor enters
  SHED or the matcher breaker trips — the first storm in production
  comes with a trace, not a shrug. Dumps are rate-limited.

All knobs live on ``Options`` (``telemetry_*``) and the config file; the
plane is ON by default.
"""

from __future__ import annotations

import json
import logging
import math
import os
import re
import tempfile
import threading
import time
from bisect import bisect_left
from collections import deque
from typing import Any, Callable, Optional

_log = logging.getLogger("mqtt_tpu.telemetry")

# the publish pipeline's stage names, in pipeline order (the flight
# recorder and the bench telemetry block both key on these). The trace
# plane (mqtt_tpu.tracing) resolves ``device_batch`` into the three
# device sub-stages when the device profiler is wired; ``device_batch``
# stays populated as their sum so rounds diff across the split
# (exp/stage_gate.py).
PUBLISH_STAGES = (
    "decode",
    "admission",
    "staging_wait",
    "h2d",
    "device_dispatch",
    "d2h",
    "device_batch",
    "encode",
    "flush",
    "fanout",
)

# the device sub-stages the staging drain loop stamps when a device
# profiler is attached (canonical here — mqtt_tpu.tracing re-exports)
DEVICE_SUBSTAGES = ("h2d", "device_dispatch", "d2h")

# the fan-out sub-stages the batched write path stamps (ISSUE 13):
# ``encode`` covers variant grouping + the per-variant frame encodes,
# ``flush`` the delivery flush (batched writev + queue fallbacks).
# ``fanout`` stays populated as their sum — same continuity contract as
# the device_batch split (exp/stage_gate.py diffs old rounds unchanged).
FANOUT_SUBSTAGES = ("encode", "flush")

# the MQTT v5 user-property key a trace id rides on (client-visible
# traces, and adoption of client-supplied ids — mqtt_tpu.tracing)
TRACE_USER_PROPERTY = "trace-id"

# delivery-path labels on the per-tenant delivery-latency SLI
# (ISSUE 14): "local" is arrival-at-decode -> frame-flush on one
# worker; "remote" is the origin worker's elapsed stamp plus the
# receiving worker's delivery segment (network transit between the two
# is not measurable without synced clocks — the trace plane joins the
# two segments by id instead)
DELIVERY_PATHS = ("local", "remote")


def _fmt(v) -> str:
    """A Prometheus-compatible number: integral floats render without
    the trailing ``.0`` so counters read as counts."""
    if isinstance(v, float):
        if v == math.inf:
            return "+Inf"
        if v != v:  # NaN
            return "NaN"
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(v)
    return str(v)


def _exemplar_str(exemplars: Optional[list], i: int) -> str:
    """The OpenMetrics-style exemplar suffix for one bucket line —
    ``# {trace_id="..."} <value>`` — or "" when the bucket has none."""
    if exemplars is None or exemplars[i] is None:
        return ""
    v, trace_id = exemplars[i]
    return f' # {{trace_id="{escape_label_value(trace_id)}"}} {_fmt(float(v))}'


def escape_label_value(v: str) -> str:
    """Prometheus label-value escaping: backslash, double-quote, and
    newline must be escaped inside the quoted value."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def escape_help(v: str) -> str:
    """# HELP escaping: backslash and newline only (quotes are legal)."""
    return v.replace("\\", "\\\\").replace("\n", "\\n")


class Histogram:
    """A fixed-bucket log-scale histogram.

    Bucket upper bounds are ``base * growth**i`` (defaults: 1us growing
    x2 for 36 buckets, topping out around 34s) plus a +Inf overflow
    bucket — Prometheus ``le`` semantics (a value equal to a boundary
    counts in that bucket). Log-scale keeps relative error bounded at
    every magnitude, which is what latency percentiles need.

    Single-writer per instance (asyncio data plane or one worker
    thread); cross-thread aggregation goes through ``merge`` — each
    thread owns a shard and the scrape merges them. A registry child
    may instead be backed by a scrape-time callback returning a merged
    snapshot (``fn``, see :meth:`live`): the sharded matcher's
    per-shard compile histograms render this way without the workers
    ever sharing a hot write path.
    """

    __slots__ = ("bounds", "counts", "count", "sum", "fn", "exemplars")

    def __init__(
        self,
        base: float = 1e-6,
        growth: float = 2.0,
        n_buckets: int = 36,
        bounds: Optional[tuple] = None,
    ) -> None:
        if bounds is not None:
            self.bounds = tuple(float(b) for b in bounds)
        else:
            self.bounds = tuple(base * growth**i for i in range(n_buckets))
        self.counts = [0] * (len(self.bounds) + 1)  # [-1] is +Inf
        self.count = 0
        self.sum = 0.0
        self.fn: Optional[Callable[[], "Histogram"]] = None
        # per-bucket (value, trace_id) exemplars, last-write-wins; None
        # until enable_exemplars() — the off-trace observe() path pays
        # one is-None check (mqtt_tpu.tracing / OpenMetrics exemplars)
        self.exemplars: Optional[list] = None

    def enable_exemplars(self) -> None:
        """Retain the last sampled (value, trace_id) per bucket; the
        exposition cross-links a p99 bucket to a concrete recorded
        trace. Merge() deliberately ignores exemplars (shard merges are
        scrape-time aggregates; the shards keep their own)."""
        if self.exemplars is None:
            self.exemplars = [None] * (len(self.bounds) + 1)

    def live(self) -> "Histogram":
        """The histogram to render at scrape time: the callback's merged
        snapshot when one is attached, else this instance. A failing
        callback renders the (empty) stored instance — a scrape must
        never take the broker down."""
        if self.fn is None:
            return self
        try:
            merged = self.fn()
        except Exception:
            _log.exception("histogram callback failed")
            return self
        return merged if isinstance(merged, Histogram) else self

    def observe(self, v: float, trace_id: Optional[str] = None) -> None:
        # bisect_left(bounds, v): first bound >= v — exactly `le`
        i = bisect_left(self.bounds, v)
        self.counts[i] += 1
        self.count += 1
        self.sum += v
        if trace_id is not None and self.exemplars is not None:
            self.exemplars[i] = (v, trace_id)

    def percentile(self, q: float) -> float:
        """The q-quantile's bucket upper bound (0.0 when empty; the
        largest finite bound for observations past it). Rank uses the
        ceiling so a single observation answers every quantile with its
        own bucket."""
        if self.count == 0:
            return 0.0
        rank = max(1, math.ceil(q * self.count))
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= rank:
                return self.bounds[i] if i < len(self.bounds) else self.bounds[-1]
        return self.bounds[-1]  # pragma: no cover - rank <= count

    def merge(self, other: "Histogram") -> None:
        """Fold another shard (identical bucket layout) into this one."""
        if other.bounds != self.bounds:
            raise ValueError("histogram bucket layouts differ; cannot merge")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.sum += other.sum

    def count_le(self, v: float) -> int:
        """Observations in buckets whose upper bound is <= ``v`` — the
        'good event' count for a latency SLO threshold. The threshold is
        snapped DOWN to the largest bucket bound at or below it, so an
        off-bucket threshold errs toward counting borderline
        observations as bad (an SLO gate should alarm early, not late —
        mqtt_tpu.slo)."""
        # bisect_right-style: first bound strictly greater than v
        i = bisect_left(self.bounds, v)
        if i < len(self.bounds) and self.bounds[i] == v:
            i += 1
        return sum(self.counts[:i])

    def summary(self) -> dict:
        return {
            "count": self.count,
            "sum": round(self.sum, 6),
            "p50": self.percentile(0.50),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
        }


class Counter:
    """A monotonic counter (single-writer; the GIL makes ``+=`` on the
    slot safe enough for telemetry from helper threads). Like Gauge it
    may instead be backed by a scrape-time callback — for mirroring
    counters another layer already maintains (system.Info,
    MatcherStats) without a second bookkeeping path, while still
    exposing honest ``# TYPE counter`` metadata for the ``_total``
    series."""

    __slots__ = ("_value", "fn")

    def __init__(self, fn: Optional[Callable[[], float]] = None) -> None:
        self._value = 0
        self.fn = fn

    def inc(self, n: int = 1) -> None:
        self._value += n

    @property
    def value(self):
        if self.fn is not None:
            try:
                return self.fn()
            except Exception:  # a scrape must not take the broker down
                _log.exception("counter callback failed")
                return 0
        return self._value


class Gauge:
    """A point-in-time value: either ``set()`` by the owner or backed by
    a zero-arg callable sampled at scrape time."""

    __slots__ = ("_value", "fn")

    def __init__(self, fn: Optional[Callable[[], float]] = None) -> None:
        self._value = 0.0
        self.fn = fn

    def set(self, v: float) -> None:
        self._value = v

    def value(self):
        if self.fn is not None:
            try:
                return self.fn()
            except Exception:  # a scrape must not take the broker down
                _log.exception("gauge callback failed")
                return 0.0
        return self._value


_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


class _Family:
    __slots__ = ("name", "mtype", "help", "children", "maker")

    def __init__(self, name: str, mtype: str, help_: str, maker) -> None:
        self.name = name
        self.mtype = mtype
        self.help = help_
        # Counter | Gauge | Histogram, keyed on the sorted label tuple;
        # Any because the renderers isinstance-dispatch per child
        self.children: dict[tuple, Any] = {}
        self.maker = maker


class MetricsRegistry:
    """Named metric families with labeled children and two renderers:
    Prometheus text exposition and the flat $SYS topic map."""

    def __init__(self) -> None:
        # lock-plane adoption (mqtt_tpu.utils.locked): every scrape
        # walks this lock against concurrent child registration, so it
        # is itself a measured contention point. Lazy import — locked.py
        # imports this module's Histogram.
        from .utils.locked import InstrumentedLock

        self._lock = InstrumentedLock("metrics_registry")
        self._families: dict[str, _Family] = {}
        # render per-bucket trace exemplars in exposition() (OpenMetrics
        # style; set via Telemetry.attach_tracer — Options.trace_exemplars)
        self.emit_exemplars = False

    def _child(self, name: str, mtype: str, help_: str, labels: dict, maker):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name: {name!r}")
        key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(name, mtype, help_, maker)
            elif fam.mtype != mtype:
                raise ValueError(
                    f"metric {name!r} re-registered as {mtype} (was {fam.mtype})"
                )
            child = fam.children.get(key)
            if child is None:
                child = fam.children[key] = maker()
            return child

    def counter(
        self, name: str, help: str = "", fn: Optional[Callable] = None, **labels
    ) -> Counter:
        c = self._child(name, "counter", help, labels, Counter)
        if fn is not None:
            c.fn = fn
        return c

    def gauge(
        self, name: str, help: str = "", fn: Optional[Callable] = None, **labels
    ) -> Gauge:
        g = self._child(name, "gauge", help, labels, Gauge)
        if fn is not None:
            g.fn = fn
        return g

    def histogram(
        self,
        name: str,
        help: str = "",
        bounds: Optional[tuple] = None,
        fn: Optional[Callable] = None,
        **labels,
    ) -> Histogram:
        h = self._child(
            name, "histogram", help, labels, lambda: Histogram(bounds=bounds)
        )
        if fn is not None:
            # scrape-time snapshot callback (per-thread shard merging):
            # the renderers resolve through Histogram.live()
            h.fn = fn
        return h

    # -- rendering ---------------------------------------------------------

    @staticmethod
    def _labels_str(key: tuple, extra: str = "") -> str:
        parts = [f'{k}="{escape_label_value(v)}"' for k, v in key]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def exposition(self) -> str:
        """The Prometheus text exposition format (version 0.0.4) served
        at ``GET /metrics``."""
        with self._lock:
            families = sorted(self._families.items())
        out: list[str] = []
        for name, fam in families:
            if fam.help:
                out.append(f"# HELP {name} {escape_help(fam.help)}")
            out.append(f"# TYPE {name} {fam.mtype}")
            for key, child in sorted(fam.children.items()):
                if isinstance(child, Counter):
                    out.append(f"{name}{self._labels_str(key)} {_fmt(child.value)}")
                elif isinstance(child, Gauge):
                    out.append(
                        f"{name}{self._labels_str(key)} {_fmt(child.value())}"
                    )
                else:  # Histogram (callback-backed ones snapshot here)
                    child = child.live()
                    ex = child.exemplars if self.emit_exemplars else None
                    acc = 0
                    for i, bound in enumerate(child.bounds):
                        acc += child.counts[i]
                        le = self._labels_str(key, f'le="{_fmt(float(bound))}"')
                        out.append(
                            f"{name}_bucket{le} {acc}" + _exemplar_str(ex, i)
                        )
                    le = self._labels_str(key, 'le="+Inf"')
                    out.append(
                        f"{name}_bucket{le} {_fmt(child.count)}"
                        + _exemplar_str(ex, -1)
                    )
                    out.append(
                        f"{name}_sum{self._labels_str(key)} {_fmt(child.sum)}"
                    )
                    out.append(
                        f"{name}_count{self._labels_str(key)} {_fmt(child.count)}"
                    )
        return "\n".join(out) + "\n"

    def sys_tree(self) -> dict:
        """A flat ``topic-suffix -> value`` map for the retained
        ``$SYS/broker/telemetry/#`` tree. ``*_seconds`` histograms
        surface their percentile summary in milliseconds (readability —
        the raw seconds live on /metrics); dimensionless histograms
        (fill ratios) surface the raw quantile values."""
        with self._lock:
            families = sorted(self._families.items())
        out: dict[str, object] = {}
        for name, fam in families:
            short = name.removeprefix("mqtt_tpu_")
            in_seconds = name.endswith("_seconds")
            for key, child in sorted(fam.children.items()):
                suffix = "/".join(v for _, v in key)
                base = f"{short}/{suffix}" if suffix else short
                if isinstance(child, Counter):
                    out[base] = child.value
                elif isinstance(child, Gauge):
                    v = child.value()
                    out[base] = round(v, 6) if isinstance(v, float) else v
                else:
                    s = child.live().summary()
                    out[f"{base}/count"] = s["count"]
                    for q in ("p50", "p95", "p99"):
                        if in_seconds:
                            out[f"{base}/{q}_ms"] = round(s[q] * 1e3, 3)
                        else:
                            out[f"{base}/{q}"] = round(s[q], 6)
        return out

    def family_children(self, name: str) -> list:
        """Snapshot of one family's ``(label-key, child)`` pairs (the
        SLO engine walks the delivery-latency family through this — the
        children themselves are read lock-free, like exposition())."""
        with self._lock:
            fam = self._families.get(name)
            return [] if fam is None else list(fam.children.items())

    def summary(self) -> dict:
        """The wire summary one worker contributes to mesh metric
        federation (ISSUE 14, cluster ``_T_METRICS`` frames): every
        family's type plus per-child values — counters/gauges as
        numbers, histograms as ``{n, s, c}`` (count, sum, bucket-count
        vector with trailing zeros trimmed) beside the family's shared
        ``le`` bounds. Values are ABSOLUTE cumulative snapshots, not
        deltas: the receiver keys them by (worker, boot, seq), so a
        re-delivered or reordered frame can never double-count and a
        restarted worker's reset counters simply replace its entry."""
        with self._lock:
            families = sorted(self._families.items())
        fams: dict[str, dict] = {}
        for name, fam in families:
            children: list = []
            bounds: Optional[list] = None
            for key, child in sorted(fam.children.items()):
                labels = [[k, v] for k, v in key]
                if isinstance(child, Counter):
                    children.append([labels, child.value])
                elif isinstance(child, Gauge):
                    children.append([labels, child.value()])
                else:
                    h = child.live()
                    if bounds is None:
                        bounds = list(h.bounds)
                    elif list(h.bounds) != bounds:
                        continue  # a mixed-layout child cannot fold
                    counts = list(h.counts)
                    while counts and counts[-1] == 0:
                        counts.pop()
                    children.append(
                        [labels, {"n": h.count, "s": round(h.sum, 9), "c": counts}]
                    )
            entry: dict = {"t": fam.mtype, "c": children}
            if fam.mtype == "histogram" and bounds is not None:
                entry["le"] = bounds
            fams[name] = entry
        return fams


class StageClock:
    """One sampled publish's trip through the pipeline: ``stamp(stage)``
    records the time since the previous stamp as that stage's duration.
    Cheap by construction — two perf_counter calls and a list append per
    stage, and only 1-in-N publishes carry one at all."""

    __slots__ = ("t0", "last", "stages")

    def __init__(self) -> None:
        self.t0 = self.last = time.perf_counter()
        self.stages: list[tuple[str, float]] = []

    def stamp(self, stage: str) -> None:
        now = time.perf_counter()
        self.stages.append((stage, now - self.last))
        self.last = now

    def stamp_until(self, stage: str, t: float) -> None:
        """Stamp a stage ending at an EXPLICIT perf_counter time (the
        staging drain loop splits device_batch into h2d/dispatch/d2h
        using boundaries measured on the resolver's thread). Clamped so
        a boundary that raced behind the previous stamp records a
        zero-length stage instead of corrupting the running total."""
        if t < self.last:
            t = self.last
        self.stages.append((stage, t - self.last))
        self.last = t

    def total(self) -> float:
        return self.last - self.t0


class RemoteStageClock(StageClock):
    """The receiving-side stage clock of a mesh-forwarded publish
    (ISSUE 14): carries the origin worker's elapsed-at-forward stamp
    (``el`` on the frame head) so the remote-path delivery SLI reads
    origin-segment + local-segment, and the origin's trace id (when the
    forward was traced) so the sample's histogram exemplar joins the
    cross-worker trace. Never routed through observe_publish — remote
    deliveries must not skew the local pipeline-stage histograms or the
    flight ring; only the delivery-latency family sees them."""

    __slots__ = ("remote_base", "trace_id")

    def __init__(
        self, remote_base: float = 0.0, trace_id: Optional[str] = None
    ) -> None:
        super().__init__()
        self.remote_base = remote_base
        self.trace_id = trace_id


class FlightRecorder:
    """A bounded ring of recent stage-clock records, JSON-dumped to disk
    when a degradation trigger fires (overload SHED, breaker trip).
    Dumps are rate-limited so a flapping posture cannot fill the disk."""

    def __init__(
        self,
        size: int = 256,
        dump_dir: str = "",
        min_interval_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.ring: deque = deque(maxlen=max(1, size))
        # "" = a private mkdtemp created lazily at the first dump: a FIXED
        # path in the shared tempdir would let any local user pre-create
        # the directory (symlink-clobber the predictable filenames) and
        # read the dumped topic names; mkdtemp is 0700 and unpredictable,
        # and the dump log line carries the chosen path
        self.dump_dir = dump_dir
        self.min_interval_s = min_interval_s
        self.clock = clock
        self.dumps = 0
        self.dumps_suppressed = 0
        self._last_dump = float("-inf")
        # lock-plane adoption: the event loop appends to the ring under
        # this lock on every sampled publish while dump threads snapshot
        from .utils.locked import InstrumentedLock

        self._lock = InstrumentedLock("flight_ring")
        self._writers: list[threading.Thread] = []

    def add(self, record: dict) -> None:
        # under the lock: a cross-thread dump() iterating the ring while
        # the event loop appends would raise "deque mutated during
        # iteration" and silently lose the trigger's trace. The critical
        # section is one append — dump()'s file IO runs OUTSIDE the lock
        with self._lock:
            self.ring.append(record)

    def dump_async(
        self,
        reason: str,
        extra: Optional[dict] = None,
        after: Optional[Callable[[str, str], None]] = None,
    ) -> None:
        """Fire-and-forget dump on a daemon thread: degradation triggers
        run under the breaker lock / on the event loop's hot path, where
        synchronous disk IO would stall the data plane at exactly peak
        load. Rate-limiting still applies inside dump(); ``after`` runs
        on the writer thread with (path, reason) only when a dump was
        actually written (the trace-plane sibling dump rides it)."""

        def _write() -> None:
            path = self.dump(reason, extra)
            if path is not None and after is not None:
                try:
                    after(path, reason)
                except Exception:
                    _log.exception("post-dump hook failed (reason=%s)", reason)

        t = threading.Thread(
            target=_write,
            daemon=True,
            name="mqtt-tpu-flight-dump",
        )
        with self._lock:
            # track EVERY live writer, not just the newest: a rate-limited
            # no-op thread must not mask an earlier write still on disk
            self._writers = [w for w in self._writers if w.is_alive()]
            self._writers.append(t)
        t.start()

    def join_writer(self, timeout: float = 5.0) -> None:
        """Wait for all in-flight async dumps (tests, orderly shutdown)."""
        with self._lock:
            writers = list(self._writers)
        for t in writers:
            t.join(timeout)

    def dump(self, reason: str, extra: Optional[dict] = None) -> Optional[str]:
        """Write the ring (plus trigger context) to one JSON file;
        returns the path, or None when rate-limited or the write failed.
        Thread-safe: triggers fire from the event loop, the breaker's
        probe thread, and sweep paths."""
        with self._lock:
            now = self.clock()
            if now - self._last_dump < self.min_interval_s:
                self.dumps_suppressed += 1
                return None
            self._last_dump = now
            records = list(self.ring)
        if not self.dump_dir:
            # first dump: a private 0700 dir (see __init__'s note). The
            # mkdtemp disk I/O runs OUTSIDE the lock (brokerlint R1 — the
            # event loop appends to the ring under it); two racing first
            # dumps each get a dir and the double-checked store below picks
            # one winner (the loser's empty tmpdir is harmless)
            ddir = tempfile.mkdtemp(prefix="mqtt_tpu_flight_")
            with self._lock:
                if not self.dump_dir:
                    self.dump_dir = ddir
        snapshot = {
            "reason": reason,
            "time_unix": int(time.time()),  # brokerlint: ok=R3 dump timestamps are wall-clock by design (operator-correlatable)
            "records": records,
            "context": extra or {},
            # the trace cross-link: every trace id active in the ring at
            # trigger time, deduped (records keep their own trace_id too)
            "trace_ids": sorted(
                {
                    r["trace_id"]
                    for r in records
                    if isinstance(r, dict) and "trace_id" in r
                }
            ),
        }
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            safe = re.sub(r"[^a-zA-Z0-9_.-]", "_", reason)
            path = os.path.join(
                self.dump_dir,
                # brokerlint: ok=R3 dump filenames carry the wall-clock stamp
                f"flight_{int(time.time())}_{safe}.json",
            )
            with open(path, "w") as f:
                json.dump(snapshot, f, indent=1)
        except OSError:
            _log.exception("flight-recorder dump failed (dir=%s)", self.dump_dir)
            return None
        self.dumps += 1
        _log.warning(
            "flight recorder dumped %d records to %s (reason=%s)",
            len(records),
            path,
            reason,
        )
        return path


# batch fill ratio buckets: linear deciles (a ratio is not log-shaped)
FILL_BOUNDS = tuple(round(0.1 * i, 1) for i in range(1, 11))


class Telemetry:
    """The broker's telemetry facade: owns the registry, the per-stage
    publish histograms, the flight recorder, and the sampling counters.
    Every instrumented layer (server, staging, clients, matcher,
    cluster) talks to this object; every exposition surface (/metrics,
    $SYS, BENCH json) renders from it."""

    def __init__(
        self,
        sample: int = 64,
        ring: int = 256,
        dump_dir: str = "",
        dump_min_interval_s: float = 30.0,
    ) -> None:
        self.registry = MetricsRegistry()
        self.sample = max(0, int(sample))  # 0 disables stage sampling
        self._n = 0  # publish counter for 1-in-N sampling
        self._out_n = 0  # outbound-enqueue counter (same 1-in-N rate)
        # the trace plane (mqtt_tpu.tracing.Tracer) or None; attached by
        # the server via attach_tracer() — publish_clock consults it so
        # 1-in-trace_sample publishes carry a full trace context
        self.tracer: Any = None
        # the host profiler (mqtt_tpu.profiling.SamplingProfiler) or
        # None; attached by the server via attach_profiler() — serves
        # GET /profile and rides trigger dumps
        self.host_profiler: Any = None
        # the lock-contention plane (mqtt_tpu.utils.locked.LockPlane)
        # or None; attached via attach_lock_plane()
        self.lock_plane: Any = None
        # the per-device observability plane (ops/devicestats.
        # DeviceStatsPlane) or None; attached via attach_device_stats()
        # — serves GET /devices, $SYS/broker/devices/#, and grows
        # trigger dumps a ``devices_*.json`` sibling
        self.device_stats: Any = None
        # cluster-wide SLO observatory (ISSUE 14): the delivery-latency
        # SLI gate (one bool test on the sampled path; Options.slo), the
        # SLO burn-rate engine (mqtt_tpu.slo.SLOEngine) and the mesh
        # metric-federation store (ClusterMetrics, attached by the
        # cluster so /metrics/cluster and /cluster/slo can render)
        self.delivery_sli = True
        self._delivery_cache: dict[tuple, Histogram] = {}
        self.slo: Any = None
        self.cluster_metrics: Any = None
        # this worker's id as a federation label (the cluster stamps it
        # when it attaches; single-worker brokers render as "0")
        self.local_worker = "0"
        self.recorder = FlightRecorder(
            size=ring, dump_dir=dump_dir, min_interval_s=dump_min_interval_s
        )
        r = self.registry
        self.stage_hist = {
            s: r.histogram(
                "mqtt_tpu_publish_stage_seconds",
                "Sampled per-publish latency by pipeline stage",
                stage=s,
            )
            for s in PUBLISH_STAGES
        }
        self.sampled_publishes = r.counter(
            "mqtt_tpu_publish_sampled_total",
            "Publishes that carried a stage clock (1-in-N sampling)",
        )
        self.batch_service = r.histogram(
            "mqtt_tpu_stage_batch_service_seconds",
            "Device match-batch resolve wall time (every batch)",
        )
        self.batch_fill = r.histogram(
            "mqtt_tpu_stage_batch_fill_ratio",
            "Match-batch occupancy against the adaptive batch cap",
            bounds=FILL_BOUNDS,
        )
        self.outbound_wait = r.histogram(
            "mqtt_tpu_outbound_queue_wait_seconds",
            "Sampled wait of an outbound publish in a client queue",
        )
        # per-leg pipeline handoff waits (ROADMAP item 1's 3-deep
        # overlapped staging): how long a formed batch waited before the
        # h2d issue thread picked it up, and how long an issued batch
        # waited before the d2h drain thread started its sync — both sit
        # near zero when the pipeline is actually full
        self.leg_wait = {
            leg: r.histogram(
                "mqtt_tpu_staging_leg_wait_seconds",
                "Per-batch handoff wait before a staging pipeline leg "
                "started",
                leg=leg,
            )
            for leg in ("h2d", "d2h")
        }
        self.fallback = {
            k: r.counter(
                "mqtt_tpu_stage_fallback_total",
                "Publishes resolved by the host walk instead of the "
                "device batch, by cause",
                **{"class": k},
            )
            for k in ("admission", "issue_error", "resolve_error", "stop")
        }
        self.rebuild_hist = r.histogram(
            "mqtt_tpu_matcher_rebuild_seconds",
            "Device index compile/rebuild/fold wall time",
        )
        r.counter(
            "mqtt_tpu_flight_dumps_total",
            "Flight-recorder dumps written",
            fn=lambda: self.recorder.dumps,
        )
        # write-path / fan-out amplification accounting (ROADMAP item 3:
        # the per-subscriber re-encode waste the encode-once rewrite will
        # eliminate — encodes / inbound publishes is its success metric)
        self.publish_encodes = r.counter(
            "mqtt_tpu_publish_encodes_total",
            "Outbound PUBLISH packet encodes (clients.write_packet + "
            "the fan-out frame cache's per-variant encodes)",
        )
        self.fanout_deliveries = r.counter(
            "mqtt_tpu_fanout_deliveries_total",
            "Outbound PUBLISH deliveries written (shared-frame and "
            "per-subscriber legs)",
        )
        self.outbound_bytes = r.counter(
            "mqtt_tpu_outbound_bytes_total",
            "Bytes written to client transports by the outbound write "
            "paths",
        )
        self.outbound_writes = r.counter(
            "mqtt_tpu_outbound_writes_total",
            "Socket write calls issued by the outbound write paths",
        )
        # zero-materialization fan-out accounting (ISSUE 13): variants
        # are the encode-once unit (amplification ~1 per variant is the
        # success metric), writev batches count the GIL-released flush
        # calls. View materializations are exported separately as a
        # callback counter over the C module's own stats (server wiring).
        self.fanout_variants = r.counter(
            "mqtt_tpu_fanout_variants_total",
            "Distinct (version, QoS, retain) encode variants the batched "
            "fan-out produced — one wire encode each",
        )
        self.fanout_writev_batches = r.counter(
            "mqtt_tpu_fanout_writev_batches_total",
            "GIL-released batched socket flush calls issued by the "
            "fan-out write path",
        )

    # -- delivery-latency SLIs (ISSUE 14) ----------------------------------

    def delivery_hist(self, tenant: str, qos: int, path: str) -> Histogram:
        """The labeled delivery-latency child for one (tenant, qos,
        path) cell, cached so the sampled path pays one dict probe
        instead of the registry lock."""
        key = (tenant, qos, path)
        h = self._delivery_cache.get(key)
        if h is None:
            h = self.registry.histogram(
                "mqtt_tpu_delivery_latency_seconds",
                "Publish arrival (decode) to frame flushed toward the "
                "subscriber socket, by tenant, publish QoS and delivery "
                "path (sampled 1-in-N; path=remote adds the origin "
                "worker's elapsed stamp to the receiving segment)",
                tenant=tenant,
                qos=str(qos),
                path=path,
            )
            if self.registry.emit_exemplars:
                h.enable_exemplars()
            self._delivery_cache[key] = h
        return h

    def observe_delivery(
        self,
        seconds: float,
        tenant: str,
        qos: int,
        path: str,
        trace_id: Optional[str] = None,
    ) -> None:
        """Record one sampled publish's arrival->flush delivery latency
        — the headline SLI the SLO engine burns against (mqtt_tpu.slo).
        Disabled (one bool test) when Options.slo is off."""
        if not self.delivery_sli:
            return
        self.delivery_hist(tenant, qos, path).observe(seconds, trace_id)

    def delivery_summary(self) -> dict:
        """Per-path delivery-latency fold across every (tenant, qos)
        cell — the bench/stage-gate face of the SLI family (rows
        ``delivery_local`` / ``delivery_remote`` in bench_block)."""
        out: dict = {}
        for path in DELIVERY_PATHS:
            merged: Optional[Histogram] = None
            for (_t, _q, p), h in list(self._delivery_cache.items()):
                if p != path or not h.count:
                    continue
                if merged is None:
                    merged = Histogram(bounds=h.bounds)
                merged.merge(h)
            if merged is not None and merged.count:
                out[f"delivery_{path}"] = {
                    "count": merged.count,
                    "p50_ms": round(merged.percentile(0.5) * 1e3, 3),
                    "p99_ms": round(merged.percentile(0.99) * 1e3, 3),
                }
        return out

    def attach_slo(self, engine: Any) -> None:
        """Attach the SLO burn-rate engine (mqtt_tpu.slo.SLOEngine):
        GET /cluster/slo serves its state beside the federated view."""
        self.slo = engine

    def attach_cluster_metrics(self, cm: Any) -> None:
        """Attach the mesh metric-federation store (ClusterMetrics,
        fed by cluster ``_T_METRICS`` frames): GET /metrics/cluster
        renders the per-worker + cluster-folded exposition from it."""
        self.cluster_metrics = cm

    # -- publish stage sampling --------------------------------------------

    def attach_tracer(self, tracer: Any, exemplars: bool = True) -> None:
        """Attach the trace plane (mqtt_tpu.tracing.Tracer): sampled
        publish clocks become trace contexts, finished clocks emit span
        trees, and (when ``exemplars``) the stage histograms retain
        per-bucket trace exemplars rendered on /metrics."""
        self.tracer = tracer
        if exemplars:
            for h in self.stage_hist.values():
                h.enable_exemplars()
            self.registry.emit_exemplars = True

    def attach_profiler(self, profiler: Any) -> None:
        """Attach the host sampling profiler
        (mqtt_tpu.profiling.SamplingProfiler): GET /profile serves its
        exports and trigger dumps grow a ``profile_*.txt`` sibling."""
        self.host_profiler = profiler

    def attach_device_stats(self, plane: Any) -> None:
        """Attach the per-device observability plane
        (mqtt_tpu.ops.devicestats.DeviceStatsPlane): GET /devices and
        the $SYS devices tree serve its snapshot, and trigger dumps
        write a ``devices_*.json`` sibling beside flight/traces/
        profile."""
        self.device_stats = plane

    def attach_lock_plane(self, plane: Any) -> None:
        """Attach the lock-contention plane
        (mqtt_tpu.utils.locked.LockPlane): every canonical lock name
        exports wait/hold histograms, acquisition/contention counters,
        and the wait-share gauge set (the top-K contended-locks view is
        this family sorted by share)."""
        self.lock_plane = plane
        # local import: utils.locked imports telemetry.Histogram, so the
        # reverse edge must resolve lazily
        from .utils.locked import LOCK_NAMES

        r = self.registry
        for name in LOCK_NAMES:
            st = plane.stats(name)
            r.histogram(
                "mqtt_tpu_lock_wait_seconds",
                "Time acquirers spent blocked on a named broker lock",
                lock=name,
                fn=lambda s=st: s.wait_hist,
            )
            r.histogram(
                "mqtt_tpu_lock_hold_seconds",
                "Time holders kept a named broker lock",
                lock=name,
                fn=lambda s=st: s.hold_hist,
            )
            r.counter(
                "mqtt_tpu_lock_acquisitions_total",
                "Acquisitions of a named broker lock",
                lock=name,
                fn=lambda s=st: s.acquisitions,
            )
            r.counter(
                "mqtt_tpu_lock_contended_total",
                "Acquisitions that actually blocked on a named broker lock",
                lock=name,
                fn=lambda s=st: s.contended,
            )
            r.gauge(
                "mqtt_tpu_lock_wait_share_ratio",
                "This lock's share of all measured lock wait time "
                "(sort descending for the top-K contended locks)",
                lock=name,
                fn=lambda n=name: plane.wait_share(n),
            )

    def fanout_block(self, inbound_publishes: int) -> dict:
        """The BENCH-json fan-out amplification block: encodes and
        deliveries per inbound PUBLISH — the number ROADMAP item 3's
        encode-once rewrite must drive toward ~1 encode/publish."""
        inbound = max(1, int(inbound_publishes))
        return {
            "inbound_publishes": int(inbound_publishes),
            "publish_encodes": self.publish_encodes.value,
            "fanout_deliveries": self.fanout_deliveries.value,
            "outbound_bytes": self.outbound_bytes.value,
            "outbound_writes": self.outbound_writes.value,
            "fanout_variants": self.fanout_variants.value,
            "fanout_writev_batches": self.fanout_writev_batches.value,
            "encode_amplification": round(
                self.publish_encodes.value / inbound, 4
            ),
            "delivery_amplification": round(
                self.fanout_deliveries.value / inbound, 4
            ),
            # encodes per VARIANT-GROUPED fan-out tick: ~1 when the
            # batched path is doing its job (the ISSUE 13 acceptance
            # number). Ticks that never grouped (legacy path) keep the
            # plain encode_amplification as their signal.
            "encode_per_variant": round(
                self.publish_encodes.value
                / max(1, self.fanout_variants.value),
                4,
            )
            if self.fanout_variants.value
            else None,
        }

    def publish_clock(self) -> Optional[StageClock]:
        """A StageClock for 1-in-N publishes, None for the rest; when
        the trace plane is attached, 1-in-trace_sample publishes get a
        PublishTrace (a StageClock that also carries a trace id). The
        unsampled path is one increment and two modulos."""
        self._n += 1
        tracer = self.tracer
        if (
            tracer is not None
            and tracer.sample
            and self._n % tracer.sample == 0
        ):
            return tracer.publish_trace()
        if self.sample == 0 or self._n % self.sample:
            return None
        return StageClock()

    def adopt_trace(self, pk: Any) -> Optional[StageClock]:
        """Adopt a client-supplied trace id: an inbound v5 PUBLISH whose
        user properties carry ``trace-id`` gets a trace context with
        THAT id (TD-MQTT-style transparent tracing — the client picks
        the id, the broker's spans join it), keeping any stamps the read
        loop already recorded. Returns the packet's (possibly new)
        clock; cost off the adopted path is the caller's empty-list
        check."""
        tracer = self.tracer
        clock = getattr(pk, "_tclock", None)
        if tracer is None or getattr(clock, "trace_id", None) is not None:
            return clock
        tid = ""
        for u in pk.properties.user:
            if u.key == TRACE_USER_PROPERTY and u.val:
                tid = u.val
                break
        if not tid or not tracer.allow_adopt():
            # adoption is rate-bounded (Tracer.allow_adopt): a client
            # stamping every publish cannot bypass trace_sample or
            # flood the ring; over-budget publishes flow untraced
            return clock
        trace = tracer.publish_trace(tid)
        if clock is not None:  # graft the read loop's decode stamp over
            trace.t0 = clock.t0
            trace.last = clock.last
            trace.stages = clock.stages
        pk._tclock = trace
        return trace

    def observe_publish(self, clock: StageClock, topic: str = "", qos: int = 0) -> None:
        """Fold one finished stage clock into the per-stage histograms
        and the flight-recorder ring; a traced clock additionally emits
        its span tree into the trace ring and stamps bucket exemplars."""
        trace_id = getattr(clock, "trace_id", None)
        hist = self.stage_hist
        sub_total = 0.0
        have_sub = False
        explicit_batch = False
        fan_total = 0.0
        have_fan = False
        explicit_fanout = False
        for stage, dt in clock.stages:
            h = hist.get(stage)
            if h is not None:
                h.observe(dt, trace_id)
            if stage in DEVICE_SUBSTAGES:
                sub_total += dt
                have_sub = True
            elif stage == "device_batch":
                explicit_batch = True
            elif stage in FANOUT_SUBSTAGES:
                fan_total += dt
                have_fan = True
            elif stage == "fanout":
                explicit_fanout = True
        if have_sub and not explicit_batch:
            # continuity across the sub-stage split: device_batch stays
            # populated as the sum, so stage_gate diffs old rounds (an
            # explicitly-stamped device_batch — the exact-map / host
            # fallback path — must not be observed twice)
            hist["device_batch"].observe(sub_total, trace_id)
        if have_fan and not explicit_fanout:
            # same continuity contract for the fan-out split: the batched
            # write path stamps encode/flush, legacy paths stamp fanout —
            # either way the coarse stage keeps diffing across rounds
            hist["fanout"].observe(fan_total, trace_id)
        self.sampled_publishes.inc()
        record = {
            # brokerlint: ok=R3 flight records carry wall-clock stamps
            "t": round(time.time(), 3),
            "topic": topic,
            "qos": qos,
            "total_ms": round(clock.total() * 1e3, 3),
            "stages_ms": {
                s: round(dt * 1e3, 4) for s, dt in clock.stages
            },
        }
        if trace_id is not None:
            # the flight-dump <-> trace cross-link: a SHED dump's records
            # name the concrete traces active at trigger time
            record["trace_id"] = trace_id
        self.recorder.add(record)
        tracer = self.tracer
        if trace_id is not None and tracer is not None:
            tracer.finish_publish(clock, topic, qos)

    def sample_outbound(self) -> bool:
        """1-in-N gate for outbound queue-wait stamps (same rate as the
        stage clock)."""
        if self.sample == 0:
            return False
        self._out_n += 1
        return self._out_n % self.sample == 0

    # -- batch-level observations (staging loop) ---------------------------

    def observe_batch(self, service_s: float, n: int, cap: int) -> None:
        self.batch_service.observe(service_s)
        if cap > 0:
            self.batch_fill.observe(min(1.0, n / cap))

    def observe_leg_wait(self, leg: str, dt: float) -> None:
        """One pipeline-leg handoff wait (called from the staging loop's
        h2d/resolve dispatch threads)."""
        h = self.leg_wait.get(leg)
        if h is not None:
            h.observe(dt)

    def note_fallback(self, klass: str, n: int = 1) -> None:
        c = self.fallback.get(klass)
        if c is not None:
            c.inc(n)

    # -- degradation triggers ----------------------------------------------

    def trigger_dump(self, reason: str, extra: Optional[dict] = None) -> None:
        """Dump the flight recorder WITHOUT blocking the caller: triggers
        fire under the breaker lock and on the governor's evaluate path
        (both on the data plane), so the file IO moves to a daemon
        thread. When the trace plane is attached, the same thread also
        writes a sibling ``traces_*.json`` (Perfetto-loadable) next to
        the flight dump — the dump's trace_ids point into it — and when
        the host profiler is attached, a ``profile_*.txt`` collapsed
        snapshot of where every broker thread was spending wall time
        as the trigger fired. Use ``recorder.dump`` directly for a
        synchronous dump."""
        after = (
            self._dump_siblings
            if self.tracer is not None
            or self.host_profiler is not None
            or self.device_stats is not None
            else None
        )
        self.recorder.dump_async(reason, extra, after=after)

    def _dump_siblings(self, dump_path: str, reason: str) -> None:
        """Write the trace ring, the profiler's collapsed stacks, and
        the device-plane snapshot beside a just-written flight dump
        (recorder writer thread)."""
        if self.tracer is not None:
            self._dump_traces(dump_path, reason)
        if self.host_profiler is not None:
            self._dump_profile(dump_path, reason)
        if self.device_stats is not None:
            self._dump_devices(dump_path, reason)

    def _dump_devices(self, dump_path: str, reason: str) -> None:
        base = os.path.basename(dump_path)
        stem = base[len("flight_"):] if base.startswith("flight_") else base
        name = "devices_" + os.path.splitext(stem)[0] + ".json"
        path = os.path.join(os.path.dirname(dump_path), name)
        try:
            with open(path, "w") as f:
                json.dump(self.device_stats.snapshot(), f, indent=1)
        except OSError:
            _log.exception("device-plane dump failed (path=%s)", path)
            return
        _log.warning("device snapshot dumped to %s (reason=%s)", path, reason)

    def _dump_profile(self, dump_path: str, reason: str) -> None:
        base = os.path.basename(dump_path)
        stem = base[len("flight_"):] if base.startswith("flight_") else base
        name = "profile_" + os.path.splitext(stem)[0] + ".txt"
        path = os.path.join(os.path.dirname(dump_path), name)
        try:
            with open(path, "w") as f:
                f.write(self.host_profiler.collapsed())
        except OSError:
            _log.exception("profile dump failed (path=%s)", path)
            return
        _log.warning("profiler stacks dumped to %s (reason=%s)", path, reason)

    def _dump_traces(self, dump_path: str, reason: str) -> None:
        """Write the trace ring beside a just-written flight dump (runs
        on the recorder's daemon writer thread, never on a data-plane
        path)."""
        base = os.path.basename(dump_path)
        name = "traces_" + (
            base[len("flight_"):] if base.startswith("flight_") else base
        )
        path = os.path.join(os.path.dirname(dump_path), name)
        try:
            with open(path, "w") as f:
                f.write(self.tracer.export_json())
        except OSError:
            _log.exception("trace dump failed (path=%s)", path)
            return
        _log.warning("trace ring dumped to %s (reason=%s)", path, reason)

    # -- rendering ---------------------------------------------------------

    def exposition(self) -> str:
        return self.registry.exposition()

    def sys_tree(self) -> dict:
        out = self.registry.sys_tree()
        out["flight/ring_depth"] = len(self.recorder.ring)
        out["flight/dumps"] = self.recorder.dumps
        out["flight/dumps_suppressed"] = self.recorder.dumps_suppressed
        return out

    def bench_block(self) -> dict:
        """The BENCH-json telemetry block: per-stage p50/p99, batch
        occupancy, and the host-fallback breakdown — so future PRs can
        diff stage-level regressions, not just end-to-end rate."""
        stages = {}
        for s, h in self.stage_hist.items():
            if h.count:
                stages[s] = {
                    "count": h.count,
                    "p50_ms": round(h.percentile(0.5) * 1e3, 3),
                    "p99_ms": round(h.percentile(0.99) * 1e3, 3),
                }
        for leg, h in self.leg_wait.items():
            # per-leg pipeline handoff waits render as stage rows so
            # exp/stage_gate.py diffs them round over round (new names
            # pass through its new_stage_names notice on round one)
            if h.count:
                stages[f"leg_wait_{leg}"] = {
                    "count": h.count,
                    "p50_ms": round(h.percentile(0.5) * 1e3, 3),
                    "p99_ms": round(h.percentile(0.99) * 1e3, 3),
                }
        # delivery-latency SLI rows (ISSUE 14): per-path folds render as
        # stage rows so exp/stage_gate.py diffs them round over round
        # (their first round passes through its new_stage_names notice)
        stages.update(self.delivery_summary())
        fill = self.batch_fill.summary()
        return {
            "stages": stages,
            "batch_service": {
                "count": self.batch_service.count,
                "p50_ms": round(self.batch_service.percentile(0.5) * 1e3, 3),
                "p99_ms": round(self.batch_service.percentile(0.99) * 1e3, 3),
            },
            "batch_fill": {"count": fill["count"], "p50": fill["p50"], "p99": fill["p99"]},
            "fallbacks": {k: c.value for k, c in self.fallback.items()},
            "flight_dumps": self.recorder.dumps,
        }


class ClusterMetrics:
    """Mesh-federated metric summaries (ISSUE 14): the per-worker
    registry snapshots that ride cluster ``_T_METRICS`` frames, stored
    latest-wins per (worker, boot incarnation, sequence) and rendered
    as ONE Prometheus exposition at ``GET /metrics/cluster`` — every
    sample with a ``worker`` label, plus pre-folded cluster totals
    (counters summed, histogram bucket vectors added) with no worker
    label, so the 32-worker drill is scrapable from the root alone.

    Idempotence: entries carry absolute cumulative values keyed by
    (boot, seq) — a re-delivered or reordered frame is a no-op, and a
    restarted worker's fresh boot nonce replaces its dead incarnation.
    Entries older than ``max_age_s`` age out of scrapes (a dead worker
    must not pin stale totals forever).

    Loop-affine by design: ingest runs on the cluster's event loop and
    the HTTP scrape handlers run on the same loop, so no lock is needed
    (the multi-process drill gives each worker its own store)."""

    def __init__(
        self,
        max_age_s: float = 120.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.max_age_s = max_age_s
        self.clock = clock
        # worker id -> {"b": boot, "q": seq, "f": fams, "at": monotonic}
        self._workers: dict[str, dict] = {}
        self.frames_ingested = 0  # accepted summary entries
        self.frames_stale = 0  # re-delivered/reordered entries dropped

    def ingest(
        self,
        worker: str,
        boot: int,
        seq: int,
        fams: dict,
        now: Optional[float] = None,
    ) -> bool:
        """Store one worker's summary; False = already have this (or a
        newer) snapshot from the same incarnation — the re-delivery
        no-op that keeps counter folding idempotent."""
        now = self.clock() if now is None else now
        cur = self._workers.get(worker)
        if cur is not None and cur["b"] == boot and seq <= cur["q"]:
            self.frames_stale += 1
            return False
        self._workers[worker] = {"b": boot, "q": seq, "f": fams, "at": now}
        self.frames_ingested += 1
        return True

    def entries(self, now: Optional[float] = None) -> dict[str, dict]:
        """Fresh per-worker entries (aged ones pruned in place) — also
        what an intermediate tree hop forwards up toward the root (the
        per-subtree fold: its own summary plus everything learned on
        child edges)."""
        now = self.clock() if now is None else now
        for wid in [
            w
            for w, e in self._workers.items()
            if now - e["at"] > self.max_age_s
        ]:
            del self._workers[wid]
        return dict(self._workers)

    @property
    def worker_count(self) -> int:
        # through entries() so aged-out workers prune here too: the
        # mqtt_tpu_cluster_metrics_workers gauge is often the ONLY
        # reader on a worker nobody scrapes (the root never sends
        # uphill), and a dead worker must drop out of it on time
        return len(self.entries())

    # -- rendering ---------------------------------------------------------

    @staticmethod
    def _label_str(pairs: list, extra: str = "") -> str:
        # one label-rendering rule for both expositions: wire labels
        # (json round-tripped) coerce to str, then the registry's own
        # formatter applies the escaping
        return MetricsRegistry._labels_str(
            tuple((str(k), str(v)) for k, v in pairs), extra
        )

    def _sources(
        self, local_registry: Optional["MetricsRegistry"], local_worker: str
    ) -> dict[str, dict]:
        """worker id -> family summary, the local registry's LIVE
        summary shadowing any stale federated copy of this worker."""
        sources: dict[str, dict] = {}
        for wid, ent in sorted(self.entries().items()):
            sources[str(wid)] = ent["f"]
        if local_registry is not None:
            sources[str(local_worker)] = local_registry.summary()
        return sources

    def exposition(
        self,
        local_registry: Optional["MetricsRegistry"] = None,
        local_worker: str = "0",
    ) -> str:
        """The federated Prometheus text exposition: per-worker samples
        labeled ``worker="<id>"`` plus cluster-folded totals (counters
        and histograms only — point-in-time gauges do not fold
        meaningfully) carrying no worker label in the same family."""
        sources = self._sources(local_registry, local_worker)
        # family name -> {"t": type, "le": bounds, "rows": [...]}
        fams: dict[str, dict] = {}
        for wid, summary in sources.items():
            if not isinstance(summary, dict):
                continue
            for name, ent in summary.items():
                if not isinstance(ent, dict) or not _NAME_RE.match(name):
                    continue
                fam = fams.setdefault(
                    name, {"t": ent.get("t"), "le": ent.get("le"), "rows": []}
                )
                if fam["t"] != ent.get("t"):
                    continue  # cross-worker type conflict: first type wins
                if (
                    ent.get("t") == "histogram"
                    and ent.get("le") != fam["le"]
                ):
                    # cross-worker bucket-layout skew (a mid-upgrade
                    # mesh): index-wise adding counts against mismatched
                    # bounds would render silently-wrong folds — skip
                    # this worker's children for the family instead
                    # (the same posture summary() takes within a worker)
                    continue
                for child in ent.get("c") or []:
                    if not isinstance(child, (list, tuple)) or len(child) != 2:
                        continue
                    labels, value = child
                    fam["rows"].append((wid, list(labels), value))
        out: list[str] = []
        for name in sorted(fams):
            fam = fams[name]
            mtype = fam["t"]
            if mtype not in ("counter", "gauge", "histogram"):
                continue
            out.append(f"# TYPE {name} {mtype}")
            folds: dict[tuple, Any] = {}
            for wid, labels, value in sorted(
                fam["rows"], key=lambda r: (r[1], r[0])
            ):
                wl = labels + [["worker", wid]]
                if mtype == "histogram":
                    if not isinstance(value, dict):
                        continue
                    self._render_hist(out, name, wl, fam["le"], value)
                    key = tuple((str(k), str(v)) for k, v in labels)
                    agg = folds.get(key)
                    if agg is None:
                        folds[key] = {
                            "n": int(value.get("n", 0)),
                            "s": float(value.get("s", 0.0)),
                            "c": list(value.get("c") or []),
                        }
                    else:
                        agg["n"] += int(value.get("n", 0))
                        agg["s"] += float(value.get("s", 0.0))
                        counts = list(value.get("c") or [])
                        if len(counts) > len(agg["c"]):
                            agg["c"].extend(
                                [0] * (len(counts) - len(agg["c"]))
                            )
                        for i, c in enumerate(counts):
                            agg["c"][i] += c
                elif isinstance(value, (int, float)):
                    out.append(
                        f"{name}{self._label_str(wl)} {_fmt(value)}"
                    )
                    if mtype == "counter":
                        key = tuple((str(k), str(v)) for k, v in labels)
                        folds[key] = folds.get(key, 0) + value
            # pre-folded cluster totals (no worker label, same family)
            for key in sorted(folds):
                pairs = [list(kv) for kv in key]
                agg = folds[key]
                if mtype == "histogram":
                    self._render_hist(out, name, pairs, fam["le"], agg)
                else:
                    out.append(
                        f"{name}{self._label_str(pairs)} {_fmt(folds[key])}"
                    )
        return "\n".join(out) + "\n"

    def _render_hist(
        self, out: list, name: str, pairs: list, bounds: Any, value: dict
    ) -> None:
        if not isinstance(bounds, list):
            return
        counts = list(value.get("c") or [])
        counts.extend([0] * (len(bounds) + 1 - len(counts)))
        acc = 0
        for i, bound in enumerate(bounds):
            acc += counts[i]
            le = self._label_str(pairs, f'le="{_fmt(float(bound))}"')
            out.append(f"{name}_bucket{le} {acc}")
        le = self._label_str(pairs, 'le="+Inf"')
        out.append(f"{name}_bucket{le} {_fmt(int(value.get('n', 0)))}")
        out.append(
            f"{name}_sum{self._label_str(pairs)} "
            f"{_fmt(float(value.get('s', 0.0)))}"
        )
        out.append(
            f"{name}_count{self._label_str(pairs)} "
            f"{_fmt(int(value.get('n', 0)))}"
        )

    def slo_state(
        self,
        local_registry: Optional["MetricsRegistry"] = None,
        local_worker: str = "0",
    ) -> dict:
        """Mesh-wide SLO objective state for ``GET /cluster/slo``: every
        worker's ``mqtt_tpu_slo_*`` gauge values keyed by worker id —
        the federated face of each worker's own SLOEngine gauges."""
        out: dict = {}
        for wid, summary in self._sources(local_registry, local_worker).items():
            rows: dict = {}
            if isinstance(summary, dict):
                for name, ent in summary.items():
                    if not name.startswith("mqtt_tpu_slo_"):
                        continue
                    for child in (ent or {}).get("c") or []:
                        if (
                            not isinstance(child, (list, tuple))
                            or len(child) != 2
                            or not isinstance(child[1], (int, float))
                        ):
                            continue
                        labels, value = child
                        suffix = ",".join(
                            f"{k}={v}" for k, v in sorted(map(tuple, labels))
                        )
                        rows[f"{name}{{{suffix}}}" if suffix else name] = value
            if rows:
                out[wid] = rows
        return out


def check_exposition(text: str) -> int:
    """A minimal pure-Python Prometheus text-format checker (CI's scrape
    gate and the test suite's oracle): every non-comment line must be a
    well-formed sample, every # TYPE must name a known type, and at
    least one sample must exist. OpenMetrics-style bucket exemplars
    (``... 5 # {trace_id="..."} 0.003``) are accepted. Returns the
    sample count."""
    sample_re = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{([a-zA-Z_][a-zA-Z0-9_]*="
        r'"(\\.|[^"\\])*"(,[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*")*)?\})?'
        r" (NaN|[+-]?Inf|[+-]?[0-9.eE+-]+)( [0-9]+)?"
        r'( # \{[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*"\}'
        r" (NaN|[+-]?Inf|[+-]?[0-9.eE+-]+)( [0-9.eE+-]+)?)?$"
    )
    samples = 0
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4 or parts[3] not in (
                "counter", "gauge", "histogram", "summary", "untyped",
            ):
                raise ValueError(f"line {i}: bad # TYPE: {line!r}")
        elif line.startswith("#"):
            if not line.startswith("# HELP "):
                raise ValueError(f"line {i}: unknown comment: {line!r}")
        elif sample_re.match(line):
            samples += 1
        else:
            raise ValueError(f"line {i}: malformed sample: {line!r}")
    if samples == 0:
        raise ValueError("no samples in exposition")
    return samples
