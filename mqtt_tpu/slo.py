"""Declarative SLO engine: multi-window burn rates over the broker's
own telemetry registry (ISSUE 14, layer 2 of the cluster-wide SLO
observatory).

Operators state objectives in a one-line grammar (``Options.
slo_objectives``)::

    p99 delivery < 50ms over 5m          # latency objective
    p99 delivery{tenant=acme} < 20ms over 5m/1h
    shed ratio < 0.1% over 5m            # event-ratio objective
    messages_dropped/messages_received ratio < 0.5%
    shard skew < 2.0 over 5m             # gauge objective (ISSUE 18)
    hbm ratio < 0.9 over 5m              # device HBM watermark

and the engine evaluates each as a MULTI-WINDOW BURN RATE (the SRE
workbook shape): the burn rate is ``bad-event fraction / allowed
fraction`` over a window, and an objective breaches only when BOTH the
fast window (default 5m — catches the storm) and the slow window
(default 12x fast — proves it is sustained, not a blip) burn above
``Options.slo_burn_threshold``. Recovery needs only the fast window to
cool, so a breach clears as soon as the bleeding actually stops.

Sources are the registry's OWN metrics — no second bookkeeping path:

- latency objectives walk a histogram family's labeled children (by
  default ``mqtt_tpu_delivery_latency_seconds``, the per-tenant
  delivery SLI); "bad" = observations past the threshold, resolved at
  bucket granularity with the threshold snapped DOWN one bucket so the
  gate alarms early, never late (telemetry.Histogram.count_le);
- ratio objectives diff two counter families (numerator = bad events,
  denominator = total events), summed across their children;
- gauge objectives (ISSUE 18's device plane) sample a gauge family each
  evaluation tick — the worst (max) child, so a per-device family
  breaches on its hottest chip — counting a tick as "bad" when the
  value exceeds the threshold. Events are TICKS: the burn windows then
  measure the fraction of recent time the gauge spent past the line
  against a ``GAUGE_BUDGET`` (10%) allowance, which plugs straight into
  the multi-window machinery below.

Each evaluation tick snapshots cumulative totals into a bounded ring;
window deltas come from the ring, so restarts/counter resets clamp to
zero instead of going negative. Breach transitions publish a retained
``$SYS/broker/slo/<name>`` message (both directions), entry fires the
flight-recorder dump path (traces + profile + flight in one bundle —
mqtt_tpu.telemetry.trigger_dump), and every objective exports
``mqtt_tpu_slo_{burn_rate,budget_remaining,breached}`` gauges that ride
mesh metric federation to GET /cluster/slo at the tree root.

The engine is loop-affine: ``evaluate()`` runs on the server's
housekeeping tick (1s), walks a handful of histogram children, and
takes no locks beyond the registry's own family-map probe.
"""

from __future__ import annotations

import json
import logging
import re
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

_log = logging.getLogger("mqtt_tpu.slo")

# the default latency SLI family the bare word "delivery" resolves to
DELIVERY_FAMILY = "mqtt_tpu_delivery_latency_seconds"

# named latency SLIs: bare word -> histogram family
LATENCY_SLIS = {
    "delivery": DELIVERY_FAMILY,
    "stage": "mqtt_tpu_publish_stage_seconds",
    "queue_wait": "mqtt_tpu_outbound_queue_wait_seconds",
}

# named ratio SLIs: bare word -> (numerator family, denominator family)
RATIO_SLIS = {
    "shed": ("mqtt_tpu_messages_dropped_total", "mqtt_tpu_messages_received_total"),
    "fallback": ("mqtt_tpu_stage_fallback_total", "mqtt_tpu_matcher_topics_total"),
    # scenario-lab oracles (mqtt_tpu.scenarios): the runner registers
    # these counters around each drill so the gate is the SLO engine,
    # not harness asserts
    "scenario_gap": (
        "mqtt_tpu_scenario_gaps_total",
        "mqtt_tpu_scenario_expected_total",
    ),
    "scenario_dup": (
        "mqtt_tpu_scenario_duplicates_total",
        "mqtt_tpu_scenario_expected_total",
    ),
    # live tenant re-key: deliveries sealed with a retired epoch key /
    # all sealed fan-outs (must hold at zero after retirement)
    "rekey_stale": (
        "mqtt_tpu_recrypt_epoch_stale_drops_total",
        "mqtt_tpu_recrypt_fanouts_total",
    ),
}

# named gauge SLIs (ISSUE 18 device plane): phrase -> gauge family; the
# engine samples the family's WORST (max) child each tick
GAUGE_SLIS = {
    "shard skew": "mqtt_tpu_device_skew_ratio",
    "hbm ratio": "mqtt_tpu_device_hbm_ratio",
}

# allowed fraction of evaluation ticks a gauge may spend past its
# threshold before the burn rate reads 1.0
GAUGE_BUDGET = 0.1

DEFAULT_FAST_S = 300.0  # 5m fast window
SLOW_FACTOR = 12.0  # slow window = 12x fast (5m -> 1h) unless spelled out

_DUR_RE = re.compile(r"^(\d+(?:\.\d+)?)(ms|s|m|h|d)$")
_DUR_UNITS = {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}

_LATENCY_RE = re.compile(
    r"^p(?P<q>\d{1,2}(?:\.\d+)?)\s+(?P<sli>[a-z_][a-z0-9_]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s*<\s*(?P<num>\d+(?:\.\d+)?)(?P<unit>us|ms|s)"
    r"(?:\s+over\s+(?P<win>\S+))?$"
)
_RATIO_RE = re.compile(
    r"^(?P<sli>[a-z_][a-z0-9_/]*)\s+ratio"
    r"\s*<\s*(?P<num>\d+(?:\.\d+)?)%"
    r"(?:\s+over\s+(?P<win>\S+))?$"
)
# tried LAST: a bare unitless comparison ("shard skew < 2.0 over 5m",
# "hbm ratio < 0.9") — multi-word phrases resolve through GAUGE_SLIS,
# single words fall back to a gauge family name
_GAUGE_RE = re.compile(
    r"^(?P<sli>[a-z_][a-z0-9_]*(?: [a-z0-9_]+)*)"
    r"\s*<\s*(?P<num>\d+(?:\.\d+)?)"
    r"(?:\s+over\s+(?P<win>\S+))?$"
)


class ObjectiveError(ValueError):
    """A spec the grammar cannot parse (parse_objectives logs and skips
    these so a config typo degrades one objective, never the broker)."""


@dataclass
class Objective:
    """One parsed objective. ``budget`` is the allowed bad-event
    fraction (p99 -> 0.01; a 0.1% ratio -> 0.001)."""

    name: str
    spec: str
    kind: str  # "latency" | "ratio" | "gauge"
    budget: float
    fast_s: float = DEFAULT_FAST_S
    slow_s: float = DEFAULT_FAST_S * SLOW_FACTOR
    # latency objectives; gauge objectives reuse both fields (family =
    # the sampled gauge family, threshold_s = the UNITLESS threshold)
    family: str = ""
    threshold_s: float = 0.0
    labels: dict = field(default_factory=dict)
    # ratio objectives
    numerator: str = ""
    denominator: str = ""


def _parse_duration(tok: str) -> float:
    m = _DUR_RE.match(tok)
    if m is None:
        raise ObjectiveError(f"bad duration {tok!r} (want e.g. 30s, 5m, 1h)")
    return float(m.group(1)) * _DUR_UNITS[m.group(2)]


def _parse_windows(tok: Optional[str]) -> tuple[float, float]:
    """``5m`` or ``5m/1h`` -> (fast_s, slow_s); the slow window defaults
    to SLOW_FACTOR x fast and is floored at the fast window."""
    if not tok:
        return DEFAULT_FAST_S, DEFAULT_FAST_S * SLOW_FACTOR
    fast_tok, _, slow_tok = tok.partition("/")
    fast = _parse_duration(fast_tok)
    slow = _parse_duration(slow_tok) if slow_tok else fast * SLOW_FACTOR
    return fast, max(fast, slow)


def _parse_labels(tok: Optional[str]) -> dict:
    out: dict = {}
    if not tok:
        return out
    for part in tok.split(","):
        part = part.strip()
        if not part:
            continue
        k, eq, v = part.partition("=")
        if not eq:
            raise ObjectiveError(f"bad label filter {part!r} (want key=value)")
        out[k.strip()] = v.strip().strip('"')
    return out


def _slug(spec: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_]+", "_", spec).strip("_")[:64]


def parse_objective(spec: str, name: str = "") -> Objective:
    """Parse one objective line; raises ObjectiveError on bad grammar."""
    s = " ".join(str(spec).split())
    m = _LATENCY_RE.match(s)
    if m is not None:
        q = float(m.group("q"))
        if not 0 < q < 100:
            raise ObjectiveError(f"quantile p{m.group('q')} out of range")
        sli = m.group("sli")
        family = LATENCY_SLIS.get(sli, sli)
        if not family.startswith("mqtt_tpu_"):
            family = "mqtt_tpu_" + family
        unit = {"us": 1e-6, "ms": 1e-3, "s": 1.0}[m.group("unit")]
        fast, slow = _parse_windows(m.group("win"))
        return Objective(
            name=name or _slug(s),
            spec=s,
            kind="latency",
            budget=round(1.0 - q / 100.0, 9),
            fast_s=fast,
            slow_s=slow,
            family=family,
            threshold_s=float(m.group("num")) * unit,
            labels=_parse_labels(m.group("labels")),
        )
    m = _RATIO_RE.match(s)
    if m is not None:
        sli = m.group("sli")
        if "/" in sli:
            num, _, den = sli.partition("/")
            if not (num and den):
                raise ObjectiveError(f"bad ratio sli {sli!r}")
        elif sli in RATIO_SLIS:
            num, den = RATIO_SLIS[sli]
        else:
            raise ObjectiveError(
                f"unknown ratio sli {sli!r} (known: {sorted(RATIO_SLIS)}, "
                "or spell numerator/denominator families)"
            )
        if not num.startswith("mqtt_tpu_"):
            num = "mqtt_tpu_" + num
        if not den.startswith("mqtt_tpu_"):
            den = "mqtt_tpu_" + den
        budget = float(m.group("num")) / 100.0
        if budget <= 0:
            raise ObjectiveError("ratio budget must be > 0%")
        fast, slow = _parse_windows(m.group("win"))
        return Objective(
            name=name or _slug(s),
            spec=s,
            kind="ratio",
            budget=budget,
            fast_s=fast,
            slow_s=slow,
            numerator=num,
            denominator=den,
        )
    m = _GAUGE_RE.match(s)
    if m is not None:
        sli = m.group("sli")
        family = GAUGE_SLIS.get(sli)
        if family is None:
            if " " in sli:
                raise ObjectiveError(
                    f"unknown gauge sli {sli!r} (known: {sorted(GAUGE_SLIS)})"
                )
            family = sli  # a single word names the gauge family itself
        if not family.startswith("mqtt_tpu_"):
            family = "mqtt_tpu_" + family
        fast, slow = _parse_windows(m.group("win"))
        return Objective(
            name=name or _slug(s),
            spec=s,
            kind="gauge",
            budget=GAUGE_BUDGET,
            fast_s=fast,
            slow_s=slow,
            family=family,
            threshold_s=float(m.group("num")),
        )
    raise ObjectiveError(
        f"unparseable objective {spec!r} (grammar: 'p99 delivery < 50ms "
        "over 5m', 'shed ratio < 0.1%', or 'shard skew < 2.0 over 5m')"
    )


def parse_objectives(specs) -> list[Objective]:
    """Parse a config list, SKIPPING (and logging) bad lines — an
    operator typo must degrade one objective, never abort the broker
    (the PR 5 priority-class posture). Duplicate names get a suffix."""
    out: list[Objective] = []
    seen: set[str] = set()
    for spec in specs or ():
        try:
            obj = parse_objective(spec)
        except ObjectiveError as e:
            _log.warning("skipping SLO objective: %s", e)
            continue
        base, n = obj.name, 2
        while obj.name in seen:
            obj.name = f"{base}_{n}"
            n += 1
        seen.add(obj.name)
        out.append(obj)
    return out


class _Track:
    """One objective's evaluation state: the cumulative-snapshot ring
    and the current verdict."""

    __slots__ = (
        "obj", "ring", "breached", "burn_fast", "burn_slow",
        "budget_remaining", "breaches", "g_fast", "g_slow", "g_budget",
        "g_breached", "cum_total", "cum_bad", "last_value",
    )

    def __init__(self, obj: Objective) -> None:
        self.obj = obj
        # (monotonic, total_events, bad_events) cumulative snapshots
        self.ring: deque = deque()
        # gauge objectives accumulate here: every evaluation tick is an
        # event, a tick with the sampled value past the threshold is bad
        self.cum_total = 0
        self.cum_bad = 0
        self.last_value = 0.0
        self.breached = False
        self.burn_fast = 0.0
        self.burn_slow = 0.0
        self.budget_remaining = 1.0
        self.breaches = 0
        self.g_fast: Any = None
        self.g_slow: Any = None
        self.g_budget: Any = None
        self.g_breached: Any = None


class SLOEngine:
    """Evaluates parsed objectives against the telemetry registry on
    the server's housekeeping tick; see the module docstring for the
    breach semantics. ``publish`` is the server's retained-$SYS
    publisher ``(topic_suffix: str, payload: dict) -> None`` — called
    only on transitions, from the evaluation (event-loop) context."""

    def __init__(
        self,
        telemetry: Any,
        objectives: list[Objective],
        burn_threshold: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
        publish: Optional[Callable[[str, dict], None]] = None,
    ) -> None:
        self.telemetry = telemetry
        self.burn_threshold = max(1e-9, float(burn_threshold))
        self.clock = clock
        self.publish = publish
        self._tracks = [_Track(o) for o in objectives]
        r = telemetry.registry
        for tr in self._tracks:
            o = tr.obj
            tr.g_fast = r.gauge(
                "mqtt_tpu_slo_burn_rate",
                "Error-budget burn rate per objective and window "
                "(1.0 = burning exactly the allowed budget)",
                objective=o.name,
                window="fast",
            )
            tr.g_slow = r.gauge(
                "mqtt_tpu_slo_burn_rate",
                "",
                objective=o.name,
                window="slow",
            )
            tr.g_budget = r.gauge(
                "mqtt_tpu_slo_budget_remaining",
                "Fraction of the slow-window error budget still unspent "
                "(clamped at 0)",
                objective=o.name,
            )
            tr.g_breached = r.gauge(
                "mqtt_tpu_slo_breached",
                "1 while the objective is in breach (fast AND slow "
                "windows burning past the threshold)",
                objective=o.name,
            )
        self.breach_transitions = r.counter(
            "mqtt_tpu_slo_breaches_total",
            "Objective transitions INTO breach",
        )

    @property
    def objectives(self) -> list[Objective]:
        return [tr.obj for tr in self._tracks]

    # -- totals from the registry ------------------------------------------

    def _totals(self, tr: _Track) -> tuple[float, float]:
        """Cumulative (total events, bad events) for one objective, read
        from the registry's live children. Gauge objectives synthesize
        events from evaluation ticks: this tick is one event, bad when
        the family's worst (max) child value exceeds the threshold."""
        obj = tr.obj
        r = self.telemetry.registry
        if obj.kind == "gauge":
            worst = 0.0
            for _key, child in r.family_children(obj.family):
                value = getattr(child, "value", None)
                try:
                    v = value() if callable(value) else value
                except Exception:  # brokerlint: ok=R4 a failing gauge callback must degrade the objective sample, never the tick
                    continue
                if isinstance(v, (int, float)):
                    worst = max(worst, float(v))
            tr.last_value = worst
            tr.cum_total += 1
            if worst > obj.threshold_s:
                tr.cum_bad += 1
            return float(tr.cum_total), float(tr.cum_bad)
        if obj.kind == "latency":
            total = bad = 0.0
            want = obj.labels
            for key, child in r.family_children(obj.family):
                if want:
                    have = dict(key)
                    if any(have.get(k) != v for k, v in want.items()):
                        continue
                h = child.live() if hasattr(child, "live") else None
                if h is None:
                    continue
                total += h.count
                bad += h.count - h.count_le(obj.threshold_s)
            return total, bad
        num = den = 0.0
        for _key, child in r.family_children(obj.numerator):
            v = getattr(child, "value", None)
            if isinstance(v, (int, float)):
                num += v
        for _key, child in r.family_children(obj.denominator):
            v = getattr(child, "value", None)
            if isinstance(v, (int, float)):
                den += v
        return den, num

    # -- evaluation --------------------------------------------------------

    @staticmethod
    def _window_delta(
        ring: deque, now: float, window_s: float
    ) -> tuple[float, float]:
        """(d_total, d_bad) between the newest snapshot and the oldest
        one inside the window (a partial window uses whatever history
        exists — standard burn-rate behavior on a fresh broker).
        Deltas clamp at zero so a counter reset reads as silence, not a
        negative burn."""
        if len(ring) < 2:
            return 0.0, 0.0
        t_now, total_now, bad_now = ring[-1]
        base = None
        for t, total, bad in ring:
            if t >= now - window_s:
                base = (t, total, bad)
                break
        if base is None or base[0] >= t_now:
            return 0.0, 0.0
        return max(0.0, total_now - base[1]), max(0.0, bad_now - base[2])

    def evaluate(self, now: Optional[float] = None) -> None:
        """One evaluation tick: snapshot, compute both windows' burn,
        transition + publish + dump on edges, refresh the gauges."""
        now = self.clock() if now is None else now
        for tr in self._tracks:
            o = tr.obj
            total, bad = self._totals(tr)
            tr.ring.append((now, total, bad))
            horizon = now - o.slow_s - 2.0
            while len(tr.ring) > 2 and tr.ring[1][0] <= horizon:
                tr.ring.popleft()
            d_total_f, d_bad_f = self._window_delta(tr.ring, now, o.fast_s)
            d_total_s, d_bad_s = self._window_delta(tr.ring, now, o.slow_s)
            frac_f = d_bad_f / d_total_f if d_total_f > 0 else 0.0
            frac_s = d_bad_s / d_total_s if d_total_s > 0 else 0.0
            tr.burn_fast = frac_f / o.budget
            tr.burn_slow = frac_s / o.budget
            tr.budget_remaining = max(0.0, 1.0 - tr.burn_slow)
            was = tr.breached
            if not was:
                # entry needs BOTH windows burning: the fast window
                # catches the storm, the slow window proves it is
                # sustained spend, not one bad minute
                tr.breached = (
                    tr.burn_fast > self.burn_threshold
                    and tr.burn_slow > self.burn_threshold
                )
            else:
                # exit on the fast window alone: once the bleeding
                # stops, the slow window's memory must not pin the alert
                tr.breached = tr.burn_fast > self.burn_threshold
            tr.g_fast.set(round(tr.burn_fast, 6))
            tr.g_slow.set(round(tr.burn_slow, 6))
            tr.g_budget.set(round(tr.budget_remaining, 6))
            tr.g_breached.set(1.0 if tr.breached else 0.0)
            if tr.breached != was:
                self._transition(tr)

    def _transition(self, tr: _Track) -> None:
        o = tr.obj
        state = self._objective_state(tr)
        if tr.breached:
            tr.breaches += 1
            self.breach_transitions.inc()
            _log.warning(
                "SLO BREACH %s (%s): burn fast=%.2f slow=%.2f",
                o.name, o.spec, tr.burn_fast, tr.burn_slow,
            )
            # the one-bundle capture: flight records + trace ring +
            # profiler stacks land beside each other on disk
            self.telemetry.trigger_dump("slo_breach_" + o.name, state)
        else:
            _log.warning("SLO recovered %s (%s)", o.name, o.spec)
        if self.publish is not None:
            try:
                self.publish(o.name, state)
            except Exception:
                _log.exception("SLO transition publish failed (%s)", o.name)

    def _objective_state(self, tr: _Track) -> dict:
        o = tr.obj
        out = {
            "objective": o.name,
            "spec": o.spec,
            "kind": o.kind,
            "breached": tr.breached,
            "burn_rate_fast": round(tr.burn_fast, 6),
            "burn_rate_slow": round(tr.burn_slow, 6),
            "budget_remaining": round(tr.budget_remaining, 6),
            "budget": o.budget,
            "window_fast_s": o.fast_s,
            "window_slow_s": o.slow_s,
            "breaches": tr.breaches,
        }
        if o.kind == "gauge":
            out["value"] = round(tr.last_value, 6)
            out["threshold"] = o.threshold_s
            out["family"] = o.family
        return out

    def state(self) -> dict:
        """Objective name -> full state (GET /cluster/slo's local half
        and the transition payloads' shape)."""
        return {tr.obj.name: self._objective_state(tr) for tr in self._tracks}
