"""Golden packet catalogue + codec conformance.

The model is the reference's golden catalogue (packets/tpackets.go, ~300
cases of raw bytes <-> expected struct): every case here pins exact wire
bytes for decode and encode, including malformed variants. Round-trip and
validation tests extend coverage beyond the hand-pinned vectors.
"""

from dataclasses import dataclass, field

import pytest

from mqtt_tpu.packets import (
    AUTH,
    CONNACK,
    CONNECT,
    DISCONNECT,
    PINGREQ,
    PINGRESP,
    PUBACK,
    PUBCOMP,
    PUBLISH,
    PUBREC,
    PUBREL,
    SUBACK,
    SUBSCRIBE,
    UNSUBACK,
    UNSUBSCRIBE,
    Code,
    ConnectParams,
    FixedHeader,
    Packet,
    Properties,
    Subscription,
    UserProperty,
    codes,
    decode_packet,
    encode_packet,
)


@dataclass
class Case:
    desc: str
    raw: bytes
    packet: Packet | None = None
    version: int = 4
    decode_err: Code | None = None  # expected decode failure
    fail_first: Code | None = None  # expected fixed-header decode failure
    group: str = ""  # "decode", "encode", or "" for both directions


def fhdr(type_, qos=0, dup=False, retain=False, remaining=0):
    return FixedHeader(type=type_, qos=qos, dup=dup, retain=retain, remaining=remaining)


CASES: list[Case] = [
    # ---- CONNECT ---------------------------------------------------------
    Case(
        "connect v4 basic",
        bytes.fromhex("1010 0004 4d515454 04 02 003c 0004 7a656e33".replace(" ", "")),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=16),
            protocol_version=4,
            connect=ConnectParams(
                protocol_name=b"MQTT", clean=True, keepalive=60, client_identifier="zen3"
            ),
        ),
    ),
    Case(
        "connect v5 with session expiry",
        bytes.fromhex("1016 0004 4d515454 05 02 003c 05 11 00000078 0004 7a656e33".replace(" ", "")),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=22),
            protocol_version=5,
            properties=Properties(session_expiry_interval=120, session_expiry_interval_flag=True),
            connect=ConnectParams(
                protocol_name=b"MQTT", clean=True, keepalive=60, client_identifier="zen3"
            ),
        ),
    ),
    Case(
        "connect v4 with will",
        bytes.fromhex(
            "101f 0004 4d515454 04 0e 003c 0004 7a656e33 0003 6c7774 0008 6e6f74616761696e".replace(" ", "")
        ),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=31),
            protocol_version=4,
            connect=ConnectParams(
                protocol_name=b"MQTT",
                clean=True,
                keepalive=60,
                client_identifier="zen3",
                will_flag=True,
                will_qos=1,
                will_topic="lwt",
                will_payload=b"notagain",
            ),
        ),
    ),
    Case(
        "connect v3 MQIsdp",
        bytes.fromhex("1011 0006 4d5149736470 03 02 001e 0003 7a656e".replace(" ", "")),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=17),
            protocol_version=3,
            connect=ConnectParams(
                protocol_name=b"MQIsdp", clean=True, keepalive=30, client_identifier="zen"
            ),
        ),
        version=3,
    ),
    Case(
        "connect truncated keepalive",
        bytes.fromhex("1009 0004 4d515454 04 02 00".replace(" ", "")),
        decode_err=codes.ERR_MALFORMED_KEEPALIVE,
        group="decode",
    ),
    Case(
        "connect body shorter than declared remaining",
        bytes.fromhex("100c 0004 4d515454 04 02 00".replace(" ", "")),
        decode_err=codes.ERR_MALFORMED_PACKET,
        group="decode",
    ),
    Case(
        "connect username flag but no username",
        bytes.fromhex("1010 0004 4d515454 04 82 003c 0004 7a656e33".replace(" ", "")),
        decode_err=codes.ERR_PROTOCOL_VIOLATION_FLAG_NO_USERNAME,
        group="decode",
    ),
    # ---- CONNACK ---------------------------------------------------------
    Case(
        "connack v4 accepted",
        bytes.fromhex("20020000"),
        Packet(fixed_header=fhdr(CONNACK, remaining=2), protocol_version=4),
    ),
    Case(
        "connack v4 session present",
        bytes.fromhex("20020100"),
        Packet(fixed_header=fhdr(CONNACK, remaining=2), protocol_version=4, session_present=True),
    ),
    Case(
        "connack v5 empty properties",
        bytes.fromhex("2003000000"),
        Packet(fixed_header=fhdr(CONNACK, remaining=3), protocol_version=5),
        version=5,
    ),
    Case(
        "connack v5 bad username or password",
        bytes.fromhex("2003008600"),
        Packet(
            fixed_header=fhdr(CONNACK, remaining=3),
            protocol_version=5,
            reason_code=0x86,
        ),
        version=5,
    ),
    # ---- PUBLISH ---------------------------------------------------------
    Case(
        "publish qos0 v4",
        bytes.fromhex("300c 0005 612f622f63 68656c6c6f".replace(" ", "")),
        Packet(
            fixed_header=fhdr(PUBLISH, remaining=12),
            protocol_version=4,
            topic_name="a/b/c",
            payload=b"hello",
        ),
    ),
    Case(
        "publish qos1 v4",
        bytes.fromhex("320e 0005 612f622f63 0007 68656c6c6f".replace(" ", "")),
        Packet(
            fixed_header=fhdr(PUBLISH, qos=1, remaining=14),
            protocol_version=4,
            topic_name="a/b/c",
            packet_id=7,
            payload=b"hello",
        ),
    ),
    Case(
        "publish qos2 retain dup v4",
        bytes.fromhex("3d0e 0005 612f622f63 0007 68656c6c6f".replace(" ", "")),
        Packet(
            fixed_header=fhdr(PUBLISH, qos=2, dup=True, retain=True, remaining=14),
            protocol_version=4,
            topic_name="a/b/c",
            packet_id=7,
            payload=b"hello",
        ),
    ),
    Case(
        "publish v5 empty properties",
        bytes.fromhex("300d 0005 612f622f63 00 68656c6c6f".replace(" ", "")),
        Packet(
            fixed_header=fhdr(PUBLISH, remaining=13),
            protocol_version=5,
            topic_name="a/b/c",
            payload=b"hello",
        ),
        version=5,
    ),
    Case(
        "publish v5 user property",
        bytes.fromhex("3016 0005 612f622f63 09 26 00026869 00027468 68656c6c6f".replace(" ", "")),
        Packet(
            fixed_header=fhdr(PUBLISH, remaining=22),
            protocol_version=5,
            topic_name="a/b/c",
            properties=Properties(user=[UserProperty("hi", "th")]),
            payload=b"hello",
        ),
        version=5,
    ),
    Case(
        "publish invalid utf8 topic",
        bytes.fromhex("3009 0005 612f62ffc3 6869".replace(" ", "")),
        decode_err=codes.ERR_MALFORMED_TOPIC,
        group="decode",
    ),
    Case(
        "publish qos3 rejected at header",
        bytes.fromhex("3600"),
        fail_first=codes.ERR_PROTOCOL_VIOLATION_QOS_OUT_OF_RANGE,
        group="decode",
    ),
    Case(
        "publish dup without qos rejected",
        bytes.fromhex("3800"),
        fail_first=codes.ERR_PROTOCOL_VIOLATION_DUP_NO_QOS,
        group="decode",
    ),
    # ---- PUBACK / PUBREC / PUBREL / PUBCOMP ------------------------------
    Case(
        "puback v4",
        bytes.fromhex("40020007"),
        Packet(fixed_header=fhdr(PUBACK, remaining=2), protocol_version=4, packet_id=7),
    ),
    Case(
        "puback v5 reason code",
        bytes.fromhex("4003000710"),
        Packet(
            fixed_header=fhdr(PUBACK, remaining=3),
            protocol_version=5,
            packet_id=7,
            reason_code=0x10,
        ),
        version=5,
        group="decode",  # encode of rc<0x80 with no props omits reason byte
    ),
    Case(
        "puback v5 error reason encodes reason byte",
        bytes.fromhex("4003000793"),
        Packet(
            fixed_header=fhdr(PUBACK, remaining=3),
            protocol_version=5,
            packet_id=7,
            reason_code=0x93,
        ),
        version=5,
    ),
    Case(
        "pubrec v4",
        bytes.fromhex("50020007"),
        Packet(fixed_header=fhdr(PUBREC, remaining=2), protocol_version=4, packet_id=7),
    ),
    Case(
        "pubrel v4",
        bytes.fromhex("62020007"),
        Packet(fixed_header=fhdr(PUBREL, qos=1, remaining=2), protocol_version=4, packet_id=7),
    ),
    Case(
        "pubrel v5 packet id not found",
        bytes.fromhex("6203000792"),
        Packet(
            fixed_header=fhdr(PUBREL, qos=1, remaining=3),
            protocol_version=5,
            packet_id=7,
            reason_code=0x92,
        ),
        version=5,
    ),
    Case(
        "pubrel bad flags",
        bytes.fromhex("60020007"),
        fail_first=codes.ERR_MALFORMED_FLAGS,
        group="decode",
    ),
    Case(
        "pubcomp v4",
        bytes.fromhex("70020007"),
        Packet(fixed_header=fhdr(PUBCOMP, remaining=2), protocol_version=4, packet_id=7),
    ),
    # ---- SUBSCRIBE / SUBACK ----------------------------------------------
    Case(
        "subscribe v4",
        bytes.fromhex("820a 0015 0005 612f622f63 01".replace(" ", "")),
        Packet(
            fixed_header=fhdr(SUBSCRIBE, qos=1, remaining=10),
            protocol_version=4,
            packet_id=21,
            filters=[Subscription(filter="a/b/c", qos=1)],
        ),
    ),
    Case(
        "subscribe v5 options",
        bytes.fromhex("820b 0015 00 0005 612f622f63 2e".replace(" ", "")),
        Packet(
            fixed_header=fhdr(SUBSCRIBE, qos=1, remaining=11),
            protocol_version=5,
            packet_id=21,
            filters=[
                Subscription(
                    filter="a/b/c",
                    qos=2,
                    no_local=True,
                    retain_as_published=True,
                    retain_handling=2,
                )
            ],
        ),
        version=5,
    ),
    Case(
        "subscribe v5 subscription identifier",
        bytes.fromhex("820d 0015 02 0b 05 0005 612f622f63 01".replace(" ", "")),
        Packet(
            fixed_header=fhdr(SUBSCRIBE, qos=1, remaining=13),
            protocol_version=5,
            packet_id=21,
            properties=Properties(subscription_identifier=[5]),
            filters=[Subscription(filter="a/b/c", qos=1, identifier=5)],
        ),
        version=5,
    ),
    Case(
        "subscribe qos out of range",
        bytes.fromhex("820a 0015 0005 612f622f63 03".replace(" ", "")),
        decode_err=codes.ERR_PROTOCOL_VIOLATION_QOS_OUT_OF_RANGE,
        group="decode",
    ),
    Case(
        "subscribe bad flags",
        bytes.fromhex("800a 0015 0005 612f622f63 01".replace(" ", "")),
        fail_first=codes.ERR_MALFORMED_FLAGS,
        group="decode",
    ),
    Case(
        "suback v4",
        bytes.fromhex("90030015 01".replace(" ", "")),
        Packet(
            fixed_header=fhdr(SUBACK, remaining=3),
            protocol_version=4,
            packet_id=21,
            reason_codes=b"\x01",
        ),
    ),
    Case(
        "suback v5",
        bytes.fromhex("9004001500 80".replace(" ", "")),
        Packet(
            fixed_header=fhdr(SUBACK, remaining=4),
            protocol_version=5,
            packet_id=21,
            reason_codes=b"\x80",
        ),
        version=5,
    ),
    # ---- UNSUBSCRIBE / UNSUBACK ------------------------------------------
    Case(
        "unsubscribe v4",
        bytes.fromhex("a209 0015 0005 612f622f63".replace(" ", "")),
        Packet(
            fixed_header=fhdr(UNSUBSCRIBE, qos=1, remaining=9),
            protocol_version=4,
            packet_id=21,
            filters=[Subscription(filter="a/b/c")],
        ),
    ),
    Case(
        "unsubscribe v5 two filters",
        bytes.fromhex("a212 0015 00 0005 612f622f63 0006 642f652f6623".replace(" ", "")),
        Packet(
            fixed_header=fhdr(UNSUBSCRIBE, qos=1, remaining=18),
            protocol_version=5,
            packet_id=21,
            filters=[Subscription(filter="a/b/c"), Subscription(filter="d/e/f#")],
        ),
        version=5,
    ),
    Case(
        "unsuback v4",
        bytes.fromhex("b0020015"),
        Packet(fixed_header=fhdr(UNSUBACK, remaining=2), protocol_version=4, packet_id=21),
    ),
    Case(
        "unsuback v5",
        bytes.fromhex("b005001500 0011".replace(" ", "")),
        Packet(
            fixed_header=fhdr(UNSUBACK, remaining=5),
            protocol_version=5,
            packet_id=21,
            reason_codes=b"\x00\x11",
        ),
        version=5,
    ),
    # ---- PING / DISCONNECT / AUTH ----------------------------------------
    Case("pingreq", bytes.fromhex("c000"), Packet(fixed_header=fhdr(PINGREQ), protocol_version=4)),
    Case("pingresp", bytes.fromhex("d000"), Packet(fixed_header=fhdr(PINGRESP), protocol_version=4)),
    Case(
        "disconnect v4",
        bytes.fromhex("e000"),
        Packet(fixed_header=fhdr(DISCONNECT), protocol_version=4),
    ),
    Case(
        "disconnect v5 server shutting down",
        bytes.fromhex("e0028b00"),
        Packet(
            fixed_header=fhdr(DISCONNECT, remaining=2),
            protocol_version=5,
            reason_code=0x8B,
        ),
        version=5,
    ),
    Case(
        "auth v5 continue authentication",
        bytes.fromhex("f0021800"),
        Packet(
            fixed_header=fhdr(AUTH, remaining=2),
            protocol_version=5,
            reason_code=0x18,
        ),
        version=5,
    ),
]


def _decode_cases():
    return [c for c in CASES if c.group in ("", "decode")]


def _encode_cases():
    return [c for c in CASES if c.group in ("", "encode") and c.packet is not None]


@pytest.mark.parametrize("case", _decode_cases(), ids=lambda c: c.desc)
def test_decode(case):
    if case.fail_first is not None:
        with pytest.raises(Code) as e:
            decode_packet(case.raw, case.version)
        assert e.value.code == case.fail_first.code
        assert e.value.reason == case.fail_first.reason
        return
    if case.decode_err is not None:
        with pytest.raises(Code) as e:
            decode_packet(case.raw, case.version)
        assert e.value.code == case.decode_err.code
        assert e.value.reason.endswith(case.decode_err.reason)
        return
    pk = decode_packet(case.raw, case.version)
    assert pk == case.packet, f"decode mismatch for {case.desc}"


@pytest.mark.parametrize("case", _encode_cases(), ids=lambda c: c.desc)
def test_encode(case):
    assert encode_packet(case.packet) == case.raw, f"encode mismatch for {case.desc}"


class TestRoundTrips:
    """encode(decode(bytes)) == bytes for every well-formed golden case."""

    @pytest.mark.parametrize(
        "case",
        [c for c in CASES if c.group == "" and c.packet is not None],
        ids=lambda c: c.desc,
    )
    def test_bytes_roundtrip(self, case):
        pk = decode_packet(case.raw, case.version)
        assert encode_packet(pk) == case.raw


class TestValidate:
    def test_connect_validate_ok(self):
        pk = decode_packet(CASES[0].raw, 4)
        assert pk.connect_validate() == codes.CODE_SUCCESS

    def test_connect_bad_protocol_name(self):
        pk = Packet(connect=ConnectParams(protocol_name=b"WRONG"), protocol_version=4)
        assert pk.connect_validate() == codes.ERR_PROTOCOL_VIOLATION_PROTOCOL_NAME

    def test_connect_bad_protocol_version(self):
        pk = Packet(connect=ConnectParams(protocol_name=b"MQTT"), protocol_version=3)
        assert pk.connect_validate() == codes.ERR_PROTOCOL_VIOLATION_PROTOCOL_VERSION
        pk = Packet(connect=ConnectParams(protocol_name=b"MQIsdp"), protocol_version=4)
        assert pk.connect_validate() == codes.ERR_PROTOCOL_VIOLATION_PROTOCOL_VERSION

    def test_connect_reserved_bit(self):
        pk = Packet(connect=ConnectParams(protocol_name=b"MQTT"), protocol_version=4, reserved_bit=1)
        assert pk.connect_validate() == codes.ERR_PROTOCOL_VIOLATION_RESERVED_BIT

    def test_connect_will_no_payload(self):
        pk = Packet(
            connect=ConnectParams(protocol_name=b"MQTT", will_flag=True), protocol_version=4
        )
        assert pk.connect_validate() == codes.ERR_PROTOCOL_VIOLATION_WILL_FLAG_NO_PAYLOAD

    def test_connect_surplus_will_retain(self):
        pk = Packet(
            connect=ConnectParams(protocol_name=b"MQTT", will_retain=True), protocol_version=4
        )
        assert pk.connect_validate() == codes.ERR_PROTOCOL_VIOLATION_WILL_FLAG_SURPLUS_RETAIN

    def test_connect_password_no_flag(self):
        pk = Packet(
            connect=ConnectParams(protocol_name=b"MQTT", password=b"x"), protocol_version=4
        )
        assert pk.connect_validate() == codes.ERR_PROTOCOL_VIOLATION_PASSWORD_NO_FLAG

    def test_publish_validate(self):
        pk = Packet(fixed_header=fhdr(PUBLISH, qos=1), topic_name="t", packet_id=0)
        assert pk.publish_validate(0) == codes.ERR_PROTOCOL_VIOLATION_NO_PACKET_ID
        pk = Packet(fixed_header=fhdr(PUBLISH), topic_name="t", packet_id=3)
        assert pk.publish_validate(0) == codes.ERR_PROTOCOL_VIOLATION_SURPLUS_PACKET_ID
        pk = Packet(fixed_header=fhdr(PUBLISH), topic_name="t/+/x")
        assert pk.publish_validate(0) == codes.ERR_PROTOCOL_VIOLATION_SURPLUS_WILDCARD
        pk = Packet(fixed_header=fhdr(PUBLISH), topic_name="t/#")
        assert pk.publish_validate(0) == codes.ERR_PROTOCOL_VIOLATION_SURPLUS_WILDCARD
        pk = Packet(fixed_header=fhdr(PUBLISH), topic_name="")
        assert pk.publish_validate(0) == codes.ERR_PROTOCOL_VIOLATION_NO_TOPIC
        pk = Packet(
            fixed_header=fhdr(PUBLISH),
            topic_name="t",
            properties=Properties(topic_alias=5, topic_alias_flag=True),
        )
        assert pk.publish_validate(0) == codes.ERR_TOPIC_ALIAS_INVALID
        pk = Packet(
            fixed_header=fhdr(PUBLISH),
            topic_name="t",
            properties=Properties(subscription_identifier=[2]),
        )
        assert pk.publish_validate(10) == codes.ERR_PROTOCOL_VIOLATION_SURPLUS_SUB_ID
        pk = Packet(fixed_header=fhdr(PUBLISH), topic_name="t")
        assert pk.publish_validate(0) == codes.CODE_SUCCESS

    def test_subscribe_validate(self):
        pk = Packet(fixed_header=fhdr(SUBSCRIBE, qos=1), packet_id=0, filters=[Subscription("a")])
        assert pk.subscribe_validate() == codes.ERR_PROTOCOL_VIOLATION_NO_PACKET_ID
        pk = Packet(fixed_header=fhdr(SUBSCRIBE, qos=1), packet_id=1, filters=[])
        assert pk.subscribe_validate() == codes.ERR_PROTOCOL_VIOLATION_NO_FILTERS
        pk = Packet(
            fixed_header=fhdr(SUBSCRIBE, qos=1),
            packet_id=1,
            filters=[Subscription("a", identifier=268435456)],
        )
        assert pk.subscribe_validate() == codes.ERR_PROTOCOL_VIOLATION_OVERSIZE_SUB_ID

    def test_unsubscribe_validate(self):
        pk = Packet(fixed_header=fhdr(UNSUBSCRIBE, qos=1), packet_id=1, filters=[])
        assert pk.unsubscribe_validate() == codes.ERR_PROTOCOL_VIOLATION_NO_FILTERS

    def test_auth_validate(self):
        pk = Packet(fixed_header=fhdr(AUTH), reason_code=0x18)
        assert pk.auth_validate() == codes.CODE_SUCCESS
        pk = Packet(fixed_header=fhdr(AUTH), reason_code=0x99)
        assert pk.auth_validate() == codes.ERR_PROTOCOL_VIOLATION_INVALID_REASON

    def test_reason_code_valid(self):
        pk = Packet(fixed_header=fhdr(PUBREC), reason_code=0x10)
        assert pk.reason_code_valid()
        pk = Packet(fixed_header=fhdr(PUBREC), reason_code=0x91)
        assert pk.reason_code_valid()
        pk = Packet(fixed_header=fhdr(PUBREC), reason_code=0x92)
        assert not pk.reason_code_valid()
        pk = Packet(fixed_header=fhdr(PUBREL), reason_code=0x92)
        assert pk.reason_code_valid()
        pk = Packet(fixed_header=fhdr(SUBACK), reason_code=0xA2)
        assert pk.reason_code_valid()
        pk = Packet(fixed_header=fhdr(UNSUBACK), reason_code=0xA2)
        assert not pk.reason_code_valid()


class TestCopyAndMerge:
    def test_copy_resets_dup(self):
        pk = Packet(
            fixed_header=fhdr(PUBLISH, qos=2, dup=True, retain=True),
            topic_name="a/b",
            payload=b"x",
            packet_id=11,
            properties=Properties(topic_alias=3, topic_alias_flag=True),
        )
        cp = pk.copy(False)
        assert not cp.fixed_header.dup  # [MQTT-4.3.1-1]
        assert cp.packet_id == 0
        assert cp.properties.topic_alias == 0  # [MQTT-3.3.2-7]
        cp2 = pk.copy(True)
        assert cp2.packet_id == 11
        assert cp2.properties.topic_alias == 3

    def test_copy_deep(self):
        pk = Packet(payload=b"abc", reason_codes=b"\x01")
        cp = pk.copy(False)
        assert cp.payload == b"abc" and cp.payload is not pk.payload or isinstance(pk.payload, bytes)
        assert cp.reason_codes == b"\x01"

    def test_merge_max_qos(self):
        a = Subscription(filter="a/b", qos=0)
        b = Subscription(filter="a/b", qos=2)
        m = a.merge(b)
        assert m.qos == 2  # [MQTT-3.3.4-2]

    def test_merge_identifiers_union(self):
        a = Subscription(filter="a/+", qos=1, identifier=3)
        b = Subscription(filter="a/b", qos=0, identifier=9)
        m = a.merge(b)
        assert m.identifiers == {"a/+": 3, "a/b": 9}

    def test_merge_no_local_sticky(self):
        a = Subscription(filter="a", no_local=False)
        b = Subscription(filter="a", no_local=True)
        assert a.merge(b).no_local  # [MQTT-3.8.3-3]

    def test_sub_options_roundtrip(self):
        s = Subscription(qos=2, no_local=True, retain_as_published=True, retain_handling=2)
        b = s.encode_options()
        t = Subscription()
        t.decode_options(b)
        assert (t.qos, t.no_local, t.retain_as_published, t.retain_handling) == (2, True, True, 2)
