"""Golden packet catalogue + codec conformance.

The catalogue itself lives in ``tpackets.py`` (the analog of the
reference's packets/tpackets.go): every case pins exact wire bytes for
decode and encode, including malformed variants. Round-trip and validation
tests extend coverage beyond the hand-pinned vectors.
"""

import pytest
from tests.tpackets import CASES, fhdr

from mqtt_tpu.packets import (
    AUTH,
    CONNECT,
    PUBLISH,
    PUBREC,
    PUBREL,
    SUBACK,
    SUBSCRIBE,
    UNSUBACK,
    UNSUBSCRIBE,
    Code,
    ConnectParams,
    Packet,
    Properties,
    Subscription,
    codes,
    decode_packet,
    encode_packet,
)


def _decode_cases():
    return [c for c in CASES if c.group in ("", "decode")]


def _encode_cases():
    return [c for c in CASES if c.group in ("", "encode") and c.packet is not None]


@pytest.mark.parametrize("case", _decode_cases(), ids=lambda c: c.desc)
def test_decode(case):
    if case.fail_first is not None:
        with pytest.raises(Code) as e:
            decode_packet(case.raw, case.version)
        assert e.value.code == case.fail_first.code
        assert e.value.reason == case.fail_first.reason
        return
    if case.decode_err is not None:
        with pytest.raises(Code) as e:
            decode_packet(case.raw, case.version)
        assert e.value.code == case.decode_err.code
        assert e.value.reason.endswith(case.decode_err.reason)
        return
    pk = decode_packet(case.raw, case.version)
    assert pk == case.packet, f"decode mismatch for {case.desc}"


@pytest.mark.parametrize("case", _encode_cases(), ids=lambda c: c.desc)
def test_encode(case):
    assert encode_packet(case.packet) == case.raw, f"encode mismatch for {case.desc}"


class TestRoundTrips:
    """encode(decode(bytes)) == bytes for every well-formed golden case."""

    @pytest.mark.parametrize(
        "case",
        [c for c in CASES if c.group == "" and c.packet is not None],
        ids=lambda c: c.desc,
    )
    def test_bytes_roundtrip(self, case):
        pk = decode_packet(case.raw, case.version)
        assert encode_packet(pk) == case.raw


class TestValidate:
    def test_connect_validate_ok(self):
        case = next(c for c in CASES if c.desc == "connect v4 basic")
        pk = decode_packet(case.raw, 4)
        assert pk.connect_validate() == codes.CODE_SUCCESS

    def test_connect_bad_protocol_name(self):
        pk = Packet(connect=ConnectParams(protocol_name=b"WRONG"), protocol_version=4)
        assert pk.connect_validate() == codes.ERR_PROTOCOL_VIOLATION_PROTOCOL_NAME

    def test_connect_bad_protocol_version(self):
        pk = Packet(connect=ConnectParams(protocol_name=b"MQTT"), protocol_version=3)
        assert pk.connect_validate() == codes.ERR_PROTOCOL_VIOLATION_PROTOCOL_VERSION
        pk = Packet(connect=ConnectParams(protocol_name=b"MQIsdp"), protocol_version=4)
        assert pk.connect_validate() == codes.ERR_PROTOCOL_VIOLATION_PROTOCOL_VERSION

    def test_connect_reserved_bit(self):
        pk = Packet(connect=ConnectParams(protocol_name=b"MQTT"), protocol_version=4, reserved_bit=1)
        assert pk.connect_validate() == codes.ERR_PROTOCOL_VIOLATION_RESERVED_BIT

    def test_connect_will_no_payload(self):
        pk = Packet(
            connect=ConnectParams(protocol_name=b"MQTT", will_flag=True), protocol_version=4
        )
        assert pk.connect_validate() == codes.ERR_PROTOCOL_VIOLATION_WILL_FLAG_NO_PAYLOAD

    def test_connect_will_qos_out_of_range(self):
        pk = Packet(
            connect=ConnectParams(
                protocol_name=b"MQTT",
                will_flag=True,
                will_topic="t",
                will_payload=b"x",
                will_qos=3,
            ),
            protocol_version=4,
        )
        assert pk.connect_validate() == codes.ERR_PROTOCOL_VIOLATION_QOS_OUT_OF_RANGE

    def test_connect_surplus_will_retain(self):
        pk = Packet(
            connect=ConnectParams(protocol_name=b"MQTT", will_retain=True), protocol_version=4
        )
        assert pk.connect_validate() == codes.ERR_PROTOCOL_VIOLATION_WILL_FLAG_SURPLUS_RETAIN

    def test_connect_password_no_flag(self):
        pk = Packet(
            connect=ConnectParams(protocol_name=b"MQTT", password=b"x"), protocol_version=4
        )
        assert pk.connect_validate() == codes.ERR_PROTOCOL_VIOLATION_PASSWORD_NO_FLAG

    def test_connect_username_no_flag(self):
        pk = Packet(
            connect=ConnectParams(protocol_name=b"MQTT", username=b"x"), protocol_version=4
        )
        assert pk.connect_validate() == codes.ERR_PROTOCOL_VIOLATION_USERNAME_NO_FLAG

    def test_connect_password_flag_no_password(self):
        pk = Packet(
            connect=ConnectParams(protocol_name=b"MQTT", password_flag=True), protocol_version=4
        )
        assert pk.connect_validate() == codes.ERR_PROTOCOL_VIOLATION_FLAG_NO_PASSWORD

    def test_publish_validate(self):
        pk = Packet(fixed_header=fhdr(PUBLISH, qos=1), topic_name="t", packet_id=0)
        assert pk.publish_validate(0) == codes.ERR_PROTOCOL_VIOLATION_NO_PACKET_ID
        pk = Packet(fixed_header=fhdr(PUBLISH), topic_name="t", packet_id=3)
        assert pk.publish_validate(0) == codes.ERR_PROTOCOL_VIOLATION_SURPLUS_PACKET_ID
        pk = Packet(fixed_header=fhdr(PUBLISH), topic_name="t/+/x")
        assert pk.publish_validate(0) == codes.ERR_PROTOCOL_VIOLATION_SURPLUS_WILDCARD
        pk = Packet(fixed_header=fhdr(PUBLISH), topic_name="t/#")
        assert pk.publish_validate(0) == codes.ERR_PROTOCOL_VIOLATION_SURPLUS_WILDCARD
        pk = Packet(fixed_header=fhdr(PUBLISH), topic_name="")
        assert pk.publish_validate(0) == codes.ERR_PROTOCOL_VIOLATION_NO_TOPIC
        pk = Packet(
            fixed_header=fhdr(PUBLISH),
            topic_name="t",
            properties=Properties(topic_alias=5, topic_alias_flag=True),
        )
        assert pk.publish_validate(0) == codes.ERR_TOPIC_ALIAS_INVALID
        pk = Packet(
            fixed_header=fhdr(PUBLISH),
            topic_name="t",
            properties=Properties(subscription_identifier=[2]),
        )
        assert pk.publish_validate(10) == codes.ERR_PROTOCOL_VIOLATION_SURPLUS_SUB_ID
        pk = Packet(fixed_header=fhdr(PUBLISH), topic_name="t")
        assert pk.publish_validate(0) == codes.CODE_SUCCESS

    def test_subscribe_validate(self):
        pk = Packet(fixed_header=fhdr(SUBSCRIBE, qos=1), packet_id=0, filters=[Subscription("a")])
        assert pk.subscribe_validate() == codes.ERR_PROTOCOL_VIOLATION_NO_PACKET_ID
        pk = Packet(fixed_header=fhdr(SUBSCRIBE, qos=1), packet_id=1, filters=[])
        assert pk.subscribe_validate() == codes.ERR_PROTOCOL_VIOLATION_NO_FILTERS
        pk = Packet(
            fixed_header=fhdr(SUBSCRIBE, qos=1),
            packet_id=1,
            filters=[Subscription("a", identifier=268435456)],
        )
        assert pk.subscribe_validate() == codes.ERR_PROTOCOL_VIOLATION_OVERSIZE_SUB_ID

    def test_unsubscribe_validate(self):
        pk = Packet(fixed_header=fhdr(UNSUBSCRIBE, qos=1), packet_id=1, filters=[])
        assert pk.unsubscribe_validate() == codes.ERR_PROTOCOL_VIOLATION_NO_FILTERS

    def test_auth_validate(self):
        pk = Packet(fixed_header=fhdr(AUTH), reason_code=0x18)
        assert pk.auth_validate() == codes.CODE_SUCCESS
        pk = Packet(fixed_header=fhdr(AUTH), reason_code=0x99)
        assert pk.auth_validate() == codes.ERR_PROTOCOL_VIOLATION_INVALID_REASON

    def test_reason_code_valid(self):
        pk = Packet(fixed_header=fhdr(PUBREC), reason_code=0x10)
        assert pk.reason_code_valid()
        pk = Packet(fixed_header=fhdr(PUBREC), reason_code=0x91)
        assert pk.reason_code_valid()
        pk = Packet(fixed_header=fhdr(PUBREC), reason_code=0x92)
        assert not pk.reason_code_valid()
        pk = Packet(fixed_header=fhdr(PUBREL), reason_code=0x92)
        assert pk.reason_code_valid()
        pk = Packet(fixed_header=fhdr(SUBACK), reason_code=0xA2)
        assert pk.reason_code_valid()
        pk = Packet(fixed_header=fhdr(UNSUBACK), reason_code=0xA2)
        assert not pk.reason_code_valid()


class TestCopyAndMerge:
    def test_copy_resets_dup(self):
        pk = Packet(
            fixed_header=fhdr(PUBLISH, qos=2, dup=True, retain=True),
            topic_name="a/b",
            payload=b"x",
            packet_id=11,
            properties=Properties(topic_alias=3, topic_alias_flag=True),
        )
        cp = pk.copy(False)
        assert not cp.fixed_header.dup  # [MQTT-4.3.1-1]
        assert cp.packet_id == 0
        assert cp.properties.topic_alias == 0  # [MQTT-3.3.2-7]
        cp2 = pk.copy(True)
        assert cp2.packet_id == 11
        assert cp2.properties.topic_alias == 3

    def test_copy_deep(self):
        pk = Packet(payload=b"abc", reason_codes=b"\x01")
        cp = pk.copy(False)
        assert cp.payload == b"abc" and cp.payload is not pk.payload or isinstance(pk.payload, bytes)
        assert cp.reason_codes == b"\x01"

    def test_merge_max_qos(self):
        a = Subscription(filter="a/b", qos=0)
        b = Subscription(filter="a/b", qos=2)
        m = a.merge(b)
        assert m.qos == 2  # [MQTT-3.3.4-2]

    def test_merge_identifiers_union(self):
        a = Subscription(filter="a/+", qos=1, identifier=3)
        b = Subscription(filter="a/b", qos=0, identifier=9)
        m = a.merge(b)
        assert m.identifiers == {"a/+": 3, "a/b": 9}

    def test_merge_no_local_sticky(self):
        a = Subscription(filter="a", no_local=False)
        b = Subscription(filter="a", no_local=True)
        assert a.merge(b).no_local  # [MQTT-3.8.3-3]

    def test_sub_options_roundtrip(self):
        s = Subscription(qos=2, no_local=True, retain_as_published=True, retain_handling=2)
        b = s.encode_options()
        t = Subscription()
        t.decode_options(b)
        assert (t.qos, t.no_local, t.retain_as_published, t.retain_handling) == (2, True, True, 2)


def _validate_cases():
    return [c for c in CASES if c.validate_err is not None]


@pytest.mark.parametrize("case", _validate_cases(), ids=lambda c: c.desc)
def test_validate_catalogue(case):
    """Decode (when wire-expressible) then run the packet type's validate;
    the reference's Invalid*/Spec* conformance tier (tpackets.go)."""
    pk = case.packet if not case.raw else decode_packet(case.raw, case.version)
    t = pk.fixed_header.type
    if t == PUBLISH:
        code = pk.publish_validate(case.validate_arg)
    elif t == SUBSCRIBE:
        code = pk.subscribe_validate()
    elif t == UNSUBSCRIBE:
        code = pk.unsubscribe_validate()
    elif t == AUTH:
        code = pk.auth_validate()
    else:
        code = pk.connect_validate()
    assert code == case.validate_err, f"{case.desc}: got {code!r}"
