"""Race hardening for the threaded matcher machinery (VERDICT r4 item 8).

The fold/rebuild/observer paths rest on hand-written concurrency
contracts — the copy-on-write fold clone (ops/flat.py), the lock-order
rule that a sharded rebuild must not run under the trie lock
(ops/delta.py:_rebuild_snapshot), torn-read retries in the lock-free trie
walks. ``go test -race`` has no CPython analog, so these tests do what
the reference's race detector did empirically: hammer the structures
from multiple threads and assert bit-parity and liveness throughout.

The main test churns subscriptions from two writer threads while a
matcher thread matches continuously; every batch is checked for parity
against the live trie (topics the overlay routes to the host are always
correct; device-served topics must match the trie too whenever the trie
is quiescent for the comparison instant — we assert the DeltaMatcher
contract instead: every result equals a host walk taken immediately
after, with all raced filters routed). Deadlock shows up as the
``timeout`` marker killing the test.
"""

import contextlib
import faulthandler
import random
import sys
import threading
import time

import pytest

from mqtt_tpu.ops.delta import DeltaMatcher
from mqtt_tpu.packets import Subscription
from mqtt_tpu.topics import SHARE_PREFIX, TopicsIndex


@contextlib.contextmanager
def switch_interval(interval_s: float):
    """Thread-schedule fuzzing fixture (ROADMAP "Correctness tooling"):
    pin ``sys.setswitchinterval`` for the block's duration — a tiny
    interval preempts threads mid-bytecode-run orders of magnitude more
    often than the 5ms default, shaking out interleavings the default
    schedule practically never produces — and ALWAYS restore the
    original, or the whole session runs degraded afterwards."""
    prev = sys.getswitchinterval()
    sys.setswitchinterval(interval_s)
    try:
        yield
    finally:
        sys.setswitchinterval(prev)

SEGS = ["alpha", "beta", "gamma", "delta", "x"]


def canon(s):
    return (
        {c: (sub.qos, sub.no_local) for c, sub in s.subscriptions.items()},
        {f: frozenset(m) for f, m in s.shared.items()},
        frozenset(s.inline_subscriptions),
    )


def _rand_filter(r):
    parts = [r.choice(SEGS + ["+"]) for _ in range(r.randint(1, 3))]
    if r.random() < 0.2:
        parts[-1] = "#"
    return "/".join(parts)


def _rand_topic(r):
    return "/".join(r.choice(SEGS) for _ in range(r.randint(1, 3)))


def test_churn_while_matching_two_writers():
    """>=2 writer threads mutate the trie for several seconds while the
    main thread matches continuously through a background-rebuilding
    DeltaMatcher; every batch must be served (no deadlock, no exception)
    and spot-checked batches must be bit-identical to the live trie under
    a writer pause."""
    index = TopicsIndex()
    r0 = random.Random(1)
    for i in range(2000):
        index.subscribe(f"base{i}", Subscription(filter=_rand_filter(r0), qos=i % 3))

    # deadlock backstop (no pytest-timeout in the image): a wedged lock
    # pair dumps all thread stacks and kills the process instead of
    # hanging the suite forever
    faulthandler.dump_traceback_later(110, exit=True)
    m = DeltaMatcher(
        index, max_levels=4, rebuild_after=64, rebuild_interval=0.05, background=True
    )
    stop = threading.Event()
    pause = threading.Event()
    resume = threading.Event()
    paused = threading.Barrier(3, timeout=30)
    errors: list = []

    def writer(seed: int) -> None:
        r = random.Random(seed)
        i = 0
        try:
            while not stop.is_set():
                if pause.is_set():
                    paused.wait()  # rendezvous with the checker
                    resume.wait()  # released when the parity check is done
                    continue
                flt = _rand_filter(r)
                kind = r.random()
                if kind < 0.45:
                    index.subscribe(f"w{seed}_{i}", Subscription(filter=flt, qos=1))
                elif kind < 0.9:
                    index.unsubscribe(flt, f"w{seed}_{r.randint(0, max(1, i))}")
                else:
                    index.subscribe(
                        f"w{seed}_{i}",
                        Subscription(filter=f"{SHARE_PREFIX}/g{seed}/{flt}", qos=1),
                    )
                i += 1
                time.sleep(0.0005)  # ~2k mutations/s per writer; leaves
                # the GIL to the matcher thread on small hosts
        except Exception as e:  # pragma: no cover - the assertion target
            errors.append(e)

    writers = [threading.Thread(target=writer, args=(s,), daemon=True) for s in (7, 8)]
    for t in writers:
        t.start()

    r = random.Random(42)
    t_end = time.time() + 10.0
    batches = 0
    try:
        while time.time() < t_end:
            topics = [_rand_topic(r) for _ in range(256)]
            results = m.match_topics(topics)  # must not deadlock or raise
            assert len(results) == len(topics)
            batches += 1
            if batches % 5 == 0:
                # parity checkpoint: pause the writers at a barrier so the
                # trie is quiescent, then device results must equal the
                # host walk exactly
                resume.clear()
                pause.set()
                paused.wait()  # both writers parked at resume.wait()
                check = [_rand_topic(r) for _ in range(64)]
                got = m.match_topics(check)
                for topic, res in zip(check, got):
                    assert canon(res) == canon(index.subscribers(topic)), topic
                pause.clear()
                resume.set()
    finally:
        stop.set()
        pause.clear()
        resume.set()
        for t in writers:
            t.join(timeout=10)
        m.close()
    faulthandler.cancel_dump_traceback_later()
    assert not errors, errors
    # liveness floor, not a throughput claim: the CPU-jax kernel on a
    # loaded 1-core host manages a few hundred ms per 256-topic batch
    # (a wedged matcher produces 0-1; anything near the floor is alive)
    assert batches >= 5, f"matcher starved: only {batches} batches in 10s"
    # the run must have exercised the incremental machinery, not just
    # full rebuilds
    assert m.stats.rebuilds + m.stats.folds > 2


def _lazy_view_churn(duration_s: float, seed: int) -> int:
    """Lazy-view lifetime drill (ISSUE 13 satellite): writer threads
    churn subscriptions (subscribe/unsubscribe/$SHARE, plus whole-client
    unsubscribes — the disconnect/session-takeover analog) while the
    main thread resolves LAZY SubscribersView batches and consumes them
    only AFTER a delay + forced GC — so unsubscribes land exactly
    between device resolve and fan-out consumption. The snapshot table
    must keep every captured (client, Subscription) alive and coherent
    (no UAF, no torn objects); quiescent parity checkpoints pin the
    materialized views against the live host walk. Returns batches
    consumed."""
    import gc

    from mqtt_tpu import native

    if native.accel() is None:
        pytest.skip("no C toolchain: lazy views cannot exist")
    index = TopicsIndex()
    r0 = random.Random(seed)
    for i in range(800):
        index.subscribe(
            f"base{i}", Subscription(filter=_rand_filter(r0), qos=i % 3)
        )
    faulthandler.dump_traceback_later(110, exit=True)
    m = DeltaMatcher(
        index, max_levels=4, rebuild_after=32, rebuild_interval=0.05,
        background=True, lazy=True,
    )
    stop = threading.Event()
    pause = threading.Event()
    resume = threading.Event()
    paused = threading.Barrier(3, timeout=30)
    errors: list = []

    def writer(wseed: int) -> None:
        r = random.Random(wseed)
        i = 0
        owned: dict = {}  # this writer's client -> [filters] mirror
        try:
            while not stop.is_set():
                if pause.is_set():
                    paused.wait()
                    resume.wait()
                    continue
                flt = _rand_filter(r)
                kind = r.random()
                if kind < 0.4:
                    cid = f"w{wseed}_{i}"
                    index.subscribe(cid, Subscription(filter=flt, qos=1))
                    owned.setdefault(cid, []).append(flt)
                elif kind < 0.8:
                    index.unsubscribe(
                        flt, f"w{wseed}_{r.randint(0, max(1, i))}"
                    )
                elif kind < 0.9:
                    index.subscribe(
                        f"w{wseed}_{i}",
                        Subscription(
                            filter=f"{SHARE_PREFIX}/g{wseed}/{flt}", qos=1
                        ),
                    )
                elif owned:
                    # the disconnect/takeover analog: drop EVERY filter
                    # a client holds, like server.unsubscribe_client
                    victim = r.choice(list(owned))
                    for f2 in owned.pop(victim):
                        index.unsubscribe(f2, victim)
                i += 1
                time.sleep(0.0005)
        except Exception as e:  # pragma: no cover - the assertion target
            errors.append(e)

    writers = [
        threading.Thread(target=writer, args=(s,), daemon=True)
        for s in (seed + 1, seed + 2)
    ]
    for t in writers:
        t.start()

    r = random.Random(seed + 99)
    t_end = time.time() + duration_s
    batches = 0
    held: list = []  # views outliving several churn windows
    try:
        while time.time() < t_end:
            topics = [_rand_topic(r) for _ in range(128)]
            views = m.match_topics(topics)
            # let unsubscribes/disconnects land between resolve and
            # consumption, then drop any dead references they freed
            time.sleep(0.002)
            if batches % 7 == 0:
                gc.collect()
            for v in views:
                consume = getattr(v, "targets", None)
                if consume is None:
                    continue  # host-routed row: plain Subscribers
                for cid, sub in consume():
                    # snapshot-time coherence: every captured object is
                    # intact, whatever the trie did since
                    assert isinstance(cid, str) and cid
                    assert isinstance(sub.filter, str)
                    assert sub.qos in (0, 1, 2)
            # a slice of views deliberately outlives the batch (the
            # slow-consumer analog): consuming them batches later must
            # still be safe
            if batches % 3 == 0:
                held.extend(v for v in views[:4] if v is not None)
                if len(held) > 32:
                    for v in held[:16]:
                        mzd = v.materialize()
                        assert mzd.subscriptions is not None
                    del held[:16]
            batches += 1
            if batches % 10 == 0:
                resume.clear()
                pause.set()
                paused.wait()
                check = [_rand_topic(r) for _ in range(32)]
                got = m.match_topics(check)
                for topic, res in zip(check, got):
                    assert canon(res) == canon(index.subscribers(topic)), topic
                pause.clear()
                resume.set()
    finally:
        stop.set()
        pause.clear()
        resume.set()
        for t in writers:
            t.join(timeout=10)
        # final parity checkpoint (always runs, however slow the box
        # was: the writers are joined, so the trie is quiescent) —
        # lazy views materialized against the live host walk
        try:
            check = [_rand_topic(r) for _ in range(32)]
            got = m.match_topics(check)
            for topic, res in zip(check, got):
                assert canon(res) == canon(index.subscribers(topic)), topic
        finally:
            m.close()
    faulthandler.cancel_dump_traceback_later()
    assert not errors, errors
    return batches


def test_lazy_view_lifetime_churn_quick():
    """Tier-1 leg of the lazy-view lifetime drill (one seed, short).
    The floor is a LIVENESS bar (a wedged pipeline yields 0-1 batches
    on any box); the invariants are per-batch asserts + the final
    quiescent parity checkpoint inside the drill."""
    assert _lazy_view_churn(4.0, seed=17) >= 2


@pytest.mark.slow
@pytest.mark.parametrize("interval_s", [1e-6, 1e-5])
@pytest.mark.parametrize("seed", [11, 23, 47])
def test_lazy_view_lifetime_switch_sweep(interval_s, seed):
    """Nightly seeded schedule sweep over the lazy-view lifetime drill:
    pathological GIL handover points between resolve, churn, GC and
    consumption."""
    with switch_interval(interval_s):
        assert _lazy_view_churn(5.0, seed=seed) >= 2


@pytest.mark.slow
@pytest.mark.parametrize("interval_s", [1e-6, 1e-5, 1e-4])
def test_churn_switch_interval_sweep(interval_s):
    """The nightly thread-schedule sweep: the two-writer churn drill
    re-run under seeded switch intervals far below the 5ms default
    (1us/10us/100us), so the GIL hands over at pathological points —
    torn trie walks, observer re-entries, fold/rebuild interleavings the
    default schedule essentially never exercises. Each leg is a
    shortened copy of the main churn test: every batch served, final
    parity bit-identical under a writer pause."""
    index = TopicsIndex()
    seed = int(interval_s * 1e7) or 1
    r0 = random.Random(seed)
    for i in range(800):
        index.subscribe(f"base{i}", Subscription(filter=_rand_filter(r0), qos=i % 3))
    faulthandler.dump_traceback_later(110, exit=True)
    stop = threading.Event()
    errors: list = []

    def writer(wseed: int) -> None:
        r = random.Random(wseed)
        i = 0
        try:
            while not stop.is_set():
                flt = _rand_filter(r)
                if r.random() < 0.5:
                    index.subscribe(f"w{wseed}_{i}", Subscription(filter=flt, qos=1))
                else:
                    index.unsubscribe(flt, f"w{wseed}_{r.randint(0, max(1, i))}")
                i += 1
                time.sleep(0.0005)
        except Exception as e:  # pragma: no cover - the assertion target
            errors.append(e)

    try:
        with switch_interval(interval_s):
            m = DeltaMatcher(
                index, max_levels=4, rebuild_after=64, rebuild_interval=0.05,
                background=True,
            )
            writers = [
                threading.Thread(target=writer, args=(s,), daemon=True)
                for s in (seed + 1, seed + 2)
            ]
            for t in writers:
                t.start()
            r = random.Random(42)
            t_end = time.time() + 3.0
            batches = 0
            try:
                while time.time() < t_end:
                    topics = [_rand_topic(r) for _ in range(128)]
                    results = m.match_topics(topics)
                    assert len(results) == len(topics)
                    batches += 1
            finally:
                stop.set()
                for t in writers:
                    t.join(timeout=10)
            # final parity once the writers stopped (trie quiescent)
            m.flush()
            try:
                for topic in [_rand_topic(r) for _ in range(48)]:
                    assert canon(m.subscribers(topic)) == canon(
                        index.subscribers(topic)
                    ), topic
            finally:
                m.close()
    finally:
        # disarm even on a failed leg: a still-armed exit=True timer
        # would hard-kill the whole nightly session 110s later
        faulthandler.cancel_dump_traceback_later()
    assert not errors, errors
    assert batches >= 2, f"matcher starved under {interval_s}s switch interval"


@pytest.mark.slow
@pytest.mark.parametrize("interval_s", [1e-6, 1e-5, 1e-4])
def test_tree_epoch_race_sweep(interval_s):
    """Thread-schedule sweep over the spanning-tree state (ISSUE 9):
    concurrent ELECTION (adopt/propose from a gossip thread and a
    health-clock thread), HEAL (membership re-adds + duplicate-window
    traffic, the park-replay shape), and SUMMARY REFRESH (counted-bloom
    churn racing bits() exports) — the three mutation streams
    mqtt_tpu.cluster runs against one Topology. Invariants: the local
    tree is ALWAYS acyclic and spanning for the local view, a racing
    (origin, boot, seq) is claimed by EXACTLY one thread (the
    exactly-once heal guarantee), and the bloom converges to exactly the
    net interest set once the churn stops."""
    from mqtt_tpu.mesh_topology import (
        CountedBloom,
        DuplicateSuppressor,
        Topology,
        TreeEpoch,
        is_spanning_tree,
        tree_neighbors,
    )

    seed = int(interval_s * 1e7) or 1
    faulthandler.dump_traceback_later(110, exit=True)
    stop = threading.Event()
    errors: list = []

    topo = Topology(0, range(16), degree=3, boot_id=99)
    bloom = CountedBloom(1024)
    dup = DuplicateSuppressor(window=4096)
    claims: dict = {}  # (origin, boot, seq) -> claim count (must be 1)
    claims_lock = threading.Lock()

    def electioneer(eseed: int) -> None:
        """The gossip/health stream: adoptions, scoped removals,
        re-join proposals — every step must leave a spanning tree."""
        r = random.Random(eseed)
        try:
            while not stop.is_set():
                op = r.randrange(4)
                if op == 0:
                    topo.propose_remove(r.randrange(16))
                elif op == 1:
                    topo.propose_add(r.randrange(16), boot=r.randrange(4))
                elif op == 2:
                    members = {
                        w: r.randrange(4)
                        for w in r.sample(range(16), r.randint(1, 12))
                    }
                    topo.adopt(
                        TreeEpoch(
                            r.randint(0, 500), r.randrange(4), r.randrange(16)
                        ),
                        members,
                    )
                else:
                    topo.propose_self()
                parents, view = topo.parents(), topo.members()
                # snapshot consistency: both reads under the same lock
                # discipline — a torn pair would fail the validator
                if set(parents) == set(view):
                    assert is_spanning_tree(parents, view)
                time.sleep(0.0002)
        except Exception as e:  # pragma: no cover - the assertion target
            errors.append(e)

    def healer(hseed: int) -> None:
        """The heal stream: replayed (origin, boot, seq) triples racing
        the other healer for the same window slots — each triple must be
        claimed exactly once across BOTH threads."""
        r = random.Random(hseed)
        try:
            for i in range(4000):
                if stop.is_set():
                    break
                # half the space is shared with the other healer (the
                # re-parenting replay race), half is private traffic
                if r.random() < 0.5:
                    key = (1, 7, r.randrange(2000))
                else:
                    key = (hseed, 7, i)
                if not dup.seen(*key):
                    with claims_lock:
                        claims[key] = claims.get(key, 0) + 1
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def refresher(rseed: int) -> None:
        """The summary stream: interest churn racing bits() exports;
        net-zero add/discard pairs must cancel exactly."""
        r = random.Random(rseed)
        try:
            while not stop.is_set():
                f = f"race/{r.randrange(32)}/x"
                bloom.add(f)
                bits = bloom.bits()  # the refresh export, mid-churn
                assert bits.might_match(f) or True  # must not raise
                bloom.discard(f)
                time.sleep(0.0001)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [
        threading.Thread(target=electioneer, args=(seed + 1,), daemon=True),
        threading.Thread(target=electioneer, args=(seed + 2,), daemon=True),
        threading.Thread(target=healer, args=(seed + 3,), daemon=True),
        threading.Thread(target=healer, args=(seed + 4,), daemon=True),
        threading.Thread(target=refresher, args=(seed + 5,), daemon=True),
    ]
    try:
        with switch_interval(interval_s):
            for t in threads:
                t.start()
            t_end = time.time() + 3.0
            while time.time() < t_end:
                # the forward path's reads, continuously: must never
                # raise and must always reflect a consistent tree
                n = topo.neighbors()
                assert 0 not in n
                topo.epoch_num()
                time.sleep(0.0005)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        faulthandler.cancel_dump_traceback_later()
    assert not errors, errors
    # exactly-once: no (origin, boot, seq) was claimed twice
    doubles = {k: v for k, v in claims.items() if v != 1}
    assert not doubles, doubles
    # quiescent convergence: the tree is spanning and neighbor reads
    # agree with a fresh recompute from the final view
    parents, view = topo.parents(), topo.members()
    assert is_spanning_tree(parents, view)
    assert set(topo.neighbors()) == set(tree_neighbors(parents, 0))
    # the bloom drained: every add was cancelled by its discard
    final = bloom.bits()
    assert not any(final.data), "counted bloom failed to drain to empty"


# -- graph-guided schedule fuzzing (ISSUE 10) --------------------------------
#
# The blunt setswitchinterval sweep above preempts EVERYWHERE; the lock
# graph says where preemption actually matters — the acquire/release
# boundaries of the staging/governor/breaker/cluster edge set. The
# PreemptionInjector (mqtt_tpu.utils.locked) yields the GIL at exactly
# those boundaries under a seeded, per-thread-deterministic schedule,
# and the session lock witness (armed in conftest) turns any
# inconsistent acquisition order the schedule provokes into a recorded
# cycle violation.

FUZZ_LOCKS = frozenset(
    {
        "overload_governor",
        "overload_peer_pressure",
        "matcher_breaker",
        "topics_trie",
        "cluster_remote_trie",
        "retained",
        "clients",
    }
)


def _fuzz_schedule(seed: int, ops_per_thread: int = 40) -> dict:
    """One fuzzed schedule: three deterministically-named threads drive
    seeded op scripts over the real broker control/data-plane objects
    (trie + retained store, remote trie, governor + peer signal,
    breaker, clients registry) while the injector preempts at the
    graph's lock boundaries. Returns the injector's per-thread decision
    logs. Asserts liveness (no deadlock: every thread joins) and that
    no thread raised."""
    from mqtt_tpu.clients import Clients
    from mqtt_tpu.overload import OverloadConfig, OverloadGovernor, PeerPressureSignal
    from mqtt_tpu.packets import Packet, Subscription as Sub
    from mqtt_tpu.resilience import CircuitBreaker
    from mqtt_tpu.utils.locked import DEFAULT_PLANE, PreemptionInjector

    index = TopicsIndex()
    remote = TopicsIndex(lock_name="cluster_remote_trie")
    gov = OverloadGovernor(OverloadConfig(eval_interval_s=0.0))
    gov.add_source("fuzz", lambda: 0.2)
    peers = PeerPressureSignal()
    breaker = CircuitBreaker(failure_threshold=3)
    clients = Clients()
    errors: list = []

    def script(tid: int) -> None:
        r = random.Random((seed << 4) | tid)
        try:
            for i in range(ops_per_thread):
                op = r.randrange(8)
                if op == 0:
                    index.subscribe(f"c{tid}_{i}", Sub(filter=_rand_filter(r), qos=1))
                elif op == 1:
                    pk = Packet()
                    pk.topic_name = f"f/{tid}/{r.randrange(8)}"
                    pk.payload = b"x"
                    pk.fixed_header.retain = True
                    index.retain_message(pk)
                elif op == 2:
                    remote.subscribe(f"r{tid}_{i}", Sub(filter=_rand_filter(r), qos=0))
                elif op == 3:
                    gov.evaluate(force=True)
                elif op == 4:
                    peers.observe(tid, r.randrange(3), r.random())
                    peers.value()
                elif op == 5:
                    if r.random() < 0.5:
                        breaker.record_failure("fuzz")
                    else:
                        breaker.record_success()
                    breaker.allow()
                elif op == 6:
                    clients.add(f"cl{tid}_{i % 4}", object())
                    clients.get(f"cl{tid}_{i % 4}")
                else:
                    index.subscribers(_rand_topic(r))
        except Exception as e:  # pragma: no cover - the assertion target
            errors.append(e)

    injector = PreemptionInjector(seed, rate=0.4, names=FUZZ_LOCKS)
    threads = [
        threading.Thread(
            target=script, args=(t,), daemon=True, name=f"fuzz-{t}"
        )
        for t in range(3)
    ]
    DEFAULT_PLANE.arm_fuzz(injector)
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
    finally:
        DEFAULT_PLANE.disarm_fuzz()
    stuck = [t.name for t in threads if t.is_alive()]
    assert not stuck, f"deadlocked schedule seed={seed}: {stuck} never joined"
    assert not errors, errors
    return {
        name: ops
        for name, ops in injector.trace().items()
        if name.startswith("fuzz-")
    }


def test_schedule_fuzz_same_seed_same_schedule():
    """The determinism contract: two fresh runs of the same seed produce
    IDENTICAL per-thread decision logs (op index, lock name, phase,
    preempt verdict) — the property that makes a failing seed
    replayable."""
    a = _fuzz_schedule(1234)
    b = _fuzz_schedule(1234)
    assert set(a) == set(b) == {"fuzz-0", "fuzz-1", "fuzz-2"}
    for tname in a:
        assert a[tname] == b[tname], f"schedule diverged on {tname}"
    # and a different seed really produces a different schedule
    c = _fuzz_schedule(4321)
    assert any(a[t] != c[t] for t in a)


def test_schedule_fuzz_quick_sweep():
    """Tier-1 leg: a dozen seeded schedules over the hot edge set with
    zero deadlocks and zero witness violations."""
    from mqtt_tpu.utils.locked import DEFAULT_PLANE

    faulthandler.dump_traceback_later(110, exit=True)
    try:
        witness = DEFAULT_PLANE.witness
        before = len(witness.violations) if witness is not None else 0
        for seed in range(12):
            _fuzz_schedule(seed)
        if witness is not None:
            assert witness.violations[before:] == [], witness.violations[before:]
    finally:
        faulthandler.cancel_dump_traceback_later()


@pytest.mark.slow
def test_schedule_fuzz_200_schedules():
    """The chaos-smoke acceptance sweep (ISSUE 10): >= 200 seeded
    schedules over the staging/governor/breaker/cluster edge set, every
    one deadlock-free, with the session witness recording zero
    lock-order cycles across the entire sweep."""
    from mqtt_tpu.utils.locked import DEFAULT_PLANE, LockWitness

    faulthandler.dump_traceback_later(540, exit=True)
    witness = DEFAULT_PLANE.witness
    owned = witness is None
    if owned:
        witness = DEFAULT_PLANE.arm_witness()
    before = len(witness.violations)
    try:
        for seed in range(200):
            _fuzz_schedule(seed, ops_per_thread=30)
    finally:
        faulthandler.cancel_dump_traceback_later()
        if owned:
            DEFAULT_PLANE.disarm_witness()
    assert witness.violations[before:] == [], witness.violations[before:]


def test_fold_lock_order_regression():
    """The ops/delta.py contract: _rebuild_snapshot must never wrap a
    rebuild in the trie lock while a mutation holds it and waits on the
    rebuild mutex. Interleave explicit flushes with mutations from
    another thread; a lock-order inversion deadlocks (caught by the
    timeout marker)."""
    faulthandler.dump_traceback_later(55, exit=True)
    index = TopicsIndex()
    r = random.Random(3)
    for i in range(500):
        index.subscribe(f"c{i}", Subscription(filter=_rand_filter(r), qos=0))
    m = DeltaMatcher(index, max_levels=4, background=False)
    stop = threading.Event()

    def mutate() -> None:
        rr = random.Random(4)
        i = 0
        while not stop.is_set():
            index.subscribe(f"m{i}", Subscription(filter=_rand_filter(rr), qos=1))
            if i % 3 == 0:
                index.unsubscribe(_rand_filter(rr), f"m{rr.randint(0, i + 1)}")
            i += 1
            time.sleep(0.0002)

    th = threading.Thread(target=mutate, daemon=True)
    th.start()
    try:
        for _ in range(30):
            m.flush()  # synchronous rebuild/fold racing the mutator
            m.match_topics([_rand_topic(r) for _ in range(32)])
    finally:
        stop.set()
        th.join(timeout=10)
        m.close()
    faulthandler.cancel_dump_traceback_later()
    # final parity once the mutator stopped
    m.flush()
    for t in [_rand_topic(r) for _ in range(32)]:
        assert canon(m.subscribers(t)) == canon(index.subscribers(t)), t


# -- shard-fabric handoff drill (ISSUE 15) ----------------------------------


async def _shard_handoff_drill(seed: int, rounds: int = 3) -> None:
    """Seeded churn over the event-loop shard fabric: clients REUSING a
    small id pool connect, publish, and vanish abruptly while a stable
    subscriber counts deliveries — takeovers land on different shards
    (least-loaded dispatch over a moving population), disconnect/stop
    teardowns marshal cross-shard, and the count must come out exact.
    Deadlock shows up as the harness timeout killing the test."""
    import asyncio

    from mqtt_tpu.hooks.auth.allow_all import AllowHook
    from mqtt_tpu.listeners import Config as LConfig
    from mqtt_tpu.listeners.tcp import TCP
    from mqtt_tpu.server import Options, Server
    from tests.test_server import connect_packet, read_wire_packet, sub_packet, pub_packet

    r = random.Random(seed)
    srv = Server(Options(loop_shards=3, overload_control=False))
    srv.add_hook(AllowHook())
    srv.add_listener(TCP(LConfig(type="tcp", id="drill", address="127.0.0.1:0")))
    await srv.serve()
    port = int(srv.listeners.get("drill").address().rsplit(":", 1)[1])

    async def conn(cid):
        cr, cw = await asyncio.open_connection("127.0.0.1", port)
        cw.write(connect_packet(cid, 4))
        await cw.drain()
        ack = await asyncio.wait_for(read_wire_packet(cr, 4), 10)
        assert ack.fixed_header.type == 2  # CONNACK
        return cr, cw

    try:
        sub_r, sub_w = await conn("stable")
        sub_w.write(sub_packet(1, [Subscription(filter="r/#", qos=0)]))
        await sub_w.drain()
        await asyncio.wait_for(read_wire_packet(sub_r, 4), 10)

        from mqtt_tpu.stress import _scan_frames

        got = 0
        published = 0
        buf = bytearray()

        async def drain_subscriber():
            """Read until every published message arrived (QoS0 over
            loopback: exact, as long as no publisher dies mid-flight —
            rounds are sequential so takeovers only hit clients whose
            publishes were already delivered)."""
            nonlocal got
            deadline = time.monotonic() + 15
            while got < published and time.monotonic() < deadline:
                try:
                    data = await asyncio.wait_for(sub_r.read(65536), 0.5)
                except asyncio.TimeoutError:
                    continue
                if not data:
                    break
                buf.extend(data)
                frames, consumed = _scan_frames(buf)
                for first, _bs, _be in frames:
                    if (first >> 4) == 3:  # PUBLISH
                        got += 1
                del buf[:consumed]

        for rnd in range(rounds):
            async def churn(slot):
                nonlocal published
                # same id every round: round N+1's connect takes over
                # round N's lingering session, usually on a DIFFERENT
                # shard (least-loaded over a moving population)
                cr, cw = await conn(f"churn{slot}")
                n = r.randint(5, 20)
                for i in range(n):
                    cw.write(pub_packet(f"r/{slot}", b"p%d" % i))
                await cw.drain()
                published += n
                if slot % 2 == 0:
                    cw.close()  # half vanish abruptly; half linger

            await asyncio.gather(*(churn(s) for s in range(6)))
            await drain_subscriber()
            assert got == published, (
                f"round {rnd}: stable subscriber got {got}/{published}"
            )
        spread = srv._fabric.spread()
        assert sum(spread.values()) >= 1  # stable + lingerers still live
    finally:
        await asyncio.wait_for(srv.close(), 20)


def test_shard_handoff_drill_quick():
    import asyncio

    # a REAL deadline (pytest-timeout is not a dependency): a fabric
    # deadlock fails HERE in 60s with a traceback, not at the CI job cap
    asyncio.run(asyncio.wait_for(_shard_handoff_drill(seed=11), 60))


@pytest.mark.slow
@pytest.mark.parametrize("interval_s", [0.0005, 0.005])
@pytest.mark.parametrize("seed", [7, 23])
def test_shard_handoff_switch_sweep(interval_s, seed):
    import asyncio

    with switch_interval(interval_s):
        asyncio.run(
            asyncio.wait_for(_shard_handoff_drill(seed=seed, rounds=4), 120)
        )


# -- cross-shard handoff schedule fuzzing (ISSUE 19) -------------------------
#
# The lock-boundary fuzzer above shakes the THREADED edge set; this one
# shakes the LOOP-AFFINITY edge set: seeded publish/deliver/takeover
# traffic over a 3-shard fabric (publish lands on shard A, delivery
# marshals to the subscriber's shard B, a same-id reconnect takes the
# session over — usually onto shard C) while the PreemptionInjector
# yields at the graph's lock boundaries AND the session loop witness is
# ESCALATED to raising: any guarded touch a fuzzed schedule drives off
# its owning loop fails the run hard instead of rotting into the next
# hand-found cross-loop bug.


def _handoff_plan(seed: int, slots: int = 3) -> dict:
    """The pure seeded schedule plan — everything a round does derives
    from this (plus the equally seeded PreemptionInjector), which is
    what makes a failing seed replayable."""
    r = random.Random(seed ^ 0x5EAF)
    return {
        "publishes": [r.randint(2, 5) for _ in range(slots)],
        "qos": [r.choice([0, 1]) for _ in range(slots)],
        "takeover_order": r.sample(range(slots), slots),
        "vanish": [r.random() < 0.5 for _ in range(slots)],
    }


class _HandoffRig:
    """One 3-shard broker + one stable cross-shard subscriber, shared
    across a whole sweep so a 200-seed schedule run is dominated by the
    schedules, not by server setup."""

    def __init__(self):
        self.published = 0
        self.got = 0
        self._buf = bytearray()

    async def start(self):
        import asyncio

        from mqtt_tpu.hooks.auth.allow_all import AllowHook
        from mqtt_tpu.listeners import Config as LConfig
        from mqtt_tpu.listeners.tcp import TCP
        from mqtt_tpu.server import Options, Server
        from tests.test_server import read_wire_packet, sub_packet

        self.srv = Server(Options(loop_shards=3, overload_control=False))
        self.srv.add_hook(AllowHook())
        self.srv.add_listener(
            TCP(LConfig(type="tcp", id="hand", address="127.0.0.1:0"))
        )
        await self.srv.serve()
        self.port = int(
            self.srv.listeners.get("hand").address().rsplit(":", 1)[1]
        )
        self.sub_r, sub_w = await self.conn("hand-stable")
        sub_w.write(sub_packet(1, [Subscription(filter="hz/#", qos=0)]))
        await sub_w.drain()
        await asyncio.wait_for(read_wire_packet(self.sub_r, 4), 10)
        return self

    async def conn(self, cid):
        import asyncio

        from tests.test_server import connect_packet, read_wire_packet

        cr, cw = await asyncio.open_connection("127.0.0.1", self.port)
        cw.write(connect_packet(cid, 4))
        await cw.drain()
        ack = await asyncio.wait_for(read_wire_packet(cr, 4), 10)
        assert ack.fixed_header.type == 2  # CONNACK
        return cr, cw

    async def drain(self, deadline_s: float = 15.0):
        """Count PUBLISH frames on the stable subscriber until the
        published total is accounted for (QoS0 over loopback: exact)."""
        import asyncio

        from mqtt_tpu.stress import _scan_frames

        deadline = time.monotonic() + deadline_s
        while self.got < self.published and time.monotonic() < deadline:
            try:
                data = await asyncio.wait_for(self.sub_r.read(65536), 0.5)
            except asyncio.TimeoutError:
                continue
            if not data:
                break
            self._buf.extend(data)
            frames, consumed = _scan_frames(self._buf)
            for first, _bs, _be in frames:
                if (first >> 4) == 3:  # PUBLISH
                    self.got += 1
            del self._buf[:consumed]
        assert self.got == self.published, (
            f"stable subscriber got {self.got}/{self.published}"
        )

    async def round(self, seed: int):
        """One seeded schedule: publish from fresh clients (shard A ->
        subscriber's shard B), drain exact, then take every session
        over by id reuse (-> shard C under least-loaded dispatch) and
        publish once more through the taken-over sessions."""
        import asyncio

        from mqtt_tpu.utils.locked import DEFAULT_PLANE, PreemptionInjector
        from tests.test_server import pub_packet

        plan = _handoff_plan(seed)
        injector = PreemptionInjector(seed, rate=0.3, names=FUZZ_LOCKS)
        DEFAULT_PLANE.arm_fuzz(injector)
        try:
            for slot, n in enumerate(plan["publishes"]):
                _cr, cw = await self.conn(f"hz{seed}x{slot}")
                for i in range(n):
                    if plan["qos"][slot]:
                        cw.write(
                            pub_packet(
                                f"hz/{seed}/{slot}", b"p%d" % i,
                                qos=1, pid=100 + i,
                            )
                        )
                    else:
                        cw.write(pub_packet(f"hz/{seed}/{slot}", b"p%d" % i))
                await cw.drain()
                self.published += n
            await self.drain()
            for slot in plan["takeover_order"]:
                _cr, cw = await self.conn(f"hz{seed}x{slot}")
                cw.write(pub_packet(f"hz/{seed}/t{slot}", b"t"))
                await cw.drain()
                self.published += 1
                if plan["vanish"][slot]:
                    cw.close()  # half vanish abruptly; half linger
            await self.drain()
        finally:
            DEFAULT_PLANE.disarm_fuzz()

    async def stop(self):
        import asyncio

        await asyncio.wait_for(self.srv.close(), 20)


def _run_handoff_sweep(seeds, deadline_s: float) -> None:
    """The sweep harness: one rig, seeded rounds, the session loop
    witness escalated to RAISING for the duration (escalate-only arm;
    the recording default is restored by attribute, mirroring how the
    lock fuzzer treats the session lock witness)."""
    import asyncio

    from mqtt_tpu.utils.loopwitness import DEFAULT_LOOP_PLANE

    witness = DEFAULT_LOOP_PLANE.arm_witness()
    prev_raise = witness.raise_on_violation
    before = len(witness.violations)
    witness.raise_on_violation = True
    faulthandler.dump_traceback_later(int(deadline_s), exit=True)
    try:

        async def sweep():
            rig = await _HandoffRig().start()
            try:
                for seed in seeds:
                    await rig.round(seed)
            finally:
                await rig.stop()

        asyncio.run(asyncio.wait_for(sweep(), deadline_s - 5))
    finally:
        faulthandler.cancel_dump_traceback_later()
        witness.raise_on_violation = prev_raise
    assert witness.violations[before:] == [], witness.violations[before:]


def test_handoff_fuzz_same_seed_is_deterministic():
    """The replayability contract: the WHOLE schedule derives from the
    seed — the op plan here, the preemption decisions in the (already
    covered) per-thread-deterministic injector — so a failing seed
    re-runs as the same schedule."""
    assert _handoff_plan(77) == _handoff_plan(77)
    assert _handoff_plan(77) != _handoff_plan(78)


def test_handoff_fuzz_quick_sweep():
    """Tier-1 leg: 12 seeded publish/deliver/takeover schedules across
    the 3-shard fabric with the loop witness raising — zero affinity
    violations, zero lost deliveries, zero deadlocks."""
    _run_handoff_sweep(range(12), deadline_s=110)


@pytest.mark.slow
def test_handoff_fuzz_200_schedules():
    """The chaos-smoke acceptance sweep (ISSUE 19): >= 200 seeded
    cross-shard handoff schedules under the raising loop witness."""
    _run_handoff_sweep(range(200), deadline_s=540)
