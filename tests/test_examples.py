"""The examples/ scripts must not rot: run the self-contained ones
end-to-end in-process (network-server examples are import-checked by the
syntax sweep; these three exercise real broker behavior)."""

import subprocess
import sys
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(name, timeout=60):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", name)],
        capture_output=True,
        timeout=timeout,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr.decode()[-500:]
    return proc.stdout.decode()


def test_direct_inline_example():
    out = _run("direct_inline.py")
    assert "direct/hello" in out and "direct/retained" in out


def test_persistence_example():
    out = _run("persistence_store.py")
    assert "still here" in out


def test_hooks_custom_example():
    out = _run("hooks_custom.py")
    assert "[modified] hello" in out
    assert "forbidden" not in out.split("seen:")[-1]  # veto worked


def test_tls_example():
    out = _run("tls_broker.py")
    assert "delivered over verified TLS" in out


def test_websocket_example():
    out = _run("websocket_broker.py")
    assert "delivered over websocket" in out


def test_paho_testing_example():
    out = _run("paho_testing.py")
    assert "denied filter obscured to unspecified error: 0x80" in out
    assert "allowed round trip" in out
