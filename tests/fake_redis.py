"""An in-repo fake of the redis client verbs the storage hook uses — the
miniredis analog (reference hooks/storage/redis/redis_test.go runs the real
go-redis client against an embedded miniredis server; this environment has
neither the redis library nor a server, so the fake sits one layer up, at
the client API: set/get/delete/scan_iter/ping/close)."""

import fnmatch
import threading


class FakeRedis:
    """Dict-backed, thread-safe, bytes-valued."""

    def __init__(self, server: dict | None = None):
        # share `server` between instances to model one redis process
        # surviving broker restarts
        self._data = server if server is not None else {}
        self._lock = threading.Lock()
        self.closed = False
        self.pings = 0

    def ping(self):
        self.pings += 1
        return True

    def set(self, key, value):
        if isinstance(key, str):
            key = key.encode()
        if isinstance(value, str):
            value = value.encode()
        with self._lock:
            self._data[key] = bytes(value)

    def get(self, key):
        if isinstance(key, str):
            key = key.encode()
        with self._lock:
            return self._data.get(key)

    def delete(self, *keys):
        n = 0
        with self._lock:
            for key in keys:
                if isinstance(key, str):
                    key = key.encode()
                if key in self._data:
                    del self._data[key]
                    n += 1
        return n

    def scan_iter(self, match="*", count=None):
        if isinstance(match, bytes):
            match = match.decode()
        with self._lock:
            keys = list(self._data)
        for key in keys:
            if fnmatch.fnmatchcase(key.decode(), match):
                yield key

    def close(self):
        self.closed = True
