"""The golden packet catalogue: raw wire bytes <-> expected Packet structs.

Modeled on the reference's conformance table (packets/tpackets.go, ~300
cases of RawBytes/Packet/FailFirst/Expect per packet type): every case pins
exact wire bytes for decode and/or encode, including malformed and
spec-violation variants for both v3.1.1 and v5. ``test_packets.py`` runs
each case in both directions plus encode(decode(bytes)) == bytes.
"""

from dataclasses import dataclass

from mqtt_tpu.packets import (
    AUTH,
    CONNACK,
    CONNECT,
    DISCONNECT,
    PINGREQ,
    PINGRESP,
    PUBACK,
    PUBCOMP,
    PUBLISH,
    PUBREC,
    PUBREL,
    SUBACK,
    SUBSCRIBE,
    UNSUBACK,
    UNSUBSCRIBE,
    Code,
    ConnectParams,
    FixedHeader,
    Packet,
    Properties,
    Subscription,
    UserProperty,
    codes,
)
from mqtt_tpu.packets import ERR_NO_VALID_PACKET_AVAILABLE


@dataclass
class Case:
    desc: str
    raw: bytes
    packet: Packet | None = None
    version: int = 4
    decode_err: Code | None = None  # expected decode failure
    fail_first: Code | None = None  # expected fixed-header decode failure
    group: str = ""  # "decode", "encode", "validate", or "" for both
    # expected <type>_validate() result (decode must succeed first); cases
    # with raw=b"" validate the given packet struct directly — the analog
    # of the reference's Packet-only TPacketCases (tpackets.go Invalid*)
    validate_err: Code | None = None
    validate_arg: int = 0  # publish_validate's topic_alias_maximum


def fhdr(type_, qos=0, dup=False, retain=False, remaining=0):
    return FixedHeader(type=type_, qos=qos, dup=dup, retain=retain, remaining=remaining)


def hx(s: str) -> bytes:
    return bytes.fromhex(s.replace(" ", ""))


CASES: list[Case] = [
    # ---- CONNECT ---------------------------------------------------------
    Case(
        "connect v4 basic",
        hx("1010 0004 4d515454 04 02 003c 0004 7a656e33"),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=16),
            protocol_version=4,
            connect=ConnectParams(
                protocol_name=b"MQTT", clean=True, keepalive=60, client_identifier="zen3"
            ),
        ),
    ),
    Case(
        "connect v5 with session expiry",
        hx("1016 0004 4d515454 05 02 003c 05 11 00000078 0004 7a656e33"),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=22),
            protocol_version=5,
            properties=Properties(session_expiry_interval=120, session_expiry_interval_flag=True),
            connect=ConnectParams(
                protocol_name=b"MQTT", clean=True, keepalive=60, client_identifier="zen3"
            ),
        ),
    ),
    Case(
        "connect v4 with will",
        hx("101f 0004 4d515454 04 0e 003c 0004 7a656e33 0003 6c7774 0008 6e6f74616761696e"),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=31),
            protocol_version=4,
            connect=ConnectParams(
                protocol_name=b"MQTT",
                clean=True,
                keepalive=60,
                client_identifier="zen3",
                will_flag=True,
                will_qos=1,
                will_topic="lwt",
                will_payload=b"notagain",
            ),
        ),
    ),
    Case(
        "connect v3 MQIsdp",
        hx("1011 0006 4d5149736470 03 02 001e 0003 7a656e"),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=17),
            protocol_version=3,
            connect=ConnectParams(
                protocol_name=b"MQIsdp", clean=True, keepalive=30, client_identifier="zen"
            ),
        ),
        version=3,
    ),
    Case(
        "connect v4 username password",
        hx("101a 0004 4d515454 04 c2 003c 0004 7a656e33 0003 7a656e 0003 746561"),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=26),
            protocol_version=4,
            connect=ConnectParams(
                protocol_name=b"MQTT",
                clean=True,
                keepalive=60,
                client_identifier="zen3",
                username_flag=True,
                password_flag=True,
                username=b"zen",
                password=b"tea",
            ),
        ),
    ),
    Case(
        "connect v4 dirty session keepalive zero",
        hx("1010 0004 4d515454 04 00 0000 0004 7a656e33"),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=16),
            protocol_version=4,
            connect=ConnectParams(
                protocol_name=b"MQTT", clean=False, keepalive=0, client_identifier="zen3"
            ),
        ),
    ),
    Case(
        "connect v4 empty client id",
        hx("100c 0004 4d515454 04 02 003c 0000"),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=12),
            protocol_version=4,
            connect=ConnectParams(
                protocol_name=b"MQTT", clean=True, keepalive=60, client_identifier=""
            ),
        ),
    ),
    Case(
        "connect v5 empty properties",
        hx("1011 0004 4d515454 05 02 003c 00 0004 7a656e33"),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=17),
            protocol_version=5,
            connect=ConnectParams(
                protocol_name=b"MQTT", clean=True, keepalive=60, client_identifier="zen3"
            ),
        ),
    ),
    Case(
        "connect v5 rich properties",
        hx(
            "102c 0004 4d515454 05 02 003c 1b 11 0000001e 17 01 19 01 21 0014"
            " 22 000a 26 0001 6b 0001 76 27 000001f4 0004 7a656e33"
        ),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=44),
            protocol_version=5,
            properties=Properties(
                session_expiry_interval=30,
                session_expiry_interval_flag=True,
                request_problem_info=1,
                request_problem_info_flag=True,
                request_response_info=1,
                receive_maximum=20,
                topic_alias_maximum=10,
                user=[UserProperty("k", "v")],
                maximum_packet_size=500,
            ),
            connect=ConnectParams(
                protocol_name=b"MQTT", clean=True, keepalive=60, client_identifier="zen3"
            ),
        ),
    ),
    Case(
        "connect v5 will properties",
        hx(
            "102e 0004 4d515454 05 2e 003c 00 0004 7a656e33 13 01 01"
            " 02 00000078 03 0004 74657874 18 0000003c 0003 6c7774 0002 6869"
        ),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=46),
            protocol_version=5,
            connect=ConnectParams(
                protocol_name=b"MQTT",
                clean=True,
                keepalive=60,
                client_identifier="zen3",
                will_flag=True,
                will_qos=1,
                will_retain=True,
                will_topic="lwt",
                will_payload=b"hi",
                will_properties=Properties(
                    payload_format=1,
                    payload_format_flag=True,
                    message_expiry_interval=120,
                    content_type="text",
                    will_delay_interval=60,
                ),
            ),
        ),
    ),
    Case(
        "connect v5 auth method and data",
        hx("101e 0004 4d515454 05 02 003c 0d 15 0005 504c41494e 16 0002 abcd 0004 7a656e33"),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=30),
            protocol_version=5,
            properties=Properties(
                authentication_method="PLAIN", authentication_data=b"\xab\xcd"
            ),
            connect=ConnectParams(
                protocol_name=b"MQTT", clean=True, keepalive=60, client_identifier="zen3"
            ),
        ),
    ),
    Case(
        "connect truncated keepalive",
        hx("1009 0004 4d515454 04 02 00"),
        decode_err=codes.ERR_MALFORMED_KEEPALIVE,
        group="decode",
    ),
    Case(
        "connect body shorter than declared remaining",
        hx("100c 0004 4d515454 04 02 00"),
        decode_err=codes.ERR_MALFORMED_PACKET,
        group="decode",
    ),
    Case(
        "connect username flag but no username",
        hx("1010 0004 4d515454 04 82 003c 0004 7a656e33"),
        decode_err=codes.ERR_PROTOCOL_VIOLATION_FLAG_NO_USERNAME,
        group="decode",
    ),
    Case(
        "connect will flag but truncated will topic",
        hx("1010 0004 4d515454 04 06 003c 0004 7a656e33"),
        decode_err=codes.ERR_MALFORMED_WILL_TOPIC,
        group="decode",
    ),
    Case(
        "connect truncated protocol name",
        hx("1004 0004 4d51"),
        decode_err=codes.ERR_MALFORMED_PROTOCOL_NAME,
        group="decode",
    ),
    Case(
        "connect missing flags",
        hx("1007 0004 4d515454 04"),
        decode_err=codes.ERR_MALFORMED_FLAGS,
        group="decode",
    ),
    Case(
        "connect password flag but truncated password",
        hx("1010 0004 4d515454 04 42 003c 0004 7a656e33"),
        decode_err=codes.ERR_MALFORMED_PASSWORD,
        group="decode",
    ),
    Case(
        "connect v5 property invalid for connect",
        hx("1014 0004 4d515454 05 02 003c 03 23 0005 0004 7a656e33"),
        decode_err=codes.ERR_MALFORMED_PROPERTIES,
        group="decode",
    ),
    # ---- CONNACK ---------------------------------------------------------
    Case(
        "connack v4 accepted",
        hx("20020000"),
        Packet(fixed_header=fhdr(CONNACK, remaining=2), protocol_version=4),
    ),
    Case(
        "connack v4 session present",
        hx("20020100"),
        Packet(fixed_header=fhdr(CONNACK, remaining=2), protocol_version=4, session_present=True),
    ),
    Case(
        "connack v4 unacceptable protocol version",
        hx("20020001"),
        Packet(fixed_header=fhdr(CONNACK, remaining=2), protocol_version=4, reason_code=1),
    ),
    Case(
        "connack v5 empty properties",
        hx("2003000000"),
        Packet(fixed_header=fhdr(CONNACK, remaining=3), protocol_version=5),
        version=5,
    ),
    Case(
        "connack v5 bad username or password",
        hx("2003008600"),
        Packet(
            fixed_header=fhdr(CONNACK, remaining=3),
            protocol_version=5,
            reason_code=0x86,
        ),
        version=5,
    ),
    Case(
        "connack v5 server properties",
        hx(
            "2027 00 00 24 11 00000078 12 0004 7a656e33 13 000a 21 0005 22 0003"
            " 24 01 25 01 27 00000400 28 01 29 01 2a 01"
        ),
        Packet(
            fixed_header=fhdr(CONNACK, remaining=39),
            protocol_version=5,
            properties=Properties(
                session_expiry_interval=120,
                session_expiry_interval_flag=True,
                assigned_client_id="zen3",
                server_keep_alive=10,
                server_keep_alive_flag=True,
                receive_maximum=5,
                topic_alias_maximum=3,
                maximum_qos=1,
                maximum_qos_flag=True,
                retain_available=1,
                retain_available_flag=True,
                maximum_packet_size=1024,
                wildcard_sub_available=1,
                wildcard_sub_available_flag=True,
                sub_id_available=1,
                sub_id_available_flag=True,
                shared_sub_available=1,
                shared_sub_available_flag=True,
            ),
        ),
        version=5,
    ),
    Case(
        "connack v5 reason string",
        hx("2009 00 80 06 1f 0003 626164"),
        Packet(
            fixed_header=fhdr(CONNACK, remaining=9),
            protocol_version=5,
            reason_code=0x80,
            properties=Properties(reason_string="bad"),
        ),
        version=5,
    ),
    Case(
        "connack empty body",
        hx("2000"),
        decode_err=codes.ERR_MALFORMED_SESSION_PRESENT,
        group="decode",
    ),
    Case(
        "connack missing reason code",
        hx("200100"),
        decode_err=codes.ERR_MALFORMED_REASON_CODE,
        group="decode",
    ),
    Case(
        "connack v5 truncated properties",
        hx("2003 00 00 05"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PROPERTIES,
        group="decode",
    ),
    # ---- PUBLISH ---------------------------------------------------------
    Case(
        "publish qos0 v4",
        hx("300c 0005 612f622f63 68656c6c6f"),
        Packet(
            fixed_header=fhdr(PUBLISH, remaining=12),
            protocol_version=4,
            topic_name="a/b/c",
            payload=b"hello",
        ),
    ),
    Case(
        "publish qos1 v4",
        hx("320e 0005 612f622f63 0007 68656c6c6f"),
        Packet(
            fixed_header=fhdr(PUBLISH, qos=1, remaining=14),
            protocol_version=4,
            topic_name="a/b/c",
            packet_id=7,
            payload=b"hello",
        ),
    ),
    Case(
        "publish qos2 retain dup v4",
        hx("3d0e 0005 612f622f63 0007 68656c6c6f"),
        Packet(
            fixed_header=fhdr(PUBLISH, qos=2, dup=True, retain=True, remaining=14),
            protocol_version=4,
            topic_name="a/b/c",
            packet_id=7,
            payload=b"hello",
        ),
    ),
    Case(
        "publish empty payload",
        hx("3007 0005 612f622f63"),
        Packet(
            fixed_header=fhdr(PUBLISH, remaining=7),
            protocol_version=4,
            topic_name="a/b/c",
        ),
    ),
    Case(
        "publish two byte remaining length",
        hx("30 cf01 0005 612f622f63") + b"a" * 200,
        Packet(
            fixed_header=fhdr(PUBLISH, remaining=207),
            protocol_version=4,
            topic_name="a/b/c",
            payload=b"a" * 200,
        ),
    ),
    Case(
        "publish v5 empty properties",
        hx("300d 0005 612f622f63 00 68656c6c6f"),
        Packet(
            fixed_header=fhdr(PUBLISH, remaining=13),
            protocol_version=5,
            topic_name="a/b/c",
            payload=b"hello",
        ),
        version=5,
    ),
    Case(
        "publish v5 qos2",
        hx("340d 0003 612f62 0009 00 68656c6c6f"),
        Packet(
            fixed_header=fhdr(PUBLISH, qos=2, remaining=13),
            protocol_version=5,
            topic_name="a/b",
            packet_id=9,
            payload=b"hello",
        ),
        version=5,
    ),
    Case(
        "publish v5 user property",
        hx("3016 0005 612f622f63 09 26 00026869 00027468 68656c6c6f"),
        Packet(
            fixed_header=fhdr(PUBLISH, remaining=22),
            protocol_version=5,
            topic_name="a/b/c",
            properties=Properties(user=[UserProperty("hi", "th")]),
            payload=b"hello",
        ),
        version=5,
    ),
    Case(
        "publish v5 topic alias only",
        hx("300b 0000 03 23 0005 68656c6c6f"),
        Packet(
            fixed_header=fhdr(PUBLISH, remaining=11),
            protocol_version=5,
            topic_name="",
            properties=Properties(topic_alias=5, topic_alias_flag=True),
            payload=b"hello",
        ),
        version=5,
    ),
    Case(
        "publish v5 expiry format content type",
        hx("3016 0003 612f62 0e 01 01 02 0000000a 03 0004 74657874 6869"),
        Packet(
            fixed_header=fhdr(PUBLISH, remaining=22),
            protocol_version=5,
            topic_name="a/b",
            properties=Properties(
                payload_format=1,
                payload_format_flag=True,
                message_expiry_interval=10,
                content_type="text",
            ),
            payload=b"hi",
        ),
        version=5,
    ),
    Case(
        # encode gates response-info props on Mods.allow_response_info, so
        # this vector is decode-only (reference packets.go Mods semantics)
        "publish v5 response topic correlation",
        hx("3013 0003 612f62 0b 08 0003 722f74 09 0002 abcd 6869"),
        Packet(
            fixed_header=fhdr(PUBLISH, remaining=19),
            protocol_version=5,
            topic_name="a/b",
            properties=Properties(response_topic="r/t", correlation_data=b"\xab\xcd"),
            payload=b"hi",
        ),
        version=5,
        group="decode",
    ),
    Case(
        "publish invalid utf8 topic",
        hx("3009 0005 612f62ffc3 6869"),
        decode_err=codes.ERR_MALFORMED_TOPIC,
        group="decode",
    ),
    Case(
        "publish qos1 missing packet id",
        hx("3205 0003 612f62"),
        decode_err=codes.ERR_MALFORMED_PACKET_ID,
        group="decode",
    ),
    Case(
        "publish v5 truncated properties",
        hx("3008 0003 612f62 05 2300"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PROPERTIES,
        group="decode",
    ),
    Case(
        "publish qos3 rejected at header",
        hx("3600"),
        fail_first=codes.ERR_PROTOCOL_VIOLATION_QOS_OUT_OF_RANGE,
        group="decode",
    ),
    Case(
        "publish dup without qos rejected",
        hx("3800"),
        fail_first=codes.ERR_PROTOCOL_VIOLATION_DUP_NO_QOS,
        group="decode",
    ),
    # ---- PUBACK / PUBREC / PUBREL / PUBCOMP ------------------------------
    Case(
        "puback v4",
        hx("40020007"),
        Packet(fixed_header=fhdr(PUBACK, remaining=2), protocol_version=4, packet_id=7),
    ),
    Case(
        "puback v5 reason code",
        hx("4003000710"),
        Packet(
            fixed_header=fhdr(PUBACK, remaining=3),
            protocol_version=5,
            packet_id=7,
            reason_code=0x10,
        ),
        version=5,
        group="decode",  # encode of rc<0x80 with no props omits reason byte
    ),
    Case(
        "puback v5 error reason encodes reason byte",
        hx("4003000793"),
        Packet(
            fixed_header=fhdr(PUBACK, remaining=3),
            protocol_version=5,
            packet_id=7,
            reason_code=0x93,
        ),
        version=5,
    ),
    Case(
        "puback v5 reason string",
        hx("400a 0007 10 06 1f 0003 626164"),
        Packet(
            fixed_header=fhdr(PUBACK, remaining=10),
            protocol_version=5,
            packet_id=7,
            reason_code=0x10,
            properties=Properties(reason_string="bad"),
        ),
        version=5,
    ),
    Case(
        "puback truncated packet id",
        hx("400100"),
        decode_err=codes.ERR_MALFORMED_PACKET_ID,
        group="decode",
    ),
    Case(
        "pubrec v4",
        hx("50020007"),
        Packet(fixed_header=fhdr(PUBREC, remaining=2), protocol_version=4, packet_id=7),
    ),
    Case(
        "pubrec v5 quota exceeded",
        hx("5003000797"),
        Packet(
            fixed_header=fhdr(PUBREC, remaining=3),
            protocol_version=5,
            packet_id=7,
            reason_code=0x97,
        ),
        version=5,
    ),
    Case(
        "pubrel v4",
        hx("62020007"),
        Packet(fixed_header=fhdr(PUBREL, qos=1, remaining=2), protocol_version=4, packet_id=7),
    ),
    Case(
        "pubrel v5 success omits reason byte",
        hx("62020007"),
        Packet(fixed_header=fhdr(PUBREL, qos=1, remaining=2), protocol_version=5, packet_id=7),
        version=5,
    ),
    Case(
        "pubrel v5 packet id not found",
        hx("6203000792"),
        Packet(
            fixed_header=fhdr(PUBREL, qos=1, remaining=3),
            protocol_version=5,
            packet_id=7,
            reason_code=0x92,
        ),
        version=5,
    ),
    Case(
        "pubrel bad flags",
        hx("60020007"),
        fail_first=codes.ERR_MALFORMED_FLAGS,
        group="decode",
    ),
    Case(
        "pubcomp v4",
        hx("70020007"),
        Packet(fixed_header=fhdr(PUBCOMP, remaining=2), protocol_version=4, packet_id=7),
    ),
    Case(
        "pubcomp v5 packet id not found",
        hx("7003000792"),
        Packet(
            fixed_header=fhdr(PUBCOMP, remaining=3),
            protocol_version=5,
            packet_id=7,
            reason_code=0x92,
        ),
        version=5,
    ),
    # ---- SUBSCRIBE / SUBACK ----------------------------------------------
    Case(
        "subscribe v4",
        hx("820a 0015 0005 612f622f63 01"),
        Packet(
            fixed_header=fhdr(SUBSCRIBE, qos=1, remaining=10),
            protocol_version=4,
            packet_id=21,
            filters=[Subscription(filter="a/b/c", qos=1)],
        ),
    ),
    Case(
        "subscribe v4 multiple filters",
        hx("8214 0015 0003 612f62 00 0003 642f23 01 0003 632f2b 02"),
        Packet(
            fixed_header=fhdr(SUBSCRIBE, qos=1, remaining=20),
            protocol_version=4,
            packet_id=21,
            filters=[
                Subscription(filter="a/b", qos=0),
                Subscription(filter="d/#", qos=1),
                Subscription(filter="c/+", qos=2),
            ],
        ),
    ),
    Case(
        "subscribe v5 options",
        hx("820b 0015 00 0005 612f622f63 2e"),
        Packet(
            fixed_header=fhdr(SUBSCRIBE, qos=1, remaining=11),
            protocol_version=5,
            packet_id=21,
            filters=[
                Subscription(
                    filter="a/b/c",
                    qos=2,
                    no_local=True,
                    retain_as_published=True,
                    retain_handling=2,
                )
            ],
        ),
        version=5,
    ),
    Case(
        "subscribe v5 subscription identifier",
        hx("820d 0015 02 0b 05 0005 612f622f63 01"),
        Packet(
            fixed_header=fhdr(SUBSCRIBE, qos=1, remaining=13),
            protocol_version=5,
            packet_id=21,
            properties=Properties(subscription_identifier=[5]),
            filters=[Subscription(filter="a/b/c", qos=1, identifier=5)],
        ),
        version=5,
    ),
    Case(
        "subscribe v5 shared subscription",
        hx("8214 0015 00 000e 2473686172652f7465612f612f62 01"),
        Packet(
            fixed_header=fhdr(SUBSCRIBE, qos=1, remaining=20),
            protocol_version=5,
            packet_id=21,
            filters=[Subscription(filter="$share/tea/a/b", qos=1)],
        ),
        version=5,
    ),
    Case(
        "subscribe qos out of range",
        hx("820a 0015 0005 612f622f63 03"),
        decode_err=codes.ERR_PROTOCOL_VIOLATION_QOS_OUT_OF_RANGE,
        group="decode",
    ),
    Case(
        "subscribe v4 missing qos",
        hx("8209 0015 0005 612f622f63"),
        decode_err=codes.ERR_MALFORMED_QOS,
        group="decode",
    ),
    Case(
        "subscribe truncated filter",
        hx("8207 0015 0005 612f62"),
        decode_err=codes.ERR_MALFORMED_TOPIC,
        group="decode",
    ),
    Case(
        "subscribe invalid utf8 filter",
        hx("8208 0015 0003 61ff62 00"),
        decode_err=codes.ERR_MALFORMED_TOPIC,
        group="decode",
    ),
    Case(
        "subscribe bad flags",
        hx("800a 0015 0005 612f622f63 01"),
        fail_first=codes.ERR_MALFORMED_FLAGS,
        group="decode",
    ),
    Case(
        "suback v4",
        hx("90030015 01"),
        Packet(
            fixed_header=fhdr(SUBACK, remaining=3),
            protocol_version=4,
            packet_id=21,
            reason_codes=b"\x01",
        ),
    ),
    Case(
        "suback v4 multiple grants",
        hx("9006 0015 00 01 02 80"),
        Packet(
            fixed_header=fhdr(SUBACK, remaining=6),
            protocol_version=4,
            packet_id=21,
            reason_codes=b"\x00\x01\x02\x80",
        ),
    ),
    Case(
        "suback v5",
        hx("9004001500 80"),
        Packet(
            fixed_header=fhdr(SUBACK, remaining=4),
            protocol_version=5,
            packet_id=21,
            reason_codes=b"\x80",
        ),
        version=5,
    ),
    Case(
        "suback v5 reason string",
        hx("900a 0015 06 1f 0003 626164 01"),
        Packet(
            fixed_header=fhdr(SUBACK, remaining=10),
            protocol_version=5,
            packet_id=21,
            properties=Properties(reason_string="bad"),
            reason_codes=b"\x01",
        ),
        version=5,
    ),
    Case(
        "suback bad flags",
        hx("9100"),
        fail_first=codes.ERR_MALFORMED_FLAGS,
        group="decode",
    ),
    # ---- UNSUBSCRIBE / UNSUBACK ------------------------------------------
    Case(
        "unsubscribe v4",
        hx("a209 0015 0005 612f622f63"),
        Packet(
            fixed_header=fhdr(UNSUBSCRIBE, qos=1, remaining=9),
            protocol_version=4,
            packet_id=21,
            filters=[Subscription(filter="a/b/c")],
        ),
    ),
    Case(
        "unsubscribe v5 two filters",
        hx("a212 0015 00 0005 612f622f63 0006 642f652f6623"),
        Packet(
            fixed_header=fhdr(UNSUBSCRIBE, qos=1, remaining=18),
            protocol_version=5,
            packet_id=21,
            filters=[Subscription(filter="a/b/c"), Subscription(filter="d/e/f#")],
        ),
        version=5,
    ),
    Case(
        "unsubscribe truncated filter",
        hx("a206 0015 0005 6162"),
        decode_err=codes.ERR_MALFORMED_TOPIC,
        group="decode",
    ),
    Case(
        "unsubscribe invalid utf8 filter",
        hx("a207 0015 0003 61ff62"),
        decode_err=codes.ERR_MALFORMED_TOPIC,
        group="decode",
    ),
    Case(
        "unsubscribe bad flags",
        hx("a000"),
        fail_first=codes.ERR_MALFORMED_FLAGS,
        group="decode",
    ),
    Case(
        "unsuback v4",
        hx("b0020015"),
        Packet(fixed_header=fhdr(UNSUBACK, remaining=2), protocol_version=4, packet_id=21),
    ),
    Case(
        "unsuback v5",
        hx("b005001500 0011"),
        Packet(
            fixed_header=fhdr(UNSUBACK, remaining=5),
            protocol_version=5,
            packet_id=21,
            reason_codes=b"\x00\x11",
        ),
        version=5,
    ),
    Case(
        "unsuback v5 reason string",
        hx("b00b 0015 06 1f 0003 626164 0011"),
        Packet(
            fixed_header=fhdr(UNSUBACK, remaining=11),
            protocol_version=5,
            packet_id=21,
            properties=Properties(reason_string="bad"),
            reason_codes=b"\x00\x11",
        ),
        version=5,
    ),
    Case(
        "unsuback truncated packet id",
        hx("b00100"),
        decode_err=codes.ERR_MALFORMED_PACKET_ID,
        group="decode",
    ),
    # ---- PING / DISCONNECT / AUTH ----------------------------------------
    Case("pingreq", hx("c000"), Packet(fixed_header=fhdr(PINGREQ), protocol_version=4)),
    Case("pingresp", hx("d000"), Packet(fixed_header=fhdr(PINGRESP), protocol_version=4)),
    Case(
        "pingreq invalid flags",
        hx("c100"),
        fail_first=codes.ERR_MALFORMED_FLAGS,
        group="decode",
    ),
    Case(
        "pingresp invalid flags",
        hx("d100"),
        fail_first=codes.ERR_MALFORMED_FLAGS,
        group="decode",
    ),
    Case(
        "disconnect v4",
        hx("e000"),
        Packet(fixed_header=fhdr(DISCONNECT), protocol_version=4),
    ),
    Case(
        "disconnect v5 server shutting down",
        hx("e0028b00"),
        Packet(
            fixed_header=fhdr(DISCONNECT, remaining=2),
            protocol_version=5,
            reason_code=0x8B,
        ),
        version=5,
    ),
    Case(
        "disconnect v5 session expiry",
        hx("e007 04 05 11 0000003c"),
        Packet(
            fixed_header=fhdr(DISCONNECT, remaining=7),
            protocol_version=5,
            reason_code=0x04,
            properties=Properties(session_expiry_interval=60, session_expiry_interval_flag=True),
        ),
        version=5,
    ),
    Case(
        "disconnect v5 server reference",
        hx("e009 9c 07 1c 0004 656c7365"),
        Packet(
            fixed_header=fhdr(DISCONNECT, remaining=9),
            protocol_version=5,
            reason_code=0x9C,
            properties=Properties(server_reference="else"),
        ),
        version=5,
    ),
    Case(
        "disconnect v5 property invalid for disconnect",
        hx("e005 00 03 23 0005"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PROPERTIES,
        group="decode",
    ),
    Case(
        "disconnect invalid flags",
        hx("e100"),
        fail_first=codes.ERR_MALFORMED_FLAGS,
        group="decode",
    ),
    Case(
        "auth v5 continue authentication",
        hx("f0021800"),
        Packet(
            fixed_header=fhdr(AUTH, remaining=2),
            protocol_version=5,
            reason_code=0x18,
        ),
        version=5,
    ),
    Case(
        "auth v5 reauthenticate",
        hx("f0021900"),
        Packet(
            fixed_header=fhdr(AUTH, remaining=2),
            protocol_version=5,
            reason_code=0x19,
        ),
        version=5,
    ),
    Case(
        "auth v5 method and data",
        hx("f00f 18 0d 15 0005 504c41494e 16 0002 abcd"),
        Packet(
            fixed_header=fhdr(AUTH, remaining=15),
            protocol_version=5,
            reason_code=0x18,
            properties=Properties(
                authentication_method="PLAIN", authentication_data=b"\xab\xcd"
            ),
        ),
        version=5,
    ),
    Case(
        "auth empty body",
        hx("f000"),
        version=5,
        decode_err=codes.ERR_MALFORMED_REASON_CODE,
        group="decode",
    ),
    # ---- CONNECT (extended) ----------------------------------------------
    Case(
        "connect v4 will qos1 retain",
        hx("101f 0004 4d515454 04 2e 003c 0004 7a656e33 0003 6c7774 0008 6e6f74616761696e"),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=31),
            protocol_version=4,
            connect=ConnectParams(
                protocol_name=b"MQTT",
                clean=True,
                keepalive=60,
                client_identifier="zen3",
                will_flag=True,
                will_qos=1,
                will_retain=True,
                will_topic="lwt",
                will_payload=b"notagain",
            ),
        ),
    ),
    Case(
        "connect v5 zero byte username with password",
        hx("1018 0004 4d515454 05 c2 003c 00 0004 7a656e33 0000 0003 746561"),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=24),
            protocol_version=5,
            connect=ConnectParams(
                protocol_name=b"MQTT",
                clean=True,
                keepalive=60,
                client_identifier="zen3",
                username_flag=True,
                username=b"",
                password_flag=True,
                password=b"tea",
            ),
        ),
        version=5,
    ),
    Case(
        "connect will flag but truncated will payload",
        hx("1015 0004 4d515454 04 06 003c 0004 7a656e33 0003 6c7774"),
        decode_err=codes.ERR_MALFORMED_WILL_PAYLOAD,
        group="decode",
    ),
    Case(
        "connect v5 truncated will properties",
        hx("1012 0004 4d515454 05 06 003c 00 0004 7a656e33 05"),
        version=5,
        decode_err=codes.ERR_MALFORMED_WILL_PROPERTIES,
        group="decode",
    ),
    Case(
        "connect client id embedded NUL [MQTT-1.5.4-2]",
        hx("1010 0004 4d515454 04 02 003c 0004 7a65006e"),
        decode_err=codes.ERR_CLIENT_IDENTIFIER_NOT_VALID,
        group="decode",
    ),
    Case(
        "connect client id UTF-16 surrogate D800",
        hx("100f 0004 4d515454 04 02 003c 0003 eda080"),
        decode_err=codes.ERR_CLIENT_IDENTIFIER_NOT_VALID,
        group="decode",
    ),
    Case(
        "connect client id UTF-16 surrogate DFFF",
        hx("100f 0004 4d515454 04 02 003c 0003 edbfbf"),
        decode_err=codes.ERR_CLIENT_IDENTIFIER_NOT_VALID,
        group="decode",
    ),
    # ---- CONNACK (extended) ----------------------------------------------
    Case(
        "connack v4 identifier rejected",
        hx("20020002"),
        Packet(fixed_header=fhdr(CONNACK, remaining=2), protocol_version=4, reason_code=2),
    ),
    Case(
        "connack v4 server unavailable",
        hx("20020003"),
        Packet(fixed_header=fhdr(CONNACK, remaining=2), protocol_version=4, reason_code=3),
    ),
    Case(
        "connack v4 bad username or password",
        hx("20020004"),
        Packet(fixed_header=fhdr(CONNACK, remaining=2), protocol_version=4, reason_code=4),
    ),
    Case(
        "connack v4 not authorized",
        hx("20020005"),
        Packet(fixed_header=fhdr(CONNACK, remaining=2), protocol_version=4, reason_code=5),
    ),
    Case(
        "connack v5 session present with success",
        hx("2003010000"),
        Packet(
            fixed_header=fhdr(CONNACK, remaining=3),
            protocol_version=5,
            session_present=True,
        ),
        version=5,
    ),
    Case(
        "connack v5 server keepalive",
        hx("2006 0000 03 13 000a"),
        Packet(
            fixed_header=fhdr(CONNACK, remaining=6),
            protocol_version=5,
            properties=Properties(server_keep_alive=10, server_keep_alive_flag=True),
        ),
        version=5,
    ),
    Case(
        "connack v5 assigned client id",
        hx("200a 0000 07 12 0004 7a656e33"),
        Packet(
            fixed_header=fhdr(CONNACK, remaining=10),
            protocol_version=5,
            properties=Properties(assigned_client_id="zen3"),
        ),
        version=5,
    ),
    Case(
        "connack session present masks to low bit",
        hx("20020200"),
        Packet(
            fixed_header=fhdr(CONNACK, remaining=2),
            protocol_version=4,
            session_present=False,
        ),
        group="decode",  # reference decodeByteBool: 1&b, no error (codec.go:81-86)
    ),
    # ---- PUBLISH (extended) ----------------------------------------------
    Case(
        "publish v5 message expiry and topic alias",
        hx("3012 0005 612f622f63 08 02 0000003c 23 0005 6869"),
        Packet(
            fixed_header=fhdr(PUBLISH, remaining=18),
            protocol_version=5,
            topic_name="a/b/c",
            properties=Properties(
                message_expiry_interval=60, topic_alias=5, topic_alias_flag=True
            ),
            payload=b"hi",
        ),
        version=5,
    ),
    Case(
        "publish v5 payload format and content type",
        hx("3013 0005 612f622f63 09 01 01 03 0004 74657874 6869"),
        Packet(
            fixed_header=fhdr(PUBLISH, remaining=19),
            protocol_version=5,
            topic_name="a/b/c",
            properties=Properties(
                payload_format=1, payload_format_flag=True, content_type="text"
            ),
            payload=b"hi",
        ),
        version=5,
    ),
    Case(
        "publish qos2 missing packet id",
        hx("3407 0005 612f622f63"),
        decode_err=codes.ERR_MALFORMED_PACKET_ID,
        group="decode",
    ),
    Case(
        "publish truncated topic",
        hx("3003 0005 61"),
        decode_err=codes.ERR_MALFORMED_TOPIC,
        group="decode",
    ),
    Case(
        "publish remaining exceeds buffer",
        hx("3010 0005 612f622f63"),
        decode_err=codes.ERR_MALFORMED_PACKET,
        group="decode",
    ),
    # ---- PUBACK / PUBREC / PUBREL / PUBCOMP (extended) -------------------
    Case(
        "puback v5 no matching subscribers",
        hx("4004 0007 10 00"),
        Packet(
            fixed_header=fhdr(PUBACK, remaining=4),
            protocol_version=5,
            packet_id=7,
            reason_code=0x10,
        ),
        version=5,
        # sub-0x80 reason with no props re-encodes to the 2-byte short form
        group="decode",
    ),
    Case(
        "puback v5 truncated properties",
        hx("4004 0007 10 05"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PROPERTIES,
        group="decode",
    ),
    Case(
        "pubrec v5 not authorized",
        hx("5003 0007 87"),
        Packet(
            fixed_header=fhdr(PUBREC, remaining=3),
            protocol_version=5,
            packet_id=7,
            reason_code=0x87,
        ),
        version=5,
    ),
    Case(
        "pubcomp v5 reason string",
        hx("700a 0007 92 06 1f 0003 626164"),
        Packet(
            fixed_header=fhdr(PUBCOMP, remaining=10),
            protocol_version=5,
            packet_id=7,
            reason_code=0x92,
            properties=Properties(reason_string="bad"),
        ),
        version=5,
    ),
    Case(
        "pubrel v5 truncated properties",
        hx("6204 0007 92 05"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PROPERTIES,
        group="decode",
    ),
    # ---- SUBSCRIBE / SUBACK (extended) -----------------------------------
    Case(
        "subscribe v5 no local and retain as published",
        hx("8209 0010 00 0003 612f62 0d"),
        Packet(
            fixed_header=fhdr(SUBSCRIBE, qos=1, remaining=9),
            protocol_version=5,
            packet_id=16,
            filters=[
                Subscription(filter="a/b", qos=1, no_local=True, retain_as_published=True)
            ],
        ),
        version=5,
    ),
    Case(
        "subscribe v5 retain handling 1",
        hx("8209 0011 00 0003 612f62 10"),
        Packet(
            fixed_header=fhdr(SUBSCRIBE, qos=1, remaining=9),
            protocol_version=5,
            packet_id=17,
            filters=[Subscription(filter="a/b", retain_handling=1)],
        ),
        version=5,
    ),
    Case(
        "subscribe v5 retain handling 2",
        hx("8209 0012 00 0003 612f62 20"),
        Packet(
            fixed_header=fhdr(SUBSCRIBE, qos=1, remaining=9),
            protocol_version=5,
            packet_id=18,
            filters=[Subscription(filter="a/b", retain_handling=2)],
        ),
        version=5,
    ),
    Case(
        "subscribe v4 three filters",
        hx("820e 0003 0001 61 01 0001 62 02 0001 63 00"),
        Packet(
            fixed_header=fhdr(SUBSCRIBE, qos=1, remaining=14),
            protocol_version=4,
            packet_id=3,
            filters=[
                Subscription(filter="a", qos=1),
                Subscription(filter="b", qos=2),
                Subscription(filter="c", qos=0),
            ],
        ),
    ),
    Case(
        "subscribe v5 truncated properties",
        hx("8203 0010 05"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PROPERTIES,
        group="decode",
    ),
    Case(
        "suback v4 failure grant",
        hx("9003 0005 80"),
        Packet(
            fixed_header=fhdr(SUBACK, remaining=3),
            protocol_version=4,
            packet_id=5,
            reason_codes=b"\x80",
        ),
    ),
    Case(
        "suback v5 mixed grants with failure",
        hx("9006 0010 00 00 01 87"),
        Packet(
            fixed_header=fhdr(SUBACK, remaining=6),
            protocol_version=5,
            packet_id=16,
            reason_codes=b"\x00\x01\x87",
        ),
        version=5,
    ),
    # ---- UNSUBSCRIBE / UNSUBACK (extended) -------------------------------
    Case(
        "unsubscribe v5 user property",
        hx("a20f 0012 07 26 0001 6b 0001 76 0003 612f62"),
        Packet(
            fixed_header=fhdr(UNSUBSCRIBE, qos=1, remaining=15),
            protocol_version=5,
            packet_id=18,
            properties=Properties(user=[UserProperty("k", "v")]),
            filters=[Subscription(filter="a/b")],
        ),
        version=5,
    ),
    Case(
        "unsuback v5 two codes",
        hx("b005 0010 00 00 11"),
        Packet(
            fixed_header=fhdr(UNSUBACK, remaining=5),
            protocol_version=5,
            packet_id=16,
            reason_codes=b"\x00\x11",
        ),
        version=5,
    ),
    # ---- DISCONNECT / AUTH (extended) ------------------------------------
    Case(
        "disconnect v5 session taken over",
        hx("e002 8e 00"),
        Packet(
            fixed_header=fhdr(DISCONNECT, remaining=2),
            protocol_version=5,
            reason_code=0x8E,
        ),
        version=5,
    ),
    Case(
        "disconnect v5 keep alive timeout",
        hx("e002 8d 00"),
        Packet(
            fixed_header=fhdr(DISCONNECT, remaining=2),
            protocol_version=5,
            reason_code=0x8D,
        ),
        version=5,
    ),
    Case(
        "disconnect v5 one byte body ignores reason",
        hx("e001 8e"),
        Packet(
            fixed_header=fhdr(DISCONNECT, remaining=1),
            protocol_version=5,
            reason_code=0,  # remaining must be >1 to carry a reason (packets.go:568)
        ),
        version=5,
        group="decode",
    ),
    Case(
        "auth v5 success empty properties",
        hx("f002 00 00"),
        Packet(fixed_header=fhdr(AUTH, remaining=2), protocol_version=5),
        version=5,
    ),
    # ---- fixed header flags ----------------------------------------------
    Case(
        "connack invalid flags",
        hx("2100"),
        fail_first=codes.ERR_MALFORMED_FLAGS,
        group="decode",
    ),
    Case(
        "puback invalid flags",
        hx("4100"),
        fail_first=codes.ERR_MALFORMED_FLAGS,
        group="decode",
    ),
    Case(
        "pubrec invalid flags",
        hx("5100"),
        fail_first=codes.ERR_MALFORMED_FLAGS,
        group="decode",
    ),
    Case(
        "pubcomp invalid flags",
        hx("7100"),
        fail_first=codes.ERR_MALFORMED_FLAGS,
        group="decode",
    ),
    Case(
        "unsuback invalid flags",
        hx("b100"),
        fail_first=codes.ERR_MALFORMED_FLAGS,
        group="decode",
    ),
    # ---- framing ---------------------------------------------------------
    Case(
        "remaining length varint overflow",
        hx("10ffffffff7f"),
        decode_err=codes.ERR_MALFORMED_VARIABLE_BYTE_INTEGER,
        group="decode",
    ),
    Case(
        "reserved packet type zero",
        hx("0000"),
        decode_err=ERR_NO_VALID_PACKET_AVAILABLE,
        group="decode",
    ),
]

# ---- validate-level conformance (tpackets.go Invalid*/Spec* cases) --------
# Wire-expressible violations decode first, then <type>_validate() must
# return the pinned reason code; raw=b"" cases validate a Packet struct the
# wire cannot express (flag/field combinations the decoder derives away).
CASES += [
    # CONNECT validate
    Case(
        "connect invalid protocol name",
        hx("1010 0004 4d515443 04 02 003c 0004 7a656e33"),  # "MQTC"
        version=4,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_PROTOCOL_NAME,
        group="validate",
    ),
    Case(
        "connect invalid protocol version 2",
        hx("1010 0004 4d515454 02 02 003c 0004 7a656e33"),
        version=4,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_PROTOCOL_VERSION,
        group="validate",
    ),
    Case(
        "connect reserved bit set",
        hx("1010 0004 4d515454 04 03 003c 0004 7a656e33"),
        version=4,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_RESERVED_BIT,
        group="validate",
    ),
    Case(
        # the reference validates only the password side of [MQTT-3.1.2-19]
        # (packets.go ConnectValidate); username flag + empty username is
        # accepted, matching TConnectZeroByteUsername
        "connect password flag with empty password",
        hx("1015 0004 4d515454 04 c2 003c 0004 7a656e33 0001 75 0000"),
        version=4,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_FLAG_NO_PASSWORD,
        group="validate",
    ),
    Case(
        "connect will flag with empty will payload",
        hx("1015 0004 4d515454 04 06 003c 0004 7a656e33 0001 74 0000"),
        version=4,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_WILL_FLAG_NO_PAYLOAD,
        group="validate",
    ),
    Case(
        "connect will qos out of range",
        hx("1017 0004 4d515454 04 1e 003c 0004 7a656e33 0001 74 0002 6f6b"),
        version=4,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_QOS_OUT_OF_RANGE,
        group="validate",
    ),
    Case(
        "connect will retain without will flag",
        hx("1010 0004 4d515454 04 22 003c 0004 7a656e33"),
        version=4,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_WILL_FLAG_SURPLUS_RETAIN,
        group="validate",
    ),
    Case(
        "connect username without flag (struct)",
        b"",
        Packet(
            fixed_header=fhdr(CONNECT),
            protocol_version=4,
            connect=ConnectParams(
                protocol_name=b"MQTT",
                clean=True,
                keepalive=30,
                client_identifier="zen",
                username=b"u",
            ),
        ),
        validate_err=codes.ERR_PROTOCOL_VIOLATION_USERNAME_NO_FLAG,
        group="validate",
    ),
    Case(
        "connect password without flag (struct)",
        b"",
        Packet(
            fixed_header=fhdr(CONNECT),
            protocol_version=4,
            connect=ConnectParams(
                protocol_name=b"MQTT",
                clean=True,
                keepalive=30,
                client_identifier="zen",
                password=b"p",
            ),
        ),
        validate_err=codes.ERR_PROTOCOL_VIOLATION_PASSWORD_NO_FLAG,
        group="validate",
    ),
    Case(
        "connect username too long (struct)",
        b"",
        Packet(
            fixed_header=fhdr(CONNECT),
            protocol_version=4,
            connect=ConnectParams(
                protocol_name=b"MQTT",
                clean=True,
                keepalive=30,
                client_identifier="zen",
                username_flag=True,
                username=b"u" * 65536,
            ),
        ),
        validate_err=codes.ERR_PROTOCOL_VIOLATION_USERNAME_TOO_LONG,
        group="validate",
    ),
    Case(
        "connect password too long (struct)",
        b"",
        Packet(
            fixed_header=fhdr(CONNECT),
            protocol_version=4,
            connect=ConnectParams(
                protocol_name=b"MQTT",
                clean=True,
                keepalive=30,
                client_identifier="zen",
                password_flag=True,
                password=b"p" * 65536,
            ),
        ),
        validate_err=codes.ERR_PROTOCOL_VIOLATION_PASSWORD_TOO_LONG,
        group="validate",
    ),
    Case(
        "connect client id too long (struct)",
        b"",
        Packet(
            fixed_header=fhdr(CONNECT),
            protocol_version=4,
            connect=ConnectParams(
                protocol_name=b"MQTT",
                clean=True,
                keepalive=30,
                client_identifier="c" * 65536,
            ),
        ),
        validate_err=codes.ERR_CLIENT_IDENTIFIER_NOT_VALID,
        group="validate",
    ),
    # PUBLISH validate
    Case(
        "publish wildcard plus in topic",
        hx("3009 0005 612f2b2f62 6f6b"),
        version=4,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_SURPLUS_WILDCARD,
        group="validate",
    ),
    Case(
        "publish wildcard hash in topic",
        hx("3007 0003 612f23 6f6b"),
        version=4,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_SURPLUS_WILDCARD,
        group="validate",
    ),
    Case(
        "publish v5 subscription identifier from client",
        hx("3008 0001 74 02 0b05 6f6b"),
        version=5,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_SURPLUS_SUB_ID,
        group="validate",
    ),
    Case(
        "publish v5 empty topic without alias",
        hx("3007 0000 00 6f6b6179"),
        version=5,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_NO_TOPIC,
        group="validate",
    ),
    Case(
        "publish v5 topic alias zero",
        hx("3009 0001 74 03 230000 6f6b"),
        version=5,
        validate_err=codes.ERR_TOPIC_ALIAS_INVALID,
        validate_arg=8,
        group="validate",
    ),
    Case(
        "publish v5 topic alias above maximum",
        hx("3009 0001 74 03 230007 6f6b"),
        version=5,
        validate_err=codes.ERR_TOPIC_ALIAS_INVALID,
        validate_arg=3,
        group="validate",
    ),
    Case(
        "publish qos1 packet id zero",
        hx("3207 0001 74 0000 6f6b"),
        version=4,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_NO_PACKET_ID,
        group="validate",
    ),
    Case(
        "publish qos0 surplus packet id (struct)",
        b"",
        Packet(
            fixed_header=fhdr(PUBLISH),
            protocol_version=4,
            topic_name="t",
            packet_id=5,
            payload=b"ok",
        ),
        validate_err=codes.ERR_PROTOCOL_VIOLATION_SURPLUS_PACKET_ID,
        group="validate",
    ),
    # SUBSCRIBE validate
    Case(
        "subscribe packet id zero",
        hx("820a 0000 0005 612f622f63 00"),
        version=4,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_NO_PACKET_ID,
        group="validate",
    ),
    Case(
        "subscribe no filters",
        hx("8202 0015"),
        version=4,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_NO_FILTERS,
        group="validate",
    ),
    Case(
        "subscribe oversize identifier (struct)",
        b"",
        Packet(
            fixed_header=fhdr(SUBSCRIBE, qos=1),
            protocol_version=5,
            packet_id=15,
            filters=[
                Subscription(filter="a/b", qos=0, identifier=268435456)
            ],
        ),
        validate_err=codes.ERR_PROTOCOL_VIOLATION_OVERSIZE_SUB_ID,
        group="validate",
    ),
    # UNSUBSCRIBE validate
    Case(
        "unsubscribe packet id zero",
        hx("a207 0000 0003 612f62"),
        version=4,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_NO_PACKET_ID,
        group="validate",
    ),
    Case(
        "unsubscribe no filters",
        hx("a202 0015"),
        version=4,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_NO_FILTERS,
        group="validate",
    ),
    # AUTH validate
    Case(
        "auth invalid reason code",
        hx("f002 8100"),
        version=5,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_INVALID_REASON,
        group="validate",
    ),
    Case(
        "auth invalid reason code success-ignore",
        hx("f002 0100"),
        version=5,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_INVALID_REASON,
        group="validate",
    ),
]

# more wire-level decode/roundtrip coverage mirroring tpackets.go
CASES += [
    Case(
        "connack v5 adjusted session expiry interval",
        hx("2008 00 00 05 11 00000078"),
        Packet(
            fixed_header=fhdr(CONNACK, remaining=8),
            protocol_version=5,
            session_present=False,
            reason_code=0,
            properties=Properties(
                session_expiry_interval=120, session_expiry_interval_flag=True
            ),
        ),
        version=5,
    ),
    Case(
        "publish v5 broker subscription identifier",
        hx("3008 0001 74 02 0b05 6f6b"),
        Packet(
            fixed_header=fhdr(PUBLISH, remaining=8),
            protocol_version=5,
            topic_name="t",
            properties=Properties(subscription_identifier=[5]),
            payload=b"ok",
        ),
        version=5,
    ),
    Case(
        "pubrec v5 remaining longer than body",
        hx("5003 0015"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PACKET,
        group="decode",
    ),
    Case(
        "disconnect v5 disconnect with will message",
        hx("e002 0400"),
        Packet(
            fixed_header=fhdr(DISCONNECT, remaining=2),
            protocol_version=5,
            reason_code=0x04,
            properties=Properties(),
        ),
        version=5,
        group="decode",
    ),
    Case(
        "disconnect v5 receive maximum exceeded",
        hx("e002 9300"),
        Packet(
            fixed_header=fhdr(DISCONNECT, remaining=2),
            protocol_version=5,
            reason_code=0x93,
            properties=Properties(),
        ),
        version=5,
        group="decode",
    ),
    Case(
        "disconnect v5 session expiry property",
        hx("e007 00 05 11 0000003c"),
        Packet(
            fixed_header=fhdr(DISCONNECT, remaining=7),
            protocol_version=5,
            reason_code=0,
            properties=Properties(
                session_expiry_interval=60, session_expiry_interval_flag=True
            ),
        ),
        version=5,
        group="decode",
    ),
    Case(
        "suback v5 shared subscriptions not supported",
        hx("9004 0015 00 9e"),
        Packet(
            fixed_header=fhdr(SUBACK, remaining=4),
            protocol_version=5,
            packet_id=0x15,
            properties=Properties(),
            reason_codes=b"\x9e",
        ),
        version=5,
    ),
    Case(
        "unsuback v5 no subscription existed",
        hx("b005 0015 00 00 11"),
        Packet(
            fixed_header=fhdr(UNSUBACK, remaining=5),
            protocol_version=5,
            packet_id=0x15,
            properties=Properties(),
            reason_codes=b"\x00\x11",
        ),
        version=5,
    ),
]

CASES += [
    Case(
        "suback v5 packet identifier in use",
        hx("9004 0015 00 91"),
        Packet(
            fixed_header=fhdr(SUBACK, remaining=4),
            protocol_version=5,
            packet_id=0x15,
            properties=Properties(),
            reason_codes=b"\x91",
        ),
        version=5,
    ),
    Case(
        "puback v5 quota exceeded",
        hx("4004 0015 97 00"),
        Packet(
            fixed_header=fhdr(PUBACK, remaining=4),
            protocol_version=5,
            packet_id=0x15,
            reason_code=0x97,
            properties=Properties(),
        ),
        version=5,
        group="decode",
    ),
    Case(
        "unsuback v5 packet identifier in use",
        hx("b004 0015 00 91"),
        Packet(
            fixed_header=fhdr(UNSUBACK, remaining=4),
            protocol_version=5,
            packet_id=0x15,
            properties=Properties(),
            reason_codes=b"\x91",
        ),
        version=5,
    ),
    Case(
        "pubcomp v5 invalid reason decodes (validity checked at server)",
        hx("7004 0015 99 00"),
        Packet(
            fixed_header=fhdr(PUBCOMP, remaining=4),
            protocol_version=5,
            packet_id=0x15,
            reason_code=0x99,
            properties=Properties(),
        ),
        version=5,
        group="decode",
    ),
]

# ---------------------------------------------------------------------------
# Round-5 expansion: the remaining malformed / FailFirst / validate variants
# from the reference catalogue (tpackets.go case ids :37-234) plus varint
# and translation boundary cases. Same conventions as above.
# ---------------------------------------------------------------------------

CASES += [
    # ---- CONNECT: remaining malformed variants ---------------------------
    Case(
        "connect missing protocol version",
        hx("1006 0004 4d515454"),
        decode_err=codes.ERR_MALFORMED_PROTOCOL_VERSION,
        group="decode",
    ),
    Case(
        "connect truncated client id",
        hx("100c 0004 4d515454 04 00 0014 0003 7a65"),
        decode_err=codes.ERR_CLIENT_IDENTIFIER_NOT_VALID,
        group="decode",
    ),
    Case(
        "connect will flag truncated will payload bytes",
        hx("101b 0004 4d515454 04 0e 0014 0003 7a656e 0003 6c7774 0009 6e6f742061"),
        decode_err=codes.ERR_MALFORMED_WILL_PAYLOAD,
        group="decode",
    ),
    Case(
        "connect will and user flags truncated username bytes",
        hx("1024 0004 4d515454 04 ce 0014 0003 7a656e 0003 6c7774 0009 6e6f7420616761696e 0005 6d6f63"),
        decode_err=codes.ERR_MALFORMED_USERNAME,
        group="decode",
    ),
    Case(
        "connect oversize fixed header varint",
        hx("10 ffffffffff"),
        decode_err=codes.ERR_MALFORMED_VARIABLE_BYTE_INTEGER,
        group="decode",
    ),
    Case(
        "connect v5 malformed properties declared past body",
        hx("100b 0004 4d515454 05 0e 001e 0a"),
        decode_err=codes.ERR_MALFORMED_PROPERTIES,
        group="decode",
    ),
    Case(
        "connect v4 username password will qos1",
        hx("102c 0004 4d515454 04 ce 0014 0003 7a656e 0003 6c7774 0009 6e6f7420616761696e 0005 6d6f636869 0004 31323334"),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=44),
            protocol_version=4,
            connect=ConnectParams(
                protocol_name=b"MQTT",
                clean=True,
                keepalive=20,
                client_identifier="zen",
                will_flag=True,
                will_qos=1,
                will_topic="lwt",
                will_payload=b"not again",
                username_flag=True,
                password_flag=True,
                username=b"mochi",
                password=b"1234",
            ),
        ),
    ),
    Case(
        "connect v5 server-limit properties roundtrip",
        hx("101b 0004 4d515454 05 02 003c 0b 11 00000000 21 0005 22 000a 0003 7a656e"),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=27),
            protocol_version=5,
            properties=Properties(
                session_expiry_interval=0,
                session_expiry_interval_flag=True,
                receive_maximum=5,
                topic_alias_maximum=10,
            ),
            connect=ConnectParams(
                protocol_name=b"MQTT", clean=True, keepalive=60, client_identifier="zen"
            ),
        ),
    ),
    Case(
        "connect client id BOM not skipped [MQTT-1.5.4-3]",
        hx("1012 0004 4d515454 04 02 003c 0006 efbbbf7a656e"),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=18),
            protocol_version=4,
            connect=ConnectParams(
                protocol_name=b"MQTT",
                clean=True,
                keepalive=60,
                client_identifier="﻿zen",
            ),
        ),
    ),
    # ---- CONNACK ---------------------------------------------------------
    Case(
        "connack v5 min with session present",
        hx("2003 010000"),
        Packet(
            fixed_header=fhdr(CONNACK, remaining=3),
            protocol_version=5,
            session_present=True,
        ),
        version=5,
    ),
    Case(
        "connack v4 encode drops v5 properties",
        hx("20020000"),
        Packet(
            fixed_header=fhdr(CONNACK, remaining=2),
            protocol_version=4,
            properties=Properties(reason_string="ignored"),
        ),
        group="encode",
    ),
    Case(
        "connack v5 body shorter than remaining",
        hx("2004 000005"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PACKET,
        group="decode",
    ),
    # ---- PUBLISH ---------------------------------------------------------
    Case(
        "publish qos1 no payload",
        hx("3209 0005 612f622f63 000b"),
        Packet(
            fixed_header=fhdr(PUBLISH, qos=1, remaining=9),
            protocol_version=4,
            topic_name="a/b/c",
            packet_id=11,
        ),
    ),
    Case(
        "publish qos1 dup",
        hx("3a0e 0005 612f622f63 000b 68656c6c6f"),
        Packet(
            fixed_header=fhdr(PUBLISH, qos=1, dup=True, remaining=14),
            protocol_version=4,
            topic_name="a/b/c",
            packet_id=11,
            payload=b"hello",
        ),
    ),
    Case(
        "publish v5 topic alias above client maximum (validate)",
        hx("300a 0003 612f62 03 23 ffff 78"),
        version=5,
        validate_err=codes.ERR_TOPIC_ALIAS_INVALID,
        validate_arg=1024,
        group="validate",
    ),
    Case(
        "publish v5 surplus subscription identifier (validate)",
        hx("3009 0003 612f62 02 0b 07 78"),
        version=5,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_SURPLUS_SUB_ID,
        validate_arg=10,
        group="validate",
    ),
]

CASES += [
    # ---- PUBACK / PUBREC / PUBREL / PUBCOMP ------------------------------
    Case(
        "puback v5 unexpected error",
        hx("4004 0007 99 00"),
        Packet(
            fixed_header=fhdr(PUBACK, remaining=4),
            protocol_version=5,
            packet_id=7,
            reason_code=0x99,
        ),
        version=5,
        group="decode",
    ),
    Case(
        "puback v5 not authorized",
        hx("4004 0007 87 00"),
        Packet(
            fixed_header=fhdr(PUBACK, remaining=4),
            protocol_version=5,
            packet_id=7,
            reason_code=0x87,
        ),
        version=5,
        group="decode",
    ),
    Case(
        "pubrec v5 packet identifier in use",
        hx("5004 0007 91 00"),
        Packet(
            fixed_header=fhdr(PUBREC, remaining=4),
            protocol_version=5,
            packet_id=7,
            reason_code=0x91,
        ),
        version=5,
        group="decode",
    ),
    Case(
        "pubrec v5 two byte body implies success [MQTT-3.5.2.1]",
        hx("5002 0007"),
        Packet(
            fixed_header=fhdr(PUBREC, remaining=2),
            protocol_version=5,
            packet_id=7,
        ),
        version=5,
        group="decode",
    ),
    Case(
        "pubrec v5 invalid reason decodes (validity checked at server)",
        hx("5004 0007 99 00"),
        Packet(
            fixed_header=fhdr(PUBREC, remaining=4),
            protocol_version=5,
            packet_id=7,
            reason_code=0x99,
        ),
        version=5,
        group="decode",
    ),
    Case(
        "pubrel v5 invalid reason decodes (validity checked at server)",
        hx("6204 0007 99 00"),
        Packet(
            fixed_header=fhdr(PUBREL, qos=1, remaining=4),
            protocol_version=5,
            packet_id=7,
            reason_code=0x99,
        ),
        version=5,
        group="decode",
    ),
    Case(
        "pubcomp truncated packet id",
        hx("7001 00"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PACKET_ID,
        group="decode",
    ),
    # ---- SUBSCRIBE / SUBACK ----------------------------------------------
    Case(
        "suback truncated packet id",
        hx("9001 00"),
        decode_err=codes.ERR_MALFORMED_PACKET_ID,
        group="decode",
    ),
    Case(
        "suback v5 truncated reason-string property",
        hx("9005 0007 05 1f 00"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PROPERTIES,
        group="decode",
    ),
    Case(
        "suback v4 no grant codes",
        hx("9002 0007"),
        Packet(
            fixed_header=fhdr(SUBACK, remaining=2),
            protocol_version=4,
            packet_id=7,
        ),
        group="decode",
    ),
    Case(
        "subscribe v5 malformed subscription identifier varint",
        hx("8206 0007 02 0b 80"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PACKET,
        group="decode",
    ),
    Case(
        "subscribe v5 shared filter with no-local option decodes",
        hx("8210 000a 00 000a 2453484152452f672f61 05"),
        Packet(
            fixed_header=fhdr(SUBSCRIBE, qos=1, remaining=16),
            protocol_version=5,
            packet_id=10,
            filters=[
                Subscription(filter="$SHARE/g/a", qos=1, no_local=True)
            ],
        ),
        version=5,
    ),
    Case(
        "subscribe missing packet id (struct validate)",
        b"",
        Packet(
            fixed_header=fhdr(SUBSCRIBE, qos=1),
            protocol_version=5,
            packet_id=0,
            filters=[Subscription(filter="a/b")],
        ),
        version=5,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_NO_PACKET_ID,
        group="validate",
    ),
    Case(
        "subscribe empty filter list (struct validate)",
        b"",
        Packet(
            fixed_header=fhdr(SUBSCRIBE, qos=1),
            protocol_version=5,
            packet_id=7,
        ),
        version=5,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_NO_FILTERS,
        group="validate",
    ),
    # ---- UNSUBSCRIBE / UNSUBACK ------------------------------------------
    Case(
        "unsubscribe v5 truncated reason-string property",
        hx("a206 0007 05 1f 00 00"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PROPERTIES,
        group="decode",
    ),
    Case(
        "unsubscribe missing packet id (struct validate)",
        b"",
        Packet(
            fixed_header=fhdr(UNSUBSCRIBE, qos=1),
            protocol_version=5,
            packet_id=0,
            filters=[Subscription(filter="a/b")],
        ),
        version=5,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_NO_PACKET_ID,
        group="validate",
    ),
    Case(
        "unsubscribe empty filter list (struct validate)",
        b"",
        Packet(
            fixed_header=fhdr(UNSUBSCRIBE, qos=1),
            protocol_version=5,
            packet_id=7,
        ),
        version=5,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_NO_FILTERS,
        group="validate",
    ),
    Case(
        "unsuback v5 truncated reason-string property",
        hx("b005 0007 05 1f 00"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PROPERTIES,
        group="decode",
    ),
    Case(
        "unsuback v4 without payload",
        hx("b002 0007"),
        Packet(
            fixed_header=fhdr(UNSUBACK, remaining=2),
            protocol_version=4,
            packet_id=7,
        ),
        group="decode",
    ),
    # ---- DISCONNECT / AUTH / PING ----------------------------------------
    Case(
        "disconnect v5 truncated session-expiry property",
        hx("e003 04 05 1f"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PROPERTIES,
        group="decode",
    ),
    Case(
        "disconnect v5 message rate too high",
        hx("e002 96 00"),
        Packet(
            fixed_header=fhdr(DISCONNECT, remaining=2),
            protocol_version=5,
            reason_code=0x96,
        ),
        version=5,
    ),
    Case(
        "auth v5 truncated auth-method property",
        hx("f003 18 05 15"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PROPERTIES,
        group="decode",
    ),
    Case(
        "auth success-code zero (struct validate ok)",
        b"",
        Packet(fixed_header=fhdr(AUTH), protocol_version=5, reason_code=0),
        version=5,
        validate_err=codes.CODE_SUCCESS,
        group="validate",
    ),
    Case(
        "pingreq tolerates nonzero remaining",
        hx("c001 00"),
        Packet(fixed_header=fhdr(PINGREQ, remaining=1), protocol_version=4),
        group="decode",
    ),
    # ---- varint / remaining-length boundaries ----------------------------
    Case(
        "publish remaining length 127 single byte boundary",
        hx("307f 0003 612f62") + b"\x00" * 122,
        Packet(
            fixed_header=fhdr(PUBLISH, remaining=127),
            protocol_version=4,
            topic_name="a/b",
            payload=b"\x00" * 122,
        ),
    ),
    Case(
        "publish remaining length 128 two byte boundary",
        hx("308001 0003 612f62") + b"\x00" * 123,
        Packet(
            fixed_header=fhdr(PUBLISH, remaining=128),
            protocol_version=4,
            topic_name="a/b",
            payload=b"\x00" * 123,
        ),
    ),
    Case(
        "publish remaining varint above protocol maximum",
        hx("30 ffffff7f"),
        decode_err=codes.ERR_MALFORMED_PACKET,
        group="decode",
    ),
]

CASES += [
    # canonical short encodes for the ack family: a non-success reason
    # emits the reason byte but omits the empty properties length
    Case(
        "pubrec v5 packet identifier in use canonical encode",
        hx("5003 0007 91"),
        Packet(
            fixed_header=fhdr(PUBREC, remaining=3),
            protocol_version=5,
            packet_id=7,
            reason_code=0x91,
        ),
        version=5,
    ),
    Case(
        "pubcomp v5 not authorized canonical encode",
        hx("7003 0007 87"),
        Packet(
            fixed_header=fhdr(PUBCOMP, remaining=3),
            protocol_version=5,
            packet_id=7,
            reason_code=0x87,
        ),
        version=5,
    ),
]

CASES += [
    # ---- CONNECT variants ------------------------------------------------
    Case(
        "connect v5 password without username [MQTT-3.1.2-22 removed in v5]",
        hx("1015 0004 4d515454 05 42 003c 00 0004 7a656e33 0002 7071"),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=21),
            protocol_version=5,
            connect=ConnectParams(
                protocol_name=b"MQTT",
                clean=True,
                keepalive=60,
                client_identifier="zen3",
                password_flag=True,
                password=b"pq",
            ),
        ),
        version=5,
    ),
    Case(
        "connect will qos2 retain",
        hx("101f 0004 4d515454 04 36 003c 0004 7a656e33 0003 6c7774 0008 6e6f74616761696e"),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=31),
            protocol_version=4,
            connect=ConnectParams(
                protocol_name=b"MQTT",
                clean=True,
                keepalive=60,
                client_identifier="zen3",
                will_flag=True,
                will_qos=2,
                will_retain=True,
                will_topic="lwt",
                will_payload=b"notagain",
            ),
        ),
    ),
    Case(
        "connect MQIsdp name with version 4 decodes (validate flags version)",
        hx("1012 0006 4d514973647004 02 003c 0004 7a656e33"),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=18),
            protocol_version=4,
            connect=ConnectParams(
                protocol_name=b"MQIsdp",
                clean=True,
                keepalive=60,
                client_identifier="zen3",
            ),
        ),
        validate_err=codes.ERR_PROTOCOL_VIOLATION_PROTOCOL_VERSION,
    ),
    Case(
        "connect keepalive maximum",
        hx("1010 0004 4d515454 04 02 ffff 0004 7a656e33"),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=16),
            protocol_version=4,
            connect=ConnectParams(
                protocol_name=b"MQTT",
                clean=True,
                keepalive=65535,
                client_identifier="zen3",
            ),
        ),
    ),
    # ---- CONNACK variants ------------------------------------------------
    Case(
        "connack v4 session present with identifier rejected",
        hx("2002 0102"),
        Packet(
            fixed_header=fhdr(CONNACK, remaining=2),
            protocol_version=4,
            session_present=True,
            reason_code=2,
        ),
    ),
    # ---- PUBLISH variants ------------------------------------------------
    Case(
        "publish remaining length 16383 two byte maximum",
        hx("30 ff7f 0003 612f62") + b"\x00" * (16383 - 5),
        Packet(
            fixed_header=fhdr(PUBLISH, remaining=16383),
            protocol_version=4,
            topic_name="a/b",
            payload=b"\x00" * (16383 - 5),
        ),
    ),
    Case(
        "publish remaining length 16384 three byte minimum",
        hx("30 808001 0003 612f62") + b"\x00" * (16384 - 5),
        Packet(
            fixed_header=fhdr(PUBLISH, remaining=16384),
            protocol_version=4,
            topic_name="a/b",
            payload=b"\x00" * (16384 - 5),
        ),
    ),
    Case(
        "publish qos2 dup retain",
        hx("3d0c 0003 612f62 0009 7061796c64"),
        Packet(
            fixed_header=fhdr(PUBLISH, qos=2, dup=True, retain=True, remaining=12),
            protocol_version=4,
            topic_name="a/b",
            packet_id=9,
            payload=b"payld",
        ),
    ),
    Case(
        "publish no topic and no alias (struct validate)",
        b"",
        Packet(
            fixed_header=fhdr(PUBLISH),
            protocol_version=5,
            topic_name="",
        ),
        version=5,
        validate_err=codes.ERR_PROTOCOL_VIOLATION_NO_TOPIC,
        group="validate",
    ),
    # ---- SUBSCRIBE variants ----------------------------------------------
    Case(
        "subscribe v5 retain handling 3 decodes (server validates range)",
        hx("820b 0007 00 0005 612f622f63 30"),
        Packet(
            fixed_header=fhdr(SUBSCRIBE, qos=1, remaining=11),
            protocol_version=5,
            packet_id=7,
            filters=[Subscription(filter="a/b/c", retain_handling=3)],
        ),
        version=5,
    ),
    Case(
        "subscribe body shorter than declared remaining",
        hx("8204 0007 00"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PACKET,
        group="decode",
    ),
    # ---- UNSUBSCRIBE / UNSUBACK variants ---------------------------------
    Case(
        "unsubscribe truncated second filter",
        hx("a20c 0007 0003 612f62 0005 632f64"),
        decode_err=codes.ERR_MALFORMED_TOPIC,
        group="decode",
    ),
    Case(
        "unsuback v5 mixed grant codes",
        hx("b006 0007 00 0011 80"),
        Packet(
            fixed_header=fhdr(UNSUBACK, remaining=6),
            protocol_version=5,
            packet_id=7,
            reason_codes=bytes([0x00, 0x11, 0x80]),
        ),
        version=5,
    ),
    # ---- DISCONNECT / AUTH / PING variants -------------------------------
    Case(
        "disconnect v4 tolerates body byte",
        hx("e001 00"),
        Packet(fixed_header=fhdr(DISCONNECT, remaining=1), protocol_version=4),
        group="decode",
    ),
    Case(
        "disconnect v5 use another server with server reference",
        hx("e00d 9c 0b 1c 0008 656c736577686572"),
        Packet(
            fixed_header=fhdr(DISCONNECT, remaining=13),
            protocol_version=5,
            reason_code=0x9C,
            properties=Properties(server_reference="elsewher"),
        ),
        version=5,
    ),
    Case(
        "auth v5 method and binary data roundtrip",
        hx("f00f 18 0d 15 0005 746f6b656e 16 0002 abcd"),
        Packet(
            fixed_header=fhdr(AUTH, remaining=15),
            protocol_version=5,
            reason_code=0x18,
            properties=Properties(
                authentication_method="token",
                authentication_data=b"\xab\xcd",
            ),
        ),
        version=5,
    ),
    Case(
        "pingresp tolerates nonzero remaining",
        hx("d001 00"),
        Packet(fixed_header=fhdr(PINGRESP, remaining=1), protocol_version=4),
        group="decode",
    ),
]

CASES += [
    # ---- v5 property-validity matrix: a property invalid for the packet
    # type must fail the properties decode (reference validPacketProperties,
    # properties.go:46-74)
    Case(
        "puback v5 topic alias invalid for type",
        hx("4007 0007 10 03 23 0005"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PROPERTIES,
        group="decode",
    ),
    Case(
        "suback v5 session expiry invalid for type",
        hx("9009 0007 05 11 00000078 00"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PROPERTIES,
        group="decode",
    ),
    Case(
        "subscribe v5 reason string invalid for type",
        hx("820e 0007 05 1f 0002 6e6f 0003 612f62 00"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PROPERTIES,
        group="decode",
    ),
    Case(
        "unsubscribe v5 subscription identifier invalid for type",
        hx("a20a 0007 02 0b 07 0003 612f62"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PROPERTIES,
        group="decode",
    ),
    Case(
        "pubrel v5 receive maximum invalid for type",
        hx("6207 0007 00 03 21 0005"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PROPERTIES,
        group="decode",
    ),
    Case(
        "disconnect v5 will delay invalid for type",
        hx("e008 00 06 18 00000005 00"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PROPERTIES,
        group="decode",
    ),
    Case(
        "connack v5 subscription identifier invalid for type",
        hx("2005 0000 02 0b 07"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PROPERTIES,
        group="decode",
    ),
    Case(
        "publish v5 maximum packet size invalid for type",
        hx("300b 0003 612f62 05 27 00000400"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PROPERTIES,
        group="decode",
    ),
    Case(
        "connect v5 retain available invalid for type",
        hx("1013 0004 4d515454 05 02 003c 02 25 01 0004 7a656e33"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PROPERTIES,
        group="decode",
    ),
    # ---- rich-property roundtrips ----------------------------------------
    Case(
        "connack v5 server capability property set",
        hx("2023 0000 20 12 0003 616263 13 003c 1c 0004 74686174 22 000a 24 01 25 00 27 00001000 28 00 29 01"),
        Packet(
            fixed_header=fhdr(CONNACK, remaining=35),
            protocol_version=5,
            properties=Properties(
                assigned_client_id="abc",
                server_keep_alive=60,
                server_keep_alive_flag=True,
                server_reference="that",
                topic_alias_maximum=10,
                maximum_qos=1,
                maximum_qos_flag=True,
                retain_available=0,
                retain_available_flag=True,
                maximum_packet_size=4096,
                wildcard_sub_available=0,
                wildcard_sub_available_flag=True,
                sub_id_available=1,
                sub_id_available_flag=True,
            ),
        ),
        version=5,
    ),
    Case(
        "connect v5 full will properties",
        hx("1032 0004 4d515454 05 06 003c 00 0003 7a656e 18 0101 02 0000003c 03 0009 746578742f6a736f6e 18 00000005 0003 6c7774 0002 686f"),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=50),
            protocol_version=5,
            connect=ConnectParams(
                protocol_name=b"MQTT",
                clean=True,
                keepalive=60,
                client_identifier="zen",
                will_flag=True,
                will_topic="lwt",
                will_payload=b"ho",
                will_properties=Properties(
                    payload_format=1,
                    payload_format_flag=True,
                    message_expiry_interval=60,
                    content_type="text/json",
                    will_delay_interval=5,
                ),
            ),
        ),
        version=5,
    ),
    Case(
        "publish v5 two user properties",
        hx("3016 0003 612f62 0e 26 0001 61 0001 31 26 0001 62 0001 32 7879"),
        Packet(
            fixed_header=fhdr(PUBLISH, remaining=22),
            protocol_version=5,
            topic_name="a/b",
            payload=b"xy",
            properties=Properties(
                user=[UserProperty("a", "1"), UserProperty("b", "2")]
            ),
        ),
        version=5,
    ),
    Case(
        "disconnect v5 session expiry and reason string",
        hx("e011 04 0f 11 0000003c 1f 0007 676f6f64627965"),
        Packet(
            fixed_header=fhdr(DISCONNECT, remaining=17),
            protocol_version=5,
            reason_code=0x04,
            properties=Properties(
                session_expiry_interval=60,
                session_expiry_interval_flag=True,
                reason_string="goodbye",
            ),
        ),
        version=5,
    ),
    Case(
        "subscribe v5 user property",
        hx("8211 0007 08 26 0001 6b 0002 7631 0003 612f62 01"),
        Packet(
            fixed_header=fhdr(SUBSCRIBE, qos=1, remaining=17),
            protocol_version=5,
            packet_id=7,
            properties=Properties(user=[UserProperty("k", "v1")]),
            filters=[Subscription(filter="a/b", qos=1)],
        ),
        version=5,
    ),
    # ---- misc edge behavior ----------------------------------------------
    Case(
        "publish v4 empty topic decodes (server rejects at validate)",
        hx("3004 0000 0000"),
        Packet(
            fixed_header=fhdr(PUBLISH, remaining=4),
            protocol_version=4,
            topic_name="",
            payload=b"\x00\x00",
        ),
        validate_err=codes.ERR_PROTOCOL_VIOLATION_NO_TOPIC,
    ),
    Case(
        "pubrel v4 tolerates trailing byte",
        hx("6203 0007 00"),
        Packet(
            fixed_header=fhdr(PUBREL, qos=1, remaining=3),
            protocol_version=4,
            packet_id=7,
        ),
        group="decode",
    ),
    Case(
        "connack nonzero flags rejected at header",
        hx("2102 0000"),
        fail_first=codes.ERR_MALFORMED_FLAGS,
        group="decode",
    ),
]

CASES += [
    # ---- empty-body decodes per type -------------------------------------
    Case(
        "connect empty body",
        hx("1000"),
        decode_err=codes.ERR_MALFORMED_PROTOCOL_NAME,
        group="decode",
    ),
    Case(
        "publish empty body",
        hx("3000"),
        decode_err=codes.ERR_MALFORMED_TOPIC,
        group="decode",
    ),
    Case(
        "subscribe empty body",
        hx("8200"),
        decode_err=codes.ERR_MALFORMED_PACKET_ID,
        group="decode",
    ),
    Case(
        "unsubscribe empty body",
        hx("a200"),
        decode_err=codes.ERR_MALFORMED_PACKET_ID,
        group="decode",
    ),
    Case(
        "puback empty body",
        hx("4000"),
        decode_err=codes.ERR_MALFORMED_PACKET_ID,
        group="decode",
    ),
    Case(
        "pubrel empty body",
        hx("6200"),
        decode_err=codes.ERR_MALFORMED_PACKET_ID,
        group="decode",
    ),
    Case(
        "suback empty body",
        hx("9000"),
        decode_err=codes.ERR_MALFORMED_PACKET_ID,
        group="decode",
    ),
    Case(
        "unsuback empty body",
        hx("b000"),
        decode_err=codes.ERR_MALFORMED_PACKET_ID,
        group="decode",
    ),
    Case(
        "auth empty body",
        hx("f000"),
        version=5,
        decode_err=codes.ERR_MALFORMED_REASON_CODE,
        group="decode",
    ),
    # ---- body/remaining mismatches and trailing bytes --------------------
    Case(
        "puback body shorter than remaining",
        hx("4003 0007"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PACKET,
        group="decode",
    ),
    Case(
        "pubrec v4 tolerates trailing byte",
        hx("5003 0007 00"),
        Packet(
            fixed_header=fhdr(PUBREC, remaining=3),
            protocol_version=4,
            packet_id=7,
        ),
        group="decode",
    ),
    Case(
        "pubcomp v4 tolerates trailing byte",
        hx("7003 0007 00"),
        Packet(
            fixed_header=fhdr(PUBCOMP, remaining=3),
            protocol_version=4,
            packet_id=7,
        ),
        group="decode",
    ),
    Case(
        "disconnect v5 overlong properties length at body end tolerated",
        hx("e002 00 05"),
        Packet(fixed_header=fhdr(DISCONNECT, remaining=2), protocol_version=5),
        version=5,
        group="decode",
    ),
    Case(
        "unsubscribe v5 zero length filter",
        hx("a206 0007 00 0000"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PACKET,
        group="decode",
    ),
    Case(
        "auth nonzero flags rejected at header",
        hx("f102 1800"),
        version=5,
        fail_first=codes.ERR_MALFORMED_FLAGS,
        group="decode",
    ),
    # ---- more roundtrips -------------------------------------------------
    Case(
        "connect v5 request problem and response information",
        hx("1015 0004 4d515454 05 02 003c 04 1700 1901 0004 7a656e33"),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=21),
            protocol_version=5,
            properties=Properties(
                request_problem_info=0,
                request_problem_info_flag=True,
                request_response_info=1,
            ),
            connect=ConnectParams(
                protocol_name=b"MQTT",
                clean=True,
                keepalive=60,
                client_identifier="zen3",
            ),
        ),
        version=5,
    ),
    Case(
        "connack v5 response information decodes (encode gated by mods)",
        hx("2008 0000 05 1a 0002 7269"),
        Packet(
            fixed_header=fhdr(CONNACK, remaining=8),
            protocol_version=5,
            properties=Properties(response_info="ri"),
        ),
        version=5,
        group="decode",
    ),
    Case(
        "publish v5 two subscription identifiers",
        hx("300b 0003 612f62 04 0b 07 0b 09 78"),
        Packet(
            fixed_header=fhdr(PUBLISH, remaining=11),
            protocol_version=5,
            topic_name="a/b",
            payload=b"x",
            properties=Properties(subscription_identifier=[7, 9]),
        ),
        version=5,
    ),
    Case(
        "connect v5 receive maximum zero decodes (encode omits zero)",
        hx("1014 0004 4d515454 05 02 003c 03 21 0000 0004 7a656e33"),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=20),
            protocol_version=5,
            connect=ConnectParams(
                protocol_name=b"MQTT",
                clean=True,
                keepalive=60,
                client_identifier="zen3",
            ),
        ),
        version=5,
        group="decode",
    ),
    Case(
        "subscribe v5 all option bits (qos2 nl rap rh2)",
        hx("820b 0007 00 0005 612f622f63 2e"),
        Packet(
            fixed_header=fhdr(SUBSCRIBE, qos=1, remaining=11),
            protocol_version=5,
            packet_id=7,
            filters=[
                Subscription(
                    filter="a/b/c",
                    qos=2,
                    no_local=True,
                    retain_as_published=True,
                    retain_handling=2,
                )
            ],
        ),
        version=5,
    ),
    Case(
        "unsubscribe v4 three filters",
        hx("a20f 0007 0003 612f62 0003 632f64 0001 65"),
        Packet(
            fixed_header=fhdr(UNSUBSCRIBE, qos=1, remaining=15),
            protocol_version=4,
            packet_id=7,
            filters=[
                Subscription(filter="a/b"),
                Subscription(filter="c/d"),
                Subscription(filter="e"),
            ],
        ),
    ),
    Case(
        "subscribe v4 duplicate filters decode (server dedups)",
        hx("8212 0007 0005 612f622f63 01 0005 612f622f63 02"),
        Packet(
            fixed_header=fhdr(SUBSCRIBE, qos=1, remaining=18),
            protocol_version=4,
            packet_id=7,
            filters=[
                Subscription(filter="a/b/c", qos=1),
                Subscription(filter="a/b/c", qos=2),
            ],
        ),
    ),
    Case(
        "publish topic with BOM roundtrip",
        hx("3009 0005 efbbbf612f 7879"),
        Packet(
            fixed_header=fhdr(PUBLISH, remaining=9),
            protocol_version=4,
            topic_name="﻿a/",
            payload=b"xy",
        ),
    ),
]

CASES += [
    Case(
        "connack v5 shared subscription available",
        hx("2005 0000 02 2a 01"),
        Packet(
            fixed_header=fhdr(CONNACK, remaining=5),
            protocol_version=5,
            properties=Properties(
                shared_sub_available=1, shared_sub_available_flag=True
            ),
        ),
        version=5,
    ),
    Case(
        "suback v5 user property",
        hx("900c 0007 08 26 0001 78 0002 7979 01"),
        Packet(
            fixed_header=fhdr(SUBACK, remaining=12),
            protocol_version=5,
            packet_id=7,
            properties=Properties(user=[UserProperty("x", "yy")]),
            reason_codes=b"\x01",
        ),
        version=5,
    ),
    Case(
        "unsuback v5 user property",
        hx("b00c 0007 08 26 0001 78 0002 7979 00"),
        Packet(
            fixed_header=fhdr(UNSUBACK, remaining=12),
            protocol_version=5,
            packet_id=7,
            properties=Properties(user=[UserProperty("x", "yy")]),
            reason_codes=b"\x00",
        ),
        version=5,
    ),
    Case(
        "puback v5 user property",
        hx("400c 0007 10 08 26 0001 78 0002 7979"),
        Packet(
            fixed_header=fhdr(PUBACK, remaining=12),
            protocol_version=5,
            packet_id=7,
            reason_code=0x10,
            properties=Properties(user=[UserProperty("x", "yy")]),
        ),
        version=5,
    ),
    Case(
        "connect v5 password flag but no password bytes",
        hx("1011 0004 4d515454 05 40 003c 00 0004 7a656e33"),
        version=5,
        decode_err=codes.ERR_MALFORMED_PASSWORD,
        group="decode",
    ),
    Case(
        "pubrel v5 reason code with reason string",
        hx("6209 0007 92 05 1f 0002 6e6f"),
        Packet(
            fixed_header=fhdr(PUBREL, qos=1, remaining=9),
            protocol_version=5,
            packet_id=7,
            reason_code=0x92,
            properties=Properties(reason_string="no"),
        ),
        version=5,
    ),
    Case(
        "disconnect v5 user property",
        hx("e00a 00 08 26 0002 6b31 0001 76"),
        Packet(
            fixed_header=fhdr(DISCONNECT, remaining=10),
            protocol_version=5,
            properties=Properties(user=[UserProperty("k1", "v")]),
        ),
        version=5,
    ),
    Case(
        "connect v5 maximum packet size property",
        hx("1016 0004 4d515454 05 02 003c 05 27 00010000 0004 7a656e33"),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=22),
            protocol_version=5,
            properties=Properties(maximum_packet_size=65536),
            connect=ConnectParams(
                protocol_name=b"MQTT",
                clean=True,
                keepalive=60,
                client_identifier="zen3",
            ),
        ),
        version=5,
    ),
]

CASES += [
    Case(
        "connack v5 maximum packet size property",
        hx("2008 0000 05 27 00000800"),
        Packet(
            fixed_header=fhdr(CONNACK, remaining=8),
            protocol_version=5,
            properties=Properties(maximum_packet_size=2048),
        ),
        version=5,
    ),
    Case(
        "subscribe v5 two filters with mixed options",
        hx("820f 0007 00 0003 612f62 01 0003 632f64 2e"),
        Packet(
            fixed_header=fhdr(SUBSCRIBE, qos=1, remaining=15),
            protocol_version=5,
            packet_id=7,
            filters=[
                Subscription(filter="a/b", qos=1),
                Subscription(
                    filter="c/d",
                    qos=2,
                    no_local=True,
                    retain_as_published=True,
                    retain_handling=2,
                ),
            ],
        ),
        version=5,
    ),
    Case(
        "connect v3 MQIsdp with will",
        hx("1019 0006 4d514973647003 0e 003c 0002 7a33 0003 6c7774 0002 6279"),
        Packet(
            fixed_header=fhdr(CONNECT, remaining=25),
            protocol_version=3,
            connect=ConnectParams(
                protocol_name=b"MQIsdp",
                clean=True,
                keepalive=60,
                client_identifier="z3",
                will_flag=True,
                will_qos=1,
                will_topic="lwt",
                will_payload=b"by",
            ),
        ),
        version=3,
    ),
    Case(
        "pubrec v5 reason code with reason string",
        hx("5009 0007 97 05 1f 0002 6e6f"),
        Packet(
            fixed_header=fhdr(PUBREC, remaining=9),
            protocol_version=5,
            packet_id=7,
            reason_code=0x97,  # quota exceeded: valid for PUBREC (3.5.2.1)
            properties=Properties(reason_string="no"),
        ),
        version=5,
    ),
    Case(
        "auth v5 reauthenticate with method property",
        hx("f00a 19 08 15 0005 746f6b656e"),
        Packet(
            fixed_header=fhdr(AUTH, remaining=10),
            protocol_version=5,
            reason_code=0x19,
            properties=Properties(authentication_method="token"),
        ),
        version=5,
    ),
    Case(
        "suback v5 quota exceeded grant",
        hx("9004 0007 00 97"),
        Packet(
            fixed_header=fhdr(SUBACK, remaining=4),
            protocol_version=5,
            packet_id=7,
            reason_codes=b"\x97",
        ),
        version=5,
    ),
]
