"""Delta-staged matcher conformance: under arbitrary churn the DeltaMatcher
must stay bit-identical to the live host trie at every instant, without
recompiling the CSR on the match path (SURVEY.md §7 stage 5, hard part #2)."""

import random
import threading
import time

from mqtt_tpu.ops.delta import DeltaMatcher
from mqtt_tpu.packets import Subscription
from mqtt_tpu.topics import SHARE_PREFIX, InlineSubscription, TopicsIndex

from tests.test_ops_matcher import canon


def test_parity_without_churn():
    index = TopicsIndex()
    index.subscribe("cl1", Subscription(filter="a/b/c", qos=1))
    index.subscribe("cl2", Subscription(filter="a/+/c", qos=2, identifier=7))
    index.subscribe("cl3", Subscription(filter="#"))
    m = DeltaMatcher(index, background=False)
    for topic in ["a/b/c", "a/x/c", "x", "$SYS/x"]:
        assert canon(m.subscribers(topic)) == canon(index.subscribers(topic)), topic
    assert m.pending_deltas == 0


def test_churn_routes_affected_topics_to_host():
    index = TopicsIndex()
    index.subscribe("old", Subscription(filter="a/b", qos=1))
    m = DeltaMatcher(index, background=False)
    assert canon(m.subscribers("a/b")) == canon(index.subscribers("a/b"))

    # mutations after the snapshot: results must reflect them immediately
    index.subscribe("new", Subscription(filter="a/+", qos=2))
    index.unsubscribe("a/b", "old")
    assert m.pending_deltas == 2
    subs = m.subscribers("a/b")
    assert canon(subs) == canon(index.subscribers("a/b"))
    assert "new" in subs.subscriptions and "old" not in subs.subscriptions

    # unaffected topics still serve from the stale snapshot
    index.subscribe("z", Subscription(filter="zzz/zzz"))
    assert canon(m.subscribers("a/b")) == canon(index.subscribers("a/b"))


def test_flush_folds_deltas_into_new_snapshot():
    index = TopicsIndex()
    index.subscribe("cl1", Subscription(filter="a/b"))
    m = DeltaMatcher(index, background=False)
    index.subscribe("cl2", Subscription(filter="a/#"))
    index.subscribe("cl3", Subscription(filter=SHARE_PREFIX + "/g/a/b"))
    index.inline_subscribe(InlineSubscription(filter="a/+", identifier=4, handler=lambda *a: None))
    assert m.pending_deltas == 3
    m.flush()
    assert m.pending_deltas == 0
    assert canon(m.subscribers("a/b")) == canon(index.subscribers("a/b"))


def test_shared_and_inline_deltas_flag_topics():
    index = TopicsIndex()
    index.subscribe("cl1", Subscription(filter="t/1"))
    m = DeltaMatcher(index, background=False)
    index.subscribe("s1", Subscription(filter=SHARE_PREFIX + "/grp/t/1"))
    subs = m.subscribers("t/1")
    assert canon(subs) == canon(index.subscribers("t/1"))
    assert SHARE_PREFIX + "/grp/t/1" in subs.shared
    index.inline_subscribe(InlineSubscription(filter="t/#", identifier=1, handler=lambda *a: None))
    assert canon(m.subscribers("t/1")) == canon(index.subscribers("t/1"))


def test_background_rebuild_drains_overlay():
    index = TopicsIndex()
    index.subscribe("cl0", Subscription(filter="seed"))
    m = DeltaMatcher(index, background=True, rebuild_after=8)
    try:
        for i in range(32):
            index.subscribe(f"cl{i}", Subscription(filter=f"t/{i}"))
        deadline = time.time() + 20
        while m.pending_deltas >= 8 and time.time() < deadline:
            time.sleep(0.05)
        assert m.pending_deltas < 8
        for i in range(32):
            assert canon(m.subscribers(f"t/{i}")) == canon(index.subscribers(f"t/{i}"))
    finally:
        m.close()


def test_concurrent_churn_differential_fuzz():
    """Mutator thread churns the trie while the main thread matches; every
    result must equal a host walk taken after the device result (mutations
    between the two walks can only make the host MORE recent, so we only
    compare topics untouched by the racing window — tracked exactly)."""
    rng = random.Random(41)
    segs = ["a", "b", "c", "", "x", "$SYS", "node"]

    def rand_topic(r):
        return "/".join(r.choice(segs) for _ in range(r.randint(1, 4)))

    def rand_filter(r):
        parts = [r.choice(segs + ["+"]) for _ in range(r.randint(1, 4))]
        if r.random() < 0.2:
            parts[-1] = "#"
        return "/".join(parts)

    index = TopicsIndex()
    for i in range(300):
        index.subscribe(f"cl{i}", Subscription(filter=rand_filter(rng), qos=rng.randint(0, 2)))
    m = DeltaMatcher(index, background=True, rebuild_after=64)
    stop = threading.Event()

    def mutate():
        r = random.Random(97)
        i = 300
        while not stop.is_set():
            if r.random() < 0.5:
                index.subscribe(f"m{i}", Subscription(filter=rand_filter(r), qos=1))
                i += 1
            else:
                index.unsubscribe(rand_filter(r), f"m{r.randint(300, max(301, i))}")
            time.sleep(0.001)

    t = threading.Thread(target=mutate, daemon=True)
    t.start()
    try:
        for _ in range(150):
            topic = rand_topic(rng)
            v0 = index.version
            dev = m.subscribers(topic)
            host = index.subscribers(topic)
            if index.version != v0:
                continue  # a mutation raced the two walks; not comparable
            assert canon(dev) == canon(host), topic
    finally:
        stop.set()
        t.join(timeout=5)
    try:
        # churn stopped: every remaining overlay delta must still route
        # correctly — these comparisons are race-free and always run
        for _ in range(100):
            topic = rand_topic(rng)
            assert canon(m.subscribers(topic)) == canon(index.subscribers(topic)), topic
    finally:
        m.close()


def test_inline_wildcard_delta_flags_dollar_topics():
    """An inline delta on '#' must flag $-topics: inline gathers are exempt
    from the MQTT-4.7.1 $-exclusion, so recording it as a client sub in the
    overlay would silently serve stale results (code-review regression)."""
    index = TopicsIndex()
    index.subscribe("cl1", Subscription(filter="seed"))
    m = DeltaMatcher(index, background=False)
    index.inline_subscribe(InlineSubscription(filter="#", identifier=5, handler=lambda *a: None))
    subs = m.subscribers("$SYS/broker/uptime")
    assert canon(subs) == canon(index.subscribers("$SYS/broker/uptime"))
    assert 5 in subs.inline_subscriptions
    # ...while a CLIENT delta on '#' must NOT flag $-topics (exclusion holds)
    index2 = TopicsIndex()
    index2.subscribe("cl1", Subscription(filter="seed"))
    m2 = DeltaMatcher(index2, background=False)
    index2.subscribe("cl2", Subscription(filter="#"))
    gen = m2._gen
    assert not gen.affected("$SYS/broker/uptime")
    assert canon(m2.subscribers("$SYS/broker/uptime")) == canon(
        index2.subscribers("$SYS/broker/uptime")
    )


def test_close_unregisters_observer():
    index = TopicsIndex()
    index.subscribe("cl1", Subscription(filter="a"))
    m = DeltaMatcher(index, background=False)
    m.close()
    index.subscribe("cl2", Subscription(filter="b"))
    assert m.pending_deltas == 0
    assert index._observers == []


def test_server_option_wires_delta_matcher():
    import asyncio

    from mqtt_tpu.server import Options, Server

    async def run():
        s = Server(Options(inline_client=True, device_matcher=True))
        got = []
        s.subscribe("d/+", 9, lambda cl, sub, pk: got.append(pk.payload))
        s.publish("d/1", b"hello", False, 0)
        await s.close()
        return got

    got = asyncio.run(run())
    assert got == [b"hello"]


def test_incremental_fold_parity_over_many_rounds():
    """Folds (in-place bucket edits + device scatter) must keep the
    snapshot bit-identical to a from-scratch rebuild across adds,
    removals, spill transitions, and brand-new wildcard shapes."""
    rng = random.Random(11)
    v = [f"t{i}" for i in range(12)]
    index = TopicsIndex()
    for i in range(400):
        parts = [rng.choice(v), rng.choice(v), rng.choice(v)]
        if rng.random() < 0.2:
            parts[rng.randrange(3)] = "+"
        index.subscribe(f"c{i}", Subscription(filter="/".join(parts), qos=i % 3))
    m = DeltaMatcher(index, background=False, max_levels=4)
    base_rebuilds = m.stats.rebuilds
    live = 400

    def check(tag):
        topics = ["/".join([rng.choice(v)] * 3) for _ in range(48)] + [
            f"{rng.choice(v)}/{rng.choice(v)}/{rng.choice(v)}" for _ in range(48)
        ]
        for t in topics:
            assert canon(m.subscribers(t)) == canon(index.subscribers(t)), (tag, t)

    for round_ in range(6):
        # adds (some to existing paths, some new paths)
        for i in range(40):
            parts = [rng.choice(v), rng.choice(v), rng.choice(v)]
            if rng.random() < 0.3:
                parts[rng.randrange(3)] = "+"
            index.subscribe(f"n{live}", Subscription(filter="/".join(parts), qos=1))
            live += 1
        # removals
        for i in range(20):
            index.unsubscribe(
                "/".join([rng.choice(v), rng.choice(v), rng.choice(v)]),
                f"c{rng.randrange(400)}",
            )
        m.flush()
        assert m.pending_deltas == 0
        check(round_)
    # folds actually ran (the whole point): no full rebuild after the first
    assert m.stats.folds >= 5, m.stats.as_dict()
    assert m.stats.rebuilds == base_rebuilds, m.stats.as_dict()


def test_fold_new_wildcard_shape_claims_pad_slot():
    index = TopicsIndex()
    index.subscribe("a", Subscription(filter="x/y", qos=0))
    m = DeltaMatcher(index, background=False, max_levels=4)
    r0 = m.stats.rebuilds
    # a shape that did not exist at build time: depth-3 with '+' at level 1
    index.subscribe("b", Subscription(filter="x/+/z", qos=1))
    m.flush()
    assert canon(m.subscribers("x/q/z")) == canon(index.subscribers("x/q/z"))
    assert m.stats.folds >= 1
    assert m.stats.rebuilds == r0  # pad slot claimed, no recompile-rebuild


def test_fold_spill_and_unspill_transitions():
    index = TopicsIndex()
    index.subscribe("seed", Subscription(filter="s/t", qos=0))
    m = DeltaMatcher(index, background=False, max_levels=4, window=16)
    # spill: push one path over the window
    for i in range(40):
        index.subscribe(f"sp{i}", Subscription(filter="s/t", qos=0))
    m.flush()
    assert canon(m.subscribers("s/t")) == canon(index.subscribers("s/t"))
    # unspill: back under the window
    for i in range(40):
        index.unsubscribe("s/t", f"sp{i}")
    m.flush()
    assert canon(m.subscribers("s/t")) == canon(index.subscribers("s/t"))
    assert m.stats.folds >= 2, m.stats.as_dict()


def test_fold_empty_then_resubscribe_path():
    index = TopicsIndex()
    index.subscribe("a", Subscription(filter="e/1", qos=0))
    index.subscribe("b", Subscription(filter="e/2", qos=0))
    m = DeltaMatcher(index, background=False, max_levels=4)
    index.unsubscribe("e/1", "a")
    m.flush()
    assert canon(m.subscribers("e/1")) == canon(index.subscribers("e/1"))
    index.subscribe("c", Subscription(filter="e/1", qos=2))
    m.flush()
    assert canon(m.subscribers("e/1")) == canon(index.subscribers("e/1"))
    assert list(m.subscribers("e/1").subscriptions) == ["c"]
