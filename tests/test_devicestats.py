"""The per-device observability plane (ISSUE 18, mqtt_tpu.ops.
devicestats): skew math, the compile-event ledger's determinism and
attribution, labeled-family exposition on the 8-way CPU-jax mesh, the
profiler's per-device windows (parity vs the single-device aggregate
oracle), the steady-state recompile regression guard (the PR 11
incident), the /devices HTTP matrix, the devices_*.json dump sibling,
the shard-skew SLO objective end-to-end, and the /healthz degraded
entries. The suite-wide conftest forces 8 XLA host devices, so every
test here sees the MULTICHIP topology.
"""

import json
import time

import numpy as np
import pytest

from mqtt_tpu import Options
from mqtt_tpu.listeners import Config as LConfig, HTTPStats
from mqtt_tpu.ops.devicestats import (
    LEDGER,
    CompileLedger,
    DeviceStatsPlane,
    KernelWatch,
    set_watch_enabled,
    skew_of,
    watch_enabled,
)
from mqtt_tpu.packets import Subscription
from mqtt_tpu.telemetry import Telemetry, check_exposition
from mqtt_tpu.topics import SYS_PREFIX, TopicsIndex
from mqtt_tpu.tracing import BatchProfile, DeviceProfiler

from tests.test_server import Harness, run
from tests.test_telemetry import _http

jax = pytest.importorskip("jax")


def _mesh_matcher(n_subs: int = 40):
    from mqtt_tpu.parallel.sharded import ShardedTpuMatcher, make_mesh

    index = TopicsIndex()
    for i in range(n_subs):
        index.subscribe(f"c{i}", Subscription(filter=f"a/{i % 8}/b"))
        index.subscribe(f"w{i}", Subscription(filter=f"a/{i % 8}/+"))
    return ShardedTpuMatcher(index, mesh=make_mesh(jax.devices()[:8]))


# -- skew math ---------------------------------------------------------------


class TestSkewMath:
    def test_balanced_is_one(self):
        assert skew_of([100, 100, 100, 100]) == pytest.approx(1.0)

    def test_one_hot_tile_is_tile_count(self):
        assert skew_of([400, 0, 0, 0]) == pytest.approx(4.0)

    def test_crafted_distribution(self):
        assert skew_of([30, 10]) == pytest.approx(1.5)

    def test_no_traffic_and_empty_claim_nothing(self):
        assert skew_of([]) == 0.0
        assert skew_of([0, 0, 0]) == 0.0

    def test_numpy_input(self):
        assert skew_of(np.array([8, 4, 4], dtype=np.int64)) == pytest.approx(
            1.5
        )


# -- compile ledger ----------------------------------------------------------


class TestCompileLedger:
    def test_watch_notes_first_call_per_signature_only(self):
        led = CompileLedger()
        calls = []
        w = KernelWatch("k", lambda *a, **kw: calls.append(1), ledger=led)
        x = np.zeros((16, 4), np.int32)
        for _ in range(5):
            w(x, capacity=128)
        assert led.total() == 1 and led.count("k") == 1
        # a new shape OR a new static is a new compile event
        w(np.zeros((32, 4), np.int32), capacity=128)
        w(x, capacity=256)
        assert led.total() == 3
        assert len(calls) == 7  # the wrapped fn ran every time

    def test_attribution_names_kernel_and_shapes(self):
        led = CompileLedger()
        w = KernelWatch("flat_match_compact", lambda *a, **kw: None, ledger=led)
        since = led.total()
        w(np.zeros((64, 8), np.int32), capacity=512)
        text = led.attribution(since)
        assert "1 compile event(s)" in text
        assert "flat_match_compact[64x8,capacity=512]" in text
        assert led.attribution(led.total()) == "no compile events recorded"

    def test_disabled_watch_skips_signature_work_entirely(self):
        led = CompileLedger()
        w = KernelWatch("k", lambda *a, **kw: None, ledger=led)
        assert watch_enabled()
        set_watch_enabled(False)
        try:
            w(np.zeros((8,), np.int32))
            assert led.total() == 0
        finally:
            set_watch_enabled(True)
        w(np.zeros((8,), np.int32))
        assert led.total() == 1

    def test_registry_binding_exports_counter_and_histogram(self):
        led = CompileLedger()
        tele = Telemetry()
        led.bind_registry(tele.registry)
        w = KernelWatch("rules_eval", lambda *a, **kw: time.sleep(0.001), ledger=led)
        w(np.zeros((4,), np.int32))
        text = tele.exposition()
        assert 'mqtt_tpu_matcher_recompiles_total{kernel="rules_eval"} 1' in text
        assert "mqtt_tpu_matcher_compile_seconds_count 1" in text
        assert check_exposition(text) > 0

    def test_snapshot_shape(self):
        led = CompileLedger()
        led.note_compile("k1", "8x4", 0.25)
        led.note_compile("k2", "16x4", 0.5)
        snap = led.snapshot()
        assert snap["total"] == 2 and snap["kernels"] == {"k1": 1, "k2": 1}
        assert snap["recent"][-1]["kernel"] == "k2"
        assert snap["seconds"]["count"] == 2


# -- the PR 11 regression guard: steady-state recompiles == 0 ----------------


class TestRecompileGuard:
    def test_steady_state_recompiles_stay_flat(self):
        """Pinned capacity + batch sizes inside one pow2 bucket: after
        warmup the device matcher must never recompile — the exact
        silent-3x failure mode PR 11 hit. A failure prints the ledger's
        kernel/shape attribution so the regression is named, not just
        counted."""
        from mqtt_tpu.ops import TpuMatcher

        index = TopicsIndex()
        for i in range(60):
            index.subscribe(f"c{i}", Subscription(filter=f"s/{i % 12}/+"))
        m = TpuMatcher(index, max_levels=4, compact=True, compact_capacity=256)
        m.rebuild()
        topics = [f"s/{i % 12}/x" for i in range(200)]
        m.match_topics(topics)  # warmup: compiles the 256-topic bucket
        since = LEDGER.total()
        for b in (201, 223, 256, 199):  # all pad to the same 256 bucket
            m.match_topics([f"s/{i % 12}/y" for i in range(b)])
        delta = LEDGER.total() - since
        assert delta == 0, (
            f"steady-state recompiles must stay flat; got {delta}:\n"
            + LEDGER.attribution(since)
        )

    def test_capacity_churn_is_caught_with_attribution(self):
        """Deliberately defeat the capacity hysteresis (fresh capacity
        per dispatch, the pre-PR-11 behavior): the ledger must record
        the recompiles and attribute them to the compact kernel."""
        from mqtt_tpu.ops import TpuMatcher

        index = TopicsIndex()
        for i in range(60):
            index.subscribe(f"c{i}", Subscription(filter=f"s/{i % 12}/+"))
        # pinned capacity forces the compact path (_compact_pays) so the
        # churn below exercises the exact kernel PR 11 thrashed
        m = TpuMatcher(index, max_levels=4, compact=True, compact_capacity=64)
        m.rebuild()
        topics = [f"s/{i % 12}/x" for i in range(100)]
        m.match_topics(topics)  # warm the pinned capacity's executable
        # churn: odd capacities no other test compiles, one per dispatch
        caps = iter((24, 56, 24, 56))
        m._compact_capacity_for = lambda b, flat: next(caps)
        since = LEDGER.total()
        m.match_topics(topics)
        m.match_topics(topics)
        delta = LEDGER.total() - since
        assert delta >= 2, LEDGER.attribution(since)
        assert "flat_match_compact" in LEDGER.attribution(since)


# -- per-device profiler windows ---------------------------------------------


class TestPerDeviceWindows:
    @staticmethod
    def _feed(prof, devices, n=4, d2h_bytes=4096):
        t = time.perf_counter()
        for i in range(n):
            rec = BatchProfile()
            rec.devices = devices
            rec.d2h_bytes = d2h_bytes
            base = t + i * 1e-3
            prof.note_dispatch(rec, base, base + 2e-4)
            prof.note_resolve(rec, base + 3e-4, base + 4e-4)

    def test_single_device_window_matches_aggregate_oracle(self):
        """Window 0 of an unstamped (devices=None) run must be
        bit-identical to the pre-ISSUE-18 aggregate fold — the parity
        oracle that proves the per-device replica arithmetic."""
        prof = DeviceProfiler()
        self._feed(prof, None)
        agg = prof.bench_block()
        dev = prof.device_snapshot()
        assert list(dev.keys()) == [0]
        d0 = dev[0]
        assert d0["batches"] == agg["batches"] == 4
        assert d0["duty_cycle"] == agg["duty_cycle"]
        assert d0["overlap_ratio"] == agg["overlap_ratio"]
        assert d0["issue_p99_ms"] == agg["issue_p99_ms"]
        assert d0["d2h_p99_ms"] == agg["d2h_p99_ms"]
        assert d0["idle_gap_p99_ms"] == agg["idle_gap_p99_ms"]

    def test_multi_device_stamp_splits_bytes_evenly(self):
        prof = DeviceProfiler()
        self._feed(prof, (0, 1, 2, 3), n=2, d2h_bytes=8192)
        dev = prof.device_snapshot()
        assert sorted(dev.keys()) == [0, 1, 2, 3]
        for d in dev.values():
            assert d["batches"] == 2
            assert d["d2h_bytes_total"] == 2 * 8192 // 4

    def test_labeled_children_registered_once_per_device(self):
        tele = Telemetry()
        prof = DeviceProfiler(registry=tele.registry)
        self._feed(prof, (0, 1))
        self._feed(prof, (0, 1))
        text = tele.exposition()
        for did in ("0", "1"):
            assert f'mqtt_tpu_device_duty_cycle_ratio{{device="{did}"}}' in text
            assert f'device="{did}"' in text
        assert check_exposition(text) > 0


# -- the 8-way mesh: labeled families + skew + tiles end-to-end --------------


class TestMeshExposition:
    def test_all_eight_devices_and_tiles_exported(self):
        tele = Telemetry()
        plane = DeviceStatsPlane(registry=tele.registry)
        prof = DeviceProfiler(registry=tele.registry)
        plane.attach_profiler(prof)
        m = _mesh_matcher()
        m.profiler = prof
        plane.attach_matcher(m)
        for _ in range(3):
            m.match_topics([f"a/{i % 8}/b" for i in range(32)])
        text = tele.exposition()
        for did in range(8):
            assert f'mqtt_tpu_device_hbm_ratio{{device="{did}"}}' in text
            assert (
                f'mqtt_tpu_device_duty_cycle_ratio{{device="{did}"}}' in text
            )
        assert "mqtt_tpu_device_skew_ratio" in text
        assert "mqtt_tpu_device_d2h_bytes_bucket" in text
        for t in range(m.n_batch):
            assert f'mqtt_tpu_device_tile_hits_total{{tile="{t}"}}' in text
            assert f'tile="{t}"' in text
        assert "mqtt_tpu_matcher_recompiles_total" in text
        assert check_exposition(text) > 0

        snap = plane.snapshot()
        assert snap["n_devices"] == 8
        assert len(snap["devices"]) == 8
        assert all(d["batches"] >= 1 for d in snap["devices"])
        assert snap["skew"]["ratio"] > 0.0
        assert snap["compiles"]["total"] >= 1
        # an even workload across 8 sub-families lands near balanced
        assert plane.skew_ratio() == pytest.approx(1.0, abs=0.5)

    def test_crafted_imbalance_moves_the_gauge(self):
        m = _mesh_matcher()
        hits = np.full(m.n_batch, 10, dtype=np.int64)
        hits[0] = 300  # one hot tile
        m._fold_tile_hits(hits, cap_local=512)
        expected = skew_of(hits)
        assert m.device_skew_ratio() == pytest.approx(expected)
        # max/mean on an n-tile mesh tops out just below n; a 30x hot
        # tile must land well clear of balanced (1.0)
        assert expected > 1.5
        assert m.tile_hit_counts().tolist() == hits.tolist()
        # per-tile fill histograms saw one batch each at hits/cap
        assert m.tile_fill_hists[0].count == 1
        assert m.tile_fill_hists[0].percentile(0.5) >= 300 / 512

    def test_hbm_snapshot_graceful_on_cpu_backend(self):
        plane = DeviceStatsPlane()
        snap = plane.snapshot()
        for d in snap["devices"]:
            # CPU-jax either answers memory_stats or the plane degrades
            # to None/-1 sentinels — never a crash, never a fake number
            hbm = d["hbm"]
            assert set(hbm) == {"live_bytes", "peak_bytes", "limit_bytes", "ratio"}
        assert snap["hbm"]["degraded"] in (False,)
        tree = plane.sys_tree()
        assert "skew_ratio" in tree
        assert "0/hbm_live_bytes" in tree and "compiles/total" in tree


# -- /devices HTTP matrix ----------------------------------------------------


class TestDevicesEndpoint:
    def test_matrix(self):
        async def scenario():
            tele = Telemetry()
            plane = DeviceStatsPlane(registry=tele.registry)
            tele.attach_device_stats(plane)
            st = HTTPStats(
                LConfig(type="sysinfo", id="d", address="127.0.0.1:0"),
                None,
                telemetry=tele,
            )
            await st.init(None)
            host, port = st.address().rsplit(":", 1)
            data = await _http(host, port, "/devices")
            head, body = data.split(b"\r\n\r\n", 1)
            assert head.startswith(b"HTTP/1.1 200")
            assert b"Cache-Control: no-store" in head
            assert b"application/json" in head
            doc = json.loads(body)
            assert doc["n_devices"] == 8
            assert {d["id"] for d in doc["devices"]} == set(range(8))
            post = await _http(host, port, "/devices", "POST")
            assert post.startswith(b"HTTP/1.1 405") and b"Allow: GET" in post
            await st.close(lambda _: None)

        run(scenario())

    def test_404_without_plane(self):
        async def scenario():
            st = HTTPStats(
                LConfig(type="sysinfo", id="d", address="127.0.0.1:0"),
                None,
                telemetry=Telemetry(),
            )
            await st.init(None)
            host, port = st.address().rsplit(":", 1)
            assert (await _http(host, port, "/devices")).startswith(
                b"HTTP/1.1 404"
            )
            await st.close(lambda _: None)

        run(scenario())


# -- dump bundle + skew SLO end-to-end ---------------------------------------


class TestDumpAndSkewSLO:
    def test_trigger_dump_writes_devices_sibling(self, tmp_path):
        tele = Telemetry(dump_dir=str(tmp_path), dump_min_interval_s=0.0)
        plane = DeviceStatsPlane(registry=tele.registry)
        tele.attach_device_stats(plane)
        tele.trigger_dump("unit_test")
        tele.recorder.join_writer()
        flights = sorted(tmp_path.glob("flight_*.json"))
        devices = sorted(tmp_path.glob("devices_*.json"))
        assert len(flights) == 1 and len(devices) == 1
        # sibling naming: devices_<flight stem sans prefix>.json
        assert devices[0].name == "devices_" + flights[0].name[len("flight_"):]
        doc = json.load(open(devices[0]))
        assert doc["n_devices"] == 8 and "compiles" in doc

    def test_skew_objective_breach_fires_bundle(self, tmp_path):
        """The acceptance leg: a 'shard skew < 2.0' objective burning
        against the live gauge breaches, /healthz degrades with
        device_skew, and the dump bundle grows the devices sibling."""

        async def scenario():
            h = Harness(
                Options(
                    inline_client=True,
                    slo_objectives=["shard skew < 2.0 over 10s/40s"],
                    telemetry_dump_dir=str(tmp_path),
                )
            )
            srv = h.server
            plane = srv.device_stats
            assert plane is not None and srv.slo is not None
            obj = srv.slo.objectives[0]
            assert obj.kind == "gauge"
            assert obj.family == "mqtt_tpu_device_skew_ratio"

            class HotTile:
                @staticmethod
                def device_skew_ratio() -> float:
                    return 5.0  # one tile doing 5x its share

            plane.matcher = HotTile()
            srv.slo.evaluate(0.0)
            for i in range(1, 4):
                srv.slo.evaluate(float(5 * i))
            st = srv.slo.state()[obj.name]
            assert st["breached"] and st["value"] == pytest.approx(5.0)
            assert st["threshold"] == pytest.approx(2.0)

            ok, report = srv.health_report()
            assert ok is True  # degraded NEVER flips readiness
            assert "device_skew" in report["degraded"]
            assert report["devices"]["skew_ratio"] == pytest.approx(5.0)

            srv.telemetry.recorder.join_writer()
            assert sorted(tmp_path.glob("flight_*slo_breach*"))
            assert sorted(tmp_path.glob("devices_*slo_breach*"))

            # balance restored: the gauge drops, the breach clears
            plane.matcher = None
            for i in range(4, 40):
                srv.slo.evaluate(float(5 * i))
            assert not srv.slo.state()[obj.name]["breached"]
            assert "device_skew" not in srv.health_report()[1]["degraded"]
            await h.shutdown()

        run(scenario())


# -- /healthz device plane + $SYS tree ---------------------------------------


class TestHealthzDevices:
    def test_hbm_watermark_degrades_but_stays_ready(self):
        async def scenario():
            h = Harness(Options(inline_client=True, device_hbm_watermark=0.8))
            srv = h.server
            plane = srv.device_stats
            assert plane is not None
            ok, report = srv.health_report()
            assert ok is True
            assert "devices" in report and report["degraded"] == []

            plane.hbm_ratio = lambda: 0.93  # above the 0.8 watermark
            ok, report = srv.health_report()
            assert ok is True and report["not_ready"] == []
            assert "hbm_watermark" in report["degraded"]
            assert report["devices"]["hbm_ratio"] == pytest.approx(0.93)

            plane.hbm_ratio = lambda: 0.0  # backend can't answer: healthy
            assert "hbm_watermark" not in srv.health_report()[1]["degraded"]
            await h.shutdown()

        run(scenario())

    def test_device_stats_off_removes_plane_and_endpoint(self):
        async def scenario():
            h = Harness(Options(inline_client=True, device_stats=False))
            srv = h.server
            assert srv.device_stats is None
            assert "devices" not in srv.health_report()[1]
            await h.shutdown()

        run(scenario())

    def test_sys_tree_rows_published(self):
        async def scenario():
            h = Harness(Options(inline_client=True))
            srv = h.server
            srv.publish_sys_topics()
            pks = srv.topics.messages(SYS_PREFIX + "/broker/devices/#")
            tree = {p.topic_name: bytes(p.payload) for p in pks}
            assert tree, "devices $SYS tree must publish retained rows"
            assert SYS_PREFIX + "/broker/devices/skew_ratio" in tree
            assert SYS_PREFIX + "/broker/devices/compiles/total" in tree
            assert SYS_PREFIX + "/broker/devices/0/hbm_live_bytes" in tree
            await h.shutdown()

        run(scenario())
