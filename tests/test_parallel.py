"""Multi-chip sharded matcher: runs on the virtual 8-device CPU mesh
(conftest sets xla_force_host_platform_device_count=8) and must be
bit-identical to the host trie."""

import random

import jax
import pytest

from mqtt_tpu.packets import Subscription
from mqtt_tpu.topics import SHARE_PREFIX, TopicsIndex
from mqtt_tpu.parallel import ShardedTpuMatcher, dryrun_multichip, make_mesh

from tests.test_ops_matcher import canon


def test_eight_virtual_devices_present():
    assert len(jax.devices()) >= 8


def test_dryrun_multichip():
    dryrun_multichip(8)


def test_sharded_matches_host_oracle():
    rng = random.Random(31337)
    segs = ["a", "b", "c", "d", "", "x"]

    def rand_topic():
        return "/".join(rng.choice(segs) for _ in range(rng.randint(1, 5)))

    def rand_filter():
        parts = [rng.choice(segs + ["+"]) for _ in range(rng.randint(1, 5))]
        if rng.random() < 0.25:
            parts[-1] = "#"
        return "/".join(parts)

    index = TopicsIndex()
    for i in range(200):
        index.subscribe(f"cl{i}", Subscription(filter=rand_filter(), qos=rng.randint(0, 2)))
    for i in range(20):
        index.subscribe(
            f"sh{i}", Subscription(filter=f"{SHARE_PREFIX}/g{i % 3}/{rand_filter()}")
        )
    matcher = ShardedTpuMatcher(index, mesh=make_mesh(jax.devices()[:8]), max_levels=6)
    topics = [rand_topic() for _ in range(64)]
    for topic, dev in zip(topics, matcher.match_topics(topics)):
        assert canon(dev) == canon(index.subscribers(topic)), topic


def test_sharded_churn_rebuild():
    index = TopicsIndex()
    for i in range(50):
        index.subscribe(f"cl{i}", Subscription(filter=f"t/{i}"))
    matcher = ShardedTpuMatcher(index, mesh=make_mesh(jax.devices()[:4]))
    assert set(matcher.subscribers("t/7").subscriptions) == {"cl7"}
    index.unsubscribe("t/7", "cl7")
    index.subscribe("new", Subscription(filter="t/7"))
    assert matcher.stale
    assert set(matcher.subscribers("t/7").subscriptions) == {"new"}
