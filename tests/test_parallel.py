"""Multi-chip sharded matcher: runs on the virtual 8-device CPU mesh
(conftest sets xla_force_host_platform_device_count=8) and must be
bit-identical to the host trie."""

import random

import jax
import pytest

from mqtt_tpu.packets import Subscription
from mqtt_tpu.topics import SHARE_PREFIX, TopicsIndex
from mqtt_tpu.parallel import ShardedTpuMatcher, dryrun_multichip, make_mesh

from tests.test_ops_matcher import canon


def test_eight_virtual_devices_present():
    assert len(jax.devices()) >= 8


def test_dryrun_multichip():
    dryrun_multichip(8)


def test_sharded_matches_host_oracle():
    rng = random.Random(31337)
    segs = ["a", "b", "c", "d", "", "x"]

    def rand_topic():
        return "/".join(rng.choice(segs) for _ in range(rng.randint(1, 5)))

    def rand_filter():
        parts = [rng.choice(segs + ["+"]) for _ in range(rng.randint(1, 5))]
        if rng.random() < 0.25:
            parts[-1] = "#"
        return "/".join(parts)

    index = TopicsIndex()
    for i in range(200):
        index.subscribe(f"cl{i}", Subscription(filter=rand_filter(), qos=rng.randint(0, 2)))
    for i in range(20):
        index.subscribe(
            f"sh{i}", Subscription(filter=f"{SHARE_PREFIX}/g{i % 3}/{rand_filter()}")
        )
    matcher = ShardedTpuMatcher(index, mesh=make_mesh(jax.devices()[:8]), max_levels=6)
    topics = [rand_topic() for _ in range(64)]
    for topic, dev in zip(topics, matcher.match_topics(topics)):
        assert canon(dev) == canon(index.subscribers(topic)), topic


def test_sharded_churn_rebuild():
    index = TopicsIndex()
    for i in range(50):
        index.subscribe(f"cl{i}", Subscription(filter=f"t/{i}"))
    matcher = ShardedTpuMatcher(index, mesh=make_mesh(jax.devices()[:4]))
    try:
        assert set(matcher.subscribers("t/7").subscriptions) == {"cl7"}
        index.unsubscribe("t/7", "cl7")
        index.subscribe("new", Subscription(filter="t/7"))
        assert matcher.stale
        assert set(matcher.subscribers("t/7").subscriptions) == {"new"}
    finally:
        matcher.close()


def test_incremental_rebuild_touches_one_shard():
    """A single subscription mutation must dirty exactly the stable-hash
    shard that owns it, and the incremental rebuild must recompile only
    that shard's replica (VERDICT r1 weak #3/#4: round-robin resharding
    made every mutation a full rebuild)."""
    from mqtt_tpu.parallel.sharded import shard_of

    index = TopicsIndex()
    for i in range(100):
        index.subscribe(f"cl{i}", Subscription(filter=f"t/{i % 10}/{i}"))
    matcher = ShardedTpuMatcher(index, mesh=make_mesh(jax.devices()[:4]))
    try:
        matcher.rebuild()
        assert matcher._dirty == [False] * matcher.n_shards
        sizes_before = [c.num_subs for c in matcher._flats]

        sub = Subscription(filter="t/3/fresh", qos=1)
        index.subscribe("fresh", sub)
        owner = shard_of("sub", "fresh", "t/3/fresh", 0, matcher.n_shards)
        dirty = [s for s in range(matcher.n_shards) if matcher._dirty[s]]
        assert dirty == [owner]

        matcher.rebuild()
        sizes_after = [c.num_subs for c in matcher._flats]
        for s in range(matcher.n_shards):
            expected = sizes_before[s] + (1 if s == owner else 0)
            assert sizes_after[s] == expected
        assert set(matcher.subscribers("t/3/fresh").subscriptions) >= {"fresh"}

        # unsubscribe dirties the same shard and shrinks it back
        index.unsubscribe("t/3/fresh", "fresh")
        assert [s for s in range(matcher.n_shards) if matcher._dirty[s]] == [owner]
        matcher.rebuild()
        assert [c.num_subs for c in matcher._flats] == sizes_before
    finally:
        matcher.close()


def test_per_shard_compile_histograms_merge_at_scrape():
    """ISSUE 5 satellite: every shard compile records into its own
    shard-local Histogram (no cross-thread write sharing) and
    ``merged_shard_compile`` folds them into one scrape-time snapshot
    whose count equals the sum of the shards'."""
    index = TopicsIndex()
    for i in range(40):
        index.subscribe(f"cl{i}", Subscription(filter=f"a/{i}/+", qos=0))
    matcher = ShardedTpuMatcher(index, mesh=make_mesh(jax.devices()[:4]))
    try:
        matcher.rebuild()  # full build: every shard compiles at least once
        per_shard = [h.count for h in matcher.shard_compile_hists]
        assert sum(per_shard) >= matcher.n_shards
        assert all(n >= 1 for n in per_shard)
        merged = matcher.merged_shard_compile()
        assert merged.count == sum(per_shard)
        assert merged.sum == pytest.approx(
            sum(h.sum for h in matcher.shard_compile_hists)
        )
        # an incremental rebuild only touches the dirty shard's histogram
        index.subscribe("late", Subscription(filter="z/z", qos=0))
        matcher.rebuild()
        after = [h.count for h in matcher.shard_compile_hists]
        assert sum(after) == sum(per_shard) + 1, (per_shard, after)
    finally:
        matcher.close()


def test_stable_hash_assignment_is_churn_invariant():
    """The shard owning a subscription must not depend on what else is in
    the index (round-robin regression guard)."""
    from mqtt_tpu.parallel.sharded import shard_of

    before = shard_of("sub", "clX", "a/b/c", 0, 4)
    # identity-only inputs: any index contents are irrelevant by construction
    assert shard_of("sub", "clX", "a/b/c", 0, 4) == before
    assert shard_of("inline", "", "a/b/c", 7, 4) == shard_of("inline", "", "a/b/c", 7, 4)


def test_sharded_incremental_matches_oracle_under_churn():
    """Randomized subscribe/unsubscribe churn with incremental rebuilds
    after every mutation batch: results must stay bit-identical."""
    rng = random.Random(4242)
    segs = ["a", "b", "c", "", "x"]

    def rand_filter():
        parts = [rng.choice(segs + ["+"]) for _ in range(rng.randint(1, 4))]
        if rng.random() < 0.2:
            parts[-1] = "#"
        return "/".join(parts)

    def rand_topic():
        return "/".join(rng.choice(segs) for _ in range(rng.randint(1, 4)))

    index = TopicsIndex()
    live: list[tuple[str, str]] = []
    for i in range(150):
        f = rand_filter()
        index.subscribe(f"cl{i}", Subscription(filter=f, qos=rng.randint(0, 2)))
        live.append((f, f"cl{i}"))
    matcher = ShardedTpuMatcher(index, mesh=make_mesh(jax.devices()[:8]), max_levels=5)
    try:
        for round_ in range(6):
            for _ in range(10):
                if live and rng.random() < 0.4:
                    f, cl = live.pop(rng.randrange(len(live)))
                    index.unsubscribe(f, cl)
                else:
                    f = rand_filter()
                    cl = f"m{round_}x{rng.randint(0, 10**6)}"
                    index.subscribe(cl, Subscription(filter=f, qos=1))
                    live.append((f, cl))
            topics = [rand_topic() for _ in range(16)]
            for topic, dev in zip(topics, matcher.match_topics(topics)):
                assert canon(dev) == canon(index.subscribers(topic)), topic
    finally:
        matcher.close()


def test_delta_matcher_over_mesh():
    """DeltaMatcher(mesh=...) serves from the sharded snapshot, routes
    affected topics to host, and folds deltas per-shard on flush."""
    from mqtt_tpu.ops.delta import DeltaMatcher
    from tests.test_ops_matcher import canon as _canon

    index = TopicsIndex()
    for i in range(60):
        index.subscribe(f"cl{i}", Subscription(filter=f"room/{i % 6}/+"))
    m = DeltaMatcher(index, background=False, mesh=make_mesh(jax.devices()[:4]))
    try:
        assert _canon(m.subscribers("room/3/x")) == _canon(index.subscribers("room/3/x"))
        # post-snapshot mutations are visible immediately (overlay -> host)
        index.subscribe("newbie", Subscription(filter="room/3/#"))
        assert "newbie" in m.subscribers("room/3/x").subscriptions
        assert m.pending_deltas == 1
        m.flush()
        assert m.pending_deltas == 0
        # folded into the device snapshot now; still identical
        assert _canon(m.subscribers("room/3/x")) == _canon(index.subscribers("room/3/x"))
        assert m.stats.rebuilds >= 2
    finally:
        m.close()
    assert index._observers == []
