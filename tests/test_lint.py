"""brokerlint's own test suite: every rule proves it FIRES on a minimal
positive fixture and stays quiet on the matching negative, then the live
``mqtt_tpu/`` tree is asserted clean — which makes the lint pass part of
tier-1 (`make verify`), not an advisory side channel.

The fixtures double as rule documentation: each positive snippet is the
smallest version of the real defect class the rule encodes (see
README.md "Static analysis" for the incident history).
"""

import json
import subprocess
import sys
import textwrap

import pytest

from tools.brokerlint import DEFAULT_BASELINE, RULE_DOC, lint_paths
from tools.brokerlint.core import load_baseline, run, save_baseline
from tools.brokerlint.rules import FILE_RULES, PROJECT_RULES


def lint_snippet(tmp_path, source, rules):
    """Lint one snippet with a selected subset of rules; returns the rule
    ids that fired (duplicates collapsed)."""
    mod = tmp_path / "mod.py"
    mod.write_text(textwrap.dedent(source))
    picked = {r: FILE_RULES[r] for r in rules}
    new, _ = run([str(mod)], str(tmp_path), picked, {})
    return [f.rule for f in new], new


# -- R1: blocking calls under a held lock -----------------------------------


def test_r1_fires_on_sleep_under_lock(tmp_path):
    fired, _ = lint_snippet(
        tmp_path,
        """
        import threading, time

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self):
                with self._lock:
                    time.sleep(0.1)
        """,
        ["R1"],
    )
    assert fired == ["R1"]


def test_r1_fires_on_mkdtemp_and_thread_join_under_lock(tmp_path):
    # the FlightRecorder regression (PR 4): first-dump mkdtemp ran inside
    # the ring lock the event loop appends under
    fired, findings = lint_snippet(
        tmp_path,
        """
        import tempfile, threading

        class Rec:
            def __init__(self):
                self._lock = threading.Lock()

            def dump(self, writer_thread):
                with self._lock:
                    d = tempfile.mkdtemp(prefix="x_")
                    writer_thread.join(timeout=5)
                return d
        """,
        ["R1"],
    )
    assert fired == ["R1", "R1"]
    assert "mkdtemp" in findings[0].msg


def test_r1_fires_on_await_under_sync_lock(tmp_path):
    fired, _ = lint_snippet(
        tmp_path,
        """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            async def bad(self):
                with self._lock:
                    await self.flush()
        """,
        ["R1"],
    )
    assert fired == ["R1"]


def test_r1_quiet_on_callback_defined_directly_in_lock_scope(tmp_path):
    # a def sitting DIRECTLY in the with body only DEFINES the callback;
    # its blocking call runs later, outside this lock scope (regression:
    # _iter_scope used to prune nested defs only one level down)
    fired, _ = lint_snippet(
        tmp_path,
        """
        import threading, time

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def register(self):
                with self._lock:
                    def cb():
                        time.sleep(0.1)
                    self.on_event = cb
        """,
        ["R1"],
    )
    assert fired == []


def test_r1_quiet_on_io_outside_lock_and_str_join(tmp_path):
    fired, _ = lint_snippet(
        tmp_path,
        """
        import threading, time

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def good(self, parts):
                with self._lock:
                    snapshot = ",".join(parts)  # str.join is not a thread join
                time.sleep(0.1)
                return snapshot
        """,
        ["R1"],
    )
    assert fired == []


# -- R2: thread-reachable code touching the event loop ----------------------


def test_r2_fires_on_set_result_reachable_from_thread(tmp_path):
    fired, findings = lint_snippet(
        tmp_path,
        """
        import threading

        class B:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                self._finish()

            def _finish(self):
                self.fut.set_result(1)
        """,
        ["R2"],
    )
    assert fired == ["R2"]
    assert "call_soon_threadsafe" in findings[0].msg


def test_r2_quiet_when_routed_through_call_soon_threadsafe(tmp_path):
    fired, _ = lint_snippet(
        tmp_path,
        """
        import threading

        class B:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                # the cluster presence-wake pattern: hand the loop-side
                # mutation to the loop instead of performing it here
                self.loop.call_soon_threadsafe(self._wake)

            def _wake(self):
                self.fut.set_result(1)
        """,
        ["R2"],
    )
    assert fired == []


def test_r2_fires_on_partial_fix_direct_call_plus_scheduled(tmp_path):
    # the partial-fix shape: the threadsafe wake was added on one path
    # but a direct cross-thread call to the same function survives
    fired, _ = lint_snippet(
        tmp_path,
        """
        import threading

        class B:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self, fast):
                if fast:
                    self._wake()  # BUG: direct cross-thread loop mutation
                else:
                    self.loop.call_soon_threadsafe(self._wake)

            def _wake(self):
                self.fut.set_result(1)
        """,
        ["R2"],
    )
    assert fired == ["R2"]


def test_r2_quiet_without_thread_entry_points(tmp_path):
    fired, _ = lint_snippet(
        tmp_path,
        """
        class Loop:
            def resolve(self, fut):
                fut.set_result(1)  # loop-side completion is the normal case
        """,
        ["R2"],
    )
    assert fired == []


# -- R3: wall-clock time.time() ---------------------------------------------


def test_r3_fires_on_time_time(tmp_path):
    fired, _ = lint_snippet(
        tmp_path,
        """
        import time

        def latency():
            t0 = time.time()
            return time.time() - t0
        """,
        ["R3"],
    )
    assert fired == ["R3", "R3"]


def test_r3_quiet_on_monotonic_and_pragmad_wall_time(tmp_path):
    fired, _ = lint_snippet(
        tmp_path,
        """
        import time

        def ok():
            t0 = time.monotonic()
            started = int(time.time())  # brokerlint: ok=R3 persisted wall-clock stamp
            return time.perf_counter() - t0, started
        """,
        ["R3"],
    )
    assert fired == []


def test_r3_fires_on_from_import_alias(tmp_path):
    fired, _ = lint_snippet(
        tmp_path,
        """
        from time import time

        def bad():
            return time()
        """,
        ["R3"],
    )
    assert fired == ["R3"]


# -- R4: silent exception swallows ------------------------------------------


def test_r4_fires_on_silent_swallow_and_bare_except(tmp_path):
    fired, _ = lint_snippet(
        tmp_path,
        """
        def bad(fn):
            try:
                fn()
            except Exception:
                pass
            try:
                fn()
            except:
                return None
        """,
        ["R4"],
    )
    assert sorted(fired) == ["R4", "R4"]


def test_r4_quiet_on_logged_counted_or_fallback_handlers(tmp_path):
    fired, _ = lint_snippet(
        tmp_path,
        """
        import logging

        log = logging.getLogger(__name__)

        def ok(fn, stats):
            buffered = 1
            try:
                fn()
            except Exception:
                log.exception("fn failed")
            try:
                fn()
            except Exception:
                stats.errors += 1
            try:
                fn()
            except Exception:
                buffered = 0  # fallback value is an observable outcome
            return buffered
        """,
        ["R4"],
    )
    assert fired == []


# -- R5: observer callbacks under a held lock -------------------------------


def test_r5_fires_on_direct_observer_call_under_lock(tmp_path):
    fired, _ = lint_snippet(
        tmp_path,
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.on_change = None

            def bad(self):
                with self._lock:
                    if self.on_change is not None:
                        self.on_change()
        """,
        ["R5"],
    )
    assert fired == ["R5"]


def test_r5_fires_inside_locked_suffix_functions(tmp_path):
    # the breaker regression (PR 4): _trip_locked invoked on_trip while
    # record_failure still held the breaker lock
    fired, _ = lint_snippet(
        tmp_path,
        """
        class Breaker:
            def _trip_locked(self):
                cb = self.on_trip
                if cb is not None:
                    cb()
        """,
        ["R5"],
    )
    assert fired == ["R5"]


def test_r5_propagates_into_functions_only_called_under_locks(tmp_path):
    # the trie-notify shape: _fanout itself takes no lock, but its every
    # call site holds one
    fired, _ = lint_snippet(
        tmp_path,
        """
        import threading

        class Trie:
            def __init__(self):
                self._lock = threading.RLock()
                self._observers = []

            def _fanout(self, m):
                for fn in self._observers:
                    fn(m)

            def mutate(self, m):
                with self._lock:
                    self._fanout(m)
        """,
        ["R5"],
    )
    assert fired == ["R5"]


def test_r5_quiet_when_callback_fires_after_release(tmp_path):
    fired, _ = lint_snippet(
        tmp_path,
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.on_change = None

            def good(self):
                with self._lock:
                    cb = self.on_change
                if cb is not None:
                    cb()
        """,
        ["R5"],
    )
    assert fired == []


# -- R6: metric catalog + naming scheme (project rule) ----------------------


def run_r6(tmp_path, module_src, catalog_names):
    (tmp_path / "m.py").write_text(textwrap.dedent(module_src))
    rows = "\n".join(f"| `{n}` | x | x | x |" for n in catalog_names)
    (tmp_path / "README.md").write_text(
        "Metrics catalog (`mqtt_tpu_` prefix elided):\n\n"
        "| name | type | labels | source |\n| --- | --- | --- | --- |\n"
        + rows + "\n"
    )
    new, _ = run([str(tmp_path / "m.py")], str(tmp_path), {}, PROJECT_RULES)
    return new


def test_r6_fires_on_catalog_drift_both_directions(tmp_path):
    findings = run_r6(
        tmp_path,
        """
        def wire(r):
            r.counter("mqtt_tpu_undocumented_total", "absent from catalog")
        """,
        ["documented_only_total"],
    )
    msgs = [f.msg for f in findings]
    assert any("missing from the README" in m for m in msgs)
    assert any("no code registers a matching metric" in m for m in msgs)


def test_r6_fires_on_naming_scheme_violations(tmp_path):
    findings = run_r6(
        tmp_path,
        """
        def wire(r):
            r.counter("mqtt_tpu_events", "counter without _total")
            r.histogram("mqtt_tpu_latency", "histogram without a unit")
            r.gauge("mqtt_tpu_depth_total", "gauge masquerading as counter")
        """,
        ["events", "latency", "depth_total"],
    )
    assert len([f for f in findings if f.rule == "R6"]) == 3


def test_r6_quiet_on_catalog_globs_and_loop_registration(tmp_path):
    findings = run_r6(
        tmp_path,
        """
        def wire(r):
            r.counter("mqtt_tpu_messages_in_total", "x")
            for name, attr in (
                ("mqtt_tpu_messages_out_total", "out"),
            ):
                r.counter(name, "mirror")
            r.histogram("mqtt_tpu_wait_seconds", "x")
        """,
        ["messages_*_total", "wait_seconds"],
    )
    assert findings == []


# -- R7: thread daemon/tracking discipline ----------------------------------


def test_r7_fires_on_missing_daemon_and_unbound_thread(tmp_path):
    fired, _ = lint_snippet(
        tmp_path,
        """
        import threading

        def bad(fn):
            threading.Thread(target=fn).start()
        """,
        ["R7"],
    )
    assert sorted(fired) == ["R7", "R7"]  # no daemon=, no binding


def test_r7_quiet_on_bound_explicit_daemon_thread(tmp_path):
    fired, _ = lint_snippet(
        tmp_path,
        """
        import threading

        class P:
            def start(self, fn):
                self._t = threading.Thread(target=fn, daemon=True)
                self._t.start()
                self._writers.append(
                    threading.Thread(target=fn, daemon=True)
                )
        """,
        ["R7"],
    )
    assert fired == []


# -- R8: mutable defaults / module singletons -------------------------------


def test_r8_fires_on_mutable_default_and_module_singleton(tmp_path):
    fired, _ = lint_snippet(
        tmp_path,
        """
        _CACHE = []

        def bad(items=[]):
            _CACHE.append(items)
        """,
        ["R8"],
    )
    assert sorted(fired) == ["R8", "R8"]


def test_r8_quiet_on_none_default_and_constant_tables(tmp_path):
    fired, _ = lint_snippet(
        tmp_path,
        """
        _TABLE = {"a": 1}  # populated constant lookup table
        _FROZEN = (1, 2)

        def ok(items=None):
            return _TABLE, _FROZEN, items
        """,
        ["R8"],
    )
    assert fired == []


# -- R9: whole-program lock-order graph (project rule) -----------------------


def run_r9(tmp_path, module_src):
    (tmp_path / "m.py").write_text(textwrap.dedent(module_src))
    new, _ = run(
        [str(tmp_path / "m.py")], str(tmp_path), {},
        {"R9": PROJECT_RULES["R9"]},
    )
    return new


CYCLE_SRC = """
    import threading

    class A:
        def __init__(self):
            self._alpha_lock = threading.Lock()
            self._beta_lock = threading.Lock()

        def one(self):
            with self._alpha_lock:
                with self._beta_lock:
                    pass

        def two(self):
            with self._beta_lock:
                with self._alpha_lock:{pragma}
                    pass
"""


def test_r9_fires_on_anonymous_lock_cycle(tmp_path):
    findings = run_r9(tmp_path, CYCLE_SRC.format(pragma=""))
    assert [f.rule for f in findings] and all(f.rule == "R9" for f in findings)
    assert any("cycle" in f.msg for f in findings)


def test_r9_quiet_on_consistent_order(tmp_path):
    findings = run_r9(
        tmp_path,
        """
        import threading

        class A:
            def __init__(self):
                self._alpha_lock = threading.Lock()
                self._beta_lock = threading.Lock()

            def one(self):
                with self._alpha_lock:
                    with self._beta_lock:
                        pass

            def two(self):
                with self._alpha_lock:
                    with self._beta_lock:
                        pass
        """,
    )
    assert findings == []


def test_r9_fires_on_reversed_blessed_edge(tmp_path):
    # metrics_registry (last in LOCK_ORDER) must never wrap a governor
    # acquisition — the scrape-path-calls-into-the-control-plane shape
    findings = run_r9(
        tmp_path,
        """
        from mqtt_tpu.utils.locked import InstrumentedLock

        class Registry:
            def __init__(self, gov: "Gov"):
                self._lock = InstrumentedLock("metrics_registry")
                self.gov = gov

            def bad(self):
                with self._lock:
                    with self.gov._lock:
                        pass

        class Gov:
            def __init__(self):
                self._lock = InstrumentedLock("overload_governor")
        """,
    )
    assert any("reversed" in f.msg for f in findings)


def test_r9_propagates_one_call_level(tmp_path):
    # the cycle only exists through the call: locked_path() holds the
    # gate lock while _touch() takes the inner one
    findings = run_r9(
        tmp_path,
        """
        import threading

        class B:
            def __init__(self):
                self._gate_lock = threading.Lock()
                self._inner_lock = threading.Lock()

            def locked_path(self):
                with self._gate_lock:
                    self._touch()

            def _touch(self):
                with self._inner_lock:
                    pass

            def reverse(self):
                with self._inner_lock:
                    with self._gate_lock:
                        pass
        """,
    )
    assert any(f.rule == "R9" and "cycle" in f.msg for f in findings)


def test_r9_param_named_rlock_reentry_is_not_a_cycle(tmp_path):
    # a parameter-named RLock resolves to EVERY name it can carry; one
    # scope still holds exactly one of them, so legal same-instance
    # re-entry through a helper must not fabricate cross-name edges
    # between the alternatives (review regression — the TopicsIndex
    # lock_name shape)
    findings = run_r9(
        tmp_path,
        """
        import threading
        from mqtt_tpu.utils.locked import InstrumentedLock

        class Trie:
            def __init__(self, lock_name: str = "topics_trie") -> None:
                self._lock = InstrumentedLock(lock_name, rlock=True)

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass

        def make_remote():
            return Trie(lock_name="cluster_remote_trie")
        """,
    )
    assert findings == []


def test_r9_lock_graph_export_survives_syntax_error(tmp_path):
    # a committed syntax error must surface as the PARSE finding, not
    # crash the --lock-graph export mid-JSON (review regression)
    (tmp_path / "ok.py").write_text("import threading\n")
    (tmp_path / "broken.py").write_text("def oops(:\n")
    out = tmp_path / "artifacts"
    r = subprocess.run(
        [sys.executable, "-m", "tools.brokerlint", str(tmp_path),
         "--root", str(tmp_path), "--baseline", str(tmp_path / "bl.json"),
         "--json", "--lock-graph", str(out)],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 1  # the PARSE finding fails the run...
    payload = json.loads(r.stdout)  # ...but the JSON is still complete
    assert any(f["rule"] == "PARSE" for f in payload["findings"])
    assert (out / "lockgraph.json").exists()


def test_r9_callback_defined_inside_with_block_is_not_held(tmp_path):
    # a def nested INSIDE the with block runs later, not under the
    # lock: no phantom edge, no false cycle (review regression — the
    # first fix only covered the call-propagation path)
    findings = run_r9(
        tmp_path,
        """
        import threading

        class G:
            def __init__(self):
                self._outer_lock = threading.Lock()
                self._inner_lock = threading.Lock()

            def register(self):
                with self._outer_lock:
                    def cb():
                        with self._inner_lock:
                            pass
                    self.on_event = cb

            def legit(self):
                with self._inner_lock:
                    with self._outer_lock:
                        pass
        """,
    )
    assert findings == []


def test_r9_module_level_with_statements_are_scanned(tmp_path):
    # module-scope lock nesting executes at import time and is part of
    # the whole-program order; a reversed nesting elsewhere is a real
    # AB-BA cycle (review regression — module body used to be skipped)
    findings = run_r9(
        tmp_path,
        """
        import threading

        _g_lock = threading.Lock()
        _h_lock = threading.Lock()

        with _g_lock:
            with _h_lock:
                pass

        def reverse():
            with _h_lock:
                with _g_lock:
                    pass
        """,
    )
    assert any(f.rule == "R9" and "cycle" in f.msg for f in findings)


def test_r9_scans_duplicate_class_names_in_every_file(tmp_path):
    # two modules defining the same class name: BOTH bodies must be
    # scanned — a cycle in the second must not hide behind the first
    # (review regression: first-definition-wins used to skip it)
    (tmp_path / "first.py").write_text(textwrap.dedent(
        """
        class Dup:
            def harmless(self):
                return 1
        """
    ))
    (tmp_path / "second.py").write_text(textwrap.dedent(
        """
        import threading

        class Dup:
            def __init__(self):
                self._p_lock = threading.Lock()
                self._q_lock = threading.Lock()

            def one(self):
                with self._p_lock:
                    with self._q_lock:
                        pass

            def two(self):
                with self._q_lock:
                    with self._p_lock:
                        pass
        """
    ))
    new, _ = run(
        [str(tmp_path / "first.py"), str(tmp_path / "second.py")],
        str(tmp_path), {}, {"R9": PROJECT_RULES["R9"]},
    )
    assert any(f.rule == "R9" and "cycle" in f.msg for f in new)


def test_r9_unblessed_lock_baseline_keys_are_per_lock(tmp_path):
    # baselining ONE unblessed lock must not suppress a DIFFERENT one in
    # the same file (review regression: empty context collapsed them)
    mod = tmp_path / "m.py"
    mod.write_text(textwrap.dedent(
        """
        from mqtt_tpu.utils.locked import InstrumentedLock

        class C:
            def __init__(self):
                self._lock = InstrumentedLock("first_unblessed")
        """
    ))
    rules = {"R9": PROJECT_RULES["R9"]}
    new, _ = run([str(mod)], str(tmp_path), {}, rules)
    bl = tmp_path / "bl.json"
    save_baseline(str(bl), new)
    mod.write_text(textwrap.dedent(
        """
        from mqtt_tpu.utils.locked import InstrumentedLock

        class C:
            def __init__(self):
                self._lock = InstrumentedLock("first_unblessed")
                self._other_lock = InstrumentedLock("second_unblessed")
        """
    ))
    new2, old2 = run(
        [str(mod)], str(tmp_path), {}, rules, baseline=load_baseline(str(bl))
    )
    assert any("second_unblessed" in f.msg for f in new2), new2
    assert all("first_unblessed" not in f.msg for f in new2)


def test_r9_fires_on_unblessed_named_lock(tmp_path):
    findings = run_r9(
        tmp_path,
        """
        from mqtt_tpu.utils.locked import InstrumentedLock

        class C:
            def __init__(self):
                self._lock = InstrumentedLock("nobody_blessed_me")
        """,
    )
    assert any("LOCK_ORDER" in f.msg for f in findings)


def test_r9_multi_item_with_orders_left_to_right(tmp_path):
    # `with a, b:` acquires left-to-right; reversed nesting elsewhere is
    # a genuine AB-BA cycle and must fire (review regression)
    findings = run_r9(
        tmp_path,
        """
        import threading

        class E:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def one(self):
                with self._a_lock, self._b_lock:
                    pass

            def two(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
        """,
    )
    assert any(f.rule == "R9" and "cycle" in f.msg for f in findings)


def test_r9_callback_definition_is_not_an_acquisition(tmp_path):
    # a method that only DEFINES a callback taking a lock (the server's
    # _trip_dump registration shape) must not be credited with that
    # acquisition — the phantom edge would fabricate a cycle here
    # (review regression)
    findings = run_r9(
        tmp_path,
        """
        import threading

        class F:
            def __init__(self):
                self._outer_lock = threading.Lock()
                self._inner_lock = threading.Lock()

            def register(self):
                def cb():
                    with self._inner_lock:
                        pass
                self.on_event = cb

            def outer_path(self):
                with self._outer_lock:
                    self.register()

            def legit(self):
                with self._inner_lock:
                    with self._outer_lock:
                        pass
        """,
    )
    assert findings == []


def test_r9_locked_suffix_scope_counts_as_held(tmp_path):
    findings = run_r9(
        tmp_path,
        """
        import threading

        class D:
            def __init__(self):
                self._lock = threading.Lock()
                self._aux_lock = threading.Lock()

            def _step_locked(self):
                with self._aux_lock:
                    pass

            def other(self):
                with self._aux_lock:
                    with self._lock:
                        pass
        """,
    )
    assert any(f.rule == "R9" and "cycle" in f.msg for f in findings)


def test_r9_reasoned_pragma_suppresses_and_reasonless_does_not(tmp_path):
    ok = run_r9(
        tmp_path,
        CYCLE_SRC.format(
            pragma="  # brokerlint: ok=R9 proven single-threaded in tests"
        ),
    )
    # the pragma'd site is suppressed; the cycle seen from the OTHER
    # direction still reports (the cycle genuinely still exists)
    assert all(f.line != 16 for f in ok)
    (tmp_path / "m.py").write_text(
        textwrap.dedent(CYCLE_SRC.format(pragma="  # brokerlint: ok=R9"))
    )
    new, _ = run(
        [str(tmp_path / "m.py")], str(tmp_path), {},
        {"R9": PROJECT_RULES["R9"]},
    )
    assert any(f.rule == "PRAGMA" for f in new)


def test_r9_baseline_and_json_round_trip(tmp_path):
    """R9 rides the identical --json/--write-baseline machinery as
    R1-R8: findings appear in JSON, grandfather into a baseline, and
    vanish from the next run."""
    mod = tmp_path / "m.py"
    mod.write_text(textwrap.dedent(CYCLE_SRC.format(pragma="")))
    bl = tmp_path / "bl.json"
    base = [
        sys.executable, "-m", "tools.brokerlint", str(mod),
        "--root", str(tmp_path), "--baseline", str(bl),
    ]
    r = subprocess.run(base + ["--json"], capture_output=True, text=True,
                       timeout=120)
    assert r.returncode == 1
    payload = json.loads(r.stdout)
    assert any(f["rule"] == "R9" for f in payload["findings"])
    r = subprocess.run(base + ["--write-baseline"], capture_output=True,
                       text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(base + ["--json"], capture_output=True, text=True,
                       timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    payload = json.loads(r.stdout)
    assert payload["findings"] == [] and payload["baselined"] > 0


def test_lock_graph_export_artifacts(tmp_path):
    """--lock-graph writes Graphviz + JSON artifacts for the CI upload;
    the JSON carries the blessed order, every named lock, and the edge
    sites."""
    out = tmp_path / "artifacts"
    r = subprocess.run(
        [sys.executable, "-m", "tools.brokerlint", "mqtt_tpu",
         "--lock-graph", str(out)],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    dot = (out / "lockgraph.dot").read_text()
    assert dot.startswith("digraph lockorder")
    data = json.loads((out / "lockgraph.json").read_text())
    from tools.brokerlint.lockgraph import LOCK_ORDER

    assert data["order"] == list(LOCK_ORDER)
    names = {n["name"] for n in data["nodes"]}
    assert set(LOCK_ORDER) <= names
    assert data["cycles"] == []
    edges = {(e["src"], e["dst"]) for e in data["edges"]}
    assert ("topics_trie", "retained") in edges
    assert all(e["sites"] for e in data["edges"])


def test_loop_graph_export_artifacts(tmp_path):
    """--loop-graph writes Graphviz + JSON artifacts beside the lock
    graph; the JSON carries the blessed affinity table, the discovered
    owner-attach sites, and the live (evidence-backed) seams."""
    out = tmp_path / "artifacts"
    r = subprocess.run(
        [sys.executable, "-m", "tools.brokerlint", "mqtt_tpu",
         "--loop-graph", str(out)],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    dot = (out / "loopgraph.dot").read_text()
    assert dot.startswith("digraph loopaffinity")
    data = json.loads((out / "loopgraph.json").read_text())
    from tools.brokerlint.loopgraph import LOOP_AFFINITY

    assert data["affinity"] == [list(p) for p in LOOP_AFFINITY]
    # the live tree must supply owner-attach evidence for the core kinds
    assert {"outbound_queue", "match_stage", "shard_task"} <= set(
        data["owners"]
    )
    for sites in data["owners"].values():
        assert all(s["path"] and s["line"] > 0 for s in sites)
    # every live seam is a blessed pair (the zz gate's static half)
    seams = {tuple(p) for p in data["seams"]}
    assert seams <= set(LOOP_AFFINITY)
    assert ("outbound_queue", "put_local") in seams


# -- R10: foreign-thread mutation of loop-affine objects ---------------------


def test_r10_fires_on_thread_reachable_event_set(tmp_path):
    # the generalized R2 shape: an asyncio.Event owned by a shard loop
    # set() directly from a worker thread (the pre-fix delta-poller bug
    # class) instead of via call_soon_threadsafe
    fired, findings = lint_snippet(
        tmp_path,
        """
        import threading

        class Poller:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                self._stopped.set()
        """,
        ["R10"],
    )
    assert fired == ["R10"]
    assert "call_soon_threadsafe" in findings[0].msg


def test_r10_quiet_on_threading_event(tmp_path):
    # the delta.py/resilience.py real shape: the event IS a
    # threading.Event, thread-safe by construction — foreign set() is
    # the intended use
    fired, _ = lint_snippet(
        tmp_path,
        """
        import threading

        class Poller:
            def __init__(self):
                self._stopped = threading.Event()

            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                self._stopped.set()
        """,
        ["R10"],
    )
    assert fired == []


def test_r10_quiet_without_thread_entry(tmp_path):
    fired, _ = lint_snippet(
        tmp_path,
        """
        class Loop:
            def stop(self):
                self._stopped.set()
        """,
        ["R10"],
    )
    assert fired == []


def test_r10_fires_on_writer_close_and_task_cancel_from_thread(tmp_path):
    fired, _ = lint_snippet(
        tmp_path,
        """
        import threading

        class Reaper:
            def start(self):
                threading.Thread(target=self._reap).start()

            def _reap(self):
                self._writer.close()
                self._tick_task.cancel()
        """,
        ["R10"],
    )
    assert fired == ["R10", "R10"]


# -- R11: blocking calls in async bodies / loop callbacks --------------------


def test_r11_fires_on_sleep_in_async_def(tmp_path):
    fired, findings = lint_snippet(
        tmp_path,
        """
        import time

        async def handler():
            time.sleep(0.1)
        """,
        ["R11"],
    )
    assert fired == ["R11"]
    assert "stalls" in findings[0].msg


def test_r11_fires_on_untimed_acquire_in_loop_callback(tmp_path):
    # the sync body runs ON the loop because it is scheduled with
    # call_soon — async-context rules apply to it too
    fired, _ = lint_snippet(
        tmp_path,
        """
        import threading

        class Stage:
            def __init__(self):
                self._lock = threading.Lock()

            def submit(self, loop):
                loop.call_soon_threadsafe(self._drain)

            def _drain(self):
                self._lock.acquire()
        """,
        ["R11"],
    )
    assert fired == ["R11"]


def test_r11_fires_on_storage_append_in_async_def(tmp_path):
    # storage hooks hit the durability path (fsync under
    # durability_fsync=always): never inline on a loop
    fired, _ = lint_snippet(
        tmp_path,
        """
        async def persist(self, rec):
            self._store.append(rec)
        """,
        ["R11"],
    )
    assert fired == ["R11"]


def test_r11_quiet_on_bounded_acquire_and_executor(tmp_path):
    fired, _ = lint_snippet(
        tmp_path,
        """
        import threading

        class Stage:
            def __init__(self):
                self._lock = threading.Lock()

            def submit(self, loop):
                loop.call_soon_threadsafe(self._drain)

            def _drain(self):
                if self._lock.acquire(timeout=0.5):
                    self._lock.release()

        async def persist(loop, store, rec):
            await loop.run_in_executor(None, store.append, rec)
        """,
        ["R11"],
    )
    assert fired == []


# -- R12: future resolution loop discipline ----------------------------------


def test_r12_fires_on_unguarded_set_exception(tmp_path):
    # the staging._fallback_all defect this rule found live (PR 19):
    # rejecting parked futures inline on the stage's thread runs their
    # done-callbacks cross-loop
    fired, findings = lint_snippet(
        tmp_path,
        """
        class Stage:
            def _fallback_all(self, exc):
                for fut in self._pending:
                    fut.set_exception(exc)
        """,
        ["R12"],
    )
    assert fired == ["R12"]
    assert "marshal" in findings[0].msg


def test_r12_quiet_under_loop_identity_guard(tmp_path):
    fired, _ = lint_snippet(
        tmp_path,
        """
        import asyncio

        class Stage:
            def _resolve(self, fut, val):
                if fut.get_loop() is asyncio.get_running_loop():
                    fut.set_result(val)
                else:
                    fut.get_loop().call_soon_threadsafe(fut.set_result, val)
        """,
        ["R12"],
    )
    assert fired == []


def test_r12_quiet_when_resolver_is_itself_marshaled(tmp_path):
    # the resolver body IS the marshal seam: it only ever runs on the
    # target loop because every reference to it rides call_soon*
    fired, _ = lint_snippet(
        tmp_path,
        """
        class Stage:
            def _resolve(self, loop):
                loop.call_soon_threadsafe(self._finish)

            def _finish(self):
                self.fut.set_result(1)
        """,
        ["R12"],
    )
    assert fired == []


def test_r12_quiet_on_locally_created_future(tmp_path):
    # same-scope create_future + resolve never crosses a loop
    fired, _ = lint_snippet(
        tmp_path,
        """
        class Stage:
            def park(self, loop):
                fut = loop.create_future()
                fut.set_result(1)
                return fut
        """,
        ["R12"],
    )
    assert fired == []


# -- R13: spawned tasks must be tracked --------------------------------------


def test_r13_fires_on_fire_and_forget_create_task(tmp_path):
    # the server.inject_packet defect this rule found live (PR 19): the
    # bridged fan-out task held no reference and could be GC'd mid-flight
    fired, findings = lint_snippet(
        tmp_path,
        """
        async def inject(loop, coro):
            loop.create_task(coro)
        """,
        ["R13"],
    )
    assert fired == ["R13"]
    assert "weak reference" in findings[0].msg


def test_r13_quiet_on_tracked_spawns(tmp_path):
    fired, _ = lint_snippet(
        tmp_path,
        """
        import asyncio

        async def spawn(shard, loop, coros):
            task = loop.create_task(coros[0])
            shard.track(loop.create_task(coros[1]))
            acks = [asyncio.ensure_future(c) for c in coros]
            return task, acks
        """,
        ["R13"],
    )
    assert fired == []


# -- R14: await/blocking under a lock, one call level deep -------------------


def test_r14_fires_on_blocking_call_in_lock_only_function(tmp_path):
    fired, findings = lint_snippet(
        tmp_path,
        """
        import threading, time

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self._flush()

            def _flush(self):
                time.sleep(0.1)
        """,
        ["R14"],
    )
    assert fired == ["R14"]
    assert "one call level deep" in findings[0].msg


def test_r14_fires_on_await_in_lock_only_function(tmp_path):
    fired, _ = lint_snippet(
        tmp_path,
        """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            async def outer(self):
                with self._lock:
                    await self._flush()

            async def _flush(self):
                await self.writer.drain()
        """,
        ["R14"],
    )
    assert fired == ["R14"]


def test_r14_quiet_when_also_called_outside_locks(tmp_path):
    # a lock-free call site means the function is NOT a lock-held scope
    fired, _ = lint_snippet(
        tmp_path,
        """
        import threading, time

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self._flush()

            def direct(self):
                self._flush()

            def _flush(self):
                time.sleep(0.1)
        """,
        ["R14"],
    )
    assert fired == []


# -- R15: implicit D2H syncs on the device hot path --------------------------


def lint_ops_snippet(tmp_path, source, rel="mqtt_tpu/ops/x.py"):
    """R15 gates on the file's repo-relative path, so its fixtures must
    live under the scoped subtree of the lint root."""
    mod = tmp_path / rel
    mod.parent.mkdir(parents=True, exist_ok=True)
    mod.write_text(textwrap.dedent(source))
    new, _ = run([str(mod)], str(tmp_path), {"R15": FILE_RULES["R15"]}, {})
    return [f.rule for f in new], new


def test_r15_fires_on_implicit_d2h_syncs(tmp_path):
    fired, findings = lint_ops_snippet(
        tmp_path,
        """
        import jax
        import numpy as np

        def resolve(out_dev):
            n = out_dev.sum().item()
            host = np.asarray(out_dev)
            got = jax.device_get(out_dev)
            return n, host, got, float(out_dev[0])
        """,
    )
    assert fired == ["R15"] * 4
    assert any("blocking" in f.msg for f in findings)


def test_r15_quiet_on_host_arrays_and_outside_scope(tmp_path):
    # host-named arrays don't trip the device heuristic...
    fired, _ = lint_ops_snippet(
        tmp_path,
        """
        import numpy as np

        def pack(ids):
            return np.asarray(ids, dtype=np.int32)
        """,
    )
    assert fired == []
    # ...and the same D2H shapes OUTSIDE ops//sharded.py are not R15's
    # business (hooks and tests read scalars freely)
    fired, _ = lint_ops_snippet(
        tmp_path,
        """
        def read(out_dev):
            return out_dev.item()
        """,
        rel="mqtt_tpu/hooks/h.py",
    )
    assert fired == []


def test_r15_pragma_round_trip(tmp_path):
    # a reasoned pragma blesses the ONE batched resolve seam; the same
    # pragma without a reason is itself a finding (the R3 contract,
    # checked for a loop-rule id)
    src = """
        import numpy as np

        def resolve(out_dev):
            return np.asarray(out_dev)  # brokerlint: ok=R15 {reason}
    """
    fired, _ = lint_ops_snippet(
        tmp_path, src.format(reason="the one batched D2H at the resolve seam")
    )
    assert fired == []
    fired, _ = lint_ops_snippet(tmp_path, src.format(reason=""))
    assert sorted(fired) == ["PRAGMA", "R15"]


# -- lockgraph callback propagation (the PR 10 residual, closed) -------------


def test_r9_propagates_through_registered_callback(tmp_path):
    # a callback registered as an observer attribute and FIRED under a
    # lock contributes its own acquisitions to the firing site's edge
    # set: retained -> topics_trie here reverses the blessed order
    findings = run_r9(
        tmp_path,
        """
        from mqtt_tpu.utils.locked import InstrumentedLock

        class Store:
            def __init__(self):
                self._fire_lock = InstrumentedLock("retained")
                self._note_lock = InstrumentedLock("topics_trie")
                self.on_change = self._note

            def mutate(self):
                with self._fire_lock:
                    self.on_change()

            def _note(self):
                with self._note_lock:
                    pass
        """,
    )
    assert any(f.rule == "R9" and "reversed" in f.msg for f in findings)


def test_r9_callback_propagation_quiet_on_blessed_order(tmp_path):
    findings = run_r9(
        tmp_path,
        """
        from mqtt_tpu.utils.locked import InstrumentedLock

        class Store:
            def __init__(self):
                self._fire_lock = InstrumentedLock("topics_trie")
                self._note_lock = InstrumentedLock("retained")
                self.on_change = self._note

            def mutate(self):
                with self._fire_lock:
                    self.on_change()

            def _note(self):
                with self._note_lock:
                    pass
        """,
    )
    assert findings == []


def test_r9_propagates_through_callback_container(tmp_path):
    # the container shape: registered via .append, fired by subscript
    # over the observer-named container
    findings = run_r9(
        tmp_path,
        """
        from mqtt_tpu.utils.locked import InstrumentedLock

        class Bus:
            def __init__(self):
                self._fire_lock = InstrumentedLock("retained")
                self._note_lock = InstrumentedLock("topics_trie")
                self._observers = []
                self._observers.append(self._note)

            def mutate(self):
                with self._fire_lock:
                    self._observers[0]()

            def _note(self):
                with self._note_lock:
                    pass
        """,
    )
    assert any(f.rule == "R9" and "reversed" in f.msg for f in findings)


# -- pragmas and baseline ---------------------------------------------------


def test_pragma_without_reason_is_itself_a_finding(tmp_path):
    fired, _ = lint_snippet(
        tmp_path,
        """
        import time

        def f():
            return time.time()  # brokerlint: ok=R3
        """,
        ["R3"],
    )
    assert sorted(fired) == ["PRAGMA", "R3"]  # unreasoned pragma suppresses nothing


def test_baseline_suppresses_known_findings(tmp_path):
    mod = tmp_path / "mod.py"
    mod.write_text("import time\n\ndef f():\n    return time.time()\n")
    new, old = run([str(mod)], str(tmp_path), {"R3": FILE_RULES["R3"]}, {})
    assert [f.rule for f in new] == ["R3"] and old == []
    bl = tmp_path / "baseline.json"
    save_baseline(str(bl), new)
    new2, old2 = run(
        [str(mod)], str(tmp_path), {"R3": FILE_RULES["R3"]}, {},
        baseline=load_baseline(str(bl)),
    )
    assert new2 == [] and [f.rule for f in old2] == ["R3"]


# -- the enforcing gates ----------------------------------------------------


def test_live_tree_is_clean():
    """The tentpole acceptance: zero un-baselined findings over mqtt_tpu/
    with the checked-in (empty) baseline."""
    new, baselined = lint_paths(["mqtt_tpu"])
    assert new == [], "\n".join(f.render() for f in new)
    # the checked-in baseline must stay empty: violations get fixed or
    # pragma'd at the site, not grandfathered
    assert load_baseline(DEFAULT_BASELINE) == set()
    assert baselined == []


def test_cli_exits_zero_on_live_tree():
    r = subprocess.run(
        [sys.executable, "-m", "tools.brokerlint", "mqtt_tpu", "--json"],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    payload = json.loads(r.stdout)
    assert payload["findings"] == []


def test_rule_catalog_is_complete():
    for rid in list(FILE_RULES) + list(PROJECT_RULES):
        assert rid in RULE_DOC


@pytest.mark.skipif(
    subprocess.run(
        [sys.executable, "-c", "import mypy"], capture_output=True
    ).returncode != 0,
    reason="mypy not installed (CI installs it; the gate is advisory locally)",
)
def test_mypy_gate_on_typed_core_modules():
    """`mypy` (config: mypy.ini) must pass over the typed core modules
    — the scope grew to faults.py, tenancy.py, inflight.py, config.py
    and utils/loopwitness.py in ISSUE 19."""
    r = subprocess.run(
        [sys.executable, "-m", "mypy", "--config-file", "mypy.ini"],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
