"""Client-level unit tests: packet-id allocation, connect parsing clamps,
inflight resend/clear — the behavioral core of the reference's
clients_test.go (47 funcs; structure-trivial map tests live in the
LockedMap coverage)."""

import pytest

from mqtt_tpu import Options, Server
from mqtt_tpu.packets import (
    PUBLISH,
    ConnectParams,
    FixedHeader,
    Packet,
    codes,
)


def make_client(**opt_kw):
    srv = Server(Options(**opt_kw))
    cl = srv.new_client(None, None, "t1", "cl1", False)
    return srv, cl


def inflight_pk(pid, qos=1):
    return Packet(fixed_header=FixedHeader(type=PUBLISH, qos=qos), packet_id=pid)


class TestNextPacketID:
    def test_sequential(self):
        srv, cl = make_client()
        assert cl.next_packet_id() == 1
        assert cl.next_packet_id() == 2

    def test_skips_ids_in_use(self):
        srv, cl = make_client()
        cl.state.inflight.set(inflight_pk(1))
        cl.state.inflight.set(inflight_pk(2))
        assert cl.next_packet_id() == 3

    def test_wraps_after_maximum(self):
        srv, cl = make_client()
        srv.options.capabilities.maximum_packet_id = 5
        cl.state.packet_id = 5
        assert cl.next_packet_id() == 1  # wrapped past the cap

    def test_exhaustion_raises_quota_exceeded(self):
        srv, cl = make_client()
        srv.options.capabilities.maximum_packet_id = 4
        for i in range(1, 5):
            cl.state.inflight.set(inflight_pk(i))
        with pytest.raises(codes.Code) as e:
            cl.next_packet_id()
        assert e.value.code == codes.ERR_QUOTA_EXCEEDED.code


class TestParseConnect:
    def _connect_pk(self, version=5, client_id="zen", keepalive=30, **props):
        pk = Packet(
            fixed_header=FixedHeader(type=codes and 1),
            protocol_version=version,
            connect=ConnectParams(
                protocol_name=b"MQTT",
                clean=True,
                keepalive=keepalive,
                client_identifier=client_id,
            ),
        )
        for k, v in props.items():
            setattr(pk.properties, k, v)
        return pk

    def test_receive_maximum_clamped_to_server_inflight(self):
        srv, cl = make_client()
        srv.options.capabilities.maximum_inflight = 8
        pk = self._connect_pk(receive_maximum=1000)
        cl.parse_connect("t1", pk)
        # [3.3.4 non-normative] client's receive max caps at server inflight
        assert cl.properties.props.receive_maximum == 8
        assert cl.state.inflight.maximum_send_quota == 8

    def test_empty_client_id_gets_generated_id(self):
        srv, cl = make_client()
        cl.id = ""
        pk = self._connect_pk(client_id="")
        cl.parse_connect("t1", pk)
        assert cl.id != ""  # xid-style assignment (clients.go:235-238)

    def test_keepalive_absorbed(self):
        srv, cl = make_client()
        pk = self._connect_pk(keepalive=77)
        cl.parse_connect("t1", pk)
        assert cl.state.keepalive == 77


class TestInflightLifecycle:
    def test_clear_inflights_returns_cleared_ids(self):
        srv, cl = make_client()
        for i in (3, 1, 2):
            cl.state.inflight.set(inflight_pk(i))
        cl.clear_inflights()
        assert len(cl.state.inflight) == 0
        assert srv.info.inflight == -3  # decremented per drop

    def test_clear_expired_inflights_honors_created(self):
        srv, cl = make_client()
        old = inflight_pk(1)
        old.created = 100
        new = inflight_pk(2)
        new.created = 10_000
        cl.state.inflight.set(old)
        cl.state.inflight.set(new)
        # expire everything created before t=5000
        cleared = cl.clear_expired_inflights(10_000, 5_000)
        assert cleared == [1]
        assert cl.state.inflight.get(2) is not None

    def test_stop_is_idempotent(self):
        srv, cl = make_client()
        cl.stop(codes.CODE_DISCONNECT())
        first = cl.stop_cause
        cl.stop(codes.ERR_SERVER_SHUTTING_DOWN())
        assert cl.stop_cause is first  # sync.Once semantics
        assert cl.closed
