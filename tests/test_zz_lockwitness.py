"""The witness⊆static cross-validation gate (ISSUE 10).

tests/conftest.py arms ``LockWitness`` on the process-wide lock plane
for the ENTIRE session, so by the time this file runs (named ``zz`` to
sort last under ``-p no:randomly``) the witness has accumulated every
named-lock acquisition-order edge the whole tier-1 suite provoked. The
gate asserts each one appears in the statically extracted lock graph
(tools/brokerlint/lockgraph.py): a runtime edge the extractor cannot
explain is an extraction gap — the static pass would be silently blind
to a whole class of orderings — and fails tier-1 loudly.

The file also drives the canonical edge set directly (a staged broker
with a retained publish, governor evaluations, breaker records), so the
gate is meaningful even when run standalone instead of last-in-suite.
"""

import os

from mqtt_tpu import Options
from mqtt_tpu.packets import PUBLISH, SUBACK, Subscription
from mqtt_tpu.utils.locked import DEFAULT_PLANE, LOCK_NAMES

from tools.brokerlint.core import collect_files, load_ctx
from tools.brokerlint.lockgraph import LOCK_ORDER, extract_lock_graph

from tests.test_server import (
    Harness,
    pub_packet,
    read_wire_packet,
    run,
    sub_packet,
)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _static_graph():
    ctxs = [
        load_ctx(p, _ROOT)
        for p in collect_files([os.path.join(_ROOT, "mqtt_tpu")], _ROOT)
    ]
    return extract_lock_graph(ctxs)


def _drive_canonical_edges():
    """Provoke the known named-lock nestings a quiet standalone run
    might not have touched yet: a retained publish through a staged
    broker (trie -> retained store), governor evaluation, breaker
    bookkeeping, and a metrics render."""

    async def scenario():
        h = Harness(
            Options(
                inline_client=True,
                device_matcher=True,
                matcher_stage_window_ms=1.0,
                matcher_opts={"max_levels": 4, "background": False},
                telemetry_sample=1,
            )
        )
        await h.server.serve()
        sub_r, sub_w, _ = await h.connect("sub")
        sub_w.write(sub_packet(1, [Subscription(filter="w/#", qos=0)]))
        await sub_w.drain()
        assert (await read_wire_packet(sub_r)).fixed_header.type == SUBACK
        h.server.matcher.flush()
        pub_r, pub_w, _ = await h.connect("pub")
        for i in range(8):
            pub_w.write(pub_packet(f"w/{i}", b"x", retain=(i % 2 == 0)))
        await pub_w.drain()
        for _ in range(8):
            assert (await read_wire_packet(sub_r)).fixed_header.type == PUBLISH
        gov = h.server.overload
        if gov is not None:
            gov.evaluate(force=True)
        breaker = getattr(h.server.matcher, "breaker", None)
        if breaker is not None:
            breaker.record_success()
            breaker.as_dict()
        if h.server.telemetry is not None:
            h.server.telemetry.exposition()
        await h.server.close()
        await h.shutdown()

    run(scenario())


class TestWitnessCrossValidation:
    def test_witness_edges_all_appear_in_static_graph(self):
        """THE gate: every (held, acquired) edge between catalog-named
        locks that the runtime witness observed — across everything the
        session ran before this file, plus the canonical drive above —
        must be present in the statically extracted graph."""
        witness = DEFAULT_PLANE.witness
        assert witness is not None, (
            "conftest must arm the session witness (DEFAULT_PLANE"
            ".arm_witness()) for the cross-validation gate to mean "
            "anything"
        )
        _drive_canonical_edges()
        graph = _static_graph()
        static_named = graph.named_edges()
        catalog = set(LOCK_ORDER)
        observed = {
            e: ev
            for e, ev in witness.edges.items()
            if e[0] in catalog and e[1] in catalog
        }
        unexplained = {
            e: ev for e, ev in observed.items() if e not in static_named
        }
        assert not unexplained, (
            "runtime lock-order edges missing from the static graph "
            "(extraction gap — fix tools/brokerlint/lockgraph.py, do not "
            "baseline): "
            + "; ".join(
                f"{a}->{b} first seen on thread {ev[0]} holding {ev[1]}"
                for (a, b), ev in sorted(unexplained.items())
            )
        )
        # the canonical drive must really have produced the flagship
        # edge, or this gate is vacuously green
        assert ("topics_trie", "retained") in observed

    def test_witness_saw_no_cycles(self):
        """No runtime acquisition order observed across the whole suite
        may close a cycle — the dynamic mirror of R9's static check."""
        witness = DEFAULT_PLANE.witness
        assert witness is not None
        assert witness.violations == [], witness.violations

    def test_static_and_catalog_agree(self):
        """LOCK_NAMES (utils/locked.py) and LOCK_ORDER (lockgraph.py)
        are the same catalog; extraction anchors every blessed name."""
        assert set(LOCK_NAMES) <= set(LOCK_ORDER)
        graph = _static_graph()
        for name in LOCK_NAMES:
            assert name in graph.defs, f"catalog lock {name!r} not extracted"
