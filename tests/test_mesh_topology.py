"""Property suite for mqtt_tpu.mesh_topology (ISSUE 9): the pure state
the spanning-tree cluster routes over.

The invariants that keep the mesh loop-free and duplicate-free live
here, each hammered with seeded randomized inputs rather than a few
hand-picked cases:

- the elected tree is ACYCLIC and SPANNING for every membership view
  (and every worker computing it from the same view agrees edge-for-edge)
- per-worker degree stays O(degree): children <= degree, +1 for the
  parent — the 32-worker drill's link-count bound, proved structurally
- TreeEpoch is a strict total order, so concurrent re-elections converge
- interest summaries can FALSE-POSITIVE but never FALSE-NEGATIVE: any
  filter matching a topic is found by the topic's prefix probes
- counted-bloom deletes really delete (UNSUBSCRIBE symmetry) without
  disturbing other keys' membership
- the (origin, boot, seq) duplicate window is exact inside its span:
  first arrival False, every re-arrival True, fresh boots start clean
"""

import random

import pytest

from mqtt_tpu.mesh_topology import (
    BloomBits,
    CountedBloom,
    DuplicateSuppressor,
    ROUTE_DUP,
    ROUTE_NEW,
    ROUTE_REFORWARD,
    Topology,
    TreeEpoch,
    compute_parents,
    compute_successor,
    decode_members,
    encode_members,
    is_spanning_tree,
    summary_key,
    topic_keys,
    tree_children,
    tree_neighbors,
)


# -- deterministic tree election ---------------------------------------------


class TestComputeParents:
    @pytest.mark.parametrize("seed", range(20))
    def test_random_views_are_acyclic_and_spanning(self, seed):
        r = random.Random(seed)
        n = r.randint(1, 64)
        members = r.sample(range(200), n)
        degree = r.randint(1, 6)
        parents = compute_parents(members, degree)
        assert is_spanning_tree(parents, members)

    @pytest.mark.parametrize("seed", range(20))
    def test_degree_bound_holds(self, seed):
        """children <= degree and neighbors <= degree + 1 for every
        worker — the O(degree) link budget the drill asserts."""
        r = random.Random(100 + seed)
        members = r.sample(range(500), r.randint(1, 64))
        degree = r.randint(1, 5)
        parents = compute_parents(members, degree)
        for w in members:
            assert len(tree_children(parents, w)) <= degree
            assert len(tree_neighbors(parents, w)) <= degree + 1

    def test_root_is_lowest_id(self):
        parents = compute_parents([7, 3, 12, 5])
        assert parents[3] is None
        assert all(p is not None for w, p in parents.items() if w != 3)

    def test_identical_across_views(self):
        """Two workers holding the same member set compute the SAME
        tree regardless of input order — announcements never need to
        carry edges."""
        r = random.Random(7)
        members = r.sample(range(100), 32)
        shuffled = list(members)
        r.shuffle(shuffled)
        assert compute_parents(members, 3) == compute_parents(shuffled, 3)

    def test_single_member_is_its_own_root(self):
        assert compute_parents([9]) == {9: None}

    def test_degree_must_be_positive(self):
        with pytest.raises(ValueError):
            compute_parents([1, 2], 0)

    def test_validator_rejects_cycles_and_forests(self):
        assert not is_spanning_tree({1: 2, 2: 1}, [1, 2])  # cycle
        assert not is_spanning_tree({1: None, 2: None}, [1, 2])  # 2 roots
        assert not is_spanning_tree({1: None}, [1, 2])  # not spanning
        assert not is_spanning_tree({}, [])  # empty is not a tree


# -- epoch total order --------------------------------------------------------


class TestTreeEpoch:
    def test_strict_total_order(self):
        """Any two distinct epochs compare strictly one way — two
        concurrent proposals can never tie, so adoption converges."""
        r = random.Random(3)
        epochs = [
            TreeEpoch(r.randint(0, 3), r.randint(0, 3), r.randint(0, 3))
            for _ in range(100)
        ]
        for a in epochs:
            for b in epochs:
                assert (a < b) + (b < a) + (a == b) == 1

    def test_counter_dominates_tiebreaks(self):
        assert TreeEpoch(2, 0, 0) > TreeEpoch(1, 99, 99)
        # same counter: the boot nonce then worker id break the tie
        assert TreeEpoch(1, 5, 0) > TreeEpoch(1, 4, 9)
        assert TreeEpoch(1, 4, 3) > TreeEpoch(1, 4, 2)


class TestTopology:
    def test_adopt_requires_strictly_greater(self):
        t = Topology(0, range(4), boot_id=11)
        assert not t.adopt(TreeEpoch(0, 0, 0), {0: 0, 1: 0})  # equal
        assert t.adopt(TreeEpoch(1, 7, 2), {0: 11, 1: 0, 2: 7})
        assert t.epoch == TreeEpoch(1, 7, 2)
        assert not t.adopt(TreeEpoch(1, 7, 2), {0: 11})  # replay
        assert t.adoptions == 1

    def test_propose_always_exceeds_seen(self):
        t = Topology(2, range(4), boot_id=5)
        t.adopt(TreeEpoch(9, 1, 0), {0: 0, 1: 0, 2: 5, 3: 0})
        ep = t.propose_remove(3)
        assert ep is not None and ep > TreeEpoch(9, 1, 0)
        assert ep.proposer == 2 and ep.boot == 5

    def test_remove_is_idempotent(self):
        t = Topology(0, range(4))
        assert t.propose_remove(3) is not None
        assert t.propose_remove(3) is None  # raced double-detection
        assert t.propose_remove(0) is None  # never remove self
        assert 3 not in t.parents()

    def test_add_new_member_re_elects(self):
        t = Topology(0, range(3))
        ep = t.propose_add(7, boot=42)
        assert ep is not None
        assert 7 in t.parents()
        assert t.members()[7] == 42

    def test_moved_boot_nonce_re_elects(self):
        """A restarted incarnation (same id, new nonce) must advance the
        epoch — its old tree can never be resurrected."""
        t = Topology(0, range(3))
        assert t.propose_add(1, boot=100) is None  # first learn: no churn
        assert t.members()[1] == 100
        ep = t.propose_add(1, boot=200)  # restart: nonce moved
        assert ep is not None
        assert t.members()[1] == 200
        assert t.propose_add(1, boot=200) is None  # steady state

    def test_adoption_excluding_self_stays_routable(self):
        t = Topology(2, range(4), boot_id=9)
        assert t.adopt(TreeEpoch(5, 1, 0), {0: 0, 1: 0, 3: 0})
        # the view excluded us, but the local tree keeps us present so
        # forwarding never indexes a missing worker
        assert 2 in t.parents()
        ep = t.propose_self()
        assert ep > TreeEpoch(5, 1, 0)
        assert 2 in t.members()

    def test_adopt_never_unlearns_boot_nonces(self):
        t = Topology(0, range(3), boot_id=1)
        t.learn_boot(1, 77)
        assert t.adopt(TreeEpoch(1, 2, 2), {0: 1, 1: 0, 2: 0})
        assert t.members()[1] == 77  # 0 in the announcement = unknown

    @pytest.mark.parametrize("seed", range(10))
    def test_random_protocol_runs_stay_spanning(self, seed):
        """Whatever interleaving of adopt/add/remove/self-rejoin runs,
        the local tree is a spanning tree of the local view."""
        r = random.Random(seed)
        t = Topology(0, range(8), degree=r.randint(1, 4), boot_id=3)
        for _ in range(200):
            op = r.randrange(4)
            if op == 0:
                t.propose_remove(r.randrange(8))
            elif op == 1:
                t.propose_add(r.randrange(12), boot=r.randrange(5))
            elif op == 2:
                members = {
                    w: r.randrange(5)
                    for w in r.sample(range(12), r.randint(1, 8))
                }
                t.adopt(
                    TreeEpoch(r.randint(0, 300), r.randrange(5), r.randrange(8)),
                    members,
                )
            else:
                t.propose_self()
            assert is_spanning_tree(t.parents(), t.members())
            assert set(t.neighbors()) == set(
                tree_neighbors(t.parents(), 0)
            )


class TestSuccessor:
    """The pre-agreed root successor (ISSUE 17): derived, not elected —
    every worker computing it from the same view must agree without any
    extra exchange, and the root-failure fast path must degrade to the
    ordinary scoped re-election when the successor itself dies."""

    @pytest.mark.parametrize("seed", range(20))
    def test_successor_is_second_lowest(self, seed):
        r = random.Random(seed)
        members = r.sample(range(200), r.randint(2, 40))
        assert compute_successor(members) == sorted(members)[1]

    def test_small_views_need_no_successor(self):
        assert compute_successor([]) is None
        assert compute_successor([7]) is None
        assert compute_successor([7, 7]) is None  # duplicates collapse

    @pytest.mark.parametrize("seed", range(20))
    def test_agreement_across_shuffled_views(self, seed):
        """Every worker holds the member list in ITS OWN order; the
        successor must not depend on that order."""
        r = random.Random(seed)
        members = r.sample(range(200), r.randint(2, 40))
        shuffled = list(members)
        r.shuffle(shuffled)
        assert compute_successor(shuffled) == compute_successor(members)

    @pytest.mark.parametrize("seed", range(20))
    def test_successor_is_roots_direct_child(self, seed):
        """The fast path only works because the successor pings the root
        first-hand: heap slot 1 always parents on slot 0, whatever the
        degree."""
        r = random.Random(seed)
        members = r.sample(range(200), r.randint(2, 40))
        parents = compute_parents(members, degree=r.randint(1, 4))
        succ = compute_successor(members)
        assert parents[succ] == min(members)

    @pytest.mark.parametrize("seed", range(10))
    def test_topology_successor_tracks_the_view(self, seed):
        """Whatever protocol interleaving runs, Topology.successor() is
        exactly compute_successor over the current member view."""
        r = random.Random(seed)
        t = Topology(0, range(8), degree=r.randint(1, 4), boot_id=3)
        for _ in range(100):
            op = r.randrange(3)
            if op == 0:
                t.propose_remove(r.randrange(8))
            elif op == 1:
                t.propose_add(r.randrange(12), boot=r.randrange(5))
            else:
                members = {
                    w: r.randrange(5)
                    for w in r.sample(range(12), r.randint(1, 8))
                }
                t.adopt(
                    TreeEpoch(r.randint(0, 200), r.randrange(5), r.randrange(8)),
                    members,
                )
            assert t.successor() == compute_successor(t.members())

    @pytest.mark.parametrize("seed", range(20))
    def test_successor_death_mid_promotion_converges(self, seed):
        """The root dies; the successor promotes on the fast path but
        dies before its epoch floods everywhere. The strict TreeEpoch
        total order plus DERIVED roots (lowest id of the adopted view)
        must still converge every survivor to ONE root — never two live
        roots within one adopted epoch."""
        r = random.Random(seed)
        n = r.randint(4, 8)
        tops = {w: Topology(w, range(n), boot_id=w + 1) for w in range(n)}
        announcements = []

        def flood(ep, members):
            if ep is not None:
                announcements.append((ep, dict(members)))

        root, succ = 0, compute_successor(range(n))
        del tops[root]
        # fast path: the successor notices the dead root first-hand
        flood(tops[succ].propose_remove(root), tops[succ].members())
        # ...but its flood only reaches SOME survivors before it dies
        for w, t in tops.items():
            if w != succ and r.random() < 0.5:
                t.adopt(*announcements[-1])
        del tops[succ]
        # ordinary scoped re-election takes over: survivors detect both
        # dead edges in arbitrary order (some already count root gone)
        for w in r.sample(list(tops), len(tops)):
            flood(tops[w].propose_remove(root), tops[w].members())
            flood(tops[w].propose_remove(succ), tops[w].members())
        # gossip every announcement in random order until quiescent
        for _ in range(20):
            changed = False
            for ep, members in r.sample(announcements, len(announcements)):
                for t in tops.values():
                    changed |= t.adopt(ep, members)
            if not changed:
                break
        live = sorted(tops)
        final = tops[live[0]].epoch
        for t in tops.values():
            assert t.epoch == final  # one total-order winner everywhere
            assert t.root() == live[0]  # the lowest LIVE id, derived
            assert t.successor() == live[1]
            assert root not in t.members() and succ not in t.members()
            assert is_spanning_tree(t.parents(), t.members())


# -- interest summaries -------------------------------------------------------


def _rand_filter(r: random.Random) -> str:
    levels = []
    for _ in range(r.randint(1, 5)):
        levels.append(r.choice(["a", "b", "c", "d", "+", "sensors", "deep"]))
    if r.random() < 0.3:
        levels.append("#")
    return "/".join(levels)


def _matching_topic(r: random.Random, filter: str) -> str:
    """A topic the filter matches: wildcards instantiated randomly."""
    out = []
    for level in filter.split("/"):
        if level == "#":
            for _ in range(r.randint(0, 3)):
                out.append(r.choice(["x", "y", "z"]))
            break
        out.append(r.choice(["x", "y", "z"]) if level == "+" else level)
    return "/".join(out) if out else "x"


class TestSummaryKeys:
    def test_prefix_truncates_at_first_wildcard(self):
        assert summary_key("a/b/c") == "a/b/c"
        assert summary_key("a/+/c") == "a"
        assert summary_key("a/b/#") == "a/b"
        assert summary_key("#") is None
        assert summary_key("+/a") is None

    def test_topic_keys_are_all_prefixes(self):
        assert topic_keys("a/b/c") == ["a", "a/b", "a/b/c"]

    @pytest.mark.parametrize("seed", range(30))
    def test_no_false_negatives(self, seed):
        """SOUNDNESS: for any filter F and any topic T that F matches,
        a summary containing F answers might_match(T) True. False
        negatives would be lost cross-worker deliveries; false
        positives only cost a conservative forward."""
        r = random.Random(seed)
        bloom = CountedBloom(512, k=3)
        filters = [_rand_filter(r) for _ in range(r.randint(1, 20))]
        for f in filters:
            bloom.add(f)
        bits = bloom.bits()
        for f in filters:
            topic = _matching_topic(r, f)
            assert bits.might_match(topic), (f, topic)


class TestCountedBloom:
    def test_discard_really_deletes(self):
        b = CountedBloom(256)
        b.add("a/b")
        assert b.bits().might_match("a/b/c")
        b.discard("a/b")
        assert not b.bits().might_match("a/b/c")

    def test_refcounted_keys_survive_partial_discard(self):
        b = CountedBloom(256)
        b.add("a/b")
        b.add("a/b")  # two subscribers, same prefix
        b.discard("a/b")
        assert b.bits().might_match("a/b")
        b.discard("a/b")
        assert not b.bits().might_match("a/b")

    def test_discard_leaves_other_keys_alone(self):
        r = random.Random(5)
        b = CountedBloom(1024)
        keep = [f"keep/{i}" for i in range(20)]
        drop = [f"drop/{i}" for i in range(20)]
        for f in keep + drop:
            b.add(f)
        for f in drop:
            b.discard(f)
        bits = b.bits()
        for f in keep:
            assert bits.might_match(f)

    def test_wildcard_rooted_filters_set_match_all(self):
        b = CountedBloom(256)
        b.add("#")
        assert b.bits().match_all
        assert b.bits().might_match("anything/at/all")
        b.discard("#")
        assert not b.bits().match_all

    def test_generation_bumps_on_every_mutation(self):
        b = CountedBloom(256)
        g0 = b.generation
        b.add("x")
        b.discard("x")
        assert b.generation == g0 + 2

    def test_saturated_slot_stays_conservative(self):
        b = CountedBloom(64, k=1)
        for _ in range(0x10001):
            b._bump(3, 1)
        b._bump(3, -1)  # saturated: the decrement is refused
        off = 2 * 3
        assert b._counts[off] | (b._counts[off + 1] << 8) == 0xFFFF

    def test_size_must_be_whole_bytes(self):
        with pytest.raises(ValueError):
            CountedBloom(100)


class TestBloomBits:
    def test_union_is_bitwise_or(self):
        a = CountedBloom(256)
        a.add("a/b")
        b = CountedBloom(256)
        b.add("c/d")
        u = a.bits().union(b.bits())
        assert u.might_match("a/b") and u.might_match("c/d")

    def test_union_mixed_sizes_degrades_to_match_all(self):
        a = BloomBits.empty(256)
        b = BloomBits.empty(512)
        assert a.union(b).match_all  # conservative, never a lost route

    def test_fill_ratio(self):
        assert BloomBits.empty(256).fill_ratio() == 0.0
        full = BloomBits(b"\xff" * 32, False)
        assert full.fill_ratio() == 1.0


# -- duplicate suppression ----------------------------------------------------


class TestDuplicateSuppressor:
    def test_first_seen_then_suppressed(self):
        d = DuplicateSuppressor()
        assert not d.seen(1, 7, 100)
        assert d.seen(1, 7, 100)
        assert d.seen(1, 7, 100)

    def test_out_of_order_inside_window_is_exact(self):
        r = random.Random(2)
        d = DuplicateSuppressor(window=128)
        seqs = list(range(1, 100))
        r.shuffle(seqs)
        for s in seqs:
            assert not d.seen(3, 9, s)
        r.shuffle(seqs)
        for s in seqs:
            assert d.seen(3, 9, s)

    def test_behind_the_window_counts_as_seen(self):
        d = DuplicateSuppressor(window=16)
        assert not d.seen(1, 1, 1000)
        assert d.seen(1, 1, 1000 - 16)  # out the back: call it seen

    def test_new_boot_opens_fresh_window(self):
        """A restarted origin's seq counter starts over; its frames must
        not be mistaken for replays of the dead incarnation."""
        d = DuplicateSuppressor()
        assert not d.seen(1, 111, 5)
        assert not d.seen(1, 222, 5)  # same origin+seq, new incarnation
        assert d.seen(1, 222, 5)

    def test_origins_are_independent(self):
        d = DuplicateSuppressor()
        assert not d.seen(1, 0, 9)
        assert not d.seen(2, 0, 9)

    def test_window_memory_stays_bounded(self):
        d = DuplicateSuppressor(window=64, max_origins=8)
        for origin in range(50):
            d.seen(origin, 0, 1)
        assert d.origins() <= 9  # clear-then-insert on overflow
        d2 = DuplicateSuppressor(window=8)
        for s in range(1, 10000):
            d2.seen(1, 1, s)
        # the per-origin recent set is trimmed to the window
        assert len(d2._origins[(1, 1)][1]) <= 4 * 8

    def test_newer_epoch_repeat_reforwards_never_redelivers(self):
        """A parked copy re-routed by a re-election can cross a worker
        the original already visited: the repeat under a strictly newer
        epoch must travel on (ROUTE_REFORWARD) — dropping it would
        starve the orphaned subtree the re-route exists to heal — but a
        repeat under the SAME epoch is a loop and stops."""
        d = DuplicateSuppressor()
        e1, e2 = (3, 10, 0), (4, 10, 2)
        assert d.route(1, 7, 100, e1) == ROUTE_NEW
        assert d.route(1, 7, 100, e1) == ROUTE_DUP  # same tree: a loop
        assert d.route(1, 7, 100, e2) == ROUTE_REFORWARD  # re-routed park
        # the re-forward was recorded under e2: the new tree can also
        # only carry it through here once
        assert d.route(1, 7, 100, e2) == ROUTE_DUP
        assert d.route(1, 7, 100, e1) == ROUTE_DUP  # older epoch: never

    def test_epochless_frames_stay_plain_duplicates(self):
        d = DuplicateSuppressor()
        assert d.route(2, 1, 5, None) == ROUTE_NEW
        assert d.route(2, 1, 5, None) == ROUTE_DUP
        # an epoch-stamped re-route of a frame first seen without one
        # still re-forwards (None compares older than any real epoch)
        assert d.route(2, 1, 5, (1, 0, 0)) == ROUTE_REFORWARD


# -- wire codec ---------------------------------------------------------------


class TestMemberCodec:
    def test_round_trip(self):
        view = {0: 12345, 7: 0, 31: 2**40}
        assert decode_members(encode_members(view)) == view
