"""The cluster-wide SLO observatory's engine half (ISSUE 14,
mqtt_tpu.slo + the delivery-latency SLI in mqtt_tpu.telemetry): the
objective grammar, burn-rate window math against injected clocks, the
seeded latency-injection breach end-to-end (retained $SYS transition +
gauges + flight dump), the delivery SLI's local/remote stamping through
a real broker, and the /healthz readiness surface's 200/503 + method
matrix.
"""

import asyncio
import json
import random

import pytest

from mqtt_tpu import Options, Server
from mqtt_tpu.listeners import Config as LConfig, HTTPStats
from mqtt_tpu.packets import PUBLISH, Subscription
from mqtt_tpu.slo import (
    ObjectiveError,
    SLOEngine,
    parse_objective,
    parse_objectives,
)
from mqtt_tpu.telemetry import Histogram, RemoteStageClock, Telemetry

from tests.test_server import (
    Harness,
    pub_packet,
    read_wire_packet,
    run,
    sub_packet,
)
from tests.test_telemetry import _http


# -- objective grammar -------------------------------------------------------


class TestObjectiveGrammar:
    def test_latency_objective(self):
        o = parse_objective("p99 delivery < 50ms over 5m")
        assert o.kind == "latency"
        assert o.family == "mqtt_tpu_delivery_latency_seconds"
        assert o.threshold_s == pytest.approx(0.05)
        assert o.budget == pytest.approx(0.01)
        assert (o.fast_s, o.slow_s) == (300.0, 3600.0)

    def test_latency_label_filter_and_explicit_windows(self):
        o = parse_objective("p95 delivery{tenant=acme,qos=1} < 20ms over 30s/2m")
        assert o.labels == {"tenant": "acme", "qos": "1"}
        assert o.budget == pytest.approx(0.05)
        assert (o.fast_s, o.slow_s) == (30.0, 120.0)

    def test_slow_window_floored_at_fast(self):
        o = parse_objective("p99 delivery < 50ms over 10m/1m")
        assert o.slow_s == o.fast_s

    def test_named_ratio(self):
        o = parse_objective("shed ratio < 0.1%")
        assert o.kind == "ratio"
        assert o.numerator == "mqtt_tpu_messages_dropped_total"
        assert o.denominator == "mqtt_tpu_messages_received_total"
        assert o.budget == pytest.approx(0.001)

    def test_explicit_family_ratio(self):
        o = parse_objective(
            "messages_dropped_total/messages_received_total ratio < 2% "
            "over 1m"
        )
        assert o.numerator == "mqtt_tpu_messages_dropped_total"
        assert o.denominator == "mqtt_tpu_messages_received_total"
        assert o.budget == pytest.approx(0.02)
        assert o.fast_s == 60.0

    def test_explicit_histogram_family(self):
        o = parse_objective("p99 publish_stage_seconds < 5ms over 1m")
        assert o.family == "mqtt_tpu_publish_stage_seconds"

    @pytest.mark.parametrize(
        "bad",
        [
            "p99 delivery > 50ms",  # wrong comparator
            "p0 delivery < 50ms",  # quantile out of range
            "delivery < 50ms",  # no quantile
            "wat ratio < 1x",  # bad unit
            "unknown_sli ratio < 1%",  # unknown named ratio
            "p99 delivery < 50ms over fortnight",  # bad duration
            "",
        ],
    )
    def test_bad_specs_raise(self, bad):
        with pytest.raises(ObjectiveError):
            parse_objective(bad)

    def test_parse_objectives_skips_bad_lines_and_dedupes(self):
        objs = parse_objectives(
            [
                "p99 delivery < 50ms over 5m",
                "complete nonsense",
                "p99 delivery < 50ms over 5m",  # duplicate name
            ]
        )
        assert len(objs) == 2
        assert objs[0].name != objs[1].name


# -- histogram threshold math ------------------------------------------------


class TestCountLe:
    def test_count_le_on_and_off_bucket(self):
        h = Histogram(bounds=(0.001, 0.01, 0.1))
        for v in (0.0005, 0.005, 0.05, 0.5):
            h.observe(v)
        assert h.count_le(0.001) == 1
        assert h.count_le(0.01) == 2
        # off-bucket threshold snaps DOWN (errs toward alarming):
        # 0.05 -> largest bound <= it is 0.01
        assert h.count_le(0.05) == 2
        assert h.count_le(0.1) == 3
        assert h.count_le(99) == 3  # +Inf bucket never counts as good


# -- burn-rate window math ---------------------------------------------------


def _engine(tele, spec, **kw):
    return SLOEngine(tele, [parse_objective(spec)], clock=lambda: 0.0, **kw)


class TestBurnRates:
    def test_no_traffic_no_burn(self):
        tele = Telemetry(sample=1)
        eng = _engine(tele, "p99 delivery < 50ms over 10s/60s")
        eng.evaluate(0.0)
        eng.evaluate(10.0)
        st = next(iter(eng.state().values()))
        assert st["burn_rate_fast"] == 0 and not st["breached"]

    def test_breach_needs_both_windows_then_clears_on_fast(self):
        tele = Telemetry(sample=1)
        eng = _engine(tele, "p99 delivery < 50ms over 10s/40s")
        name = eng.objectives[0].name
        eng.evaluate(0.0)
        # 100% bad traffic: burn = 1.0/0.01 = 100x on any window with data
        for _ in range(50):
            tele.observe_delivery(1.0, "", 0, "local")
        eng.evaluate(5.0)
        st = eng.state()[name]
        assert st["breached"], st
        assert st["burn_rate_fast"] > 1 and st["burn_rate_slow"] > 1
        assert st["budget_remaining"] == 0.0
        # traffic turns good: the FAST window's delta goes clean while
        # the slow window still remembers the storm -> must clear
        for _ in range(5000):
            tele.observe_delivery(0.001, "", 0, "local")
        eng.evaluate(16.0)  # the bad burst has left the 10s fast window
        st = eng.state()[name]
        assert st["burn_rate_fast"] < 1.0
        assert not st["breached"]

    def test_one_bad_blip_does_not_breach(self):
        # bad events confined to a tiny fraction under the budget: the
        # burn stays below threshold on both windows
        tele = Telemetry(sample=1)
        eng = _engine(tele, "p99 delivery < 50ms over 10s/40s")
        eng.evaluate(0.0)
        tele.observe_delivery(1.0, "", 0, "local")  # 1 bad
        for _ in range(1000):  # 1000 good
            tele.observe_delivery(0.001, "", 0, "local")
        eng.evaluate(5.0)
        st = next(iter(eng.state().values()))
        assert not st["breached"]
        assert st["burn_rate_fast"] < 1.0

    def test_counter_reset_clamps_to_zero(self):
        tele = Telemetry(sample=1)
        eng = _engine(tele, "shed ratio < 1% over 10s/40s")
        tele.registry.counter("mqtt_tpu_messages_dropped_total").inc(100)
        tele.registry.counter("mqtt_tpu_messages_received_total").inc(200)
        eng.evaluate(0.0)
        # simulate a restart-style reset by a LOWER cumulative snapshot
        fam = tele.registry.counter("mqtt_tpu_messages_dropped_total")
        fam._value = 0
        eng.evaluate(5.0)
        st = next(iter(eng.state().values()))
        assert st["burn_rate_fast"] == 0.0

    def test_label_filtered_latency_objective(self):
        tele = Telemetry(sample=1)
        eng = _engine(tele, "p99 delivery{tenant=acme} < 50ms over 10s/40s")
        eng.evaluate(0.0)
        # the OTHER tenant melts down; acme stays healthy
        for _ in range(100):
            tele.observe_delivery(1.0, "bulk", 0, "local")
            tele.observe_delivery(0.001, "acme", 0, "local")
        eng.evaluate(5.0)
        st = next(iter(eng.state().values()))
        assert not st["breached"]
        assert st["burn_rate_fast"] == 0.0

    def test_gauges_exported_on_registry(self):
        tele = Telemetry(sample=1)
        eng = _engine(tele, "p99 delivery < 50ms over 10s/40s")
        eng.evaluate(0.0)
        text = tele.exposition()
        assert 'mqtt_tpu_slo_burn_rate{objective="' in text
        assert 'window="fast"' in text and 'window="slow"' in text
        assert "mqtt_tpu_slo_budget_remaining{" in text
        assert "mqtt_tpu_slo_breached{" in text
        assert "mqtt_tpu_slo_breaches_total" in text


# -- seeded latency-injection breach, end to end -----------------------------


class TestBreachEndToEnd:
    def test_breach_publishes_sys_sets_gauges_and_dumps(self, tmp_path):
        """The acceptance leg: a seeded latency injection drives a
        burn-rate breach — the retained $SYS/broker/slo/# transition
        reaches a live subscriber, the gauges flip, the flight dump is
        written — then recovery publishes the clearing transition."""

        async def scenario():
            h = Harness(
                Options(
                    inline_client=True,
                    slo_objectives=["p99 delivery < 50ms over 10s/40s"],
                    telemetry_dump_dir=str(tmp_path),
                )
            )
            srv = h.server
            assert srv.slo is not None
            name = srv.slo.objectives[0].name
            r, w, _ = await h.connect("slo-watcher", version=4)
            w.write(
                sub_packet(
                    1, [Subscription(filter="$SYS/broker/slo/#", qos=0)], 4
                )
            )
            await read_wire_packet(r, 4)

            rng = random.Random(7)
            srv.slo.evaluate(0.0)
            tele = srv.telemetry
            for _ in range(200):
                # seeded injection: every delivery lands 100-400ms past
                # the 50ms objective
                tele.observe_delivery(
                    0.1 + rng.random() * 0.3, "", 0, "local"
                )
            srv.slo.evaluate(5.0)

            pk = await read_wire_packet(r, 4)
            assert pk.fixed_header.type == PUBLISH
            assert pk.topic_name == "$SYS/broker/slo/" + name
            body = json.loads(bytes(pk.payload))
            assert body["breached"] is True
            assert body["burn_rate_fast"] > 1.0

            st = srv.slo.state()[name]
            assert st["breached"] and st["breaches"] == 1
            text = tele.exposition()
            assert (
                f'mqtt_tpu_slo_breached{{objective="{name}"}} 1' in text
            )
            # the one-bundle capture: the flight dump was written
            tele.recorder.join_writer()
            assert tele.recorder.dumps == 1
            dumps = list(tmp_path.glob("flight_*slo_breach*"))
            assert dumps, list(tmp_path.iterdir())

            # recovery: good traffic floods in, the fast window cools
            for _ in range(20000):
                tele.observe_delivery(0.001, "", 0, "local")
            srv.slo.evaluate(16.0)
            pk2 = await read_wire_packet(r, 4)
            assert json.loads(bytes(pk2.payload))["breached"] is False
            assert not srv.slo.state()[name]["breached"]
            await h.shutdown()

        run(scenario())

    def test_ratio_breach_from_real_broker_counters(self):
        """A shed-ratio objective burns off the broker's own Info
        mirrors (messages_dropped / messages_received)."""

        async def scenario():
            h = Harness(
                Options(
                    inline_client=True,
                    slo_objectives=["shed ratio < 1% over 10s/40s"],
                )
            )
            srv = h.server
            srv.slo.evaluate(0.0)
            srv.info.messages_received += 100
            srv.info.messages_dropped += 50  # 50% shed >> the 1% budget
            srv.slo.evaluate(5.0)
            st = next(iter(srv.slo.state().values()))
            assert st["breached"]
            assert st["burn_rate_fast"] > 1.0
            await h.shutdown()

        run(scenario())


# -- the delivery SLI through a real broker ----------------------------------


class TestDeliverySLI:
    def test_local_delivery_samples_with_tenant_and_qos_labels(self):
        async def scenario():
            h = Harness(Options(inline_client=True, telemetry_sample=1))
            srv = h.server
            sr, sw, _ = await h.connect("sli-sub", version=4)
            sw.write(sub_packet(1, [Subscription(filter="t/#", qos=1)], 4))
            await read_wire_packet(sr, 4)
            pr, pw, _ = await h.connect("sli-pub", version=4)
            pw.write(pub_packet("t/a", b"x", version=4, qos=1, pid=9))
            await read_wire_packet(pr, 4)  # PUBACK
            got = await read_wire_packet(sr, 4)
            assert got.fixed_header.type == PUBLISH
            text = srv.telemetry.exposition()
            assert (
                'mqtt_tpu_delivery_latency_seconds_count'
                '{path="local",qos="1",tenant=""}' in text
            )
            await h.shutdown()

        run(scenario())

    def test_slo_off_records_nothing(self):
        async def scenario():
            h = Harness(
                Options(inline_client=True, telemetry_sample=1, slo=False)
            )
            srv = h.server
            assert srv.telemetry.delivery_sli is False
            sr, sw, _ = await h.connect("sli-sub", version=4)
            sw.write(sub_packet(1, [Subscription(filter="t/#", qos=0)], 4))
            await read_wire_packet(sr, 4)
            pr, pw, _ = await h.connect("sli-pub", version=4)
            pw.write(pub_packet("t/a", b"x", version=4))
            await read_wire_packet(sr, 4)
            assert "mqtt_tpu_delivery_latency_seconds" not in (
                srv.telemetry.exposition()
            )
            await h.shutdown()

        run(scenario())

    def test_remote_clock_adds_origin_elapsed(self):
        tele = Telemetry(sample=1)
        clock = RemoteStageClock(0.25, "tid-1")
        clock.stamp("decode")
        tele.observe_delivery(
            clock.total() + clock.remote_base, "", 0, "remote",
            trace_id=clock.trace_id,
        )
        h = tele.delivery_hist("", 0, "remote")
        assert h.count == 1
        # the origin's 250ms elapsed stamp dominates the recorded value
        assert h.percentile(0.5) >= 0.25
        rows = tele.delivery_summary()
        assert rows["delivery_remote"]["count"] == 1

    def test_bench_block_carries_delivery_stage_rows(self):
        tele = Telemetry(sample=1)
        tele.observe_delivery(0.001, "", 0, "local")
        tele.observe_delivery(0.3, "acme", 1, "remote")
        stages = tele.bench_block()["stages"]
        assert stages["delivery_local"]["count"] == 1
        assert stages["delivery_remote"]["p99_ms"] >= 300


# -- /healthz ----------------------------------------------------------------


class TestHealthz:
    def test_matrix_and_degraded_vs_not_ready(self):
        async def scenario():
            h = Harness(Options(inline_client=True))
            srv = h.server
            st = HTTPStats(
                LConfig(type="sysinfo", id="s", address="127.0.0.1:0"),
                srv.info,
                telemetry=srv.telemetry,
                health=srv.health_report,
            )
            await st.init(srv.log)
            host, port = st.address().rsplit(":", 1)
            data = await _http(host, port, "/healthz")
            head, body = data.split(b"\r\n\r\n", 1)
            assert head.startswith(b"HTTP/1.1 200")
            assert b"Cache-Control: no-store" in head
            report = json.loads(body)
            assert report["ok"] is True and report["not_ready"] == []

            # non-GET on the known path: 405 + Allow
            post = await _http(host, port, "/healthz", "POST")
            assert post.startswith(b"HTTP/1.1 405") and b"Allow: GET" in post

            # draining -> 503 with the failing component named
            srv._draining = True
            data = await _http(host, port, "/healthz")
            head, body = data.split(b"\r\n\r\n", 1)
            assert head.startswith(b"HTTP/1.1 503")
            assert "draining" in json.loads(body)["not_ready"]
            srv._draining = False

            # governor SHED -> 503 (the state property re-evaluates
            # lazily, so pin the internal state for the probe)
            from mqtt_tpu.overload import NORMAL, SHED

            srv.overload._state = SHED
            data = await _http(host, port, "/healthz")
            assert data.startswith(b"HTTP/1.1 503")
            assert b"governor_shed" in data
            srv.overload._state = NORMAL

            await st.close(lambda _: None)
            await h.shutdown()

        run(scenario())

    def test_404_without_health_fn(self):
        async def scenario():
            h = Harness()
            st = HTTPStats(
                LConfig(type="sysinfo", id="s", address="127.0.0.1:0"),
                h.server.info,
            )
            await st.init(h.server.log)
            host, port = st.address().rsplit(":", 1)
            assert (await _http(host, port, "/healthz")).startswith(
                b"HTTP/1.1 404"
            )
            # federation surfaces 404 without telemetry too
            assert (await _http(host, port, "/metrics/cluster")).startswith(
                b"HTTP/1.1 404"
            )
            assert (await _http(host, port, "/cluster/slo")).startswith(
                b"HTTP/1.1 404"
            )
            await st.close(lambda _: None)
            await h.shutdown()

        run(scenario())

    def test_staging_death_fails_readiness(self):
        """A dead staging pipeline must flip readiness (the component
        /healthz exists to catch)."""
        jax = pytest.importorskip("jax")  # noqa: F841

        async def scenario():
            h = Harness(
                Options(
                    inline_client=True,
                    device_matcher=True,
                    matcher_opts={"max_levels": 4, "background": False},
                )
            )
            srv = h.server
            await srv.serve()
            try:
                ok, detail = srv.health_report()
                assert ok and detail["staging"]["alive"]
                # kill the collector task: alive() must go false
                for t in srv._stage._tasks:
                    t.cancel()
                await asyncio.gather(
                    *srv._stage._tasks, return_exceptions=True
                )
                ok, detail = srv.health_report()
                assert not ok
                assert "staging_dead" in detail["not_ready"]
            finally:
                await srv.close()
                await h.shutdown()

        run(scenario())
