"""Driver-contract tests for __graft_entry__.dryrun_multichip.

MULTICHIP_r01 failed because the dryrun initialized the real TPU plugin
(libtpu mismatch in the driver sandbox). These tests run the dryrun in a
fresh subprocess with the platform deliberately poisoned: if any code path
queries a non-CPU backend, the run dies; passing proves the dryrun is
hermetic to virtual CPU devices.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CODE = (
    "import __graft_entry__ as g; g.dryrun_multichip(8); print('DRYRUN_OK')"
)


def _run(env_overrides: dict, drop: tuple = ()) -> subprocess.CompletedProcess:
    env = {k: v for k, v in os.environ.items() if k not in drop}
    env.update(env_overrides)
    return subprocess.run(
        [sys.executable, "-c", CODE],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )


def test_dryrun_clean_env():
    """No JAX_PLATFORMS / XLA_FLAGS at all: the dryrun must provision its
    own 8 virtual CPU devices."""
    r = _run({}, drop=("JAX_PLATFORMS", "XLA_FLAGS"))
    assert r.returncode == 0, r.stderr[-3000:]
    assert "DRYRUN_OK" in r.stdout


def test_dryrun_poisoned_tpu_platform():
    """JAX_PLATFORMS=tpu poison: if the dryrun did not pin the platform to
    cpu before backend init, jax would try (and in the driver sandbox fail)
    to bring up the accelerator plugin. Passing proves the override."""
    r = _run({"JAX_PLATFORMS": "tpu"}, drop=("XLA_FLAGS",))
    assert r.returncode == 0, r.stderr[-3000:]
    assert "DRYRUN_OK" in r.stdout


def test_dryrun_small_xla_flags_raised():
    """A pre-set XLA_FLAGS with too few host devices must be raised, not
    trusted."""
    r = _run(
        {"XLA_FLAGS": "--xla_force_host_platform_device_count=2"},
        drop=("JAX_PLATFORMS",),
    )
    assert r.returncode == 0, r.stderr[-3000:]
    assert "DRYRUN_OK" in r.stdout
