"""Interoperability smoke test with an INDEPENDENT MQTT v5 client.

The reference validates against the external Paho suite
(examples/paho.testing/main.go:29-31, README.md:468-471); neither paho nor
any third-party MQTT client ships in this image, so this file carries a
minimal v5 client written directly from the OASIS MQTT 5.0 spec — it
deliberately imports NOTHING from mqtt_tpu.packets, so any codec asymmetry
between our broker and the wire spec fails here instead of cancelling out.

The broker runs with the same configuration the reference's paho harness
uses: ObscureNotAuthorized + PassiveClientDisconnect +
NoInheritedPropertiesOnAck compat flags and an ACL denying
'test/nosubscribe' (examples/paho.testing/main.go:29-31,77).
"""

import asyncio
import struct

from mqtt_tpu import Options, Server
from mqtt_tpu.hooks import ON_ACL_CHECK, ON_CONNECT_AUTHENTICATE, Hook
from mqtt_tpu.listeners import Config as ListenerConfig
from mqtt_tpu.listeners.tcp import TCP

from tests.test_server import run

PORT = 18871


# --------------------------------------------------------------------------
# the independent client: every byte below is derived from the MQTT 5.0
# spec (packet type table 2-1, variable byte integer 1.5.5, UTF-8 string
# 1.5.4, property ids 2.2.2.2) — NOT from mqtt_tpu's codec
# --------------------------------------------------------------------------


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n % 128
        n //= 128
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _utf8(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack(">H", len(b)) + b


def _frame(first_byte: int, body: bytes) -> bytes:
    return bytes([first_byte]) + _varint(len(body)) + body


class MiniV5Client:
    """connect / subscribe / publish QoS0+1 / receive, MQTT 5.0 only."""

    def __init__(self):
        self.reader = None
        self.writer = None

    async def connect(
        self,
        host: str,
        port: int,
        client_id: str,
        will: tuple[str, bytes] | None = None,
    ) -> int:
        self.reader, self.writer = await asyncio.open_connection(host, port)
        flags = 0x02  # 3.1.2.4 clean start
        if will is not None:
            flags |= 0x04  # 3.1.2.5 will flag
        body = (
            _utf8("MQTT")  # 3.1.2.1 protocol name
            + b"\x05"  # 3.1.2.2 version 5
            + bytes([flags])
            + struct.pack(">H", 60)  # 3.1.2.10 keep alive
            + b"\x00"  # 3.1.2.11 no properties
            + _utf8(client_id)  # 3.1.3.1
        )
        if will is not None:
            # 3.1.3.2 will properties (none) + 3.1.3.3 topic + 3.1.3.4 payload
            topic, payload = will
            body += b"\x00" + _utf8(topic) + struct.pack(">H", len(payload)) + payload
        self.writer.write(_frame(0x10, body))
        await self.writer.drain()
        t, body = await self._read_frame()
        assert t == 0x20, f"expected CONNACK, got {t:#x}"
        return body[1]  # 3.2.2.2 connect reason code

    async def publish_qos2(self, topic: str, payload: bytes, pid: int) -> None:
        """Full QoS2 flow: PUBLISH -> PUBREC -> PUBREL -> PUBCOMP (4.3.3)."""
        body = _utf8(topic) + struct.pack(">H", pid) + b"\x00" + payload
        self.writer.write(_frame(0x34, body))  # qos2
        await self.writer.drain()
        t, rb = await self._read_frame()
        assert t == 0x50, f"expected PUBREC, got {t:#x}"
        assert struct.unpack(">H", rb[:2])[0] == pid
        self.writer.write(_frame(0x62, struct.pack(">H", pid)))  # PUBREL 3.6.1
        await self.writer.drain()
        t, cb = await self._read_frame()
        assert t == 0x70, f"expected PUBCOMP, got {t:#x}"
        assert struct.unpack(">H", cb[:2])[0] == pid

    async def recv_publish_qos2(self) -> tuple[str, bytes]:
        """Receive one QoS2 PUBLISH and complete the receiver side of 4.3.3."""
        t, body = await self._read_frame()
        assert (t & 0xF0) == 0x30 and ((t >> 1) & 3) == 2, f"got {t:#x}"
        tlen = struct.unpack(">H", body[:2])[0]
        topic = body[2 : 2 + tlen].decode("utf-8")
        off = 2 + tlen
        pid = struct.unpack(">H", body[off : off + 2])[0]
        off += 2
        plen, off = self._read_varint(body, off)
        payload = body[off + plen :]
        self.writer.write(_frame(0x50, struct.pack(">H", pid)))  # PUBREC
        await self.writer.drain()
        t, rb = await self._read_frame()
        assert t == 0x62, f"expected PUBREL, got {t:#x}"
        assert struct.unpack(">H", rb[:2])[0] == pid
        self.writer.write(_frame(0x70, struct.pack(">H", pid)))  # PUBCOMP
        await self.writer.drain()
        return topic, payload

    def drop(self) -> None:
        """Abrupt socket close (no DISCONNECT): triggers the will (3.1.2.5)."""
        self.writer.transport.abort()

    async def subscribe(self, pid: int, topic: str, qos: int) -> int:
        body = struct.pack(">H", pid) + b"\x00" + _utf8(topic) + bytes([qos])
        self.writer.write(_frame(0x82, body))  # 3.8.1 flags 0b0010
        await self.writer.drain()
        t, body = await self._read_frame()
        assert t == 0x90, f"expected SUBACK, got {t:#x}"
        assert struct.unpack(">H", body[:2])[0] == pid
        # packet id (2) + property length varint + properties, then codes
        plen, off = self._read_varint(body, 2)
        return body[off + plen]  # first reason code

    async def publish(
        self, topic: str, payload: bytes, qos: int = 0, pid: int = 0, retain=False
    ) -> None:
        first = 0x30 | (qos << 1) | (1 if retain else 0)
        body = _utf8(topic)
        if qos:
            body += struct.pack(">H", pid)
        body += b"\x00" + payload  # no properties
        self.writer.write(_frame(first, body))
        await self.writer.drain()
        if qos == 1:
            t, ab = await self._read_frame()
            assert t == 0x40, f"expected PUBACK, got {t:#x}"
            assert struct.unpack(">H", ab[:2])[0] == pid
            if len(ab) > 2:  # 3.4.2.1 reason code present
                assert ab[2] == 0x00

    async def recv_publish(self) -> tuple[str, bytes, int, bool]:
        t, body = await self._read_frame()
        assert (t & 0xF0) == 0x30, f"expected PUBLISH, got {t:#x}"
        qos = (t >> 1) & 0x3
        retain = bool(t & 0x1)
        tlen = struct.unpack(">H", body[:2])[0]
        topic = body[2 : 2 + tlen].decode("utf-8")
        off = 2 + tlen
        pid = 0
        if qos:
            pid = struct.unpack(">H", body[off : off + 2])[0]
            off += 2
        plen, off = self._read_varint(body, off)
        payload = body[off + plen :]
        if qos == 1:  # ack it
            self.writer.write(_frame(0x40, struct.pack(">H", pid)))
            await self.writer.drain()
        return topic, payload, qos, retain

    async def disconnect(self) -> None:
        self.writer.write(_frame(0xE0, b"\x00\x00"))  # reason 0, no props
        await self.writer.drain()
        self.writer.close()

    async def _read_frame(self) -> tuple[int, bytes]:
        first = (await asyncio.wait_for(self.reader.readexactly(1), 5))[0]
        remaining = 0
        shift = 0
        while True:
            b = (await asyncio.wait_for(self.reader.readexactly(1), 5))[0]
            remaining |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        body = (
            await asyncio.wait_for(self.reader.readexactly(remaining), 5)
            if remaining
            else b""
        )
        return first, body

    @staticmethod
    def _read_varint(buf: bytes, off: int) -> tuple[int, int]:
        val = 0
        shift = 0
        while True:
            b = buf[off]
            off += 1
            val |= (b & 0x7F) << shift
            if not (b & 0x80):
                return val, off
            shift += 7


# --------------------------------------------------------------------------


class PahoTestingACL(Hook):
    """The reference paho-harness auth: allow everything except subscribing
    to test/nosubscribe (examples/paho.testing/main.go:77)."""

    def id(self):
        return "paho-acl"

    def provides(self, b):
        return b in (ON_CONNECT_AUTHENTICATE, ON_ACL_CHECK)

    def on_connect_authenticate(self, cl, pk):
        return True

    def on_acl_check(self, cl, topic, write):
        return not (not write and topic == "test/nosubscribe")


async def _broker():
    opts = Options()
    opts.capabilities.compatibilities.obscure_not_authorized = True
    opts.capabilities.compatibilities.passive_client_disconnect = True
    opts.capabilities.compatibilities.no_inherited_properties_on_ack = True
    srv = Server(opts)
    srv.add_hook(PahoTestingACL())
    srv.add_listener(
        TCP(ListenerConfig(type="tcp", id="interop", address=f"127.0.0.1:{PORT}"))
    )
    await srv.serve()
    return srv


class TestInterop:
    def test_connect_sub_pub_qos1_retain(self):
        async def scenario():
            srv = await _broker()
            try:
                sub = MiniV5Client()
                assert await sub.connect("127.0.0.1", PORT, "interop-sub") == 0
                assert await sub.subscribe(1, "test/topic/+", qos=1) == 1

                pub = MiniV5Client()
                assert await pub.connect("127.0.0.1", PORT, "interop-pub") == 0
                # QoS0
                await pub.publish("test/topic/a", b"zero")
                topic, payload, qos, _ = await sub.recv_publish()
                assert (topic, payload, qos) == ("test/topic/a", b"zero", 0)
                # QoS1 with PUBACK both directions
                await pub.publish("test/topic/b", b"one", qos=1, pid=7)
                topic, payload, qos, _ = await sub.recv_publish()
                assert (topic, payload, qos) == ("test/topic/b", b"one", 1)
                # retained: delivered to a later subscriber with retain set
                await pub.publish("test/retained", b"sticky", retain=True)
                late = MiniV5Client()
                assert await late.connect("127.0.0.1", PORT, "interop-late") == 0
                assert await late.subscribe(1, "test/retained", qos=0) == 0
                topic, payload, _, retain = await late.recv_publish()
                assert (topic, payload, retain) == ("test/retained", b"sticky", True)
                await sub.disconnect()
                await pub.disconnect()
                await late.disconnect()
            finally:
                await srv.close()

        run(scenario())

    def test_acl_denied_subscribe_is_obscured(self):
        async def scenario():
            srv = await _broker()
            try:
                c = MiniV5Client()
                assert await c.connect("127.0.0.1", PORT, "interop-deny") == 0
                code = await c.subscribe(1, "test/nosubscribe", qos=0)
                # ObscureNotAuthorized: 0x80 unspecified, not 0x87
                assert code == 0x80
                await c.disconnect()
            finally:
                await srv.close()

        run(scenario())

    def test_qos2_end_to_end(self):
        """Exactly-once flow both directions through the broker: the
        independent client drives PUBLISH/PUBREC/PUBREL/PUBCOMP on the
        sender side and PUBREC/PUBREL/PUBCOMP on the receiver side
        (spec 4.3.3; reference flow server.go:1175-1238)."""

        async def scenario():
            srv = await _broker()
            try:
                sub = MiniV5Client()
                assert await sub.connect("127.0.0.1", PORT, "q2-sub") == 0
                assert await sub.subscribe(11, "exactly/once", 2) == 2
                pub = MiniV5Client()
                assert await pub.connect("127.0.0.1", PORT, "q2-pub") == 0
                await pub.publish_qos2("exactly/once", b"only-one", pid=21)
                topic, payload = await asyncio.wait_for(sub.recv_publish_qos2(), 5)
                assert (topic, payload) == ("exactly/once", b"only-one")
                await pub.disconnect()
                await sub.disconnect()
            finally:
                await srv.close()

        run(scenario())

    def test_will_delivered_on_abrupt_drop(self):
        """A client that dies without DISCONNECT has its will published to
        matching subscribers (3.1.2.5; reference sendLWT server.go:1515)."""

        async def scenario():
            srv = await _broker()
            try:
                watcher = MiniV5Client()
                assert await watcher.connect("127.0.0.1", PORT, "watcher") == 0
                assert await watcher.subscribe(5, "wills/+", 0) == 0
                doomed = MiniV5Client()
                assert (
                    await doomed.connect(
                        "127.0.0.1", PORT, "doomed", will=("wills/doomed", b"gone")
                    )
                    == 0
                )
                doomed.drop()
                topic, payload, qos, retain = await asyncio.wait_for(
                    watcher.recv_publish(), 10
                )
                assert (topic, payload) == ("wills/doomed", b"gone")
                await watcher.disconnect()
            finally:
                await srv.close()

        run(scenario())


class TestHostileBytes:
    """Socket-level robustness: random and malformed byte streams must
    never crash the broker — connections either proceed or are closed,
    and the broker keeps serving well-behaved clients afterwards
    (SURVEY §5 failure detection; the reference's fault injection is
    malformed TPacketCase bytes over net.Pipe, server_test.go)."""

    def test_random_garbage_then_clean_client(self):
        async def scenario():
            srv = await _broker()
            try:
                import random as _r

                rng = _r.Random(1234)
                for i in range(30):
                    reader, writer = await asyncio.open_connection("127.0.0.1", PORT)
                    n = rng.randrange(1, 400)
                    writer.write(bytes(rng.randrange(256) for _ in range(n)))
                    try:
                        await writer.drain()
                        await asyncio.wait_for(reader.read(256), 0.25)
                    except (asyncio.TimeoutError, ConnectionError):
                        pass
                    writer.close()
                # mid-stream malformed continuation: valid CONNECT then junk
                cl = MiniV5Client()
                assert await cl.connect("127.0.0.1", PORT, "fuzz-mid") == 0
                cl.writer.write(b"\xff\xff\xff\xff\xff\xff")
                await cl.writer.drain()
                try:
                    await asyncio.wait_for(cl.reader.read(256), 1)
                except (asyncio.TimeoutError, ConnectionError):
                    pass
                # the broker still serves a clean session end to end
                good = MiniV5Client()
                assert await good.connect("127.0.0.1", PORT, "post-fuzz") == 0
                assert await good.subscribe(1, "ok/topic", 0) == 0
                await good.publish("ok/topic", b"alive")
                topic, payload, qos, retain = await asyncio.wait_for(
                    good.recv_publish(), 5
                )
                assert (topic, payload) == ("ok/topic", b"alive")
                await good.disconnect()
            finally:
                await srv.close()

        run(scenario())

    def test_oversize_remaining_length_disconnects(self):
        """With a maximum-packet-size capability set, a header declaring a
        200MB body is rejected instead of the broker waiting for the bytes
        (reference ReadFixedHeader, clients.go:453)."""

        async def scenario():
            opts = Options()
            opts.capabilities.maximum_packet_size = 1024
            srv = Server(opts)
            from mqtt_tpu.hooks.auth import AllowHook

            srv.add_hook(AllowHook())
            srv.add_listener(
                TCP(ListenerConfig(type="tcp", id="big", address=f"127.0.0.1:{PORT + 1}"))
            )
            await srv.serve()
            try:
                reader, writer = await asyncio.open_connection("127.0.0.1", PORT + 1)
                # CONNECT header declaring a 200MB body
                writer.write(b"\x10\xff\xff\xff\x7f")
                await writer.drain()
                data = await asyncio.wait_for(reader.read(64), 5)
                assert data == b"" or data[0] in (0x20, 0xE0)  # closed or rejected
            finally:
                await srv.close()

        run(scenario())
