"""Aux-surface tests: storage hooks (write-through + restore), auth ledger,
debug hook, websocket/unix/http listeners, config loader, mempool."""

import asyncio
import json
import socket
import struct

import pytest

from mqtt_tpu import Options, Server
from mqtt_tpu import config as config_mod
from mqtt_tpu.hooks.auth import (
    ACCESS_READ_ONLY,
    ACCESS_READ_WRITE,
    ACCESS_WRITE_ONLY,
    ACLRule,
    AllowHook,
    AuthHook,
    AuthOptions,
    AuthRule,
    Ledger,
    RString,
    UserRule,
    match_topic,
)
from mqtt_tpu.hooks.debug import DebugHook, DebugOptions
from mqtt_tpu.hooks.storage.memory import MemoryStore
from mqtt_tpu.hooks.storage.sqlite import SqliteOptions, SqliteStore
from mqtt_tpu.listeners import (
    Config as LConfig,
    HTTPHealthCheck,
    HTTPStats,
    UnixSock,
    Websocket,
)
from mqtt_tpu.packets import (
    CONNACK,
    CONNECT,
    PUBLISH,
    SUBSCRIBE,
    ConnectParams,
    FixedHeader,
    Packet,
    Subscription,
    decode_length,
    decode_packet,
    encode_packet,
)
from mqtt_tpu.utils.mempool import BufferPool

from tests.test_server import Harness, connect_packet, read_wire_packet, run


# -- ledger / auth ---------------------------------------------------------


class FakeClient:
    def __init__(self, id_="c1", username=b"alice", remote="1.2.3.4:5"):
        self.id = id_
        self.properties = type("P", (), {"username": username})()
        self.net = type("N", (), {"remote": remote})()


class TestMatchTopic:
    # the ledger's own matcher differs from the trie walk by design
    def test_matches(self):
        assert match_topic("a/b/+/c", "a/b/d/c") == (["d"], True)
        assert match_topic("a/#", "a/b/c") == (["b/c"], True)
        assert match_topic("a/b", "a/b") == ([], True)
        assert match_topic("a/b/#", "a/b")[1] is False  # no parent-level match
        assert match_topic("a/+", "a")[1] is False
        assert match_topic("a/b", "a/c")[1] is False


class TestRString:
    def test_matches(self):
        assert RString("").matches("anything")
        assert RString("*").matches("anything")
        assert RString("exact").matches("exact")
        assert not RString("exact").matches("other")
        assert RString("pre*").matches("prefix-anything")
        assert not RString("pre*").matches("pr")


class TestLedger:
    def _pk(self, password=b"secret"):
        return Packet(connect=ConnectParams(password=password))

    def test_users_first(self):
        ledger = Ledger(users={"alice": UserRule(password=RString("secret"))})
        assert ledger.auth_ok(FakeClient(), self._pk())[1]
        assert not ledger.auth_ok(FakeClient(), self._pk(b"wrong"))[1]

    def test_users_disallow(self):
        ledger = Ledger(
            users={"alice": UserRule(password=RString("secret"), disallow=True)}
        )
        assert not ledger.auth_ok(FakeClient(), self._pk())[1]

    def test_auth_rules_in_order(self):
        ledger = Ledger(auth=[AuthRule(username=RString("alice"), allow=True)])
        assert ledger.auth_ok(FakeClient(), self._pk())[1]
        assert ledger.auth_ok(FakeClient(username=b"bob"), self._pk())[1] is False

    def test_acl_filters(self):
        ledger = Ledger(
            users={
                "alice": UserRule(
                    acl={
                        RString("read/#"): ACCESS_READ_ONLY,
                        RString("write/#"): ACCESS_WRITE_ONLY,
                        RString("both/#"): ACCESS_READ_WRITE,
                    }
                )
            }
        )
        cl = FakeClient()
        assert ledger.acl_ok(cl, "read/x", False)[1]
        assert not ledger.acl_ok(cl, "read/x", True)[1]
        assert ledger.acl_ok(cl, "write/x", True)[1]
        assert not ledger.acl_ok(cl, "write/x", False)[1]
        assert ledger.acl_ok(cl, "both/x", True)[1]
        assert ledger.acl_ok(cl, "both/x", False)[1]

    def test_acl_rules_then_auth_fallback(self):
        ledger = Ledger(
            auth=[AuthRule(username=RString("alice"), allow=True)],
            acl=[ACLRule(username=RString("bob"), filters={RString("b/#"): ACCESS_READ_WRITE})],
        )
        assert ledger.acl_ok(FakeClient(), "anything", True)[1]  # via auth fallback
        assert ledger.acl_ok(FakeClient(username=b"bob"), "b/x", True)[1]
        assert not ledger.acl_ok(FakeClient(username=b"carol"), "b/x", True)[1]

    def test_unmarshal_json_yaml(self):
        data = {
            "users": {"u": {"password": "p", "acl": {"t/#": ACCESS_READ_WRITE}}},
            "auth": [{"username": "x", "allow": True}],
            "acl": [{"client": "c*", "filters": {"f/#": ACCESS_READ_ONLY}}],
        }
        for raw in (json.dumps(data).encode(), __import__("yaml").safe_dump(data).encode()):
            ledger = Ledger()
            ledger.unmarshal(raw)
            assert "u" in ledger.users
            assert ledger.auth[0].allow
            assert ledger.acl[0].client == "c*"

    def test_auth_hook(self):
        hook = AuthHook()
        hook.init(AuthOptions(ledger=Ledger(auth=[AuthRule(allow=True)])))
        assert hook.on_connect_authenticate(FakeClient(), self._pk())
        assert hook.on_acl_check(FakeClient(), "t", True)


# -- storage hooks ---------------------------------------------------------


def _roundtrip_store(make_hook):
    """Drive a broker session with a storage hook attached, then restore a
    fresh broker from the same store and check the five datasets."""

    async def scenario():
        store = make_hook()
        h = Harness()
        h.server.add_hook(store, getattr(store, "_test_config", None))

        # v4 + clean=False is a persistent session (restore keeps it;
        # v5 with session-expiry 0 would expire on load, server.go:1667)
        r, w, _ = await h.connect("persist-cl", version=4, clean=False)
        w.write(
            encode_packet(
                Packet(
                    fixed_header=FixedHeader(type=SUBSCRIBE, qos=1),
                    protocol_version=4,
                    packet_id=1,
                    filters=[Subscription(filter="stored/+", qos=1)],
                )
            )
        )
        await w.drain()
        await read_wire_packet(r, 4)
        w.write(
            encode_packet(
                Packet(
                    fixed_header=FixedHeader(type=PUBLISH, retain=True),
                    protocol_version=4,
                    topic_name="stored/ret",
                    payload=b"keep",
                )
            )
        )
        await w.drain()
        await asyncio.sleep(0.05)
        h.server.publish_sys_topics()

        subs = store.stored_subscriptions()
        assert [s.filter for s in subs] == ["stored/+"]
        clients = store.stored_clients()
        assert [c.id for c in clients] == ["persist-cl"]
        retained = store.stored_retained_messages()
        # $SYS topics are retained too; find ours
        assert any(m.topic_name == "stored/ret" and m.payload == b"keep" for m in retained)
        assert store.stored_sys_info() is not None

        # restore into a fresh broker, attaching the already-initialized
        # store without re-running init
        h2 = Harness()
        h2.server.hooks._hooks = h2.server.hooks._hooks + [store]
        h2.server.read_store()
        assert h2.server.clients.get("persist-cl") is not None
        assert len(h2.server.topics.subscribers("stored/x").subscriptions) == 1
        assert any(
            p.topic_name == "stored/ret" for p in h2.server.topics.messages("stored/#")
        )
        await h.shutdown()
        await h2.shutdown()

    run(scenario())


class TestStorageHooks:
    def test_memory_store_roundtrip(self):
        _roundtrip_store(MemoryStore)

    def test_sqlite_store_roundtrip(self, tmp_path):
        def make():
            store = SqliteStore()
            store._test_config = SqliteOptions(path=str(tmp_path / "t.db"))
            return store

        _roundtrip_store(make)

    def test_sqlite_persists_across_instances(self, tmp_path):
        path = str(tmp_path / "p.db")
        s1 = SqliteStore()
        s1.init(SqliteOptions(path=path))
        s1._set("CL_x", b'{"id": "x"}')
        s1.stop()
        s2 = SqliteStore()
        s2.init(SqliteOptions(path=path))
        assert s2._get("CL_x") == b'{"id": "x"}'
        assert s2._iter("CL") == [b'{"id": "x"}']
        s2._del("CL_x")
        assert s2._get("CL_x") is None
        s2.stop()

    def test_redis_store_gated(self):
        from mqtt_tpu.hooks.storage.redis import RedisStore

        store = RedisStore()
        with pytest.raises((RuntimeError, Exception)):
            store.init(None)  # redis lib absent or server unreachable

    def test_redis_store_roundtrip(self):
        from mqtt_tpu.hooks.storage.redis import RedisOptions, RedisStore

        from tests.fake_redis import FakeRedis

        def make():
            store = RedisStore()
            store._test_config = RedisOptions(client=FakeRedis())
            return store

        _roundtrip_store(make)

    def test_redis_persists_across_instances(self):
        from mqtt_tpu.hooks.storage.redis import RedisOptions, RedisStore

        from tests.fake_redis import FakeRedis

        server = {}  # one fake redis process, two hook lifetimes
        s1 = RedisStore()
        s1.init(RedisOptions(client=FakeRedis(server)))
        s1._set("CL_x", b'{"id": "x"}')
        s1.stop()
        s2 = RedisStore()
        s2.init(RedisOptions(client=FakeRedis(server)))
        assert s2._get("CL_x") == b'{"id": "x"}'
        assert list(s2._iter("CL")) == [b'{"id": "x"}']
        s2._del("CL_x")
        assert s2._get("CL_x") is None
        s2.stop()

    def test_redis_prefix_isolation(self):
        from mqtt_tpu.hooks.storage.redis import RedisOptions, RedisStore

        from tests.fake_redis import FakeRedis

        server = {}
        a = RedisStore()
        a.init(RedisOptions(client=FakeRedis(server), h_prefix="a-"))
        b = RedisStore()
        b.init(RedisOptions(client=FakeRedis(server), h_prefix="b-"))
        a._set("CL_x", b"1")
        b._set("CL_x", b"2")
        assert a._get("CL_x") == b"1"
        assert b._get("CL_x") == b"2"
        assert list(a._iter("CL")) == [b"1"]


# -- debug hook ------------------------------------------------------------


class TestDebugHook:
    def test_logs_packet_flow(self, caplog):
        import logging

        hook = DebugHook()
        hook.init(DebugOptions(show_packet_data=True))
        hook.log = logging.getLogger("debugtest")
        with caplog.at_level(logging.DEBUG, logger="debugtest"):
            cl = FakeClient()
            hook.on_packet_read(cl, Packet(fixed_header=FixedHeader(type=PUBLISH), topic_name="t", payload=b"x"))
            hook.on_packet_sent(cl, Packet(fixed_header=FixedHeader(type=CONNACK)), b"")
        assert "PUBLISH << c1" in caplog.text
        assert "CONNACK >> c1" in caplog.text

    def test_pings_hidden_by_default(self, caplog):
        import logging

        from mqtt_tpu.packets import PINGREQ

        hook = DebugHook()
        hook.init(None)
        hook.log = logging.getLogger("debugtest2")
        with caplog.at_level(logging.DEBUG, logger="debugtest2"):
            hook.on_packet_read(FakeClient(), Packet(fixed_header=FixedHeader(type=PINGREQ)))
        assert "PINGREQ" not in caplog.text


# -- listeners -------------------------------------------------------------


def _ws_client_frame(payload: bytes) -> bytes:
    """A masked client->server binary frame."""
    mask = b"\x01\x02\x03\x04"
    masked = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
    n = len(payload)
    if n < 126:
        return struct.pack("!BB", 0x82, 0x80 | n) + mask + masked
    return struct.pack("!BBH", 0x82, 0x80 | 126, n) + mask + masked


class TestWebsocketListener:
    def test_mqtt_over_websocket(self):
        async def scenario():
            h = Harness()
            ws = Websocket(LConfig(type="ws", id="ws1", address="127.0.0.1:0"))
            h.server.add_listener(ws)
            await ws.init(h.server.log)
            await ws.serve(h.server.establish_connection)
            host, port = ws.address().rsplit(":", 1)

            reader, writer = await asyncio.open_connection(host, int(port))
            writer.write(
                b"GET /mqtt HTTP/1.1\r\n"
                b"Host: x\r\nUpgrade: websocket\r\nConnection: Upgrade\r\n"
                b"Sec-WebSocket-Key: dGhlIHNhbXBsZSBub25jZQ==\r\n"
                b"Sec-WebSocket-Protocol: mqtt\r\n"
                b"Sec-WebSocket-Version: 13\r\n\r\n"
            )
            await writer.drain()
            resp = await asyncio.wait_for(reader.readuntil(b"\r\n\r\n"), 3)
            assert b"101 Switching Protocols" in resp
            assert b"Sec-WebSocket-Protocol: mqtt" in resp

            # send CONNECT in a masked binary frame; read CONNACK frame back
            writer.write(_ws_client_frame(connect_packet("wsclient", 4)))
            await writer.drain()
            head = await asyncio.wait_for(reader.readexactly(2), 3)
            assert head[0] & 0x0F == 0x2  # binary frame
            length = head[1] & 0x7F
            payload = await asyncio.wait_for(reader.readexactly(length), 3)
            ack = decode_packet(payload, 4)
            assert ack.fixed_header.type == CONNACK
            assert ack.reason_code == 0
            assert h.server.clients.get("wsclient") is not None
            writer.close()
            await ws.close(lambda _: None)
            await h.shutdown()

        run(scenario())


class TestUnixListener:
    def test_mqtt_over_unix_socket(self, tmp_path):
        async def scenario():
            h = Harness()
            path = str(tmp_path / "mqtt.sock")
            ul = UnixSock(LConfig(type="unix", id="u1", address=path))
            h.server.add_listener(ul)
            await ul.init(h.server.log)
            await ul.serve(h.server.establish_connection)
            reader, writer = await asyncio.open_unix_connection(path)
            writer.write(connect_packet("unixclient"))
            await writer.drain()
            ack = await read_wire_packet(reader)
            assert ack.fixed_header.type == CONNACK and ack.reason_code == 0
            writer.close()
            await ul.close(lambda _: None)
            await h.shutdown()

        run(scenario())


class TestHttpListeners:
    async def _http_get(self, host, port, path):
        reader, writer = await asyncio.open_connection(host, int(port))
        writer.write(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
        await writer.drain()
        data = await asyncio.wait_for(reader.read(65536), 3)
        writer.close()
        return data

    def test_healthcheck(self):
        async def scenario():
            hc = HTTPHealthCheck(LConfig(type="healthcheck", id="h1", address="127.0.0.1:0"))
            await hc.init(__import__("logging").getLogger("t"))
            host, port = hc.address().rsplit(":", 1)
            ok = await self._http_get(host, port, "/healthcheck")
            assert ok.startswith(b"HTTP/1.1 200")
            missing = await self._http_get(host, port, "/nope")
            assert missing.startswith(b"HTTP/1.1 404")
            await hc.close(lambda _: None)

        run(scenario())

    def test_sysinfo(self):
        async def scenario():
            h = Harness()
            st = HTTPStats(
                LConfig(type="sysinfo", id="s1", address="127.0.0.1:0"), h.server.info
            )
            await st.init(h.server.log)
            host, port = st.address().rsplit(":", 1)
            data = await self._http_get(host, port, "/")
            body = data.split(b"\r\n\r\n", 1)[1]
            info = json.loads(body)
            assert info["version"] == "0.1.0"
            await st.close(lambda _: None)
            await h.shutdown()

        run(scenario())


# -- config ----------------------------------------------------------------


class TestConfig:
    def test_yaml_config(self):
        raw = b"""
listeners:
  - type: tcp
    id: t1
    address: ":0"
  - type: ws
    id: ws1
    address: ":0"
hooks:
  auth:
    allow_all: true
  debug:
    show_pings: true
options:
  inline_client: true
  capabilities:
    maximum_qos: 1
    compatibilities:
      obscure_not_authorized: true
logging:
  level: warning
"""
        opts = config_mod.from_bytes(raw)
        assert opts is not None
        assert len(opts.listeners) == 2
        assert opts.inline_client
        assert opts.capabilities.maximum_qos == 1
        assert opts.capabilities.compatibilities.obscure_not_authorized
        kinds = [type(h).__name__ for h, _ in opts.hooks]
        assert kinds == ["AllowHook", "DebugHook"]

    def test_json_config(self):
        raw = json.dumps(
            {
                "listeners": [{"type": "tcp", "id": "t1", "address": ":0"}],
                "hooks": {"auth": {"allow_all": True}},
            }
        ).encode()
        opts = config_mod.from_bytes(raw)
        assert len(opts.listeners) == 1
        assert type(opts.hooks[0][0]).__name__ == "AllowHook"

    def test_config_driven_server_boots(self):
        async def scenario():
            raw = b"""
listeners:
  - type: tcp
    id: cfg-tcp
    address: "127.0.0.1:0"
hooks:
  auth:
    allow_all: true
"""
            opts = config_mod.from_bytes(raw)
            server = Server(opts)
            await server.serve()
            addr = server.listeners.get("cfg-tcp").address()
            host, port = addr.rsplit(":", 1)
            reader, writer = await asyncio.open_connection(host, int(port))
            writer.write(connect_packet("cfg-client"))
            await writer.drain()
            ack = await read_wire_packet(reader)
            assert ack.reason_code == 0
            writer.close()
            await server.close()

        run(scenario())


class TestMempool:
    def test_pool_reuse_and_cap(self):
        pool = BufferPool(max_size=8)
        b = pool.get()
        b += b"12345"
        pool.put(b)
        b2 = pool.get()
        assert b2 is b and len(b2) == 0  # cleared and reused
        big = bytearray(b"123456789")
        pool.put(big)
        assert pool.get() is not big  # oversized discarded


# -- dashboard / authfile / CLI --------------------------------------------


class TestDashboard:
    async def _http_get(self, host, port, path, auth=None):
        import base64

        reader, writer = await asyncio.open_connection(host, int(port))
        hdr = f"GET {path} HTTP/1.1\r\nHost: x\r\n"
        if auth:
            hdr += "Authorization: Basic " + base64.b64encode(auth.encode()).decode() + "\r\n"
        writer.write((hdr + "\r\n").encode())
        await writer.drain()
        data = await asyncio.wait_for(reader.readexactly(12), 3)
        try:
            data += await asyncio.wait_for(reader.read(262144), 3)
        except asyncio.TimeoutError:
            pass
        writer.close()
        return data

    def test_endpoints_and_basic_auth(self):
        from mqtt_tpu.listeners import Dashboard

        async def scenario():
            h = Harness()
            r, w, _ = await h.connect("dash-cl")
            d = Dashboard(
                LConfig(type="dashboard", id="d1", address="127.0.0.1:0"),
                h.server.info,
                h.server.clients,
                auth={"admin": "pw"},
                listener_summary="mqtt: test",
            )
            await d.init(h.server.log)
            host, port = d.address().rsplit(":", 1)

            denied = await self._http_get(host, port, "/information")
            assert b"401" in denied.split(b"\r\n", 1)[0]
            badpw = await self._http_get(host, port, "/information", "admin:nope")
            assert b"401" in badpw.split(b"\r\n", 1)[0]

            info = await self._http_get(host, port, "/information", "admin:pw")
            body = json.loads(info.split(b"\r\n\r\n", 1)[1])
            assert "clients_connected" in body

            conns = await self._http_get(host, port, "/connections", "admin:pw")
            assert b"dash-cl" in conns and b"text/html" in conns

            raw = await self._http_get(host, port, "/clientsrawdata", "admin:pw")
            assert b'"id": "dash-cl"' in raw

            rec = await self._http_get(host, port, "/processrecords", "admin:pw")
            records = json.loads(rec.split(b"\r\n\r\n", 1)[1])
            assert records and "rss_bytes" in records[0]

            missing = await self._http_get(host, port, "/nope", "admin:pw")
            assert b"404" in missing.split(b"\r\n", 1)[0]

            # regression (ADVICE r1): unknown user + empty password must NOT
            # authorize (the get(user, "") == "" bypass)
            bypass = await self._http_get(host, port, "/information", "ghost:")
            assert b"401" in bypass.split(b"\r\n", 1)[0]
            colonless = await self._http_get(host, port, "/information", "ghost")
            assert b"401" in colonless.split(b"\r\n", 1)[0]
            await d.close(lambda _: None)
            await h.shutdown()

        run(scenario())

    def test_empty_configured_password_never_authorizes(self):
        from mqtt_tpu.listeners import Dashboard

        async def scenario():
            h = Harness()
            d = Dashboard(
                LConfig(type="dashboard", id="d2", address="127.0.0.1:0"),
                h.server.info,
                h.server.clients,
                auth={"admin": ""},
            )
            await d.init(h.server.log)
            host, port = d.address().rsplit(":", 1)
            r = await self._http_get(host, port, "/information", "admin:")
            assert b"401" in r.split(b"\r\n", 1)[0]
            await d.close(lambda _: None)
            await h.shutdown()

        run(scenario())


class TestObfuscation:
    def test_roundtrip_and_passthrough(self):
        from mqtt_tpu.utils.obfuscate import obfuscate, try_deobfuscate

        for pwd in ["", "a", "hunter2", "pass:with colon", "ünïcødé"]:
            coded = obfuscate(pwd)
            assert coded.startswith("$MOB$") and coded != pwd
            assert try_deobfuscate(coded) == pwd
        assert try_deobfuscate("plaintext") == "plaintext"
        # distinct passwords -> distinct codings
        assert obfuscate("aaaa") != obfuscate("aaab")


class TestAuthfile:
    def test_sample_roundtrip(self, tmp_path):
        from mqtt_tpu.hooks.auth.authfile import (
            from_authfile,
            init_authfile,
            parse_authfile,
        )
        from mqtt_tpu.utils.obfuscate import obfuscate

        p = tmp_path / "auth.yaml"
        init_authfile(str(p))
        ledger = from_authfile(str(p))
        # disallowed sample user skipped (auth.go:56-59)
        assert "sample-acl-user" not in ledger.users
        assert str(ledger.users["device01"].password) == "secret01"
        assert ledger.users["operator"].acl

        coded = f"coded:\n    password: '{obfuscate('s3cret')}'\n"
        led = parse_authfile(coded.encode(), coded_pwd=True)
        assert str(led.users["coded"].password) == "s3cret"
        led_plain = parse_authfile(coded.encode(), coded_pwd=False)
        assert str(led_plain.users["coded"].password).startswith("$MOB$")


class TestCLI:
    def test_initauth_and_code_password(self, tmp_path, capsys):
        from mqtt_tpu.__main__ import main

        p = tmp_path / "a.yaml"
        assert main(["initauth", str(p)]) == 0
        assert p.exists()
        assert main(["code-password", "hunter2"]) == 0
        out = capsys.readouterr().out
        assert "$MOB$" in out

    def test_genecc(self, tmp_path, monkeypatch):
        from mqtt_tpu.__main__ import main

        monkeypatch.chdir(tmp_path)
        assert main(["genecc"]) == 0
        for f in ["root-key.ec.pem", "root.ec.pem", "cert-key.ec.pem", "cert.ec.pem"]:
            assert (tmp_path / f).exists(), f

    def test_admin_user_requires_password(self):
        from mqtt_tpu.__main__ import main

        with pytest.raises(SystemExit):
            main(["serve", "--admin-user", "admin"])  # missing :PASS

    def test_tls_port_requires_cert_and_key(self):
        from mqtt_tpu.__main__ import build_server
        import types

        args = types.SimpleNamespace(
            config=None, auth=None, coded_pwd=False, disable_auth=True,
            admin_user=None, port=18999, tls_port=18998, cert=None, key=None,
            rootca=None, ws_port=0, stats_port=0, dashboard_port=0, msg_timeout=0,
        )
        with pytest.raises(SystemExit):
            build_server(args)

    def test_flags_before_subcommand_survive(self, monkeypatch):
        import mqtt_tpu.__main__ as m

        captured = {}
        monkeypatch.setattr(
            m, "cmd_serve", lambda a, argv: captured.update(vars(a)) or 0
        )
        assert m.main(["--port", "1999", "serve"]) == 0
        assert captured["port"] == 1999


class TestLogKVStore:
    def test_roundtrip(self, tmp_path):
        from mqtt_tpu.hooks.storage.logkv import LogKVOptions, LogKVStore

        def make():
            store = LogKVStore()
            store._test_config = LogKVOptions(path=str(tmp_path / "kv"), gc_interval=0)
            return store

        _roundtrip_store(make)

    def test_persists_across_instances(self, tmp_path):
        from mqtt_tpu.hooks.storage.logkv import LogKVOptions, LogKVStore

        path = str(tmp_path / "kv")
        s1 = LogKVStore()
        s1.init(LogKVOptions(path=path, gc_interval=0))
        s1._set("CL_x", b'{"id": "x"}')
        s1._set("CL_y", b'{"id": "y"}')
        s1._del("CL_y")
        s1._set("RET_t", b'{"topic": "t"}')
        s1.stop()

        s2 = LogKVStore()
        s2.init(LogKVOptions(path=path, gc_interval=0))
        assert s2._get("CL_x") == b'{"id": "x"}'
        assert s2._get("CL_y") is None
        assert sorted(s2._iter("CL")) == [b'{"id": "x"}']
        assert s2._iter("RET") == [b'{"topic": "t"}']
        s2.stop()

    def test_compaction_drops_dead_records(self, tmp_path):
        import os

        from mqtt_tpu.hooks.storage.logkv import LogKVOptions, LogKVStore

        path = str(tmp_path / "kv")
        s = LogKVStore()
        s.init(LogKVOptions(path=path, gc_interval=0))
        for i in range(200):
            s._set("CL_hot", b"v" * 100)  # 199 dead versions
        size_before = sum(
            os.path.getsize(os.path.join(path, n)) for n in os.listdir(path)
        )
        assert s.compact(0.5)
        size_after = sum(
            os.path.getsize(os.path.join(path, n)) for n in os.listdir(path)
        )
        assert size_after < size_before / 10
        assert s._get("CL_hot") == b"v" * 100
        s.stop()
        # compacted store reopens correctly
        s2 = LogKVStore()
        s2.init(LogKVOptions(path=path, gc_interval=0))
        assert s2._get("CL_hot") == b"v" * 100
        s2.stop()

    def test_torn_tail_record_tolerated(self, tmp_path):
        import os

        from mqtt_tpu.hooks.storage.logkv import LogKVOptions, LogKVStore

        path = str(tmp_path / "kv")
        s = LogKVStore()
        s.init(LogKVOptions(path=path, gc_interval=0))
        s._set("CL_a", b"aaa")
        s._set("CL_b", b"bbb")
        s.stop()
        # simulate a crash mid-append: truncate the last record's crc
        seg = sorted(os.listdir(path))[-1]
        p = os.path.join(path, seg)
        os.truncate(p, os.path.getsize(p) - 2)
        s2 = LogKVStore()
        s2.init(LogKVOptions(path=path, gc_interval=0))
        assert s2._get("CL_a") == b"aaa"
        assert s2._get("CL_b") is None  # torn record dropped, not fatal
        s2.stop()

    def test_midfile_corruption_warns_and_counts(self, tmp_path, caplog):
        """A bit flip mid-file must not be a SILENT discard of everything
        after it: the replay logs the segment name + byte offset and
        counts the skipped trailing bytes in store-level counters."""
        import logging
        import os

        from mqtt_tpu.hooks.storage.logkv import LogKVOptions, LogKVStore

        path = str(tmp_path / "kv")
        s = LogKVStore()
        s.init(LogKVOptions(path=path, gc_interval=0))
        s._set("CL_a", b"va")
        s._set("CL_b", b"vb")
        s._set("CL_c", b"vc")
        s.stop()
        # each record: header(9) + key(4) + value(2) + crc(4) = 19 bytes
        seg = sorted(os.listdir(path))[0]
        p = os.path.join(path, seg)
        data = bytearray(open(p, "rb").read())
        assert len(data) == 3 * 19
        data[19 + 9 + 4] ^= 0xFF  # flip a bit in record b's value
        open(p, "wb").write(bytes(data))

        s2 = LogKVStore()
        with caplog.at_level(logging.WARNING, logger="mqtt_tpu.hook"):
            s2.init(LogKVOptions(path=path, gc_interval=0))
        assert s2._get("CL_a") == b"va"  # before the flip: intact
        assert s2._get("CL_b") is None  # the corrupt record
        assert s2._get("CL_c") is None  # trailing records: skipped
        assert s2.replay_corruptions == 1
        assert s2.replay_skipped_bytes == 2 * 19  # records b + c
        warn = [
            r for r in caplog.records if "corrupt record" in r.getMessage()
        ]
        assert warn, caplog.records
        msg = warn[0].getMessage()
        assert seg in msg and "offset=19" in msg
        s2.stop()

    def test_gc_crash_between_compact_write_and_delete(self, tmp_path):
        """Crash-safety for GC compaction: a crash AFTER writing the
        compacted segment but BEFORE deleting the old ones leaves
        overlapping segments on disk; replay (segment-sequence order,
        compacted segment last) must reconverge to the same map."""
        import os as _os

        import pytest as _pytest

        from mqtt_tpu.hooks.storage import logkv as logkv_mod
        from mqtt_tpu.hooks.storage.logkv import LogKVOptions, LogKVStore

        path = str(tmp_path / "kv")
        s = LogKVStore()
        s.init(LogKVOptions(path=path, gc_interval=0))
        for i in range(20):
            s._set(f"CL_{i}", f"v{i}".encode())
        for i in range(5):
            s._set(f"CL_{i}", f"w{i}".encode())  # dead versions
        for i in range(15, 20):
            s._del(f"CL_{i}")
        expected = dict(s._map)

        with _pytest.MonkeyPatch.context() as mp:
            # the simulated crash: the compacted segment is written and
            # fsynced, but the old-segment deletes never happen
            def crash(_p):
                raise OSError("crash injected before delete")

            mp.setattr(logkv_mod.os, "unlink", crash)
            with _pytest.raises(OSError):
                s.compact(0.0)
        s._file.close()  # abandon the crashed store

        assert len(_os.listdir(path)) >= 2  # overlapping segments remain
        s2 = LogKVStore()
        s2.init(LogKVOptions(path=path, gc_interval=0))
        assert s2._map == expected  # replay reconverged
        assert s2.replay_corruptions == 0
        s2.stop()

    def test_segment_rotation(self, tmp_path):
        import os

        from mqtt_tpu.hooks.storage.logkv import LogKVOptions, LogKVStore

        path = str(tmp_path / "kv")
        s = LogKVStore()
        s.init(LogKVOptions(path=path, gc_interval=0, max_segment_bytes=512))
        for i in range(50):
            s._set(f"CL_{i}", b"x" * 64)
        assert len(os.listdir(path)) > 1  # rotated
        s.stop()
        s2 = LogKVStore()
        s2.init(LogKVOptions(path=path, gc_interval=0))
        assert s2._get("CL_49") == b"x" * 64
        s2.stop()


class TestTLSListener:
    def test_mqtt_over_tls(self, tmp_path, monkeypatch):
        """Full MQTT connect/sub/pub over a real TLS socket, using certs
        from the CLI's genecc generator (cmd/main.go:155-185 analog)."""
        import ssl

        from mqtt_tpu.__main__ import cmd_genecc
        from mqtt_tpu.listeners import Config as LConfig
        from mqtt_tpu.listeners.tcp import TCP
        from tests.test_server import (
            Harness,
            connect_packet,
            pub_packet,
            read_wire_packet,
            run,
            sub_packet,
        )

        monkeypatch.chdir(tmp_path)
        assert cmd_genecc(None) == 0
        server_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        server_ctx.load_cert_chain(
            str(tmp_path / "cert.ec.pem"), str(tmp_path / "cert-key.ec.pem")
        )
        client_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        client_ctx.load_verify_locations(str(tmp_path / "root.ec.pem"))

        async def scenario():
            h = Harness()
            h.server.add_listener(
                TCP(
                    LConfig(
                        type="tcp",
                        id="tls1",
                        address="127.0.0.1:0",
                        tls_config=server_ctx,
                    )
                )
            )
            await h.server.serve()
            try:
                bound = h.server.listeners.get("tls1").address()
                port = int(bound.rsplit(":", 1)[1])
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", port, ssl=client_ctx, server_hostname="localhost"
                )
                writer.write(connect_packet("tls-client", 4))
                await writer.drain()
                raw = await asyncio.wait_for(reader.readexactly(4), 5)
                assert raw == bytes.fromhex("20020000")
                writer.write(sub_packet(1, [Subscription(filter="tls/#", qos=0)]))
                await writer.drain()
                await read_wire_packet(reader)
                writer.write(pub_packet("tls/x", b"secure"))
                await writer.drain()
                pk = await read_wire_packet(reader)
                assert pk.topic_name == "tls/x" and bytes(pk.payload) == b"secure"
                writer.close()
            finally:
                await h.server.close()
                await h.shutdown()

        run(scenario())


class TestConfigDeviceMatcher:
    def test_device_matcher_options_from_yaml(self):
        from mqtt_tpu.config import from_bytes

        opts = from_bytes(
            b"options:\n"
            b"  device_matcher: true\n"
            b"  matcher_stage_window_ms: 3.5\n"
            b"  matcher_opts:\n"
            b"    max_levels: 4\n"
            b"    background: false\n"
        )
        assert opts.device_matcher is True
        assert opts.matcher_stage_window_ms == 3.5
        assert opts.matcher_opts == {"max_levels": 4, "background": False}

    def test_degenerate_staging_knobs_normalized(self):
        """Config-reachable zeros must not busy-spin the collector
        (max_batch=0) or unbound the pipeline queue (max_inflight=0)."""
        from mqtt_tpu.config import from_bytes

        opts = from_bytes(
            b"options:\n"
            b"  matcher_stage_max_batch: 0\n"
            b"  matcher_stage_max_inflight: 0\n"
            b"  matcher_stage_window_ms: -1\n"
        )
        opts.ensure_defaults()
        assert opts.matcher_stage_max_batch > 0
        assert opts.matcher_stage_max_inflight > 0
        assert opts.matcher_stage_window_ms == 0.0
