"""Zero-materialization fan-out (ISSUE 13): lazy SubscribersView
semantics, the Subscription freelist pool's lifetime rules, the
encode-once variant-grouped write path, and the lazy-vs-eager delivery
differential across exact/+/#/$SHARE/predicated/tenant-scoped mixes.

The eager resolvers (accelmod.resolve_compact / resolve_batch) are the
differential oracle throughout — every lazy behavior is pinned against
them, unit-level (views) and wire-level (delivered frames).
"""

from __future__ import annotations

import asyncio
import gc

import numpy as np
import pytest

from mqtt_tpu import Options
from mqtt_tpu.packets import PUBLISH, SUBACK, Subscription
from mqtt_tpu.topics import Subscribers

from tests.test_server import (
    Harness,
    pub_packet,
    read_wire_packet,
    run,
    sub_packet,
)

acc = pytest.importorskip("mqtt_tpu.native").accel()
if acc is None:
    pytest.skip("no C toolchain: lazy views cannot exist", allow_module_level=True)

import importlib.util
import os

needs_jax = pytest.mark.skipif(
    importlib.util.find_spec("jax") is None
    or os.environ.get("MQTT_TPU_SAN") == "1",
    reason="jax not importable (the sanitizer leg also skips these: "
    "jaxlib is uninstrumented and its XLA compiler aborts under ASAN — "
    "the leg exists to verify OUR C, the view/pool/flush machinery)",
)


def wire_equiv(a: Subscription, b: Subscription) -> bool:
    """Delivery equivalence: every field publish_to_client consults.
    (A borrowed single-sighting target keeps identifiers=None where the
    eager copy materializes {filter: 0} — wire-identical, since only
    identifier values > 0 ever reach the encoder [MQTT-3.3.4-3].)"""
    ids_a = {k: v for k, v in (a.identifiers or {}).items() if v > 0}
    ids_b = {k: v for k, v in (b.identifiers or {}).items() if v > 0}
    return (
        a.qos, a.no_local, a.retain_as_published, a.fwd_retained_flag,
        a.predicates, ids_a,
    ) == (
        b.qos, b.no_local, b.retain_as_published, b.fwd_retained_flag,
        b.predicates, ids_b,
    )


def snap_fixture():
    """A 3-entry snapshot table (window 4) covering client, shared and
    inline sections plus a duplicate-client overlap."""
    sub_plus = Subscription(filter="a/+", qos=1)
    sub_exact = Subscription(filter="a/b", qos=0, no_local=True)
    sub_hash = Subscription(filter="a/#", qos=2)
    sub_ident = Subscription(filter="i/#", identifier=9, qos=1)
    shared = Subscription(filter="$share/g/a/+", qos=1)

    class _Inline:
        def __init__(self, ident):
            self.identifier = ident
            self.filter = "a/#"
            self.predicates = ()

    inline = _Inline(41)
    snaps = [
        # entry 0: two clients, one shared member, one inline
        ((("c1", sub_plus), ("c2", sub_exact)), (("s1", shared),), (inline,)),
        # entry 1: c1 again (duplicate-client merge) via a/#
        ((("c1", sub_hash),), (), ()),
        # entry 2: identifier-carrying subscription (copy-on-sight)
        ((("c3", sub_ident),), (), ()),
    ]
    return snaps, dict(
        sub_plus=sub_plus, sub_exact=sub_exact, sub_hash=sub_hash,
        sub_ident=sub_ident, shared=shared, inline=inline,
    )


def make_views(sids, totals, route, n_topics, snaps, window=4):
    sids = np.asarray(sids, dtype=np.int32)
    totals = np.asarray(totals, dtype=np.int32)
    route = np.asarray(route, dtype=np.int32)
    return acc.resolve_compact_views(
        sids, None, totals, route, int(totals.sum()), n_topics, snaps,
        window, Subscribers,
    )


def make_eager(sids, totals, route, n_topics, snaps, window=4):
    sids = np.asarray(sids, dtype=np.int32)
    totals = np.asarray(totals, dtype=np.int32)
    route = np.asarray(route, dtype=np.int32)
    return acc.resolve_compact(
        sids, None, totals, route, int(totals.sum()), n_topics, snaps,
        window, Subscribers,
    )


class TestViewSemantics:
    def test_targets_wire_equivalent_to_eager(self):
        snaps, _ = snap_fixture()
        # topic hits: c1 (a/+), c2 (a/b), c1 again (a/# -> merge)
        views, ovf = make_views([0, 1, 4], [3], [0], 1, snaps)
        eager, eovf = make_eager([0, 1, 4], [3], [0], 1, snaps)
        assert ovf == [] and eovf == []
        t = dict(views[0].targets())
        e = eager[0].subscriptions
        assert set(t) == set(e)
        for cid in e:
            assert wire_equiv(t[cid], e[cid]), cid
        # the duplicate-client entry is a true merge (value-equal)
        assert t["c1"] == e["c1"]
        assert t["c1"].qos == 2  # max of a/+ (1) and a/# (2)

    def test_single_sighting_is_zero_copy(self):
        snaps, fix = snap_fixture()
        views, _ = make_views([1], [1], [0], 1, snaps)
        ((cid, sub),) = views[0].targets()
        assert cid == "c2"
        assert sub is fix["sub_exact"]  # the STORED object, no copy

    def test_identifier_carrier_is_copied_and_materialized(self):
        """identifier > 0 must take the eager first-sighting copy
        ([MQTT-3.3.4-3]: the identifiers map materializes), never the
        borrowed stored object."""
        snaps, fix = snap_fixture()
        views, _ = make_views([8], [1], [0], 1, snaps)
        ((cid, sub),) = views[0].targets()
        assert cid == "c3"
        assert sub is not fix["sub_ident"]
        assert sub.identifiers == {"i/#": 9}
        # the stored subscription was NOT mutated (identifiers was None)
        assert fix["sub_ident"].identifiers is None

    def test_classification_flags(self):
        snaps, _ = snap_fixture()
        views, _ = make_views([0, 2, 3], [3], [0], 1, snaps)
        v = views[0]
        assert v.has_shared and v.has_inline
        views2, _ = make_views([0, 1], [2], [0], 1, snaps)
        assert not views2[0].has_shared and not views2[0].has_inline

    def test_materialize_matches_eager_exactly(self):
        snaps, _ = snap_fixture()
        sids, totals, route = [0, 1, 2, 3, 4], [5], [0]
        views, _ = make_views(sids, totals, route, 1, snaps)
        eager, _ = make_eager(sids, totals, route, 1, snaps)
        m = views[0].materialize()
        assert m.subscriptions == eager[0].subscriptions
        assert m.shared == eager[0].shared
        assert m.inline_subscriptions == eager[0].inline_subscriptions

    def test_attribute_delegation_and_len(self):
        snaps, _ = snap_fixture()
        views, _ = make_views([0, 2], [2], [0], 1, snaps)
        v = views[0]
        assert v.is_lazy
        assert len(v) == 2
        # dict-semantics access transparently materializes
        assert set(v.subscriptions) == {"c1"}
        assert set(v.shared) == {"$share/g/a/+"}
        assert not v.is_lazy
        # Subscribers methods reach through too (select_shared mutates
        # the materialized result via setattr delegation)
        v.select_shared()
        assert v.shared_selected

    def test_routed_rows_and_geometry_tripwire(self):
        snaps, _ = snap_fixture()
        views, ovf = make_views([0, 1], [1, 1], [0, 1], 2, snaps)
        assert ovf == [1] and views[1] is None and views[0] is not None
        with pytest.raises(ValueError):
            # totals claim more pairs than the stream carries
            acc.resolve_compact_views(
                np.array([0], dtype=np.int32), None,
                np.array([3], dtype=np.int32),
                np.array([0], dtype=np.int32),
                3, 1, snaps, 4, Subscribers,
            )

    def test_ranges_views_match_eager(self):
        snaps, _ = snap_fixture()
        P = 2
        packed = np.array(
            [
                [0, 4, 2, 1, 3, 0],  # sids 0,1 + 4 (c1 dup-merge)
                [8, 0, 1, 0, 1, 0],  # sid 8 (identifier carrier)
                [0, 0, 0, 0, 0, 1],  # overflow row
            ],
            dtype=np.int32,
        )
        lazy, lovf = acc.resolve_batch_views(
            packed, 3, P, snaps, 4, Subscribers
        )
        eager, eovf = acc.resolve_batch(packed, 3, P, snaps, 4, Subscribers)
        assert lovf == eovf == [2]
        assert lazy[2] is None
        for i in range(2):
            t = dict(lazy[i].targets())
            e = eager[i].subscriptions
            assert set(t) == set(e)
            for cid in e:
                assert wire_equiv(t[cid], e[cid])
        assert len(lazy[0]) == 3

    def test_empty_view(self):
        snaps, _ = snap_fixture()
        views, _ = make_views([], [0], [0], 1, snaps)
        v = views[0]
        assert len(v) == 0 and v.targets() == []
        assert not v.has_shared and not v.has_inline
        assert v.materialize().subscriptions == {}


class TestFreelistPool:
    def test_pool_cycles_and_reuses(self):
        snaps, _ = snap_fixture()
        acc.pool_clear()
        base = acc.view_stats()
        for _ in range(3):
            views, _ = make_views([8], [1], [0], 1, snaps)
            views[0].targets()
            del views
            gc.collect()
        st = acc.view_stats()
        assert st["pool_returns"] - base["pool_returns"] >= 3
        assert st["pool_hits"] - base["pool_hits"] >= 2

    def test_consumer_held_copy_is_never_recycled(self):
        """UAF-safety: a pool copy the consumer still references must
        NOT be parked when its view dies — recycling it would alias a
        live Subscription."""
        snaps, fix = snap_fixture()
        acc.pool_clear()
        views, _ = make_views([8], [1], [0], 1, snaps)
        ((_cid, held),) = views[0].targets()
        snapshot = (held.filter, held.identifier, dict(held.identifiers))
        base = acc.view_stats()["pool_returns"]
        del views
        gc.collect()
        assert acc.view_stats()["pool_returns"] == base  # not parked
        # another round may allocate fresh copies; the held object must
        # stay untouched throughout
        views2, _ = make_views([8], [1], [0], 1, snaps)
        views2[0].targets()
        del views2
        gc.collect()
        assert (held.filter, held.identifier, dict(held.identifiers)) == snapshot
        assert wire_equiv(held, fix["sub_ident"].self_merged_copy())

    def test_snapshot_pins_subscriptions_across_mutation(self):
        """The view's batch owns the snapshot list: dropping every
        other reference to the stored subscriptions (the unsubscribe
        analog) must leave consumption intact — lifetime safety is by
        ownership, not by luck."""
        snaps, fix = snap_fixture()
        views, _ = make_views([0, 1, 4], [3], [0], 1, snaps)
        del snaps, fix
        gc.collect()
        t = dict(views[0].targets())
        assert t["c1"].qos == 2 and t["c2"].filter == "a/b"


def _collect(r, n, version=4):
    async def inner():
        out = []
        for _ in range(n):
            pk = await read_wire_packet(r, version)
            assert pk.fixed_header.type == PUBLISH
            out.append(
                (
                    pk.topic_name,
                    bytes(pk.payload),
                    pk.fixed_header.qos,
                    pk.fixed_header.retain,
                    pk.packet_id,
                    tuple(pk.properties.subscription_identifier or ()),
                )
            )
        return out

    return inner()


@needs_jax
class TestDeliveryDifferential:
    """Delivered wire frames must be bit-identical between the lazy
    batched path and the legacy eager path across subscription shapes."""

    SCENARIO = [
        # (client id, version, filters [(filter, qos)])
        ("exact", 4, [("d/t/1", 0)]),
        ("plus", 4, [("d/+/1", 1)]),
        ("hash", 5, [("d/#", 1)]),
        ("multi", 4, [("d/+/1", 0), ("d/t/+", 1)]),  # dup-merge target
        ("shared", 4, [("$share/g/d/t/1", 1)]),
        ("pred", 5, [("d/t/2$GT{5}", 0)]),
    ]
    PUBLISHES = [
        ("d/t/1", b"alpha", 0),
        ("d/t/1", b"beta", 1),
        ("d/t/2", b"9.5", 0),   # passes $GT{5}
        ("d/t/2", b"1.0", 0),   # filtered for pred, delivered to hash
        ("d/x/9", b"gamma", 1),  # only d/#
    ]
    EXPECTED = {
        "exact": 2, "plus": 2, "hash": 5, "multi": 4, "shared": 2,
        "pred": 1,
    }

    def _run_scenario(self, lazy: bool):
        async def scenario():
            h = Harness(
                Options(
                    inline_client=True,
                    device_matcher=True,
                    matcher_opts={"max_levels": 4, "background": False},
                    matcher_lazy_views=lazy,
                    fanout_batch=lazy,
                )
            )
            await h.server.serve()
            conns = {}
            for cid, ver, filters in self.SCENARIO:
                r, w, _ = await h.connect(cid, version=ver)
                w.write(
                    sub_packet(
                        1,
                        [Subscription(filter=f, qos=q) for f, q in filters],
                        version=ver,
                    )
                )
                await w.drain()
                assert (await read_wire_packet(r, ver)).fixed_header.type == SUBACK
                conns[cid] = (r, ver)
            h.server.matcher.flush()
            pr, pw, _ = await h.connect("src")
            pid = 1
            for topic, payload, qos in self.PUBLISHES:
                pw.write(
                    pub_packet(topic, payload, qos=qos, pid=pid if qos else 0)
                )
                pid += 1
            await pw.drain()
            got = {}
            for cid, (r, ver) in conns.items():
                got[cid] = await asyncio.wait_for(
                    # generous: the first staged batch pays the XLA
                    # compile of the match kernel inside this wait
                    _collect(r, self.EXPECTED[cid], ver), 60
                )
            await h.server.close()
            await h.shutdown()
            return got

        return run(scenario())

    def test_lazy_matches_eager_bit_identically(self):
        lazy = self._run_scenario(True)
        eager = self._run_scenario(False)
        assert lazy == eager
        # and the lazy run actually delivered everything it promised
        assert {k: len(v) for k, v in lazy.items()} == self.EXPECTED


@needs_jax
class TestTenantScopedDifferential:
    """Tenant-scoped delivery through the lazy path: namespace-scoped
    topics resolve to views too, deliveries strip the scope prefix, and
    cross-tenant isolation + wire bytes match the eager path exactly."""

    def _run(self, lazy: bool):
        async def scenario():
            h = Harness(
                Options(
                    inline_client=True,
                    device_matcher=True,
                    matcher_opts={"max_levels": 4, "background": False},
                    matcher_lazy_views=lazy,
                    fanout_batch=lazy,
                    tenancy=True,
                    tenants={"acme": {}, "globex": {}},
                    tenant_users={
                        "a-sub": "acme", "a-pub": "acme", "g-sub": "globex",
                    },
                )
            )
            await h.server.serve()
            a_r, a_w, _ = await h.connect("a-sub")
            a_w.write(sub_packet(1, [Subscription(filter="t/+", qos=1)]))
            await a_w.drain()
            assert (await read_wire_packet(a_r)).fixed_header.type == SUBACK
            g_r, g_w, _ = await h.connect("g-sub")
            g_w.write(sub_packet(1, [Subscription(filter="t/+", qos=1)]))
            await g_w.drain()
            assert (await read_wire_packet(g_r)).fixed_header.type == SUBACK
            h.server.matcher.flush()
            p_r, p_w, _ = await h.connect("a-pub")
            p_w.write(pub_packet("t/1", b"scoped", qos=1, pid=5))
            p_w.write(pub_packet("t/2", b"zero"))
            await p_w.drain()
            got = await asyncio.wait_for(_collect(a_r, 2), 60)
            # cross-tenant isolation: globex must receive NOTHING
            with pytest.raises(asyncio.TimeoutError):
                await asyncio.wait_for(read_wire_packet(g_r), 0.4)
            await h.server.close()
            await h.shutdown()
            return got

        return run(scenario())

    def test_tenant_lazy_matches_eager(self):
        lazy = self._run(True)
        eager = self._run(False)
        assert lazy == eager
        assert [(t, p) for t, p, *_r in lazy] == [
            ("t/1", b"scoped"), ("t/2", b"zero")
        ]


@needs_jax
class TestLazyLifetimeE2E:
    def test_unsubscribe_and_disconnect_between_resolve_and_consume(self):
        """A subscriber that unsubscribes or disconnects BETWEEN device
        resolve and fan-out consumption must neither UAF nor receive
        the publish once dead — the view snapshot pins objects, the
        live client registry gates delivery."""

        async def scenario():
            h = Harness(
                Options(
                    inline_client=True,
                    device_matcher=True,
                    matcher_opts={"max_levels": 4, "background": False},
                )
            )
            await h.server.serve()
            r1, w1, _ = await h.connect("stay")
            w1.write(sub_packet(1, [Subscription(filter="l/+", qos=0)]))
            await w1.drain()
            assert (await read_wire_packet(r1)).fixed_header.type == SUBACK
            r2, w2, _ = await h.connect("leave")
            w2.write(sub_packet(1, [Subscription(filter="l/+", qos=0)]))
            await w2.drain()
            assert (await read_wire_packet(r2)).fixed_header.type == SUBACK
            h.server.matcher.flush()

            # resolve views OUT OF BAND (the exact state fan-out sees),
            # then kill the subscriber before consumption
            views = h.server.matcher.match_topics(["l/1"])
            leaver = h.server.clients.get("leave")
            leaver.stop()
            h.server.clients.delete("leave")
            gc.collect()
            targets = dict(views[0].targets())
            assert set(targets) == {"stay", "leave"}  # snapshot-time truth
            # now the real fan-out: only the live client receives
            pr, pw, _ = await h.connect("src")
            pw.write(pub_packet("l/1", b"z"))
            await pw.drain()
            pk = await read_wire_packet(r1)
            assert pk.topic_name == "l/1"
            assert h.server.clients.get("leave") is None
            await h.server.close()
            await h.shutdown()

        run(scenario())


@needs_jax
class TestScanGate:
    def test_coalesced_scans_deliver_identically(self):
        async def scenario():
            h = Harness(Options(inline_client=True, scan_coalesce=True))
            await h.server.serve()
            gate = h.server._ops.scan_gate
            assert gate is not None
            r, w, _ = await h.connect("sub")
            w.write(sub_packet(1, [Subscription(filter="s/#", qos=0)]))
            await w.drain()
            assert (await read_wire_packet(r)).fixed_header.type == SUBACK
            pr, pw, _ = await h.connect("pub")
            n = 16
            for i in range(n):
                pw.write(pub_packet(f"s/{i}", f"m{i}".encode()))
            await pw.drain()
            for i in range(n):
                pk = await read_wire_packet(r)
                assert pk.fixed_header.type == PUBLISH
            assert gate.batches > 0 and gate.scans >= gate.batches
            await h.server.close()
            await h.shutdown()

        run(scenario())


class TestRecryptAssembly:
    def test_c_frame_assembly_matches_numpy(self):
        from mqtt_tpu import native

        head = b"\x30\x20\x00\x03a/b"
        n, pt = 5, b"secret payload bytes"
        rng = np.random.default_rng(7)
        nonces = rng.integers(0, 256, (n, 12), dtype=np.uint8)
        ks = rng.integers(0, 256, (n, 32), dtype=np.uint8)
        out = native.assemble_frames(head, nonces, ks, pt)
        if out is None:
            pytest.skip("native library unavailable")
        pt_arr = np.frombuffer(pt, dtype=np.uint8)
        for i in range(n):
            expect = (
                head + nonces[i].tobytes()
                + (ks[i][: len(pt)] ^ pt_arr).tobytes()
            )
            assert out[i].tobytes() == expect

    def test_assembly_refuses_short_keystream(self):
        from mqtt_tpu import native

        nonces = np.zeros((1, 12), dtype=np.uint8)
        ks = np.zeros((1, 4), dtype=np.uint8)
        assert native.assemble_frames(b"h", nonces, ks, b"longer-than-4") is None
