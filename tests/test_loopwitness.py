"""LoopWitness/LoopPlane unit coverage (ISSUE 19): the arming matrix
(disarmed / recording / mid-session escalation), seam selection, and
violation semantics — plus the instrumented OutboundQueue touch points
driven against a private plane so the session-wide witness
(tests/conftest.py) stays undisturbed.

The cross-validation against the static model lives in
tests/test_zz_loopwitness.py; this file proves the witness machinery
itself.
"""

import asyncio
import threading

import pytest

import mqtt_tpu.clients as clients_mod
from mqtt_tpu.clients import OutboundQueue
from mqtt_tpu.utils.loopwitness import (
    DEFAULT_LOOP_PLANE,
    LoopAffinityViolation,
    LoopPlane,
    LoopWitness,
    current_loop,
)


class TestLoopWitnessUnit:
    def test_note_crossing_picks_seam_by_owner(self):
        w = LoopWitness()

        async def drive():
            me = asyncio.get_running_loop()
            # no affinity established yet -> the local seam
            w.note_crossing("k", "local", "cross", None)
            # on the owning loop -> local
            w.note_crossing("k2", "local", "cross", me)
            # owner is some OTHER loop -> cross
            other = asyncio.new_event_loop()
            try:
                w.note_crossing("k3", "local", "cross", other)
            finally:
                other.close()

        asyncio.run(drive())
        assert ("k", "local") in w.edges
        assert ("k2", "local") in w.edges
        assert ("k3", "cross") in w.edges
        # plain-thread context (no running loop) with an owner -> cross
        owner = asyncio.new_event_loop()
        try:
            assert current_loop() is None
            w.note_crossing("k4", "local", "cross", owner)
        finally:
            owner.close()
        assert ("k4", "cross") in w.edges

    def test_note_records_first_seen_evidence_once(self):
        w = LoopWitness()
        w.note("k", "s", detail="first")
        w.note("k", "s", detail="second")
        thread_name, detail = w.edges[("k", "s")]
        assert thread_name == threading.current_thread().name
        assert detail == "first"

    def test_check_owner_collects_without_raising_when_recording(self):
        w = LoopWitness()  # recording mode
        owner = asyncio.new_event_loop()
        try:
            w.check_owner("k", "s", owner, detail="cid-1")
        finally:
            owner.close()
        assert w.edges == {}  # a violation is not a legal seam traversal
        assert len(w.violations) == 1
        assert "off its owning loop" in w.violations[0]
        assert "cid-1" in w.violations[0]

    def test_check_owner_raises_when_armed_raising(self):
        w = LoopWitness(raise_on_violation=True)
        owner = asyncio.new_event_loop()
        try:
            with pytest.raises(LoopAffinityViolation):
                w.check_owner("k", "s", owner)
        finally:
            owner.close()
        assert len(w.violations) == 1  # collected AND raised

    def test_check_owner_legal_on_owner_or_unattached(self):
        w = LoopWitness(raise_on_violation=True)
        w.check_owner("k", "s", None)  # not yet attached: trivially legal

        async def drive():
            w.check_owner("k2", "s2", asyncio.get_running_loop())

        asyncio.run(drive())
        assert ("k", "s") in w.edges and ("k2", "s2") in w.edges
        assert w.violations == []


class TestLoopPlaneArmingMatrix:
    def test_disarmed_plane_is_inert(self):
        plane = LoopPlane()
        assert plane.active is False and plane.witness is None

    def test_arm_is_idempotent_and_returns_same_witness(self):
        plane = LoopPlane()
        w1 = plane.arm_witness()
        w2 = plane.arm_witness()
        assert w1 is w2 and plane.active is True
        assert w1.raise_on_violation is False

    def test_mid_session_escalation_never_deescalates(self):
        # the schedule fuzzer's contract: conftest arms a recording
        # witness first; the fuzzer escalates IN PLACE to raising, and
        # a later recording arm must not quietly drop the tripwire
        plane = LoopPlane()
        w = plane.arm_witness()
        w.note("k", "s")
        escalated = plane.arm_witness(raise_on_violation=True)
        assert escalated is w  # same witness, evidence preserved
        assert w.raise_on_violation is True
        assert ("k", "s") in w.edges
        again = plane.arm_witness(raise_on_violation=False)
        assert again is w and w.raise_on_violation is True

    def test_disarm_detaches_and_reset_clears_in_place(self):
        plane = LoopPlane()
        w = plane.arm_witness()
        w.note("k", "s")
        w.violations.append("x")
        plane.reset()
        assert plane.witness is w  # reset keeps the attachment
        assert w.edges == {} and w.violations == []
        plane.disarm_witness()
        assert plane.witness is None and plane.active is False


class TestInstrumentedTouchPoints:
    """Drive the real OutboundQueue seams against a PRIVATE plane
    swapped into mqtt_tpu.clients, covering all three arming states
    without touching the session witness."""

    @pytest.fixture
    def plane(self, monkeypatch):
        p = LoopPlane()
        monkeypatch.setattr(clients_mod, "_LOOP_PLANE", p)
        return p

    def _put_get(self):
        async def drive():
            q = OutboundQueue(maxsize=4)
            q.put_nowait(b"x")
            assert await q.get() == b"x"
            return q

        return asyncio.run(drive())

    def test_disarmed_records_nothing(self, plane):
        self._put_get()
        assert plane.witness is None  # never materialized a witness

    def test_armed_records_queue_seams(self, plane):
        w = plane.arm_witness()
        self._put_get()
        assert ("outbound_queue", "put_local") in w.edges
        assert ("outbound_queue", "get_owner") in w.edges

    def test_cross_thread_put_records_cross_seam(self, plane):
        w = plane.arm_witness()

        async def drive():
            q = OutboundQueue(maxsize=4)
            getter = asyncio.ensure_future(q.get())
            await asyncio.sleep(0)  # park the consumer (stamps the owner)
            t = threading.Thread(target=q.put_nowait, args=(b"y",))
            t.start()
            t.join()
            assert await asyncio.wait_for(getter, 5) == b"y"

        asyncio.run(drive())
        assert ("outbound_queue", "put_cross") in w.edges
        assert w.violations == []

    def test_escalated_witness_trips_on_foreign_get(self, plane):
        # stamp the queue's owner on one loop, then consume from a
        # DIFFERENT loop: a real single-consumer contract breach
        w = plane.arm_witness()
        q = OutboundQueue(maxsize=4)

        async def consume():
            q.put_nowait(b"z")
            await q.get()

        asyncio.run(consume())  # stamps loop A as owner, then discards it
        assert w.violations == []
        plane.arm_witness(raise_on_violation=True)
        with pytest.raises(LoopAffinityViolation):
            asyncio.run(consume())  # a second, different loop
        assert len(w.violations) == 1

    def test_session_plane_is_armed_recording(self):
        # tier-1 runs with the conftest-armed witness; this file must
        # not have disturbed it (the private-plane fixture guarantees
        # isolation, this asserts it)
        assert DEFAULT_LOOP_PLANE.active is True
        assert DEFAULT_LOOP_PLANE.witness is not None
