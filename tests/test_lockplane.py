"""Unit tests for the lock plane's order-verification layer (ISSUE 10):
the runtime witness (held-set tracking, edge merge, cycle detection),
the plane's single-flag fast path, and the seeded preemption injector.

The whole-suite integration of the same machinery lives in
tests/test_zz_lockwitness.py (witness⊆static cross-validation) and
tests/test_race.py (graph-guided schedule fuzzing)."""

import threading

import pytest

from mqtt_tpu.utils.locked import (
    InstrumentedLock,
    LockOrderViolation,
    LockPlane,
    LockWitness,
    PreemptionInjector,
)


# -- witness held-set tracking ----------------------------------------------


class TestWitnessHeldSet:
    def test_nested_acquire_records_edge_and_stack(self):
        plane = LockPlane()
        w = plane.arm_witness()
        a = InstrumentedLock("a", plane=plane)
        b = InstrumentedLock("b", plane=plane)
        with a:
            assert w.held() == ("a",)
            with b:
                assert w.held() == ("a", "b")
            assert w.held() == ("a",)
        assert w.held() == ()
        assert ("a", "b") in w.edges
        assert ("b", "a") not in w.edges
        thread, stack = w.edges[("a", "b")]
        assert stack == ("a", "b")
        assert thread == threading.current_thread().name

    def test_non_lifo_release_drops_right_name(self):
        w = LockWitness()
        w.note_acquire("a")
        w.note_acquire("b")
        w.note_release("a")  # out of order: A released while B held
        assert w.held() == ("b",)
        w.note_release("b")
        assert w.held() == ()

    def test_reentrant_same_name_is_not_a_self_edge(self):
        plane = LockPlane()
        w = plane.arm_witness()
        r = InstrumentedLock("re", rlock=True, plane=plane)
        with r:
            with r:
                pass
        assert w.edges == {}
        assert w.held() == ()

    def test_same_name_two_instances_is_not_a_self_edge(self):
        # two tries sharing one stats name: name-level order has nothing
        # to say about one name, so no (x, x) edge and no violation
        plane = LockPlane()
        w = plane.arm_witness()
        t1 = InstrumentedLock("trie", plane=plane)
        t2 = InstrumentedLock("trie", plane=plane)
        with t1:
            with t2:
                pass
        assert w.edges == {}
        assert w.violations == []

    def test_per_thread_stacks_are_independent(self):
        w = LockWitness()
        w.note_acquire("main-held")
        seen = {}

        def other():
            seen["held"] = w.held()
            w.note_acquire("other-held")
            seen["after"] = w.held()
            w.note_release("other-held")

        t = threading.Thread(target=other, daemon=True)
        t.start()
        t.join(timeout=10)
        assert seen["held"] == ()
        assert seen["after"] == ("other-held",)
        assert w.held() == ("main-held",)
        assert w.edges == {}  # no thread ever held two names at once


# -- witness cycle detection -------------------------------------------------


class TestWitnessCycles:
    def test_reversed_order_is_a_violation(self):
        w = LockWitness()
        w.note_acquire("a")
        w.note_acquire("b")
        w.note_release("b")
        w.note_release("a")
        assert w.violations == []
        w.note_acquire("b")
        w.note_acquire("a")  # closes a -> b -> a
        assert len(w.violations) == 1
        assert "a" in w.violations[0] and "b" in w.violations[0]

    def test_three_party_cycle_detected(self):
        w = LockWitness()
        for src, dst in (("a", "b"), ("b", "c")):
            w.note_acquire(src)
            w.note_acquire(dst)
            w.note_release(dst)
            w.note_release(src)
        assert w.violations == []
        w.note_acquire("c")
        w.note_acquire("a")  # a -> b -> c -> a
        assert len(w.violations) == 1
        assert "->" in w.violations[0]

    def test_raise_on_cycle(self):
        w = LockWitness(raise_on_cycle=True)
        w.note_acquire("x")
        w.note_acquire("y")
        w.note_release("y")
        w.note_release("x")
        w.note_acquire("y")
        with pytest.raises(LockOrderViolation):
            w.note_acquire("x")

    def test_raise_on_cycle_only_for_the_closing_acquire(self):
        # an innocent never-seen edge AFTER a recorded violation must
        # not re-raise someone else's old cycle (review regression)
        w = LockWitness(raise_on_cycle=True)
        w.note_acquire("x")
        w.note_acquire("y")
        w.note_release("y")
        w.note_release("x")
        w.note_acquire("y")
        with pytest.raises(LockOrderViolation):
            w.note_acquire("x")
        w.note_release("y")  # x was never pushed (the acquire raised)
        w.note_release("x")
        w.note_acquire("c")
        w.note_acquire("d")  # fresh edge, no cycle: must NOT raise
        w.note_release("d")
        w.note_release("c")
        assert len(w.violations) == 1

    def test_raise_on_cycle_through_lock_releases_inner(self):
        # the tripwire fails the offending acquire() CLEANLY: the inner
        # lock it just took is released, so no thread deadlocks on a
        # lock nobody will ever release (review regression)
        plane = LockPlane()
        plane.arm_witness(raise_on_cycle=True)
        a = InstrumentedLock("ra", plane=plane)
        b = InstrumentedLock("rb", plane=plane)
        with a:
            with b:
                pass
        with b:
            with pytest.raises(LockOrderViolation):
                a.acquire()
        assert not a.locked()  # the failed acquire left nothing held
        with a:  # and the lock is still usable
            pass

    def test_diamond_is_not_a_cycle(self):
        w = LockWitness()
        for src, dst in (("a", "b"), ("a", "c"), ("b", "d"), ("c", "d")):
            w.note_acquire(src)
            w.note_acquire(dst)
            w.note_release(dst)
            w.note_release(src)
        assert w.violations == []


# -- plane fast path / arming ------------------------------------------------


class TestPlaneArming:
    def test_disarmed_plane_records_nothing(self):
        plane = LockPlane()
        lk = InstrumentedLock("quiet", plane=plane)
        with lk:
            pass
        assert not plane.active
        assert plane.stats("quiet").acquisitions == 0

    def test_witness_without_stats_keeps_stats_silent(self):
        plane = LockPlane()
        w = plane.arm_witness()
        assert plane.active and not plane.enabled
        a = InstrumentedLock("w1", plane=plane)
        b = InstrumentedLock("w2", plane=plane)
        with a:
            with b:
                pass
        assert ("w1", "w2") in w.edges
        # stats arming is a separate refcount: witness alone must not
        # pay the perf_counter/histogram writes
        assert plane.stats("w1").acquisitions == 0

    def test_active_flag_tracks_all_three_modes(self):
        plane = LockPlane()
        assert not plane.active
        plane.arm()
        assert plane.active and plane.enabled
        plane.disarm()
        assert not plane.active
        plane.arm_witness()
        assert plane.active
        plane.disarm_witness()
        assert not plane.active
        plane.arm_fuzz(lambda name, phase: None)
        assert plane.active
        plane.disarm_fuzz()
        assert not plane.active

    def test_disarm_cost_is_one_flag_test(self):
        """The disarmed acquire path must not touch witness/fuzz/stats
        state at all — the overhead contract that lets the witness knob
        default off in production."""
        plane = LockPlane()
        lk = InstrumentedLock("cheap", plane=plane)
        calls = []
        plane.fuzz = calls.append  # NOT via arm_fuzz: active stays False
        with lk:
            pass
        assert calls == []  # fast path never consulted the hook
        plane.fuzz = None

    def test_arm_witness_escalates_raise_on_cycle(self):
        # a caller asking for the raising tripwire must get it even when
        # a recording witness was armed first (review regression)
        plane = LockPlane()
        w1 = plane.arm_witness()
        assert not w1.raise_on_cycle
        w2 = plane.arm_witness(raise_on_cycle=True)
        assert w2 is w1 and w1.raise_on_cycle
        # never de-escalates through arm_witness
        plane.arm_witness(raise_on_cycle=False)
        assert w1.raise_on_cycle

    def test_witness_armed_mid_hold_unwinds_cleanly(self):
        plane = LockPlane()
        lk = InstrumentedLock("mid", plane=plane)
        lk.acquire()  # fast path: no depth bookkeeping
        w = plane.arm_witness()
        lk.release()  # must not underflow or ghost-release a held name
        assert w.held() == ()
        with lk:
            assert w.held() == ("mid",)
        assert w.held() == ()


# -- preemption injector ------------------------------------------------------


class TestPreemptionInjector:
    def _drive(self, seed, ops=24, name="det-thread"):
        inj = PreemptionInjector(seed, rate=0.5, pause_s=0.0)
        out = {}

        def work():
            for i in range(ops):
                inj("lockA" if i % 2 else "lockB", "acquire")
                inj("lockA" if i % 2 else "lockB", "release")
            out["trace"] = inj.trace()[name]

        t = threading.Thread(target=work, daemon=True, name=name)
        t.start()
        t.join(timeout=10)
        return out["trace"]

    def test_same_seed_same_thread_name_same_decisions(self):
        assert self._drive(7) == self._drive(7)

    def test_different_seed_differs(self):
        assert self._drive(7) != self._drive(8)

    def test_different_thread_name_draws_its_own_stream(self):
        a = self._drive(7, name="det-a")
        b = self._drive(7, name="det-b")
        # decision logs cover identical op sequences but independent
        # RNG streams; equality would mean the streams are shared
        assert [(i, n, p) for i, n, p, _ in a] == [(i, n, p) for i, n, p, _ in b]
        assert a != b

    def test_reused_thread_name_continues_its_log(self):
        # two sequential threads sharing a name: trace() must hold the
        # COMBINED decision log, not just the second thread's (review
        # regression: the old code replaced the list)
        inj = PreemptionInjector(5, rate=0.5)

        def work():
            inj("lk", "acquire")
            inj("lk", "release")

        for _ in range(2):
            t = threading.Thread(target=work, daemon=True, name="reused")
            t.start()
            t.join(timeout=10)
        log = inj.trace()["reused"]
        assert len(log) == 4
        assert [op[0] for op in log] == [0, 1, 2, 3]  # indices continue

    def test_names_filter_skips_other_locks(self):
        inj = PreemptionInjector(3, rate=1.0, names=frozenset({"hot"}))
        inj("cold", "acquire")
        assert inj.trace() == {} or all(
            not ops for ops in inj.trace().values()
        )
        inj("hot", "acquire")
        ops = [o for log in inj.trace().values() for o in log]
        assert [(o[1], o[2]) for o in ops] == [("hot", "acquire")]

    def test_plane_integration_fires_both_phases(self):
        plane = LockPlane()
        log = []
        plane.arm_fuzz(lambda name, phase: log.append((name, phase)))
        lk = InstrumentedLock("fz", plane=plane)
        with lk:
            pass
        plane.disarm_fuzz()
        assert log == [("fz", "acquire"), ("fz", "release")]
        with lk:
            pass
        assert len(log) == 2  # disarmed: no further calls
