"""The unified telemetry plane (mqtt_tpu.telemetry): histogram bucket
math, Prometheus exposition format, the per-publish stage clock through a
real staged broker, the flight recorder's degradation triggers, the HTTP
surfaces (/metrics, 405-on-known-paths, Cache-Control), and the
monotonic-uptime drift fix.
"""

import asyncio
import json
import os

import pytest

from mqtt_tpu import Options, Server
from mqtt_tpu.listeners import Config as LConfig, HTTPHealthCheck, HTTPStats
from mqtt_tpu.packets import PUBLISH, SUBACK, Subscription
from mqtt_tpu.system import Info
from mqtt_tpu.telemetry import (
    FILL_BOUNDS,
    PUBLISH_STAGES,
    FlightRecorder,
    Histogram,
    MetricsRegistry,
    StageClock,
    Telemetry,
    check_exposition,
    escape_label_value,
)
from mqtt_tpu.topics import SYS_PREFIX

from tests.test_server import (
    Harness,
    pub_packet,
    read_wire_packet,
    run,
    sub_packet,
)


# -- histogram bucket math ---------------------------------------------------


class TestHistogram:
    def test_log_scale_boundaries(self):
        h = Histogram(base=1e-6, growth=2.0, n_buckets=36)
        assert h.bounds[0] == 1e-6
        for a, b in zip(h.bounds, h.bounds[1:]):
            assert b / a == pytest.approx(2.0)
        # +Inf overflow bucket on top of the finite bounds
        assert len(h.counts) == len(h.bounds) + 1

    def test_boundary_observation_is_le(self):
        """A value exactly on a bucket boundary counts in THAT bucket
        (Prometheus `le` semantics)."""
        h = Histogram(base=1e-6, growth=2.0, n_buckets=8)
        h.observe(h.bounds[3])
        assert h.counts[3] == 1 and sum(h.counts) == 1
        h.observe(h.bounds[3] * 1.0001)  # just past: next bucket
        assert h.counts[4] == 1

    def test_underflow_and_overflow(self):
        h = Histogram(base=1e-6, growth=2.0, n_buckets=4)
        h.observe(0.0)  # below the base: first bucket
        assert h.counts[0] == 1
        h.observe(1e9)  # past the last bound: +Inf bucket
        assert h.counts[-1] == 1
        assert h.count == 2

    def test_percentile_edge_counts(self):
        h = Histogram(base=1e-6, growth=2.0, n_buckets=16)
        assert h.percentile(0.99) == 0.0  # empty
        h.observe(3e-6)  # lands in the (2us, 4us] bucket
        # a single observation answers every quantile with its bucket
        for q in (0.01, 0.5, 0.99, 1.0):
            assert h.percentile(q) == pytest.approx(4e-6)
        # overflow observations report the largest finite bound
        h2 = Histogram(base=1e-6, growth=2.0, n_buckets=4)
        h2.observe(1e9)
        assert h2.percentile(0.99) == h2.bounds[-1]

    def test_percentile_rank_math(self):
        h = Histogram(base=1e-6, growth=2.0, n_buckets=16)
        for _ in range(99):
            h.observe(3e-6)  # -> 4us bucket
        h.observe(1e-3)  # one outlier -> ~1ms bucket
        assert h.percentile(0.50) == pytest.approx(4e-6)
        # p99 rank = ceil(0.99*100) = 99 -> still the 4us bucket
        assert h.percentile(0.99) == pytest.approx(4e-6)
        assert h.percentile(1.0) >= 1e-3

    def test_merge_of_shards(self):
        """Per-thread shards merge into one aggregate (same layout)."""
        a, b = Histogram(n_buckets=8), Histogram(n_buckets=8)
        for v in (1e-6, 5e-6, 9e-6):
            a.observe(v)
        for v in (2e-5, 3e-5):
            b.observe(v)
        a.merge(b)
        assert a.count == 5
        assert a.sum == pytest.approx(1e-6 + 5e-6 + 9e-6 + 2e-5 + 3e-5)
        assert sum(a.counts) == 5

    def test_merge_layout_mismatch_raises(self):
        a = Histogram(n_buckets=8)
        b = Histogram(n_buckets=9)
        with pytest.raises(ValueError):
            a.merge(b)

    def test_callback_histogram_renders_merged_snapshot(self):
        """A registry histogram may be backed by a scrape-time callback
        returning a merged snapshot (the sharded matcher's per-thread
        shard pattern): exposition and sys_tree render the snapshot, and
        a failing callback degrades to the empty stored child instead of
        killing the scrape."""
        from mqtt_tpu.telemetry import MetricsRegistry, check_exposition

        shards = [Histogram(), Histogram()]
        shards[0].observe(1e-5)
        shards[1].observe(2e-5)
        shards[1].observe(4e-5)

        def merged():
            out = Histogram()
            for s in shards:
                out.merge(s)
            return out

        r = MetricsRegistry()
        r.histogram("mqtt_tpu_shardy_seconds", "merged shards", fn=merged)
        text = r.exposition()
        check_exposition(text)
        assert "mqtt_tpu_shardy_seconds_count 3" in text
        tree = r.sys_tree()
        assert tree["shardy_seconds/count"] == 3
        shards[0].observe(8e-5)  # live: the next scrape sees new data
        assert r.sys_tree()["shardy_seconds/count"] == 4

        def boom():
            raise RuntimeError("shard walk failed")

        r2 = MetricsRegistry()
        r2.histogram("mqtt_tpu_shardy_seconds", "merged shards", fn=boom)
        assert "mqtt_tpu_shardy_seconds_count 0" in r2.exposition()

    def test_linear_bounds_for_ratios(self):
        h = Histogram(bounds=FILL_BOUNDS)
        h.observe(0.05)
        h.observe(0.55)
        h.observe(1.0)
        assert h.counts[0] == 1  # <= 0.1
        assert h.counts[5] == 1  # <= 0.6
        assert h.counts[9] == 1  # exactly 1.0 -> last finite bucket
        assert h.counts[-1] == 0


# -- exposition format -------------------------------------------------------


class TestExposition:
    def test_help_type_and_samples(self):
        r = MetricsRegistry()
        r.counter("t_requests_total", "Total requests").inc(3)
        r.gauge("t_depth", "Queue depth").set(7)
        h = r.histogram("t_latency_seconds", "Latency")
        h.observe(3e-6)
        text = r.exposition()
        lines = text.splitlines()
        assert "# HELP t_requests_total Total requests" in lines
        assert "# TYPE t_requests_total counter" in lines
        assert "# TYPE t_depth gauge" in lines
        assert "# TYPE t_latency_seconds histogram" in lines
        # one TYPE line per family, even with many children
        assert sum(1 for l in lines if l.startswith("# TYPE ")) == 3
        assert "t_requests_total 3" in lines
        assert "t_depth 7" in lines
        # histogram renders cumulative buckets + sum + count
        assert any(l.startswith("t_latency_seconds_bucket{le=") for l in lines)
        assert 't_latency_seconds_bucket{le="+Inf"} 1' in lines
        assert "t_latency_seconds_count 1" in lines
        assert check_exposition(text) > 0

    def test_histogram_buckets_cumulative(self):
        r = MetricsRegistry()
        h = r.histogram("t_h", "x")
        for v in (1e-6, 1e-6, 1e-3, 10.0):
            h.observe(v)
        lines = [
            l for l in r.exposition().splitlines() if l.startswith("t_h_bucket")
        ]
        counts = [int(l.rsplit(" ", 1)[1]) for l in lines]
        assert counts == sorted(counts), "bucket counts must be cumulative"
        assert counts[-1] == 4  # +Inf == total count

    def test_label_escaping(self):
        assert escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
        r = MetricsRegistry()
        r.counter("t_labeled_total", "labels", topic='we/"ird\\\n').inc()
        text = r.exposition()
        assert '\\"ird\\\\\\n' in text
        assert check_exposition(text) > 0  # the checker accepts escapes

    def test_checker_rejects_garbage(self):
        with pytest.raises(ValueError):
            check_exposition("this is not a metric line\n")
        with pytest.raises(ValueError):
            check_exposition("# TYPE foo frobnicator\nfoo 1\n")
        with pytest.raises(ValueError):
            check_exposition("")  # no samples

    def test_type_conflict_rejected(self):
        r = MetricsRegistry()
        r.counter("t_x", "a")
        with pytest.raises(ValueError):
            r.gauge("t_x", "b")
        with pytest.raises(ValueError):
            r.counter("bad name!", "c")

    def test_sys_tree(self):
        r = MetricsRegistry()
        r.counter("mqtt_tpu_foo_total", "x").inc(2)
        h = r.histogram("mqtt_tpu_lat_seconds", "x", stage="decode")
        h.observe(2e-3)
        fill = r.histogram("mqtt_tpu_fill_ratio", "x", bounds=FILL_BOUNDS)
        fill.observe(0.7)
        tree = r.sys_tree()
        assert tree["foo_total"] == 2
        assert tree["lat_seconds/decode/count"] == 1
        assert tree["lat_seconds/decode/p99_ms"] >= 2.0
        # dimensionless histograms surface RAW quantiles, never *_ms
        assert tree["fill_ratio/p50"] == pytest.approx(0.7)
        assert "fill_ratio/p50_ms" not in tree


# -- stage clock / sampling --------------------------------------------------


class TestStageClockAndSampling:
    def test_stage_durations_sum_to_total(self):
        c = StageClock()
        c.stamp("decode")
        c.stamp("admission")
        c.stamp("fanout")
        assert [s for s, _ in c.stages] == ["decode", "admission", "fanout"]
        assert sum(dt for _, dt in c.stages) == pytest.approx(c.total())

    def test_one_in_n_sampling(self):
        t = Telemetry(sample=4)
        clocks = [t.publish_clock() for _ in range(12)]
        assert sum(1 for c in clocks if c is not None) == 3
        assert clocks[3] is not None and clocks[0] is None

    def test_sampling_disabled(self):
        t = Telemetry(sample=0)
        assert all(t.publish_clock() is None for _ in range(10))
        assert not any(t.sample_outbound() for _ in range(10))

    def test_observe_publish_feeds_histograms_and_ring(self):
        t = Telemetry(sample=1, ring=4)
        for i in range(6):
            c = t.publish_clock()
            c.stamp("decode")
            c.stamp("fanout")
            t.observe_publish(c, topic=f"a/{i}", qos=0)
        assert t.stage_hist["decode"].count == 6
        assert t.stage_hist["fanout"].count == 6
        assert len(t.recorder.ring) == 4  # ring bounded
        rec = list(t.recorder.ring)[-1]
        assert rec["topic"] == "a/5" and "decode" in rec["stages_ms"]


# -- flight recorder ---------------------------------------------------------


class TestFlightRecorder:
    def test_dump_and_rate_limit(self, tmp_path):
        fr = FlightRecorder(size=8, dump_dir=str(tmp_path), min_interval_s=60.0)
        for i in range(3):
            fr.add({"t": i})
        path = fr.dump("test_reason", {"k": "v"})
        assert path is not None and os.path.exists(path)
        snap = json.load(open(path))
        assert snap["reason"] == "test_reason"
        assert snap["context"] == {"k": "v"}
        assert [r["t"] for r in snap["records"]] == [0, 1, 2]
        # second dump inside the interval is suppressed
        assert fr.dump("again") is None
        assert fr.dumps == 1 and fr.dumps_suppressed == 1

    def test_dump_async_offloads_io(self, tmp_path):
        fr = FlightRecorder(size=8, dump_dir=str(tmp_path), min_interval_s=0.0)
        fr.add({"t": 1})
        fr.dump_async("async_reason")
        fr.join_writer()
        files = list(tmp_path.iterdir())
        assert len(files) == 1 and "async_reason" in files[0].name

    def test_add_during_dump_is_safe(self, tmp_path):
        """add() and dump() race from different threads without losing
        the dump to a 'deque mutated during iteration'."""
        import threading

        fr = FlightRecorder(size=512, dump_dir=str(tmp_path), min_interval_s=0.0)
        stop = threading.Event()

        def pound():
            i = 0
            while not stop.is_set():
                fr.add({"t": i})
                i += 1

        th = threading.Thread(target=pound, daemon=True)
        th.start()
        try:
            for i in range(20):
                assert fr.dump(f"race_{i}") is not None
        finally:
            stop.set()
            th.join(2)
        assert fr.dumps == 20

    def test_shed_transition_dumps(self, tmp_path):
        """A NORMAL -> SHED transition in the governor dumps the ring
        (the server wires on_transition in __init__)."""
        srv = Server(
            Options(
                telemetry_sample=1,
                telemetry_dump_dir=str(tmp_path),
                overload_eval_interval_ms=0.001,
            )
        )
        srv.overload.add_source("test", lambda: 1.0)
        srv.telemetry.recorder.add({"t": 1})
        state = srv.overload.evaluate(force=True)
        assert state == "shed"
        srv.telemetry.recorder.join_writer()  # dump IO is off-thread
        flights = sorted(tmp_path.glob("flight_*.json"))
        assert len(flights) == 1 and "overload_shed" in flights[0].name
        snap = json.load(open(flights[0]))
        assert snap["context"]["to"] == "shed"
        assert snap["context"]["gauges"]["state"] == "shed"
        # the trace plane (on by default) writes its sibling export on
        # the same writer thread (mqtt_tpu.tracing)
        traces = sorted(tmp_path.glob("traces_*.json"))
        assert len(traces) == 1 and "overload_shed" in traces[0].name

    def test_breaker_trip_dumps(self, tmp_path):
        """A matcher breaker trip dumps the ring (server chains the
        breaker's on_trip)."""
        srv = Server(
            Options(
                device_matcher=True,
                matcher_opts={"max_levels": 4, "background": False},
                breaker_failure_threshold=2,
                telemetry_dump_dir=str(tmp_path),
            )
        )
        try:
            breaker = srv.matcher.breaker
            breaker.record_failure("error")
            breaker.record_failure("error")
            assert breaker.trips == 1
            srv.telemetry.recorder.join_writer()  # dump IO is off-thread
            flights = sorted(tmp_path.glob("flight_*.json"))
            assert len(flights) == 1 and "breaker_trip" in flights[0].name
            # the trace plane's sibling export rides the same trigger
            assert len(sorted(tmp_path.glob("traces_*.json"))) == 1
        finally:
            srv.matcher.close()


# -- cluster link RTT --------------------------------------------------------


class TestClusterRtt:
    def test_pong_observes_rtt_histogram(self, tmp_path):
        import struct
        import time as _time

        from mqtt_tpu.cluster import Cluster

        srv = Server(Options(telemetry_sample=1))
        c = Cluster(srv, worker_id=0, n_workers=2, sock_dir=str(tmp_path))
        c._on_pong(1, struct.pack(">d", _time.perf_counter() - 0.005))
        h = srv.telemetry.registry.histogram(
            "mqtt_tpu_cluster_peer_rtt_seconds", peer="1"
        )
        assert h.count == 1 and h.sum >= 0.005
        c._on_pong(1, b"short")  # malformed payloads are ignored
        c._on_pong(1, struct.pack(">d", _time.perf_counter() + 100))  # anomaly
        assert h.count == 1
        text = srv.telemetry.exposition()
        assert 'mqtt_tpu_cluster_peer_rtt_seconds_bucket{peer="1"' in text
        assert check_exposition(text) > 0


# -- monotonic uptime (satellite) -------------------------------------------


class TestUptimeDrift:
    def test_uptime_survives_wall_clock_steps(self):
        info = Info(version="x", started=1_000_000)
        info._mono_started -= 7  # 7s of real elapsed time
        info.started += 3600  # wall clock stepped an hour FORWARD
        assert info.as_dict()["uptime"] == 7
        info.started -= 7200  # ...and back two hours
        assert info.uptime_now() == 7

    def test_clone_keeps_anchor_and_asdict_excludes_it(self):
        info = Info()
        info._mono_started -= 5
        c = info.clone()
        assert c.uptime_now() >= 5
        assert "_mono_started" not in c.as_dict()

    def test_sys_uptime_uses_monotonic(self):
        async def scenario():
            h = Harness()
            h.server.info._mono_started -= 9
            h.server.info.started += 10_000  # wall step must not matter
            h.server.publish_sys_topics()
            msgs = {
                p.topic_name: p for p in h.server.topics.messages("$SYS/#")
            }
            assert 9 <= int(bytes(msgs["$SYS/broker/uptime"].payload)) < 60
            await h.shutdown()

        run(scenario())


# -- HTTP surfaces -----------------------------------------------------------


async def _http(host, port, path, method="GET"):
    reader, writer = await asyncio.open_connection(host, int(port))
    writer.write(f"{method} {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
    await writer.drain()
    # the listener answers Connection: close — read to EOF, not one
    # recv (a grown /metrics body spans several TCP segments)
    chunks = []
    while True:
        chunk = await asyncio.wait_for(reader.read(65536), 3)
        if not chunk:
            break
        chunks.append(chunk)
    writer.close()
    return b"".join(chunks)


class TestHttpSurfaces:
    def test_healthcheck_method_matrix(self):
        async def scenario():
            hc = HTTPHealthCheck(
                LConfig(type="healthcheck", id="h", address="127.0.0.1:0")
            )
            await hc.init(__import__("logging").getLogger("t"))
            host, port = hc.address().rsplit(":", 1)
            ok = await _http(host, port, "/healthcheck")
            assert ok.startswith(b"HTTP/1.1 200")
            # non-GET on a KNOWN path: 405 with Allow
            post = await _http(host, port, "/healthcheck", "POST")
            assert post.startswith(b"HTTP/1.1 405") and b"Allow: GET" in post
            # unknown path: 404 regardless of method
            assert (await _http(host, port, "/nope")).startswith(b"HTTP/1.1 404")
            assert (await _http(host, port, "/nope", "POST")).startswith(
                b"HTTP/1.1 404"
            )
            await hc.close(lambda _: None)

        run(scenario())

    def test_stats_no_store_and_405(self):
        async def scenario():
            h = Harness()
            st = HTTPStats(
                LConfig(type="sysinfo", id="s", address="127.0.0.1:0"),
                h.server.info,
            )
            await st.init(h.server.log)
            host, port = st.address().rsplit(":", 1)
            data = await _http(host, port, "/")
            assert data.startswith(b"HTTP/1.1 200")
            assert b"Cache-Control: no-store" in data
            post = await _http(host, port, "/", "POST")
            assert post.startswith(b"HTTP/1.1 405") and b"Allow: GET" in post
            # no telemetry attached: /metrics is an unknown path
            assert (await _http(host, port, "/metrics")).startswith(
                b"HTTP/1.1 404"
            )
            await st.close(lambda _: None)
            await h.shutdown()

        run(scenario())

    def test_dashboard_unknown_path_404_on_post(self):
        from mqtt_tpu.listeners import Dashboard

        async def scenario():
            h = Harness()
            d = Dashboard(
                LConfig(type="dashboard", id="d", address="127.0.0.1:0"),
                h.server.info,
                h.server.clients,
            )
            await d.init(h.server.log)
            host, port = d.address().rsplit(":", 1)
            info = await _http(host, port, "/information")
            assert info.startswith(b"HTTP/1.1 200")
            assert b"Cache-Control: no-store" in info
            post = await _http(host, port, "/information", "POST")
            assert post.startswith(b"HTTP/1.1 405")
            assert (await _http(host, port, "/nope", "POST")).startswith(
                b"HTTP/1.1 404"
            )
            await d.close(lambda _: None)
            await h.shutdown()

        run(scenario())

    def test_metrics_endpoint_serves_exposition(self):
        async def scenario():
            h = Harness(Options(telemetry_sample=1))
            st = HTTPStats(
                LConfig(type="sysinfo", id="s", address="127.0.0.1:0"),
                h.server.info,
                telemetry=h.server.telemetry,
            )
            await st.init(h.server.log)
            host, port = st.address().rsplit(":", 1)
            data = await _http(host, port, "/metrics")
            head, body = data.split(b"\r\n\r\n", 1)
            assert head.startswith(b"HTTP/1.1 200")
            assert b"text/plain; version=0.0.4" in head
            assert b"Cache-Control: no-store" in head
            text = body.decode()
            assert check_exposition(text) > 0
            assert "mqtt_tpu_publish_stage_seconds" in text
            assert "mqtt_tpu_uptime_seconds" in text
            await st.close(lambda _: None)
            await h.shutdown()

        run(scenario())


# -- staged broker end-to-end ------------------------------------------------


class TestStagedPipelineTelemetry:
    def test_stage_histograms_sys_tree_and_metrics(self):
        """Every pipeline stage records through a real staged broker:
        decode -> admission -> staging_wait -> device_batch -> fanout,
        batch service/fill histograms, and both exposition surfaces."""

        async def scenario():
            h = Harness(
                Options(
                    inline_client=True,
                    device_matcher=True,
                    matcher_stage_window_ms=2.0,
                    matcher_opts={"max_levels": 4, "background": False},
                    telemetry_sample=1,  # every publish carries a clock
                )
            )
            await h.server.serve()
            tele = h.server.telemetry
            assert tele is not None and h.server._stage.telemetry is tele

            sub_r, sub_w, _ = await h.connect("sub")
            sub_w.write(sub_packet(1, [Subscription(filter="t/#", qos=0)]))
            await sub_w.drain()
            assert (await read_wire_packet(sub_r)).fixed_header.type == SUBACK
            h.server.matcher.flush()

            pub_r, pub_w, _ = await h.connect("pub")
            n = 24
            for i in range(n):
                pub_w.write(pub_packet(f"t/{i}", f"m{i}".encode()))
            await pub_w.drain()
            for _ in range(n):
                pk = await read_wire_packet(sub_r)
                assert pk.fixed_header.type == PUBLISH

            # every stage of the staged pipeline observed samples
            for stage in PUBLISH_STAGES:
                assert tele.stage_hist[stage].count > 0, stage
            assert tele.batch_service.count > 0
            assert tele.batch_fill.count > 0
            # NOTE: outbound_wait deliberately unasserted here — the
            # batched fan-out (ISSUE 13) delivers to idle sockets
            # directly, so nothing queues and there is no queue wait to
            # observe; the queued path's sampling is covered by
            # test_outbound_queue_wait_sampling
            assert tele.sampled_publishes.value >= n

            # $SYS tree surfaces the same aggregates
            h.server.publish_sys_topics()
            retained = h.server.topics.retained
            for stage in PUBLISH_STAGES:
                t = SYS_PREFIX + f"/broker/telemetry/publish_stage_seconds/{stage}/p99_ms"
                assert retained.get(t) is not None, t
            assert (
                retained.get(SYS_PREFIX + "/broker/telemetry/flight/ring_depth")
                is not None
            )

            # the exposition parses and carries the acceptance metrics
            text = tele.exposition()
            assert check_exposition(text) > 0
            for stage in PUBLISH_STAGES:
                assert f'stage="{stage}"' in text
            assert "mqtt_tpu_stage_batch_fill_ratio_bucket" in text
            assert "mqtt_tpu_matcher_batches_total" in text

            await h.server.close()
            await h.shutdown()

        run(scenario())

    def test_outbound_queue_wait_sampling(self):
        """The legacy (non-batched) fan-out delivers through the
        bounded outbound queue, so sampled enqueues observe a queue
        wait — the path the batched flush deliberately skips for idle
        sockets (ISSUE 13)."""

        async def scenario():
            h = Harness(
                Options(
                    inline_client=True,
                    telemetry_sample=1,
                    fanout_batch=False,
                )
            )
            await h.server.serve()
            tele = h.server.telemetry
            sub_r, sub_w, _ = await h.connect("sub")
            sub_w.write(sub_packet(1, [Subscription(filter="t/#", qos=0)]))
            await sub_w.drain()
            assert (await read_wire_packet(sub_r)).fixed_header.type == SUBACK
            pub_r, pub_w, _ = await h.connect("pub")
            for i in range(8):
                pub_w.write(pub_packet(f"t/{i}", b"m"))
            await pub_w.drain()
            for _ in range(8):
                pk = await read_wire_packet(sub_r)
                assert pk.fixed_header.type == PUBLISH
            assert tele.outbound_wait.count > 0

            await h.server.close()
            await h.shutdown()

        run(scenario())

    def test_disabled_telemetry_is_inert(self):
        async def scenario():
            h = Harness(Options(telemetry=False))
            await h.server.serve()
            assert h.server.telemetry is None
            r, w, _ = await h.connect("p")
            w.write(pub_packet("a/b", b"x"))
            await w.drain()
            h.server.publish_sys_topics()
            assert (
                h.server.topics.retained.get(
                    SYS_PREFIX + "/broker/telemetry/flight/ring_depth"
                )
                is None
            )
            await h.server.close()
            await h.shutdown()

        run(scenario())
