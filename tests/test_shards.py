"""Event-loop shard fabric tests (ISSUE 15 / ROADMAP item 4).

Covers the fabric's cross-shard contracts end to end over real TCP:
delivery parity with the single-loop oracle (QoS0 shared frames and
QoS1 marshaled bookkeeping), least-loaded dispatch spread, cross-shard
session takeover through the clients registry, the per-shard
slow-consumer eviction sweep vs the single-loop sweep's semantics, the
thread-safe OutboundQueue, and the staging pipeline's cross-loop
submit/resolve seam.
"""

from __future__ import annotations

import asyncio
import socket
import threading
import time

import pytest

from mqtt_tpu.clients import OutboundQueue
from mqtt_tpu.hooks.auth.allow_all import AllowHook
from mqtt_tpu.listeners import Config as LConfig
from mqtt_tpu.listeners.tcp import TCP
from mqtt_tpu.packets import DISCONNECT, PUBACK, PUBLISH, Subscription
from mqtt_tpu.server import Options, Server
from mqtt_tpu.staging import MatchStage
from mqtt_tpu.topics import Subscribers
from tests.test_server import (
    CONNACK,
    SUBACK,
    connect_packet,
    pub_packet,
    read_wire_packet,
    run,
    sub_packet,
)

TIMEOUT = 10


class FabricHarness:
    """One broker on a real TCP listener + raw socket clients."""

    def __init__(self, shards: int = 3, **opt_kw):
        opt_kw.setdefault("loop_shards", shards)
        self.server = Server(Options(**opt_kw))
        self.server.add_hook(AllowHook())
        self.server.add_listener(
            TCP(LConfig(type="tcp", id="fab", address="127.0.0.1:0"))
        )
        self.port = 0

    async def start(self):
        await self.server.serve()
        addr = self.server.listeners.get("fab").address()
        self.port = int(addr.rsplit(":", 1)[1])
        return self

    async def connect(self, client_id, version=4, clean=True, expect_code=0):
        reader, writer = await asyncio.open_connection("127.0.0.1", self.port)
        writer.write(connect_packet(client_id, version, clean=clean))
        await writer.drain()
        ack = await asyncio.wait_for(read_wire_packet(reader, version), TIMEOUT)
        assert ack.fixed_header.type == CONNACK
        assert ack.reason_code == expect_code
        return reader, writer, ack

    async def subscribe(self, reader, writer, pid, filters, version=4):
        writer.write(sub_packet(pid, filters, version=version))
        await writer.drain()
        ack = await asyncio.wait_for(read_wire_packet(reader, version), TIMEOUT)
        assert ack.fixed_header.type == SUBACK

    def shard_of(self, client_id):
        cl = self.server.clients.get(client_id)
        assert cl is not None
        fabric = self.server._fabric
        if fabric is None:
            return None
        return fabric.shard_of(cl.net.loop)

    async def stop(self):
        await self.server.close()


async def collect_publishes(reader, want, version=4, timeout=TIMEOUT):
    """Read until ``want`` PUBLISH packets arrive; returns them."""
    got = []
    deadline = time.monotonic() + timeout
    while len(got) < want:
        budget = deadline - time.monotonic()
        assert budget > 0, f"timed out with {len(got)}/{want} publishes"
        pk = await asyncio.wait_for(read_wire_packet(reader, version), budget)
        if pk.fixed_header.type == PUBLISH:
            got.append(pk)
    return got


# -- unit: the thread-safe outbound queue -----------------------------------


class TestOutboundQueue:
    def test_fifo_and_bounds(self):
        async def scenario():
            q = OutboundQueue(maxsize=3)
            for i in range(3):
                q.put_nowait(i)
            assert q.full() and q.qsize() == 3 and not q.empty()
            with pytest.raises(asyncio.QueueFull):
                q.put_nowait(99)
            assert [await q.get() for _ in range(3)] == [0, 1, 2]
            assert q.empty() and not q.full()

        run(scenario())

    def test_get_waits_for_put(self):
        async def scenario():
            q = OutboundQueue(maxsize=8)
            getter = asyncio.get_running_loop().create_task(q.get())
            await asyncio.sleep(0.01)
            assert not getter.done()
            q.put_nowait("x")
            assert await asyncio.wait_for(getter, TIMEOUT) == "x"

        run(scenario())

    def test_cross_thread_put_wakes_consumer(self):
        """A producer on a foreign thread (no loop at all) must wake a
        parked consumer through call_soon_threadsafe."""

        async def scenario():
            q = OutboundQueue(maxsize=8)
            getter = asyncio.get_running_loop().create_task(q.get())
            await asyncio.sleep(0.01)
            t = threading.Thread(target=q.put_nowait, args=("cross",))
            t.start()
            assert await asyncio.wait_for(getter, TIMEOUT) == "cross"
            t.join(TIMEOUT)

        run(scenario())

    def test_cancelled_get_clears_waiter(self):
        async def scenario():
            q = OutboundQueue(maxsize=8)
            getter = asyncio.get_running_loop().create_task(q.get())
            await asyncio.sleep(0.01)
            getter.cancel()
            with pytest.raises(asyncio.CancelledError):
                await getter
            # a later put must not wedge on the dead waiter, and a new
            # consumer still gets the item
            q.put_nowait("alive")
            assert await asyncio.wait_for(q.get(), TIMEOUT) == "alive"

        run(scenario())


# -- fabric: dispatch + delivery parity -------------------------------------


SCENARIO_FILTERS = {
    "subA": "t/#",
    "subB": "t/+/x",
    "subC": "t/1/x",
}
SCENARIO_TOPICS = ["t/1/x", "t/2/x", "t/0", "t/1/y"]
EXPECTED = {
    # host-trie oracle, computed by hand from the filters above
    "subA": {"t/1/x", "t/2/x", "t/0", "t/1/y"},
    "subB": {"t/1/x", "t/2/x"},
    "subC": {"t/1/x"},
}


async def _delivery_scenario(shards: int) -> dict:
    h = await FabricHarness(shards=shards).start()
    try:
        subs = {}
        for pid, (cid, filt) in enumerate(SCENARIO_FILTERS.items(), start=1):
            r, w, _ = await h.connect(cid)
            await h.subscribe(r, w, pid, [Subscription(filter=filt, qos=0)])
            subs[cid] = (r, w)
        pub_r, pub_w, _ = await h.connect("pub")
        for topic in SCENARIO_TOPICS:
            pub_w.write(pub_packet(topic, topic.encode()))
        await pub_w.drain()
        got = {}
        for cid in SCENARIO_FILTERS:
            pks = await collect_publishes(subs[cid][0], len(EXPECTED[cid]))
            got[cid] = {pk.topic_name for pk in pks}
            for pk in pks:
                assert bytes(pk.payload) == pk.topic_name.encode()
        return got
    finally:
        await h.stop()


class TestFabricDelivery:
    def test_delivery_matches_single_loop_oracle(self):
        """The same pub/sub scenario delivers identically with the
        fabric on (3 shards) and off (the single-loop oracle)."""

        fabric = run(_delivery_scenario(3))
        single = run(_delivery_scenario(1))
        assert fabric == single == EXPECTED

    def test_least_loaded_spread(self):
        async def scenario():
            h = await FabricHarness(shards=3).start()
            try:
                conns = [await h.connect(f"idle{i}") for i in range(9)]
                spread = h.server._fabric.spread()
                assert sum(spread.values()) == 9
                assert max(spread.values()) - min(spread.values()) <= 1
                assert h.server._fabric.dispatched == 9
                # every client's read loop runs on ITS shard's loop
                for i in range(9):
                    cl = h.server.clients.get(f"idle{i}")
                    assert h.server._fabric.owns(cl.net.loop)
                for _r, w, _a in conns:
                    w.close()
            finally:
                await h.stop()

        run(scenario())

    def test_per_shard_scan_gate_default_on(self):
        async def scenario():
            h = await FabricHarness(shards=2).start()
            try:
                r, w, _ = await h.connect("gated")
                cl = h.server.clients.get("gated")
                shard = h.shard_of("gated")
                assert cl.scan_gate is not None
                assert cl.scan_gate is shard.scan_gate
                # distinct per shard
                gates = {s.scan_gate for s in h.server._fabric.shards}
                assert len(gates) == 2
            finally:
                await h.stop()

        run(scenario())

    def test_shard_metrics_exposed(self):
        async def scenario():
            h = await FabricHarness(shards=2).start()
            try:
                r, w, _ = await h.connect("m1")
                text = h.server.telemetry.registry.exposition()
                for family in (
                    "mqtt_tpu_shard_connections",
                    "mqtt_tpu_shard_accepted_total",
                    "mqtt_tpu_shard_evictions_total",
                    "mqtt_tpu_shard_scan_batches_total",
                    "mqtt_tpu_shard_scan_buffers_total",
                    "mqtt_tpu_shard_backlog_messages",
                    "mqtt_tpu_shard_dispatch_total",
                ):
                    assert family in text, family
                assert 'shard="0"' in text and 'shard="1"' in text
            finally:
                await h.stop()

        run(scenario())


class TestCrossShardQoS1:
    def test_qos1_delivery_across_shards(self):
        """Publisher and subscriber on DIFFERENT shards: the QoS1
        bookkeeping (packet id, inflight) is marshaled to the owner
        loop and the ack cycle completes."""

        async def scenario():
            h = await FabricHarness(shards=2).start()
            try:
                sub_r, sub_w, _ = await h.connect("q1sub")
                pub_r, pub_w, _ = await h.connect("q1pub")
                assert h.shard_of("q1sub") is not h.shard_of("q1pub")
                await h.subscribe(
                    sub_r, sub_w, 1, [Subscription(filter="q/#", qos=1)]
                )
                pub_w.write(pub_packet("q/a", b"hello", qos=1, pid=7))
                await pub_w.drain()
                # publisher's inbound ack
                ack = await asyncio.wait_for(
                    read_wire_packet(pub_r, 4), TIMEOUT
                )
                assert ack.fixed_header.type == PUBACK
                assert ack.packet_id == 7
                # subscriber's delivery, marshaled cross-shard
                pk = (await collect_publishes(sub_r, 1))[0]
                assert pk.topic_name == "q/a"
                assert pk.fixed_header.qos == 1
                assert pk.packet_id > 0
                scl = h.server.clients.get("q1sub")
                assert len(scl.state.inflight) == 1
                from tests.test_server import encode_packet
                from mqtt_tpu.packets import FixedHeader, Packet

                sub_w.write(
                    encode_packet(
                        Packet(
                            fixed_header=FixedHeader(type=PUBACK),
                            protocol_version=4,
                            packet_id=pk.packet_id,
                        )
                    )
                )
                await sub_w.drain()
                deadline = time.monotonic() + TIMEOUT
                while len(scl.state.inflight) and time.monotonic() < deadline:
                    await asyncio.sleep(0.02)
                assert len(scl.state.inflight) == 0
            finally:
                await h.stop()

        run(scenario())


class TestCrossShardTakeover:
    def test_same_id_reconnects_on_another_shard(self):
        """The registry-routed takeover (ISSUE 15): a persistent session
        reconnecting onto a DIFFERENT shard inherits its subscriptions,
        and the old connection is closed from the new client's shard."""

        async def scenario():
            h = await FabricHarness(shards=2).start()
            try:
                # steer placement: filler -> shard 0, dup#1 -> shard 1,
                # dup#2 -> shard 0 (tie breaks to the lowest index)
                f_r, f_w, _ = await h.connect("filler")
                r1, w1, _ = await h.connect("dup", version=4, clean=False)
                shard1 = h.shard_of("dup")
                await h.subscribe(
                    r1, w1, 1, [Subscription(filter="take/#", qos=0)]
                )
                r2, w2, ack2 = await h.connect("dup", version=4, clean=False)
                shard2 = h.shard_of("dup")
                assert shard1 is not shard2, "takeover landed on one shard"
                assert ack2.session_present  # [MQTT-3.2.2-3]
                # the OLD connection dies (cross-shard marshaled stop)
                with pytest.raises(
                    (asyncio.IncompleteReadError, ConnectionError)
                ):
                    while True:
                        pk = await asyncio.wait_for(
                            read_wire_packet(r1, 4), TIMEOUT
                        )
                        if pk.fixed_header.type == DISCONNECT:
                            raise ConnectionResetError("takeover disconnect")
                # the inherited subscription delivers WITHOUT resubscribe
                p_r, p_w, _ = await h.connect("tpub")
                p_w.write(pub_packet("take/x", b"inherited"))
                await p_w.drain()
                pk = (await collect_publishes(r2, 1))[0]
                assert pk.topic_name == "take/x"
                assert bytes(pk.payload) == b"inherited"
            finally:
                await h.stop()

        run(scenario())


class TestPerShardEviction:
    @pytest.mark.parametrize("shards", [1, 3])
    def test_slow_consumer_evicted(self, shards):
        """Per-shard eviction-sweep semantics vs the single-loop oracle:
        the same stalled consumer under forced SHED is evicted by the
        sweep on either front-end, and with the fabric the sweep RUNS on
        the owning shard's loop."""

        async def scenario():
            h = await FabricHarness(
                shards=shards,
                overload_eval_interval_ms=20.0,
                overload_eviction_grace_ms=100.0,
                overload_min_dwell_ms=50.0,
                overload_client_buffer_limit_bytes=4096,
            ).start()
            try:
                gov = h.server.overload
                slow_r, slow_w, _ = await h.connect("stall")
                # shrink the victim's buffers so the backlog shows fast:
                # clamp BOTH kernel socket buffers, or a host with large
                # tcp autotuning limits (tcp_rmem max can be tens of MB)
                # absorbs the whole flood in the kernel and the asyncio
                # write buffer — what the sweep measures — never grows
                scl = h.server.clients.get("stall")
                import socket as _socket

                slow_w.transport.get_extra_info("socket").setsockopt(
                    _socket.SOL_SOCKET, _socket.SO_RCVBUF, 4096
                )
                scl.net.writer.transport.get_extra_info("socket").setsockopt(
                    _socket.SOL_SOCKET, _socket.SO_SNDBUF, 4096
                )
                await h.subscribe(
                    slow_r, slow_w, 1, [Subscription(filter="e/#", qos=0)]
                )
                slow_w.transport.pause_reading()  # a truly stalled reader

                pub_r, pub_w, _ = await h.connect("epub")
                payload = b"x" * 32768
                for _ in range(60):
                    pub_w.write(pub_packet("e/x", payload))
                await pub_w.drain()
                await asyncio.sleep(0.3)

                def sweep():
                    """Run the sweep where the victim's loop lives."""
                    fabric = h.server._fabric
                    if fabric is None:
                        h.server.sweep_overload()
                        return
                    gov.evaluate(force=True)
                    cl = h.server.clients.get("stall")
                    shard = fabric.shard_of(cl.net.loop)

                    async def _s():
                        return h.server.sweep_clients_for_loop(shard.loop)

                    shard.evictions += asyncio.run_coroutine_threadsafe(
                        _s(), shard.loop
                    ).result(TIMEOUT)

                sweep()  # observes the over-limit backlog
                assert scl.state.backlog_over_since is not None
                pressure = [2.0]
                gov.add_source("test", lambda: pressure[0])
                sweep()
                assert gov.state == "shed"
                assert gov.evictions == 0  # grace not elapsed
                await asyncio.sleep(0.15)
                sweep()
                assert gov.evictions == 1
                deadline = time.monotonic() + TIMEOUT
                while not scl.closed and time.monotonic() < deadline:
                    await asyncio.sleep(0.02)
                assert scl.closed
                if h.server._fabric is not None:
                    assert sum(
                        s.evictions for s in h.server._fabric.shards
                    ) >= 1
            finally:
                await h.stop()

        run(scenario())


# -- staging: cross-loop submit/resolve -------------------------------------


class _FakeMatcher:
    def __init__(self):
        self.batches = []

    def match_topics_async(self, topics, profile=None):
        self.batches.append(list(topics))

        def resolve():
            return [Subscribers() for _ in topics]

        return resolve


class TestStagingCrossLoop:
    def test_submit_from_foreign_loop_resolves_there(self):
        """A shard-loop publisher submits into a stage whose collector
        runs on another loop: the future must park AND resolve on the
        submitter's loop (mqtt_tpu.shards contract)."""

        async def scenario():
            stage = MatchStage(
                _FakeMatcher(), lambda t: Subscribers(), window_s=0.001
            )
            stage.start()
            results = {}
            loop2 = asyncio.new_event_loop()
            t = threading.Thread(target=loop2.run_forever, daemon=True)
            t.start()

            async def submit_there():
                fut = stage.submit("from/shard")
                assert fut.get_loop() is loop2
                results["value"] = await asyncio.wait_for(fut, TIMEOUT)
                results["loop"] = asyncio.get_running_loop()

            cfut = asyncio.run_coroutine_threadsafe(submit_there(), loop2)
            deadline = time.monotonic() + TIMEOUT
            while not cfut.done() and time.monotonic() < deadline:
                await asyncio.sleep(0.01)
            cfut.result(0)
            assert isinstance(results["value"], Subscribers)
            assert results["loop"] is loop2
            await stage.stop()
            loop2.call_soon_threadsafe(loop2.stop)
            t.join(TIMEOUT)
            loop2.close()

        run(scenario())

    def test_stop_resolves_foreign_parked_futures(self):
        async def scenario():
            stage = MatchStage(
                _FakeMatcher(), lambda t: Subscribers(), window_s=0.001
            )
            # armed but never started: parked entries stay parked
            stage._wake = asyncio.Event()
            loop2 = asyncio.new_event_loop()
            t = threading.Thread(target=loop2.run_forever, daemon=True)
            t.start()
            holder = {}

            async def park():
                holder["fut"] = stage.submit("parked/topic")
                await asyncio.sleep(0)

            asyncio.run_coroutine_threadsafe(park(), loop2).result(TIMEOUT)
            await stage.stop()

            async def check():
                return await asyncio.wait_for(holder["fut"], TIMEOUT)

            got = asyncio.run_coroutine_threadsafe(check(), loop2).result(
                TIMEOUT
            )
            assert isinstance(got, Subscribers)
            loop2.call_soon_threadsafe(loop2.stop)
            t.join(TIMEOUT)
            loop2.close()

        run(scenario())


class TestConfigKnobs:
    def test_options_normalization(self):
        o = Options(loop_shards=-3, loop_shard_accept="bogus")
        o.ensure_defaults()
        assert o.loop_shards == 1
        assert o.loop_shard_accept == "handoff"
        o2 = Options(loop_shards=4, loop_shard_accept="REUSEPORT")
        o2.ensure_defaults()
        assert o2.loop_shards == 4
        assert o2.loop_shard_accept == "reuseport"

    def test_config_file_passthrough(self):
        from mqtt_tpu.config import from_bytes

        opts = from_bytes(
            b'{"options": {"loop_shards": 3, "loop_shard_accept": '
            b'"reuseport", "scan_coalesce": true}}'
        )
        assert opts.loop_shards == 3
        assert opts.loop_shard_accept == "reuseport"
        assert opts.scan_coalesce is True
