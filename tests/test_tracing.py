"""The end-to-end trace plane (mqtt_tpu.tracing): span-tree integrity
and parent/child timing invariants through a real staged broker, seeded
sampling determinism, the cross-worker trace join over a 2-worker mesh,
exemplar -> flight-dump cross-linking, the pure-Python trace-event
validator, the device duty-cycle profiler's window math, and the
/traces HTTP matrix (PR 3 conventions)."""

import asyncio
import json
import time

import pytest

from mqtt_tpu import Options, Server
from mqtt_tpu.cluster import Cluster
from mqtt_tpu.listeners import Config as LConfig, HTTPStats
from mqtt_tpu.packets import (
    PUBACK,
    PUBLISH,
    SUBACK,
    Properties,
    Subscription,
    UserProperty,
)
from mqtt_tpu.telemetry import (
    DEVICE_SUBSTAGES,
    TRACE_USER_PROPERTY,
    Telemetry,
    check_exposition,
)
from mqtt_tpu.tracing import DeviceProfiler, Tracer, check_trace_events

from tests.test_server import (
    Harness,
    pub_packet,
    read_wire_packet,
    run,
    sub_packet,
)

# slop for exported microsecond timestamps: they are wall-anchored
# (epoch-scale, ~1.8e15 us), where a double's ULP is ~0.25 us — plus the
# 3-decimal rounding the export applies
EPS_US = 2.0


def spans_by_trace(doc: dict) -> dict:
    out: dict = {}
    for ev in doc["traceEvents"]:
        out.setdefault(ev["args"]["trace_id"], []).append(ev)
    return out


def assert_publish_tree(events: list) -> None:
    """The span-tree invariants for one trace's origin-worker events:
    exactly one root, every stage child parented on it, children
    back-to-back inside the root's window, ending where the root ends."""
    roots = [e for e in events if e["name"] == "publish"]
    assert len(roots) == 1
    root = roots[0]
    t0, t1 = root["ts"], root["ts"] + root["dur"]
    stages = sorted(
        (e for e in events if e["cat"] == "stage"), key=lambda e: e["ts"]
    )
    assert stages, "no stage children"
    prev_end = t0
    for ev in stages:
        assert ev["args"]["parent_id"] == root["args"]["span_id"]
        assert ev["ts"] >= t0 - EPS_US
        assert ev["ts"] + ev["dur"] <= t1 + EPS_US
        # stage spans tile the root: each begins where the last ended
        assert abs(ev["ts"] - prev_end) <= EPS_US, (ev["name"], ev["ts"], prev_end)
        prev_end = ev["ts"] + ev["dur"]
    assert abs(prev_end - t1) <= EPS_US  # the last stage closes the root


# -- tracer unit behavior ----------------------------------------------------


class TestTracer:
    def test_seeded_ids_are_deterministic(self):
        a, b = Tracer(seed=42), Tracer(seed=42)
        assert [a.new_trace_id() for _ in range(4)] == [
            b.new_trace_id() for _ in range(4)
        ]
        assert a.new_span_id() == b.new_span_id()

    def test_sampling_verdicts_and_ids_replay(self):
        """Two identically-seeded planes make identical sampling
        decisions AND identical trace ids — a repro run traces the same
        publishes under the same ids."""

        def drive():
            tele = Telemetry(sample=4)
            tele.attach_tracer(Tracer(seed=7, sample=4))
            out = []
            for i in range(16):
                c = tele.publish_clock()
                out.append((i, getattr(c, "trace_id", None)))
            return out

        assert drive() == drive()

    def test_trace_sampling_independent_of_stage_sampling(self):
        tele = Telemetry(sample=0)  # stage sampling off entirely
        tele.attach_tracer(Tracer(seed=1, sample=2))
        clocks = [tele.publish_clock() for _ in range(8)]
        traced = [c for c in clocks if c is not None]
        assert len(traced) == 4
        assert all(c.trace_id for c in traced)

    def test_ring_is_bounded(self):
        t = Tracer(ring=32, seed=0)
        for i in range(100):
            t.add_span(f"s{i}", "x", "t1", f"{i}", None, 0.0, 1e-6)
        assert len(t.ring) == 32
        assert t.spans_total == 100

    def test_finish_publish_emits_root_and_stage_children(self):
        t = Tracer(seed=3)
        tr = t.publish_trace()
        tr.stamp("decode")
        tr.stamp("admission")
        tr.stamp("fanout")
        t.finish_publish(tr, "a/b", 1)
        doc = t.export()
        assert check_trace_events(doc) == 4
        by_trace = spans_by_trace(doc)
        assert list(by_trace) == [tr.trace_id]
        assert_publish_tree(by_trace[tr.trace_id])
        root = [e for e in by_trace[tr.trace_id] if e["name"] == "publish"][0]
        assert root["args"]["topic"] == "a/b" and root["args"]["qos"] == 1

    def test_adopted_weird_trace_ids_export_safely(self):
        t = Tracer(seed=0)
        tr = t.publish_trace("client-chose-this-id/πß")
        tr.stamp("fanout")
        t.finish_publish(tr, "t", 0)
        assert check_trace_events(t.export()) == 2


# -- the trace-event validator ----------------------------------------------


class TestValidator:
    def test_rejects_garbage(self):
        with pytest.raises(ValueError):
            check_trace_events({"traceEvents": []})
        with pytest.raises(ValueError):
            check_trace_events({"nope": 1})
        with pytest.raises(ValueError):
            check_trace_events('{"traceEvents": [{"ph": "X"}]}')  # no name
        ok = {
            "name": "s", "ph": "X", "ts": 1.0, "dur": 1.0,
            "pid": 0, "tid": 0, "args": {},
        }
        for bad in (
            {**ok, "ph": "B"},
            {**ok, "dur": -1},
            {**ok, "ts": "x"},
            {**ok, "pid": "0"},
            {**ok, "args": 7},
        ):
            with pytest.raises(ValueError):
                check_trace_events({"traceEvents": [bad]})

    def test_accepts_unresolved_parents(self):
        # one worker's half of a cross-worker trace is a legal export
        ev = {
            "name": "remote_fanout", "ph": "X", "ts": 1.0, "dur": 2.0,
            "pid": 1, "tid": 9,
            "args": {"trace_id": "t", "span_id": "a", "parent_id": "elsewhere"},
        }
        assert check_trace_events({"traceEvents": [ev]}) == 1

    def test_accepts_json_string(self):
        t = Tracer(seed=1)
        t.add_span("s", "c", "t1", "a", None, 0.0, 1e-6)
        assert check_trace_events(t.export_json()) == 1


# -- device duty-cycle profiler ---------------------------------------------


class TestDeviceProfiler:
    def test_window_union_overlap_and_idle_math(self):
        p = DeviceProfiler()
        # batch 1: dispatched at t=1, synced at t=3 -> window [1, 3]
        r1, r2, r3 = p.open_batch(), p.open_batch(), p.open_batch()
        p.note_dispatch(r1, 0.0, 1.0)
        # batch 2: dispatched at t=2 (overlaps batch 1), window [2, 4]
        p.note_dispatch(r2, 1.5, 2.0)
        p.note_resolve(r1, 2.5, 3.0)
        p.note_resolve(r2, 3.5, 4.0)
        # batch 3 after a 6s idle gap: window [10, 11]
        p.note_dispatch(r3, 9.0, 10.0)
        p.note_resolve(r3, 10.5, 11.0)
        assert p.batches == 3
        # busy union [1,4] + [10,11] = 4s over wall [1, 11] = 10s
        assert p.duty_cycle() == pytest.approx(0.4)
        # summed windows 2+2+1 = 5s; overlapped [2,3] = 1s
        assert p.overlap_ratio() == pytest.approx(0.2)
        assert p.idle_gap_hist.count == 1
        assert 6.0 <= p.idle_gap_hist.percentile(0.99) <= 10.0
        block = p.bench_block()
        assert block["batches"] == 3
        assert block["duty_cycle"] == pytest.approx(0.4)
        assert block["overlap_ratio"] == pytest.approx(0.2)

    def test_record_pairing_is_exact_out_of_order(self):
        """Concurrent/out-of-order resolution (the resilience guard
        pool) cannot cross-attribute windows: each batch's boundaries
        live on its own record."""
        p = DeviceProfiler()
        a, b = p.open_batch(), p.open_batch()
        p.note_dispatch(a, 0.0, 1.0)
        p.note_dispatch(b, 1.0, 2.0)
        p.note_resolve(b, 2.0, 3.0)  # B resolves FIRST
        p.note_resolve(a, 4.0, 5.0)
        assert a.dispatch == (0.0, 1.0) and a.d2h == (4.0, 5.0)
        assert b.dispatch == (1.0, 2.0) and b.d2h == (2.0, 3.0)
        assert p.batches == 2

    def test_undispatched_record_stays_empty(self):
        # the exact-map fast path / host fallback never fill the record:
        # the staging drain then applies the coarse device_batch stamp
        p = DeviceProfiler()
        rec = p.open_batch()
        assert rec.dispatch is None and rec.d2h is None
        p.note_resolve(rec, 1.0, 2.0)  # resolve without dispatch
        assert p.batches == 0 and p.d2h_hist.count == 1
        assert p.duty_cycle() == 0.0


# -- staged broker end-to-end: span-tree integrity ---------------------------


class TestStagedSpanTree:
    def test_full_pipeline_span_tree_and_invariants(self):
        """Every sampled publish through the staged device pipeline
        yields one root with decode -> admission -> staging_wait -> h2d
        -> device_dispatch -> d2h -> encode -> flush children that tile
        the root window (the batched fan-out splits the old fanout span
        — ISSUE 13), and the export passes the validator."""

        async def scenario():
            h = Harness(
                Options(
                    inline_client=True,
                    device_matcher=True,
                    matcher_stage_window_ms=2.0,
                    matcher_opts={"max_levels": 4, "background": False},
                    telemetry_sample=1,
                    trace_sample=1,  # every publish carries a trace
                )
            )
            await h.server.serve()
            assert h.server.tracer is not None
            assert h.server.profiler is not None

            sub_r, sub_w, _ = await h.connect("sub")
            sub_w.write(sub_packet(1, [Subscription(filter="t/#", qos=0)]))
            await sub_w.drain()
            assert (await read_wire_packet(sub_r)).fixed_header.type == SUBACK
            h.server.matcher.flush()

            pub_r, pub_w, _ = await h.connect("pub")
            n = 12
            for i in range(n):
                pub_w.write(pub_packet(f"t/{i}", f"m{i}".encode()))
            await pub_w.drain()
            for _ in range(n):
                assert (await read_wire_packet(sub_r)).fixed_header.type == PUBLISH

            doc = h.server.tracer.export()
            assert check_trace_events(doc) > 0
            trees = spans_by_trace(doc)
            assert len(trees) == n
            expected = {
                "decode", "admission", "staging_wait",
                "h2d", "device_dispatch", "d2h", "encode", "flush",
            }
            for events in trees.values():
                assert_publish_tree(events)
                names = {e["name"] for e in events if e["cat"] == "stage"}
                assert names == expected, names
            # the sub-stages also landed in the histograms, and
            # device_batch aggregates them exactly once per publish
            tele = h.server.telemetry
            for s in DEVICE_SUBSTAGES:
                assert tele.stage_hist[s].count == n
            assert tele.stage_hist["device_batch"].count == n
            # same continuity for the fan-out split: encode/flush land
            # in their own histograms AND the coarse fanout stage keeps
            # populating as their sum (exactly once per publish)
            for s in ("encode", "flush", "fanout"):
                assert tele.stage_hist[s].count == n, s

            await h.server.close()
            await h.shutdown()

        run(scenario())


# -- cross-worker trace join -------------------------------------------------


class TestMeshTraceJoin:
    def test_two_worker_join_packet_leg(self, tmp_path):
        """The acceptance drill: ONE sampled publish on a 2-worker mesh
        yields one joined trace — origin spans decode -> admission ->
        staging_wait -> h2d -> device_dispatch -> d2h -> fanout, a
        per-peer forward span, and the peer's remote_fanout span — and
        the merged export passes the in-repo validator."""

        async def scenario():
            h0 = Harness(
                Options(
                    inline_client=True,
                    device_matcher=True,
                    matcher_opts={"max_levels": 4, "background": False},
                    telemetry_sample=1,
                    trace_sample=1,
                )
            )
            h1 = Harness(
                Options(inline_client=True, telemetry_sample=1, trace_sample=1)
            )
            c0 = Cluster(h0.server, 0, 2, str(tmp_path))
            c1 = Cluster(h1.server, 1, 2, str(tmp_path))
            await h0.server.serve()
            await h1.server.serve()
            await c0.start()
            await c1.start()
            assert h0.server.tracer.pid == 0 and h1.server.tracer.pid == 1

            async def wait_for(cond, timeout=10.0):
                deadline = time.monotonic() + timeout
                while time.monotonic() < deadline:
                    if cond():
                        return True
                    await asyncio.sleep(0.02)
                return False

            assert await wait_for(
                lambda: c0.peer_count == 1 and c1.peer_count == 1
            )

            # a LOCAL wildcard subscriber on the origin keeps the filter
            # set non-exact, so the publish takes the packed device path
            # (h2d/device_dispatch/d2h); the REMOTE subscriber pulls the
            # forward leg
            l_r, l_w, _ = await h0.connect("local-sub")
            l_w.write(sub_packet(1, [Subscription(filter="tr/#", qos=0)]))
            await l_w.drain()
            assert (await read_wire_packet(l_r)).fixed_header.type == SUBACK
            r_r, r_w, _ = await h1.connect("remote-sub", version=5)
            r_w.write(
                sub_packet(1, [Subscription(filter="tr/t", qos=1)], version=5)
            )
            await r_w.drain()
            assert (await read_wire_packet(r_r, 5)).fixed_header.type == SUBACK
            assert await wait_for(
                lambda: c0._interested_peers("tr/t") == (1,)
            )
            h0.server.matcher.flush()

            p_r, p_w, _ = await h0.connect("pub", version=5)
            p_w.write(pub_packet("tr/t", b"joined", qos=1, pid=1, version=5))
            await p_w.drain()
            assert (await read_wire_packet(p_r, 5)).fixed_header.type == PUBACK
            got = await read_wire_packet(r_r, 5)
            assert got.fixed_header.type == PUBLISH
            assert bytes(got.payload) == b"joined"
            assert (await read_wire_packet(l_r)).fixed_header.type == PUBLISH
            assert await wait_for(
                lambda: any(s[0] == "remote_fanout" for s in h1.server.tracer.ring)
            )

            d0 = h0.server.tracer.export()
            d1 = h1.server.tracer.export()
            fwd = [e for e in d0["traceEvents"] if e["name"] == "forward"]
            assert len(fwd) == 1 and fwd[0]["args"]["peer"] == 1
            tid = fwd[0]["args"]["trace_id"]
            origin = [
                e for e in d0["traceEvents"] if e["args"]["trace_id"] == tid
            ]
            assert_publish_tree([e for e in origin if e["cat"] != "cluster"])
            names = {e["name"] for e in origin if e["cat"] == "stage"}
            assert names == {
                "decode", "admission", "staging_wait",
                "h2d", "device_dispatch", "d2h", "encode", "flush",
            }, names
            root = [e for e in origin if e["name"] == "publish"][0]
            assert fwd[0]["args"]["parent_id"] == root["args"]["span_id"]
            remote = [
                e for e in d1["traceEvents"] if e["name"] == "remote_fanout"
            ]
            assert len(remote) == 1
            assert remote[0]["args"]["trace_id"] == tid
            assert remote[0]["args"]["parent_id"] == fwd[0]["args"]["span_id"]
            assert remote[0]["pid"] == 1 and root["pid"] == 0
            # the merged two-worker document is ONE valid joined trace
            merged = {"traceEvents": d0["traceEvents"] + d1["traceEvents"]}
            assert check_trace_events(merged) == len(merged["traceEvents"])

            await c0.stop()
            await c1.stop()
            await h0.server.close()
            await h1.server.close()
            await h0.shutdown()
            await h1.shutdown()

        run(scenario())

    def test_traced_frame_leg_joins(self, tmp_path):
        """The QoS0 v4 passthrough leg: a traced frame forwards as
        _T_TFRAME and the peer's remote_fanout span joins the trace."""

        async def scenario():
            h0 = Harness(Options(inline_client=True, trace_sample=1))
            h1 = Harness(Options(inline_client=True, trace_sample=1))
            c0 = Cluster(h0.server, 0, 2, str(tmp_path))
            c1 = Cluster(h1.server, 1, 2, str(tmp_path))
            await h0.server.serve()
            await h1.server.serve()
            await c0.start()
            await c1.start()

            async def wait_for(cond, timeout=10.0):
                deadline = time.monotonic() + timeout
                while time.monotonic() < deadline:
                    if cond():
                        return True
                    await asyncio.sleep(0.02)
                return False

            assert await wait_for(
                lambda: c0.peer_count == 1 and c1.peer_count == 1
            )
            s_r, s_w, _ = await h1.connect("sub")
            s_w.write(sub_packet(1, [Subscription(filter="f/t", qos=0)]))
            await s_w.drain()
            assert (await read_wire_packet(s_r)).fixed_header.type == SUBACK
            assert await wait_for(lambda: c0._interested_peers("f/t") == (1,))

            # the raw v4 qos0 frame the fast path would relay verbatim
            topic = b"f/t"
            body = len(topic).to_bytes(2, "big") + topic + b"fastpath"
            frame = bytes([0x30, len(body)]) + body
            clock = h0.server.tracer.publish_trace()
            clock.stamp("decode")
            c0.forward_frame("f/t", frame, "pub", clock)
            got = await read_wire_packet(s_r)
            assert got.fixed_header.type == PUBLISH
            assert bytes(got.payload) == b"fastpath"
            assert await wait_for(
                lambda: any(s[0] == "remote_fanout" for s in h1.server.tracer.ring)
            )
            fwd = [
                e for e in h0.server.tracer.export()["traceEvents"]
                if e["name"] == "forward"
            ]
            assert len(fwd) == 1 and fwd[0]["args"]["sent"] is True
            remote = [
                e for e in h1.server.tracer.export()["traceEvents"]
                if e["name"] == "remote_fanout"
            ]
            assert remote[0]["args"]["trace_id"] == clock.trace_id
            assert remote[0]["args"]["parent_id"] == fwd[0]["args"]["span_id"]

            await c0.stop()
            await c1.stop()
            await h0.server.close()
            await h1.server.close()
            await h0.shutdown()
            await h1.shutdown()

        run(scenario())


# -- exemplars + flight-dump cross-link --------------------------------------


class TestExemplarDumpLink:
    def test_shed_dump_carries_trace_ids_and_sibling_trace_file(self, tmp_path):
        """A SHED dump's records name their trace ids, the snapshot
        dedupes them into trace_ids, a Perfetto-loadable traces_*.json
        lands beside the flight dump, and the /metrics exemplars point
        at the same ids."""
        srv = Server(
            Options(
                telemetry_sample=1,
                trace_sample=1,
                telemetry_dump_dir=str(tmp_path),
                overload_eval_interval_ms=0.001,
            )
        )
        tele = srv.telemetry
        ids = []
        for i in range(5):
            c = tele.publish_clock()
            assert c is not None and c.trace_id
            ids.append(c.trace_id)
            c.stamp("decode")
            c.stamp("fanout")
            tele.observe_publish(c, f"x/{i}", 0)
        srv.overload.add_source("test", lambda: 1.0)
        assert srv.overload.evaluate(force=True) == "shed"
        tele.recorder.join_writer()

        flights = sorted(tmp_path.glob("flight_*.json"))
        traces = sorted(tmp_path.glob("traces_*.json"))
        assert len(flights) == 1 and len(traces) == 1
        snap = json.load(open(flights[0]))
        assert snap["trace_ids"] == sorted(set(ids))
        assert all(r["trace_id"] in ids for r in snap["records"])
        doc = json.load(open(traces[0]))
        assert check_trace_events(doc) > 0
        dumped_ids = {e["args"]["trace_id"] for e in doc["traceEvents"]}
        assert set(ids) <= dumped_ids

        text = tele.exposition()
        assert check_exposition(text) > 0
        exemplar_lines = [l for l in text.splitlines() if "# {trace_id=" in l]
        assert exemplar_lines
        assert any(tid in l for tid in ids for l in exemplar_lines)

    def test_exemplars_disabled_by_knob(self):
        srv = Server(
            Options(telemetry_sample=1, trace_sample=1, trace_exemplars=False)
        )
        tele = srv.telemetry
        c = tele.publish_clock()
        c.stamp("fanout")
        tele.observe_publish(c, "t", 0)
        assert "# {trace_id=" not in tele.exposition()

    def test_checker_accepts_and_rejects_exemplar_forms(self):
        check_exposition(
            "# TYPE t_h histogram\n"
            't_h_bucket{le="0.1"} 3 # {trace_id="abc"} 0.05\n'
            't_h_bucket{le="+Inf"} 3\nt_h_sum 0.1\nt_h_count 3\n'
        )
        with pytest.raises(ValueError):
            check_exposition('t_h_bucket{le="0.1"} 3 # trace_id=abc\n')


# -- v5 user-property traces -------------------------------------------------


class TestUserPropertyTraces:
    def test_inbound_trace_id_is_adopted(self):
        """An inbound v5 publish carrying trace-id joins the broker's
        spans to the CLIENT-chosen id, even when sampling would have
        skipped it."""

        async def scenario():
            h = Harness(
                Options(
                    inline_client=True,
                    telemetry_sample=0,
                    trace_sample=1_000_000,  # natural sampling never fires
                )
            )
            await h.server.serve()
            s_r, s_w, _ = await h.connect("sub", version=5)
            s_w.write(sub_packet(1, [Subscription(filter="a/b", qos=0)], version=5))
            await s_w.drain()
            assert (await read_wire_packet(s_r, 5)).fixed_header.type == SUBACK
            p_r, p_w, _ = await h.connect("pub", version=5)
            props = Properties(user=[UserProperty(TRACE_USER_PROPERTY, "client-id-1")])
            p_w.write(pub_packet("a/b", b"x", version=5, props=props))
            await p_w.drain()
            got = await read_wire_packet(s_r, 5)
            assert got.fixed_header.type == PUBLISH
            doc = h.server.tracer.export()
            trees = spans_by_trace(doc)
            assert "client-id-1" in trees
            names = {e["name"] for e in trees["client-id-1"]}
            assert "publish" in names
            assert {"fanout"} <= names or {"encode", "flush"} <= names
            await h.server.close()
            await h.shutdown()

        run(scenario())

    def test_adoption_is_rate_bounded(self):
        """A client stamping trace-id on every publish cannot bypass
        trace_sample: adoptions cap at trace_adopt_max_per_s and the
        excess flows untraced."""
        from mqtt_tpu.telemetry import Telemetry

        tele = Telemetry(sample=0)
        tracer = Tracer(seed=1, sample=1_000_000)
        tracer.adopt_max_per_s = 3
        tele.attach_tracer(tracer)

        class _Pk:
            def __init__(self):
                self.properties = Properties(
                    user=[UserProperty(TRACE_USER_PROPERTY, "flood")]
                )

        adopted = sum(
            1
            for _ in range(10)
            if getattr(tele.adopt_trace(_Pk()), "trace_id", None) is not None
        )
        assert adopted == 3
        tracer.adopt_max_per_s = 0  # 0 disables adoption outright
        assert tele.adopt_trace(_Pk()) is None

    def test_outbound_stamp_behind_knob(self):
        """With trace_user_property on, a sampled publish's subscribers
        see the trace id as a v5 user property; default off."""

        async def scenario():
            h = Harness(
                Options(
                    inline_client=True,
                    telemetry_sample=1,
                    trace_sample=1,
                    trace_user_property=True,
                )
            )
            await h.server.serve()
            s_r, s_w, _ = await h.connect("sub", version=5)
            s_w.write(sub_packet(1, [Subscription(filter="a/b", qos=0)], version=5))
            await s_w.drain()
            assert (await read_wire_packet(s_r, 5)).fixed_header.type == SUBACK
            p_r, p_w, _ = await h.connect("pub", version=5)
            p_w.write(pub_packet("a/b", b"x", version=5))
            await p_w.drain()
            got = await read_wire_packet(s_r, 5)
            assert got.fixed_header.type == PUBLISH
            keys = {u.key: u.val for u in got.properties.user}
            assert TRACE_USER_PROPERTY in keys
            # the stamped id is the one the trace recorded
            trees = spans_by_trace(h.server.tracer.export())
            assert keys[TRACE_USER_PROPERTY] in trees
            await h.server.close()
            await h.shutdown()

        run(scenario())


# -- /traces HTTP matrix -----------------------------------------------------


async def _http(host, port, path, method="GET"):
    reader, writer = await asyncio.open_connection(host, int(port))
    writer.write(f"{method} {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
    await writer.drain()
    raw = b""
    while True:
        try:
            chunk = await asyncio.wait_for(reader.read(65536), 3)
        except asyncio.TimeoutError:
            break
        if not chunk:
            break
        raw += chunk
    writer.close()
    return raw


class TestTracesEndpoint:
    def test_traces_matrix(self):
        async def scenario():
            h = Harness(Options(telemetry_sample=1, trace_sample=1))
            tele = h.server.telemetry
            c = tele.publish_clock()
            c.stamp("decode")
            c.stamp("fanout")
            tele.observe_publish(c, "t/x", 0)
            st = HTTPStats(
                LConfig(type="sysinfo", id="s", address="127.0.0.1:0"),
                h.server.info,
                telemetry=tele,
            )
            await st.init(h.server.log)
            host, port = st.address().rsplit(":", 1)
            data = await _http(host, port, "/traces")
            head, body = data.split(b"\r\n\r\n", 1)
            assert head.startswith(b"HTTP/1.1 200")
            assert b"application/json" in head
            assert b"Cache-Control: no-store" in head
            assert check_trace_events(body.decode()) > 0
            post = await _http(host, port, "/traces", "POST")
            assert post.startswith(b"HTTP/1.1 405") and b"Allow: GET" in post
            await st.close(lambda _: None)
            await h.shutdown()

        run(scenario())

    def test_traces_404_when_tracing_off(self):
        async def scenario():
            h = Harness(Options(telemetry_sample=1, trace=False))
            assert h.server.tracer is None
            st = HTTPStats(
                LConfig(type="sysinfo", id="s", address="127.0.0.1:0"),
                h.server.info,
                telemetry=h.server.telemetry,
            )
            await st.init(h.server.log)
            host, port = st.address().rsplit(":", 1)
            assert (await _http(host, port, "/traces")).startswith(
                b"HTTP/1.1 404"
            )
            # /metrics keeps working without the trace plane
            assert (await _http(host, port, "/metrics")).startswith(
                b"HTTP/1.1 200"
            )
            await st.close(lambda _: None)
            await h.shutdown()

        run(scenario())
