"""The 32-worker spanning-tree acceptance drill (ISSUE 9, slow/nightly).

Three subprocess broker fleets run the SAME seeded client script
(mqtt_tpu.stress.run_mesh_drill — per-worker pinned subscribers, a QoS1
publish storm over a flapping mesh, a post-heal verify batch, per-worker
$SYS scrapes):

1. ``tree``  — 32 workers on the epoch-stamped spanning tree, with a
   partition storm (seeded link flaps + held asymmetric cuts crossing
   the PARTITIONED threshold, so scoped re-elections fire mid-traffic);
2. ``mesh``  — the same 32 workers and the same storm on the PR 5
   all-pairs fabric: the measured baseline the O(degree) claims are
   asserted AGAINST, not assumed;
3. ``oracle`` — a single-worker broker running the identical script:
   the delivery oracle the post-heal verify phase must match.

Asserted: per-worker live link count stays <= degree+1 on the tree vs
~N-1 all-pairs, per-worker control-plane gossip bytes stay a small
fraction of the all-pairs baseline, the partition storm heals into ONE
converged epoch with exactly-once park replay, zero duplicate deliveries
and zero routing loops (the (origin, boot, seq) suppression counters are
scraped and reported), and the verify-phase delivery multiset matches
the single-worker oracle exactly.

Worker stderr logs and the drill reports land in
``MQTT_TPU_DRILL_ARTIFACTS`` (CI uploads that directory when the nightly
run fails) or the test's tmp_path.
"""

import asyncio
import json
import os
import socket
import statistics
import subprocess
import sys
import time

import pytest

from mqtt_tpu.stress import run_mesh_drill

pytestmark = pytest.mark.slow

WORKERS = 32
DEGREE = 4
PING_S = "0.5"


def _free_base_port(span: int = WORKERS + 2, start: int = 29010) -> int:
    """A base port with the whole private-port window free."""
    for base in range(start, 60000, span + 7):
        try:
            for off in (0, 1, span - 1):
                with socket.socket() as s:
                    s.bind(("127.0.0.1", base + off))
            return base
        except OSError:
            continue
    raise RuntimeError("no free port window for the drill")


def _artifact_dir(tmp_path, leg: str) -> str:
    root = os.environ.get("MQTT_TPU_DRILL_ARTIFACTS") or str(tmp_path)
    d = os.path.join(root, leg)
    os.makedirs(d, exist_ok=True)
    return d


def _launch(base: int, workers: int, topology: str, log_dir: str, flap: bool,
            extra: tuple = ()):
    env = dict(os.environ)
    env.update(
        {
            "MQTT_TPU_WORKER_PORTS": "1",
            "MQTT_TPU_CLUSTER_PING_S": PING_S,
            # 32 brokers on a couple of cores stall past one 0.5s ping
            # interval all the time: widen the missed-pong window so
            # scheduler jitter is not a partition (real cuts sever the
            # socket and mark SUSPECT immediately regardless) — SUSPECT
            # at 3s of silence, PARTITIONED at 4.5s, held flap cuts
            # auto-stretch to keep crossing it
            "MQTT_TPU_CLUSTER_SUSPECT_PINGS": "6",
            "MQTT_TPU_SYS_RESEND_S": "1",
            "MQTT_TPU_WORKER_LOG_DIR": log_dir,
            # routing drill, not an overload drill: with the governor
            # live, a CPU-starved runner SHEDs QoS1 at the origin (a
            # silent loss to the v4 publishers) and fails verify for a
            # reason that has nothing to do with the tree
            "MQTT_TPU_OVERLOAD_CONTROL": "0",
            "JAX_PLATFORMS": "cpu",
        }
    )
    cmd = [
        sys.executable, "-m", "mqtt_tpu.stress", "--serve",
        "--broker", f"127.0.0.1:{base}", "--workers", str(workers),
    ]
    if topology:
        cmd += ["--topology", topology, "--degree", str(DEGREE)]
    if flap:
        # 4 flapping workers x one disturbance per ~0.6s for 6s, about a
        # third of them held cuts long enough to cross the PARTITIONED
        # threshold: a partition storm with a guaranteed heal phase
        cmd += ["--flap-peer-s", "0.6", "--flap-for-s", "6",
                "--flap-workers", "4"]
    cmd += list(extra)
    proc = subprocess.Popen(
        cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env
    )
    line = proc.stdout.readline().strip()
    if line != b"READY":
        proc.kill()
        raise AssertionError(f"drill broker failed to boot: {line!r}")
    return proc


def _stop(proc) -> None:
    try:
        proc.stdin.close()
        proc.wait(timeout=120)
    except Exception:
        proc.kill()


def _run_leg(tmp_path, leg: str, workers: int, topology: str, flap: bool) -> dict:
    log_dir = _artifact_dir(tmp_path, leg)
    base = _free_base_port()
    proc = _launch(base, workers, topology, log_dir, flap)
    try:
        time.sleep(2.0)  # let the fabric link up before the storm
        report = asyncio.run(
            run_mesh_drill(
                "127.0.0.1", base, workers,
                settle_s=8.0 if flap else 2.0,
                # generous: on a CPU-oversubscribed runner (32 broker
                # processes on 2 cores in CI) post-heal epoch churn can
                # park-and-replay QoS1 forwards several times over
                verify_timeout_s=150.0,
            )
        )
    finally:
        _stop(proc)
    with open(os.path.join(log_dir, "report.json"), "w") as f:
        json.dump(report, f, indent=1)
    return report


def _gauge(report: dict, worker: int, key: str) -> int:
    return int(report["cluster_sys"][worker].get(key, "0"))


def test_32_worker_partition_storm_drill(tmp_path):
    # -- leg 1: the spanning tree under a partition storm ------------------
    tree = _run_leg(tmp_path, "tree", WORKERS, "tree", flap=True)
    # the storm HEALED: links reconciled, parks drained, one epoch —
    # observed from the outside before the verify batch was sent
    assert tree["healed"], tree
    assert tree["route_converged"], tree
    assert tree["verify_complete"], tree["verify_missing"]
    assert tree["dup_deliveries"] == 0, tree
    assert tree["verify_anomalies"] == {}, tree["verify_anomalies"]

    scraped = [
        w for w in range(WORKERS) if "tree/epoch" in tree["cluster_sys"][w]
    ]
    assert len(scraped) >= WORKERS - 2, "too many workers unscrapable"
    # post-heal the mesh converged on ONE epoch...
    epochs = {_gauge(tree, w, "tree/epoch") for w in scraped}
    assert len(epochs) == 1, f"epoch split survived the heal: {epochs}"
    # ...the storm actually exercised the election machinery...
    assert sum(_gauge(tree, w, "tree/re_elections") for w in scraped) > 0
    # ...and every worker's live link count is O(degree), not O(N)
    tree_links = [_gauge(tree, w, "tree/links") for w in scraped]
    assert max(tree_links) <= DEGREE + 1, tree_links
    for w in scraped:
        assert _gauge(tree, w, "tree/neighbors") <= DEGREE + 1
    # the loop/duplicate guards are live and scrapable (their VERDICT —
    # zero duplicate deliveries — is asserted at the subscribers above;
    # suppressed counts > 0 simply mean the window did real work)
    suppressed = sum(
        _gauge(tree, w, "tree/duplicates_suppressed") for w in scraped
    )
    replayed = sum(_gauge(tree, w, "replayed_forwards") for w in scraped)
    assert suppressed >= 0 and replayed >= 0
    tree_rates = list(tree["control_rate"].values())
    assert len(tree_rates) >= WORKERS - 2

    # -- leg 2: the all-pairs baseline under the same storm ----------------
    mesh = _run_leg(tmp_path, "mesh", WORKERS, "", flap=True)
    assert mesh["healed"], mesh
    # the probe gate matters most HERE: all-pairs links converge before
    # the presence resync re-teaches re-dialed peers the drill interest
    assert mesh["route_converged"], mesh
    assert mesh["verify_complete"], mesh["verify_missing"]
    assert mesh["dup_deliveries"] == 0, mesh
    mesh_scraped = [
        w
        for w in range(WORKERS)
        if "control_bytes" in mesh["cluster_sys"][w]
    ]
    assert len(mesh_scraped) >= WORKERS - 2
    # all-pairs: every worker holds ~N-1 links (a couple may be
    # mid-re-dial at scrape time)
    mesh_links = [
        int(mesh["cluster_sys"][w].get("peers", "0")) for w in mesh_scraped
    ]
    assert statistics.median(mesh_links) >= WORKERS - 4, mesh_links
    # the O(degree) gossip-volume claim, asserted against the MEASURED
    # baseline: both legs sample their post-heal steady-state per-worker
    # control-plane byte RATE over the same fixed window (cumulative
    # bytes would compare storm histories, not the fabric — the tree
    # pays election floods the all-pairs mesh never does). The tree
    # rate must be a small fraction of all-pairs (< 1/3 asserted; the
    # structural ratio is ~degree/N ≈ 1/6 at 32 workers, degree 4)
    mesh_rates = list(mesh["control_rate"].values())
    assert len(mesh_rates) >= WORKERS - 2
    assert (
        statistics.median(tree_rates) * 3
        < statistics.median(mesh_rates)
    ), (statistics.median(tree_rates), statistics.median(mesh_rates))

    # -- leg 3: the single-worker delivery oracle --------------------------
    oracle = _run_leg(tmp_path, "oracle", 1, "", flap=False)
    assert oracle["verify_complete"] and oracle["dup_deliveries"] == 0
    assert oracle["verify_anomalies"] == {}
    # identical script, identical expected set, both anomaly-free:
    # every tree subscriber's verify multiset IS the oracle's
    assert tree["verify_sent"] == oracle["verify_sent"]


# -- cross-machine WAN drill (ISSUE 17) ------------------------------------


def test_wan_two_machine_predicate_drill(tmp_path):
    """The same 32-worker tree split across two 16-worker "machine"
    groups joined by REAL TCP peer links, with every inter-group edge
    shaped to a 50ms-RTT 1%-loss WAN profile, the partition storm still
    running on top — plus the predicate push-down leg: payloads failing
    ``$GT{v:50}`` must be filtered at the cross-machine edges (counted),
    passing payloads must still arrive everywhere exactly once."""
    log_dir = _artifact_dir(tmp_path, "wan")
    # one window holds both the broker ports and the peer-link ports
    base = _free_base_port(span=2 * WORKERS + 12)
    peer_base = base + WORKERS + 8
    proc = _launch(
        base, WORKERS, "tree", log_dir, flap=True,
        extra=(
            "--transport", "tcp", "--cluster-base-port", str(peer_base),
            "--machine-split", str(WORKERS // 2),
            "--shape-rtt-ms", "50", "--shape-loss", "0.01",
        ),
    )
    try:
        time.sleep(3.0)  # TCP dial + TLS-free handshake across 32 peers
        report = asyncio.run(
            run_mesh_drill(
                "127.0.0.1", base, WORKERS,
                settle_s=8.0,
                # shaped RTT + loss-as-late-delivery on top of the CPU
                # oversubscription: give replay generous headroom
                verify_timeout_s=180.0,
                pred_msgs=10,
            )
        )
    finally:
        _stop(proc)
    with open(os.path.join(log_dir, "report.json"), "w") as f:
        json.dump(report, f, indent=1)

    assert report["healed"], report
    assert report["route_converged"], report
    assert report["verify_complete"], report["verify_missing"]
    assert report["dup_deliveries"] == 0, report
    assert report["verify_anomalies"] == {}, report["verify_anomalies"]
    # predicate leg: every passing payload landed, no failing payload
    # EVER reached a subscriber (edge filter + delivery gate soundness)
    assert report["pred_complete"], report["pred_missing"]
    assert report["pred_leaks"] == 0, report
    # ...and the filtering happened AT THE EDGES, not just at delivery:
    # the failing half of the batch never crossed toward remote workers
    assert report["predicate_filtered_total"] > 0, report
    # the WAN profile did not cost exactly-once or epoch convergence
    scraped = [
        w for w in range(WORKERS) if "tree/epoch" in report["cluster_sys"][w]
    ]
    assert len(scraped) >= WORKERS - 2
    epochs = {_gauge(report, w, "tree/epoch") for w in scraped}
    assert len(epochs) == 1, f"epoch split survived the heal: {epochs}"


def test_root_kill_promotes_pre_agreed_successor(tmp_path):
    """kill -9 the tree root mid-serve: the pre-agreed successor
    (worker 1, the second-lowest id) must promote on the fast path —
    observed from the outside via its $SYS rows: a root_failovers tick,
    a sub-second failover latency gauge, and the surviving mesh agreeing
    root=1 on one epoch."""
    from mqtt_tpu.stress import _drill_port, _read_cluster_sys

    workers = 8
    log_dir = _artifact_dir(tmp_path, "rootkill")
    base = _free_base_port(span=workers + 2)
    proc = _launch(
        base, workers, "tree", log_dir, flap=False,
        extra=("--kill-root-after-s", "2.5"),
    )
    try:
        async def await_failover() -> dict:
            deadline = time.monotonic() + 90.0
            last: dict = {}
            while time.monotonic() < deadline:
                try:
                    last = await _read_cluster_sys(
                        "127.0.0.1", _drill_port(base, workers, 1), wait_s=2.0
                    )
                except (OSError, AssertionError, asyncio.IncompleteReadError):
                    last = {}
                if int(last.get("tree/root_failovers", "0")) >= 1:
                    return last
                await asyncio.sleep(0.5)
            raise AssertionError(f"no failover observed; last scrape: {last}")

        promoted = asyncio.run(await_failover())
        assert promoted["tree/root"] == "1"
        # the fast path fired at SUSPECT and the promotion window itself
        # (drop root + reconcile + flood the new epoch) completed inside
        # 2 ping intervals (1.0s at the drill's 0.5s cadence) — the
        # acceptance bound: no full re-election blackout on this path
        assert 0.0 < float(promoted["tree/root_failover_last_s"]) < 1.0

        async def await_convergence() -> None:
            deadline = time.monotonic() + 60.0
            while True:
                rows = {}
                for w in range(1, workers):
                    try:
                        rows[w] = await _read_cluster_sys(
                            "127.0.0.1", _drill_port(base, workers, w),
                            wait_s=2.0,
                        )
                    except (OSError, AssertionError, asyncio.IncompleteReadError):
                        rows[w] = {}
                roots = {r.get("tree/root") for r in rows.values()}
                epochs = {r.get("tree/epoch") for r in rows.values()}
                if roots == {"1"} and len(epochs) == 1 and None not in epochs:
                    # the NEXT successor is pre-agreed too: worker 2
                    assert {r.get("tree/successor") for r in rows.values()} == {"2"}
                    return
                if time.monotonic() > deadline:
                    raise AssertionError(f"survivors split: {rows}")
                await asyncio.sleep(1.0)

        asyncio.run(await_convergence())
    finally:
        _stop(proc)
