"""Device matcher conformance: the TPU CSR/NFA matcher must be bit-identical
to the host trie (the oracle) on the same corpora that validate the trie —
the wildcard matrix, shared groups, $-exclusions, and a randomized
differential fuzz with live churn (SURVEY.md §7 stages 4-5)."""

import random

import pytest

from mqtt_tpu.packets import Subscription
from mqtt_tpu.topics import SHARE_PREFIX, InlineSubscription, TopicsIndex
from mqtt_tpu.ops import TpuMatcher

from tests.test_topics import FIND_MATRIX


def canon(subs):
    """Canonicalize a Subscribers result for set comparison: client -> (qos,
    no_local, sorted positive identifiers); shared -> group filters ->
    client sets; inline -> identifier set. Zero-valued identifier entries
    are excluded (Go-map zero-value semantics make them unobservable)."""
    return (
        {
            c: (s.qos, s.no_local, tuple(sorted(v for v in (s.identifiers or {c: s.identifier}).values() if v > 0)))
            for c, s in subs.subscriptions.items()
        },
        {g: frozenset(m) for g, m in subs.shared.items()},
        frozenset(subs.inline_subscriptions),
    )


@pytest.mark.parametrize("filter_,topic,matched", FIND_MATRIX, ids=[f"{f}~{t}" for f, t, _ in FIND_MATRIX])
def test_find_matrix_on_device(filter_, topic, matched):
    index = TopicsIndex()
    index.subscribe("cl1", Subscription(filter=filter_))
    matcher = TpuMatcher(index)
    subs = matcher.subscribers(topic)
    assert (len(subs.subscriptions) == 1) == matched
    assert canon(subs) == canon(index.subscribers(topic))


def test_scan_subscribers_table_on_device():
    index = TopicsIndex()
    index.subscribe("cl1", Subscription(qos=1, filter="a/b/c", identifier=22))
    index.subscribe("cl1", Subscription(qos=1, filter="a/b/c/d/e/f"))
    index.subscribe("cl1", Subscription(qos=2, filter="a/b/c/d/+/f"))
    index.subscribe("cl2", Subscription(qos=0, filter="a/#"))
    index.subscribe("cl2", Subscription(qos=1, filter="a/b/c"))
    index.subscribe("cl2", Subscription(qos=2, filter="a/b/+", identifier=77))
    index.subscribe("cl2", Subscription(qos=2, filter="d/e/f", identifier=7237))
    index.subscribe("cl2", Subscription(qos=2, filter="$SYS/uptime", identifier=3))
    index.subscribe("cl3", Subscription(qos=1, filter="+/b", identifier=234))
    index.subscribe("cl4", Subscription(qos=0, filter="#", identifier=5))
    index.subscribe("cl2", Subscription(qos=0, filter="$SYS/test", identifier=2))
    matcher = TpuMatcher(index)
    for topic in ["a/b/c", "d/e/f/g", "a/b", "$SYS/uptime", "$SYS/test", "x"]:
        assert canon(matcher.subscribers(topic)) == canon(index.subscribers(topic)), topic


def test_shared_and_inline_on_device():
    index = TopicsIndex()
    index.subscribe("cl1", Subscription(qos=1, filter=SHARE_PREFIX + "/tmp/a/b/c", identifier=111))
    index.subscribe("cl2", Subscription(qos=0, filter=SHARE_PREFIX + "/tmp/a/b/c", identifier=112))
    index.subscribe("cl3", Subscription(qos=0, filter=SHARE_PREFIX + "/tmp2/a/b/+", identifier=113))
    index.subscribe("cl4", Subscription(qos=0, filter="a/b/c"))
    index.inline_subscribe(InlineSubscription(filter="a/+/c", identifier=9, handler=lambda *a: None))
    index.inline_subscribe(InlineSubscription(filter="a/#", identifier=8, handler=lambda *a: None))
    matcher = TpuMatcher(index)
    for topic in ["a/b/c", "a/x/c", "a", "a/b"]:
        assert canon(matcher.subscribers(topic)) == canon(index.subscribers(topic)), topic


def test_inline_parent_hash_quirk_on_device():
    # an inline sub on a/# must NOT match topic "a" (topics.go:615 quirk)
    index = TopicsIndex()
    index.inline_subscribe(InlineSubscription(filter="a/#", identifier=1, handler=lambda *a: None))
    matcher = TpuMatcher(index)
    assert len(matcher.subscribers("a").inline_subscriptions) == 0
    assert len(matcher.subscribers("a/b").inline_subscriptions) == 1


def test_differential_fuzz_with_churn():
    rng = random.Random(99)
    segs = ["a", "b", "c", "dd", "", "x", "$SYS", "long-segment-name"]

    def rand_topic():
        return "/".join(rng.choice(segs) for _ in range(rng.randint(1, 6)))

    def rand_filter():
        parts = [rng.choice(segs + ["+"]) for _ in range(rng.randint(1, 6))]
        if rng.random() < 0.25:
            parts[-1] = "#"
        return "/".join(parts)

    index = TopicsIndex()
    filters = {}
    for i in range(500):
        flt = rand_filter()
        filters[f"cl{i}"] = flt
        index.subscribe(f"cl{i}", Subscription(filter=flt, qos=rng.randint(0, 2), identifier=rng.choice([0, 0, i])))
    matcher = TpuMatcher(index)

    topics = [rand_topic() for _ in range(600)]
    device = matcher.match_topics(topics)
    for topic, dev in zip(topics, device):
        host = index.subscribers(topic)
        assert canon(dev) == canon(host), topic

    # churn: unsubscribe a third, add some, then verify staleness triggers
    # rebuild and results stay identical
    for i in range(0, 500, 3):
        index.unsubscribe(filters[f"cl{i}"], f"cl{i}")
    for i in range(500, 550):
        flt = rand_filter()
        filters[f"cl{i}"] = flt
        index.subscribe(f"cl{i}", Subscription(filter=flt, qos=1))
    assert matcher.stale
    topics = [rand_topic() for _ in range(300)]
    for topic, dev in zip(topics, matcher.match_topics(topics)):
        assert canon(dev) == canon(index.subscribers(topic)), topic


def test_overflow_falls_back_to_host():
    index = TopicsIndex()
    # >out_slots matching subs on one topic forces output overflow
    for i in range(40):
        index.subscribe(f"cl{i}", Subscription(filter="hot/topic", qos=0))
    matcher = TpuMatcher(index, out_slots=16)
    subs = matcher.subscribers("hot/topic")
    assert len(subs.subscriptions) == 40

    # deep topic beyond max_levels falls back too
    deep = "/".join(["d"] * 20)
    index.subscribe("deep", Subscription(filter=deep))
    matcher2 = TpuMatcher(index, max_levels=8)
    assert "deep" in matcher2.subscribers(deep).subscriptions


def test_frontier_overflow_falls_back():
    index = TopicsIndex()
    # many '+' forks at each level explode the frontier beyond 2 slots
    for i, flt in enumerate(["+/+/+/a", "+/+/a/+", "+/a/+/+", "a/+/+/+", "a/a/a/a"]):
        index.subscribe(f"w{i}", Subscription(filter=flt))
    matcher = TpuMatcher(index, frontier=2)
    subs = matcher.subscribers("a/a/a/a")
    assert len(subs.subscriptions) == 5


def test_ranges_transfer_carries_large_fanouts_without_fallback():
    """The packed ranges output carries the COMPLETE result (2P ints per
    topic), so a fan-out that would have exceeded any slot prefix still
    resolves entirely from the device — no host fallback class for it.
    (``transfer_slots`` remains accepted for API compatibility.)"""
    index = TopicsIndex()
    # 12 subs all matching 'hot/x'; 1 sub matching 'cold/y'
    for i in range(6):
        index.subscribe(f"e{i}", Subscription(filter="hot/x", qos=1))
        index.subscribe(f"w{i}", Subscription(filter="hot/+", qos=2))
    index.subscribe("solo", Subscription(filter="cold/y"))
    matcher = TpuMatcher(index, max_levels=4, out_slots=32, transfer_slots=4)
    hot = matcher.subscribers("hot/x")
    cold = matcher.subscribers("cold/y")
    assert canon(hot) == canon(index.subscribers("hot/x"))
    assert canon(cold) == canon(index.subscribers("cold/y"))
    assert len(hot.subscriptions) == 12
    assert matcher.stats.host_fallbacks == 0
    assert matcher.stats.overflows == 0
    assert matcher.stats.topics == 2


def test_saturated_bucket_routes_to_host():
    """Entries dropped from a build-saturated bucket must never produce
    false negatives: the kernel flags any probe touching the bucket and the
    topic re-walks the host trie (ops/flat.py SAT marker)."""
    import numpy as np

    from mqtt_tpu.ops.flat import _M2, KIND_EXACT, _mix_np, hash_token

    S = 1024  # build_flat_index's minimum bucket count

    def slot_of(token: str) -> int:
        a, _ = hash_token(token, 0)
        with np.errstate(over="ignore"):
            h1 = np.uint32(np.uint64(1) * np.uint64(_M2) & np.uint64(0xFFFFFFFF)) ^ np.uint32(KIND_EXACT)
            h1 = _mix_np(h1, np.uint32(a))
        return int(h1) & (S - 1)

    by_slot = {}
    colliding = None
    for i in range(200_000):
        tok = f"sat{i}"
        s = slot_of(tok)
        by_slot.setdefault(s, []).append(tok)
        if len(by_slot[s]) == 6:
            colliding = by_slot[s]
            break
    assert colliding, "no 6-way bucket collision found in 200k tokens"

    index = TopicsIndex()
    for i, tok in enumerate(colliding):
        index.subscribe(f"cl{i}", Subscription(filter=tok, qos=1))
    index.subscribe("solo", Subscription(filter="plain/topic", qos=0))
    # one wildcard filter keeps the index off the exact-map host fast path
    # (this test exercises the DEVICE path's saturation routing); it
    # matches neither the colliding tokens nor plain/topic
    index.subscribe("wild", Subscription(filter="wild/only/+", qos=0))
    matcher = TpuMatcher(index, max_levels=4)
    matcher.rebuild()
    assert matcher.csr.n_sat >= 1  # the build really saturated a bucket
    # every dropped filter still matches, via the host route
    for i, tok in enumerate(colliding):
        subs = matcher.subscribers(tok)
        assert list(subs.subscriptions) == [f"cl{i}"], tok
    assert matcher.stats.overflows >= len(colliding)
    # untouched buckets still serve from the device
    before = matcher.stats.host_fallbacks
    assert list(matcher.subscribers("plain/topic").subscriptions) == ["solo"]
    assert matcher.stats.host_fallbacks == before


def test_window_above_meta_capacity_raises():
    from mqtt_tpu.ops.flat import MAX_WINDOW, build_flat_index

    index = TopicsIndex()
    index.subscribe("c", Subscription(filter="a/b"))
    with pytest.raises(ValueError):
        build_flat_index(index, window=MAX_WINDOW + 1)


def test_duplicate_client_merge_matches_host_exactly_and_does_not_accumulate():
    """One client matching a topic through several filters must merge
    exactly like the host gather (max QoS, identifiers union, sticky
    no_local) — and repeated matching must NOT accumulate state across
    results (the expand_sids fast path copies per result; a shared
    identifiers map would leak merge products between batches)."""
    index = TopicsIndex()
    index.subscribe("dup", Subscription(filter="m/x", qos=0, identifier=7))
    index.subscribe("dup", Subscription(filter="m/+", qos=2, identifier=9, no_local=True))
    index.subscribe("dup", Subscription(filter="m/#", qos=1))
    index.subscribe("other", Subscription(filter="m/x", qos=1))
    matcher = TpuMatcher(index, max_levels=4)
    matcher.rebuild()

    host = index.subscribers("m/x")
    for attempt in range(3):  # identical every time: no accumulation
        dev = matcher.subscribers("m/x")
        assert set(dev.subscriptions) == {"dup", "other"}
        d, h = dev.subscriptions["dup"], host.subscriptions["dup"]
        assert (d.qos, d.no_local) == (h.qos, h.no_local) == (2, True)
        assert {k: v for k, v in d.identifiers.items() if v > 0} == {
            k: v for k, v in h.identifiers.items() if v > 0
        } == {"m/x": 7, "m/+": 9}, attempt
        o = dev.subscriptions["other"]
        assert (o.qos, {k: v for k, v in o.identifiers.items() if v > 0}) == (1, {})
        # result objects are fresh per match: mutating one must not bleed
        d.qos = 99
        d.identifiers["poison"] = 1
        # (the stored trie copy keeps its own map only when it had one; the
        # device result's map must at minimum not feed back into results)
        nxt = matcher.subscribers("m/x").subscriptions["dup"]
        assert nxt.qos == 2 and "poison" not in {
            k for k, v in nxt.identifiers.items() if v > 0
        }


class TestExactMapFastPath:
    """Wildcard-free filter sets answer from the host exact-map — one dict
    probe per topic, no device dispatch, no fallback classes (SURVEY §7
    hard part 4; VERDICT r4 item 5)."""

    def _index(self):
        index = TopicsIndex()
        index.subscribe("c1", Subscription(filter="a/b/c", qos=1, identifier=9))
        index.subscribe("c2", Subscription(filter="a/b/c", qos=2))
        index.subscribe("c3", Subscription(filter="x/y", qos=0))
        index.subscribe("sys", Subscription(filter="$SYS/broker/load", qos=0))
        index.subscribe(
            "m1", Subscription(filter=f"{SHARE_PREFIX}/g1/a/b/c", qos=1)
        )
        index.inline_subscribe(
            InlineSubscription(filter="x/y", identifier=5, handler=lambda *a: None)
        )
        # deeper than max_levels: the device table would drop it; the map
        # still serves it
        index.subscribe("deep", Subscription(filter="d/e/f/g/h/i", qos=1))
        return index

    def test_serves_without_device_and_matches_host(self):
        index = self._index()
        matcher = TpuMatcher(index, max_levels=4)
        matcher.rebuild()
        assert matcher.csr.exact_map is not None
        topics = ["a/b/c", "x/y", "$SYS/broker/load", "d/e/f/g/h/i", "no/match", ""]
        results = matcher.match_topics(topics)
        for topic, got in zip(topics, results):
            assert canon(got) == canon(index.subscribers(topic)), topic
        assert matcher.stats.host_fast == 5  # all but the empty topic
        assert matcher.stats.host_fallbacks == 0

    def test_spilled_entry_served_from_map(self):
        index = TopicsIndex()
        for i in range(40):  # >> window: device entry would spill
            index.subscribe(f"c{i}", Subscription(filter="hot/topic", qos=1))
        matcher = TpuMatcher(index, max_levels=4, window=8)
        matcher.rebuild()
        assert matcher.csr.exact_map is not None
        subs = matcher.subscribers("hot/topic")
        assert len(subs.subscriptions) == 40
        assert canon(subs) == canon(index.subscribers("hot/topic"))
        assert matcher.stats.host_fallbacks == 0

    def test_any_wildcard_disables_map(self):
        index = self._index()
        index.subscribe("w", Subscription(filter="a/+/c", qos=0))
        matcher = TpuMatcher(index, max_levels=4)
        matcher.rebuild()
        assert matcher.csr.exact_map is None
        # deep-wildcard-only sets must not sneak back onto the fast path
        index2 = TopicsIndex()
        index2.subscribe("c", Subscription(filter="a/b/c/d/e/f/+", qos=0))
        m2 = TpuMatcher(index2, max_levels=4)
        m2.rebuild()
        assert m2.csr.exact_map is None

    def test_fold_maintains_map(self):
        from mqtt_tpu.ops.delta import DeltaMatcher

        index = self._index()
        m = DeltaMatcher(index, max_levels=4, background=False)
        assert m._snap.csr.exact_map is not None
        index.subscribe("new", Subscription(filter="fresh/topic", qos=2))
        index.unsubscribe("x/y", "c3")
        m.flush()
        for topic in ["fresh/topic", "x/y", "a/b/c"]:
            assert canon(m.subscribers(topic)) == canon(index.subscribers(topic))
        # a folded-in wildcard drops the map and stays correct
        index.subscribe("w", Subscription(filter="fresh/+", qos=1))
        m.flush()
        assert canon(m.subscribers("fresh/topic")) == canon(
            index.subscribers("fresh/topic")
        )
        m.close()

    def test_identifier_merge_parity_on_fast_path(self):
        index = TopicsIndex()
        index.subscribe("c1", Subscription(filter="t/1", qos=1, identifier=3))
        matcher = TpuMatcher(index)
        got = matcher.subscribers("t/1").subscriptions["c1"]
        want = index.subscribers("t/1").subscriptions["c1"]
        assert got.identifiers == want.identifiers == {"t/1": 3}
