"""Hook dispatcher semantics beyond the broker e2e tests: chain order,
first-non-empty-wins for Stored*, provides filtering, init failure, stop
propagation, and the error-isolation contract (hooks.go:123-680)."""

import pytest

from mqtt_tpu.hooks import (
    ON_CONNECT,
    ON_PACKET_READ,
    ON_SELECT_SUBSCRIBERS,
    ON_SYS_INFO_TICK,
    STORED_CLIENTS,
    STORED_RETAINED_MESSAGES,
    Hook,
    Hooks,
)
from mqtt_tpu.hooks.storage import Client as StoredClient
from mqtt_tpu.packets import PINGREQ, FixedHeader, Packet
from mqtt_tpu.topics import Subscribers


class Recorder(Hook):
    def __init__(self, name, provides=(), clients=None):
        super().__init__()
        self.name = name
        self._provides = set(provides)
        self._clients = clients
        self.calls = []
        self.stopped = False
        self.inited = None

    def id(self):
        return self.name

    def provides(self, b):
        return b in self._provides

    def init(self, config):
        self.inited = config

    def stop(self):
        self.stopped = True

    def on_connect(self, cl, pk):
        self.calls.append("on_connect")

    def on_packet_read(self, cl, pk):
        self.calls.append("on_packet_read")
        pk.payload = bytes(pk.payload) + self.name.encode()
        return pk

    def stored_clients(self):
        self.calls.append("stored_clients")
        return self._clients

    def on_select_subscribers(self, subs, pk):
        self.calls.append("select")
        subs.subscriptions[self.name] = None
        return subs


class Boom(Hook):
    def id(self):
        return "boom"

    def init(self, config):
        raise ValueError("no init for you")


class TestDispatcher:
    def test_add_init_failure_raises_and_excludes(self):
        hooks = Hooks()
        with pytest.raises(RuntimeError):
            hooks.add(Boom(), None)
        assert len(hooks) == 0

    def test_provides_filters_dispatch(self):
        hooks = Hooks()
        a = Recorder("a", provides=(ON_CONNECT,))
        b = Recorder("b", provides=())  # provides nothing
        hooks.add(a, None)
        hooks.add(b, None)
        hooks.on_connect(None, None)
        assert a.calls == ["on_connect"]
        assert b.calls == []

    def test_modifier_chain_runs_in_attach_order(self):
        hooks = Hooks()
        a = Recorder("a", provides=(ON_PACKET_READ,))
        b = Recorder("b", provides=(ON_PACKET_READ,))
        hooks.add(a, None)
        hooks.add(b, None)
        pk = Packet(fixed_header=FixedHeader(type=PINGREQ), payload=b"x")
        out = hooks.on_packet_read(None, pk)
        assert bytes(out.payload) == b"xab"  # a then b, chained

    def test_stored_first_non_empty_wins(self):
        hooks = Hooks()
        empty = Recorder("empty", provides=(STORED_CLIENTS,), clients=[])
        full = Recorder(
            "full", provides=(STORED_CLIENTS,), clients=[StoredClient(id="x")]
        )
        later = Recorder(
            "later", provides=(STORED_CLIENTS,), clients=[StoredClient(id="y")]
        )
        hooks.add(empty, None)
        hooks.add(full, None)
        hooks.add(later, None)
        got = hooks.stored_clients()
        assert [c.id for c in got] == ["x"]  # first NON-EMPTY wins
        assert later.calls == []  # never consulted

    def test_stop_propagates_to_all(self):
        hooks = Hooks()
        a, b = Recorder("a"), Recorder("b")
        hooks.add(a, None)
        hooks.add(b, None)
        hooks.stop()
        assert a.stopped and b.stopped

    def test_select_subscribers_chains(self):
        hooks = Hooks()
        a = Recorder("a", provides=(ON_SELECT_SUBSCRIBERS,))
        b = Recorder("b", provides=(ON_SELECT_SUBSCRIBERS,))
        hooks.add(a, None)
        hooks.add(b, None)
        subs = hooks.on_select_subscribers(Subscribers(), None)
        assert set(subs.subscriptions) == {"a", "b"}

    def test_init_receives_config(self):
        hooks = Hooks()
        a = Recorder("a")
        hooks.add(a, {"k": 1})
        assert a.inited == {"k": 1}

    def test_len_and_provides_aggregate(self):
        hooks = Hooks()
        hooks.add(Recorder("a", provides=(ON_SYS_INFO_TICK,)), None)
        assert len(hooks) == 1
        assert hooks.provides(ON_SYS_INFO_TICK)
        assert not hooks.provides(STORED_RETAINED_MESSAGES)
