"""The per-stage regression gate (exp/stage_gate.py): bench telemetry
blocks diff stage-by-stage against the previous BENCH artifact, failing
only on real p99 regressions — path-matched blocks, sample-count floors,
and graceful pass-through when a run carries no telemetry at all."""

import importlib.util
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

spec = importlib.util.spec_from_file_location(
    "stage_gate", os.path.join(REPO, "exp", "stage_gate.py")
)
stage_gate = importlib.util.module_from_spec(spec)
spec.loader.exec_module(stage_gate)


def bench_doc(p99_ms, count=100, stage="device_batch", config="5"):
    return {
        "parsed": {
            "configs": {
                config: {
                    "telemetry": {
                        "stages": {
                            stage: {
                                "count": count,
                                "p50_ms": p99_ms / 2,
                                "p99_ms": p99_ms,
                            }
                        }
                    }
                }
            }
        }
    }


class TestCompare:
    def test_regression_detected_past_threshold(self):
        reg, cmp_ = stage_gate.compare(bench_doc(2.0), bench_doc(1.0))
        assert len(cmp_) == 1
        assert len(reg) == 1
        assert "device_batch" in reg[0]

    def test_within_threshold_passes(self):
        reg, cmp_ = stage_gate.compare(
            bench_doc(1.2), bench_doc(1.0), threshold=0.25
        )
        assert cmp_ and not reg

    def test_improvement_passes(self):
        reg, _ = stage_gate.compare(bench_doc(0.5), bench_doc(1.0))
        assert not reg

    def test_small_samples_are_ignored(self):
        reg, cmp_ = stage_gate.compare(
            bench_doc(10.0, count=5), bench_doc(1.0, count=5), min_count=20
        )
        assert not cmp_ and not reg

    def test_blocks_match_by_path_not_position(self):
        # config 8's regression must not diff against config 5's numbers
        cur = bench_doc(9.0, config="8")
        prev = bench_doc(1.0, config="5")
        reg, cmp_ = stage_gate.compare(cur, prev)
        assert not cmp_ and not reg

    def test_new_stage_without_baseline_passes(self):
        cur = bench_doc(9.0, stage="fanout")
        prev = bench_doc(1.0, stage="decode")
        reg, cmp_ = stage_gate.compare(cur, prev)
        assert not cmp_ and not reg

    def test_zero_baseline_is_skipped(self):
        reg, cmp_ = stage_gate.compare(bench_doc(1.0), bench_doc(0.0))
        assert not cmp_ and not reg

    def test_batch_service_row_compares(self):
        cur = {"telemetry": {"stages": {}, "batch_service": {"count": 50, "p99_ms": 4.0}}}
        prev = {"telemetry": {"stages": {}, "batch_service": {"count": 50, "p99_ms": 1.0}}}
        reg, cmp_ = stage_gate.compare(cur, prev)
        assert cmp_ == ["/telemetry:batch_service"]
        assert len(reg) == 1


def _multi_stage_doc(stages: dict, config="2"):
    return {
        "parsed": {
            "configs": {
                config: {
                    "telemetry": {
                        "stages": {
                            name: {"count": 100, "p50_ms": p99 / 2, "p99_ms": p99}
                            for name, p99 in stages.items()
                        }
                    }
                }
            }
        }
    }


class TestDeviceSubStages:
    """The trace plane split device_batch into h2d/device_dispatch/d2h
    (PR 6): a prior round recorded before the split must pass through
    with a notice — never a vacuous failure — while the still-shared
    device_batch row keeps diffing."""

    def test_substages_pass_through_without_baseline(self):
        cur = _multi_stage_doc(
            {"device_batch": 1.0, "h2d": 0.2, "device_dispatch": 0.5, "d2h": 0.3}
        )
        prev = _multi_stage_doc({"device_batch": 1.0})
        reg, cmp_ = stage_gate.compare(cur, prev)
        assert not reg
        # only the shared stage was diffed; no sub-stage failed vacuously
        assert cmp_ == ["/parsed/configs/2/telemetry:device_batch"]
        assert stage_gate.new_stage_names(cur, prev) == [
            "d2h", "device_dispatch", "h2d",
        ]

    def test_substages_diff_once_both_rounds_have_them(self):
        cur = _multi_stage_doc({"h2d": 2.0, "d2h": 0.3})
        prev = _multi_stage_doc({"h2d": 1.0, "d2h": 0.3})
        reg, cmp_ = stage_gate.compare(cur, prev)
        assert len(reg) == 1 and "h2d" in reg[0]
        assert stage_gate.new_stage_names(cur, prev) == []

    def test_device_batch_regression_still_caught_across_split(self):
        # the sum row regressed even though only the new rounds carry
        # sub-stages: the shared device_batch row catches it
        cur = _multi_stage_doc({"device_batch": 5.0, "d2h": 4.0})
        prev = _multi_stage_doc({"device_batch": 1.0})
        reg, _ = stage_gate.compare(cur, prev)
        assert len(reg) == 1 and "device_batch" in reg[0]

    def test_pipeline_substages_pass_through_without_baseline(self):
        """The 3-deep pipeline's per-leg waits and the compaction d2h
        leg (ISSUE 11) land as new stage rows on their first round: the
        gate must notice them, never fail them vacuously."""
        cur = _multi_stage_doc(
            {
                "device_batch": 1.0,
                "leg_wait_h2d": 0.05,
                "leg_wait_d2h": 0.04,
                "compact_d2h": 0.3,
            }
        )
        prev = _multi_stage_doc({"device_batch": 1.0})
        reg, cmp_ = stage_gate.compare(cur, prev)
        assert not reg
        assert cmp_ == ["/parsed/configs/2/telemetry:device_batch"]
        assert stage_gate.new_stage_names(cur, prev) == [
            "compact_d2h", "leg_wait_d2h", "leg_wait_h2d",
        ]

    def test_pipeline_substages_diff_once_both_rounds_have_them(self):
        cur = _multi_stage_doc({"leg_wait_d2h": 2.0, "compact_d2h": 0.3})
        prev = _multi_stage_doc({"leg_wait_d2h": 1.0, "compact_d2h": 0.3})
        reg, _ = stage_gate.compare(cur, prev)
        assert len(reg) == 1 and "leg_wait_d2h" in reg[0]

    def test_fanout_split_passes_through_and_keeps_diffing(self):
        """The ISSUE 13 fan-out split: encode/flush land as new stage
        rows on their first round (noticed, never vacuously failed)
        while the coarse fanout row — still populated as their sum —
        keeps diffing against pre-split rounds."""
        cur = _multi_stage_doc(
            {"fanout": 1.1, "encode": 0.3, "flush": 0.8}
        )
        prev = _multi_stage_doc({"fanout": 1.0})
        reg, cmp_ = stage_gate.compare(cur, prev)
        assert not reg
        assert cmp_ == ["/parsed/configs/2/telemetry:fanout"]
        assert stage_gate.new_stage_names(cur, prev) == ["encode", "flush"]
        # a fanout regression across the split is still caught via the
        # shared sum row
        cur2 = _multi_stage_doc({"fanout": 5.0, "encode": 0.2, "flush": 4.8})
        reg2, _ = stage_gate.compare(cur2, prev)
        assert len(reg2) == 1 and "fanout" in reg2[0]

    def test_delivery_sli_rows_pass_through_and_keep_diffing(self):
        """The ISSUE 14 delivery-latency SLI rows (delivery_local /
        delivery_remote, folded per path from the labeled
        mqtt_tpu_delivery_latency_seconds family) land as new stage rows
        on their first round: noticed via new_stage_names, never a
        vacuous failure — and once both rounds carry them, a real p99
        regression IS caught."""
        cur = _multi_stage_doc(
            {"fanout": 1.0, "delivery_local": 2.0, "delivery_remote": 6.0}
        )
        prev = _multi_stage_doc({"fanout": 1.0})
        reg, cmp_ = stage_gate.compare(cur, prev)
        assert not reg
        assert cmp_ == ["/parsed/configs/2/telemetry:fanout"]
        assert stage_gate.new_stage_names(cur, prev) == [
            "delivery_local", "delivery_remote",
        ]
        # second round: the rows have a baseline and diff for real
        cur2 = _multi_stage_doc(
            {"fanout": 1.0, "delivery_local": 3.0, "delivery_remote": 6.0}
        )
        reg2, cmp2 = stage_gate.compare(cur2, cur)
        assert len(reg2) == 1 and "delivery_local" in reg2[0]
        assert "/parsed/configs/2/telemetry:delivery_remote" in cmp2

    def test_retired_stage_is_noticed_never_failed(self):
        """A stage present only in the PREVIOUS round (renamed/retired
        by the pipeline split) is surfaced as a notice and never
        diffed."""
        cur = _multi_stage_doc({"leg_wait_h2d": 0.1})
        prev = _multi_stage_doc({"device_batch": 1.0})
        reg, cmp_ = stage_gate.compare(cur, prev)
        assert not reg and not cmp_
        assert stage_gate.removed_stage_names(cur, prev) == ["device_batch"]
        assert stage_gate.removed_stage_names(prev, prev) == []

    def test_cli_prints_retired_stage_notice(self, tmp_path):
        cur = tmp_path / "BENCH_r02.json"
        prev = tmp_path / "BENCH_r01.json"
        cur.write_text(
            json.dumps(_multi_stage_doc({"leg_wait_h2d": 0.1, "fanout": 1.0}))
        )
        prev.write_text(
            json.dumps(_multi_stage_doc({"device_batch": 1.0, "fanout": 1.0}))
        )
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "exp", "stage_gate.py"),
             "--current", str(cur), "--previous", str(prev)],
            capture_output=True, text=True, cwd=REPO,
        )
        assert r.returncode == 0, r.stdout
        assert "retired" in r.stdout
        assert "device_batch" in r.stdout

    def test_cli_prints_new_stage_notice(self, tmp_path):
        cur = tmp_path / "BENCH_r02.json"
        prev = tmp_path / "BENCH_r01.json"
        cur.write_text(json.dumps(_multi_stage_doc({"device_batch": 1.0, "h2d": 0.2})))
        prev.write_text(json.dumps(_multi_stage_doc({"device_batch": 1.0})))
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "exp", "stage_gate.py"),
             "--current", str(cur), "--previous", str(prev)],
            capture_output=True, text=True, cwd=REPO,
        )
        assert r.returncode == 0, r.stdout
        assert "new stage(s) without a baseline" in r.stdout
        assert "h2d" in r.stdout


class TestBenchRanking:
    def test_newest_pair_orders_by_round(self, tmp_path):
        for name in ("BENCH_r02.json", "BENCH_r10.json", "BENCH_r09.json"):
            (tmp_path / name).write_text("{}")
        pair = stage_gate.newest_pair(str(tmp_path))
        assert os.path.basename(pair[0]) == "BENCH_r10.json"
        assert os.path.basename(pair[1]) == "BENCH_r09.json"

    def test_suffixed_variants_never_diff_against_their_round(self, tmp_path):
        """A _local/_cpu_fullscale variant is a different machine or
        backend: the auto-pick must compare canonical rounds (r05 vs
        r04), never a variant against its plain sibling."""
        for name in (
            "BENCH_r04.json", "BENCH_r05.json",
            "BENCH_r05_cpu_fullscale.json", "BENCH_r04_local.json",
        ):
            (tmp_path / name).write_text("{}")
        pair = stage_gate.newest_pair(str(tmp_path))
        assert os.path.basename(pair[0]) == "BENCH_r05.json"
        assert os.path.basename(pair[1]) == "BENCH_r04.json"

    def test_variants_used_only_without_canonical_rounds(self, tmp_path):
        for name in ("BENCH_r05_local.json", "BENCH_r05_cpu.json"):
            (tmp_path / name).write_text("{}")
        assert stage_gate.newest_pair(str(tmp_path)) is not None

    def test_fewer_than_two_files(self, tmp_path):
        (tmp_path / "BENCH_r01.json").write_text("{}")
        assert stage_gate.newest_pair(str(tmp_path)) is None


class TestCli:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "exp", "stage_gate.py"), *args],
            capture_output=True, text=True, cwd=REPO,
        )

    def test_cli_fails_on_regression(self, tmp_path):
        cur = tmp_path / "BENCH_r02.json"
        prev = tmp_path / "BENCH_r01.json"
        cur.write_text(json.dumps(bench_doc(5.0)))
        prev.write_text(json.dumps(bench_doc(1.0)))
        r = self._run("--current", str(cur), "--previous", str(prev))
        assert r.returncode == 1
        assert "REGRESSION" in r.stdout

    def test_cli_passes_clean_pair(self, tmp_path):
        cur = tmp_path / "BENCH_r02.json"
        prev = tmp_path / "BENCH_r01.json"
        cur.write_text(json.dumps(bench_doc(1.0)))
        prev.write_text(json.dumps(bench_doc(1.0)))
        r = self._run("--current", str(cur), "--previous", str(prev))
        assert r.returncode == 0, r.stdout

    def test_single_flag_never_self_diffs(self, tmp_path):
        """--current alone must pair against the newest OTHER round,
        never against itself (a self-diff passes vacuously)."""
        cur = tmp_path / "BENCH_r05.json"
        prev = tmp_path / "BENCH_r04.json"
        cur.write_text(json.dumps(bench_doc(9.0)))
        prev.write_text(json.dumps(bench_doc(1.0)))
        r = self._run("--current", str(cur), "--repo", str(tmp_path))
        assert r.returncode == 1, r.stdout  # 9x regression vs r04 caught
        assert "BENCH_r04.json" in r.stdout

    def test_cli_passes_repo_artifacts(self):
        """The checked-in BENCH history must pass the gate as wired in CI
        (device-less driver runs carry no telemetry blocks: notice+pass)."""
        r = self._run()
        assert r.returncode == 0, r.stdout
