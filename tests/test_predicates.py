"""MQTT+ payload-predicate subscriptions (ISSUE 8 / ROADMAP item 4).

Covers: the suffix grammar (malformed suffixes stay literal filters),
host-interpreter semantics (skip-to-pass for missing/non-numeric/
non-JSON payloads, float32 coercion), the seeded device-vs-host
differential oracle across every op code, registry interning/refcounts,
Subscription merge OR semantics, the engine's fan-out filtering (client
/ shared / inline legs), aggregation windows, the three round-trip
seams (retained matching, $SHARE parsing, v5 SUBACK reasons), the
breaker chaos leg (device eval degrades to the host interpreter
mid-storm), persistence round-trip, and the seconds-dialable cluster
SUSPECT window satellite.
"""

import asyncio
import json
import math
import random

import numpy as np
import pytest

from mqtt_tpu import Options, Server
from mqtt_tpu.packets import PUBLISH, SUBACK, UNSUBACK, Subscription
from mqtt_tpu.predicates import (
    OP_CONTAINS,
    OP_GT,
    OP_MEAN,
    PredicateEngine,
    compile_suffix,
    eval_rule_host,
    payload_number,
)
from mqtt_tpu.topics import SYS_PREFIX, Subscribers, split_predicate_suffix

from tests.test_server import (
    Harness,
    pub_packet,
    read_wire_packet,
    run,
    sub_packet,
)


def staged_options(**kw):
    return Options(
        inline_client=True,
        device_matcher=True,
        matcher_stage_window_ms=kw.pop("window_ms", 5.0),
        matcher_opts={"max_levels": 4, "background": False},
        predicate_oracle_sample=kw.pop("oracle_sample", 1),
        **kw,
    )


class TestGrammar:
    def test_numeric_suffix_splits(self):
        assert split_predicate_suffix("sensors/+/temp$GT{25.0}") == (
            "sensors/+/temp",
            "$GT{25.0}",
        )
        assert split_predicate_suffix("a/b$LTE{hum:-1.5}") == (
            "a/b",
            "$LTE{hum:-1.5}",
        )

    def test_bare_predicate_widens_to_hash(self):
        assert split_predicate_suffix("$CONTAINS{alarm}") == (
            "#",
            "$CONTAINS{alarm}",
        )

    def test_malformed_suffixes_stay_literal(self):
        for literal in (
            "a/b$GT{notanum}",  # non-numeric threshold
            "a/b$GT{}",  # empty arg
            "a/b$CONTAINS{}",  # empty substring
            "a/b$MEAN{temp:0}",  # window < 1
            "a/b$FOO{1}",  # unknown op
            "a/b$GT{1}/c",  # suffix not at the end
            "a/b$GT{nan}",  # explicit nan threshold
            "plain/topic",
        ):
            base, suffix = split_predicate_suffix(literal)
            assert (base, suffix) == (literal, ""), literal

    def test_share_filter_splits_on_base(self):
        base, suffix = split_predicate_suffix("$SHARE/g/a/b$GT{t:1.5}")
        assert base == "$SHARE/g/a/b" and suffix == "$GT{t:1.5}"

    def test_wildcard_base_with_suffix_validates(self):
        # the raw string would be an INVALID filter ('#' not last); the
        # base after the split is valid — the SUBACK seam relies on this
        base, suffix = split_predicate_suffix("alerts/#$CONTAINS{alarm}")
        assert base == "alerts/#" and suffix == "$CONTAINS{alarm}"

    def test_compile_suffix_round_trip(self):
        spec = compile_suffix("$GT{temp:25.0}")
        assert spec.op == OP_GT and spec.field == "temp" and spec.value == 25.0
        spec = compile_suffix("$CONTAINS{alarm}")
        assert spec.op == OP_CONTAINS and spec.text == b"alarm"
        spec = compile_suffix("$MEAN{v:10}")
        assert spec.op == OP_MEAN and spec.window == 10 and spec.is_agg


class TestNestedFieldPaths:
    """Dotted JSON field paths (ISSUE 12 satellite / PR 8 residual):
    ``$GT{battery.level:20}`` traverses nested objects in the
    once-per-publish extraction; flat fields keep their exact slots and
    a literal dotted FLAT key wins over traversal."""

    NESTED = json.dumps(
        {"battery": {"level": 17.5, "meta": {"v": 3}}, "temp": 21.0}
    ).encode()

    def test_dotted_path_extraction(self):
        assert payload_number(self.NESTED, "battery.level") == 17.5
        assert payload_number(self.NESTED, "battery.meta.v") == 3.0
        assert payload_number(self.NESTED, "temp") == 21.0

    def test_missing_path_is_nan_skip_to_pass(self):
        assert math.isnan(payload_number(self.NESTED, "battery.volts"))
        assert math.isnan(payload_number(self.NESTED, "battery.level.deep"))
        assert math.isnan(payload_number(self.NESTED, "nope.x"))
        spec = compile_suffix("$GT{battery.volts:1}")
        assert eval_rule_host(spec, self.NESTED)  # missing path: pass

    def test_flat_dotted_key_wins_over_traversal(self):
        flat = json.dumps(
            {"battery.level": 99.0, "battery": {"level": 1.0}}
        ).encode()
        assert payload_number(flat, "battery.level") == 99.0

    def test_nested_predicate_through_engine_and_device(self):
        """The device kernel sees only the extracted feature slot, so
        host and device agree on nested paths by construction — drive
        the full eval_batch path and cross-check the host oracle."""
        eng = PredicateEngine(oracle_sample=1)
        eng.register("$GT{battery.level:20}")
        eng.register("$LTE{battery.meta.v:3}")
        passing = json.dumps({"battery": {"level": 33, "meta": {"v": 3}}}).encode()
        failing = json.dumps({"battery": {"level": 5, "meta": {"v": 9}}}).encode()
        feats = [eng.features_for(p) for p in (passing, failing)]
        resolver = eng.eval_batch_async(feats)
        assert resolver is not None
        resolved = resolver()
        assert resolved is not None
        rows, eligible, _gen = resolved
        assert eligible == [0, 1]
        gt = eng._rules["$GT{battery.level:20}"]
        lte = eng._rules["$LTE{battery.meta.v:3}"]
        for row, payload in zip(rows, (passing, failing)):
            for rule in (gt, lte):
                bit = bool((row[rule.idx >> 5] >> np.uint32(rule.idx & 31)) & 1)
                assert bit == eval_rule_host(rule.spec, payload)

    def test_nested_subscribe_end_to_end(self):
        async def scenario():
            s = Server(staged_options())
            _collect = []

            def handler(cl, sub, pk):
                _collect.append(bytes(pk.payload))

            s.subscribe("batt/+$GT{battery.level:20}", 9, handler)
            s.publish("batt/a", json.dumps({"battery": {"level": 42}}).encode(), False, 0)
            s.publish("batt/a", json.dumps({"battery": {"level": 3}}).encode(), False, 0)
            await asyncio.sleep(0)
            assert _collect == [json.dumps({"battery": {"level": 42}}).encode()]

        run(scenario())


class TestHostInterpreter:
    def test_whole_payload_number(self):
        assert payload_number(b"25.5", "") == 25.5
        assert math.isnan(payload_number(b"abc", ""))
        assert math.isnan(payload_number(b"", ""))

    def test_json_field_extraction(self):
        p = json.dumps({"temp": 21.5, "ok": True, "s": "x"}).encode()
        assert payload_number(p, "temp") == 21.5
        assert math.isnan(payload_number(p, "missing"))
        assert math.isnan(payload_number(p, "s"))
        assert math.isnan(payload_number(p, "ok"))  # bool is not a number
        assert math.isnan(payload_number(b"not json", "temp"))

    def test_skip_to_pass(self):
        spec = compile_suffix("$GT{temp:25.0}")
        assert eval_rule_host(spec, b"not json")  # non-JSON: pass
        assert eval_rule_host(spec, b"{}")  # missing field: pass
        assert eval_rule_host(spec, b'{"temp": "warm"}')  # non-numeric: pass
        assert not eval_rule_host(spec, b'{"temp": 20}')  # applies: fail
        assert eval_rule_host(spec, b'{"temp": 30}')  # applies: pass

    def test_contains_never_skips(self):
        spec = compile_suffix("$CONTAINS{alarm}")
        assert eval_rule_host(spec, b"fire alarm!")
        assert not eval_rule_host(spec, b"all quiet")
        assert not eval_rule_host(spec, b"")


class TestRegistry:
    def test_intern_and_refcount(self):
        eng = PredicateEngine()
        r1 = eng.register("$GT{v:1.0}")
        r2 = eng.register("$GT{v:1.0}")
        assert r1 is r2 and r1.refs == 2 and eng.rule_count == 1
        eng.release(("$GT{v:1.0}",))
        assert eng.rule_count == 1
        eng.release(("$GT{v:1.0}",))
        assert eng.rule_count == 0 and not eng.active

    def test_max_rules_degrades_to_host_only(self):
        eng = PredicateEngine(max_rules=1)
        a = eng.register("$GT{v:1.0}")
        b = eng.register("$GT{v:2.0}")
        assert a.device and not b.device  # past the cap: host interpreter

    def test_agg_rules_never_on_device(self):
        eng = PredicateEngine()
        r = eng.register("$MEAN{v:5}")
        assert not r.device

    def test_parse_subscribe(self):
        eng = PredicateEngine()
        base, preds = eng.parse_subscribe("s/+/t$GT{25.0}")
        assert base == "s/+/t" and preds == ("$GT{25.0}",)
        base, preds = eng.parse_subscribe("plain/t")
        assert base == "plain/t" and preds == ()


class TestMergeSemantics:
    def test_unpredicated_side_clears(self):
        a = Subscription(filter="a/+", predicates=())
        b = Subscription(filter="a/b", predicates=("$GT{1.0}",))
        assert a.merge(b).predicates == ()
        assert b.merge(a).predicates == ()

    def test_predicated_union(self):
        a = Subscription(filter="a/+", predicates=("$GT{1.0}",))
        b = Subscription(filter="a/b", predicates=("$LT{0.5}",))
        assert a.merge(b).predicates == ("$GT{1.0}", "$LT{0.5}")
        c = Subscription(filter="a/#", predicates=("$GT{1.0}",))
        assert a.merge(c).predicates == ("$GT{1.0}",)

    def test_self_merged_copy_keeps_predicates(self):
        a = Subscription(filter="a/+", identifier=3, predicates=("$GT{1.0}",))
        assert a.self_merged_copy().predicates == ("$GT{1.0}",)


class TestDifferentialOracle:
    """The satellite property test: seeded rules x payload corpus, every
    device verdict must equal the host interpreter's — across op codes,
    NaN/missing-field payloads, and non-JSON payloads."""

    def test_seeded_device_vs_host_property(self):
        import numpy as np

        rng = random.Random(1234)
        eng = PredicateEngine(oracle_sample=0)
        suffixes = []
        ops = ["GT", "GTE", "LT", "LTE", "EQ", "NE"]
        for _ in range(120):
            op = rng.choice(ops)
            field = rng.choice(["", "temp", "hum", "deep"])
            thr = round(rng.uniform(-3, 3), 3)
            s = "$%s{%s%s}" % (op, (field + ":") if field else "", thr)
            if s not in suffixes:
                eng.register(s)
                suffixes.append(s)
        for text in ("alarm", "zed", "}{"):
            s = "$CONTAINS{%s}" % text
            eng.register(s)
            suffixes.append(s)
        payloads = [
            b"1.5",
            b"-2",
            b"0",
            b"",
            b"not json at all",
            b"alarm",
            b"alarm}{",
            json.dumps({"temp": 1.25, "hum": -0.5}).encode(),
            json.dumps({"temp": "hot"}).encode(),
            json.dumps({"hum": 2.999}).encode(),
            json.dumps([1, 2, 3]).encode(),
            json.dumps({"deep": 0.0, "temp": None}).encode(),
        ]
        # exact-threshold payloads drill EQ/NE/GTE/LTE boundary cases
        for s in suffixes[:40]:
            spec = eng._rules[s].spec
            if spec.field in ("", "temp") and spec.op <= 6:
                payloads.append(
                    json.dumps({"temp": spec.value}).encode()
                    if spec.field
                    else repr(spec.value).encode()
                )
        feats = [eng.features_for(p) for p in payloads]
        resolved = eng.eval_batch_async(feats)
        assert resolved is not None
        eng.attach_rows(feats, resolved())
        mismatches = []
        for p, f in zip(payloads, feats):
            assert f.device_row is not None
            for s in suffixes:
                rule = eng._rules[s]
                bit = bool(
                    (f.device_row[rule.idx >> 5] >> np.uint32(rule.idx & 31)) & 1
                )
                want = eval_rule_host(rule.spec, p)
                if bit != want:
                    mismatches.append((s, p, bit, want))
        assert not mismatches, mismatches[:5]

    def test_registry_churn_between_build_and_eval_stays_host(self):
        eng = PredicateEngine(oracle_sample=0)
        eng.register("$GT{v:1.0}")
        feats = [eng.features_for(b'{"v": 2.0}')]
        eng.register("$LT{v:9.0}")  # layout changed after extraction
        resolved = eng.eval_batch_async(feats)
        # stale-version rows are excluded: either no eligible rows (None)
        # or the carrier stays unstamped — the host interpreter decides
        if resolved is not None:
            eng.attach_rows(feats, resolved())
        assert feats[0].device_row is None


def _subs_with(*entries) -> Subscribers:
    s = Subscribers()
    for cid, sub in entries:
        s.subscriptions[cid] = sub
    return s


class TestApplyFiltering:
    def test_client_filtering_and_fail_open(self):
        eng = PredicateEngine(oracle_sample=0)
        eng.register("$GT{v:5.0}")
        eng.register("$GT{v:99.0}")
        subs = _subs_with(
            ("hot", Subscription(filter="t", predicates=("$GT{v:5.0}",))),
            ("plain", Subscription(filter="t")),
            ("gone", Subscription(filter="t", predicates=("$GT{v:99.0}",))),
        )
        out, emissions = eng.apply(subs, b'{"v": 6.0}')
        assert set(out.subscriptions) == {"hot", "plain"}
        assert emissions == []
        assert eng.filtered == 1 and eng.deliveries == 1

    def test_released_rule_fails_open(self):
        eng = PredicateEngine(oracle_sample=0)
        eng.register("$GT{v:5.0}")  # keeps the engine active
        subs = _subs_with(
            ("c", Subscription(filter="t", predicates=("$LT{v:0.0}",))),
        )
        out, _ = eng.apply(subs, b'{"v": 3.0}')  # rule never registered
        assert "c" in out.subscriptions  # unknown rule: deliver

    def test_shared_groups_filter_before_selection(self):
        eng = PredicateEngine(oracle_sample=0)
        eng.register("$GT{v:5.0}")
        subs = Subscribers()
        subs.shared["$SHARE/g/t"] = {
            "fail": Subscription(filter="t", predicates=("$GT{v:5.0}",)),
            "pass": Subscription(filter="t"),
        }
        out, _ = eng.apply(subs, b'{"v": 1.0}')
        assert set(out.shared["$SHARE/g/t"]) == {"pass"}
        out.select_shared()
        assert set(out.shared_selected) == {"pass"}

    def test_empty_shared_group_removed(self):
        eng = PredicateEngine(oracle_sample=0)
        eng.register("$GT{v:5.0}")
        subs = Subscribers()
        subs.shared["$SHARE/g/t"] = {
            "fail": Subscription(filter="t", predicates=("$GT{v:5.0}",)),
        }
        out, _ = eng.apply(subs, b'{"v": 1.0}')
        assert out.shared == {}

    def test_aggregation_window_mean_and_max(self):
        eng = PredicateEngine(oracle_sample=0)
        eng.register("$MEAN{v:3}")
        eng.register("$MAX{v:2}")
        sub_mean = Subscription(filter="t", predicates=("$MEAN{v:3}",))
        sub_max = Subscription(filter="t", predicates=("$MAX{v:2}",))
        emitted = []
        for v in (1.0, 2.0, 6.0, 4.0):
            subs = _subs_with(("m", sub_mean), ("x", sub_max))
            out, emissions = eng.apply(subs, json.dumps({"v": v}).encode())
            # aggregation subscriptions never get the raw message
            assert "m" not in out.subscriptions
            assert "x" not in out.subscriptions
            emitted.extend(emissions)
        kinds = [(k, t, p) for k, t, _s, p in emitted]
        # MAX window 2 completes twice: max(1,2)=2, max(6,4)=6
        # MEAN window 3 completes once: (1+2+6)/3 = 3
        assert ("client", "x", b"2") in kinds
        assert ("client", "x", b"6") in kinds
        assert ("client", "m", b"3") in kinds
        assert eng.agg_emits == 3

    def test_aggregation_skips_nan_samples(self):
        eng = PredicateEngine(oracle_sample=0)
        eng.register("$MIN{v:2}")
        sub = Subscription(filter="t", predicates=("$MIN{v:2}",))
        emitted = []
        for payload in (b'{"v": 5}', b"not json", b'{"v": 3}'):
            _, emissions = eng.apply(_subs_with(("c", sub)), payload)
            emitted.extend(emissions)
        assert len(emitted) == 1 and emitted[0][3] == b"3"

    def test_inline_subscriptions_filter(self):
        from mqtt_tpu.topics import InlineSubscription

        eng = PredicateEngine(oracle_sample=0)
        eng.register("$CONTAINS{alarm}")
        subs = Subscribers()
        subs.inline_subscriptions[1] = InlineSubscription(
            filter="t", identifier=1, handler=lambda *a: None,
            predicates=("$CONTAINS{alarm}",),
        )
        out, _ = eng.apply(subs, b"quiet")
        assert out.inline_subscriptions == {}
        subs = Subscribers()
        subs.inline_subscriptions[1] = InlineSubscription(
            filter="t", identifier=1, handler=lambda *a: None,
            predicates=("$CONTAINS{alarm}",),
        )
        out, _ = eng.apply(subs, b"ALARM alarm")
        assert 1 in out.inline_subscriptions


class TestBreakerDegradation:
    """The chaos leg: device predicate evaluation fails mid-storm, the
    breaker trips, the host interpreter keeps filtering correctly, and
    a healthy probe closes the breaker again."""

    class _BoomEvaluator:
        n_rules = 1
        n_slots = 1
        n_cwords = 1

        def rebuild(self, *a, **k):
            pass

        def eval_async(self, feats, cmask):
            raise RuntimeError("injected device fault")

    def test_breaker_trips_to_host_and_probes_back(self):
        eng = PredicateEngine(oracle_sample=0)
        eng.register("$GT{v:5.0}")
        # force the evaluator in and poison it
        eng._rebuild_evaluator()
        healthy = eng._evaluator
        eng._evaluator = self._BoomEvaluator()
        sub = Subscription(filter="t", predicates=("$GT{v:5.0}",))
        for _ in range(eng.breaker.failure_threshold):
            feats = [eng.features_for(b'{"v": 9.0}')]
            resolved = eng.eval_batch_async(feats)
            assert resolved is None  # issue leg failed -> breaker failure
            # fan-out still filters correctly via the host interpreter
            out, _ = eng.apply(_subs_with(("c", sub)), b'{"v": 1.0}', feats[0])
            assert "c" not in out.subscriptions
            out, _ = eng.apply(_subs_with(("c", sub)), b'{"v": 9.0}', feats[0])
            assert "c" in out.subscriptions
        assert eng.breaker.state == "open"
        assert eng.device_errors >= eng.breaker.failure_threshold
        # while OPEN (before the probe window) the device is not touched
        assert eng.eval_batch_async([eng.features_for(b"1")]) is None
        # heal the device; force the probe window open
        eng._evaluator = healthy
        eng._table_gen = -1  # rebuild against the healthy evaluator
        eng.breaker._retry_at = 0.0
        closed = 0
        for _ in range(eng.breaker.probe_successes):
            eng.breaker._retry_at = 0.0
            feats = [eng.features_for(b'{"v": 9.0}')]
            resolved = eng.eval_batch_async(feats)
            assert resolved is not None  # the probe batch runs on device
            assert resolved() is not None
            closed += 1
        assert eng.breaker.state == "closed"
        # and device decisions flow again
        feats = [eng.features_for(b'{"v": 9.0}')]
        resolved = eng.eval_batch_async(feats)
        eng.attach_rows(feats, resolved())
        assert feats[0].device_row is not None


class TestBrokerEndToEnd:
    def test_staged_device_filtering_with_oracle(self):
        async def scenario():
            h = Harness(staged_options())
            await h.server.serve()
            r1, w1, _ = await h.connect("pred-sub")
            w1.write(
                sub_packet(1, [Subscription(filter="s/+/t$GT{temp:25.0}", qos=0)])
            )
            await w1.drain()
            ack = await read_wire_packet(r1)
            assert ack.fixed_header.type == SUBACK
            assert ack.reason_codes == b"\x00"
            r2, w2, _ = await h.connect("plain-sub")
            w2.write(sub_packet(1, [Subscription(filter="s/#", qos=0)]))
            await w2.drain()
            await read_wire_packet(r2)
            h.server.matcher.flush()
            # the trie stores the BASE filter with the predicate attached
            subs = h.server.topics.subscribers("s/1/t")
            assert any(
                s.predicates == ("$GT{temp:25.0}",)
                for s in subs.subscriptions.values()
            )
            rp, wp, _ = await h.connect("pub")
            for v in (20.0, 30.0, 26.5):
                wp.write(pub_packet("s/1/t", json.dumps({"temp": v}).encode()))
            await wp.drain()
            for _ in range(3):  # plain subscriber: everything
                pk = await read_wire_packet(r2)
                assert pk.fixed_header.type == PUBLISH
            got = []  # predicated subscriber: only > 25
            for _ in range(2):
                pk = await read_wire_packet(r1)
                got.append(json.loads(bytes(pk.payload))["temp"])
            assert got == [30.0, 26.5], got
            eng = h.server._predicates
            g = eng.gauges()
            assert g["oracle_mismatches"] == 0
            assert g["filtered"] == 1 and g["deliveries"] == 2, g
            assert g["device_decisions"] >= 1, g  # device path really ran
            # $SYS tree renders the plane
            h.server.publish_sys_topics()
            pks = h.server.topics.messages(SYS_PREFIX + "/broker/predicates/+")
            tree = {p.topic_name: bytes(p.payload) for p in pks}
            assert tree[SYS_PREFIX + "/broker/predicates/rules"] == b"1"
            await h.server.close()
            await h.shutdown()

        run(scenario())

    def test_unsubscribe_with_original_suffixed_filter(self):
        async def scenario():
            h = Harness(staged_options())
            await h.server.serve()
            r, w, _ = await h.connect("c1")
            w.write(sub_packet(1, [Subscription(filter="a/b$GT{1.0}", qos=0)]))
            await w.drain()
            await read_wire_packet(r)
            assert h.server._predicates.rule_count == 1
            assert h.server.info.subscriptions == 1
            from mqtt_tpu.packets import (
                UNSUBSCRIBE,
                FixedHeader,
                Packet,
                encode_packet,
            )

            w.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=UNSUBSCRIBE, qos=1),
                        packet_id=2,
                        filters=[Subscription(filter="a/b$GT{1.0}")],
                    )
                )
            )
            await w.drain()
            ack = await read_wire_packet(r)
            assert ack.fixed_header.type == UNSUBACK
            assert h.server.info.subscriptions == 0
            assert h.server._predicates.rule_count == 0  # refs released
            await h.server.close()
            await h.shutdown()

        run(scenario())

    def test_resubscribe_replaces_predicate(self):
        async def scenario():
            h = Harness(staged_options())
            await h.server.serve()
            r, w, _ = await h.connect("c1")
            w.write(sub_packet(1, [Subscription(filter="a/b$GT{1.0}", qos=0)]))
            await w.drain()
            await read_wire_packet(r)
            w.write(sub_packet(2, [Subscription(filter="a/b$LT{9.0}", qos=0)]))
            await w.drain()
            await read_wire_packet(r)
            eng = h.server._predicates
            assert eng.rule_count == 1  # the $GT rule's ref was released
            assert "$LT{9.0}" in eng._rules
            # replacing with a PLAIN subscribe drops the last rule too
            w.write(sub_packet(3, [Subscription(filter="a/b", qos=0)]))
            await w.drain()
            await read_wire_packet(r)
            assert eng.rule_count == 0
            await h.server.close()
            await h.shutdown()

        run(scenario())


class TestRoundTripSeams:
    """ISSUE 8 satellite: the stripped suffix must not leak into retained
    matching, $SHARE parsing, or the v5 SUBACK reason path."""

    def test_retained_matching_uses_base_and_filters_payload(self):
        async def scenario():
            h = Harness(staged_options())
            await h.server.serve()
            rp, wp, _ = await h.connect("retainer")
            wp.write(
                pub_packet("s/1/t", json.dumps({"temp": 30.0}).encode(), retain=True)
            )
            wp.write(
                pub_packet("s/2/t", json.dumps({"temp": 10.0}).encode(), retain=True)
            )
            await wp.drain()
            retained = h.server.topics.retained
            deadline = asyncio.get_event_loop().time() + 10
            while (
                retained.get("s/1/t") is None or retained.get("s/2/t") is None
            ) and asyncio.get_event_loop().time() < deadline:
                await asyncio.sleep(0.02)
            assert retained.get("s/1/t") is not None
            assert retained.get("s/2/t") is not None
            # a predicated subscribe matches retained messages on the
            # BASE filter and delivers only the passing payload
            r, w, _ = await h.connect("late-sub")
            w.write(
                sub_packet(1, [Subscription(filter="s/+/t$GT{temp:25.0}", qos=0)])
            )
            await w.drain()
            got = []
            for _ in range(2):  # SUBACK + exactly one retained publish
                pk = await read_wire_packet(r)
                got.append(pk)
            types = [p.fixed_header.type for p in got]
            assert SUBACK in types and PUBLISH in types
            pub = got[types.index(PUBLISH)]
            assert pub.topic_name == "s/1/t"
            assert json.loads(bytes(pub.payload))["temp"] == 30.0
            with pytest.raises(asyncio.TimeoutError):
                await read_wire_packet(r)  # the failing retained never comes
            await h.server.close()
            await h.shutdown()

        run(scenario())

    def test_share_group_parses_on_base(self):
        async def scenario():
            h = Harness(staged_options())
            await h.server.serve()
            r, w, _ = await h.connect("shared-1")
            w.write(
                sub_packet(
                    1,
                    [Subscription(filter="$SHARE/grp/s/t$GT{v:5.0}", qos=0)],
                )
            )
            await w.drain()
            ack = await read_wire_packet(r)
            assert ack.reason_codes == b"\x00"
            # the share index stores the BASE group filter
            subs = h.server.topics.subscribers("s/t")
            assert "$SHARE/grp/s/t" in subs.shared
            h.server.matcher.flush()
            rp, wp, _ = await h.connect("pub")
            wp.write(pub_packet("s/t", b'{"v": 1.0}'))  # fails the predicate
            wp.write(pub_packet("s/t", b'{"v": 7.0}'))  # passes
            await wp.drain()
            pk = await read_wire_packet(r)
            assert json.loads(bytes(pk.payload))["v"] == 7.0
            with pytest.raises(asyncio.TimeoutError):
                await read_wire_packet(r)
            await h.server.close()
            await h.shutdown()

        run(scenario())

    def test_v5_suback_reasons_and_identifier(self):
        async def scenario():
            from mqtt_tpu.packets import (
                ERR_TOPIC_FILTER_INVALID,
                SUBSCRIBE,
                FixedHeader,
                Packet,
                Properties,
                encode_packet,
            )

            h = Harness(staged_options())
            await h.server.serve()
            r, w, _ = await h.connect("v5-sub", version=5)
            # reason-code seam: one valid predicated filter (the raw
            # string would be INVALID: '#' not last), one invalid base,
            # one valid plain — codes reflect the BASE filters
            w.write(
                sub_packet(
                    1,
                    [
                        Subscription(filter="bad/#/mid$GT{1.0}", qos=0),
                        Subscription(filter="plain/t", qos=0),
                    ],
                    version=5,
                )
            )
            await w.drain()
            ack = await read_wire_packet(r, version=5)
            assert ack.fixed_header.type == SUBACK
            assert ack.reason_codes == bytes(
                [ERR_TOPIC_FILTER_INVALID.code, 0]
            )
            # identifier seam: the v5 subscription-identifier property
            # must survive the suffix strip onto delivered publishes
            w.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=SUBSCRIBE, qos=1),
                        protocol_version=5,
                        packet_id=2,
                        properties=Properties(subscription_identifier=[7]),
                        filters=[
                            Subscription(
                                filter="alerts/#$CONTAINS{alarm}",
                                qos=1,
                                identifier=7,
                            )
                        ],
                    )
                )
            )
            await w.drain()
            ack = await read_wire_packet(r, version=5)
            assert ack.reason_codes == bytes([1])
            h.server.matcher.flush()
            rp, wp, _ = await h.connect("pub")
            wp.write(pub_packet("alerts/fire", b"big alarm"))
            wp.write(pub_packet("alerts/fire", b"quiet"))
            await wp.drain()
            pk = await read_wire_packet(r, version=5)
            assert bytes(pk.payload) == b"big alarm"
            # the v5 subscription identifier survives the strip
            assert pk.properties.subscription_identifier == [7]
            with pytest.raises(asyncio.TimeoutError):
                await read_wire_packet(r, version=5)
            await h.server.close()
            await h.shutdown()

        run(scenario())


class TestPersistence:
    def test_storage_round_trip_re_registers_rules(self):
        from mqtt_tpu.hooks.storage import Subscription as StoredSub
        from mqtt_tpu.hooks.storage.base import subscription_from_dict

        rec = StoredSub(
            client="c1",
            filter="s/+/t",
            qos=1,
            predicates=["$GT{temp:25.0}"],
        )
        back = subscription_from_dict(
            json.loads(json.dumps(rec.__dict__))
        )
        assert back.predicates == ["$GT{temp:25.0}"]
        s = Server(Options(inline_client=False))
        s.load_subscriptions([back])
        assert s._predicates.rule_count == 1
        subs = s.topics.subscribers("s/1/t")
        assert subs.subscriptions["c1"].predicates == ("$GT{temp:25.0}",)

    def test_disabled_plane_restores_base_filter(self):
        from mqtt_tpu.hooks.storage import Subscription as StoredSub

        s = Server(Options(inline_client=False, predicate_filters=False))
        s.load_subscriptions(
            [StoredSub(client="c1", filter="s/t", predicates=["$GT{1.0}"])]
        )
        # fails open: base filter serves unfiltered, nothing crashes
        assert "c1" in s.topics.subscribers("s/t").subscriptions


class TestFastPathGate:
    def test_plan_negative_caches_predicated_topics(self):
        s = Server(Options(inline_client=False))
        s.topics.subscribe("plain", Subscription(filter="t/a"))
        assert s._plan_for_topic("t/a")  # fast-path plan exists
        s.topics.subscribe(
            "pred", Subscription(filter="t/b", predicates=("$GT{1.0}",))
        )
        assert s._plan_for_topic("t/b") is None  # decode path: per-payload
        # and the plain topic keeps its plan
        assert s._plan_for_topic("t/a")


class TestInlinePredicates:
    def test_inline_subscribe_filters_and_releases(self):
        async def scenario():
            h = Harness(staged_options())
            await h.server.serve()
            got = []
            h.server.subscribe(
                "s/t$CONTAINS{alarm}", 42, lambda cl, sub, pk: got.append(bytes(pk.payload))
            )
            assert h.server._predicates.rule_count == 1
            h.server.publish("s/t", b"no match", False, 0)
            h.server.publish("s/t", b"alarm now", False, 0)
            await asyncio.sleep(0.1)
            assert got == [b"alarm now"]
            h.server.unsubscribe("s/t$CONTAINS{alarm}", 42)
            assert h.server._predicates.rule_count == 0
            await h.server.close()
            await h.shutdown()

        run(scenario())

    def test_unmatched_inline_unsubscribe_never_underflows_refs(self):
        async def scenario():
            h = Harness(staged_options())
            await h.server.serve()
            handler = lambda *a: None  # noqa: E731
            h.server.subscribe("a/t$GT{5.0}", 1, handler)
            h.server.subscribe("b/t$GT{5.0}", 2, handler)  # shared rule
            eng = h.server._predicates
            assert eng._rules["$GT{5.0}"].refs == 2
            # unsubscribes that match NOTHING must not drop the refs
            h.server.unsubscribe("a/t$GT{5.0}", 99)  # wrong id
            h.server.unsubscribe("zz/t$GT{5.0}", 1)  # wrong filter
            assert eng._rules["$GT{5.0}"].refs == 2
            h.server.unsubscribe("a/t$GT{5.0}", 1)
            h.server.unsubscribe("b/t$GT{5.0}", 2)
            assert eng.rule_count == 0
            await h.server.close()
            await h.shutdown()

        run(scenario())

    def test_inline_resubscribe_releases_replaced_rule(self):
        async def scenario():
            h = Harness(staged_options())
            await h.server.serve()
            handler = lambda *a: None  # noqa: E731
            h.server.subscribe("a/t$GT{5.0}", 1, handler)
            h.server.subscribe("a/t$GT{5.0}", 1, handler)  # replace, same rule
            eng = h.server._predicates
            assert eng._rules["$GT{5.0}"].refs == 1
            h.server.subscribe("a/t$LT{2.0}", 1, handler)  # replace, new rule
            assert "$GT{5.0}" not in eng._rules
            assert eng._rules["$LT{2.0}"].refs == 1
            h.server.unsubscribe("a/t$LT{2.0}", 1)
            assert eng.rule_count == 0 and not eng.active
            await h.server.close()
            await h.shutdown()

        run(scenario())


class TestSuspectWindowKnob:
    """ISSUE 8 satellite: the PR 5 SUSPECT window becomes seconds-dialable
    via cluster_suspect_window_s (wall-clock wins over the pings knob)."""

    def _cluster_for(self, opts):
        from mqtt_tpu.cluster import Cluster

        s = Server(opts)
        return Cluster(s, worker_id=0, n_workers=1, sock_dir="/tmp")

    def test_window_converts_to_ping_intervals(self):
        from mqtt_tpu.cluster import Cluster

        opts = Options(cluster_suspect_window_s=27.0)
        c = self._cluster_for(opts)
        # 27s at a 5s ping cadence rounds UP to 6 missed pongs; the
        # default PARTITIONED threshold (5) is re-floored strictly above
        assert c.suspect_pings == math.ceil(27.0 / Cluster.PING_INTERVAL_S)
        assert c.partition_pings == c.suspect_pings + 3

    def test_sub_interval_window_floors_at_one(self):
        opts = Options(cluster_suspect_window_s=0.5)
        c = self._cluster_for(opts)
        assert c.suspect_pings == 1
        assert c.partition_pings == 5  # default already strictly above

    def test_zero_keeps_legacy_pings_knob(self):
        opts = Options(
            cluster_suspect_window_s=0.0, cluster_peer_health_suspect_pings=3
        )
        c = self._cluster_for(opts)
        assert c.suspect_pings == 3

    def test_negative_normalizes_to_legacy(self):
        opts = Options(cluster_suspect_window_s=-5.0)
        opts.ensure_defaults()
        assert opts.cluster_suspect_window_s == 0.0


class TestStringAndCompoundPredicates:
    """The $EQS / $AND / $OR grammar extension (ISSUE 20 satellite):
    string equality rides the host-computed verdict bitmask like
    CONTAINS; compounds intern their members as ordinary (device-
    eligible) rules and combine the child bits host-side."""

    def test_eqs_suffix_splits_and_compiles(self):
        from mqtt_tpu.predicates import OP_EQS

        assert split_predicate_suffix("cfg/mode$EQS{mode:active}") == (
            "cfg/mode",
            "$EQS{mode:active}",
        )
        spec = compile_suffix("$EQS{mode:active}")
        assert spec.op == OP_EQS
        assert spec.field == "mode" and spec.text == b"active"
        # empty field: whole payload as the string
        spec = compile_suffix("$EQS{:go}")
        assert spec.field == "" and spec.text == b"go"

    def test_compound_suffix_splits_and_compiles(self):
        from mqtt_tpu.predicates import OP_AND, OP_GT, OP_LT, OP_OR

        base, suffix = split_predicate_suffix("$AND{$GT{t:1.0}$LT{t:5.0}}")
        assert (base, suffix) == ("#", "$AND{$GT{t:1.0}$LT{t:5.0}}")
        spec = compile_suffix(suffix)
        assert spec.op == OP_AND and spec.is_compound
        assert [c.op for c in spec.children] == [OP_GT, OP_LT]
        base, suffix = split_predicate_suffix(
            "a/b$OR{$EQS{m:on}$CONTAINS{hot}}"
        )
        assert base == "a/b"
        assert compile_suffix(suffix).op == OP_OR

    def test_malformed_forms_stay_literal_filters(self):
        for literal in (
            "a/b$EQS{noseparator}",  # no field:literal colon
            "a/b$AND{$GT{t:1.0}}",  # compound of one: spell it plainly
            "a/b$AND{$MEAN{t:5}$GT{t:1.0}}",  # agg member has no verdict
            "a/b$AND{$GT{t:1.0}junk}",  # trailing junk in the argument
            "a/b$AND{}",  # empty compound
        ):
            assert split_predicate_suffix(literal) == (literal, ""), literal

    def test_eqs_host_semantics(self):
        from mqtt_tpu.predicates import eval_equals

        spec = compile_suffix("$EQS{mode:active}")
        assert eval_rule_host(spec, b'{"mode": "active"}')
        assert not eval_rule_host(spec, b'{"mode": "idle"}')
        # skip-to-pass: missing / non-string field, non-JSON payload
        assert eval_rule_host(spec, b'{"other": 1}')
        assert eval_rule_host(spec, b'{"mode": 7}')
        assert eval_rule_host(spec, b"not json")
        # whole-payload equality has no skip: bytes match or they don't
        whole = compile_suffix("$EQS{:go}")
        assert eval_rule_host(whole, b"go")
        assert not eval_rule_host(whole, b"stop")
        assert eval_equals(b'{"a.b": "x"}', "a.b", b"x")

    def test_compound_host_semantics(self):
        land = compile_suffix("$AND{$GT{t:1.0}$LT{t:5.0}}")
        assert eval_rule_host(land, b'{"t": 3}')
        assert not eval_rule_host(land, b'{"t": 9}')
        lor = compile_suffix("$OR{$GT{t:5.0}$CONTAINS{hot}}")
        assert eval_rule_host(lor, b'{"t": 1, "s": "hot"}')
        assert eval_rule_host(lor, b'{"t": 9}')
        assert not eval_rule_host(lor, b'{"t": 1}')

    def test_engine_interns_members_and_releases_refcounted(self):
        eng = PredicateEngine(oracle_sample=0)
        compound = "$AND{$GT{v:1.0}$EQS{m:on}}"
        rule = eng.register(compound)
        assert rule.children == ("$GT{v:1.0}", "$EQS{m:on}")
        assert not rule.device  # the compound row itself never on device
        assert eng._rules["$GT{v:1.0}"].device  # ...but its members are
        eng.register("$GT{v:1.0}")  # an independent plain subscription
        eng.release((compound,))
        assert compound not in eng._rules
        assert eng._rules["$GT{v:1.0}"].refs == 1  # member ref dropped
        assert "$EQS{m:on}" not in eng._rules
        eng.release(("$GT{v:1.0}",))
        assert not eng._rules

    def test_apply_filters_through_compound_and_eqs(self):
        eng = PredicateEngine(oracle_sample=0)
        eng.register("$AND{$GT{v:5.0}$EQS{mode:run}}")
        eng.register("$EQS{mode:run}")

        def subs():  # apply() consumes its per-publish copy in place
            return _subs_with(
                (
                    "both",
                    Subscription(
                        filter="t",
                        predicates=("$AND{$GT{v:5.0}$EQS{mode:run}}",),
                    ),
                ),
                (
                    "str",
                    Subscription(filter="t", predicates=("$EQS{mode:run}",)),
                ),
            )

        out, _ = eng.apply(subs(), b'{"v": 3.0, "mode": "run"}')
        assert set(out.subscriptions) == {"str"}  # AND fails on v
        out, _ = eng.apply(subs(), b'{"v": 9.0, "mode": "run"}')
        assert set(out.subscriptions) == {"both", "str"}
        out, _ = eng.apply(subs(), b'{"v": 9.0, "mode": "walk"}')
        assert set(out.subscriptions) == set()

    def test_eqs_device_vs_host_differential(self):
        import numpy as np

        eng = PredicateEngine(oracle_sample=0)
        suffixes = [
            "$EQS{mode:active}",
            "$EQS{mode:idle}",
            "$EQS{:go}",
            "$CONTAINS{go}",  # shares the verdict bit space with EQS
            "$GT{v:2.0}",
        ]
        for s in suffixes:
            eng.register(s)
        payloads = [
            b'{"mode": "active", "v": 3}',
            b'{"mode": "idle"}',
            b"go",
            b'{"mode": 5, "v": 1}',
            b"not json",
        ]
        feats = [eng.features_for(p) for p in payloads]
        resolved = eng.eval_batch_async(feats)
        assert resolved is not None
        eng.attach_rows(feats, resolved())
        for p, f in zip(payloads, feats):
            assert f.device_row is not None
            for s in suffixes:
                rule = eng._rules[s]
                bit = bool(
                    (f.device_row[rule.idx >> 5] >> np.uint32(rule.idx & 31))
                    & 1
                )
                assert bit == eval_rule_host(rule.spec, p), (s, p)

    def test_gauges_count_equals_bits(self):
        eng = PredicateEngine(oracle_sample=0)
        eng.register("$EQS{a:x}")
        eng.register("$CONTAINS{y}")
        g = eng.gauges()
        assert g["equals"] == 1 and g["contains"] == 1
