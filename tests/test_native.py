"""Differential tests: native C core vs the pure-Python reference paths.

The native library is optional; when it can't be built these tests skip
(except the pure-Python fallback cases, which always run).
"""

import hashlib
import random

import numpy as np
import pytest

from mqtt_tpu import native
from mqtt_tpu.native import (
    Frame,
    _frame_scan_py,
    _varint_decode_py,
    _varint_encode_py,
    frame_scan,
    hash_token_native,
    tokenize_topics_native,
    utf8_valid,
    varint_decode,
    varint_encode,
)
from mqtt_tpu.ops.hashing import tokenize_topics_py
from tests.tpackets import CASES

needs_native = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)


@needs_native
class TestBlake2b:
    def test_matches_hashlib(self):
        rng = random.Random(11)
        for _ in range(500):
            n = rng.randrange(0, 300)
            tok = bytes(rng.randrange(256) for _ in range(n))
            salt = rng.randrange(1 << 63)
            want = int.from_bytes(
                hashlib.blake2b(
                    tok, digest_size=8, salt=salt.to_bytes(8, "little")
                ).digest(),
                "little",
            )
            assert hash_token_native(tok, salt) == want

    def test_multiblock_boundaries(self):
        for n in (0, 1, 127, 128, 129, 255, 256, 257, 1024):
            tok = bytes(range(256)) * 5
            tok = tok[:n]
            want = int.from_bytes(
                hashlib.blake2b(
                    tok, digest_size=8, salt=(0).to_bytes(8, "little")
                ).digest(),
                "little",
            )
            assert hash_token_native(tok, 0) == want


@needs_native
class TestTokenize:
    def test_matches_python(self):
        rng = random.Random(12)
        words = ["a", "bb", "sensor", "+", "#", "$SYS", "x" * 40, "", "日本語"]
        topics = ["", "/", "//", "a//b/", "$SYS/broker/load"]
        for _ in range(300):
            topics.append(
                "/".join(rng.choice(words) for _ in range(rng.randrange(1, 12)))
            )
        for salt in (0, 7, 123456789):
            py = tokenize_topics_py(topics, 8, salt)
            nat = tokenize_topics_native(topics, 8, salt)
            for a, b in zip(py, nat):
                assert np.array_equal(a, b)

    def test_empty_batch(self):
        nat = tokenize_topics_native([], 4, 0)
        assert nat[0].shape == (0, 4)


class TestVarint:
    @pytest.mark.parametrize(
        "value,encoded",
        [
            (0, b"\x00"),
            (127, b"\x7f"),
            (128, b"\x80\x01"),
            (16383, b"\xff\x7f"),
            (16384, b"\x80\x80\x01"),
            (2097151, b"\xff\xff\x7f"),
            (2097152, b"\x80\x80\x80\x01"),
            (268435455, b"\xff\xff\xff\x7f"),
        ],
    )
    def test_roundtrip(self, value, encoded):
        assert varint_encode(value) == encoded
        assert _varint_encode_py(value) == encoded
        assert varint_decode(encoded) == (value, len(encoded))
        assert _varint_decode_py(encoded) == (value, len(encoded))

    def test_incomplete(self):
        assert varint_decode(b"\x80")[1] == 0
        assert _varint_decode_py(b"\x80")[1] == 0

    def test_overflow(self):
        with pytest.raises(ValueError):
            varint_decode(b"\xff\xff\xff\xff")
        with pytest.raises(ValueError):
            _varint_decode_py(b"\xff\xff\xff\xff")
        with pytest.raises(ValueError):
            varint_encode(268435456)

    def test_differential_random(self):
        rng = random.Random(13)
        for _ in range(300):
            v = rng.randrange(268435456)
            e = varint_encode(v)
            assert e == _varint_encode_py(v)
            assert varint_decode(e) == _varint_decode_py(e) == (v, len(e))


class TestFrameScan:
    def _scan_both(self, buf, **kw):
        got = frame_scan(buf, **kw)
        py = _frame_scan_py(buf, kw.get("max_frames", 1024), kw.get("max_packet_size", 0))
        assert [
            (f.first_byte, f.body_offset, f.remaining) for f in got[0]
        ] == [(f.first_byte, f.body_offset, f.remaining) for f in py[0]]
        assert got[1:] == py[1:]
        return got

    def test_golden_catalogue_stream(self):
        """Concatenate all well-formed golden packets and re-find each one."""
        good = [c for c in CASES if c.raw and c.decode_err is None and c.fail_first is None]
        buf = b"".join(c.raw for c in good)
        frames, consumed, err = self._scan_both(buf)
        assert err == 0
        assert consumed == len(buf)
        assert len(frames) == len(good)
        pos = 0
        for f, c in zip(frames, good):
            assert f.first_byte == c.raw[0]  # first byte of this packet
            # body = raw minus fixed header (first byte + varint length)
            header_len = len(c.raw) - f.remaining
            assert f.body_offset == pos + header_len
            assert buf[f.body_offset : f.body_offset + f.remaining] == c.raw[header_len:]
            pos += len(c.raw)

    def test_partial_tail(self):
        pk = bytes.fromhex("30080003612f62706179")  # publish a/b "pay" (8 body bytes)
        frames, consumed, err = self._scan_both(pk + pk[:4])
        assert err == 0 and len(frames) == 1 and consumed == len(pk)

    def test_reserved_type_scans_as_frame(self):
        # type 0 with zero flags passes header validation; the decoder
        # dispatch is what rejects it (matching FixedHeader.decode)
        frames, consumed, err = self._scan_both(b"\x00\x00")
        assert err == 0 and len(frames) == 1

    def test_malformed_header_flags(self):
        # PINGREQ with nonzero flags violates [MQTT-3.12.1-1]
        frames, consumed, err = self._scan_both(b"\xc1\x00")
        assert err == -1 and consumed == 0

    def test_malformed_second_packet(self):
        pk = bytes.fromhex("c000")  # PINGREQ
        bad = b"\x63\x00"  # PUBLISH with QoS 3
        frames, consumed, err = self._scan_both(pk + bad)
        # the complete PINGREQ before the error is still returned
        assert err == -1 and consumed == len(pk) and len(frames) == 1

    def test_max_packet_size(self):
        pk = bytes.fromhex("30080003612f62706179")
        frames, consumed, err = self._scan_both(pk, max_packet_size=5)
        assert err == -2 and consumed == 0
        frames, consumed, err = self._scan_both(pk, max_packet_size=100)
        assert err == 0 and len(frames) == 1

    def test_max_frames(self):
        ping = bytes.fromhex("c000")
        frames, consumed, err = self._scan_both(ping * 10, max_frames=3)
        assert err == 0 and len(frames) == 3 and consumed == 6

    def test_incomplete_varint(self):
        frames, consumed, err = self._scan_both(b"\x30\xff")
        assert err == 0 and frames == [] and consumed == 0

    def test_bytearray_input_zero_copy_path(self):
        # the client read loop passes its mutable bytearray buffer
        pk = bytearray(bytes.fromhex("c000") * 3)
        frames, consumed, err = frame_scan(pk)
        assert err == 0 and len(frames) == 3 and consumed == 6
        del pk[:consumed]  # must not raise BufferError (no live exports)
        assert len(pk) == 0

    def test_empty_buffer(self):
        frames, consumed, err = self._scan_both(b"")
        assert err == 0 and frames == [] and consumed == 0

    def test_dup_without_qos_rejected(self):
        # PUBLISH DUP=1 QoS=0 violates [MQTT-3.3.1-2]
        frames, consumed, err = self._scan_both(b"\x38\x00")
        assert err == -1


class TestUtf8:
    @pytest.mark.parametrize(
        "data,ok",
        [
            (b"plain", True),
            ("日本語".encode(), True),
            (b"with\x00nul", False),  # [MQTT-1.5.4-2]
            (b"\xc0\xaf", False),  # overlong '/'
            (b"\xed\xa0\x80", False),  # surrogate
            (b"\xf4\x90\x80\x80", False),  # > U+10FFFF
            (b"\xff", False),
            (b"\xe2\x82", False),  # truncated
            ("\U0010ffff".encode(), True),
            (b"", True),
        ],
    )
    def test_cases(self, data, ok):
        assert utf8_valid(data) is ok
        # python fallback path agreement
        py_ok = b"\x00" not in data
        if py_ok:
            try:
                data.decode("utf-8", "strict")
            except UnicodeDecodeError:
                py_ok = False
        assert py_ok is ok


@needs_native
def test_matcher_pipeline_uses_native(monkeypatch):
    """tokenize_topics (the matcher input path) must agree with the
    pure-Python reference even when served by the native core."""
    from mqtt_tpu.ops.hashing import tokenize_topics

    topics = ["a/b/c", "$share/g/t", "", "x/+/#"]
    nat = tokenize_topics(topics, 4, 3)
    py = tokenize_topics_py(topics, 4, 3)
    for a, b in zip(nat, py):
        assert np.array_equal(a, b)
