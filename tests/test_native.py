"""Differential tests: native C core vs the pure-Python reference paths.

The native library is optional; when it can't be built these tests skip
(except the pure-Python fallback cases, which always run).
"""

import hashlib
import random

import numpy as np
import pytest

from mqtt_tpu import native
from mqtt_tpu.native import (
    Frame,
    _frame_scan_py,
    _varint_decode_py,
    _varint_encode_py,
    frame_scan,
    hash_token_native,
    tokenize_topics_native,
    utf8_valid,
    varint_decode,
    varint_encode,
)
from mqtt_tpu.ops.hashing import tokenize_topics_py
from tests.tpackets import CASES

needs_native = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)


@needs_native
class TestBlake2b:
    def test_matches_hashlib(self):
        rng = random.Random(11)
        for _ in range(500):
            n = rng.randrange(0, 300)
            tok = bytes(rng.randrange(256) for _ in range(n))
            salt = rng.randrange(1 << 63)
            want = int.from_bytes(
                hashlib.blake2b(
                    tok, digest_size=8, salt=salt.to_bytes(8, "little")
                ).digest(),
                "little",
            )
            assert hash_token_native(tok, salt) == want

    def test_multiblock_boundaries(self):
        for n in (0, 1, 127, 128, 129, 255, 256, 257, 1024):
            tok = bytes(range(256)) * 5
            tok = tok[:n]
            want = int.from_bytes(
                hashlib.blake2b(
                    tok, digest_size=8, salt=(0).to_bytes(8, "little")
                ).digest(),
                "little",
            )
            assert hash_token_native(tok, 0) == want


@needs_native
class TestTokenize:
    def test_matches_python(self):
        rng = random.Random(12)
        words = ["a", "bb", "sensor", "+", "#", "$SYS", "x" * 40, "", "日本語"]
        topics = ["", "/", "//", "a//b/", "$SYS/broker/load"]
        for _ in range(300):
            topics.append(
                "/".join(rng.choice(words) for _ in range(rng.randrange(1, 12)))
            )
        for salt in (0, 7, 123456789):
            py = tokenize_topics_py(topics, 8, salt)
            nat = tokenize_topics_native(topics, 8, salt)
            for a, b in zip(py, nat):
                assert np.array_equal(a, b)

    def test_empty_batch(self):
        nat = tokenize_topics_native([], 4, 0)
        assert nat[0].shape == (0, 4)


class TestVarint:
    @pytest.mark.parametrize(
        "value,encoded",
        [
            (0, b"\x00"),
            (127, b"\x7f"),
            (128, b"\x80\x01"),
            (16383, b"\xff\x7f"),
            (16384, b"\x80\x80\x01"),
            (2097151, b"\xff\xff\x7f"),
            (2097152, b"\x80\x80\x80\x01"),
            (268435455, b"\xff\xff\xff\x7f"),
        ],
    )
    def test_roundtrip(self, value, encoded):
        assert varint_encode(value) == encoded
        assert _varint_encode_py(value) == encoded
        assert varint_decode(encoded) == (value, len(encoded))
        assert _varint_decode_py(encoded) == (value, len(encoded))

    def test_incomplete(self):
        assert varint_decode(b"\x80")[1] == 0
        assert _varint_decode_py(b"\x80")[1] == 0

    def test_overflow(self):
        with pytest.raises(ValueError):
            varint_decode(b"\xff\xff\xff\xff")
        with pytest.raises(ValueError):
            _varint_decode_py(b"\xff\xff\xff\xff")
        with pytest.raises(ValueError):
            varint_encode(268435456)

    def test_differential_random(self):
        rng = random.Random(13)
        for _ in range(300):
            v = rng.randrange(268435456)
            e = varint_encode(v)
            assert e == _varint_encode_py(v)
            assert varint_decode(e) == _varint_decode_py(e) == (v, len(e))


class TestFrameScan:
    def _scan_both(self, buf, **kw):
        got = frame_scan(buf, **kw)
        py = _frame_scan_py(buf, kw.get("max_frames", 1024), kw.get("max_packet_size", 0))
        assert [
            (f.first_byte, f.body_offset, f.remaining) for f in got[0]
        ] == [(f.first_byte, f.body_offset, f.remaining) for f in py[0]]
        assert got[1:] == py[1:]
        return got

    def test_golden_catalogue_stream(self):
        """Concatenate all well-formed golden packets and re-find each one."""
        good = [c for c in CASES if c.raw and c.decode_err is None and c.fail_first is None]
        buf = b"".join(c.raw for c in good)
        frames, consumed, err = self._scan_both(buf)
        assert err == 0
        assert consumed == len(buf)
        assert len(frames) == len(good)
        pos = 0
        for f, c in zip(frames, good):
            assert f.first_byte == c.raw[0]  # first byte of this packet
            # body = raw minus fixed header (first byte + varint length)
            header_len = len(c.raw) - f.remaining
            assert f.body_offset == pos + header_len
            assert buf[f.body_offset : f.body_offset + f.remaining] == c.raw[header_len:]
            pos += len(c.raw)

    def test_partial_tail(self):
        pk = bytes.fromhex("30080003612f62706179")  # publish a/b "pay" (8 body bytes)
        frames, consumed, err = self._scan_both(pk + pk[:4])
        assert err == 0 and len(frames) == 1 and consumed == len(pk)

    def test_reserved_type_scans_as_frame(self):
        # type 0 with zero flags passes header validation; the decoder
        # dispatch is what rejects it (matching FixedHeader.decode)
        frames, consumed, err = self._scan_both(b"\x00\x00")
        assert err == 0 and len(frames) == 1

    def test_malformed_header_flags(self):
        # PINGREQ with nonzero flags violates [MQTT-3.12.1-1]
        frames, consumed, err = self._scan_both(b"\xc1\x00")
        assert err == -1 and consumed == 0

    def test_malformed_second_packet(self):
        pk = bytes.fromhex("c000")  # PINGREQ
        bad = b"\x63\x00"  # PUBLISH with QoS 3
        frames, consumed, err = self._scan_both(pk + bad)
        # the complete PINGREQ before the error is still returned
        assert err == -1 and consumed == len(pk) and len(frames) == 1

    def test_max_packet_size(self):
        pk = bytes.fromhex("30080003612f62706179")
        frames, consumed, err = self._scan_both(pk, max_packet_size=5)
        assert err == -2 and consumed == 0
        frames, consumed, err = self._scan_both(pk, max_packet_size=100)
        assert err == 0 and len(frames) == 1

    def test_max_frames(self):
        ping = bytes.fromhex("c000")
        frames, consumed, err = self._scan_both(ping * 10, max_frames=3)
        assert err == 0 and len(frames) == 3 and consumed == 6

    def test_incomplete_varint(self):
        frames, consumed, err = self._scan_both(b"\x30\xff")
        assert err == 0 and frames == [] and consumed == 0

    def test_bytearray_input_zero_copy_path(self):
        # the client read loop passes its mutable bytearray buffer
        pk = bytearray(bytes.fromhex("c000") * 3)
        frames, consumed, err = frame_scan(pk)
        assert err == 0 and len(frames) == 3 and consumed == 6
        del pk[:consumed]  # must not raise BufferError (no live exports)
        assert len(pk) == 0

    def test_empty_buffer(self):
        frames, consumed, err = self._scan_both(b"")
        assert err == 0 and frames == [] and consumed == 0

    def test_dup_without_qos_rejected(self):
        # PUBLISH DUP=1 QoS=0 violates [MQTT-3.3.1-2]
        frames, consumed, err = self._scan_both(b"\x38\x00")
        assert err == -1


class TestUtf8:
    @pytest.mark.parametrize(
        "data,ok",
        [
            (b"plain", True),
            ("日本語".encode(), True),
            (b"with\x00nul", False),  # [MQTT-1.5.4-2]
            (b"\xc0\xaf", False),  # overlong '/'
            (b"\xed\xa0\x80", False),  # surrogate
            (b"\xf4\x90\x80\x80", False),  # > U+10FFFF
            (b"\xff", False),
            (b"\xe2\x82", False),  # truncated
            ("\U0010ffff".encode(), True),
            (b"", True),
        ],
    )
    def test_cases(self, data, ok):
        assert utf8_valid(data) is ok
        # python fallback path agreement
        py_ok = b"\x00" not in data
        if py_ok:
            try:
                data.decode("utf-8", "strict")
            except UnicodeDecodeError:
                py_ok = False
        assert py_ok is ok


@needs_native
def test_matcher_pipeline_uses_native(monkeypatch):
    """tokenize_topics (the matcher input path) must agree with the
    pure-Python reference even when served by the native core."""
    from mqtt_tpu.ops.hashing import tokenize_topics

    topics = ["a/b/c", "$share/g/t", "", "x/+/#"]
    nat = tokenize_topics(topics, 4, 3)
    py = tokenize_topics_py(topics, 4, 3)
    for a, b in zip(nat, py):
        assert np.array_equal(a, b)


# -- C materializer (accelmod.c) differential tests -------------------------

needs_accel = pytest.mark.skipif(
    native.accel() is None, reason="accel extension unavailable"
)


def _random_snaps(rng, n_entries, window):
    """Snapshot tuples shaped like ops/flat builds them: clients first,
    then shared members, then inline subscriptions, all within window."""
    from mqtt_tpu.packets import Subscription
    from mqtt_tpu.topics import InlineSubscription

    snaps = []
    for e in range(n_entries):
        n_cli = rng.randint(0, 4)
        n_shr = rng.randint(0, 2)
        n_inl = rng.randint(0, 2)
        cli = tuple(
            (
                f"cl{e}_{i}" if rng.random() < 0.8 else "dup",  # force merges
                Subscription(
                    filter=f"f/{e}/{i}",
                    qos=rng.randint(0, 2),
                    identifier=rng.choice([0, 0, 5, 9]),
                    identifiers={f"prev/{e}": 3} if rng.random() < 0.3 else None,
                    no_local=rng.random() < 0.2,
                ),
            )
            for i in range(n_cli)
        )
        shr = tuple(
            (
                f"m{e}_{i}",
                Subscription(filter=f"$SHARE/g{i % 2}/f/{e}", qos=1),
            )
            for i in range(n_shr)
        )
        inl = tuple(
            InlineSubscription(
                filter=f"f/{e}", identifier=e * 10 + i + 1, handler=lambda *a: None
            )
            for i in range(n_inl)
        )
        snaps.append((cli, shr, inl))
    return snaps


def _canon(s):
    return (
        {
            c: (
                sub.qos,
                sub.no_local,
                sub.filter,
                tuple(sorted((sub.identifiers or {}).items())),
            )
            for c, sub in s.subscriptions.items()
        },
        {f: set(m) for f, m in s.shared.items()},
        set(s.inline_subscriptions),
    )


@needs_accel
class TestResolveBatch:
    def _packed(self, rng, n_topics, P, snaps, window):
        """Random VALID range rows: counts never exceed the entry's actual
        snapshot population (the device meta word guarantees this in
        production — counts are derived from the snapshot lengths)."""
        totals = [sum(len(part) for part in s) for s in snaps]
        packed = np.zeros((n_topics, 2 * P + 2), dtype=np.int32)
        for i in range(n_topics):
            if rng.random() < 0.1:
                packed[i, 2 * P + 1] = 1  # overflow row
                continue
            for p in range(P):
                if rng.random() < 0.5:
                    e = rng.randrange(len(snaps))
                    if not totals[e]:
                        continue
                    lo = rng.randrange(totals[e])  # the $-mask's lo offset
                    packed[i, p] = e * window + lo
                    packed[i, P + p] = rng.randint(0, totals[e] - lo)
        return packed

    def _python_reference(self, packed, P, snaps, window):
        """expand_sids over a _LazySubTable built from the same snaps."""
        from mqtt_tpu.ops.flat import _LazySubTable
        from mqtt_tpu.ops.matcher import expand_sids
        from mqtt_tpu.topics import Subscribers

        table = _LazySubTable(window, list(snaps), len(snaps) * window)
        results = []
        for row in packed.tolist():
            if row[2 * P + 1]:
                results.append(None)
                continue
            sids = []
            for p in range(P):
                c = row[P + p]
                if c:
                    sids.extend(range(row[p], row[p] + c))
            results.append(expand_sids(table, sids, Subscribers()))
        return results

    def test_differential_random(self):
        from mqtt_tpu.topics import Subscribers

        acc = native.accel()
        rng = random.Random(11)
        window, P, n_entries = 8, 3, 64
        snaps = _random_snaps(rng, n_entries, window)
        packed = self._packed(rng, 512, P, snaps, window)
        res_c, ovf = acc.resolve_batch(packed, 512, P, snaps, window, Subscribers)
        res_py = self._python_reference(packed, P, snaps, window)
        assert len(res_c) == len(res_py) == 512
        assert [i for i, r in enumerate(res_py) if r is None] == list(ovf)
        for a, b in zip(res_c, res_py):
            assert (a is None) == (b is None)
            if a is not None:
                assert _canon(a) == _canon(b)

    def test_identifiers_shared_and_extended(self):
        """A stored identifiers map is mutated by the copy when
        identifier > 0 — Subscription.merge semantics, which the Python
        and C paths must share exactly."""
        from mqtt_tpu.packets import Subscription
        from mqtt_tpu.topics import Subscribers

        acc = native.accel()
        stored = Subscription(filter="a/b", qos=1, identifier=7, identifiers={"x": 1})
        snaps = [(( ("c1", stored), ), (), ())]
        packed = np.zeros((1, 2 * 1 + 2), dtype=np.int32)
        packed[0, 0] = 0
        packed[0, 1] = 1
        res, ovf = acc.resolve_batch(packed, 1, 1, snaps, 4, Subscribers)
        got = res[0].subscriptions["c1"]
        assert got is not stored  # fresh copy
        assert got.identifiers is stored.identifiers  # the SHARED map
        assert stored.identifiers == {"x": 1, "a/b": 7}  # extended in place

    def test_out_of_range_sids_skipped(self):
        from mqtt_tpu.topics import Subscribers

        acc = native.accel()
        snaps = _random_snaps(random.Random(1), 2, 4)
        packed = np.zeros((1, 4), dtype=np.int32)
        packed[0, 0] = 4 * 100  # ordinal way past the table
        packed[0, 1] = 3
        res, ovf = acc.resolve_batch(packed, 1, 1, snaps, 4, Subscribers)
        assert not ovf
        assert not res[0].subscriptions

    def test_dict_class_fallback(self):
        """Subclasses without a usable slots layout route through the
        Python self_merged_copy / merge methods — same values."""
        from mqtt_tpu.packets import Subscription
        from mqtt_tpu.topics import Subscribers

        class DictSub(Subscription):
            pass  # plain subclass: instances carry a __dict__

        acc = native.accel()
        stored = DictSub(filter="q/w", qos=2, identifier=3)
        snaps = [((("c1", stored),), (), ())]
        packed = np.zeros((1, 4), dtype=np.int32)
        packed[0, 1] = 1
        res, _ = acc.resolve_batch(packed, 1, 1, snaps, 8, Subscribers)
        got = res[0].subscriptions["c1"]
        assert type(got) is DictSub
        assert (got.qos, got.identifiers) == (2, {"q/w": 3})

    def test_expand_sids_list_matches_expand_sids(self):
        from mqtt_tpu.ops.flat import _LazySubTable
        from mqtt_tpu.ops.matcher import expand_sids
        from mqtt_tpu.topics import Subscribers

        acc = native.accel()
        rng = random.Random(3)
        window = 8
        snaps = _random_snaps(rng, 32, window)
        table = _LazySubTable(window, list(snaps), len(snaps) * window)
        # only slots the snapshots actually populate (production sids are
        # bounded by the per-entry counts)
        valid = [
            e * window + k
            for e, s in enumerate(snaps)
            for k in range(sum(len(part) for part in s))
        ]
        sids = sorted(rng.sample(valid, min(64, len(valid))))
        a = acc.expand_sids_list(sids, snaps, window, Subscribers())
        b = expand_sids(table, list(sids), Subscribers())
        assert _canon(a) == _canon(b)


@needs_accel
def test_expand_snap_matches_python():
    from mqtt_tpu.ops.matcher import TpuMatcher
    from mqtt_tpu.topics import Subscribers

    acc = native.accel()
    rng = random.Random(5)
    for snap in _random_snaps(rng, 24, 8):
        cli, shr, inl = snap
        # a real trie node keys clients uniquely (the subscriptions map);
        # drop the generator's forced-dup entries for this single-node case
        seen, uniq = set(), []
        for client, sub in cli:
            if client not in seen:
                seen.add(client)
                uniq.append((client, sub))
        snap = (tuple(uniq), shr, inl)
        a = acc.expand_snap(snap, Subscribers)
        b = TpuMatcher._expand_snap(snap)
        assert _canon(a) == _canon(b)
    # empty snapshot
    empty = acc.expand_snap(((), (), ()), Subscribers)
    assert not empty.subscriptions and not empty.shared
