"""End-to-end broker tests over in-memory socket pairs — the analog of the
reference's net.Pipe() scenarios (server_test.go): raw wire bytes in, exact
response packets out, for v3.1.1 and v5, plus hook-fake behavioral checks.
"""

import asyncio
import socket

import pytest

from mqtt_tpu import Capabilities, Options, Server
from mqtt_tpu.hooks import (
    ON_ACL_CHECK,
    ON_CONNECT,
    ON_CONNECT_AUTHENTICATE,
    ON_PACKET_READ,
    ON_PUBLISH,
    ON_QOS_DROPPED,
    Hook,
    Hooks,
)
from mqtt_tpu.hooks.auth import AllowHook
from mqtt_tpu.packets import (
    AUTH,
    CONNACK,
    CONNECT,
    DISCONNECT,
    PINGREQ,
    PINGRESP,
    PUBACK,
    PUBCOMP,
    PUBLISH,
    PUBREC,
    PUBREL,
    SUBACK,
    SUBSCRIBE,
    UNSUBACK,
    UNSUBSCRIBE,
    Code,
    ConnectParams,
    FixedHeader,
    Packet,
    Subscription,
    codes,
    decode_length,
    decode_packet,
    encode_packet,
)

TIMEOUT = 3.0


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=15))


def connect_packet(client_id="test", version=4, clean=True, keepalive=30, will=None):
    cp = ConnectParams(
        protocol_name=b"MQTT",
        clean=clean,
        keepalive=keepalive,
        client_identifier=client_id,
    )
    if will:
        cp.will_flag = True
        cp.will_topic = will[0]
        cp.will_payload = will[1]
        cp.will_qos = will[2] if len(will) > 2 else 0
        cp.will_retain = will[3] if len(will) > 3 else False
    return encode_packet(
        Packet(fixed_header=FixedHeader(type=CONNECT), protocol_version=version, connect=cp)
    )


async def read_wire_packet(reader, version=4):
    """Read one framed packet off the stream and decode it."""
    first = await asyncio.wait_for(reader.readexactly(1), TIMEOUT)
    buf = bytearray(first)
    while True:
        b = await asyncio.wait_for(reader.readexactly(1), TIMEOUT)
        buf += b
        if not (b[0] & 0x80):
            break
    remaining, _ = decode_length(bytes(buf), 1)
    if remaining:
        buf += await asyncio.wait_for(reader.readexactly(remaining), TIMEOUT)
    return decode_packet(bytes(buf), version)


class Harness:
    """One broker plus helpers to attach raw in-memory client connections."""

    def __init__(self, options=None, allow=True):
        self.server = Server(options or Options(inline_client=True))
        if allow:
            self.server.add_hook(AllowHook())
        self.tasks = []

    async def attach(self):
        """Create a socketpair; server side becomes an attached client."""
        s1, s2 = socket.socketpair()
        s1.setblocking(False)
        s2.setblocking(False)
        client_reader, client_writer = await asyncio.open_connection(sock=s1)
        server_reader, server_writer = await asyncio.open_connection(sock=s2)
        cl = self.server.new_client(server_reader, server_writer, "t1", "", False)
        task = asyncio.get_running_loop().create_task(self.server.attach_client(cl, "t1"))
        self.tasks.append(task)
        return client_reader, client_writer, task

    async def connect(self, client_id="test", version=4, expect_code=0, **kw):
        reader, writer, task = await self.attach()
        writer.write(connect_packet(client_id, version, **kw))
        await writer.drain()
        ack = await read_wire_packet(reader, version)
        assert ack.fixed_header.type == CONNACK
        assert ack.reason_code == expect_code, f"connack code {ack.reason_code:#x}"
        return reader, writer, task

    async def shutdown(self):
        for t in self.tasks:
            if not t.done():
                t.cancel()
        await asyncio.gather(*self.tasks, return_exceptions=True)


class TestEstablishConnection:
    def test_connect_v4(self):
        async def scenario():
            h = Harness()
            reader, writer, task = await h.attach()
            writer.write(connect_packet("zen", 4))
            await writer.drain()
            raw = await asyncio.wait_for(reader.readexactly(4), TIMEOUT)
            assert raw == bytes.fromhex("20020000")  # exact CONNACK bytes
            writer.write(encode_packet(Packet(fixed_header=FixedHeader(type=DISCONNECT), protocol_version=4)))
            await writer.drain()
            await asyncio.wait_for(task, TIMEOUT)
            await h.shutdown()

        run(scenario())

    def test_connect_v5_properties(self):
        async def scenario():
            h = Harness()
            reader, writer, task = await h.connect("zen5", version=5)
            assert h.server.clients.get("zen5") is not None
            await h.shutdown()

        run(scenario())

    def test_first_packet_must_be_connect(self):
        async def scenario():
            h = Harness()
            reader, writer, task = await h.attach()
            writer.write(encode_packet(Packet(fixed_header=FixedHeader(type=PINGREQ))))
            await writer.drain()
            await asyncio.wait_for(task, TIMEOUT)  # connection dropped
            data = await asyncio.wait_for(reader.read(16), TIMEOUT)
            assert data == b""  # no CONNACK, just close
            await h.shutdown()

        run(scenario())

    def test_auth_default_deny(self):
        async def scenario():
            h = Harness(allow=False)  # no hooks: OR-default deny-all
            reader, writer, task = await h.attach()
            writer.write(connect_packet("nope", 4))
            await writer.drain()
            ack = await read_wire_packet(reader, 4)
            assert ack.fixed_header.type == CONNACK
            # v5 0x86 translates to v3 0x05 not-authorized (codes.go:141-148)
            assert ack.reason_code == 0x05
            await h.shutdown()

        run(scenario())

    def test_maximum_clients(self):
        async def scenario():
            opts = Options(capabilities=Capabilities(maximum_clients=0))
            h = Harness(opts)
            reader, writer, task = await h.attach()
            writer.write(connect_packet("late", 4))
            await writer.drain()
            ack = await read_wire_packet(reader, 4)
            assert ack.reason_code == 0x03  # v3 server unavailable
            await h.shutdown()

        run(scenario())

    def test_pingreq_pingresp(self):
        async def scenario():
            h = Harness()
            reader, writer, task = await h.connect("pinger")
            writer.write(encode_packet(Packet(fixed_header=FixedHeader(type=PINGREQ))))
            await writer.drain()
            resp = await read_wire_packet(reader)
            assert resp.fixed_header.type == PINGRESP
            await h.shutdown()

        run(scenario())


class TestPubSub:
    def test_subscribe_publish_roundtrip(self):
        async def scenario():
            h = Harness()
            sub_r, sub_w, _ = await h.connect("subber")
            sub_w.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=SUBSCRIBE, qos=1),
                        protocol_version=4,
                        packet_id=11,
                        filters=[Subscription(filter="a/b/+", qos=0)],
                    )
                )
            )
            await sub_w.drain()
            suback = await read_wire_packet(sub_r)
            assert suback.fixed_header.type == SUBACK
            assert suback.reason_codes == b"\x00"

            pub_r, pub_w, _ = await h.connect("pubber")
            pub_w.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=PUBLISH),
                        protocol_version=4,
                        topic_name="a/b/c",
                        payload=b"hello",
                    )
                )
            )
            await pub_w.drain()
            msg = await read_wire_packet(sub_r)
            assert msg.fixed_header.type == PUBLISH
            assert msg.topic_name == "a/b/c"
            assert msg.payload == b"hello"
            await h.shutdown()

        run(scenario())

    def test_qos1_flow(self):
        async def scenario():
            h = Harness()
            r, w, _ = await h.connect("q1")
            w.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=PUBLISH, qos=1),
                        protocol_version=4,
                        topic_name="q/1",
                        packet_id=7,
                        payload=b"x",
                    )
                )
            )
            await w.drain()
            ack = await read_wire_packet(r)
            assert ack.fixed_header.type == PUBACK
            assert ack.packet_id == 7
            await h.shutdown()

        run(scenario())

    def test_qos2_flow(self):
        async def scenario():
            h = Harness()
            r, w, _ = await h.connect("q2")
            w.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=PUBLISH, qos=2),
                        protocol_version=4,
                        topic_name="q/2",
                        packet_id=9,
                        payload=b"x",
                    )
                )
            )
            await w.drain()
            rec = await read_wire_packet(r)
            assert rec.fixed_header.type == PUBREC and rec.packet_id == 9
            w.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=PUBREL, qos=1),
                        protocol_version=4,
                        packet_id=9,
                    )
                )
            )
            await w.drain()
            comp = await read_wire_packet(r)
            assert comp.fixed_header.type == PUBCOMP and comp.packet_id == 9
            await h.shutdown()

        run(scenario())

    def test_qos_downgrade_to_subscription(self):
        async def scenario():
            h = Harness()
            sub_r, sub_w, _ = await h.connect("downsub")
            sub_w.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=SUBSCRIBE, qos=1),
                        protocol_version=4,
                        packet_id=1,
                        filters=[Subscription(filter="dn/t", qos=0)],
                    )
                )
            )
            await sub_w.drain()
            await read_wire_packet(sub_r)  # suback

            pr, pw, _ = await h.connect("downpub")
            pw.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=PUBLISH, qos=1),
                        protocol_version=4,
                        topic_name="dn/t",
                        packet_id=3,
                        payload=b"m",
                    )
                )
            )
            await pw.drain()
            await read_wire_packet(pr)  # puback to publisher
            msg = await read_wire_packet(sub_r)
            assert msg.fixed_header.qos == 0  # min(sub 0, msg 1)
            await h.shutdown()

        run(scenario())

    def test_retained_delivered_on_subscribe(self):
        async def scenario():
            h = Harness()
            pr, pw, _ = await h.connect("retainer")
            pw.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=PUBLISH, retain=True),
                        protocol_version=4,
                        topic_name="ret/t",
                        payload=b"keepme",
                    )
                )
            )
            await pw.drain()
            await asyncio.sleep(0.05)
            assert len(h.server.topics.retained) == 1

            sr, sw, _ = await h.connect("late-sub")
            sw.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=SUBSCRIBE, qos=1),
                        protocol_version=4,
                        packet_id=2,
                        filters=[Subscription(filter="ret/#", qos=0)],
                    )
                )
            )
            await sw.drain()
            suback = await read_wire_packet(sr)
            assert suback.fixed_header.type == SUBACK
            msg = await read_wire_packet(sr)
            assert msg.topic_name == "ret/t"
            assert msg.payload == b"keepme"
            assert msg.fixed_header.retain  # fwd_retained keeps the flag
            await h.shutdown()

        run(scenario())

    def test_unsubscribe(self):
        async def scenario():
            h = Harness()
            r, w, _ = await h.connect("unsub")
            w.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=SUBSCRIBE, qos=1),
                        protocol_version=4,
                        packet_id=4,
                        filters=[Subscription(filter="u/t", qos=0)],
                    )
                )
            )
            await w.drain()
            await read_wire_packet(r)
            w.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=UNSUBSCRIBE, qos=1),
                        protocol_version=4,
                        packet_id=5,
                        filters=[Subscription(filter="u/t")],
                    )
                )
            )
            await w.drain()
            unsuback = await read_wire_packet(r)
            assert unsuback.fixed_header.type == UNSUBACK
            assert len(h.server.topics.subscribers("u/t").subscriptions) == 0
            await h.shutdown()

        run(scenario())


class TestSessionsAndWills:
    def test_session_takeover(self):
        async def scenario():
            h = Harness()
            r1, w1, t1 = await h.connect("dup", version=5, clean=False)
            r2, w2, t2 = await h.connect("dup", version=5, clean=False)
            # first client receives DISCONNECT(session taken over)
            pk = await read_wire_packet(r1, 5)
            assert pk.fixed_header.type == DISCONNECT
            assert pk.reason_code == 0x8E
            assert h.server.clients.get("dup") is not None
            await h.shutdown()

        run(scenario())

    def test_lwt_published_on_abnormal_disconnect(self):
        async def scenario():
            h = Harness()
            sr, sw, _ = await h.connect("watcher")
            sw.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=SUBSCRIBE, qos=1),
                        protocol_version=4,
                        packet_id=6,
                        filters=[Subscription(filter="lwt/t", qos=0)],
                    )
                )
            )
            await sw.drain()
            await read_wire_packet(sr)

            dr, dw, dt = await h.connect("dier", will=("lwt/t", b"gone", 0))
            dw.transport.abort()  # abrupt connection loss
            msg = await read_wire_packet(sr)
            assert msg.topic_name == "lwt/t"
            assert msg.payload == b"gone"
            await h.shutdown()

        run(scenario())

    def test_clean_disconnect_no_lwt(self):
        async def scenario():
            h = Harness()
            sr, sw, _ = await h.connect("watcher2")
            sw.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=SUBSCRIBE, qos=1),
                        protocol_version=4,
                        packet_id=6,
                        filters=[Subscription(filter="lwt2/t", qos=0)],
                    )
                )
            )
            await sw.drain()
            await read_wire_packet(sr)

            dr, dw, dt = await h.connect("polite", will=("lwt2/t", b"gone", 0))
            dw.write(encode_packet(Packet(fixed_header=FixedHeader(type=DISCONNECT), protocol_version=4)))
            await dw.drain()
            await asyncio.wait_for(dt, TIMEOUT)
            # no will should arrive; publish a sentinel to prove ordering
            h.server.publish("lwt2/t", b"sentinel", False, 0)
            msg = await read_wire_packet(sr)
            assert msg.payload == b"sentinel"
            await h.shutdown()

        run(scenario())


class TestInlineClient:
    def test_inline_pub_sub(self):
        async def scenario():
            h = Harness()
            got = []
            h.server.subscribe("in/+", 1, lambda cl, sub, pk: got.append(pk.topic_name))
            h.server.publish("in/x", b"v", False, 0)
            assert got == ["in/x"]
            h.server.unsubscribe("in/+", 1)
            h.server.publish("in/y", b"v", False, 0)
            assert got == ["in/x"]
            await h.shutdown()

        run(scenario())

    def test_inline_requires_option(self):
        from mqtt_tpu import InlineClientNotEnabledError

        s = Server(Options(inline_client=False))
        with pytest.raises(InlineClientNotEnabledError):
            s.publish("a", b"b", False, 0)
        with pytest.raises(InlineClientNotEnabledError):
            s.subscribe("a", 1, lambda *a: None)


class TestSysTopics:
    def test_sys_topics_retained(self):
        async def scenario():
            h = Harness()
            h.server.publish_sys_topics()
            pks = h.server.topics.messages("$SYS/#")
            topics = {p.topic_name for p in pks}
            assert "$SYS/broker/version" in topics
            assert "$SYS/broker/clients/connected" in topics
            assert "$SYS/broker/overload/state" in topics
            assert "$SYS/broker/telemetry/flight/ring_depth" in topics
            assert "$SYS/broker/predicates/rules" in topics
            if h.server.device_stats is not None:
                assert "$SYS/broker/devices/skew_ratio" in topics
            base = {
                t
                for t in topics
                if not t.startswith("$SYS/broker/overload/")
                and not t.startswith("$SYS/broker/telemetry/")
                and not t.startswith("$SYS/broker/predicates/")
                # device observatory rows scale with the device count
                and not t.startswith("$SYS/broker/devices/")
            }
            assert len(base) == 20
            await h.shutdown()

        run(scenario())


class TestHooksDispatcher:
    def test_modifier_chain_order(self):
        hooks = Hooks()

        class Adder(Hook):
            def __init__(self, tag):
                super().__init__()
                self.tag = tag

            def id(self):
                return self.tag

            def provides(self, b):
                return b == ON_PACKET_READ

            def on_packet_read(self, cl, pk):
                pk.topic_name += self.tag
                return pk

        hooks.add(Adder("a"), None)
        hooks.add(Adder("b"), None)
        pk = hooks.on_packet_read(None, Packet(topic_name="x"))
        assert pk.topic_name == "xab"

    def test_reject_short_circuits(self):
        hooks = Hooks()

        class Rejecter(Hook):
            def id(self):
                return "rej"

            def provides(self, b):
                return b == ON_PUBLISH

            def on_publish(self, cl, pk):
                raise codes.ERR_REJECT_PACKET()

        hooks.add(Rejecter(), None)
        with pytest.raises(Code) as e:
            hooks.on_publish(None, Packet())
        assert e.value == codes.ERR_REJECT_PACKET

    def test_auth_or_semantics(self):
        hooks = Hooks()
        assert not hooks.on_connect_authenticate(None, Packet())  # default deny

        class Denier(Hook):
            def id(self):
                return "deny"

            def provides(self, b):
                return b in (ON_CONNECT_AUTHENTICATE, ON_ACL_CHECK)

        hooks.add(Denier(), None)
        assert not hooks.on_acl_check(None, "t", True)
        hooks.add(AllowHook(), None)
        assert hooks.on_connect_authenticate(None, Packet())
        assert hooks.on_acl_check(None, "t", True)


class TestInflight:
    def test_set_get_delete(self):
        from mqtt_tpu.inflight import Inflight

        i = Inflight()
        assert i.set(Packet(packet_id=1, created=10))
        assert not i.set(Packet(packet_id=1, created=11))
        assert i.get(1) is not None
        assert len(i) == 1
        assert i.delete(1)
        assert not i.delete(1)

    def test_quotas(self):
        from mqtt_tpu.inflight import Inflight

        i = Inflight()
        i.reset_receive_quota(2)
        i.decrease_receive_quota()
        i.decrease_receive_quota()
        i.decrease_receive_quota()  # floors at 0
        assert i.receive_quota == 0
        i.increase_receive_quota()
        assert i.receive_quota == 1
        for _ in range(5):
            i.increase_receive_quota()
        assert i.receive_quota == 2  # capped at maximum

    def test_get_all_sorted_and_immediate(self):
        from mqtt_tpu.inflight import Inflight

        i = Inflight()
        i.set(Packet(packet_id=1, created=30))
        i.set(Packet(packet_id=2, created=10))
        i.set(Packet(packet_id=3, created=20, expiry=-1))
        assert [p.packet_id for p in i.get_all(False)] == [2, 3, 1]
        nxt = i.next_immediate()
        assert nxt is not None and nxt.packet_id == 3

    def test_clone(self):
        from mqtt_tpu.inflight import Inflight

        i = Inflight()
        i.set(Packet(packet_id=5))
        c = i.clone()
        assert c.get(5) is not None
        c.delete(5)
        assert i.get(5) is not None


class TestRetainFlagRegression:
    def test_live_publish_after_retained_has_retain_cleared(self):
        """The trie-stored subscription must not keep fwd_retained_flag after
        retained delivery: a later LIVE publish with retain=1 must reach the
        subscriber with retain=0 [MQTT-3.3.1-12]."""

        async def scenario():
            h = Harness()
            pr, pw, _ = await h.connect("retainer2")
            pw.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=PUBLISH, retain=True),
                        protocol_version=4,
                        topic_name="rf/t",
                        payload=b"old",
                    )
                )
            )
            await pw.drain()
            await asyncio.sleep(0.05)

            sr, sw, _ = await h.connect("flag-sub")
            sw.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=SUBSCRIBE, qos=1),
                        protocol_version=4,
                        packet_id=3,
                        filters=[Subscription(filter="rf/t", qos=0)],
                    )
                )
            )
            await sw.drain()
            await read_wire_packet(sr)  # suback
            retained = await read_wire_packet(sr)
            assert retained.fixed_header.retain  # retained replay keeps flag

            pw.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=PUBLISH, retain=True),
                        protocol_version=4,
                        topic_name="rf/t",
                        payload=b"live",
                    )
                )
            )
            await pw.drain()
            live = await read_wire_packet(sr)
            assert live.payload == b"live"
            assert not live.fixed_header.retain  # [MQTT-3.3.1-12]
            await h.shutdown()

        run(scenario())


def sub_packet(pid, filters, version=4):
    return encode_packet(
        Packet(
            fixed_header=FixedHeader(type=SUBSCRIBE, qos=1),
            protocol_version=version,
            packet_id=pid,
            filters=filters,
        )
    )


def pub_packet(topic, payload, qos=0, pid=0, version=4, retain=False, props=None):
    pk = Packet(
        fixed_header=FixedHeader(type=PUBLISH, qos=qos, retain=retain),
        protocol_version=version,
        topic_name=topic,
        packet_id=pid,
        payload=payload,
    )
    if props is not None:
        pk.properties = props
    return encode_packet(pk)


class TestTopicAliases:
    def test_inbound_alias_resolves_empty_topic(self):
        """v5 publisher sets an alias then sends alias-only publishes; the
        subscriber sees the real topic both times (server.go:904-906)."""

        async def scenario():
            from mqtt_tpu.packets import Properties

            h = Harness()
            sr, sw, _ = await h.connect("alias-sub")
            sw.write(sub_packet(1, [Subscription(filter="al/t", qos=0)]))
            await sw.drain()
            await read_wire_packet(sr)

            pr, pw, _ = await h.connect("alias-pub", version=5)
            pw.write(
                pub_packet(
                    "al/t", b"one", version=5,
                    props=Properties(topic_alias=4, topic_alias_flag=True),
                )
            )
            pw.write(
                pub_packet(
                    "", b"two", version=5,
                    props=Properties(topic_alias=4, topic_alias_flag=True),
                )
            )
            await pw.drain()
            m1 = await read_wire_packet(sr)
            m2 = await read_wire_packet(sr)
            assert (m1.topic_name, m1.payload) == ("al/t", b"one")
            assert (m2.topic_name, m2.payload) == ("al/t", b"two")
            await h.shutdown()

        run(scenario())

    def test_outbound_alias_assigned_when_client_allows(self):
        """A v5 subscriber advertising topic_alias_maximum gets an alias on
        first delivery and an empty topic afterwards (server.go:1052-1061)."""

        async def scenario():
            from mqtt_tpu.packets import Properties

            h = Harness()
            reader, writer, task = await h.attach()
            cp = ConnectParams(
                protocol_name=b"MQTT", clean=True, keepalive=30,
                client_identifier="alias-out",
            )
            writer.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=CONNECT),
                        protocol_version=5,
                        properties=Properties(topic_alias_maximum=8),
                        connect=cp,
                    )
                )
            )
            await writer.drain()
            await read_wire_packet(reader, 5)
            writer.write(sub_packet(1, [Subscription(filter="ob/t", qos=0)], version=5))
            await writer.drain()
            await read_wire_packet(reader, 5)

            h.server.publish("ob/t", b"m1", False, 0)
            h.server.publish("ob/t", b"m2", False, 0)
            m1 = await read_wire_packet(reader, 5)
            m2 = await read_wire_packet(reader, 5)
            assert m1.topic_name == "ob/t" and m1.properties.topic_alias == 1
            assert m2.topic_name == "" and m2.properties.topic_alias == 1
            assert m2.payload == b"m2"
            await h.shutdown()

        run(scenario())


class TestQuotasAndLimits:
    def test_receive_maximum_disconnect(self):
        """Exceeding the in-flight receive quota with unacked QoS2 publishes
        disconnects with ErrReceiveMaximum (server.go:862-864)."""

        async def scenario():
            opts = Options(capabilities=Capabilities(receive_maximum=1))
            h = Harness(opts)
            reader, writer, task = await h.connect("greedy", version=5)
            writer.write(pub_packet("q/t", b"a", qos=2, pid=1, version=5))
            await writer.drain()
            rec = await read_wire_packet(reader, 5)
            assert rec.fixed_header.type == PUBREC
            # second QoS2 publish without completing the first
            writer.write(pub_packet("q/t", b"b", qos=2, pid=2, version=5))
            await writer.drain()
            disc = await read_wire_packet(reader, 5)
            assert disc.fixed_header.type == DISCONNECT
            assert disc.reason_code == codes.ERR_RECEIVE_MAXIMUM.code
            await h.shutdown()

        run(scenario())

    def test_maximum_packet_size_drops_oversized(self):
        """Messages larger than the client's maximum packet size are not
        delivered to it [MQTT-3.1.2-24] (clients.go:595-598)."""

        async def scenario():
            from mqtt_tpu.packets import Properties

            h = Harness()
            reader, writer, task = await h.attach()
            writer.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=CONNECT),
                        protocol_version=5,
                        properties=Properties(maximum_packet_size=25),
                        connect=ConnectParams(
                            protocol_name=b"MQTT", clean=True, keepalive=30,
                            client_identifier="small",
                        ),
                    )
                )
            )
            await writer.drain()
            await read_wire_packet(reader, 5)
            writer.write(sub_packet(1, [Subscription(filter="mx/t", qos=0)], version=5))
            await writer.drain()
            await read_wire_packet(reader, 5)

            h.server.publish("mx/t", b"x" * 100, False, 0)  # oversized: dropped
            h.server.publish("mx/t", b"ok", False, 0)
            m = await read_wire_packet(reader, 5)
            assert m.payload == b"ok"
            await h.shutdown()

        run(scenario())


class TestDelayedLWT:
    def test_will_delay_interval_defers_and_reconnect_cancels(self):
        """A v5 will with a delay interval is queued, published by the
        delayed-LWT tick, and cancelled by reconnection (server.go:1744-1758,
        [MQTT-3.1.3-9])."""

        async def scenario():
            import time as _time
            from mqtt_tpu.packets import Properties

            h = Harness()
            sr, sw, _ = await h.connect("lwt-watcher")
            sw.write(sub_packet(1, [Subscription(filter="dl/t", qos=0)]))
            await sw.drain()
            await read_wire_packet(sr)

            async def connect_with_delayed_will():
                reader, writer, task = await h.attach()
                cp = ConnectParams(
                    protocol_name=b"MQTT", clean=False, keepalive=30,
                    client_identifier="doomed", will_flag=True,
                    will_topic="dl/t", will_payload=b"gone",
                )
                cp.will_properties = Properties(will_delay_interval=30)
                writer.write(
                    encode_packet(
                        Packet(
                            fixed_header=FixedHeader(type=CONNECT),
                            protocol_version=5,
                            connect=cp,
                        )
                    )
                )
                await writer.drain()
                await read_wire_packet(reader, 5)
                return reader, writer, task

            reader, writer, task = await connect_with_delayed_will()
            writer.close()  # abnormal disconnect
            await asyncio.sleep(0.1)
            assert len(h.server.will_delayed) == 1

            # not yet due: nothing published
            h.server.send_delayed_lwt(int(_time.time()))
            with pytest.raises(asyncio.TimeoutError):
                await read_wire_packet(sr)

            # due: published to the watcher
            h.server.send_delayed_lwt(int(_time.time()) + 3600)
            m = await read_wire_packet(sr)
            assert (m.topic_name, m.payload) == ("dl/t", b"gone")
            assert len(h.server.will_delayed) == 0

            # reconnect cancels a re-queued delayed will [MQTT-3.1.3-9]
            reader, writer, task = await connect_with_delayed_will()
            writer.close()
            await asyncio.sleep(0.1)
            assert len(h.server.will_delayed) == 1
            reader, writer, task = await connect_with_delayed_will()
            assert len(h.server.will_delayed) == 0
            await h.shutdown()

        run(scenario())


class TestTakeover:
    def test_takeover_inherits_inflight_and_resends_dup(self):
        """Session takeover moves unacked QoS1 inflights to the new
        connection and resends them with DUP (server.go:561-603,
        clients.go:302-327)."""

        async def scenario():
            h = Harness()
            r1, w1, _ = await h.connect("dur", clean=False)
            w1.write(sub_packet(1, [Subscription(filter="tk/t", qos=1)]))
            await w1.drain()
            await read_wire_packet(r1)

            h.server.publish("tk/t", b"keep", False, 1)
            m = await read_wire_packet(r1)
            assert m.fixed_header.type == PUBLISH and m.fixed_header.qos == 1
            assert not m.fixed_header.dup

            # second connection with same id takes over without acking
            r2, w2, _ = await h.connect("dur", clean=False, expect_code=0)
            redo = await read_wire_packet(r2)
            assert redo.fixed_header.type == PUBLISH
            assert redo.payload == b"keep"
            assert redo.fixed_header.dup  # [MQTT-3.3.1-1] resend marks DUP
            await h.shutdown()

        run(scenario())

    def test_second_connect_is_protocol_violation(self):
        """A second CONNECT on a live connection disconnects the client
        (server.go:734-738, [MQTT-3.1.0-2])."""

        async def scenario():
            h = Harness()
            reader, writer, task = await h.connect("twice", version=5)
            writer.write(connect_packet("twice", 5))
            await writer.drain()
            disc = await read_wire_packet(reader, 5)
            assert disc.fixed_header.type == DISCONNECT
            assert disc.reason_code == codes.ERR_PROTOCOL_VIOLATION_SECOND_CONNECT.code
            await h.shutdown()

        run(scenario())


class TestSubscriptionOptions:
    def test_subscription_identifier_attached(self):
        """v5 subscription identifiers ride on delivered publishes, sorted
        [MQTT-3.3.4-3/4] (server.go:1033-1040)."""

        async def scenario():
            from mqtt_tpu.packets import Properties

            h = Harness()
            reader, writer, task = await h.connect("subid", version=5)
            pk = Packet(
                fixed_header=FixedHeader(type=SUBSCRIBE, qos=1),
                protocol_version=5,
                packet_id=2,
                properties=Properties(subscription_identifier=[7]),
                filters=[Subscription(filter="si/t", qos=0, identifier=7)],
            )
            writer.write(encode_packet(pk))
            await writer.drain()
            await read_wire_packet(reader, 5)

            h.server.publish("si/t", b"x", False, 0)
            m = await read_wire_packet(reader, 5)
            assert m.properties.subscription_identifier == [7]
            await h.shutdown()

        run(scenario())

    def test_no_local_suppresses_echo(self):
        """A no-local subscriber never receives its own publishes
        [MQTT-3.8.3-3] (server.go:1024-1026)."""

        async def scenario():
            h = Harness()
            reader, writer, task = await h.connect("nl", version=5)
            writer.write(
                sub_packet(1, [Subscription(filter="nl/t", qos=0, no_local=True)], version=5)
            )
            await writer.drain()
            await read_wire_packet(reader, 5)

            writer.write(pub_packet("nl/t", b"echo", version=5))
            await writer.drain()
            with pytest.raises(asyncio.TimeoutError):
                await read_wire_packet(reader, 5)

            # another client's publish still arrives
            h.server.publish("nl/t", b"other", False, 0)
            m = await read_wire_packet(reader, 5)
            assert m.payload == b"other"
            await h.shutdown()

        run(scenario())


class TestExpiryLoops:
    def test_clear_expired_retained_messages(self):
        async def scenario():
            import time as _time

            h = Harness()
            opts = h.server.options
            r, w, _ = await h.connect("ret", version=5)
            from mqtt_tpu.packets import Properties

            w.write(
                pub_packet(
                    "ex/t", b"v", version=5, retain=True,
                    props=Properties(message_expiry_interval=5),
                )
            )
            await w.drain()
            await asyncio.sleep(0.1)
            assert len(h.server.topics.retained) == 1
            h.server.clear_expired_retained_messages(int(_time.time()) + 60)
            assert len(h.server.topics.retained) == 0
            await h.shutdown()

        run(scenario())

    def test_clear_expired_clients(self):
        async def scenario():
            import time as _time

            h = Harness()
            # v4 with clean=False survives disconnect (server.go:484);
            # a v5 session with no expiry property would end immediately
            r, w, _ = await h.connect("mortal", clean=False)
            w.close()
            await asyncio.sleep(0.1)
            assert h.server.clients.get("mortal") is not None
            # session expiry defaults to the server maximum; far future expires
            h.server.clear_expired_clients(int(_time.time()) + 2 ** 33)
            assert h.server.clients.get("mortal") is None
            await h.shutdown()

        run(scenario())

    def test_clear_expired_inflights(self):
        async def scenario():
            import time as _time

            h = Harness()
            r, w, _ = await h.connect("ifm", clean=False)
            w.write(sub_packet(1, [Subscription(filter="if/t", qos=1)]))
            await w.drain()
            await read_wire_packet(r)
            h.server.publish("if/t", b"x", False, 1)
            await read_wire_packet(r)  # delivered, never acked
            cl = h.server.clients.get("ifm")
            assert len(cl.state.inflight) == 1
            h.server.clear_expired_inflights(int(_time.time()) + 2 ** 33)
            assert len(cl.state.inflight) == 0
            await h.shutdown()

        run(scenario())


class TestServerAPIs:
    def test_disconnect_client_sends_v5_disconnect(self):
        async def scenario():
            h = Harness()
            reader, writer, task = await h.connect("kickme", version=5)
            cl = h.server.clients.get("kickme")
            # error-class codes re-raise after stopping (mirrors the
            # reference's error return, server.go:1413-1437)
            with pytest.raises(Code):
                h.server.disconnect_client(cl, codes.ERR_ADMINISTRATIVE_ACTION)
            disc = await read_wire_packet(reader, 5)
            assert disc.fixed_header.type == DISCONNECT
            assert disc.reason_code == codes.ERR_ADMINISTRATIVE_ACTION.code
            await h.shutdown()

        run(scenario())

    def test_unsubscribe_client_clears_trie(self):
        async def scenario():
            h = Harness()
            reader, writer, task = await h.connect("unsub-all")
            writer.write(
                sub_packet(1, [Subscription(filter="ua/1", qos=0), Subscription(filter="ua/2", qos=0)])
            )
            await writer.drain()
            await read_wire_packet(reader)
            assert len(h.server.topics.subscribers("ua/1").subscriptions) == 1
            cl = h.server.clients.get("unsub-all")
            h.server.unsubscribe_client(cl)
            assert len(h.server.topics.subscribers("ua/1").subscriptions) == 0
            assert len(h.server.topics.subscribers("ua/2").subscriptions) == 0
            await h.shutdown()

        run(scenario())

    def test_inject_packet_publishes(self):
        async def scenario():
            h = Harness()
            reader, writer, task = await h.connect("inj-sub")
            writer.write(sub_packet(1, [Subscription(filter="in/t", qos=0)]))
            await writer.drain()
            await read_wire_packet(reader)
            cl = h.server.clients.get("inj-sub")
            h.server.inject_packet(
                cl,
                Packet(
                    fixed_header=FixedHeader(type=PUBLISH),
                    topic_name="in/t",
                    payload=b"injected",
                ),
            )
            m = await read_wire_packet(reader)
            assert m.payload == b"injected"
            await h.shutdown()

        run(scenario())


def unsub_packet(pid, filters, version=4):
    return encode_packet(
        Packet(
            fixed_header=FixedHeader(type=UNSUBSCRIBE, qos=1),
            protocol_version=version,
            packet_id=pid,
            filters=[Subscription(filter=f) for f in filters],
        )
    )


class TestCompatibilities:
    """The reference's compatibility-mode flags (server.go:86-93)."""

    def test_obscure_not_authorized_masks_suback_code(self):
        async def scenario():
            opts = Options()
            opts.capabilities.compatibilities.obscure_not_authorized = True
            h = Harness(opts, allow=False)

            class DenyACL(Hook):
                def id(self):
                    return "deny-acl"

                def provides(self, b):
                    return b in (ON_CONNECT_AUTHENTICATE, ON_ACL_CHECK)

                def on_connect_authenticate(self, cl, pk):
                    return True

                def on_acl_check(self, cl, topic, write):
                    return False

            h.server.add_hook(DenyACL())
            r, w, _ = await h.connect("obsc")
            w.write(sub_packet(1, [Subscription(filter="a/b", qos=0)]))
            await w.drain()
            ack = await read_wire_packet(r)
            assert ack.fixed_header.type == SUBACK
            assert ack.reason_codes == b"\x80"  # unspecified, NOT 0x87
            await h.shutdown()

        run(scenario())

    def test_not_authorized_suback_code_without_flag(self):
        async def scenario():
            h = Harness(allow=False)

            class DenyACL(Hook):
                def id(self):
                    return "deny-acl"

                def provides(self, b):
                    return b in (ON_CONNECT_AUTHENTICATE, ON_ACL_CHECK)

                def on_connect_authenticate(self, cl, pk):
                    return True

                def on_acl_check(self, cl, topic, write):
                    return False

            h.server.add_hook(DenyACL())
            r, w, _ = await h.connect("noobsc", version=5)
            w.write(sub_packet(1, [Subscription(filter="a/b", qos=0)], version=5))
            await w.drain()
            ack = await read_wire_packet(r, 5)
            assert ack.reason_codes == b"\x87"  # not authorized, unmasked
            await h.shutdown()

        run(scenario())

    def test_passive_client_disconnect_keeps_connection(self):
        async def scenario():
            opts = Options()
            opts.capabilities.compatibilities.passive_client_disconnect = True
            h = Harness(opts)
            r, w, _ = await h.connect("passive", version=5)
            cl = h.server.clients.get("passive")
            # an error-class disconnect writes DISCONNECT but must NOT stop
            # the client nor raise (server.go:1413-1437 passive mode)
            h.server.disconnect_client(cl, codes.ERR_KEEP_ALIVE_TIMEOUT)
            pk = await read_wire_packet(r, 5)
            assert pk.fixed_header.type == DISCONNECT
            assert not cl.closed
            await h.shutdown()

        run(scenario())

    def test_always_return_response_info(self):
        async def scenario():
            opts = Options()
            opts.capabilities.compatibilities.always_return_response_info = True
            h = Harness(opts)
            reader, writer, task = await h.attach()
            pk = Packet(
                fixed_header=FixedHeader(type=CONNECT),
                protocol_version=5,
                connect=ConnectParams(
                    protocol_name=b"MQTT",
                    clean=True,
                    keepalive=30,
                    client_identifier="ri",
                ),
            )
            pk.properties.request_response_info = 1
            writer.write(encode_packet(pk))
            await writer.drain()
            ack = await read_wire_packet(reader, 5)
            assert ack.fixed_header.type == CONNACK
            await h.shutdown()

        run(scenario())

    def test_no_inherited_properties_on_ack(self):
        async def scenario():
            opts = Options()
            opts.capabilities.compatibilities.no_inherited_properties_on_ack = True
            h = Harness(opts)
            r, w, _ = await h.connect("noinherit", version=5)
            pk = Packet(
                fixed_header=FixedHeader(type=PUBLISH, qos=1),
                protocol_version=5,
                topic_name="n/i",
                packet_id=3,
                payload=b"x",
            )
            from mqtt_tpu.packets import UserProperty
            pk.properties.user = [UserProperty("k", "v")]
            w.write(encode_packet(pk))
            await w.drain()
            ack = await read_wire_packet(r, 5)
            assert ack.fixed_header.type == PUBACK
            assert not ack.properties.user  # properties NOT inherited
            await h.shutdown()

        run(scenario())

    def test_restore_sys_info_on_restart(self):
        from mqtt_tpu.hooks.storage import SystemInfo as StoredSysInfo
        from mqtt_tpu.hooks import STORED_SYS_INFO as _SSI

        class SysStore(Hook):
            def id(self):
                return "sys-store"

            def provides(self, b):
                return b == _SSI

            def stored_sys_info(self):
                info = StoredSysInfo()
                info.info.version = "2.7.9"  # first NON-EMPTY wins (hooks.go:644)
                info.info.bytes_received = 777
                info.info.messages_received = 42
                return info

        async def scenario():
            opts = Options()
            opts.capabilities.compatibilities.restore_sys_info_on_restart = True
            h = Harness(opts)
            h.server.add_hook(SysStore())
            h.server.read_store()
            assert h.server.info.bytes_received == 777
            assert h.server.info.messages_received == 42
            await h.shutdown()

        run(scenario())


class TestSubscribeEdges:
    def test_shared_no_local_violation_code(self):
        async def scenario():
            h = Harness()
            r, w, _ = await h.connect("snl", version=5)
            w.write(
                sub_packet(
                    1,
                    [Subscription(filter="$share/g/a", qos=0, no_local=True)],
                    version=5,
                )
            )
            await w.drain()
            ack = await read_wire_packet(r, 5)
            assert ack.reason_codes[0] == 0x82  # protocol error [MQTT-3.8.3-4]
            await h.shutdown()

        run(scenario())

    def test_invalid_filter_reason_code(self):
        async def scenario():
            h = Harness()
            r, w, _ = await h.connect("badf", version=5)
            w.write(sub_packet(1, [Subscription(filter="a/#/b", qos=0)], version=5))
            await w.drain()
            ack = await read_wire_packet(r, 5)
            assert ack.reason_codes[0] == codes.ERR_TOPIC_FILTER_INVALID.code
            await h.shutdown()

        run(scenario())

    def test_packet_id_in_use_suback(self):
        async def scenario():
            h = Harness()
            r, w, _ = await h.connect("piu", version=5)
            cl = h.server.clients.get("piu")
            cl.state.inflight.set(
                Packet(fixed_header=FixedHeader(type=PUBLISH, qos=1), packet_id=9)
            )
            w.write(sub_packet(9, [Subscription(filter="a/b", qos=0)], version=5))
            await w.drain()
            ack = await read_wire_packet(r, 5)
            assert ack.reason_codes[0] == codes.ERR_PACKET_IDENTIFIER_IN_USE.code
            await h.shutdown()

        run(scenario())

    def test_granted_qos_capped_by_server_maximum(self):
        async def scenario():
            opts = Options()
            opts.capabilities.maximum_qos = 1
            h = Harness(opts)
            r, w, _ = await h.connect("qcap")
            w.write(sub_packet(1, [Subscription(filter="a/b", qos=2)]))
            await w.drain()
            ack = await read_wire_packet(r)
            assert ack.reason_codes == b"\x01"  # granted qos1, not qos2
            await h.shutdown()

        run(scenario())

    def test_subscription_counter_tracks_new_and_existing(self):
        async def scenario():
            h = Harness()
            r, w, _ = await h.connect("cnt")
            w.write(sub_packet(1, [Subscription(filter="c/1", qos=0)]))
            await w.drain()
            await read_wire_packet(r)
            n1 = h.server.info.subscriptions
            w.write(sub_packet(2, [Subscription(filter="c/1", qos=1)]))  # resubscribe
            await w.drain()
            await read_wire_packet(r)
            assert h.server.info.subscriptions == n1  # not double counted
            await h.shutdown()

        run(scenario())

    def test_unsubscribe_decrements_counter_and_acks(self):
        async def scenario():
            h = Harness()
            r, w, _ = await h.connect("uns", version=5)
            w.write(sub_packet(1, [Subscription(filter="u/1", qos=0)], version=5))
            await w.drain()
            await read_wire_packet(r, 5)
            n1 = h.server.info.subscriptions
            w.write(unsub_packet(2, ["u/1", "u/nope"], version=5))
            await w.drain()
            ack = await read_wire_packet(r, 5)
            assert ack.fixed_header.type == UNSUBACK
            assert ack.reason_codes == b"\x00\x11"  # success, no sub existed
            assert h.server.info.subscriptions == n1 - 1
            await h.shutdown()

        run(scenario())


class TestInflightQuotaEdges:
    def test_maximum_inflight_gate_drops_qos_publish(self):
        async def scenario():
            opts = Options()
            opts.capabilities.maximum_inflight = 1
            h = Harness(opts)
            sub_r, sub_w, _ = await h.connect("slow")
            sub_w.write(sub_packet(1, [Subscription(filter="g/#", qos=1)]))
            await sub_w.drain()
            await read_wire_packet(sub_r)
            cl = h.server.clients.get("slow")
            # occupy the single inflight slot
            cl.state.inflight.set(
                Packet(fixed_header=FixedHeader(type=PUBLISH, qos=1), packet_id=60000)
            )
            dropped0 = h.server.info.inflight_dropped
            pub_r, pub_w, _ = await h.connect("fast")
            pub_w.write(pub_packet("g/1", b"x", qos=1, pid=5))
            await pub_w.drain()
            await read_wire_packet(pub_r)  # publisher still gets PUBACK
            await asyncio.sleep(0.05)
            assert h.server.info.inflight_dropped == dropped0 + 1
            await h.shutdown()

        run(scenario())

    def test_packet_id_exhaustion_counts_and_hook(self):
        async def scenario():
            h = Harness()
            seen = []

            class IdHook(Hook):
                def id(self):
                    return "ids"

                def provides(self, b):
                    from mqtt_tpu.hooks import ON_PACKET_ID_EXHAUSTED

                    return b == ON_PACKET_ID_EXHAUSTED

                def on_packet_id_exhausted(self, cl, pk):
                    seen.append(cl.id)

            h.server.add_hook(IdHook())
            sub_r, sub_w, _ = await h.connect("exhaust")
            sub_w.write(sub_packet(1, [Subscription(filter="e/#", qos=1)]))
            await sub_w.drain()
            await read_wire_packet(sub_r)
            cl = h.server.clients.get("exhaust")
            # fill the entire id space
            caps_max = h.server.options.capabilities.maximum_packet_id
            for i in range(1, caps_max + 1):
                cl.state.inflight.set(
                    Packet(fixed_header=FixedHeader(type=PUBLISH, qos=1), packet_id=i)
                )
            # bypass the inflight-count gate so next_packet_id is reached
            h.server.options.capabilities.maximum_inflight = caps_max + 10
            pub_r, pub_w, _ = await h.connect("src")
            pub_w.write(pub_packet("e/1", b"x", qos=1, pid=5))
            await pub_w.drain()
            await read_wire_packet(pub_r)
            await asyncio.sleep(0.05)
            assert seen == ["exhaust"]
            await h.shutdown()

        run(scenario())

    def test_send_quota_zero_marks_immediate_resend(self):
        async def scenario():
            h = Harness()
            reader, writer, task = await h.attach()
            pk = Packet(
                fixed_header=FixedHeader(type=CONNECT),
                protocol_version=5,
                connect=ConnectParams(
                    protocol_name=b"MQTT",
                    clean=True,
                    keepalive=30,
                    client_identifier="quota1",
                ),
            )
            pk.properties.receive_maximum = 1  # client accepts 1 inflight
            writer.write(encode_packet(pk))
            await writer.drain()
            await read_wire_packet(reader, 5)
            writer.write(sub_packet(1, [Subscription(filter="q/#", qos=1)], version=5))
            await writer.drain()
            await read_wire_packet(reader, 5)

            pub_r, pub_w, _ = await h.connect("qsrc")
            pub_w.write(pub_packet("q/a", b"1", qos=1, pid=2))
            pub_w.write(pub_packet("q/b", b"2", qos=1, pid=3))
            await pub_w.drain()
            await read_wire_packet(pub_r)
            await read_wire_packet(pub_r)
            # first delivery consumed the quota; second is parked immediate
            out1 = await read_wire_packet(reader, 5)
            assert out1.fixed_header.type == PUBLISH
            cl = h.server.clients.get("quota1")
            await asyncio.sleep(0.05)
            assert cl.state.inflight.next_immediate() is not None
            # PUBACK frees quota -> the parked publish drains
            writer.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=PUBACK),
                        protocol_version=5,
                        packet_id=out1.packet_id,
                    )
                )
            )
            await writer.drain()
            out2 = await read_wire_packet(reader, 5)
            assert out2.fixed_header.type == PUBLISH
            assert bytes(out2.payload) == b"2"
            await h.shutdown()

        run(scenario())

    def test_pubrel_unknown_id_gets_pubcomp_not_found(self):
        async def scenario():
            h = Harness()
            r, w, _ = await h.connect("rel5", version=5)
            w.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=PUBREL, qos=1),
                        protocol_version=5,
                        packet_id=77,
                    )
                )
            )
            await w.drain()
            ack = await read_wire_packet(r, 5)
            assert ack.fixed_header.type == PUBCOMP
            assert ack.reason_code == 0x92  # packet identifier not found
            await h.shutdown()

        run(scenario())

    def test_puback_unknown_id_is_ignored(self):
        async def scenario():
            h = Harness()
            r, w, _ = await h.connect("ack4")
            w.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=PUBACK),
                        protocol_version=4,
                        packet_id=555,
                    )
                )
            )
            w.write(encode_packet(Packet(fixed_header=FixedHeader(type=PINGREQ))))
            await w.drain()
            pk = await read_wire_packet(r)
            assert pk.fixed_header.type == PINGRESP  # connection healthy
            await h.shutdown()

        run(scenario())

    def test_receive_quota_restored_after_qos2_complete(self):
        async def scenario():
            h = Harness()
            r, w, _ = await h.connect("q2q")
            cl = h.server.clients.get("q2q")
            quota0 = cl.state.inflight.receive_quota
            w.write(pub_packet("t/2", b"x", qos=2, pid=9))
            await w.drain()
            rec = await read_wire_packet(r)
            assert rec.fixed_header.type == PUBREC
            assert cl.state.inflight.receive_quota == quota0 - 1
            w.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=PUBREL, qos=1),
                        protocol_version=4,
                        packet_id=9,
                    )
                )
            )
            await w.drain()
            comp = await read_wire_packet(r)
            assert comp.fixed_header.type == PUBCOMP
            assert cl.state.inflight.receive_quota == quota0
            await h.shutdown()

        run(scenario())


class TestTakeoverEdges:
    def test_clean_takeover_discards_session(self):
        async def scenario():
            h = Harness()
            r1, w1, _ = await h.connect("td", clean=False)
            w1.write(sub_packet(1, [Subscription(filter="t/d", qos=1)]))
            await w1.drain()
            await read_wire_packet(r1)
            # reconnect CLEAN: subscriptions must be discarded
            r2, w2, _ = await h.connect("td", clean=True)
            await asyncio.sleep(0.05)
            subs = h.server.topics.subscribers("t/d")
            assert "td" not in subs.subscriptions
            await h.shutdown()

        run(scenario())

    def test_dirty_takeover_keeps_subscriptions_and_session_present(self):
        async def scenario():
            h = Harness()
            r1, w1, _ = await h.connect("tk", clean=False)
            w1.write(sub_packet(1, [Subscription(filter="t/k", qos=1)]))
            await w1.drain()
            await read_wire_packet(r1)
            reader, writer, task = await h.attach()
            writer.write(connect_packet("tk", 4, clean=False))
            await writer.drain()
            raw = await asyncio.wait_for(reader.readexactly(4), TIMEOUT)
            assert raw == bytes.fromhex("20020100")  # session present = 1
            subs = h.server.topics.subscribers("t/k")
            assert "tk" in subs.subscriptions
            await h.shutdown()

        run(scenario())

    def test_takeover_of_disconnected_session(self):
        async def scenario():
            h = Harness()
            r1, w1, t1 = await h.connect("gone", clean=False)
            w1.close()  # abnormal drop; session survives (non-clean)
            await asyncio.sleep(0.05)
            r2, w2, _ = await h.connect("gone", clean=False)
            await asyncio.sleep(0.05)
            cl = h.server.clients.get("gone")
            assert cl is not None and not cl.closed
            await h.shutdown()

        run(scenario())


class TestRetainEdges:
    def test_empty_payload_deletes_retained(self):
        async def scenario():
            h = Harness()
            r, w, _ = await h.connect("ret")
            w.write(pub_packet("r/1", b"keep", retain=True))
            await w.drain()
            await asyncio.sleep(0.05)
            assert h.server.topics.retained.get("r/1") is not None
            assert h.server.info.retained == 1
            w.write(pub_packet("r/1", b"", retain=True))  # delete [MQTT-3.3.1-6]
            await w.drain()
            await asyncio.sleep(0.05)
            assert h.server.topics.retained.get("r/1") is None
            assert h.server.info.retained == 0
            await h.shutdown()

        run(scenario())

    def test_retain_available_zero_ignores_retain(self):
        async def scenario():
            opts = Options()
            opts.capabilities.retain_available = 0
            h = Harness(opts)
            r, w, _ = await h.connect("noret")
            w.write(pub_packet("r/2", b"x", retain=True))
            await w.drain()
            await asyncio.sleep(0.05)
            assert h.server.topics.retained.get("r/2") is None
            await h.shutdown()

        run(scenario())

    def test_retain_handling_1_skips_existing_subscription(self):
        async def scenario():
            h = Harness()
            pub_r, pub_w, _ = await h.connect("rp")
            pub_w.write(pub_packet("rh/1", b"x", retain=True))
            await pub_w.drain()
            r, w, _ = await h.connect("rh1", version=5)
            # retain_handling=1: send retained only if subscription is NEW
            w.write(
                sub_packet(
                    1,
                    [Subscription(filter="rh/1", qos=0, retain_handling=1)],
                    version=5,
                )
            )
            await w.drain()
            await read_wire_packet(r, 5)  # suback
            pk = await read_wire_packet(r, 5)
            assert pk.fixed_header.type == PUBLISH  # new sub -> retained sent
            # resubscribe: filter exists -> retained NOT sent again
            w.write(
                sub_packet(
                    2,
                    [Subscription(filter="rh/1", qos=0, retain_handling=1)],
                    version=5,
                )
            )
            await w.drain()
            ack2 = await read_wire_packet(r, 5)
            assert ack2.fixed_header.type == SUBACK
            w.write(encode_packet(Packet(fixed_header=FixedHeader(type=PINGREQ))))
            await w.drain()
            nxt = await read_wire_packet(r, 5)
            assert nxt.fixed_header.type == PINGRESP  # no second retained
            await h.shutdown()

        run(scenario())

    def test_retain_handling_2_never_sends_retained(self):
        async def scenario():
            h = Harness()
            pub_r, pub_w, _ = await h.connect("rp2")
            pub_w.write(pub_packet("rh/2", b"x", retain=True))
            await pub_w.drain()
            r, w, _ = await h.connect("rh2c", version=5)
            w.write(
                sub_packet(
                    1,
                    [Subscription(filter="rh/2", qos=0, retain_handling=2)],
                    version=5,
                )
            )
            await w.drain()
            await read_wire_packet(r, 5)  # suback
            w.write(encode_packet(Packet(fixed_header=FixedHeader(type=PINGREQ))))
            await w.drain()
            nxt = await read_wire_packet(r, 5)
            assert nxt.fixed_header.type == PINGRESP  # nothing retained sent
            await h.shutdown()

        run(scenario())

    def test_retain_as_published_preserves_flag(self):
        async def scenario():
            h = Harness()
            r, w, _ = await h.connect("rap", version=5)
            w.write(
                sub_packet(
                    1,
                    [Subscription(filter="rap/#", qos=0, retain_as_published=True)],
                    version=5,
                )
            )
            await w.drain()
            await read_wire_packet(r, 5)
            pub_r, pub_w, _ = await h.connect("rapsrc")
            pub_w.write(pub_packet("rap/t", b"x", retain=True))
            await pub_w.drain()
            pk = await read_wire_packet(r, 5)
            assert pk.fixed_header.retain is True  # RAP keeps the flag
            await h.shutdown()

        run(scenario())

    def test_retained_qos_downgraded_to_subscription(self):
        async def scenario():
            h = Harness()
            pub_r, pub_w, _ = await h.connect("rqsrc")
            pub_w.write(pub_packet("rq/1", b"x", qos=1, pid=4, retain=True))
            await pub_w.drain()
            await read_wire_packet(pub_r)
            r, w, _ = await h.connect("rqsub")
            w.write(sub_packet(1, [Subscription(filter="rq/1", qos=0)]))
            await w.drain()
            await read_wire_packet(r)
            pk = await read_wire_packet(r)
            assert pk.fixed_header.type == PUBLISH
            assert pk.fixed_header.qos == 0  # min(sub 0, msg 1)
            await h.shutdown()

        run(scenario())

    def test_sys_topics_not_matched_by_top_level_wildcard(self):
        async def scenario():
            h = Harness()
            r, w, _ = await h.connect("wild")
            w.write(sub_packet(1, [Subscription(filter="#", qos=0)]))
            await w.drain()
            await read_wire_packet(r)
            h.server.publish_sys_topics()
            w.write(encode_packet(Packet(fixed_header=FixedHeader(type=PINGREQ))))
            await w.drain()
            pk = await read_wire_packet(r)
            assert pk.fixed_header.type == PINGRESP  # no $SYS leaked to '#'
            await h.shutdown()

        run(scenario())

    def test_sys_topics_delivered_to_explicit_subscriber(self):
        async def scenario():
            h = Harness()
            r, w, _ = await h.connect("sysw")
            w.write(sub_packet(1, [Subscription(filter="$SYS/broker/uptime", qos=0)]))
            await w.drain()
            await read_wire_packet(r)
            h.server.publish_sys_topics()
            pk = await read_wire_packet(r)
            assert pk.topic_name == "$SYS/broker/uptime"
            await h.shutdown()

        run(scenario())


class TestPublishEdges:
    def test_publish_to_sys_topic_is_dropped(self):
        async def scenario():
            h = Harness()
            spy_r, spy_w, _ = await h.connect("spy")
            spy_w.write(sub_packet(1, [Subscription(filter="$SYS/#", qos=0)]))
            await spy_w.drain()
            await read_wire_packet(spy_r)
            r, w, _ = await h.connect("evil")
            w.write(pub_packet("$SYS/broker/uptime", b"hax"))
            w.write(encode_packet(Packet(fixed_header=FixedHeader(type=PINGREQ))))
            await w.drain()
            await read_wire_packet(r)  # pingresp: publisher not disconnected
            spy_w.write(encode_packet(Packet(fixed_header=FixedHeader(type=PINGREQ))))
            await spy_w.drain()
            pk = await read_wire_packet(spy_r)
            assert pk.fixed_header.type == PINGRESP  # $SYS publish dropped
            await h.shutdown()

        run(scenario())

    def test_inbound_alias_above_maximum_disconnects(self):
        async def scenario():
            opts = Options()
            opts.capabilities.topic_alias_maximum = 2
            h = Harness(opts)
            r, w, _ = await h.connect("alias5", version=5)
            pk = Packet(
                fixed_header=FixedHeader(type=PUBLISH),
                protocol_version=5,
                topic_name="a/t",
                payload=b"x",
            )
            pk.properties.topic_alias = 9  # above server maximum
            pk.properties.topic_alias_flag = True
            w.write(encode_packet(pk))
            await w.drain()
            out = await read_wire_packet(r, 5)
            assert out.fixed_header.type == DISCONNECT
            assert out.reason_code == codes.ERR_TOPIC_ALIAS_INVALID.code
            await h.shutdown()

        run(scenario())

    def test_v3_acl_deny_publish_disconnects(self):
        async def scenario():
            h = Harness(allow=False)

            class WriteDeny(Hook):
                def id(self):
                    return "write-deny"

                def provides(self, b):
                    return b in (ON_CONNECT_AUTHENTICATE, ON_ACL_CHECK)

                def on_connect_authenticate(self, cl, pk):
                    return True

                def on_acl_check(self, cl, topic, write):
                    return not write  # deny writes only

            h.server.add_hook(WriteDeny())
            r, w, task = await h.connect("v3deny")
            w.write(pub_packet("x/y", b"no", qos=1, pid=3))
            await w.drain()
            await asyncio.wait_for(task, TIMEOUT)  # v3: connection dropped
            await h.shutdown()

        run(scenario())

    def test_v5_acl_deny_qos1_acks_not_authorized(self):
        async def scenario():
            h = Harness(allow=False)

            class WriteDeny(Hook):
                def id(self):
                    return "write-deny"

                def provides(self, b):
                    return b in (ON_CONNECT_AUTHENTICATE, ON_ACL_CHECK)

                def on_connect_authenticate(self, cl, pk):
                    return True

                def on_acl_check(self, cl, topic, write):
                    return not write

            h.server.add_hook(WriteDeny())
            r, w, _ = await h.connect("v5deny", version=5)
            w.write(pub_packet("x/y", b"no", qos=1, pid=3, version=5))
            await w.drain()
            ack = await read_wire_packet(r, 5)
            assert ack.fixed_header.type == PUBACK
            assert ack.reason_code == codes.ERR_NOT_AUTHORIZED.code
            await h.shutdown()

        run(scenario())

    def test_qos2_duplicate_publish_acks_in_use(self):
        async def scenario():
            h = Harness()
            r, w, _ = await h.connect("dup2", version=5)
            w.write(pub_packet("d/2", b"x", qos=2, pid=8, version=5))
            await w.drain()
            rec1 = await read_wire_packet(r, 5)
            assert rec1.fixed_header.type == PUBREC
            w.write(pub_packet("d/2", b"x", qos=2, pid=8, version=5))
            await w.drain()
            rec2 = await read_wire_packet(r, 5)
            assert rec2.fixed_header.type == PUBREC
            assert rec2.reason_code == codes.ERR_PACKET_IDENTIFIER_IN_USE.code
            await h.shutdown()

        run(scenario())

    def test_message_expiry_interval_rewritten_on_delivery(self):
        async def scenario():
            h = Harness()
            r, w, _ = await h.connect("exp5", version=5)
            w.write(sub_packet(1, [Subscription(filter="ex/#", qos=0)], version=5))
            await w.drain()
            await read_wire_packet(r, 5)
            pub_r, pub_w, _ = await h.connect("expsrc", version=5)
            pk = Packet(
                fixed_header=FixedHeader(type=PUBLISH),
                protocol_version=5,
                topic_name="ex/1",
                payload=b"x",
            )
            pk.properties.message_expiry_interval = 300
            pub_w.write(encode_packet(pk))
            await pub_w.drain()
            out = await read_wire_packet(r, 5)
            # [MQTT-3.3.2-6]: remaining lifetime, <= original interval
            assert 0 < out.properties.message_expiry_interval <= 300
            await h.shutdown()

        run(scenario())


class TestDisconnectAndSessionEdges:
    def test_disconnect_with_will_message_sends_lwt(self):
        async def scenario():
            h = Harness()
            sub_r, sub_w, _ = await h.connect("lwtwatch")
            sub_w.write(sub_packet(1, [Subscription(filter="will/#", qos=0)]))
            await sub_w.drain()
            await read_wire_packet(sub_r)
            r, w, task = await h.connect(
                "willer", version=5, will=("will/us", b"bye")
            )
            w.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=DISCONNECT),
                        protocol_version=5,
                        reason_code=0x04,  # disconnect WITH will message
                    )
                )
            )
            await w.drain()
            pk = await read_wire_packet(sub_r)
            assert pk.topic_name == "will/us"
            assert bytes(pk.payload) == b"bye"
            await h.shutdown()

        run(scenario())

    def test_disconnect_zero_to_nonzero_expiry_violation(self):
        async def scenario():
            h = Harness()
            r, w, task = await h.connect("zexp", version=5)
            pk = Packet(
                fixed_header=FixedHeader(type=DISCONNECT),
                protocol_version=5,
                reason_code=0,
            )
            pk.properties.session_expiry_interval = 60
            pk.properties.session_expiry_interval_flag = True
            w.write(encode_packet(pk))
            await w.drain()
            out = await read_wire_packet(r, 5)
            assert out.fixed_header.type == DISCONNECT  # [MQTT-3.1.2-23]
            assert out.reason_code == codes.ERR_PROTOCOL_VIOLATION_ZERO_NON_ZERO_EXPIRY.code
            await h.shutdown()

        run(scenario())

    def test_session_expiry_clamped_to_server_maximum(self):
        async def scenario():
            opts = Options()
            opts.capabilities.maximum_session_expiry_interval = 100
            h = Harness(opts)
            reader, writer, task = await h.attach()
            pk = Packet(
                fixed_header=FixedHeader(type=CONNECT),
                protocol_version=5,
                connect=ConnectParams(
                    protocol_name=b"MQTT",
                    clean=True,
                    keepalive=30,
                    client_identifier="clamp",
                ),
            )
            pk.properties.session_expiry_interval = 99999
            pk.properties.session_expiry_interval_flag = True
            writer.write(encode_packet(pk))
            await writer.drain()
            ack = await read_wire_packet(reader, 5)
            assert ack.fixed_header.type == CONNACK
            cl = h.server.clients.get("clamp")
            assert cl.properties.props.session_expiry_interval == 100
            await h.shutdown()

        run(scenario())

    def test_auth_packet_dispatches_hook(self):
        async def scenario():
            h = Harness()
            seen = []

            class AuthHook(Hook):
                def id(self):
                    return "auth-watch"

                def provides(self, b):
                    from mqtt_tpu.hooks import ON_AUTH_PACKET

                    return b == ON_AUTH_PACKET

                def on_auth_packet(self, cl, pk):
                    seen.append(pk.reason_code)
                    return pk

            h.server.add_hook(AuthHook())
            r, w, _ = await h.connect("auth5", version=5)
            w.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=AUTH),
                        protocol_version=5,
                        reason_code=0x19,  # re-authenticate
                    )
                )
            )
            w.write(encode_packet(Packet(fixed_header=FixedHeader(type=PINGREQ))))
            await w.drain()
            pk = await read_wire_packet(r, 5)
            assert pk.fixed_header.type == PINGRESP
            assert seen == [0x19]
            await h.shutdown()

        run(scenario())

    def test_unsubscribe_clears_shared_group_membership(self):
        async def scenario():
            h = Harness()
            r, w, _ = await h.connect("shm", version=5)
            w.write(
                sub_packet(
                    1, [Subscription(filter="$share/g1/s/t", qos=0)], version=5
                )
            )
            await w.drain()
            await read_wire_packet(r, 5)
            assert h.server.topics.subscribers("s/t").shared
            w.write(unsub_packet(2, ["$share/g1/s/t"], version=5))
            await w.drain()
            await read_wire_packet(r, 5)
            assert not h.server.topics.subscribers("s/t").shared
            await h.shutdown()

        run(scenario())

    def test_shared_subscription_delivers_to_one_member(self):
        async def scenario():
            h = Harness()
            members = []
            for i in range(3):
                r, w, _ = await h.connect(f"gm{i}")
                w.write(
                    sub_packet(1, [Subscription(filter="$share/gg/x/y", qos=0)])
                )
                await w.drain()
                await read_wire_packet(r)
                members.append((r, w))
            pub_r, pub_w, _ = await h.connect("gpub")
            pub_w.write(pub_packet("x/y", b"once"))
            await pub_w.drain()
            await asyncio.sleep(0.1)
            got = 0
            for r, w in members:
                w.write(encode_packet(Packet(fixed_header=FixedHeader(type=PINGREQ))))
                await w.drain()
                pk = await read_wire_packet(r)
                if pk.fixed_header.type == PUBLISH:
                    got += 1
                    await read_wire_packet(r)  # trailing pingresp
            assert got == 1  # exactly one group member receives it
            await h.shutdown()

        run(scenario())


class TestReferenceScenarioParity:
    """Edge scenarios ported from the reference suite that had no analog
    here yet (server_test.go: ZeroByteUsername, ServerKeepalive,
    ConnackFailureReason, AuthInvalidReason, PubrecInvalidReason,
    PubrelBadReason, SendLWTRetain, OnPublishAckErrorContinue,
    SubscribeWithRetain[DifferentFilter], BadFixedHeader)."""

    def test_zero_byte_username_is_valid(self):
        # server_test.go TestServerEstablishConnectionZeroByteUsernameIsValid
        async def scenario():
            h = Harness()
            reader, writer, task = await h.attach()
            cp = ConnectParams(
                protocol_name=b"MQTT",
                clean=True,
                keepalive=30,
                client_identifier="zbu",
                username_flag=True,
                username=b"",
            )
            writer.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=CONNECT),
                        protocol_version=5,
                        connect=cp,
                    )
                )
            )
            await writer.drain()
            ack = await read_wire_packet(reader, 5)
            assert ack.fixed_header.type == CONNACK
            assert ack.reason_code == 0  # [MQTT-3.1.3-11]
            await h.shutdown()

        run(scenario())

    def test_connack_carries_server_keepalive(self):
        # server_test.go TestServerSendConnackWithServerKeepalive
        async def scenario():
            h = Harness()

            class KeepaliveSetter(Hook):
                def id(self):
                    return "ka-set"

                def provides(self, b):
                    return b == ON_CONNECT

                def on_connect(self, cl, pk):
                    cl.state.server_keepalive = True

            h.server.add_hook(KeepaliveSetter())
            reader, writer, task = await h.attach()
            writer.write(connect_packet("kasrv", 5, keepalive=30))
            await writer.drain()
            ack = await read_wire_packet(reader, 5)
            assert ack.fixed_header.type == CONNACK
            assert ack.properties.server_keep_alive_flag  # [MQTT-3.1.2-21]
            assert ack.properties.server_keep_alive == 30
            await h.shutdown()

        run(scenario())

    def test_connack_failure_carries_reason_string(self):
        # server_test.go TestServerSendConnackFailureReason
        async def scenario():
            h = Harness(allow=False)  # default deny-all
            reader, writer, task = await h.attach()
            writer.write(connect_packet("noway", 5))
            await writer.drain()
            ack = await read_wire_packet(reader, 5)
            assert ack.fixed_header.type == CONNACK
            # connect-time auth failure maps to bad-username-or-password
            # (server.go:552 validateConnect)
            assert ack.reason_code == codes.ERR_BAD_USERNAME_OR_PASSWORD.code
            assert (
                ack.properties.reason_string
                == codes.ERR_BAD_USERNAME_OR_PASSWORD.reason
            )
            assert ack.session_present is False  # [MQTT-3.2.2-6]
            await h.shutdown()

        run(scenario())

    def test_auth_invalid_reason_code_disconnects(self):
        # server_test.go TestServerProcessPacketAuthInvalidReason
        async def scenario():
            h = Harness()
            r, w, task = await h.connect("badauth", version=5)
            w.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=AUTH),
                        protocol_version=5,
                        reason_code=0x99,  # not one of 0x00/0x18/0x19
                    )
                )
            )
            await w.drain()
            out = await read_wire_packet(r, 5)
            assert out.fixed_header.type == DISCONNECT  # [MQTT-3.15.2-1]
            assert (
                out.reason_code
                == codes.ERR_PROTOCOL_VIOLATION_INVALID_REASON.code
            )
            await h.shutdown()

        run(scenario())

    def test_pubrec_invalid_reason_drops_outbound_qos2(self):
        # server_test.go TestServerProcessPacketPubrecInvalidReason
        async def scenario():
            h = Harness()
            dropped = []

            class DropWatch(Hook):
                def id(self):
                    return "drop-watch"

                def provides(self, b):
                    return b == ON_QOS_DROPPED

                def on_qos_dropped(self, cl, pk):
                    dropped.append(pk.packet_id)

            h.server.add_hook(DropWatch())
            r, w, _ = await h.connect("q2sub", version=5)
            w.write(sub_packet(1, [Subscription(filter="o/q2", qos=2)], version=5))
            await w.drain()
            await read_wire_packet(r, 5)
            pr, pw, _ = await h.connect("q2pub", version=5)
            pw.write(pub_packet("o/q2", b"x", qos=2, pid=5, version=5))
            await pw.drain()
            out = await read_wire_packet(r, 5)  # server->sub PUBLISH qos2
            assert out.fixed_header.type == PUBLISH
            assert out.fixed_header.qos == 2
            cl = h.server.clients.get("q2sub")
            assert len(cl.state.inflight) == 1
            # reply PUBREC with an error reason: flow must be abandoned
            w.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=PUBREC),
                        protocol_version=5,
                        packet_id=out.packet_id,
                        reason_code=0x80,
                    )
                )
            )
            w.write(encode_packet(Packet(fixed_header=FixedHeader(type=PINGREQ))))
            await w.drain()
            nxt = await read_wire_packet(r, 5)
            assert nxt.fixed_header.type == PINGRESP  # no PUBREL was sent
            assert len(cl.state.inflight) == 0
            assert dropped == [out.packet_id]
            await h.shutdown()

        run(scenario())

    def test_pubrel_bad_reason_drops_inbound_qos2(self):
        # server_test.go TestServerProcessPacketPubrelBadReason
        async def scenario():
            h = Harness()
            r, w, _ = await h.connect("relbad", version=5)
            w.write(pub_packet("i/q2", b"x", qos=2, pid=9, version=5))
            await w.drain()
            rec = await read_wire_packet(r, 5)
            assert rec.fixed_header.type == PUBREC
            cl = h.server.clients.get("relbad")
            assert len(cl.state.inflight) == 1
            w.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=PUBREL, qos=1),
                        protocol_version=5,
                        packet_id=9,
                        reason_code=0x83,  # error-class: MQTT5 4.13.2 ¶2
                    )
                )
            )
            w.write(encode_packet(Packet(fixed_header=FixedHeader(type=PINGREQ))))
            await w.drain()
            nxt = await read_wire_packet(r, 5)
            assert nxt.fixed_header.type == PINGRESP  # no PUBCOMP was sent
            assert len(cl.state.inflight) == 0
            await h.shutdown()

        run(scenario())

    def test_lwt_retain_flag_stores_retained_message(self):
        # server_test.go TestServerSendLWTRetain
        async def scenario():
            h = Harness()
            r, w, task = await h.connect(
                "willret", version=5, will=("will/ret", b"gone", 1, True)
            )
            w.close()  # abnormal disconnect fires the will
            await asyncio.wait_for(task, TIMEOUT)
            msgs = h.server.topics.messages("will/ret")
            assert len(msgs) == 1  # [MQTT-3.1.2-14/-15]
            assert bytes(msgs[0].payload) == b"gone"
            assert msgs[0].fixed_header.retain
            await h.shutdown()

        run(scenario())

    def test_on_publish_error_v4_continues_delivery(self):
        # server_test.go TestServerProcessPublishOnPublishAckErrorContinue
        async def scenario():
            h = Harness()

            class Failer(Hook):
                def id(self):
                    return "pub-fail"

                def provides(self, b):
                    return b == ON_PUBLISH

                def on_publish(self, cl, pk):
                    raise codes.ERR_UNSPECIFIED_ERROR()

            h.server.add_hook(Failer())
            sr, sw, _ = await h.connect("v4watch")
            sw.write(sub_packet(1, [Subscription(filter="c/#", qos=0)]))
            await sw.drain()
            await read_wire_packet(sr)
            r, w, _ = await h.connect("v4pub")
            w.write(pub_packet("c/1", b"still"))
            await w.drain()
            out = await read_wire_packet(sr)  # v3: error falls through
            assert out.fixed_header.type == PUBLISH
            assert bytes(out.payload) == b"still"
            await h.shutdown()

        run(scenario())

    def test_on_publish_error_v5_qos1_acks_error_no_delivery(self):
        # server_test.go TestServerProcessPublishOnPublishAckErrorRWError
        async def scenario():
            h = Harness()

            class Failer(Hook):
                def id(self):
                    return "pub-fail5"

                def provides(self, b):
                    return b == ON_PUBLISH

                def on_publish(self, cl, pk):
                    raise codes.ERR_UNSPECIFIED_ERROR()

            h.server.add_hook(Failer())
            sr, sw, _ = await h.connect("v5watch", version=5)
            sw.write(sub_packet(1, [Subscription(filter="c5/#", qos=0)], version=5))
            await sw.drain()
            await read_wire_packet(sr, 5)
            r, w, _ = await h.connect("v5pub", version=5)
            w.write(pub_packet("c5/1", b"no", qos=1, pid=4, version=5))
            await w.drain()
            ack = await read_wire_packet(r, 5)
            assert ack.fixed_header.type == PUBACK
            assert ack.reason_code == codes.ERR_UNSPECIFIED_ERROR.code
            sw.write(encode_packet(Packet(fixed_header=FixedHeader(type=PINGREQ))))
            await sw.drain()
            nxt = await read_wire_packet(sr, 5)
            assert nxt.fixed_header.type == PINGRESP  # nothing was delivered
            await h.shutdown()

        run(scenario())

    def test_inline_subscribe_receives_retained(self):
        # server_test.go TestServerSubscribeWithRetain
        async def scenario():
            h = Harness()
            h.server.publish("ret/in", b"kept", True, 0)
            got = []
            h.server.subscribe(
                "ret/#", 7, lambda cl, sub, pk: got.append(bytes(pk.payload))
            )
            assert got == [b"kept"]  # [MQTT-3.8.4-4]
            await h.shutdown()

        run(scenario())

    def test_inline_subscribe_different_filter_gets_no_retained(self):
        # server_test.go TestServerSubscribeWithRetainDifferentFilter
        async def scenario():
            h = Harness()
            h.server.publish("ret/in2", b"kept", True, 0)
            got = []
            h.server.subscribe(
                "other/#", 7, lambda cl, sub, pk: got.append(bytes(pk.payload))
            )
            assert got == []
            await h.shutdown()

        run(scenario())

    def test_bad_connect_fixed_header_closes_connection(self):
        # server_test.go TestServerReadConnectionPacketBadFixedHeader
        async def scenario():
            h = Harness()
            reader, writer, task = await h.attach()
            writer.write(bytes([0x13, 0x00]))  # CONNECT with reserved flags set
            await writer.drain()
            await asyncio.wait_for(task, TIMEOUT)
            data = await asyncio.wait_for(reader.read(16), TIMEOUT)
            assert data == b""  # dropped before any CONNACK
            await h.shutdown()

        run(scenario())


class TestFastPublishPassthrough:
    """The QoS0 v4 frame passthrough must be byte- and counter-identical
    to the decode path, and must defer every case it cannot prove."""

    async def _roundtrip(self, h, extra_hook=None):
        if extra_hook is not None:
            h.server.add_hook(extra_hook)
        sr, sw, _ = await h.connect("fsub")
        sw.write(sub_packet(1, [Subscription(filter="fp/+", qos=0)]))
        await sw.drain()
        await read_wire_packet(sr)
        pr, pw, _ = await h.connect("fpub")
        frames = []
        for i in range(5):
            pw.write(pub_packet(f"fp/{i}", f"payload-{i}".encode()))
        await pw.drain()
        for i in range(5):
            pk = await read_wire_packet(sr)
            frames.append((pk.topic_name, bytes(pk.payload), pk.fixed_header.retain))
        stats = (h.server.info.messages_received, h.server.info.messages_sent)
        return frames, stats

    def test_fast_and_slow_paths_deliver_identical_bytes_and_counters(self):
        async def scenario():
            fast_h = Harness()
            fast_frames, fast_stats = await self._roundtrip(fast_h)
            await fast_h.shutdown()

            class SlowForcer(Hook):
                """Providing ON_PUBLISH disables the passthrough."""

                def id(self):
                    return "slow-forcer"

                def provides(self, b):
                    return b == ON_PUBLISH

                def on_publish(self, cl, pk):
                    return pk

            slow_h = Harness()
            slow_frames, slow_stats = await self._roundtrip(slow_h, SlowForcer())
            await slow_h.shutdown()

            assert fast_frames == slow_frames
            assert fast_stats == slow_stats
            await asyncio.sleep(0)

        run(scenario())

    def test_mixed_version_targets_fast_v4_slow_v5(self):
        async def scenario():
            h = Harness()
            r4, w4, _ = await h.connect("v4t")
            w4.write(sub_packet(1, [Subscription(filter="mx/#", qos=0)]))
            await w4.drain()
            await read_wire_packet(r4)
            r5, w5, _ = await h.connect("v5t", version=5)
            w5.write(sub_packet(1, [Subscription(filter="mx/#", qos=0)], version=5))
            await w5.drain()
            await read_wire_packet(r5, 5)
            pr, pw, _ = await h.connect("mixpub")
            pw.write(pub_packet("mx/a", b"both"))
            await pw.drain()
            pk4 = await read_wire_packet(r4)
            pk5 = await read_wire_packet(r5, 5)
            assert bytes(pk4.payload) == bytes(pk5.payload) == b"both"
            assert pk4.topic_name == pk5.topic_name == "mx/a"
            await h.shutdown()

        run(scenario())

    def test_no_local_suppressed_on_fast_path(self):
        async def scenario():
            h = Harness()
            # a v5 session subscribes with no_local, then is taken over by
            # a v4 connection (subscriptions inherited): the v4 publisher
            # IS eligible for the passthrough, so the no_local origin
            # check must fire inside the fast dispatcher itself
            r5, w5, _ = await h.connect("selfpub", version=5, clean=False)
            w5.write(
                sub_packet(
                    1,
                    [Subscription(filter="nl/#", qos=0, no_local=True)],
                    version=5,
                )
            )
            await w5.drain()
            await read_wire_packet(r5, 5)
            r, w, _ = await h.connect("selfpub", version=4, clean=False)
            assert h.server.topics.subscribers("nl/x").subscriptions  # inherited
            w.write(pub_packet("nl/x", b"echo"))
            w.write(encode_packet(Packet(fixed_header=FixedHeader(type=PINGREQ))))
            await w.drain()
            nxt = await read_wire_packet(r)
            assert nxt.fixed_header.type == PINGRESP  # no echo delivered
            await h.shutdown()

        run(scenario())

    def test_acl_denied_fast_publish_drops_silently(self):
        async def scenario():
            h = Harness(allow=False)  # OR-auth: AllowHook would override

            class DenyPub(Hook):
                def id(self):
                    return "deny-pub"

                def provides(self, b):
                    return b in (ON_CONNECT_AUTHENTICATE, ON_ACL_CHECK)

                def on_connect_authenticate(self, cl, pk):
                    return True

                def on_acl_check(self, cl, topic, write):
                    return not (write and topic.startswith("secret/"))

            h.server.add_hook(DenyPub())
            sr, sw, _ = await h.connect("aclsub")
            sw.write(sub_packet(1, [Subscription(filter="#", qos=0)]))
            await sw.drain()
            await read_wire_packet(sr)
            pr, pw, _ = await h.connect("aclpub")
            pw.write(pub_packet("secret/x", b"no"))
            pw.write(pub_packet("open/x", b"yes"))
            pw.write(encode_packet(Packet(fixed_header=FixedHeader(type=PINGREQ))))
            await pw.drain()
            assert (await read_wire_packet(pr)).fixed_header.type == PINGRESP
            out = await read_wire_packet(sr)
            assert out.topic_name == "open/x"  # denied topic never arrived
            await h.shutdown()

        run(scenario())

    def test_wildcard_and_dollar_topics_defer_to_slow_path(self):
        async def scenario():
            h = Harness()
            r, w, _ = await h.connect("oddpub")
            # publishing to a wildcard topic surfaces through the decode
            # path (the passthrough must defer it), which for v4 drops
            # the connection without a reply
            w.write(pub_packet("bad/+/topic", b"x"))
            await w.drain()
            data = await asyncio.wait_for(r.read(16), TIMEOUT)
            assert data == b""  # connection closed by the broker
            await h.shutdown()

        run(scenario())

    def test_padded_varint_publish_defers_to_decode_path(self):
        """A non-minimal remaining-length varint must NOT be relayed
        verbatim: the decode path re-encodes the frame minimally."""

        async def scenario():
            h = Harness()
            sr, sw, _ = await h.connect("vsub")
            sw.write(sub_packet(1, [Subscription(filter="pv/#", qos=0)]))
            await sw.drain()
            await read_wire_packet(sr)
            pr, pw, _ = await h.connect("vpub")
            body = b"\x00\x04pv/a" + b"x"
            pw.write(bytes([0x30, 0x80 | len(body), 0x00]) + body)
            await pw.drain()
            raw_first = await asyncio.wait_for(sr.readexactly(2), TIMEOUT)
            assert raw_first[1] == len(body)  # minimal single-byte varint
            rest = await asyncio.wait_for(sr.readexactly(raw_first[1]), TIMEOUT)
            pk = decode_packet(bytes(raw_first + rest), 4)
            assert pk.topic_name == "pv/a" and bytes(pk.payload) == b"x"
            await h.shutdown()

        run(scenario())


class TestMoreReferenceScenarios:
    def test_on_publish_reject_packet_silently_ignores(self):
        # server_test.go TestServerProcessPublishOnMessageRecvRejected:
        # ErrRejectPacket from on_publish drops the message with no error
        async def scenario():
            h = Harness()

            class Rejecter(Hook):
                def id(self):
                    return "rejector"

                def provides(self, b):
                    return b == ON_PUBLISH

                def on_publish(self, cl, pk):
                    if pk.topic_name.startswith("reject/"):
                        raise codes.ERR_REJECT_PACKET()
                    return pk

            h.server.add_hook(Rejecter())
            sr, sw, _ = await h.connect("rsub")
            sw.write(sub_packet(1, [Subscription(filter="#", qos=0)]))
            await sw.drain()
            await read_wire_packet(sr)
            pr, pw, _ = await h.connect("rpub")
            pw.write(pub_packet("reject/x", b"no"))
            pw.write(pub_packet("pass/x", b"yes"))
            pw.write(encode_packet(Packet(fixed_header=FixedHeader(type=PINGREQ))))
            await pw.drain()
            # publisher not disconnected (silent drop)
            assert (await read_wire_packet(pr)).fixed_header.type == PINGRESP
            out = await read_wire_packet(sr)
            assert out.topic_name == "pass/x"  # rejected one never delivered
            await h.shutdown()

        run(scenario())

    def test_server_close_fires_on_stopped_and_sets_done(self):
        # server_test.go TestServerClose
        async def scenario():
            h = Harness()
            stopped = []

            class StopWatch(Hook):
                def id(self):
                    return "stop-watch"

                def provides(self, b):
                    from mqtt_tpu.hooks import ON_STOPPED

                    return b == ON_STOPPED

                def on_stopped(self):
                    stopped.append(True)

            h.server.add_hook(StopWatch())
            r, w, task = await h.connect("closer")
            await h.server.close()
            assert stopped == [True]
            assert h.server.done.is_set()
            await h.shutdown()

        run(scenario())

    def test_sys_info_tick_republishes_uptime(self):
        # server_test.go TestServerEventLoop analog: the $SYS publication
        # refreshes uptime and fires the OnSysInfoTick hook
        async def scenario():
            h = Harness()
            ticks = []

            class TickWatch(Hook):
                def id(self):
                    return "tick-watch"

                def provides(self, b):
                    from mqtt_tpu.hooks import ON_SYS_INFO_TICK

                    return b == ON_SYS_INFO_TICK

                def on_sys_info_tick(self, info):
                    ticks.append(info.uptime)

            h.server.add_hook(TickWatch())
            # pretend 5s of uptime: rewind the MONOTONIC anchor (uptime is
            # clock-step immune now — rewinding wall-clock `started` would
            # not move it, by design; see system.Info.uptime_now)
            h.server.info._mono_started -= 5
            h.server.publish_sys_topics()
            assert ticks and ticks[0] >= 5
            msgs = {p.topic_name: p for p in h.server.topics.messages("$SYS/#")}
            assert int(bytes(msgs["$SYS/broker/uptime"].payload)) >= 5
            await h.shutdown()

        run(scenario())


class TestProtocolEdges:
    def test_subscribe_without_filters_is_protocol_violation(self):
        # server_test.go TestServerProcessPacketSubscribeInvalid
        async def scenario():
            h = Harness()
            r, w, task = await h.connect("nofilt", version=5)
            w.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=SUBSCRIBE, qos=1),
                        protocol_version=5,
                        packet_id=3,
                        filters=[],
                    )
                )
            )
            await w.drain()
            out = await read_wire_packet(r, 5)
            assert out.fixed_header.type == DISCONNECT  # [MQTT-3.10.3-2]
            await h.shutdown()

        run(scenario())

    def test_unsubscribe_without_filters_is_protocol_violation(self):
        # server_test.go TestServerProcessPacketUnsubscribeInvalid
        async def scenario():
            h = Harness()
            r, w, task = await h.connect("nounfilt", version=5)
            w.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=UNSUBSCRIBE, qos=1),
                        protocol_version=5,
                        packet_id=4,
                        filters=[],
                    )
                )
            )
            await w.drain()
            out = await read_wire_packet(r, 5)
            assert out.fixed_header.type == DISCONNECT
            await h.shutdown()

        run(scenario())

    def test_unsubscribe_nonexistent_filter_acks_no_subscription_existed(self):
        async def scenario():
            h = Harness()
            r, w, _ = await h.connect("unx", version=5)
            w.write(unsub_packet(5, ["never/was"], version=5))
            await w.drain()
            ack = await read_wire_packet(r, 5)
            assert ack.fixed_header.type == UNSUBACK
            assert ack.reason_codes[0] == codes.CODE_NO_SUBSCRIPTION_EXISTED.code
            await h.shutdown()

        run(scenario())

    def test_connack_advertises_reduced_maximum_qos(self):
        # SendConnack capability surface [MQTT-3.2.2-9]
        async def scenario():
            opts = Options(capabilities=Capabilities(maximum_qos=1))
            h = Harness(opts)
            reader, writer, task = await h.attach()
            writer.write(connect_packet("qcap", 5))
            await writer.drain()
            ack = await read_wire_packet(reader, 5)
            assert ack.fixed_header.type == CONNACK
            assert ack.properties.maximum_qos_flag
            assert ack.properties.maximum_qos == 1
            await h.shutdown()

        run(scenario())

    def test_inline_subscribe_invalid_filter_raises(self):
        from mqtt_tpu.packets import Code

        async def scenario():
            h = Harness()
            with pytest.raises(Code):
                h.server.subscribe("bad/#/deep", 1, lambda *a: None)
            with pytest.raises(Code):
                h.server.unsubscribe("bad/#/deep", 1)
            await h.shutdown()

        run(scenario())

    def test_serve_propagates_read_store_failure(self):
        # server_test.go TestServerServeReadStoreFailure
        async def scenario():
            h = Harness()

            class BadStore(Hook):
                def id(self):
                    return "bad-store"

                def provides(self, b):
                    from mqtt_tpu.hooks import STORED_CLIENTS

                    return b == STORED_CLIENTS

                def stored_clients(self):
                    raise RuntimeError("store corrupted")

            h.server.add_hook(BadStore())
            with pytest.raises(RuntimeError, match="store corrupted"):
                await h.server.serve()
            await h.shutdown()

        run(scenario())
