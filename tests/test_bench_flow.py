"""The bench artifact's tunnel-proof flow (VERDICT r4 item 2): with the
device unreachable, `python bench.py` must still emit a well-formed JSON
line carrying the broker and host-materializer configs plus an explicit
device_unreachable flag — never a bare zero headline with no explanation.

The probe subprocess genuinely HANGS in backend init here (the device
plugin ignores the bogus platform override and dials its dead transport),
so this exercises the production failure mode: the probe's watchdog kills
the hung child and the bench degrades gracefully. BENCH_PROBE_TIMEOUT
keeps the hang short for the suite."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_device_down_run_is_flagged_and_partial():
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="nonexistent-backend",  # probe subprocess fails fast
        BENCH_FAST="1",
        BENCH_CONFIGS="2,7",  # one device config (skipped) + one host config
        BENCH_PROBE_RETRIES="1",
        BENCH_PROBE_WAIT="1",
        BENCH_PROBE_TIMEOUT="20",  # the hang path, without 90s per probe
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True,
        timeout=420,
        env=env,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr.decode()[-800:]
    out = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    # the explicit flag replaces a silent zero headline
    assert out["device_unreachable"] is True
    assert "device_probe_error" in out
    # the device config was skipped, the host config still ran
    assert "2_1m_plus" not in out["configs"]
    cfg7 = out["configs"]["7_materializer_host"]
    assert cfg7["python_oracle_topics_per_sec"] > 0
    # the headline is SKIPPED (nothing e2e ran) — null value and
    # vs_baseline with an explicit reason, never a silent 0 that poisons
    # vs_baseline trend lines (ISSUE 11 satellite: the r05 artifact
    # published 0.0 for a run that never touched the device)
    assert out["value"] is None
    assert out["vs_baseline"] is None
    assert out["skipped"] is True
    assert "device unreachable" in out["skip_reason"]
