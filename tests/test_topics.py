"""Topic trie conformance — ports of the reference's oracle tables
(topics_test.go): the wildcard match matrix (TestSubscribersFind :590), the
multi-client merge scan (TestScanSubscribers :490), the inheritance bug-check
(:530), shared-group selection (:539-588), retained-message patterns (:640),
isolate_particle empty-level semantics (:452), and filter validity (:755).

These tables are the bit-identical oracle for the TPU matcher.
"""

import pytest

from mqtt_tpu.packets import FixedHeader, Packet, Subscription, PUBLISH
from mqtt_tpu.topics import (
    SHARE_PREFIX,
    InlineSubscription,
    SharedSubscriptions,
    Subscribers,
    TopicAliases,
    TopicsIndex,
    is_shared_filter,
    is_valid_filter,
    isolate_particle,
)


class TestIsolateParticle:
    def test_basic(self):
        assert isolate_particle("path/to/my/mqtt", 0) == ("path", True)
        assert isolate_particle("path/to/my/mqtt", 1) == ("to", True)
        assert isolate_particle("path/to/my/mqtt", 2) == ("my", True)
        assert isolate_particle("path/to/my/mqtt", 3) == ("mqtt", False)

    def test_empty_levels(self):
        assert isolate_particle("/path/", 0) == ("", True)
        assert isolate_particle("/path/", 1) == ("path", True)
        assert isolate_particle("/path/", 2) == ("", False)

    def test_wildcards(self):
        assert isolate_particle("a/b/c/+/+", 3) == ("+", True)
        assert isolate_particle("a/b/c/+/+", 4) == ("+", False)

    def test_clamps_past_end(self):
        assert isolate_particle("a/b", 5) == ("b", False)


class TestSubscribe:
    def test_new_and_existing(self):
        index = TopicsIndex()
        assert index.subscribe("cl1", Subscription(filter="a/b/c", qos=1))
        assert not index.subscribe("cl1", Subscription(filter="a/b/c", qos=2))
        assert index.subscribe("cl2", Subscription(filter="a/b/c"))

    def test_shared(self):
        index = TopicsIndex()
        assert index.subscribe("cl1", Subscription(filter=SHARE_PREFIX + "/grp/a/b"))
        assert not index.subscribe("cl1", Subscription(filter=SHARE_PREFIX + "/grp/a/b"))
        assert index.subscribe("cl2", Subscription(filter=SHARE_PREFIX + "/grp/a/b"))
        subs = index.subscribers("a/b")
        assert len(subs.shared) == 1

    def test_unsubscribe(self):
        index = TopicsIndex()
        index.subscribe("cl1", Subscription(filter="a/b/c"))
        index.subscribe("cl2", Subscription(filter="a/b/c"))
        assert index.unsubscribe("a/b/c", "cl1")
        assert not index.unsubscribe("d/e/f", "cl1")
        subs = index.subscribers("a/b/c")
        assert list(subs.subscriptions) == ["cl2"]

    def test_unsubscribe_no_cascade(self):
        index = TopicsIndex()
        index.subscribe("cl1", Subscription(filter="a/b/c/d"))
        index.subscribe("cl1", Subscription(filter="a/b"))
        assert index.unsubscribe("a/b/c/d", "cl1")
        subs = index.subscribers("a/b")
        assert len(subs.subscriptions) == 1

    def test_unsubscribe_shared(self):
        index = TopicsIndex()
        index.subscribe("cl1", Subscription(filter=SHARE_PREFIX + "/grp/a/b"))
        assert index.unsubscribe(SHARE_PREFIX + "/grp/a/b", "cl1")
        assert len(index.subscribers("a/b").shared) == 0


class TestRetainMessage:
    def _pk(self, topic, payload=b"hello"):
        return Packet(
            fixed_header=FixedHeader(type=PUBLISH, retain=True),
            topic_name=topic,
            payload=payload,
        )

    def test_add_clear(self):
        index = TopicsIndex()
        assert index.retain_message(self._pk("a/b/c")) == 1
        assert index.retain_message(self._pk("a/b/c")) == 1  # replace
        assert index.retain_message(self._pk("a/b/c", b"")) == -1  # clear
        assert index.retain_message(self._pk("a/b/c", b"")) == 0  # no-op clear
        assert len(index.retained) == 0


class TestScanSubscribers:
    def _index(self):
        index = TopicsIndex()
        index.subscribe("cl1", Subscription(qos=1, filter="a/b/c", identifier=22))
        index.subscribe("cl1", Subscription(qos=1, filter="a/b/c/d/e/f"))
        index.subscribe("cl1", Subscription(qos=2, filter="a/b/c/d/+/f"))
        index.subscribe("cl2", Subscription(qos=0, filter="a/#"))
        index.subscribe("cl2", Subscription(qos=1, filter="a/b/c"))
        index.subscribe("cl2", Subscription(qos=2, filter="a/b/+", identifier=77))
        index.subscribe("cl2", Subscription(qos=2, filter="d/e/f", identifier=7237))
        index.subscribe("cl2", Subscription(qos=2, filter="$SYS/uptime", identifier=3))
        index.subscribe("cl3", Subscription(qos=1, filter="+/b", identifier=234))
        index.subscribe("cl4", Subscription(qos=0, filter="#", identifier=5))
        index.subscribe("cl2", Subscription(qos=0, filter="$SYS/test", identifier=2))
        return index

    def test_multi_client_merge(self):
        subs = self._index().subscribers("a/b/c")
        assert set(subs.subscriptions) == {"cl1", "cl2", "cl4"}
        assert subs.subscriptions["cl1"].qos == 1
        assert subs.subscriptions["cl2"].qos == 2
        assert subs.subscriptions["cl4"].qos == 0
        assert subs.subscriptions["cl1"].identifiers["a/b/c"] == 22
        # Go map zero-value semantics: absent-or-zero both read as 0
        assert subs.subscriptions["cl2"].identifiers.get("a/#", 0) == 0
        assert subs.subscriptions["cl2"].identifiers["a/b/+"] == 77
        assert subs.subscriptions["cl2"].identifiers.get("a/b/c", 0) == 0
        assert subs.subscriptions["cl4"].identifiers["#"] == 5

    def test_hash_only(self):
        subs = self._index().subscribers("d/e/f/g")
        assert set(subs.subscriptions) == {"cl4"}
        assert subs.subscriptions["cl4"].qos == 0
        assert subs.subscriptions["cl4"].identifiers["#"] == 5

    def test_empty_topic(self):
        assert len(self._index().subscribers("").subscriptions) == 0

    def test_topic_inheritance_bug(self):
        # a/b must NOT match a/b/c (topics_test.go:530)
        index = TopicsIndex()
        index.subscribe("cl1", Subscription(qos=0, filter="a/b/c"))
        index.subscribe("cl2", Subscription(qos=0, filter="a/b"))
        subs = index.subscribers("a/b/c")
        assert len(subs.subscriptions) == 1


class TestSharedScan:
    def test_groups_matched(self):
        index = TopicsIndex()
        index.subscribe("cl1", Subscription(qos=1, filter=SHARE_PREFIX + "/tmp/a/b/c", identifier=111))
        index.subscribe("cl2", Subscription(qos=0, filter=SHARE_PREFIX + "/tmp/a/b/c", identifier=112))
        index.subscribe("cl3", Subscription(qos=0, filter=SHARE_PREFIX + "/tmp2/a/b/c", identifier=113))
        index.subscribe("cl2", Subscription(qos=0, filter=SHARE_PREFIX + "/tmp/a/b/+", identifier=10))
        index.subscribe("cl3", Subscription(qos=1, filter=SHARE_PREFIX + "/tmp/a/b/+", identifier=200))
        index.subscribe("cl4", Subscription(qos=0, filter=SHARE_PREFIX + "/tmp/a/b/+", identifier=201))
        index.subscribe("cl5", Subscription(qos=0, filter=SHARE_PREFIX + "/tmp/a/b/c/#"))
        subs = index.subscribers("a/b/c")
        assert len(subs.shared) == 4

    def test_select_shared(self):
        index = TopicsIndex()
        index.subscribe("cl1", Subscription(qos=1, filter=SHARE_PREFIX + "/tmp/a/b/c", identifier=110))
        index.subscribe("cl1b", Subscription(qos=0, filter=SHARE_PREFIX + "/tmp/a/b/c", identifier=111))
        index.subscribe("cl2", Subscription(qos=0, filter=SHARE_PREFIX + "/tmp/a/b/c", identifier=112))
        index.subscribe("cl3", Subscription(qos=0, filter=SHARE_PREFIX + "/tmp2/a/b/c", identifier=113))
        subs = index.subscribers("a/b/c")
        assert len(subs.shared) == 2
        assert SHARE_PREFIX + "/tmp/a/b/c" in subs.shared
        assert SHARE_PREFIX + "/tmp2/a/b/c" in subs.shared
        assert len(subs.shared[SHARE_PREFIX + "/tmp/a/b/c"]) == 3
        assert len(subs.shared[SHARE_PREFIX + "/tmp2/a/b/c"]) == 1
        subs.select_shared()
        assert len(subs.shared_selected) == 2

    def test_merge_shared_selected(self):
        s = Subscribers()
        s.shared_selected = {
            "cl1": Subscription(qos=1, filter=SHARE_PREFIX + "/tmp/a/b/c", identifier=110),
            "cl2": Subscription(qos=1, filter=SHARE_PREFIX + "/tmp2/a/b/c", identifier=111),
        }
        s.subscriptions = {
            "cl2": Subscription(qos=1, filter="a/b/c", identifier=112),
        }
        s.merge_shared_selected()
        assert set(s.subscriptions) == {"cl1", "cl2"}
        assert s.subscriptions["cl2"].identifiers == {
            SHARE_PREFIX + "/tmp2/a/b/c": 111,
            "a/b/c": 112,
        }


# the wildcard match matrix from topics_test.go:590-627
FIND_MATRIX = [
    ("a", "a", True),
    ("a/", "a", False),
    ("a/", "a/", True),
    ("/a", "/a", True),
    ("path/to/my/mqtt", "path/to/my/mqtt", True),
    ("path/to/+/mqtt", "path/to/my/mqtt", True),
    ("+/to/+/mqtt", "path/to/my/mqtt", True),
    ("#", "path/to/my/mqtt", True),
    ("+/+/+/+", "path/to/my/mqtt", True),
    ("+/+/+/#", "path/to/my/mqtt", True),
    ("zen/#", "zen", True),  # as per 4.7.1.2
    ("trailing-end/#", "trailing-end/", True),
    ("+/prefixed", "/prefixed", True),
    ("+/+/#", "path/to/my/mqtt", True),
    ("path/to/", "path/to/my/mqtt", False),
    ("#/stuff", "path/to/my/mqtt", False),
    ("#", "$SYS/info", False),
    ("$SYS/#", "$SYS/info", True),
    ("+/info", "$SYS/info", False),
]


@pytest.mark.parametrize("filter_,topic,matched", FIND_MATRIX, ids=[f"{f}~{t}" for f, t, _ in FIND_MATRIX])
def test_subscribers_find(filter_, topic, matched):
    index = TopicsIndex()
    index.subscribe("cl1", Subscription(filter=filter_))
    subs = index.subscribers(topic)
    assert (len(subs.subscriptions) == 1) == matched


# the retained-message pattern matrix from topics_test.go:640-686
RETAINED_TOPICS = [
    "$SYS/uptime",
    "$SYS/info",
    "a/b/c/d",
    "a/b/c/e",
    "a/b/d/f",
    "q/w/e/r/t/y",
    "q/x/e/r/t/o",
    "asdf",
]

MESSAGES_MATRIX = [
    ("a/b/c/d", 1),
    ("$SYS/+", 2),
    ("$SYS/#", 2),
    ("#", 6),
    ("a/b/c/+", 2),
    ("a/+/c/+", 2),
    ("+/+/+/d", 1),
    ("q/w/e/#", 1),
    ("+/+/+/+", 3),
    ("q/#", 2),
    ("asdf", 1),
    ("", 0),
]


@pytest.mark.parametrize("filter_,expected", MESSAGES_MATRIX, ids=[f or "(empty)" for f, _ in MESSAGES_MATRIX])
def test_messages_pattern(filter_, expected):
    index = TopicsIndex()
    for topic in RETAINED_TOPICS:
        index.retain_message(
            Packet(
                fixed_header=FixedHeader(type=PUBLISH, retain=True),
                topic_name=topic,
                payload=b"hello",
            )
        )
    assert len(index.messages(filter_)) == expected


class TestIsValidFilter:
    def test_subscribe_filters(self):
        assert is_valid_filter("a/b/c", False)
        assert is_valid_filter("a/b//c", False)
        assert is_valid_filter("$SYS", False)
        assert is_valid_filter("$SYS/info", False)
        assert is_valid_filter("$sys/info", False)
        assert is_valid_filter("+/info", False)
        assert is_valid_filter("#", False)
        assert not is_valid_filter("", False)  # [MQTT-4.7.3-1]
        assert not is_valid_filter("a/#/c", False)  # [MQTT-4.7.1-2]
        assert not is_valid_filter("#/", False)
        assert not is_valid_filter(SHARE_PREFIX, False)  # [MQTT-4.8.2-1]
        assert not is_valid_filter(SHARE_PREFIX + "/grp", False)
        assert not is_valid_filter(SHARE_PREFIX + "/gr+p/a", False)  # [MQTT-4.8.2-2]
        assert is_valid_filter(SHARE_PREFIX + "/grp/a/b", False)
        assert is_valid_filter("$share/grp/a/b", False)  # case-insensitive prefix

    def test_publish_topics(self):
        assert is_valid_filter("a/b/c", True)
        assert not is_valid_filter("$SYS/info", True)  # 4.7.2 unpublishable
        assert not is_valid_filter("$sys/info", True)
        assert not is_valid_filter("a/+/c", True)  # [MQTT-3.3.2-2]
        assert not is_valid_filter("a/#", True)
        assert is_valid_filter("", True)  # alias may supply the topic


class TestIsSharedFilter:
    def test(self):
        assert is_shared_filter(SHARE_PREFIX + "/grp/a")
        assert is_shared_filter("$share/grp/a")
        assert not is_shared_filter("a/b/c")


class TestTopicAliases:
    def test_inbound(self):
        a = TopicAliases(5).inbound
        assert a.set(1, "a/b") == "a/b"
        assert a.set(1, "") == "a/b"  # empty topic resolves existing alias
        assert a.set(1, "c/d") == "c/d"

    def test_inbound_max_zero(self):
        a = TopicAliases(0).inbound
        assert a.set(1, "a/b") == "a/b"
        assert a.internal == {}

    def test_outbound(self):
        a = TopicAliases(2).outbound
        assert a.set("a/b") == (1, False)
        assert a.set("a/b") == (1, True)
        assert a.set("c/d") == (2, False)
        assert a.set("e/f") == (0, False)  # exhausted

    def test_outbound_max_zero(self):
        a = TopicAliases(0).outbound
        assert a.set("a/b") == (0, False)


class TestInlineSubscriptions:
    def test_subscribe_match_unsubscribe(self):
        calls = []

        def handler(cl, sub, pk):
            calls.append((sub.filter, pk.topic_name))

        index = TopicsIndex()
        assert index.inline_subscribe(InlineSubscription(filter="a/+", identifier=1, handler=handler))
        assert not index.inline_subscribe(InlineSubscription(filter="a/+", identifier=1, handler=handler))
        subs = index.subscribers("a/b")
        assert len(subs.inline_subscriptions) == 1
        assert index.inline_unsubscribe(1, "a/+")
        assert not index.inline_unsubscribe(9, "x/y")
        assert len(index.subscribers("a/b").inline_subscriptions) == 0

    def test_inline_hash_quirk(self):
        # reference quirk (topics.go:615): an inline sub on a/# does NOT
        # match topic "a" via the terminal child-# branch
        index = TopicsIndex()
        index.inline_subscribe(InlineSubscription(filter="a/#", identifier=1, handler=lambda *a: None))
        assert len(index.subscribers("a").inline_subscriptions) == 0
        assert len(index.subscribers("a/b").inline_subscriptions) == 1


class TestSharedContainers:
    def test_shared_subscriptions(self):
        s = SharedSubscriptions()
        s.add("grp", "cl1", Subscription(filter="a"))
        s.add("grp", "cl2", Subscription(filter="a"))
        s.add("grp2", "cl1", Subscription(filter="a"))
        assert s.group_len() == 2
        assert len(s) == 3
        assert s.get("grp", "cl1") is not None
        s.delete("grp", "cl1")
        s.delete("grp", "cl2")
        assert s.group_len() == 1  # empty group pruned


def reference_match(flt: str, topic: str) -> bool:
    """Independent closed-form matcher encoding the REFERENCE's semantics
    (not the pure spec): '#' matches its parent level only when the level
    before '#' is a literal (topics.go:612 partKey != "+"), and top-level
    +/# filters never match $-topics. Used as a differential oracle."""
    if not topic:
        return False
    F, T = flt.split("/"), topic.split("/")
    if topic[0] == "$" and flt and flt[0] in "+#":
        return False
    if F[-1] == "#":
        P = F[:-1]
        if len(T) < len(P):
            return False
        if any(p != "+" and p != t for p, t in zip(P, T)):
            return False
        if len(T) > len(P):
            return True
        return len(P) == 0 or P[-1] != "+"
    return len(F) == len(T) and all(p == "+" or p == t for p, t in zip(F, T))


class TestDifferentialFuzz:
    """Seeded randomized parity between the trie walk and the closed-form
    oracle — the same harness later validates the TPU matcher."""

    def test_trie_matches_oracle(self):
        import random

        rng = random.Random(1234)
        segs = ["a", "b", "c", "dd", "", "x", "$SYS"]

        def rand_topic():
            return "/".join(rng.choice(segs) for _ in range(rng.randint(1, 4)))

        def rand_filter():
            parts = [rng.choice(segs + ["+"]) for _ in range(rng.randint(1, 4))]
            if rng.random() < 0.3:
                parts[-1] = "#"
            return "/".join(parts)

        index = TopicsIndex()
        filters = {}
        for i in range(300):
            flt = rand_filter()
            filters[f"cl{i}"] = flt
            index.subscribe(f"cl{i}", Subscription(filter=flt, qos=rng.randint(0, 2)))
        for _ in range(1500):
            topic = rand_topic()
            got = set(index.subscribers(topic).subscriptions)
            want = {cl for cl, flt in filters.items() if reference_match(flt, topic)}
            assert got == want, f"topic={topic!r} extra={got - want} missing={want - got}"
        # churn: remove half, parity must hold and empty nodes must trim
        for i in range(0, 300, 2):
            index.unsubscribe(filters[f"cl{i}"], f"cl{i}")
        for _ in range(500):
            topic = rand_topic()
            got = set(index.subscribers(topic).subscriptions)
            want = {
                f"cl{i}" for i in range(1, 300, 2) if reference_match(filters[f"cl{i}"], topic)
            }
            assert got == want


class TestRetainAndTrim:
    """Trie retain bookkeeping + pruning (topics.go:453-522)."""

    def _pub(self, topic, payload=b"x", retain=True):
        return Packet(
            fixed_header=FixedHeader(type=PUBLISH, retain=retain),
            topic_name=topic,
            payload=payload,
        )

    def test_retain_message_return_codes(self):
        idx = TopicsIndex()
        assert idx.retain_message(self._pub("a/b")) == 1  # new
        assert idx.retain_message(self._pub("a/b", b"y")) == 1  # replace
        assert idx.retain_message(self._pub("a/b", b"")) == -1  # clear
        assert idx.retain_message(self._pub("a/b", b"")) == 0  # nothing
        assert idx.retained.get("a/b") is None

    def test_unsubscribe_trims_empty_particles(self):
        idx = TopicsIndex()
        idx.subscribe("c1", Subscription(filter="deep/ly/nested/leaf"))
        assert "deep" in idx.root.particles
        assert idx.unsubscribe("deep/ly/nested/leaf", "c1")
        assert "deep" not in idx.root.particles  # chain pruned to root

    def test_trim_stops_at_retained_path(self):
        idx = TopicsIndex()
        idx.retain_message(self._pub("keep/me"))
        idx.subscribe("c1", Subscription(filter="keep/me/deeper"))
        idx.unsubscribe("keep/me/deeper", "c1")
        # 'keep/me' survives (it anchors a retained message) but 'deeper'
        # is pruned
        assert "keep" in idx.root.particles
        assert "deeper" not in idx.root.particles["keep"].particles["me"].particles
        assert len(list(idx.messages("keep/#"))) == 1

    def test_trim_stops_at_shared_subscription(self):
        idx = TopicsIndex()
        idx.subscribe("m1", Subscription(filter="$share/g/t/x"))
        idx.subscribe("c1", Subscription(filter="t/x/y"))
        idx.unsubscribe("t/x/y", "c1")
        assert idx.subscribers("t/x").shared  # shared branch untouched

    def test_clear_retained_under_subscription_keeps_node(self):
        idx = TopicsIndex()
        idx.subscribe("c1", Subscription(filter="r/t"))
        idx.retain_message(self._pub("r/t"))
        idx.retain_message(self._pub("r/t", b""))  # clear
        assert "r" in idx.root.particles  # subscription anchors the node
        assert len(idx.subscribers("r/t").subscriptions) == 1

    def test_messages_skips_sys_for_top_level_wildcards(self):
        idx = TopicsIndex()
        idx.retain_message(self._pub("$SYS/broker/uptime", b"1"))
        idx.retain_message(self._pub("normal/topic", b"2"))
        assert [p.topic_name for p in idx.messages("#")] == ["normal/topic"]
        assert [p.topic_name for p in idx.messages("+/broker/uptime")] == []
        assert [p.topic_name for p in idx.messages("$SYS/#")] == ["$SYS/broker/uptime"]
