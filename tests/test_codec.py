"""Primitive codec conformance: mirrors the reference's codec tests
(packets/codec_test.go) — offsets, errors, varint bounds, UTF-8 rules."""

import pytest

from mqtt_tpu.packets import codec
from mqtt_tpu.packets.codes import (
    ERR_MALFORMED_INVALID_UTF8,
    ERR_MALFORMED_OFFSET_BOOL_OUT_OF_RANGE,
    ERR_MALFORMED_OFFSET_BYTE_OUT_OF_RANGE,
    ERR_MALFORMED_OFFSET_BYTES_OUT_OF_RANGE,
    ERR_MALFORMED_OFFSET_UINT_OUT_OF_RANGE,
    ERR_MALFORMED_VARIABLE_BYTE_INTEGER,
)


class TestUint:
    def test_decode_uint16(self):
        assert codec.decode_uint16(b"\x00\x7b\xff", 0) == (123, 2)
        assert codec.decode_uint16(b"\xff\x01\xc8", 1) == (456, 3)

    def test_decode_uint16_underflow(self):
        with pytest.raises(type(ERR_MALFORMED_OFFSET_UINT_OUT_OF_RANGE)) as e:
            codec.decode_uint16(b"\x01", 0)
        assert e.value == ERR_MALFORMED_OFFSET_UINT_OUT_OF_RANGE

    def test_decode_uint32(self):
        assert codec.decode_uint32(b"\x00\x00\x00\x7b", 0) == (123, 4)
        assert codec.decode_uint32(b"\x00\x00\x01\xc8\x27", 0) == (456, 4)

    def test_decode_uint32_underflow(self):
        with pytest.raises(Exception) as e:
            codec.decode_uint32(b"\x01\x02\x03", 0)
        assert e.value == ERR_MALFORMED_OFFSET_UINT_OUT_OF_RANGE

    def test_roundtrip(self):
        assert codec.encode_uint16(123) == b"\x00\x7b"
        assert codec.encode_uint32(70000) == b"\x00\x01\x11\x70"


class TestStringsBytes:
    def test_decode_string(self):
        assert codec.decode_string(b"\x00\x03\x61\x2f\x62", 0) == ("a/b", 5)

    def test_decode_string_invalid_utf8(self):
        with pytest.raises(Exception) as e:
            codec.decode_string(b"\x00\x02\xff\xfe", 0)
        assert e.value == ERR_MALFORMED_INVALID_UTF8

    def test_decode_string_rejects_nul(self):
        # [MQTT-1.5.4-2]
        with pytest.raises(Exception) as e:
            codec.decode_string(b"\x00\x03a\x00b", 0)
        assert e.value == ERR_MALFORMED_INVALID_UTF8

    def test_decode_bytes(self):
        assert codec.decode_bytes(b"\x00\x02\xde\xad\xbe", 0) == (b"\xde\xad", 4)

    def test_decode_bytes_overflow(self):
        with pytest.raises(Exception) as e:
            codec.decode_bytes(b"\x00\x05\x01", 0)
        assert e.value == ERR_MALFORMED_OFFSET_BYTES_OUT_OF_RANGE

    def test_decode_byte(self):
        assert codec.decode_byte(b"\x07", 0) == (7, 1)
        with pytest.raises(Exception) as e:
            codec.decode_byte(b"", 0)
        assert e.value == ERR_MALFORMED_OFFSET_BYTE_OUT_OF_RANGE

    def test_decode_byte_bool(self):
        assert codec.decode_byte_bool(b"\x01", 0) == (True, 1)
        assert codec.decode_byte_bool(b"\x00", 0) == (False, 1)
        with pytest.raises(Exception) as e:
            codec.decode_byte_bool(b"", 0)
        assert e.value == ERR_MALFORMED_OFFSET_BOOL_OUT_OF_RANGE

    def test_encode_string(self):
        assert codec.encode_string("a/b") == b"\x00\x03a/b"
        assert codec.encode_string("") == b"\x00\x00"

    def test_encode_bytes(self):
        assert codec.encode_bytes(b"\x01\x02") == b"\x00\x02\x01\x02"


class TestVarint:
    @pytest.mark.parametrize(
        "value,encoded",
        [
            (0, b"\x00"),
            (127, b"\x7f"),
            (128, b"\x80\x01"),
            (16383, b"\xff\x7f"),
            (16384, b"\x80\x80\x01"),
            (2097151, b"\xff\xff\x7f"),
            (2097152, b"\x80\x80\x80\x01"),
            (268435455, b"\xff\xff\xff\x7f"),
        ],
    )
    def test_roundtrip(self, value, encoded):
        out = bytearray()
        codec.encode_length(out, value)
        assert bytes(out) == encoded
        assert codec.decode_length(encoded, 0) == (value, len(encoded))

    def test_decode_overflow(self):
        with pytest.raises(Exception) as e:
            codec.decode_length(b"\xff\xff\xff\xff\x7f", 0)
        assert e.value == ERR_MALFORMED_VARIABLE_BYTE_INTEGER

    def test_decode_truncated(self):
        with pytest.raises(Exception) as e:
            codec.decode_length(b"\x80", 0)
        assert e.value == ERR_MALFORMED_VARIABLE_BYTE_INTEGER


class TestValidUtf8:
    def test_valid(self):
        assert codec.valid_utf8(b"hello")
        assert codec.valid_utf8("héllo".encode())
        assert codec.valid_utf8(b"")

    def test_invalid(self):
        assert not codec.valid_utf8(b"\xff\xfe")
        assert not codec.valid_utf8(b"a\x00b")
