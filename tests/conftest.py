"""Test configuration.

Forces JAX onto the host CPU platform with 8 virtual devices BEFORE any test
imports jax, so multi-chip sharding tests (mqtt_tpu.parallel) compile and run
without TPU hardware. Benchmarks (bench.py) run outside pytest and use the
real device.
"""

import os

# Force CPU even when the environment preselects a TPU platform — tests
# must run on the virtual 8-device mesh. jax may already be imported by a
# site hook, so set the config directly too (the backend initializes
# lazily, so this still applies).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Lock-order witness (ISSUE 10): armed for the WHOLE session so every
# named-lock acquisition any test provokes feeds the process-wide edge
# set. tests/test_zz_lockwitness.py (named to sort last under
# -p no:randomly) asserts the accumulated edges all appear in the
# statically extracted lock graph — an unexplained runtime edge is an
# extraction gap and fails tier-1. Cost: a disarmed-stats acquire grows
# by one held-stack append/pop and one dict probe per held lock.
from mqtt_tpu.utils.locked import DEFAULT_PLANE  # noqa: E402

DEFAULT_PLANE.arm_witness()

# Loop-affinity witness (ISSUE 19): same contract as the lock witness —
# recording (non-raising) for the whole session, so every instrumented
# affinity seam any test traverses feeds the process-wide (kind, seam)
# set. tests/test_zz_loopwitness.py asserts observed ⊆ the blessed
# LOOP_AFFINITY table (tools/brokerlint/loopgraph.py) and that zero
# guarded touches ran off their owning loop. Disarmed cost at every
# touch point: one plane-flag read + branch (bench cfg 8).
from mqtt_tpu.utils.loopwitness import DEFAULT_LOOP_PLANE  # noqa: E402

DEFAULT_LOOP_PLANE.arm_witness()
