"""Test configuration.

Forces JAX onto the host CPU platform with 8 virtual devices BEFORE any test
imports jax, so multi-chip sharding tests (mqtt_tpu.parallel) compile and run
without TPU hardware. Benchmarks (bench.py) run outside pytest and use the
real device.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
