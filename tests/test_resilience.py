"""Chaos suite for the degradation manager (mqtt_tpu.resilience) and the
worker-mesh link hardening (mqtt_tpu.cluster), driven by the seeded fault
injector (mqtt_tpu.faults).

Covers: breaker state machine + backoff determinism, the guard pool's
wedged-worker accounting, every injectable fault class (hang / error /
issue_error / corrupt / slow) resolving bit-identical to the host-trie
oracle within the watchdog budget, automatic half-open recovery, the
end-to-end staged broker under seeded chaos with $SYS gauge assertions,
and mesh peer-link kill/stall with reconnect + presence resync.
"""

import asyncio
import random
import threading
import time

import pytest

from mqtt_tpu import Options, Server
from mqtt_tpu.faults import (
    CHAOS_CLIENT,
    FaultPlan,
    FaultyMatcher,
    sever_peer_link,
)
from mqtt_tpu.hooks.chaos import ChaosHook, ChaosOptions
from mqtt_tpu.ops.matcher import subscribers_equal
from mqtt_tpu.packets import PUBLISH, SUBACK, Subscription
from mqtt_tpu.resilience import (
    CLOSED,
    OPEN,
    Backoff,
    BreakerConfig,
    CircuitBreaker,
    GuardPool,
    GuardTimeout,
    ResilientMatcher,
)
from mqtt_tpu.topics import SYS_PREFIX, Subscribers, TopicsIndex

from tests.test_server import (
    Harness,
    pub_packet,
    read_wire_packet,
    run,
    sub_packet,
)


class HostBatchMatcher:
    """A 'device' matcher that actually walks the host trie — the perfect
    substrate for fault injection: healthy dispatches are bit-identical
    to the oracle by construction, so any divergence IS the fault."""

    def __init__(self, index: TopicsIndex) -> None:
        self.index = index
        self.dispatches = 0

    def match_topics_async(self, topics):
        self.dispatches += 1
        index = self.index

        def resolve():
            return [
                index.subscribers(t) if t else Subscribers() for t in topics
            ]

        return resolve

    def close(self) -> None:
        pass


def small_index() -> TopicsIndex:
    ti = TopicsIndex()
    ti.subscribe("alice", Subscription(filter="a/+", qos=1))
    ti.subscribe("bob", Subscription(filter="a/b"))
    ti.subscribe("carol", Subscription(filter="c/#"))
    return ti


def fast_config(**kw) -> BreakerConfig:
    kw.setdefault("failure_threshold", 3)
    kw.setdefault("watchdog_s", 0.2)
    kw.setdefault("probe_backoff_s", 0.03)
    kw.setdefault("probe_backoff_max_s", 0.2)
    kw.setdefault("probe_jitter", 0.0)
    kw.setdefault("probe_successes", 1)
    kw.setdefault("verify_sample", 8)
    kw.setdefault("seed", 7)
    return BreakerConfig(**kw)


def oracle(ti, topics):
    return [ti.subscribers(t) if t else Subscribers() for t in topics]


def assert_oracle(ti, topics, results):
    want = oracle(ti, topics)
    assert len(results) == len(want)
    for r, w in zip(results, want):
        assert subscribers_equal(r, w)


# -- unit: backoff + breaker state machine ----------------------------------


class TestBackoff:
    def test_deterministic_growth_and_cap(self):
        a = Backoff(initial=0.1, maximum=1.0, jitter=0.2, seed=42)
        b = Backoff(initial=0.1, maximum=1.0, jitter=0.2, seed=42)
        seq_a = [a.next() for _ in range(8)]
        seq_b = [b.next() for _ in range(8)]
        assert seq_a == seq_b  # same seed, same schedule
        # grows geometrically and respects the cap (+20% jitter headroom)
        assert seq_a[0] < seq_a[2] < seq_a[4]
        assert all(d <= 1.0 * 1.2 + 1e-9 for d in seq_a)
        a.reset()
        assert a.next() <= 0.1 * 1.2 + 1e-9

    def test_huge_attempt_counts_do_not_overflow(self):
        """Regression: factor**attempts overflowed a float before min()
        could cap it, killing the re-dial loop after a ~day-long outage."""
        a = Backoff(initial=0.05, maximum=2.0, jitter=0.0)
        for _ in range(1200):
            assert a.next() <= 2.0

    def test_jitter_desyncs_seeds(self):
        seqs = {
            tuple(round(Backoff(0.1, 1.0, seed=s).next(), 6) for _ in range(4))
            for s in range(5)
        }
        assert len(seqs) > 1  # different seeds do not re-dial in lockstep


class TestCircuitBreaker:
    def make(self, **kw):
        t = [0.0]
        kw.setdefault("backoff", Backoff(initial=1.0, maximum=8.0, jitter=0.0))
        br = CircuitBreaker(clock=lambda: t[0], **kw)
        return br, t

    def test_trips_after_consecutive_failures_only(self):
        br, _ = self.make(failure_threshold=3)
        br.record_failure()
        br.record_failure()
        br.record_success()  # success resets the consecutive count
        br.record_failure()
        br.record_failure()
        assert br.state == CLOSED and br.allow()
        br.record_failure()
        assert br.state == OPEN and not br.allow()
        assert br.trips == 1

    def test_half_open_probe_cycle_and_backoff_growth(self):
        br, t = self.make(failure_threshold=1, probe_successes=2)
        br.record_failure("hang")
        assert br.state == OPEN
        assert not br.acquire_probe()  # backoff (1.0s) not elapsed
        delay1 = br.seconds_until_probe()
        t[0] = 1.5
        assert br.acquire_probe()
        assert br.state == "half_open"
        br.record_probe_failure("error")  # probe failed: re-open + backoff
        assert br.state == OPEN
        assert br.seconds_until_probe() > delay1  # 2.0s > 1.0s
        t[0] = 10.0
        assert br.acquire_probe()
        br.record_probe_success()  # 1 of 2: fast-follow probe, still open
        assert br.state == OPEN
        t[0] = 20.0
        assert br.acquire_probe()
        br.record_probe_success()
        assert br.state == CLOSED and br.allow()
        d = br.as_dict()
        assert d["trips"] == 2 and d["probes"] == 3
        assert d["failures_hang"] == 1 and d["failures_error"] == 1

    def test_single_probe_slot(self):
        br, t = self.make(failure_threshold=1)
        br.record_failure()
        t[0] = 5.0
        assert br.acquire_probe()
        assert not br.acquire_probe()  # slot already claimed
        assert br.acquire_probe(force=True)  # tests/ops override

    def test_stale_live_outcomes_cannot_claim_the_probe_slot(self):
        """A batch issued before the trip resolving during HALF_OPEN must
        not count as the probe's outcome in either direction."""
        br, t = self.make(failure_threshold=1, probe_successes=1)
        br.record_failure()
        t[0] = 5.0
        assert br.acquire_probe()  # HALF_OPEN, slot held
        br.record_success()  # stale live batch resolves fine...
        assert br.state == "half_open"  # ...but the breaker stays probing
        assert not br.acquire_probe()  # and the slot stays claimed
        br.record_failure("hang")  # stale live failure mid-probe
        assert br.state == "half_open"  # no spurious re-trip
        assert br.probe_failures == 0
        br.record_probe_success()  # only the probe's verdict closes it
        assert br.state == CLOSED


class TestGuardPool:
    def test_hang_is_abandoned_and_capacity_recovers(self):
        pool = GuardPool(workers=1)
        release = threading.Event()
        task = pool.submit(lambda: (release.wait(5), "late")[1])
        with pytest.raises(GuardTimeout):
            task.wait(0.05)
        pool.report_wedged(task)  # spawns the substitute worker
        assert pool.wedged == 1
        # the substitute serves new work while the first call is wedged
        assert pool.submit(lambda: "fresh").wait(2) == "fresh"
        release.set()  # the hung call returns; its worker retires
        deadline = time.monotonic() + 2
        while pool.wedged and time.monotonic() < deadline:
            time.sleep(0.01)
        assert pool.wedged == 0
        pool.close()

    def test_completion_racing_the_wedge_report_is_not_counted(self):
        """Regression: a call finishing between the GuardTimeout raise
        and report_wedged must not skew the wedge count negative or
        spawn a spurious replacement."""
        pool = GuardPool(workers=1)
        release = threading.Event()
        task = pool.submit(lambda: (release.wait(5), "late")[1])
        with pytest.raises(GuardTimeout):
            task.wait(0.05)
        release.set()  # completes BEFORE the caller reports the wedge
        task._done.wait(2)
        pool.report_wedged(task)
        assert pool.wedged == 0  # not a wedge: nothing counted
        assert pool.submit(lambda: "still-served").wait(2) == "still-served"
        assert pool.live_unwedged == 1  # and no spurious extra worker
        pool.close()

    def test_wedges_past_the_cap_bound_threads_and_recover(self):
        """Regression: past MAX_WEDGED the pool stopped spawning while
        abandoned workers still retired, so capacity bled to zero with
        no recovery path. Now thread growth is hard-bounded AND capacity
        returns once hung calls come back (workers beyond the spawn cap
        keep serving instead of retiring)."""
        pool = GuardPool(workers=1)
        pool.MAX_WEDGED = 2  # shrink the cap for the test
        releases = []
        for _ in range(4):  # wedge past the cap
            ev = threading.Event()
            releases.append(ev)
            task = pool.submit(lambda ev=ev: ev.wait(10))
            with pytest.raises(GuardTimeout):
                task.wait(0.1)
            pool.report_wedged(task)
        assert pool.wedged == 4
        # bounded: 1 original + MAX_WEDGED replacements, all now stuck
        # (the 4th 'wedge' is a queued abandon) — the probe path reads
        # this and stops burning threads
        assert pool.live_unwedged <= 0
        for ev in releases:  # the 'link heals': hung calls return
            ev.set()
        deadline = time.monotonic() + 3
        while pool.wedged and time.monotonic() < deadline:
            time.sleep(0.01)
        assert pool.wedged == 0
        # capacity recovered without ever exceeding the thread bound
        assert pool.submit(lambda: "after").wait(2) == "after"
        assert pool.live_unwedged >= 1
        pool.close()

    def test_exceptions_ferry_to_the_waiter(self):
        pool = GuardPool(workers=1)
        task = pool.submit(lambda: 1 / 0)
        with pytest.raises(ZeroDivisionError):
            task.wait(2)
        pool.close()


# -- unit: the degradation manager over injected faults ----------------------


class TestResilientMatcherFaults:
    TOPICS = ["a/b", "a/x", "c/d/e", "nope"]

    def build(self, plan: FaultPlan, **cfg):
        ti = small_index()
        inner = HostBatchMatcher(ti)
        faulty = FaultyMatcher(inner, plan)
        rm = ResilientMatcher(faulty, ti, fast_config(**cfg))
        return ti, inner, faulty, rm

    def test_dispatch_error_falls_back_and_trips(self):
        ti, inner, faulty, rm = self.build(
            FaultPlan(at={0: "error", 1: "error", 2: "error"})
        )
        try:
            for _ in range(3):
                assert_oracle(ti, self.TOPICS, rm.match_topics(self.TOPICS))
            assert rm.breaker.state == OPEN
            assert rm.breaker.failure_kinds.get("error") == 3
            # OPEN: matching never touches the device (host route only)
            seen = inner.dispatches
            assert_oracle(ti, self.TOPICS, rm.match_topics(self.TOPICS))
            assert inner.dispatches == seen
            assert rm.fallback_batches >= 1
        finally:
            rm.close()

    def test_issue_error_is_survived(self):
        ti, _inner, _faulty, rm = self.build(
            FaultPlan(at={0: "issue_error"}), failure_threshold=1
        )
        try:
            assert_oracle(ti, self.TOPICS, rm.match_topics(self.TOPICS))
            assert rm.breaker.state == OPEN
        finally:
            rm.close()

    def test_hang_is_bounded_by_watchdog(self):
        ti, _inner, faulty, rm = self.build(
            FaultPlan(at={0: "hang"}, hang_s=10.0), failure_threshold=1
        )
        try:
            t0 = time.monotonic()
            results = rm.match_topics(self.TOPICS)
            elapsed = time.monotonic() - t0
            assert elapsed < 2.0, "publish futures must not wedge"
            assert_oracle(ti, self.TOPICS, results)
            assert rm.breaker.failure_kinds.get("hang") == 1
            assert rm.pool.wedged == 1
            faulty.release.set()  # un-wedge; the worker retires
            deadline = time.monotonic() + 2
            while rm.pool.wedged and time.monotonic() < deadline:
                time.sleep(0.01)
            assert rm.pool.wedged == 0
        finally:
            faulty.release.set()
            rm.close()

    def test_corrupt_result_caught_by_differential_rewalk(self):
        ti, _inner, _faulty, rm = self.build(
            FaultPlan(at={0: "corrupt"}), failure_threshold=1
        )
        try:
            results = rm.match_topics(self.TOPICS)
            # the falsified entry must NOT leak to fan-out
            assert_oracle(ti, self.TOPICS, results)
            for r in results:
                assert CHAOS_CLIENT not in r.subscriptions
            assert rm.breaker.failure_kinds.get("corrupt") == 1
            assert rm.breaker.state == OPEN
        finally:
            rm.close()

    def test_slow_link_does_not_trip(self):
        ti, _inner, _faulty, rm = self.build(
            FaultPlan(at={0: "slow"}, slow_s=0.05), watchdog_s=1.0
        )
        try:
            assert_oracle(ti, self.TOPICS, rm.match_topics(self.TOPICS))
            assert rm.breaker.state == CLOSED
            assert rm.breaker.failures == 0
        finally:
            rm.close()

    def test_automatic_half_open_recovery(self):
        """Trip the breaker, then let the BACKGROUND probe thread verify
        health and close it — no live traffic involved."""
        ti, inner, _faulty, rm = self.build(
            FaultPlan(at={0: "error", 1: "error", 2: "error"})
        )
        try:
            for _ in range(3):
                rm.match_topics(self.TOPICS)
            assert rm.breaker.state == OPEN
            deadline = time.monotonic() + 5
            while rm.breaker.state != CLOSED and time.monotonic() < deadline:
                time.sleep(0.02)
            assert rm.breaker.state == CLOSED, rm.breaker.as_dict()
            assert rm.breaker.probes >= 1
            # re-admitted: live traffic reaches the device again
            seen = inner.dispatches
            assert_oracle(ti, self.TOPICS, rm.match_topics(self.TOPICS))
            assert inner.dispatches == seen + 1
        finally:
            rm.close()

    def test_probe_now_requires_verified_health(self):
        """A probe against a STILL-corrupting device must not close the
        breaker (re-admission requires verified healthy matches)."""
        ti, _inner, _faulty, rm = self.build(
            # every dispatch corrupts, forever
            FaultPlan(corrupt_rate=1.0),
            failure_threshold=1,
            probe_backoff_s=30.0,  # keep the background prober out of it
            probe_backoff_max_s=60.0,
        )
        try:
            rm.match_topics(self.TOPICS)  # trips
            assert rm.breaker.state == OPEN
            assert rm.probe_now() is False
            assert rm.breaker.state == OPEN
            assert rm.breaker.probe_failures >= 1
        finally:
            rm.close()

    def test_churn_between_resolve_and_verify_is_not_corruption(self):
        """A SUBSCRIBE landing after the device resolve makes the live
        host walk legitimately diverge from a CORRECT device result; the
        differential check must treat that as indeterminate, not trip
        the breaker as 'corrupt'."""

        class ChurningMatcher(HostBatchMatcher):
            def match_topics_async(self, topics):
                resolver = super().match_topics_async(topics)

                def resolve():
                    results = resolver()  # correct at resolve time
                    # post-resolve churn: a new subscriber on a matched
                    # filter, before the verify step can run
                    self.index.subscribe(
                        f"late{self.dispatches}", Subscription(filter="a/+")
                    )
                    return results

                return resolve

        ti = small_index()
        rm = ResilientMatcher(
            ChurningMatcher(ti), ti, fast_config(failure_threshold=1)
        )
        try:
            for _ in range(3):
                rm.match_topics(["a/b", "a/x"])
            assert rm.breaker.state == CLOSED, rm.breaker.as_dict()
            assert "corrupt" not in rm.breaker.failure_kinds
        finally:
            rm.close()

    def test_seeded_fault_schedule_is_replayable(self):
        kinds = ["hang", "error", "corrupt", "slow", None]
        draws1 = [FaultPlan(seed=3, error_rate=0.3, slow_rate=0.2).draw(i) for i in range(64)]
        draws2 = [FaultPlan(seed=3, error_rate=0.3, slow_rate=0.2).draw(i) for i in range(64)]
        assert draws1 == draws2
        assert any(d is not None for d in draws1)
        assert all(d in kinds for d in draws1)


# -- end-to-end: staged broker under seeded chaos ----------------------------


N_PUBS = 8
MSGS_EACH = 6


def chaos_options(**kw):
    return Options(
        inline_client=True,
        device_matcher=True,
        matcher_stage_window_ms=2.0,
        matcher_opts={"max_levels": 4, "background": False},
        # fast, deterministic breaker: any fault trips; probes every
        # ~40ms verify against the host walk and close after 1 success
        breaker_failure_threshold=1,
        breaker_watchdog_ms=kw.pop("watchdog_ms", 1500.0),
        breaker_probe_backoff_ms=40.0,
        breaker_probe_backoff_max_ms=200.0,
        breaker_probe_jitter=0.0,
        breaker_probe_successes=1,
        breaker_verify_sample=8,
        **kw,
    )


async def _read_sys_gauge(h, topic):
    pk = h.server.topics.retained.get(SYS_PREFIX + topic)
    return None if pk is None else pk.payload.decode()


class TestBrokerChaos:
    def test_staged_broker_survives_seeded_fault_storm(self):
        """The acceptance drill: dispatch hang/exception/corrupt/slow at
        seeded random points under live publish traffic. Delivery stays
        bit-identical to the host-trie oracle (every message exactly
        once), no publish future outlives the watchdog budget, and the
        breaker demonstrably trips OPEN and recovers through half-open
        probes — asserted via the $SYS gauges."""

        async def scenario():
            h = Harness(chaos_options())
            await h.server.serve()

            sub_r, sub_w, _ = await h.connect("sub")
            sub_w.write(sub_packet(1, [Subscription(filter="c/#", qos=0)]))
            await sub_w.drain()
            assert (await read_wire_packet(sub_r)).fixed_header.type == SUBACK
            h.server.matcher.flush()

            pubs = []
            for i in range(N_PUBS):
                _, w, _ = await h.connect(f"pub{i}")
                pubs.append(w)

            # warm the dispatch path (first-batch compile must not eat
            # the watchdog budget), then arm chaos at seeded random
            # dispatch indices — replayable from the seed alone
            pubs[0].write(pub_packet("c/warm/up", b"w0"))
            await pubs[0].drain()
            pk = await asyncio.wait_for(read_wire_packet(sub_r), 10)
            assert pk.topic_name == "c/warm/up"

            rng = random.Random(1207)
            idxs = sorted(rng.sample(range(1, 24), 5))
            kinds = ["hang", "error", "corrupt", "slow", "error"]
            chaos = ChaosHook()
            chaos.init(
                ChaosOptions(
                    server=h.server,
                    seed=1207,
                    hang_s=3.0,
                    slow_s=0.02,
                    at=dict(zip(idxs, kinds)),
                )
            )
            chaos.install(h.server)

            async def publish_all(i, w):
                for m in range(MSGS_EACH):
                    w.write(pub_packet(f"c/p{i}/x", f"m{i}-{m}".encode()))
                    await w.drain()
                    await asyncio.sleep(0.004)  # spread across batches

            await asyncio.gather(
                *(publish_all(i, w) for i, w in enumerate(pubs))
            )

            # the oracle: the wildcard subscriber receives EVERY message
            # exactly once, each read bounded (nothing wedges past the
            # watchdog + pipeline depth)
            expect = {
                (f"c/p{i}/x", f"m{i}-{m}".encode())
                for i in range(N_PUBS)
                for m in range(MSGS_EACH)
            }
            got = []
            for _ in range(len(expect)):
                pk = await asyncio.wait_for(read_wire_packet(sub_r), 10)
                assert pk.fixed_header.type == PUBLISH
                got.append((pk.topic_name, bytes(pk.payload)))
            assert set(got) == expect, "lost deliveries"
            assert len(got) == len(expect), "duplicated deliveries"
            for topic, payload in got:
                assert CHAOS_CLIENT not in topic  # corrupt never leaked

            # the breaker tripped on the injected faults...
            assert chaos.injected, "chaos never fired"
            br = h.server.matcher.breaker
            assert br.trips >= 1, br.as_dict()
            # ...and recovers through half-open probes
            deadline = time.monotonic() + 8
            while br.state != CLOSED and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            assert br.state == CLOSED, br.as_dict()
            assert br.probes >= 1

            # state transitions are visible through the $SYS gauges
            h.server.publish_sys_topics()
            state = await _read_sys_gauge(h, "/broker/matcher/breaker/state")
            trips = await _read_sys_gauge(h, "/broker/matcher/breaker/trips")
            fb = await _read_sys_gauge(
                h, "/broker/matcher/breaker/fallback_batches"
            )
            assert state == CLOSED
            assert trips is not None and int(trips) >= 1
            assert fb is not None and int(fb) >= 1

            chaos.uninstall()
            await h.server.close()
            await h.shutdown()

        run(scenario())

    def test_breaker_open_serves_from_host_with_no_device_calls(self):
        """With the device permanently dark (every dispatch hangs), the
        broker keeps serving within the watchdog bound and the $SYS
        gauges show the degradation."""

        async def scenario():
            h = Harness(chaos_options(watchdog_ms=200.0))
            await h.server.serve()

            sub_r, sub_w, _ = await h.connect("sub")
            sub_w.write(sub_packet(1, [Subscription(filter="d/+", qos=0)]))
            await sub_w.drain()
            await read_wire_packet(sub_r)
            h.server.matcher.flush()

            chaos = ChaosHook()
            chaos.init(
                ChaosOptions(server=h.server, hang_rate=1.0, hang_s=30.0)
            )
            chaos.install(h.server)

            pub_r, pub_w, _ = await h.connect("pub")
            t0 = time.monotonic()
            for m in range(6):
                pub_w.write(pub_packet("d/x", f"k{m}".encode()))
                await pub_w.drain()
                pk = await asyncio.wait_for(read_wire_packet(sub_r), 10)
                assert bytes(pk.payload) == f"k{m}".encode()
            # 6 round trips: the first eats one watchdog (200ms); OPEN
            # ones are instant host walks
            assert time.monotonic() - t0 < 8.0
            # degraded: OPEN, or HALF_OPEN while a (doomed) probe runs
            assert h.server.matcher.breaker.state != CLOSED
            assert h.server.matcher.breaker.trips >= 1
            assert h.server.matcher.fallback_batches >= 1

            chaos.faulty.release.set()  # let wedged workers retire
            chaos.uninstall()
            await h.server.close()
            await h.shutdown()

        run(scenario())


# -- worker mesh: peer-link kill + reconnect + presence resync ---------------


class TestMeshLinkChaos:
    def test_peer_kill_reconnect_and_presence_resync(self, tmp_path):
        """Sever a live mesh link mid-traffic: the dial side reconnects
        with backoff, presence replays in full on reattach (including
        filters subscribed DURING the outage), and cross-worker delivery
        resumes. Reconnects surface in the $SYS gauge counters."""
        from mqtt_tpu.cluster import Cluster

        async def wait_until(cond, timeout=5.0, what=""):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if cond():
                    return
                await asyncio.sleep(0.02)
            raise AssertionError(f"timeout waiting for {what}")

        async def scenario():
            hA = Harness()
            hB = Harness()
            await hA.server.serve()
            await hB.server.serve()
            cA = Cluster(hA.server, 0, 2, str(tmp_path))
            cB = Cluster(hB.server, 1, 2, str(tmp_path))
            await cA.start()
            await cB.start()
            await wait_until(
                lambda: cA.peer_count == 1 and cB.peer_count == 1,
                what="mesh up",
            )

            # subscriber on A; publisher on B reaches it across the mesh
            sub_r, sub_w, _ = await hA.connect("subA")
            sub_w.write(sub_packet(1, [Subscription(filter="m/+", qos=0)]))
            await sub_w.drain()
            await read_wire_packet(sub_r)
            await wait_until(
                lambda: cB._interested_peers("m/1"), what="presence at B"
            )
            pub_r, pub_w, _ = await hB.connect("pubB")
            pub_w.write(pub_packet("m/1", b"pre-kill"))
            await pub_w.drain()
            pk = await asyncio.wait_for(read_wire_packet(sub_r), 5)
            assert bytes(pk.payload) == b"pre-kill"

            # KILL the link mid-traffic (connection reset, as a crashed
            # worker would present)
            assert sever_peer_link(cB, 0)
            await wait_until(
                lambda: cB.peer_count == 0 or cA.peer_count == 0,
                what="link down observed",
            )
            # a filter subscribed DURING the outage: its presence message
            # is unsendable now and must arrive via the reattach replay
            sub_w.write(sub_packet(2, [Subscription(filter="n/+", qos=0)]))
            await sub_w.drain()
            await read_wire_packet(sub_r)

            # the dial side heals the link with backoff...
            await wait_until(
                lambda: cA.peer_count == 1 and cB.peer_count == 1,
                what="mesh reconnect",
            )
            assert cB.reconnects_total >= 1  # B dials worker 0
            # ...and the full presence replay converges B's interest map
            await wait_until(
                lambda: cB._interested_peers("n/5"),
                what="outage-subscribed presence resync",
            )
            pub_w.write(pub_packet("n/5", b"post-heal"))
            await pub_w.drain()
            pk = await asyncio.wait_for(read_wire_packet(sub_r), 5)
            assert bytes(pk.payload) == b"post-heal"

            await cA.stop()
            await cB.stop()
            await hA.server.close()
            await hB.server.close()
            await hA.shutdown()
            await hB.shutdown()

        run(scenario())

    def test_qos_forward_drop_is_counted_not_silent(self, tmp_path):
        """The documented known-limit: QoS>0 forwards drop at the
        peer-buffer cap — per peer and per class, never silently."""
        from mqtt_tpu.cluster import _T_PACKET, Cluster
        from mqtt_tpu.packets import FixedHeader, Packet

        class WedgedTransport:
            def get_write_buffer_size(self):
                return Cluster.MAX_PEER_BUFFER + 1

            def abort(self):
                pass

        class WedgedWriter:
            transport = WedgedTransport()

            def write(self, data):
                raise AssertionError("a wedged peer must not be written")

        async def scenario():
            h = Harness()
            c = Cluster(h.server, 0, 2, str(tmp_path))
            c._writers[1] = WedgedWriter()
            c._apply_presence(1, "x/y", True, False)

            pk = Packet(
                fixed_header=FixedHeader(type=PUBLISH, qos=1),
                protocol_version=5,
            )
            pk.topic_name = "x/y"
            pk.payload = b"hello"
            pk.packet_id = 9
            c.forward_packet(pk)

            assert c.dropped_forwards == 1
            assert c.dropped_by_peer == {1: 1}
            assert c.dropped_qos_forwards == 1
            # a QoS0 drop counts in the totals but not the QoS>0 class
            assert c._send_nowait(1, c._writers[1], _T_PACKET, b"x") is False
            assert c.dropped_forwards == 2
            assert c.dropped_qos_forwards == 1
            await h.shutdown()

        run(scenario())

    def test_presence_wake_from_foreign_thread(self, tmp_path):
        """Satellite regression: a trie mutation from an embedder thread
        must not lose the presence wake (the wake routes through
        call_soon_threadsafe when off-loop)."""
        from mqtt_tpu.cluster import Cluster

        async def scenario():
            h = Harness()
            await h.server.serve()
            c = Cluster(h.server, 0, 1, str(tmp_path))
            await c.start()

            def embedder():
                h.server.topics.subscribe(
                    "thread-cli", Subscription(filter="t/h/r")
                )

            t = threading.Thread(target=embedder)
            t.start()
            t.join()
            deadline = time.monotonic() + 3
            while c._pending_presence and time.monotonic() < deadline:
                await asyncio.sleep(0.02)
            assert not c._pending_presence, "presence wake was lost"
            await c.stop()
            await h.server.close()
            await h.shutdown()

        run(scenario())


# -- slow chaos smoke (make chaos-smoke) -------------------------------------


@pytest.mark.slow
class TestChaosSmoke:
    def test_rate_driven_fault_storm_long(self):
        """The long randomized drill: rate-driven seeded faults across
        hundreds of dispatches under sustained traffic; delivery stays
        exactly-once against the oracle and the breaker ends CLOSED."""

        async def scenario():
            h = Harness(chaos_options(watchdog_ms=800.0))
            await h.server.serve()
            sub_r, sub_w, _ = await h.connect("sub")
            sub_w.write(sub_packet(1, [Subscription(filter="s/#", qos=0)]))
            await sub_w.drain()
            await read_wire_packet(sub_r)
            h.server.matcher.flush()

            pubs = []
            for i in range(4):
                _, w, _ = await h.connect(f"p{i}")
                pubs.append(w)
            pubs[0].write(pub_packet("s/warm", b"w"))
            await pubs[0].drain()
            await asyncio.wait_for(read_wire_packet(sub_r), 10)

            chaos = ChaosHook()
            chaos.init(
                ChaosOptions(
                    server=h.server,
                    seed=99,
                    hang_rate=0.04,
                    error_rate=0.08,
                    corrupt_rate=0.05,
                    slow_rate=0.1,
                    hang_s=2.0,
                    slow_s=0.01,
                )
            )
            chaos.install(h.server)

            n_msgs = 50
            async def publish_all(i, w):
                for m in range(n_msgs):
                    w.write(pub_packet(f"s/{i}/t", f"{i}.{m}".encode()))
                    await w.drain()
                    await asyncio.sleep(0.003)

            await asyncio.gather(*(publish_all(i, w) for i, w in enumerate(pubs)))

            expect = {
                (f"s/{i}/t", f"{i}.{m}".encode())
                for i in range(4)
                for m in range(n_msgs)
            }
            got = []
            for _ in range(len(expect)):
                pk = await asyncio.wait_for(read_wire_packet(sub_r), 15)
                got.append((pk.topic_name, bytes(pk.payload)))
            assert set(got) == expect and len(got) == len(expect)

            br = h.server.matcher.breaker
            deadline = time.monotonic() + 10
            while br.state != CLOSED and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            assert br.state == CLOSED, br.as_dict()
            assert chaos.injected

            chaos.faulty.release.set()
            chaos.uninstall()
            await h.server.close()
            await h.shutdown()

        run(scenario())
