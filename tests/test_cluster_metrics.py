"""Mesh metric federation (ISSUE 14, telemetry.ClusterMetrics + cluster
``_T_METRICS``): the registry wire summary, cross-worker fold semantics
(labeled tenant x qos x worker families, histogram bucket-vector
addition, counter-delta idempotence under re-delivered frames), the
federated exposition's validity, and the 3-worker tree-mesh end-to-end
drill — root-scraped /metrics/cluster with per-worker labels, folded
delivery-latency histograms covering local AND remote paths, /healthz
and /cluster/slo beside it.
"""

import asyncio
import json
import re

import pytest

from mqtt_tpu.server import Options
from mqtt_tpu.telemetry import (
    ClusterMetrics,
    Histogram,
    MetricsRegistry,
    Telemetry,
    check_exposition,
)

from tests.test_server import read_wire_packet, sub_packet
from tests.test_tree_mesh import TreeMesh, run, wait_for


# -- the wire summary --------------------------------------------------------


class TestRegistrySummary:
    def test_summary_round_trips_all_types(self):
        r = MetricsRegistry()
        r.counter("mqtt_tpu_c_total", "c").inc(3)
        r.gauge("mqtt_tpu_g", "g").set(1.5)
        h = r.histogram("mqtt_tpu_h_seconds", "h", tenant="a", qos="1")
        h.observe(0.002)
        h.observe(0.002)
        s = r.summary()
        assert s["mqtt_tpu_c_total"]["t"] == "counter"
        assert s["mqtt_tpu_c_total"]["c"][0][1] == 3
        assert s["mqtt_tpu_g"]["c"][0][1] == 1.5
        ent = s["mqtt_tpu_h_seconds"]
        assert ent["t"] == "histogram" and isinstance(ent["le"], list)
        labels, val = ent["c"][0]
        assert dict(map(tuple, labels)) == {"tenant": "a", "qos": "1"}
        assert val["n"] == 2
        # trailing zero buckets are trimmed off the wire
        assert len(val["c"]) <= len(ent["le"]) + 1
        assert sum(val["c"]) == 2
        # and the whole thing survives a json round trip (the wire)
        assert json.loads(json.dumps(s)) == s


class TestIngestIdempotence:
    def test_re_delivered_frame_is_a_no_op(self):
        cm = ClusterMetrics(clock=lambda: 0.0)
        fams = {"mqtt_tpu_c_total": {"t": "counter", "c": [[[], 7]]}}
        assert cm.ingest("1", 42, 1, fams)
        before = cm.exposition()
        # the same (boot, seq) frame again: idempotent, fold unchanged
        assert not cm.ingest("1", 42, 1, fams)
        assert cm.frames_stale == 1
        assert cm.exposition() == before
        assert "mqtt_tpu_c_total 7" in before  # folded once, not twice

    def test_reordered_older_seq_dropped_newer_accepted(self):
        cm = ClusterMetrics(clock=lambda: 0.0)
        fams_new = {"mqtt_tpu_c_total": {"t": "counter", "c": [[[], 9]]}}
        fams_old = {"mqtt_tpu_c_total": {"t": "counter", "c": [[[], 5]]}}
        assert cm.ingest("1", 42, 3, fams_new)
        assert not cm.ingest("1", 42, 2, fams_old)  # late frame loses
        assert "mqtt_tpu_c_total 9" in cm.exposition()

    def test_restarted_boot_replaces_dead_incarnation(self):
        cm = ClusterMetrics(clock=lambda: 0.0)
        cm.ingest("1", 42, 100, {"mqtt_tpu_c_total": {"t": "counter", "c": [[[], 50]]}})
        # fresh boot nonce, seq restarts at 1: must WIN
        assert cm.ingest("1", 77, 1, {"mqtt_tpu_c_total": {"t": "counter", "c": [[[], 2]]}})
        assert "mqtt_tpu_c_total 2" in cm.exposition()

    def test_stale_workers_age_out(self):
        now = [0.0]
        cm = ClusterMetrics(max_age_s=10.0, clock=lambda: now[0])
        cm.ingest("1", 1, 1, {"mqtt_tpu_c_total": {"t": "counter", "c": [[[], 1]]}})
        assert cm.worker_count == 1
        now[0] = 11.0
        assert cm.entries() == {}
        assert "mqtt_tpu_c_total" not in cm.exposition()


# -- cross-worker folding ----------------------------------------------------


def _delivery_summary(counts_by_cell):
    """A summary fragment holding delivery-latency children:
    {(tenant, qos, path): bucket_counts}."""
    bounds = [0.001, 0.01, 0.1]
    children = []
    for (tenant, qos, path), counts in sorted(counts_by_cell.items()):
        children.append(
            [
                [["path", path], ["qos", qos], ["tenant", tenant]],
                {"n": sum(counts), "s": 0.01 * sum(counts), "c": counts},
            ]
        )
    return {
        "mqtt_tpu_delivery_latency_seconds": {
            "t": "histogram",
            "le": bounds,
            "c": children,
        }
    }


class TestFolding:
    def test_labeled_family_folds_tenant_qos_across_workers(self):
        cm = ClusterMetrics(clock=lambda: 0.0)
        cm.ingest(
            "1", 1, 1,
            _delivery_summary({("acme", "1", "local"): [1, 2, 0]}),
        )
        cm.ingest(
            "2", 1, 1,
            _delivery_summary(
                {
                    ("acme", "1", "local"): [4, 0, 1],
                    ("bulk", "0", "remote"): [0, 7, 0],
                }
            ),
        )
        text = cm.exposition()
        check_exposition(text)
        # per-worker rows keep their identity
        assert (
            'mqtt_tpu_delivery_latency_seconds_count{path="local",qos="1",'
            'tenant="acme",worker="1"} 3' in text
        )
        assert (
            'mqtt_tpu_delivery_latency_seconds_count{path="local",qos="1",'
            'tenant="acme",worker="2"} 5' in text
        )
        # the fold sums the SAME (tenant, qos, path) cell across workers
        assert (
            'mqtt_tpu_delivery_latency_seconds_count{path="local",qos="1",'
            'tenant="acme"} 8' in text
        )
        # a cell only one worker observed still folds (to itself)
        assert (
            'mqtt_tpu_delivery_latency_seconds_count{path="remote",'
            'qos="0",tenant="bulk"} 7' in text
        )

    def test_histogram_bucket_vectors_add(self):
        cm = ClusterMetrics(clock=lambda: 0.0)
        cm.ingest("1", 1, 1, _delivery_summary({("", "0", "local"): [1, 0, 2]}))
        cm.ingest("2", 1, 1, _delivery_summary({("", "0", "local"): [0, 5]}))
        text = cm.exposition()
        check_exposition(text)
        # folded buckets: cumulative 1, 6, 8 then +Inf 8
        fold = [
            line
            for line in text.splitlines()
            if line.startswith("mqtt_tpu_delivery_latency_seconds_bucket")
            and "worker=" not in line
        ]
        got = [int(line.rsplit(" ", 1)[1]) for line in fold]
        assert got == [1, 6, 8, 8]

    def test_local_registry_shadows_stale_self_summary(self):
        r = MetricsRegistry()
        r.counter("mqtt_tpu_c_total", "c").inc(10)
        cm = ClusterMetrics(clock=lambda: 0.0)
        # a stale federated copy of worker 0 says 3; the live local
        # registry says 10 — local wins
        cm.ingest("0", 1, 1, {"mqtt_tpu_c_total": {"t": "counter", "c": [[[], 3]]}})
        text = cm.exposition(r, "0")
        assert 'mqtt_tpu_c_total{worker="0"} 10' in text
        assert 'mqtt_tpu_c_total{worker="0"} 3' not in text

    def test_gauges_render_per_worker_only(self):
        cm = ClusterMetrics(clock=lambda: 0.0)
        cm.ingest("1", 1, 1, {"mqtt_tpu_g": {"t": "gauge", "c": [[[], 5]]}})
        cm.ingest("2", 1, 1, {"mqtt_tpu_g": {"t": "gauge", "c": [[[], 7]]}})
        text = cm.exposition()
        check_exposition(text)
        assert 'mqtt_tpu_g{worker="1"} 5' in text
        assert 'mqtt_tpu_g{worker="2"} 7' in text
        # no folded (worker-less) gauge row: 5+7=12 means nothing
        assert re.search(r"^mqtt_tpu_g (\d+)$", text, re.M) is None

    def test_malformed_entries_are_skipped_not_fatal(self):
        cm = ClusterMetrics(clock=lambda: 0.0)
        cm.ingest(
            "1", 1, 1,
            {
                "not a metric name!": {"t": "counter", "c": [[[], 1]]},
                "mqtt_tpu_ok_total": {"t": "counter", "c": [[[], 2]]},
                "mqtt_tpu_weird": {"t": "wat", "c": [[[], 3]]},
                "mqtt_tpu_broken": "nope",
            },
        )
        text = cm.exposition()
        check_exposition(text)
        assert "mqtt_tpu_ok_total" in text
        assert "wat" not in text and "nope" not in text

    def test_slo_state_extracts_federated_gauges(self):
        cm = ClusterMetrics(clock=lambda: 0.0)
        cm.ingest(
            "3", 1, 1,
            {
                "mqtt_tpu_slo_breached": {
                    "t": "gauge",
                    "c": [[[["objective", "p99"]], 1]],
                },
                "mqtt_tpu_c_total": {"t": "counter", "c": [[[], 1]]},
            },
        )
        st = cm.slo_state()
        assert st == {"3": {"mqtt_tpu_slo_breached{objective=p99}": 1}}


# -- live telemetry -> summary -> fold (the real shapes) ---------------------


class TestLiveRegistryFederation:
    def test_two_live_telemetries_fold_validly(self):
        t1 = Telemetry(sample=1)
        t2 = Telemetry(sample=1)
        for tele, tenant, n in ((t1, "acme", 3), (t2, "acme", 5)):
            for i in range(n):
                tele.observe_delivery(0.001 * (i + 1), tenant, 1, "local")
            tele.publish_encodes.inc(n)
        cm = ClusterMetrics(clock=lambda: 0.0)
        cm.ingest("1", 1, 1, t1.registry.summary())
        text = cm.exposition(t2.registry, "2")
        samples = check_exposition(text)
        assert samples > 0
        assert (
            'mqtt_tpu_delivery_latency_seconds_count{path="local",qos="1",'
            'tenant="acme"} 8' in text
        )
        m = re.search(r"^mqtt_tpu_publish_encodes_total (\d+)$", text, re.M)
        assert m is not None and int(m.group(1)) == 8


# -- mesh-mode remote SLI stamping (the default topology) --------------------


class TestMeshModeElStamp:
    def test_sampled_untraced_qos0_frame_carries_el(self, tmp_path):
        """The DEFAULT all-pairs topology must federate remote QoS0
        latency with tracing off: a sampled-but-untraced clock switches
        the forward to a _T_TFRAME whose json head carries the origin's
        elapsed stamp (and no trace id — the receiver's remote span
        no-ops, only the delivery SLI records)."""
        import struct

        from mqtt_tpu.cluster import _T_FRAME, _T_TFRAME
        from mqtt_tpu.telemetry import StageClock
        from tests.test_federation import _FakeWriter, _bare_cluster

        c, _gov = _bare_cluster(tmp_path, with_governor=False)
        c._apply_presence(1, "t/#", True, False)
        w = c._writers[1] = _FakeWriter()
        frame = b"\x30\x05\x00\x03t/xp"
        # unsampled publish: the plain _T_FRAME encoding, byte-for-byte
        c.forward_frame("t/x", frame, "orig", None)
        assert w.sent and w.sent[-1][4] == _T_FRAME
        # sampled (clock) but untraced: _T_TFRAME with {"el": ...}
        clock = StageClock()
        c.forward_frame("t/x", frame, "orig", clock)
        raw = w.sent[-1]
        assert raw[4] == _T_TFRAME
        (olen,) = struct.unpack(">H", raw[5:7])
        off = 7 + olen
        (tlen,) = struct.unpack(">H", raw[off : off + 2])
        tr = json.loads(raw[off + 2 : off + 2 + tlen])
        assert tr.get("el", -1) >= 0 and "tid" not in tr
        assert raw[off + 2 + tlen :] == frame


# -- the 3-worker tree-mesh end-to-end drill ---------------------------------


class TestTreeFederationE2E:
    def test_root_scrapes_whole_mesh_with_remote_delivery(self, tmp_path):
        """The acceptance drill at CI scale: a 3-worker tree, a
        cross-worker QoS1 burst, and ONE valid exposition at the root
        carrying per-worker labels, cluster-folded delivery-latency
        histograms on BOTH paths, plus /cluster/slo's federated view."""

        async def scenario():
            mesh = TreeMesh(
                3,
                tmp_path,
                telemetry_sample=1,
                slo_objectives=["p99 delivery < 5s over 30s/2m"],
            )
            try:
                await mesh.start()
                root = mesh.harnesses[0].server
                # subscriber on worker 2, publisher on worker 0: every
                # delivery crosses the mesh
                sr, sw = await mesh.subscribe(2, "fed-sub", "fed/#", qos=1)
                await mesh.settle_summaries()
                pr, pw, _ = await mesh.harnesses[0].connect(
                    "fed-pub", version=4
                )
                from tests.test_server import pub_packet

                for i in range(30):
                    pw.write(
                        pub_packet("fed/x", b"m%d" % i, qos=1, pid=i + 1)
                    )
                # every delivery arrives (QoS1: the packet leg carries
                # the origin's elapsed stamp)
                got = 0
                while got < 30:
                    pk = await read_wire_packet(sr, 4)
                    if pk.fixed_header.type == 3:  # PUBLISH
                        got += 1
                # a local-path sample too: root-local subscriber
                lr, lw, _ = await mesh.harnesses[0].connect(
                    "loc-sub", version=4
                )
                from mqtt_tpu.packets import Subscription

                lw.write(
                    sub_packet(
                        1, [Subscription(filter="fed/#", qos=0)], 4
                    )
                )
                await read_wire_packet(lr, 4)
                pw.write(pub_packet("fed/x", b"local", qos=0))
                await read_wire_packet(lr, 4)

                # worker 2 recorded remote-path samples
                tele2 = mesh.harnesses[2].server.telemetry
                await wait_for(
                    lambda: any(
                        p == "remote" and h.count
                        for (_t, _q, p), h in tele2._delivery_cache.items()
                    ),
                    msg="remote-path delivery samples on worker 2",
                )
                # federation: the root aggregates both children (the
                # post-delivery snapshot needs one more gossip tick)
                cm = root.telemetry.cluster_metrics

                def _w2_has_delivery():
                    ent = cm.entries().get("2")
                    if ent is None:
                        return False
                    fam = ent["f"].get(
                        "mqtt_tpu_delivery_latency_seconds"
                    )
                    return bool(fam and fam.get("c"))

                await wait_for(
                    lambda: cm is not None and _w2_has_delivery(),
                    msg="worker 2's delivery samples federated to root",
                )
                await wait_for(
                    lambda: "1" in cm.entries(),
                    msg="worker 1's summary at the root",
                )

                text = cm.exposition(
                    root.telemetry.registry, root.telemetry.local_worker
                )
                check_exposition(text)
                for wid in ("0", "1", "2"):
                    assert f'worker="{wid}"' in text
                # remote-path rows from worker 2, visible at the root
                assert re.search(
                    r'delivery_latency_seconds_count\{[^}]*path="remote"'
                    r'[^}]*worker="2"\} [1-9]',
                    text,
                ), text[:2000]
                # local-path rows from the root itself
                assert re.search(
                    r'delivery_latency_seconds_count\{[^}]*path="local"'
                    r'[^}]*worker="0"\} [1-9]',
                    text,
                )
                # the cluster folds carry BOTH paths with no worker label
                for path in ("local", "remote"):
                    assert re.search(
                        r"delivery_latency_seconds_count\{(?![^}]*worker=)"
                        rf'[^}}]*path="{path}"[^}}]*\}} [1-9]',
                        text,
                    ), path

                # mesh-wide SLO state: every worker's slo gauges at root
                slo = cm.slo_state(
                    root.telemetry.registry, root.telemetry.local_worker
                )
                assert set(slo) == {"0", "1", "2"}

                # the mesh-mode frames counter moved on the root
                assert root._cluster.metrics_frames_rx > 0
            finally:
                await mesh.stop()

        run(scenario(), timeout=90)
