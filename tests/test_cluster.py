"""Multi-core data plane (mqtt_tpu.cluster): N SO_REUSEPORT worker
processes joined by the unix-socket forwarding mesh must behave like one
broker for pub/sub traffic — cross-worker delivery over both forwarding
legs (verbatim QoS0 frames and re-encoded packets), retained-message
replication, and presence withdrawal.

Workers also bind deterministic private ports (base+1+worker_id) so the
tests can pin which worker a client lands on; the shared SO_REUSEPORT
port is exercised for liveness only (the kernel picks the worker)."""

import asyncio
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONNECT_V5 = bytes.fromhex("101000044d5154540502003c032100140000")
CONNECT_V4 = bytes.fromhex("100c00044d5154540402003c0000")
BASE_PORT = 18960


@pytest.fixture(scope="module")
def cluster():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["MQTT_TPU_WORKER_PORTS"] = "1"  # expose base+1+id pinning ports
    proc = subprocess.Popen(
        [sys.executable, "-m", "mqtt_tpu.stress", "--serve", "--broker",
         f"127.0.0.1:{BASE_PORT}", "--workers", "2"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env, cwd=REPO,
    )
    try:
        assert proc.stdout.readline().strip() == b"READY"
        yield proc
    finally:
        try:
            proc.stdin.close()
            proc.wait(timeout=10)
        except Exception:
            proc.kill()


async def _conn(port: int, v4: bool = False):
    r, w = await asyncio.open_connection("127.0.0.1", port)
    w.write(CONNECT_V4 if v4 else CONNECT_V5)
    await w.drain()
    ca = await r.read(64)
    assert ca[0] == 0x20, ca.hex()
    return r, w


async def _sub(r, w, filt: str, pid: int = 1, qos: int = 0):
    fb = filt.encode()
    var = pid.to_bytes(2, "big") + b"\x00" + len(fb).to_bytes(2, "big") + fb + bytes([qos])
    w.write(b"\x82" + bytes([len(var)]) + var)
    await w.drain()
    sa = await r.read(64)
    assert sa[0] == 0x90, sa.hex()


def _pub(topic: str, payload: bytes, retain: bool = False, qos: int = 0) -> bytes:
    tb = topic.encode()
    body = len(tb).to_bytes(2, "big") + tb
    if qos:
        body += (7).to_bytes(2, "big")
    body += b"\x00" + payload  # empty v5 properties
    return bytes([0x30 | (qos << 1) | (1 if retain else 0)]) + bytes([len(body)]) + body


def test_cross_worker_fast_frame(cluster):
    async def run():
        r0, w0 = await _conn(BASE_PORT + 1)  # worker 0
        await _sub(r0, w0, "xw/+/t")
        await asyncio.sleep(0.4)  # presence propagation
        r1, w1 = await _conn(BASE_PORT + 2, v4=True)  # worker 1, fast path
        w1.write(_pub("xw/a/t", b"fast-leg"))
        await w1.drain()
        got = await asyncio.wait_for(r0.read(256), 5)
        assert got[0] >> 4 == 3 and b"fast-leg" in got, got.hex()
        w0.close(); w1.close()

    asyncio.run(run())


def test_cross_worker_packet_leg_v5(cluster):
    async def run():
        r0, w0 = await _conn(BASE_PORT + 1)
        await _sub(r0, w0, "pk/leg")
        await asyncio.sleep(0.4)
        r1, w1 = await _conn(BASE_PORT + 2)  # v5 publisher: decode path
        w1.write(_pub("pk/leg", b"packet-leg"))
        await w1.drain()
        got = await asyncio.wait_for(r0.read(256), 5)
        assert got[0] >> 4 == 3 and b"packet-leg" in got, got.hex()
        w0.close(); w1.close()

    asyncio.run(run())


def test_retained_replicates_to_all_workers(cluster):
    async def run():
        r1, w1 = await _conn(BASE_PORT + 2)
        w1.write(_pub("ret/state", b"persisted", retain=True))
        await w1.drain()
        # a NEW subscriber on the OTHER worker receives the retained copy;
        # replication is async, so retry with fresh sessions until it
        # lands (bounded by the loop, generous on a loaded 1-core host)
        got = b""
        for _attempt in range(10):
            await asyncio.sleep(0.4)
            r0, w0 = await _conn(BASE_PORT + 1)
            await _sub(r0, w0, "ret/state", pid=2)
            try:
                got = await asyncio.wait_for(r0.read(256), 2)
            except asyncio.TimeoutError:
                got = b""
            w0.close()
            if b"persisted" in got:
                break
        assert b"persisted" in got, got.hex()
        w1.close()

    asyncio.run(run())


def test_presence_withdrawal_stops_forwarding(cluster):
    async def run():
        r0, w0 = await _conn(BASE_PORT + 1)
        await _sub(r0, w0, "gone/t")
        await asyncio.sleep(0.4)
        # disconnect the only subscriber: presence must withdraw
        w0.write(b"\xe0\x00")  # DISCONNECT
        await w0.drain()
        w0.close()
        await asyncio.sleep(0.4)
        # a fresh publish from worker 1 has nowhere to go; nothing crashes
        r1, w1 = await _conn(BASE_PORT + 2, v4=True)
        w1.write(_pub("gone/t", b"void"))
        await w1.drain()
        # the shared REUSEPORT port still accepts (liveness after all legs)
        r2, w2 = await _conn(BASE_PORT)
        w2.write(b"\xc0\x00")  # PINGREQ
        await w2.drain()
        pong = await asyncio.wait_for(r2.read(16), 5)
        assert pong[0] == 0xD0, pong.hex()
        w1.close(); w2.close()

    asyncio.run(run())


def test_qos1_cross_worker_delivery(cluster):
    async def run():
        r0, w0 = await _conn(BASE_PORT + 1)
        await _sub(r0, w0, "q1/t", pid=3, qos=1)
        await asyncio.sleep(0.4)
        r1, w1 = await _conn(BASE_PORT + 2)
        w1.write(_pub("q1/t", b"ackd", qos=1))
        await w1.drain()
        # publisher gets PUBACK from its own worker
        ack = await asyncio.wait_for(r1.read(64), 5)
        assert ack[0] == 0x40, ack.hex()
        # subscriber receives at qos1 with a packet id from ITS worker
        got = await asyncio.wait_for(r0.read(256), 5)
        assert got[0] >> 4 == 3 and (got[0] >> 1) & 3 == 1 and b"ackd" in got, got.hex()
        w0.close(); w1.close()

    asyncio.run(run())


def test_cluster_sys_topics(cluster):
    """$SYS exposes the worker-mesh gauges (worker id, live peers,
    dropped forwards) alongside the broker counters."""

    async def run():
        r0, w0 = await _conn(BASE_PORT + 1)
        await _sub(r0, w0, "$SYS/broker/cluster/#", pid=9)
        # $SYS topics are retained; the first resend interval may not have
        # elapsed, so poll for the retained set
        seen = {}
        deadline = asyncio.get_event_loop().time() + 15
        buf = b""
        while asyncio.get_event_loop().time() < deadline and len(seen) < 3:
            try:
                chunk = await asyncio.wait_for(r0.read(4096), 2)
            except asyncio.TimeoutError:
                continue
            if not chunk:
                break  # EOF: fail fast below instead of spinning
            buf += chunk
            for key in (b"cluster/worker", b"cluster/peers", b"cluster/dropped_forwards"):
                if key in buf:
                    seen[key] = True
        assert len(seen) == 3, (seen, buf[:200])
        w0.close()

    asyncio.run(run())


def test_flapping_peer_keeps_reconnect_discipline(tmp_path):
    """A peer link flapping FASTER than the backoff floor (seeded abort
    every ~10ms against a 50ms dial floor): the reconnect counter stays
    monotonic, the mesh converges once the flapping stops, and no
    duplicate ``_read_loop`` survives per peer (the R7 thread/task
    discipline applied to the mesh — a flap must never leave two loops
    draining one peer's frames)."""
    import random

    from mqtt_tpu.cluster import Cluster
    from mqtt_tpu.server import Options, Server

    async def scenario():
        s0, s1 = Server(Options()), Server(Options())
        c0 = Cluster(s0, 0, 2, str(tmp_path))
        c1 = Cluster(s1, 1, 2, str(tmp_path))
        for c in (c0, c1):
            c.PING_INTERVAL_S = 0.1
        await c0.start()
        await c1.start()

        async def wait_for(cond, timeout=10.0):
            deadline = asyncio.get_event_loop().time() + timeout
            while asyncio.get_event_loop().time() < deadline:
                if cond():
                    return True
                await asyncio.sleep(0.02)
            return False

        assert await wait_for(lambda: c0.peer_count == 1 and c1.peer_count == 1)

        rng = random.Random(77)
        samples = []
        for _ in range(30):
            w = c0._writers.get(1) or c1._writers.get(0)
            if w is not None:
                w.transport.abort()
            samples.append(c1.reconnects_total + c0.reconnects_total)
            await asyncio.sleep(rng.uniform(0.005, 0.015))
        # monotonic: a flap may only ever GROW the reconnect counters
        assert samples == sorted(samples)

        # the mesh settles after the abuse
        assert await wait_for(lambda: c0.peer_count == 1 and c1.peer_count == 1)
        assert c1.reconnects_total >= 1
        await asyncio.sleep(0.2)  # let raced teardowns drain

        # exactly one live read loop per peer on each side — a flap must
        # never leave a zombie loop double-draining frames
        for c in (c0, c1):
            for peer, n in c._live_read_loops.items():
                assert n <= 1, (c.worker_id, peer, n, c._live_read_loops)
        assert c0._live_read_loops.get(1) == 1
        assert c1._live_read_loops.get(0) == 1

        await c0.stop()
        await c1.stop()

    asyncio.run(scenario())


@pytest.mark.slow
def test_partition_storm_subprocess(tmp_path):
    """Nightly chaos drill (stress.py --partition): a 2-worker mesh whose
    worker 0 severs a peer link every 0.4s while a seeded storm blasts
    through the shared port; the broker must stay live, keep delivering,
    and account every partition-time loss in the $SYS mesh gauges."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["MQTT_TPU_WORKER_PORTS"] = "1"
    port = BASE_PORT + 40
    proc = subprocess.Popen(
        [sys.executable, "-m", "mqtt_tpu.stress", "--serve", "--broker",
         f"127.0.0.1:{port}", "--workers", "2", "--flap-peer-s", "0.4"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env, cwd=REPO,
    )
    try:
        assert proc.stdout.readline().strip() == b"READY"
        from mqtt_tpu.stress import run_partition

        out = asyncio.run(
            run_partition(
                "127.0.0.1", port, publishers=6, msgs_each=400,
                # worker 1's private port: re-dial counters live on the
                # DIALING side, and only higher-numbered workers dial
                sys_port=port + 2,
            )
        )
        # liveness: the storm completed and traffic flowed end to end
        assert out["offered"]["total"] == 6 * 400
        assert out["delivered"] > 0
        assert out["publishers_disconnected"] == 0
        # accounting: the mesh gauges are present and parse as integers
        sys_gauges = out["cluster_sys"]
        for key in (
            "peer_drops_partition", "peer_drops_backlog",
            "parked_forwards", "replayed_forwards", "reconnects",
        ):
            assert key in sys_gauges, (key, sorted(sys_gauges))
            int(sys_gauges[key])
        # the flapping link forced at least one re-dial
        assert int(sys_gauges["reconnects"]) >= 1
    finally:
        try:
            proc.stdin.close()
            proc.wait(timeout=10)
        except Exception:
            proc.kill()


def test_peer_link_reconnects_in_process(tmp_path):
    """A dropped mesh link heals: the dialing side re-dials and replays
    presence, so forwarding interest converges again (in-process, two
    Cluster instances over a private socket dir)."""
    from mqtt_tpu.cluster import Cluster
    from mqtt_tpu.hooks.auth import AllowHook
    from mqtt_tpu.packets import Subscription
    from mqtt_tpu.server import Options, Server

    async def scenario():
        s0, s1 = Server(Options()), Server(Options())
        for s in (s0, s1):
            s.add_hook(AllowHook())
        c0 = Cluster(s0, 0, 2, str(tmp_path))
        c1 = Cluster(s1, 1, 2, str(tmp_path))
        await c0.start()
        await c1.start()

        async def wait_for(cond, timeout=10.0):
            deadline = asyncio.get_event_loop().time() + timeout
            while asyncio.get_event_loop().time() < deadline:
                if cond():
                    return True
                await asyncio.sleep(0.05)
            return False

        assert await wait_for(lambda: c0.peer_count == 1 and c1.peer_count == 1)
        # a subscription on worker 1 becomes forwarding interest at worker 0
        s1.topics.subscribe("clA", Subscription(filter="heal/t", qos=0))
        assert await wait_for(lambda: c0._interested_peers("heal/t") == (1,))

        # sever the link from worker 0's side (the wedged-link abort path)
        c0._writers[1].transport.abort()
        # the dialer (worker 1) re-dials; both sides re-register
        assert await wait_for(lambda: c0.peer_count == 1 and c1.peer_count == 1)
        # new interest propagates over the healed link
        s1.topics.subscribe("clB", Subscription(filter="heal/u", qos=0))
        assert await wait_for(lambda: c0._interested_peers("heal/u") == (1,))
        # withdrawals lost during the outage were cleaned at link-down:
        # heal/t interest must have been re-announced, not leaked
        assert await wait_for(lambda: c0._interested_peers("heal/t") == (1,))

        await c0.stop()
        await c1.stop()

    asyncio.run(scenario())
