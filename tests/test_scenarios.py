"""Scenario lab (ISSUE 20): the delivery-oracle math, the SLO gate's
breach behavior against injected failures, catalog sanity, one cheap
scenario end-to-end — and the QoS2 exactly-once regression suite the
``qos2_fanout`` scenario's kill -9 leg motivated, including the named
regression for the ``process_pubrec`` durable-window persistence fix.
"""

import asyncio
import shutil
import tempfile

import pytest

from mqtt_tpu import Options
from mqtt_tpu.hooks.storage.logkv import LogKVOptions, LogKVStore
from mqtt_tpu.packets import (
    PUBCOMP,
    PUBLISH,
    PUBREC,
    PUBREL,
    FixedHeader,
    Packet,
    encode_packet,
)
from mqtt_tpu.scenarios import (
    SCENARIOS,
    DeliveryOracle,
    ScenarioBroker,
    ScenarioClient,
    ScenarioGate,
    run_scenario,
    scenario_names,
)
from mqtt_tpu.slo import parse_objectives
from mqtt_tpu.telemetry import Telemetry


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=60))


# -- oracle math -------------------------------------------------------------


class TestDeliveryOracle:
    def test_clean_run_settles_zero_gaps_zero_dups(self):
        o = DeliveryOracle("t")
        for k in ("a", "b", "c"):
            o.expect(k)
            o.deliver(k)
        s = o.summary()
        assert s == {
            "expected": 3,
            "delivered": 3,
            "gaps": 0,
            "duplicates": 0,
            "faults": 0,
        }
        assert o.complete()

    def test_gap_duplicate_and_unexpected_accounting(self):
        o = DeliveryOracle("t")
        o.expect("arrives")
        o.expect("lost")
        o.deliver("arrives")
        o.deliver("arrives")  # repeat of an expected key: 1 duplicate
        o.deliver("leak")  # nobody expected it: also budget spend
        o.fault(2)
        s = o.summary()
        assert s["gaps"] == 1
        assert s["duplicates"] == 2  # 1 repeat + 1 unexpected
        assert s["delivered"] == 3
        assert s["faults"] == 2
        assert not o.complete()

    def test_settle_publishes_labeled_counters(self):
        tel = Telemetry()
        o = DeliveryOracle("mytest")
        o.expect("k")
        o.deliver("k")
        o.settle(tel.registry)
        text = tel.registry.exposition()
        assert (
            'mqtt_tpu_scenario_expected_total{scenario="mytest"} 1' in text
        )
        assert (
            'mqtt_tpu_scenario_delivered_total{scenario="mytest"} 1' in text
        )
        assert 'mqtt_tpu_scenario_gaps_total{scenario="mytest"} 0' in text


# -- the SLO gate ------------------------------------------------------------


OBJ = (
    "scenario_gap ratio < 0.1% over 5s",
    "scenario_dup ratio < 0.1% over 5s",
)


class TestScenarioGate:
    def test_clean_oracle_passes(self):
        tel = Telemetry()
        gate = ScenarioGate(tel, OBJ)
        o = DeliveryOracle("clean")
        for i in range(100):
            o.expect(i)
            o.deliver(i)
        o.settle(tel.registry)
        ok, rows = gate.verdict()
        assert ok
        assert len(rows) == 2

    def test_injected_gaps_breach(self):
        tel = Telemetry()
        gate = ScenarioGate(tel, OBJ)
        o = DeliveryOracle("gappy")
        for i in range(100):
            o.expect(i)
            if i % 10:  # 10% of expected deliveries never arrive
                o.deliver(i)
        o.settle(tel.registry)
        ok, rows = gate.verdict()
        assert not ok
        breached = {r["spec"] for r in rows if r["breached"]}
        assert "scenario_gap ratio < 0.1% over 5s" in breached

    def test_injected_duplicates_breach(self):
        tel = Telemetry()
        gate = ScenarioGate(tel, OBJ)
        o = DeliveryOracle("dupey")
        for i in range(100):
            o.expect(i)
            o.deliver(i)
        for i in range(5):
            o.deliver(i)  # 5% exactly-once violations
        o.settle(tel.registry)
        ok, rows = gate.verdict()
        assert not ok
        breached = {r["spec"] for r in rows if r["breached"]}
        assert "scenario_dup ratio < 0.1% over 5s" in breached


# -- catalog sanity ----------------------------------------------------------


class TestCatalog:
    def test_matrix_covers_the_issue_and_seeds_are_unique(self):
        assert len(SCENARIOS) >= 6
        for required in (
            "payload_sweep",
            "qos2_fanout",
            "mixed_fleet",
            "will_storm",
            "bridge_federation",
            "tenant_rekey",
        ):
            assert required in SCENARIOS
        seeds = [s.seed for s in SCENARIOS.values()]
        assert len(set(seeds)) == len(seeds)
        for name, spec in SCENARIOS.items():
            assert spec.name == name

    def test_every_objective_parses(self):
        for spec in SCENARIOS.values():
            objs = parse_objectives(list(spec.objectives))
            assert objs, spec.name

    def test_smoke_subset_is_proper_and_nonempty(self):
        smoke = scenario_names(smoke_only=True)
        assert smoke
        assert set(smoke) < set(scenario_names())


# -- one scenario end-to-end -------------------------------------------------


class TestScenarioEndToEnd:
    def test_payload_sweep_runs_green(self):
        r = run_scenario("payload_sweep")
        assert r["passed"], r["failures"]
        assert r["oracle"]["gaps"] == 0
        assert r["oracle"]["duplicates"] == 0
        assert r["oracle"]["delivered"] == r["oracle"]["expected"] > 0
        assert r["slo"]["passed"]
        assert r["metrics"]["recrypt_fanouts"] > 0

    def test_seed_override_is_reported(self):
        # reseeding must be visible in the result doc (reproducibility
        # contract: the doc + seed is enough to replay the run)
        r = run_scenario("mixed_fleet", seed=4242)
        assert r["seed"] == 4242
        assert r["passed"], r["failures"]

    @pytest.mark.slow
    def test_full_matrix_is_green(self):
        from mqtt_tpu.scenarios import run_matrix

        results = run_matrix(scenario_names())
        failed = [r["scenario"] for r in results if not r["passed"]]
        assert not failed, failed


# -- QoS2 exactly-once regression suite --------------------------------------


class TestQoS2ExactlyOnce:
    """The named regressions behind the ``qos2_fanout`` scenario: the
    cross-shard ack cycle, session-present resume semantics, and the
    durable PUBLISH -> PUBREL window transition whose absence re-sent
    already-PUBREC'd messages across a kill -9 ([MQTT-4.3.3-6])."""

    def test_cross_shard_pubrec_pubrel_pubcomp_cycle(self):
        async def drill():
            b = await ScenarioBroker(
                Options(inline_client=False, loop_shards=2)
            ).start()
            got: list[tuple[str, str]] = []
            subs = []
            try:
                for i in range(4):
                    c = ScenarioClient(b.port, f"x-{i}")
                    await c.connect()
                    c.on_publish = (
                        lambda t, p, pk, cid=c.cid: got.append((cid, bytes(p).decode()))
                    )
                    await c.subscribe("x/t", qos=2)
                    subs.append(c)
                pub = ScenarioClient(b.port, "x-pub")
                await pub.connect()
                subs.append(pub)
                for seq in range(3):
                    await pub.publish("x/t", f"m{seq}".encode(), qos=2)
                for _ in range(200):
                    if len(got) >= 12 and b.total_inflight() == 0:
                        break
                    await asyncio.sleep(0.02)
                assert sorted(got) == sorted(
                    (f"x-{i}", f"m{s}") for i in range(4) for s in range(3)
                )
                assert b.total_inflight() == 0
            finally:
                for c in subs:
                    await c.close()
                await b.stop()

        run(drill())

    def test_reconnect_session_present_resends_pubrel_not_publish(self):
        """A receiver that PUBREC'd then dropped must resume with the
        broker re-sending PUBREL — a repeat PUBLISH would be delivered
        twice ([MQTT-4.3.3-6]). In-memory sessions: the inflight window
        itself was flipped to a PUBREL packet by process_pubrec."""

        async def drill():
            b = await ScenarioBroker(Options(inline_client=False)).start()
            publishes: list[bytes] = []
            try:
                c = ScenarioClient(b.port, "rp")
                await c.connect(clean=False)
                c.withhold_pubcomp = True
                c.on_publish = lambda t, p, pk: publishes.append(bytes(p))
                await c.subscribe("rp/t", qos=2)
                pub = ScenarioClient(b.port, "rp-pub")
                await pub.connect()
                await pub.publish("rp/t", b"once", qos=2)
                for _ in range(100):
                    if c.pubrel_seen:
                        break
                    await asyncio.sleep(0.02)
                assert c.pubrel_seen == {1}
                c.abort()
                await c.close()

                c2 = ScenarioClient(b.port, "rp")
                c2.on_publish = lambda t, p, pk: publishes.append(bytes(p))
                present = await c2.connect(clean=False)
                assert present
                for _ in range(100):
                    if c2.pubrel_seen and b.total_inflight() == 0:
                        break
                    await asyncio.sleep(0.02)
                assert c2.pubrel_seen == {1}  # resumed at PUBREL...
                assert publishes == [b"once"]  # ...not with a repeat
                assert b.total_inflight() == 0
                await c2.close()
                await pub.close()
            finally:
                await b.stop()

        run(drill())

    def test_duplicate_publish_after_reconnect_is_suppressed(self):
        """A sender that reconnects (session-present) before PUBREL and
        re-sends the PUBLISH with DUP must get a fresh PUBREC and NO
        second fan-out ([MQTT-4.3.3-10]): the open receiver window is
        the dedup state."""

        def raw_publish(c, pid, dup):
            c.writer.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=PUBLISH, qos=2, dup=dup),
                        protocol_version=4,
                        topic_name="dup/t",
                        packet_id=pid,
                        payload=b"once",
                    )
                )
            )

        async def drill():
            b = await ScenarioBroker(Options(inline_client=False)).start()
            got: list[bytes] = []
            try:
                sub = ScenarioClient(b.port, "dup-sub")
                await sub.connect()
                sub.on_publish = lambda t, p, pk: got.append(bytes(p))
                await sub.subscribe("dup/t", qos=2)

                pub = ScenarioClient(b.port, "dup-pub")
                await pub.connect(clean=False)
                rec = pub._future(PUBREC, 7)
                raw_publish(pub, 7, dup=False)  # ...but never PUBREL
                await asyncio.wait_for(rec, 10)
                await _wait(lambda: len(got) == 1)
                pub.abort()
                await pub.close()

                pub2 = ScenarioClient(b.port, "dup-pub")
                present = await pub2.connect(clean=False)
                assert present
                rec2 = pub2._future(PUBREC, 7)
                raw_publish(pub2, 7, dup=True)  # the reconnect re-send
                await asyncio.wait_for(rec2, 10)  # re-acknowledged...
                await asyncio.sleep(0.2)
                assert got == [b"once"]  # ...but never re-delivered
                comp = pub2._future(PUBCOMP, 7)
                pub2._send(PUBREL, 7, qos=1)
                await asyncio.wait_for(comp, 10)
                assert await _wait(lambda: b.total_inflight() == 0)
                await pub2.close()
                await sub.close()
            finally:
                await b.stop()

        async def _wait(cond, timeout=10.0):
            for _ in range(int(timeout / 0.02)):
                if cond():
                    return True
                await asyncio.sleep(0.02)
            return cond()

        run(drill())

    def test_pubrec_flips_the_durable_record_to_pubrel(self):
        """THE regression for the process_pubrec persistence fix: once
        PUBREC arrives, the stored inflight record must carry PUBREL —
        before the fix it stayed PUBLISH and every crash-restore
        re-delivered the message."""

        async def drill(path):
            b = ScenarioBroker(Options(inline_client=False))
            store = LogKVStore()
            b.server.add_hook(store, LogKVOptions(path=path, gc_interval=0))
            await b.start()
            try:
                c = ScenarioClient(b.port, "dr")
                await c.connect(clean=False)
                c.withhold_pubcomp = True
                await c.subscribe("dr/t", qos=2)
                pub = ScenarioClient(b.port, "dr-pub")
                await pub.connect()
                await pub.publish("dr/t", b"x", qos=2)
                for _ in range(100):
                    if c.pubrel_seen:
                        break
                    await asyncio.sleep(0.02)
                assert c.pubrel_seen
                recs = [
                    m
                    for m in store.stored_inflight_messages()
                    if m.client == "dr"
                ]
                assert len(recs) == 1
                assert recs[0].fixed_header_type == PUBREL
                assert recs[0].fixed_header_type != PUBLISH
                await c.close()
                await pub.close()
            finally:
                await b.stop()
                store.stop()

        tmp = tempfile.mkdtemp(prefix="q2-rec-")
        try:
            run(drill(tmp + "/kv"))
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    def test_kill9_mid_window_resume_is_exactly_once(self):
        """Freeze a QoS2 session at the PUBREL stage, copy the store the
        way kill -9 leaves it, boot a second broker life on the image:
        the restored window must finish via PUBREL/PUBCOMP with zero
        repeat PUBLISHes, through the batched inflight restore plane."""

        async def drill(path, crash):
            publishes: list[bytes] = []
            b1 = ScenarioBroker(Options(inline_client=False))
            store = LogKVStore()
            b1.server.add_hook(store, LogKVOptions(path=path, gc_interval=0))
            await b1.start()
            try:
                c = ScenarioClient(b1.port, "k9")
                await c.connect(clean=False)
                c.withhold_pubcomp = True
                c.on_publish = lambda t, p, pk: publishes.append(bytes(p))
                await c.subscribe("k9/t", qos=2)
                pub = ScenarioClient(b1.port, "k9-pub")
                await pub.connect()
                for seq in range(3):
                    await pub.publish("k9/t", f"v{seq}".encode(), qos=2)
                for _ in range(100):
                    if len(c.pubrel_seen) >= 3:
                        break
                    await asyncio.sleep(0.02)
                assert len(c.pubrel_seen) == 3
                assert len(publishes) == 3
                store.sync()
                shutil.copytree(path, crash)
                await pub.close()
            finally:
                c.abort()
                await c.close()
                await b1.stop()
                store.stop()

            b2 = ScenarioBroker(Options(inline_client=False))
            b2.server.add_hook(
                LogKVStore(), LogKVOptions(path=crash, gc_interval=0)
            )
            await b2.start()  # serve() replays the image via read_store
            try:
                assert b2.server._durable["restored_inflight"] >= 3
                c2 = ScenarioClient(b2.port, "k9")
                c2.on_publish = lambda t, p, pk: publishes.append(bytes(p))
                present = await c2.connect(clean=False)
                assert present
                for _ in range(200):
                    if (
                        len(c2.pubrel_seen) >= 3
                        and b2.total_inflight() == 0
                    ):
                        break
                    await asyncio.sleep(0.02)
                assert len(c2.pubrel_seen) == 3
                assert b2.total_inflight() == 0
                # the exactly-once assertion: life 1 delivered all 3,
                # life 2 must add NOTHING
                assert sorted(publishes) == [b"v0", b"v1", b"v2"]
                await c2.close()
            finally:
                await b2.stop()

        tmp = tempfile.mkdtemp(prefix="q2-k9-")
        try:
            run(drill(tmp + "/kv", tmp + "/kv-crash"))
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
