"""Device-resident hit compaction (ISSUE 11): seeded parity of the
compacted (topic_idx, sid) pair path against the host trie oracle across
exact/`+`/`#`/`$SHARE`/predicated subscriptions, capacity edge cases
(hits == capacity, hits > capacity per-batch fallback), empty batches,
the C-vs-Python materializer differential, the sharded gathered-result
compaction, the 3-deep pipelined staging's per-leg accounting, the
buffered-window device aggregation reductions, and a chaos leg where the
breaker degrades mid-pipeline with batches in flight."""

import asyncio
import json
import random

import numpy as np
import pytest

import jax.numpy as jnp

from mqtt_tpu.ops.flat import _bucket, build_flat_index, flat_match_compact, pack_tokens
from mqtt_tpu.ops.hashing import tokenize_topics
from mqtt_tpu.ops.matcher import TpuMatcher, resolve_compact_py
from mqtt_tpu.ops.delta import DeltaMatcher
from mqtt_tpu.packets import Subscription
from mqtt_tpu.topics import SHARE_PREFIX, InlineSubscription, Subscribers, TopicsIndex
from mqtt_tpu import native

from tests.test_ops_matcher import canon
from tests.test_server import run


def _noop(*_a) -> None:
    pass


def build_index(seed: int, n: int = 400) -> tuple[TopicsIndex, list]:
    """A seeded subscription mix over every gather class: exact, `+`,
    `#`, `$SHARE` groups, inline, sub identifiers."""
    r = random.Random(seed)
    segs = [f"s{i}" for i in range(10)]
    index = TopicsIndex()
    for i in range(n):
        parts = [r.choice(segs) for _ in range(r.randint(1, 4))]
        roll = r.random()
        if roll < 0.2:
            parts[r.randrange(len(parts))] = "+"
        elif roll < 0.3:
            parts = parts[: r.randint(1, len(parts))] + ["#"]
        flt = "/".join(parts)
        if r.random() < 0.1:
            flt = f"{SHARE_PREFIX}/grp{r.randrange(4)}/{flt}"
        index.subscribe(
            f"cl{i}",
            Subscription(filter=flt, qos=i % 3, identifier=i % 5),
        )
    index.inline_subscribe(
        InlineSubscription(filter="s1/#", identifier=777, handler=_noop)
    )

    def topic_gen():
        parts = [r.choice(segs) for _ in range(r.randint(1, 5))]
        if r.random() < 0.05:
            parts[0] = "$SYS"
        return "/".join(parts)

    return index, topic_gen


def assert_parity(matcher, index, topics):
    for t, dev in zip(topics, matcher.match_topics(topics)):
        if t:
            assert canon(dev) == canon(index.subscribers(t)), t
        else:
            assert canon(dev) == canon(Subscribers())


class TestCompactParity:
    @pytest.mark.parametrize("seed", [3, 17, 90125])
    def test_seeded_mix_matches_host_oracle(self, seed):
        index, topic_gen = build_index(seed)
        m = TpuMatcher(index, max_levels=4)
        m.rebuild()
        topics = [topic_gen() for _ in range(150)] + ["", "a/b/c/d/e/f"]
        # first batch may overflow the seed capacity (high fan-in
        # seeds): the per-batch fallback serves it bit-identically and
        # teaches the EWMA, so the SECOND batch always compacts
        assert_parity(m, index, topics)
        assert_parity(m, index, topics)
        assert m.stats.compact_batches >= 1
        assert m.stats.compact_overflows <= 1
        assert m.stats.d2h_bytes > 0

    def test_empty_batch_and_empty_index(self):
        index, _ = build_index(1, n=0)
        m = TpuMatcher(index, max_levels=4)
        m.rebuild()
        assert m.match_topics([]) == []
        assert canon(m.match_topics(["a/b"])[0]) == canon(Subscribers())
        index2, topic_gen = build_index(5)
        m2 = TpuMatcher(index2, max_levels=4)
        m2.rebuild()
        assert m2.match_topics([]) == []

    def test_compact_off_still_bit_identical(self):
        index, topic_gen = build_index(23)
        m = TpuMatcher(index, max_levels=4, compact=False)
        m.rebuild()
        topics = [topic_gen() for _ in range(80)]
        assert_parity(m, index, topics)
        assert m.stats.compact_batches == 0

    def test_delta_churn_keeps_parity(self):
        """Compaction under the delta overlay: mutated filters host-route
        until folded, compacted results stay bit-identical throughout."""
        index, topic_gen = build_index(41)
        dm = DeltaMatcher(index, max_levels=4, background=False)
        try:
            topics = [topic_gen() for _ in range(60)]
            assert_parity(dm, index, topics)
            index.subscribe("late", Subscription(filter="s1/+", qos=1))
            index.unsubscribe("s1/s2", "cl3")
            assert_parity(dm, index, topics)  # overlay host-routes
            dm.flush()
            assert_parity(dm, index, topics)  # folded snapshot
        finally:
            dm.close()


class TestCapacityEdges:
    def _kernel_out(self, capacity):
        index, topic_gen = build_index(7)
        flat = build_flat_index(index, max_levels=4)
        arrays = tuple(
            jnp.asarray(a)
            for a in (flat.table, flat.pat_kind, flat.pat_depth, flat.pat_mask)
        )
        r = random.Random(7)
        topics = [topic_gen() for _ in range(40)]
        padded = topics + [""] * (_bucket(len(topics), minimum=16) - len(topics))
        tok = tokenize_topics(padded, flat.max_levels, flat.salt)
        out = np.asarray(
            flat_match_compact(
                *arrays,
                jnp.asarray(pack_tokens(*tok[:4])),
                max_levels=flat.max_levels,
                capacity=capacity,
            )
        )
        return out, len(padded)

    def test_hits_equal_capacity_is_not_overflow(self):
        out, bp = self._kernel_out(4096)
        n_hits = int(out[0])
        assert n_hits > 0
        exact, _ = self._kernel_out(n_hits)
        assert int(exact[0]) == n_hits
        assert int(exact[1]) == 0  # hits == capacity fits exactly
        # every pair slot is real (no -1 padding left)
        assert (exact[2 + 2 * bp :] >= 0).all()

    def test_hits_past_capacity_sets_the_flag(self):
        out, _bp = self._kernel_out(4096)
        n_hits = int(out[0])
        over, _ = self._kernel_out(max(1, n_hits - 1))
        assert int(over[1]) == 1
        # the TRUE hit count still reports (the capacity EWMA feeds on it)
        assert int(over[0]) == n_hits

    def test_matcher_overflow_falls_back_per_batch_and_recovers(self):
        index, topic_gen = build_index(7)
        m = TpuMatcher(index, max_levels=4, compact_capacity=8)
        m.rebuild()
        topics = [topic_gen() for _ in range(60)]
        assert_parity(m, index, topics)
        assert m.stats.compact_overflows == 1
        assert m.stats.compact_batches == 0
        # the overflow taught the EWMA the true rate: an ADAPTIVE matcher
        # seeded by it compacts the very next batch
        m.compact_capacity = 0
        m._hits_ewma = max(m._hits_ewma, 1.0)
        assert_parity(m, index, topics)
        assert m.stats.compact_batches >= 1


class TestMaterializerDifferential:
    def test_c_and_python_pair_expansion_identical(self):
        acc = native.accel()
        if acc is None or not hasattr(acc, "resolve_compact"):
            pytest.skip("C materializer unavailable")
        index, topic_gen = build_index(13)
        m = TpuMatcher(index, max_levels=4)
        m.rebuild()
        flat = m.csr
        topics = [topic_gen() for _ in range(50)] + [""]
        padded = topics + [""] * (_bucket(len(topics), minimum=16) - len(topics))
        tok = tokenize_topics(padded, flat.max_levels, flat.salt)
        cap = 4096
        out = np.asarray(
            flat_match_compact(
                *m.device_arrays,
                jnp.asarray(pack_tokens(*tok[:4])),
                max_levels=flat.max_levels,
                capacity=cap,
            )
        )
        bp = len(padded)
        n_hits = int(out[0])
        totals = out[2 : 2 + bp]
        route = out[2 + bp : 2 + 2 * bp].astype(np.int32)
        sids = out[2 + 2 * bp : 2 + 2 * bp + cap]
        res_c, ovf_c = acc.resolve_compact(
            np.ascontiguousarray(sids), None, np.ascontiguousarray(totals),
            np.ascontiguousarray(route), n_hits, len(topics),
            flat.subs.snaps, flat.window, Subscribers,
        )
        res_p, ovf_p = resolve_compact_py(
            sids, None, totals, route.astype(bool), topics, flat.subs
        )
        assert ovf_c == ovf_p
        for a, b in zip(res_c, res_p):
            if a is None or b is None:
                assert a is None and b is None
            else:
                assert canon(a) == canon(b)

    def test_python_rejects_mismatched_geometry(self):
        """The Python expansion enforces the same tripwire as the C
        path: totals that disagree with the pair stream raise instead
        of silently truncating the slices."""
        sids = np.zeros(8, dtype=np.int32)
        totals = np.full(4, 2, dtype=np.int32)
        route = np.zeros(4, dtype=bool)
        with pytest.raises(ValueError):
            resolve_compact_py(
                sids, None, totals, route, ["a"] * 4, None, n_hits=3
            )

    def test_c_rejects_mismatched_geometry(self):
        acc = native.accel()
        if acc is None or not hasattr(acc, "resolve_compact"):
            pytest.skip("C materializer unavailable")
        sids = np.zeros(8, dtype=np.int32)
        totals = np.full(4, 2, dtype=np.int32)
        route = np.zeros(4, dtype=np.int32)
        with pytest.raises(ValueError):
            # totals claim 8 pairs but n_hits says 3: never mis-expand
            acc.resolve_compact(
                sids, None, totals, route, 3, 4, [], 16, Subscribers
            )


class TestShardedCompact:
    def _mesh_matcher(self, index, **kw):
        from mqtt_tpu.parallel import ShardedTpuMatcher, make_mesh

        return ShardedTpuMatcher(index, mesh=make_mesh(), max_levels=4, **kw)

    def test_gathered_compaction_matches_host_oracle(self):
        index, topic_gen = build_index(29)
        m = self._mesh_matcher(index)
        try:
            topics = [topic_gen() for _ in range(60)] + [""]
            assert_parity(m, index, topics)  # may overflow: EWMA learns
            before = m.stats.d2h_bytes
            assert_parity(m, index, topics)  # compacts
            assert m.stats.compact_batches >= 1
            # the compacted transfer beats the padded [S, B, K] buffer
            bp = _bucket(61, minimum=max(2, m.n_batch))
            bp += (-bp) % m.n_batch
            padded_bytes = m.n_shards * bp * m.out_slots * 4
            assert m.stats.d2h_bytes - before < padded_bytes
        finally:
            m.close()

    def test_sharded_overflow_falls_back_per_batch(self):
        index, topic_gen = build_index(29)
        m = self._mesh_matcher(index, compact_capacity=8)
        try:
            topics = [topic_gen() for _ in range(60)]
            assert_parity(m, index, topics)
            assert m.stats.compact_overflows >= 1
        finally:
            m.close()


class TestPipelinedStaging:
    def test_leg_waits_and_inflight_accounting(self):
        """A few batches through the 3-deep pipeline: both leg-wait
        histograms populate, the in-flight gauge returns to zero, and
        every result is host-parity."""
        from mqtt_tpu.staging import MatchStage
        from mqtt_tpu.telemetry import Telemetry

        index, topic_gen = build_index(53)
        m = TpuMatcher(index, max_levels=4)
        m.rebuild()
        tel = Telemetry(sample=0)

        async def scenario():
            stage = MatchStage(
                m, index.subscribers, window_s=0.001, telemetry=tel,
                pipeline_depth=3,
            )
            assert stage.pipeline_depth == 3
            stage.start()
            topics = [topic_gen() for _ in range(120)]
            for burst in range(0, 120, 40):
                futs = [stage.submit(t) for t in topics[burst : burst + 40]]
                got = await asyncio.gather(*futs)
                for t, subs in zip(topics[burst : burst + 40], got):
                    assert canon(subs) == canon(index.subscribers(t))
                await asyncio.sleep(0.01)
            await stage.stop()
            assert stage.inflight_batches == 0

        run(scenario())
        assert tel.leg_wait["h2d"].count >= 3
        assert tel.leg_wait["d2h"].count >= 3
        block = tel.bench_block()
        assert "leg_wait_h2d" in block["stages"]
        assert "leg_wait_d2h" in block["stages"]

    def test_pipeline_depth_zero_falls_back_to_max_inflight(self):
        from mqtt_tpu.staging import MatchStage

        stage = MatchStage(
            object(), lambda t: Subscribers(), max_inflight=5,
            pipeline_depth=0,
        )
        assert stage.pipeline_depth == 5


class TestPredicatedStagedBroker:
    def test_predicated_delivery_through_compacted_pipeline(self):
        """MQTT+ predicate filtering rides the compacted staged batch:
        a `$GT` subscriber sees only passing payloads, a plain wildcard
        subscriber sees everything, and the matcher compacted."""
        from mqtt_tpu import Options
        from mqtt_tpu.packets import PUBLISH, SUBACK
        from tests.test_server import (
            Harness, pub_packet, read_wire_packet, sub_packet,
        )

        async def scenario():
            h = Harness(
                Options(
                    inline_client=True,
                    device_matcher=True,
                    matcher_stage_window_ms=5.0,
                    matcher_opts={"max_levels": 4, "background": False},
                    matcher_compact=True,
                    matcher_stage_pipeline_depth=3,
                )
            )
            await h.server.serve()
            pred_r, pred_w, _ = await h.connect("sub-pred")
            pred_w.write(
                sub_packet(1, [Subscription(filter="t/+/v$GT{n:5.0}", qos=0)])
            )
            await pred_w.drain()
            assert (await read_wire_packet(pred_r)).fixed_header.type == SUBACK
            wild_r, wild_w, _ = await h.connect("sub-wild")
            wild_w.write(sub_packet(1, [Subscription(filter="t/#", qos=0)]))
            await wild_w.drain()
            assert (await read_wire_packet(wild_r)).fixed_header.type == SUBACK

            # fold the subscribe mutations into a fresh snapshot so the
            # publishes take the compacted device path instead of the
            # delta overlay's host route
            h.server.matcher.flush()
            pub_r, pub_w, _ = await h.connect("pub")
            payloads = [
                json.dumps({"n": n}).encode() for n in (1.0, 9.0, 3.0, 7.5)
            ]
            for i, p in enumerate(payloads):
                pub_w.write(pub_packet(f"t/d{i}/v", p, qos=0))
            await pub_w.drain()

            async def read_n(reader, n):
                got = []
                for _ in range(n):
                    pk = await asyncio.wait_for(read_wire_packet(reader), 5)
                    assert pk.fixed_header.type == PUBLISH
                    got.append(pk.payload)
                return got

            wild_got = await read_n(wild_r, 4)
            pred_got = await read_n(pred_r, 2)
            assert sorted(wild_got) == sorted(payloads)
            assert sorted(pred_got) == sorted(
                [json.dumps({"n": n}).encode() for n in (9.0, 7.5)]
            )
            stats = h.server.matcher.stats
            assert stats.compact_batches >= 1
            await h.server.close()
            await h.shutdown()

        run(scenario())


class TestChaosMidPipeline:
    def test_breaker_degrades_with_batches_in_flight(self):
        """The chaos leg: seeded device faults under the full stack
        (FaultyMatcher -> ResilientMatcher -> 3-deep MatchStage). The
        breaker trips mid-pipeline with compacted batches in flight;
        every future still resolves bit-identical to the host trie."""
        from mqtt_tpu.faults import FaultPlan, FaultyMatcher
        from mqtt_tpu.resilience import BreakerConfig, ResilientMatcher
        from mqtt_tpu.staging import MatchStage

        index, topic_gen = build_index(67)
        inner = TpuMatcher(index, max_levels=4)
        inner.rebuild()
        plan = FaultPlan(
            seed=9, error_rate=0.3, issue_error_rate=0.1,
            at={2: "error", 3: "error"},
        )
        faulty = FaultyMatcher(inner, plan)
        resilient = ResilientMatcher(
            faulty,
            index,
            BreakerConfig(
                failure_threshold=2, probe_backoff_s=30.0, seed=4,
                verify_sample=1, watchdog_s=5.0,
            ),
        )

        async def scenario():
            stage = MatchStage(
                resilient, index.subscribers, window_s=0.001,
                pipeline_depth=3,
            )
            stage.start()
            try:
                for _ in range(12):
                    topics = [topic_gen() for _ in range(25)]
                    futs = [stage.submit(t) for t in topics]
                    got = await asyncio.gather(*futs)
                    for t, subs in zip(topics, got):
                        assert canon(subs) == canon(index.subscribers(t))
            finally:
                await stage.stop()

        try:
            run(scenario())
            # the seeded plan guarantees consecutive failures: the
            # breaker tripped and host fallbacks served traffic
            assert resilient.breaker.trips >= 1
            assert resilient.fallback_batches >= 1
        finally:
            resilient.close()


class TestDeviceAggReduction:
    def _engine(self, min_batch=1, **kw):
        from mqtt_tpu.predicates import PredicateEngine

        eng = PredicateEngine(oracle_sample=0, **kw)
        # most tests complete one or two windows per tick; production
        # gates the dispatch on a real batch (device_agg_min_batch=4)
        eng.device_agg_min_batch = min_batch
        return eng

    def _subs(self, *entries):
        s = Subscribers()
        for cid, sub in entries:
            s.subscriptions[cid] = sub
        return s

    def test_large_windows_reduce_on_device(self):
        eng = self._engine(device_agg_min_window=4)
        eng.register("$MEAN{v:5}")
        eng.register("$MAX{v:4}")
        eng.register("$MIN{v:4}")
        sub_mean = Subscription(filter="t", predicates=("$MEAN{v:5}",))
        sub_max = Subscription(filter="t", predicates=("$MAX{v:4}",))
        sub_min = Subscription(filter="t", predicates=("$MIN{v:4}",))
        emitted = []
        vals = [3.0, 9.0, 1.5, 6.0, 0.5]
        for v in vals:
            subs = self._subs(("m", sub_mean), ("x", sub_max), ("n", sub_min))
            _out, emissions = eng.apply(subs, json.dumps({"v": v}).encode())
            emitted.extend(emissions)
        assert eng.agg_device_reductions >= 2  # max + min windows (4 wide)
        got = {(k, t): p for k, t, _s, p in emitted}
        assert got[("client", "x")] == b"9"  # max(3, 9, 1.5, 6) exact
        assert got[("client", "n")] == b"1.5"  # min exact
        mean = float(got[("client", "m")])
        assert abs(mean - sum(vals) / 5) < 1e-4  # float32 device mean

    def test_small_windows_keep_the_host_accumulator(self):
        eng = self._engine(device_agg_min_window=32)
        eng.register("$MEAN{v:3}")
        sub = Subscription(filter="t", predicates=("$MEAN{v:3}",))
        emitted = []
        for v in (1.0, 2.0, 6.0):
            _out, emissions = eng.apply(
                self._subs(("m", sub)), json.dumps({"v": v}).encode()
            )
            emitted.extend(emissions)
        assert eng.agg_device_reductions == 0
        assert emitted[0][3] == b"3"

    def test_device_fault_degrades_to_host_reduction(self, monkeypatch):
        import mqtt_tpu.ops.predicates as opspred

        def boom(_pending):
            raise RuntimeError("injected device fault")

        monkeypatch.setattr(opspred, "agg_reduce_batch", boom)
        eng = self._engine(device_agg_min_window=2)
        eng.register("$MAX{v:3}")
        sub = Subscription(filter="t", predicates=("$MAX{v:3}",))
        emitted = []
        for v in (1.0, 7.0, 2.0):
            _out, emissions = eng.apply(
                self._subs(("m", sub)), json.dumps({"v": v}).encode()
            )
            emitted.extend(emissions)
        assert emitted[0][3] == b"7"  # host fallback, value intact
        assert eng.agg_device_reductions == 0
        assert eng.device_errors >= 1

    def test_oracle_samples_device_reductions(self):
        eng = self._engine(device_agg_min_window=2)
        eng.oracle_sample = 1  # every apply checks
        eng.register("$MAX{v:2}")
        sub = Subscription(filter="t", predicates=("$MAX{v:2}",))
        # non-float32-representable samples: the oracle must still agree
        # exactly (both sides reduce float32-coerced values)
        for v in (0.1, 0.30000000000004):
            eng.apply(self._subs(("m", sub)), json.dumps({"v": v}).encode())
        assert eng.agg_device_reductions >= 1
        assert eng.oracle_checks >= 1
        assert eng.oracle_mismatches == 0

    def test_single_window_ticks_stay_on_host(self):
        """Below device_agg_min_batch the samples (already host-resident)
        reduce on host — no device round trip for one window."""
        eng = self._engine(min_batch=4, device_agg_min_window=2)
        eng.register("$MAX{v:2}")
        sub = Subscription(filter="t", predicates=("$MAX{v:2}",))
        emitted = []
        for v in (1.0, 7.0):
            _out, emissions = eng.apply(
                self._subs(("m", sub)), json.dumps({"v": v}).encode()
            )
            emitted.extend(emissions)
        assert eng.agg_device_reductions == 0
        assert emitted[0][3] == b"7"

    def test_open_breaker_serves_windows_from_host_silently(self):
        """An open breaker must not pay a failing dispatch per tick:
        windows reduce on host with no device attempt at all."""
        eng = self._engine(device_agg_min_window=2)
        eng.breaker.record_failure("agg")
        eng.breaker.record_failure("agg")
        eng.breaker.record_failure("agg")
        assert not eng.breaker.allow()
        eng.register("$MIN{v:2}")
        sub = Subscription(filter="t", predicates=("$MIN{v:2}",))
        emitted = []
        for v in (5.0, 2.0):
            _out, emissions = eng.apply(
                self._subs(("m", sub)), json.dumps({"v": v}).encode()
            )
            emitted.extend(emissions)
        assert eng.agg_device_reductions == 0
        assert eng.device_errors == 0  # no failing dispatch was attempted
        assert emitted[0][3] == b"2"
