"""The loop-affinity witness⊆static cross-validation gate (ISSUE 19).

tests/conftest.py arms ``LoopWitness`` on the process-wide loop plane
for the ENTIRE session, so by the time this file runs (named ``zz`` to
sort last under ``-p no:randomly``) the witness has accumulated every
(kind, seam) affinity crossing the whole tier-1 suite provoked at the
instrumented touch points (OutboundQueue, ClientState delivery seams,
staging submit/resolve, cluster writer dispatch, shard task tracking).
The gate asserts each observed crossing is blessed by the
``LOOP_AFFINITY`` table AND backed by the statically extracted model
(tools/brokerlint/loopgraph.py): an unexplained runtime crossing is a
model gap — the static rules would be silently blind to a whole class
of cross-loop traffic — and fails tier-1 loudly. It also asserts ZERO
guarded touches ran off their owning loop across the entire session.

The file drives the canonical cross-shard seams directly (a staged
2-shard broker, QoS1 delivery publisher→subscriber across shards), so
the gate is meaningful even when run standalone instead of
last-in-suite.
"""

import asyncio
import os
import threading

from mqtt_tpu.clients import OutboundQueue
from mqtt_tpu.packets import (
    PUBACK,
    PUBLISH,
    FixedHeader,
    Packet,
    Subscription,
)
from mqtt_tpu.utils.loopwitness import DEFAULT_LOOP_PLANE

from tools.brokerlint.core import collect_files, load_ctx
from tools.brokerlint.loopgraph import (
    AFFINITY_HOME,
    LOOP_AFFINITY,
    extract_loop_graph,
)

from tests.test_server import pub_packet, read_wire_packet, run
from tests.test_shards import TIMEOUT, FabricHarness, collect_publishes

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _static_model():
    ctxs = [
        load_ctx(p, _ROOT)
        for p in collect_files([os.path.join(_ROOT, "mqtt_tpu")], _ROOT)
    ]
    return extract_loop_graph(ctxs)


def _drive_canonical_seams():
    """Provoke the known affinity crossings a quiet standalone run might
    not have touched yet: a staged 2-shard broker with publisher and
    subscriber on different shards — QoS1 delivery marshals the
    client-state touch to the owner loop, the fan-out enqueues onto a
    foreign shard's outbound queue, and the staged matcher parks/
    resolves futures across the stage boundary."""

    async def scenario():
        h = await FabricHarness(
            shards=2,
            device_matcher=True,
            matcher_stage_window_ms=1.0,
            matcher_opts={"max_levels": 4, "background": False},
        ).start()
        try:
            sub_r, sub_w, _ = await h.connect("wit-sub")
            pub_r, pub_w, _ = await h.connect("wit-pub")
            assert h.shard_of("wit-sub") is not h.shard_of("wit-pub")
            await h.subscribe(
                sub_r, sub_w, 1, [Subscription(filter="wit/#", qos=1)]
            )
            h.server.matcher.flush()
            for i in range(4):
                pub_w.write(
                    pub_packet(f"wit/{i}", b"x", qos=1, pid=10 + i)
                )
            await pub_w.drain()
            for _ in range(4):
                ack = await asyncio.wait_for(
                    read_wire_packet(pub_r, 4), TIMEOUT
                )
                assert ack.fixed_header.type == PUBACK
            assert len(await collect_publishes(sub_r, 4)) == 4
            # the QoS0 leg fans out INLINE from the publisher's shard
            # (no alias state to marshal): the enqueue onto the
            # subscriber's thread-safe queue is the put_cross seam
            for i in range(4):
                pub_w.write(pub_packet(f"wit/z{i}", b"y", qos=0))
            await pub_w.drain()
            assert len(await collect_publishes(sub_r, 4)) == 4
            # the per-subscriber marshal seam: a QoS1 delivery issued
            # from a loop that does NOT own the subscriber (here: the
            # main test loop) must route through _deliver_remote on the
            # owner shard — the deliver_marshal crossing
            scl = h.server.clients.get("wit-sub")
            assert scl is not None
            dpk = Packet(
                fixed_header=FixedHeader(type=PUBLISH, qos=1),
                protocol_version=4,
                topic_name="wit/direct",
                payload=b"d",
            )
            sub = Subscription(filter="wit/#", qos=1)
            inline = h.server._deliver_to_client(scl, sub, dpk)
            assert inline is False  # marshaled, not run inline
            assert len(await collect_publishes(sub_r, 1)) == 1
        finally:
            await h.stop()

    run(scenario())

    async def queue_leg():
        # the any-thread enqueue contract, exercised directly: the
        # broker's shared-frame fan-out marshals whole per-shard groups
        # onto owner loops (put_local), so a quiet run may never
        # cross-put through the broker itself — but the queue's seam
        # contract is any-thread, and test_shards drives it under load
        q = OutboundQueue(maxsize=4)
        getter = asyncio.ensure_future(q.get())
        await asyncio.sleep(0)  # park the consumer (stamps the owner)
        t = threading.Thread(
            target=q.put_nowait, args=(b"x",), name="wit-putter"
        )
        t.start()
        t.join()
        assert await asyncio.wait_for(getter, TIMEOUT) == b"x"

    run(queue_leg())


class TestLoopWitnessCrossValidation:
    def test_witness_seams_all_blessed_and_model_backed(self):
        """THE gate: every (kind, seam) crossing the runtime witness
        observed — across everything the session ran before this file,
        plus the canonical drive above — must appear in the blessed
        LOOP_AFFINITY table AND in the extracted model's seam set (the
        blessed pairs whose owning constructs / marshal sites really
        exist in the source)."""
        witness = DEFAULT_LOOP_PLANE.witness
        assert witness is not None, (
            "conftest must arm the session loop witness "
            "(DEFAULT_LOOP_PLANE.arm_witness()) for the gate to mean "
            "anything"
        )
        _drive_canonical_seams()
        blessed = set(LOOP_AFFINITY)
        model = _static_model().seams()
        observed = dict(witness.edges)
        unblessed = {
            e: ev for e, ev in observed.items() if e not in blessed
        }
        assert not unblessed, (
            "runtime affinity crossings missing from LOOP_AFFINITY "
            "(model gap — bless the seam in tools/brokerlint/"
            "loopgraph.py in review, or fix the code): "
            + "; ".join(
                f"{k}/{s} first seen on thread {ev[0]} ({ev[1]})"
                for (k, s), ev in sorted(unblessed.items())
            )
        )
        unmodeled = {e: ev for e, ev in observed.items() if e not in model}
        assert not unmodeled, (
            "observed seams whose static evidence (owning construct / "
            "marshal site) was not extracted: "
            + "; ".join(f"{k}/{s}" for (k, s) in sorted(unmodeled))
        )
        # the canonical drive must really have crossed the flagship
        # seams, or this gate is vacuously green
        assert ("outbound_queue", "get_owner") in observed
        assert ("outbound_queue", "put_cross") in observed
        assert ("client_state", "deliver_marshal") in observed
        assert ("match_stage", "submit_cross") in observed

    def test_witness_saw_no_affinity_violations(self):
        """Zero guarded touches off their owning loop across the whole
        suite — the dynamic mirror of R10/R12's static contracts."""
        witness = DEFAULT_LOOP_PLANE.witness
        assert witness is not None
        assert witness.violations == [], witness.violations

    def test_blessed_table_is_model_consistent(self):
        """Model sanity: every blessed kind names a home module that
        exists, every kind's owning construct extracts from the live
        tree, and every cross/marshal seam's home really contains a
        marshal call site — the static preconditions that make the
        runtime comparison meaningful."""
        kinds = {k for k, _ in LOOP_AFFINITY}
        assert kinds == set(AFFINITY_HOME)
        for rel in AFFINITY_HOME.values():
            assert os.path.exists(os.path.join(_ROOT, rel)), rel
        graph = _static_model()
        for kind in sorted(kinds):
            assert kind in graph.owners, (
                f"no owning construct extracted for blessed kind {kind!r}"
            )
        # with every owner + marshal site present on the live tree, the
        # model's seam set is exactly the blessed table
        assert graph.seams() == set(LOOP_AFFINITY)
